"""Dispatch deadline watchdog: bound the device window in wall time.

A dead device raises and the degradation ladder (scheduler/degrade.py)
absorbs it. A SLOW-not-dead device is worse: the readback
``block_until_ready`` simply never returns, nothing raises, and the
whole scheduling cycle wedges behind a single sick chip — the failure
mode ROADMAP calls out for the fault catalog. ``KOORD_TPU_CYCLE_
DEADLINE_MS`` cannot help (it fires AFTER the cycle completes, which a
hung sync never does).

``DeadlineWatchdog.run(fn, path)`` executes the designated blocking
readback ``fn`` on a worker thread and waits ``deadline_seconds``:

  * in time -> the result (or the worker's exception) passes through
    unchanged, same thread-visible semantics as calling ``fn`` inline;
  * overrun -> the overrun callback fires (metrics + flight dump) and
    :class:`DispatchDeadlineExceeded` raises into the dispatch window,
    where the ladder treats it exactly like a raised device fault —
    retry once, then demote. The worker keeps draining the slow sync in
    the background; the owner must ABANDON the device state it was
    syncing (the scheduler rebuilds its DeviceSnapshot; the shared
    rebalance mirror leaves its dispatch window open so donation never
    re-arms under the still-running program) instead of blocking on it.

With no deadline configured (the default) ``run`` calls ``fn`` inline —
zero threads, zero overhead, byte-identical behavior.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Callable, Optional

logger = logging.getLogger(__name__)


class DispatchDeadlineExceeded(RuntimeError):
    """A monitored device sync overran its deadline. Raised into the
    dispatch window strictly before any binding of that window applies,
    so the ladder may retry/demote; carries the path label for the
    ``koord_scheduler_dispatch_deadline_overruns_total`` counter."""

    def __init__(self, path: str, deadline_seconds: float) -> None:
        super().__init__(
            f"{path} dispatch exceeded the "
            f"{deadline_seconds * 1000.0:.0f}ms device deadline")
        self.path = path
        self.deadline_seconds = deadline_seconds


def dispatch_deadline_from_env() -> Optional[float]:
    """KOORD_TPU_DISPATCH_DEADLINE_MS=N bounds every device window
    (serial, fused/chained waves, mesh merge, the rebalance pass) in
    wall time; an overrun demotes the ladder instead of wedging the
    cycle. Unset/0 disables (the default). Distinct from
    KOORD_TPU_CYCLE_DEADLINE_MS, which is dump-only and measures the
    COMPLETED cycle. Returns seconds or None."""
    raw = os.environ.get("KOORD_TPU_DISPATCH_DEADLINE_MS", "").strip()
    if not raw:
        return None
    try:
        ms = float(raw)
    except ValueError:
        logger.warning("KOORD_TPU_DISPATCH_DEADLINE_MS=%r not a number; "
                       "dispatch deadline off", raw)
        return None
    return ms / 1000.0 if ms > 0 else None


def deadline_seconds_from(ms, default_env: bool = True) -> Optional[float]:
    """Resolve a deadline argument: None reads the env (when asked),
    <=0 pins it off, >0 is milliseconds."""
    if ms is None:
        return dispatch_deadline_from_env() if default_env else None
    ms = float(ms)
    return ms / 1000.0 if ms > 0 else None


class DeadlineWatchdog:
    """Monitored-sync runner for one dispatch owner (scheduler or
    rebalancer). Stateless between runs except the overrun counter;
    every ``run`` spawns its own worker, so an abandoned slow sync never
    blocks the next window's watchdog."""

    def __init__(self, deadline_seconds: Optional[float] = None,
                 on_overrun: Optional[Callable[[str], None]] = None) -> None:
        self.deadline_seconds = deadline_seconds
        self.on_overrun = on_overrun
        self._lock = threading.Lock()
        self.overruns = 0  # koordlint: guarded-by(_lock)

    def run(self, fn: Callable[[], object], path: str):
        """Run the blocking sync ``fn`` under the deadline. No deadline
        configured: calls inline (no thread)."""
        deadline = self.deadline_seconds
        if deadline is None:
            return fn()
        box: dict = {}
        done = threading.Event()

        def worker() -> None:
            try:
                box["result"] = fn()
            except BaseException as exc:  # delivered to the waiter
                box["error"] = exc
            finally:
                done.set()

        t = threading.Thread(target=worker, daemon=True,
                             name=f"koord-dispatch-sync-{path}")
        t.start()
        if done.wait(deadline):
            err = box.get("error")
            if err is not None:
                raise err
            return box["result"]
        with self._lock:
            self.overruns += 1
        logger.warning(
            "%s dispatch overran the %.0fms device deadline; abandoning "
            "the in-flight window (the worker drains it in the "
            "background)", path, deadline * 1000.0)
        if self.on_overrun is not None:
            self.on_overrun(path)
        raise DispatchDeadlineExceeded(path, deadline)
