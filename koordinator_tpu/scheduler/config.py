"""Scheduler componentconfig: versioned plugin args, defaulting, validation.

Analog of reference `pkg/scheduler/apis/config/` (types.go:30-214, v1beta2
defaults, validation/): each plugin's knobs are a dataclass with the v1beta2
defaults baked in; `from_dict` decodes a config-file mapping with unknown-key
rejection (strict decoding, as the reference's scheme does); `validate()`
raises `ConfigValidationError` aggregating every violation.

`LoadAwareArgs` lives in ops/loadaware.py (device kernel + host share it);
it is re-exported and validated here so `SchedulerConfiguration` covers all
seven plugins.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.scheduler.cpu_topology import (
    FULL_PCPUS,
    NUMA_LEAST_ALLOCATED,
    NUMA_MOST_ALLOCATED,
    SPREAD_BY_PCPUS,
)


class ConfigValidationError(ValueError):
    def __init__(self, errors: List[str]):
        super().__init__("; ".join(errors))
        self.errors = errors


@dataclass
class NodeNUMAResourceArgs:
    """types.go NodeNUMAResourceArgs. (The reference's scoringStrategy field
    has no analog here: node scoring happens in the batched kernel, not the
    host plugin — only knobs with real consumers are exposed.)"""

    default_cpu_bind_policy: str = FULL_PCPUS
    numa_allocate_strategy: str = NUMA_MOST_ALLOCATED
    max_ref_count: int = 1

    def validate(self) -> List[str]:
        errs = []
        if self.default_cpu_bind_policy not in (FULL_PCPUS, SPREAD_BY_PCPUS):
            errs.append(
                f"defaultCPUBindPolicy: unknown {self.default_cpu_bind_policy!r}")
        if self.numa_allocate_strategy not in (
                NUMA_MOST_ALLOCATED, NUMA_LEAST_ALLOCATED):
            errs.append(
                f"numaAllocateStrategy: unknown {self.numa_allocate_strategy!r}")
        if self.max_ref_count < 1:
            errs.append("maxRefCount: must be >= 1")
        return errs


@dataclass
class ReservationArgs:
    """types.go ReservationArgs. (Candidate-node sampling knobs from the
    reference don't apply — the batched kernel evaluates every node.)"""

    gc_duration_seconds: float = 24 * 3600.0

    def validate(self) -> List[str]:
        if self.gc_duration_seconds <= 0:
            return ["gcDurationSeconds: must be > 0"]
        return []


@dataclass
class ElasticQuotaArgs:
    """types.go ElasticQuotaArgs."""

    delay_evict_time_seconds: float = 300.0
    revoke_pod_interval_seconds: float = 60.0
    monitor_all_quotas: bool = False

    def validate(self) -> List[str]:
        errs = []
        if self.delay_evict_time_seconds < 0:
            errs.append("delayEvictTime: must be >= 0")
        if self.revoke_pod_interval_seconds <= 0:
            errs.append("revokePodInterval: must be > 0")
        return errs


@dataclass
class CoschedulingArgs:
    """types.go CoschedulingArgs."""

    default_timeout_seconds: float = 600.0
    controller_workers: int = 1

    def validate(self) -> List[str]:
        errs = []
        if self.default_timeout_seconds <= 0:
            errs.append("defaultTimeout: must be > 0")
        if self.controller_workers < 1:
            errs.append("controllerWorkers: must be >= 1")
        return errs


@dataclass
class DeviceShareArgs:
    """types.go DeviceShareArgs."""

    # MostAllocated packs fractional GPU requests (the reference allocator's
    # default preference); LeastAllocated spreads them
    scoring_strategy: str = "MostAllocated"

    def validate(self) -> List[str]:
        if self.scoring_strategy not in ("LeastAllocated", "MostAllocated"):
            return [f"scoringStrategy: unknown {self.scoring_strategy!r}"]
        return []


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _validate_loadaware(args: LoadAwareArgs) -> List[str]:
    errs = []
    if args.node_metric_expiration_seconds <= 0:
        errs.append("nodeMetricExpirationSeconds: must be > 0")
    for name, w in args.resource_weights.items():
        if not _num(w) or w < 0:
            errs.append(f"resourceWeights[{name}]: must be a number >= 0")
    for name, pct in {**args.usage_thresholds,
                      **args.prod_usage_thresholds}.items():
        if not _num(pct) or not (0 <= pct <= 100):
            errs.append(f"usageThresholds[{name}]: must be in [0,100]")
    for name, pct in args.estimated_scaling_factors.items():
        if not _num(pct) or not (0 < pct <= 100):
            errs.append(f"estimatedScalingFactors[{name}]: must be in (0,100]")
    if args.agg_usage_aggregation_type not in (
            "", "avg", "p50", "p90", "p95", "p99"):
        errs.append(
            f"aggregated.usageAggregationType: unknown "
            f"{args.agg_usage_aggregation_type!r}")
    return errs


@dataclass
class SchedulerConfiguration:
    """All plugin args under their registered plugin names."""

    load_aware: LoadAwareArgs = field(default_factory=LoadAwareArgs)
    node_numa_resource: NodeNUMAResourceArgs = field(
        default_factory=NodeNUMAResourceArgs)
    reservation: ReservationArgs = field(default_factory=ReservationArgs)
    elastic_quota: ElasticQuotaArgs = field(default_factory=ElasticQuotaArgs)
    coscheduling: CoschedulingArgs = field(default_factory=CoschedulingArgs)
    device_share: DeviceShareArgs = field(default_factory=DeviceShareArgs)

    def validate(self) -> None:
        errs = _validate_loadaware(self.load_aware)
        for section in (self.node_numa_resource, self.reservation,
                        self.elastic_quota, self.coscheduling,
                        self.device_share):
            errs.extend(section.validate())
        if errs:
            raise ConfigValidationError(errs)


_SECTION_TYPES = {
    "LoadAwareScheduling": ("load_aware", LoadAwareArgs),
    "NodeNUMAResource": ("node_numa_resource", NodeNUMAResourceArgs),
    "Reservation": ("reservation", ReservationArgs),
    "ElasticQuota": ("elastic_quota", ElasticQuotaArgs),
    "Coscheduling": ("coscheduling", CoschedulingArgs),
    "DeviceShare": ("device_share", DeviceShareArgs),
}


def from_dict(raw: Dict[str, Any],
              validate: bool = True) -> SchedulerConfiguration:
    """Decode {pluginName: {field: value}} strictly: unknown plugin or field
    names are errors (the reference's scheme decoding posture), missing fields
    take the v1beta2 defaults."""
    cfg = SchedulerConfiguration()
    errs: List[str] = []
    for section_name, fields in raw.items():
        if section_name not in _SECTION_TYPES:
            errs.append(f"unknown plugin config section {section_name!r}")
            continue
        attr, cls = _SECTION_TYPES[section_name]
        known = {f.name for f in dataclasses.fields(cls)}
        kwargs = {}
        for key, value in (fields or {}).items():
            if key not in known:
                errs.append(f"{section_name}: unknown field {key!r}")
                continue
            kwargs[key] = value
        try:
            setattr(cfg, attr, cls(**kwargs))
        except TypeError as e:
            errs.append(f"{section_name}: {e}")
    if errs:
        raise ConfigValidationError(errs)
    if validate:
        cfg.validate()
    return cfg
