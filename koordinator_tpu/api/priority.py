"""Priority bands.

Semantics from reference `apis/extension/priority.go:29-48`: four koordinator
priority classes mapped onto disjoint integer priority ranges:

    koord-prod  [9000, 9999]
    koord-mid   [7000, 7999]
    koord-batch [5000, 5999]
    koord-free  [3000, 3999]

A pod's priority class is resolved from (a) the `koordinator.sh/priority-class`
label, else (b) its numeric `spec.priority` mapped through the bands
(priority.go:74-104). Sub-priority within a band comes from the
`koordinator.sh/priority` label (priority.go:107-116).
"""

from __future__ import annotations

import enum
from typing import Optional


class PriorityClass(enum.IntEnum):
    """Int-encoded priority band (order: PROD highest)."""

    PROD = 0
    MID = 1
    BATCH = 2
    FREE = 3
    NONE = 4

    @property
    def label(self) -> str:
        return "" if self is PriorityClass.NONE else f"koord-{self.name.lower()}"


# Band boundaries (min, max), reference priority.go:38-48. Kept as module-level
# variables (not enum payload) because the reference allows customizing ranges.
PRIORITY_BANDS = {
    PriorityClass.PROD: (9000, 9999),
    PriorityClass.MID: (7000, 7999),
    PriorityClass.BATCH: (5000, 5999),
    PriorityClass.FREE: (3000, 3999),
}

# Default numeric priority assigned when only the class is known (the webhook picks
# the band max, mirroring ClusterColocationProfile defaulting).
DEFAULT_PRIORITY_BY_CLASS = {cls: hi for cls, (_, hi) in PRIORITY_BANDS.items()}

_BY_LABEL = {c.label: c for c in PriorityClass if c is not PriorityClass.NONE}


def priority_class_by_name(label: str) -> PriorityClass:
    """Resolve a priority-class label; unknown -> NONE (priority.go:60-69)."""
    return _BY_LABEL.get(label, PriorityClass.NONE)


def priority_class_by_value(priority: Optional[int]) -> PriorityClass:
    """Map a numeric pod priority into its band; outside all bands -> NONE
    (priority.go:86-104)."""
    if priority is None:
        return PriorityClass.NONE
    for cls, (lo, hi) in PRIORITY_BANDS.items():
        if lo <= priority <= hi:
            return cls
    return PriorityClass.NONE
