"""QoS classes.

Semantics from reference `apis/extension/qos.go:22-39`: five classes
LSE/LSR/LS/BE/SYSTEM plus the empty "none"; unknown strings resolve to none.

The integer values double as the on-device encoding used by the packed pod tensors
(`ops/packing.py`); ordering is chosen so that comparisons "is latency sensitive"
(< BE) are single vectorized compares.
"""

from __future__ import annotations

import enum


class QoSClass(enum.IntEnum):
    """Koordinator QoS class, int-encoded for device tensors."""

    LSE = 0  # latency-sensitive exclusive: pinned cpus, no sharing
    LSR = 1  # latency-sensitive reserved: pinned cpus, sharable with BE suppression
    LS = 2   # latency-sensitive (shared pool)
    BE = 3   # best-effort (colocated batch; runs on batch-* resources)
    SYSTEM = 4
    NONE = 5

    @property
    def label(self) -> str:
        return "" if self is QoSClass.NONE else self.name

    @property
    def is_latency_sensitive(self) -> bool:
        return self in (QoSClass.LSE, QoSClass.LSR, QoSClass.LS)

    @property
    def is_best_effort(self) -> bool:
        return self is QoSClass.BE


_BY_NAME = {c.name: c for c in QoSClass if c is not QoSClass.NONE}


def qos_class_by_name(name: str) -> QoSClass:
    """Resolve a QoS label value; unknown -> NONE (qos.go:31-39)."""
    return _BY_NAME.get(name, QoSClass.NONE)
