"""Data-model layer: the analog of the reference's `apis/` tree.

Everything the control plane communicates through — QoS classes, priority bands,
extended resources, well-known labels/annotations, and the CRD object model — lives
here, so that the rest of the framework (kernels included) depends only on this spec.
"""

from koordinator_tpu.api.qos import QoSClass, qos_class_by_name  # noqa: F401
from koordinator_tpu.api.priority import (  # noqa: F401
    PriorityClass,
    priority_class_by_value,
    priority_class_by_name,
    DEFAULT_PRIORITY_BY_CLASS,
)
from koordinator_tpu.api.resources import (  # noqa: F401
    ResourceName,
    RESOURCE_AXES,
    RESOURCE_INDEX,
    NUM_RESOURCES,
    ResourceList,
    translate_resource_by_priority_class,
)
from koordinator_tpu.api.objects import (  # noqa: F401
    ObjectMeta,
    PodSpec,
    Pod,
    Node,
    NodeMetric,
    NodeMetricInfo,
    PodMetricInfo,
    Reservation,
    PodGroup,
    ElasticQuota,
    Device,
    DeviceInfo,
    NodeSLO,
    NodeResourceTopology,
    PodMigrationJob,
    ClusterColocationProfile,
)
