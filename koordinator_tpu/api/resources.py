"""Resource model.

The reference represents resources as `corev1.ResourceList` (map[name]Quantity) and
defines koordinator extended resources in `apis/extension/resource.go:26-29`
(kubernetes.io/batch-cpu|batch-memory|mid-cpu|mid-memory) and GPU/device resources in
`apis/extension/device_share.go:38-46` (koordinator.sh/gpu-core, gpu-memory,
gpu-memory-ratio, gpu.shared, rdma, fpga).

TPU-first design: every resource list is packed into a fixed-length float32 vector
over the canonical RESOURCE_AXES below, so pod requests become a [P, R] matrix and
node allocatable a [N, R] matrix, and the whole Filter chain is elementwise compares
with reductions over R. Units are normalized so float32 is exact enough for parity:

  * cpu-like axes  -> milli-cores  (int-valued, < 2^24 for any real node)
  * memory-like    -> MiB          (int-valued for practical quantities)
  * counts/percent -> raw

The host-side object model (`api/objects.py`) keeps exact integers; `ResourceList`
converts at the packing boundary, and BOTH the serial parity emulator and the batched
kernel consume the packed encoding, so binding parity is by construction.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterator, Mapping, Optional

import numpy as np

from koordinator_tpu.api.priority import PriorityClass

# Canonical string names (values mirror the reference's wire names).
class ResourceName:
    CPU = "cpu"                                  # milli-cores
    MEMORY = "memory"                            # bytes on the wire, MiB packed
    EPHEMERAL_STORAGE = "ephemeral-storage"
    PODS = "pods"
    BATCH_CPU = "kubernetes.io/batch-cpu"        # resource.go:26
    BATCH_MEMORY = "kubernetes.io/batch-memory"  # resource.go:27
    MID_CPU = "kubernetes.io/mid-cpu"            # resource.go:28
    MID_MEMORY = "kubernetes.io/mid-memory"      # resource.go:29
    GPU = "nvidia.com/gpu"
    GPU_CORE = "koordinator.sh/gpu-core"             # device_share.go
    GPU_MEMORY = "koordinator.sh/gpu-memory"
    GPU_MEMORY_RATIO = "koordinator.sh/gpu-memory-ratio"
    GPU_SHARED = "koordinator.sh/gpu.shared"
    RDMA = "koordinator.sh/rdma"
    FPGA = "koordinator.sh/fpga"


# Axis order of the packed [R] vector. Order groups the hot axes (cpu/memory and the
# colocation batch/mid tiers) first so narrow kernels can slice a prefix.
RESOURCE_AXES = (
    ResourceName.CPU,
    ResourceName.MEMORY,
    ResourceName.BATCH_CPU,
    ResourceName.BATCH_MEMORY,
    ResourceName.MID_CPU,
    ResourceName.MID_MEMORY,
    ResourceName.EPHEMERAL_STORAGE,
    ResourceName.PODS,
    ResourceName.GPU,
    ResourceName.GPU_CORE,
    ResourceName.GPU_MEMORY,
    ResourceName.GPU_MEMORY_RATIO,
    ResourceName.RDMA,
    ResourceName.FPGA,
)
RESOURCE_INDEX: Dict[str, int] = {name: i for i, name in enumerate(RESOURCE_AXES)}
NUM_RESOURCES = len(RESOURCE_AXES)
# axes koord-manager computes AFTER applying node reservation — the node
# transformer must not trim them again (pkg/util/node.go)
BATCH_AXES = (RESOURCE_INDEX[ResourceName.BATCH_CPU],
              RESOURCE_INDEX[ResourceName.BATCH_MEMORY])

# Axes whose wire unit is bytes; packed as MiB to stay exact in float32.
_MEMORY_LIKE = frozenset(
    {
        ResourceName.MEMORY,
        ResourceName.BATCH_MEMORY,
        ResourceName.MID_MEMORY,
        ResourceName.EPHEMERAL_STORAGE,
        ResourceName.GPU_MEMORY,
    }
)
MIB = 1024 * 1024

# Packing scale per axis (wire value / scale = packed value).
PACK_SCALE = np.array(
    [MIB if name in _MEMORY_LIKE else 1 for name in RESOURCE_AXES], dtype=np.float64
)


@dataclass
class ResourceList:
    """Exact host-side resource map (wire units: milli-cpu, bytes, counts)."""

    quantities: Dict[str, int] = field(default_factory=dict)

    @staticmethod
    def of(**kwargs: int) -> "ResourceList":
        """Build from python-friendly names: cpu (milli), memory (bytes), etc."""
        alias = {
            "cpu": ResourceName.CPU,
            "memory": ResourceName.MEMORY,
            "batch_cpu": ResourceName.BATCH_CPU,
            "batch_memory": ResourceName.BATCH_MEMORY,
            "mid_cpu": ResourceName.MID_CPU,
            "mid_memory": ResourceName.MID_MEMORY,
            "ephemeral_storage": ResourceName.EPHEMERAL_STORAGE,
            "pods": ResourceName.PODS,
            "gpu": ResourceName.GPU,
            "gpu_core": ResourceName.GPU_CORE,
            "gpu_memory": ResourceName.GPU_MEMORY,
            "gpu_memory_ratio": ResourceName.GPU_MEMORY_RATIO,
            "rdma": ResourceName.RDMA,
            "fpga": ResourceName.FPGA,
        }
        return ResourceList({alias[k]: int(v) for k, v in kwargs.items() if v})

    def get(self, name: str, default: int = 0) -> int:
        return self.quantities.get(name, default)

    def __getitem__(self, name: str) -> int:
        return self.quantities.get(name, 0)

    def __iter__(self) -> Iterator[str]:
        return iter(self.quantities)

    def __bool__(self) -> bool:
        return any(self.quantities.values())

    def add(self, other: "ResourceList") -> "ResourceList":
        out = dict(self.quantities)
        for k, v in other.quantities.items():
            out[k] = out.get(k, 0) + v
        return ResourceList(out)

    def sub(self, other: "ResourceList") -> "ResourceList":
        out = dict(self.quantities)
        for k, v in other.quantities.items():
            out[k] = out.get(k, 0) - v
        return ResourceList(out)

    def max(self, other: "ResourceList") -> "ResourceList":
        out = dict(self.quantities)
        for k, v in other.quantities.items():
            out[k] = max(out.get(k, 0), v)
        return ResourceList(out)

    def copy(self) -> "ResourceList":
        return ResourceList(dict(self.quantities))

    def fill_wire_row(self, out_row: np.ndarray) -> None:
        """Write wire-unit quantities into a preallocated [R] row — the
        allocation-free half of to_vector, shared with the batch packer
        (callers scale by PACK_SCALE once over the whole matrix)."""
        for name, q in self.quantities.items():
            idx = RESOURCE_INDEX.get(name)
            if idx is not None:
                out_row[idx] = q

    def to_vector(self) -> np.ndarray:
        """Pack into the canonical [R] float32 vector (normalized units)."""
        vec = np.zeros(NUM_RESOURCES, dtype=np.float64)
        self.fill_wire_row(vec)
        return (vec / PACK_SCALE).astype(np.float32)

    @staticmethod
    def pack_wire_matrix(resource_lists) -> np.ndarray:
        """Pack many ResourceLists into one [K, R] float32 matrix: a single
        fill + scale instead of K to_vector allocations. Rows are
        bit-identical to to_vector() (same float64 fill, divide, cast)."""
        rls = list(resource_lists)
        mat = np.zeros((len(rls), NUM_RESOURCES), np.float64)
        for j, rl in enumerate(rls):
            rl.fill_wire_row(mat[j])
        return (mat / PACK_SCALE).astype(np.float32)

    @staticmethod
    def from_vector(vec: np.ndarray) -> "ResourceList":
        """Inverse of to_vector (rounds back to wire units)."""
        wire = np.asarray(vec, dtype=np.float64) * PACK_SCALE
        return ResourceList(
            {
                name: int(round(wire[i]))
                for i, name in enumerate(RESOURCE_AXES)
                if wire[i] != 0
            }
        )


_QUANTITY_SUFFIX = {
    "": 1,
    "k": 10**3,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
    "P": 10**15,
    "E": 10**18,
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}


def parse_quantity(value, cpu: bool = False) -> int:
    """Parse a k8s resource.Quantity string ("10Gi", "500m", "2k", "1.5") into an
    integer in wire units: milli for cpu=True, raw value otherwise (bytes/counts).
    Accepts ints/floats as-is (already wire units)."""
    if isinstance(value, (int, float)):
        return int(value)
    s = str(value).strip()
    if not s:
        raise ValueError("empty quantity")
    if s.endswith("m"):
        num = float(s[:-1])
        milli = num
        return int(round(milli)) if cpu else int(round(milli / 1000.0))
    suffix = ""
    for suf in sorted(_QUANTITY_SUFFIX, key=len, reverse=True):
        if suf and s.endswith(suf):
            suffix = suf
            break
    num = float(s[: len(s) - len(suffix)] if suffix else s)
    raw = num * _QUANTITY_SUFFIX[suffix]
    return int(round(raw * 1000)) if cpu else int(round(raw))


def translate_resource_by_priority_class(
    priority_class: PriorityClass, resource: str
) -> Optional[str]:
    """cpu/memory -> batch-* or mid-* for BATCH/MID priority pods; PROD/NONE keep
    native names (reference resource.go:40-59)."""
    if priority_class in (PriorityClass.PROD, PriorityClass.NONE):
        return resource
    table: Mapping[PriorityClass, Mapping[str, str]] = {
        PriorityClass.BATCH: {
            ResourceName.CPU: ResourceName.BATCH_CPU,
            ResourceName.MEMORY: ResourceName.BATCH_MEMORY,
        },
        PriorityClass.MID: {
            ResourceName.CPU: ResourceName.MID_CPU,
            ResourceName.MEMORY: ResourceName.MID_MEMORY,
        },
    }
    return table.get(priority_class, {}).get(resource)
