"""CRD-like object model.

Python analogs of the reference's API types — core k8s objects (Pod, Node) plus the
ten koordinator CRDs installed from `config/crd/bases/` (SURVEY.md section 2.7):
NodeMetric, NodeSLO, Reservation, Device, PodGroup, ElasticQuota, PodMigrationJob,
ClusterColocationProfile, NodeResourceTopology, ElasticQuotaProfile.

These are deliberately plain dataclasses: the control plane manipulates them on host;
`ops/packing.py` lowers snapshots of them into device tensors. Field names follow the
reference's json tags so traces serialize compatibly. Durable state is externalized
into these objects exactly as in the reference (SURVEY.md section 5.4): restart =
re-list + rebuild caches.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Tuple

from koordinator_tpu.api.priority import (
    PriorityClass,
    priority_class_by_name,
    priority_class_by_value,
)
from koordinator_tpu.api.qos import QoSClass, qos_class_by_name
from koordinator_tpu.api.resources import ResourceList

# Well-known labels/annotations (reference apis/extension/constants.go:21-47 and
# plugin-specific files; cited per constant).
DOMAIN_PREFIX = "koordinator.sh/"
SCHEDULING_DOMAIN_PREFIX = "scheduling.koordinator.sh"
NODE_DOMAIN_PREFIX = "node.koordinator.sh"
POD_DOMAIN_PREFIX = "pod.koordinator.sh"
QUOTA_DOMAIN_PREFIX = "quota.scheduling.koordinator.sh"

LABEL_POD_QOS = DOMAIN_PREFIX + "qosClass"                      # constants.go:31
LABEL_POD_PRIORITY = DOMAIN_PREFIX + "priority"                 # constants.go:32
LABEL_POD_PRIORITY_CLASS = DOMAIN_PREFIX + "priority-class"     # constants.go:36
LABEL_POD_GROUP = "pod-group.scheduling.sigs.k8s.io"            # coscheduling
ANNOTATION_RESOURCE_SPEC = SCHEDULING_DOMAIN_PREFIX + "/resource-spec"
ANNOTATION_RESOURCE_STATUS = SCHEDULING_DOMAIN_PREFIX + "/resource-status"
ANNOTATION_DEVICE_ALLOCATED = SCHEDULING_DOMAIN_PREFIX + "/device-allocated"
ANNOTATION_RESERVATION_ALLOCATED = SCHEDULING_DOMAIN_PREFIX + "/reservation-allocated"
ANNOTATION_EXTENDED_RESOURCE_SPEC = NODE_DOMAIN_PREFIX + "/extended-resource-spec"
# marks the fake pods the scheduler itself creates for Reservation CRs; user
# pods may never carry it (pkg/util/reservation/reservation.go:44, enforced
# by webhook pod/validating/verify_annotations.go:60-76)
ANNOTATION_RESERVE_POD = SCHEDULING_DOMAIN_PREFIX + "/reserve-pod"
# node-level resource reservation for system daemons
# (apis/extension/node_reservation.go:28-44): {"resources": {...},
# "reservedCPUs": "1-6", "applyPolicy": "Default"|"ReservedCPUsOnly"}
ANNOTATION_NODE_RESERVATION = NODE_DOMAIN_PREFIX + "/reservation"
# CPU cores dedicated to SYSTEM QoS pods (apis/extension/system_qos.go:24):
# {"cpuset": "0-1", "cpusetExclusive": true} — exclusive (the default) bars
# LS/LSR/BE pods from those cores
ANNOTATION_NODE_SYSTEM_QOS = NODE_DOMAIN_PREFIX + "/system-qos-resource"
# koordwatch decision correlation (obs/timeline.py): the device-window
# decision id a PodMigrationJob was issued under, copied onto its
# replacement Reservation — joins descheduler decisions to scheduler
# timeline windows, spans and flight records
ANNOTATION_DECISION_ID = DOMAIN_PREFIX + "decision-id"
# pod operating mode (apis/extension/operating_pod.go:28-50): a pod labeled
# "Reservation" schedules normally but then acts as a reservation whose
# owners (JSON ReservationOwner list annotation) consume its resources
LABEL_POD_OPERATING_MODE = SCHEDULING_DOMAIN_PREFIX + "/operating-mode"
ANNOTATION_RESERVATION_OWNERS = (
    SCHEDULING_DOMAIN_PREFIX + "/reservation-owners")
ANNOTATION_RESERVATION_CURRENT_OWNER = (
    SCHEDULING_DOMAIN_PREFIX + "/reservation-current-owner")
LABEL_QUOTA_NAME = QUOTA_DOMAIN_PREFIX + "/name"
LABEL_QUOTA_PARENT = QUOTA_DOMAIN_PREFIX + "/parent"
LABEL_QUOTA_IS_PARENT = QUOTA_DOMAIN_PREFIX + "/is-parent"
LABEL_QUOTA_SHARED_WEIGHT = QUOTA_DOMAIN_PREFIX + "/shared-weight"
LABEL_QUOTA_TREE_ID = QUOTA_DOMAIN_PREFIX + "/tree-id"
LABEL_QUOTA_ALLOW_LENT = QUOTA_DOMAIN_PREFIX + "/allow-lent-resource"
ANNOTATION_QUOTA_GUARANTEED = QUOTA_DOMAIN_PREFIX + "/guaranteed"


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    creation_timestamp: float = field(default_factory=time.time)
    resource_version: int = 0
    deletion_timestamp: Optional[float] = None
    owner_kind: str = ""
    owner_name: str = ""

    @property
    def key(self) -> str:
        # memoized: the packed-snapshot path reads keys tens of thousands
        # of times per cycle. Identity-checked against name/namespace so a
        # rebound field (tests mutate metas in place) recomputes.
        cached = self.__dict__.get("_key_memo")
        if (cached is not None and cached[0] is self.name
                and cached[1] is self.namespace):
            return cached[2]
        k = f"{self.namespace}/{self.name}"
        self.__dict__["_key_memo"] = (self.name, self.namespace, k)
        return k


@dataclass
class PodAffinityTerm:
    """requiredDuringSchedulingIgnoredDuringExecution inter-pod (anti-)
    affinity term: pods matching `selector` within the `topology_key`
    domain of a candidate node (core/v1 PodAffinityTerm, matchLabels
    form — the form the vendored kube-scheduler InterPodAffinity plugin
    evaluates in Filter)."""

    selector: Dict[str, str] = field(default_factory=dict)
    topology_key: str = "kubernetes.io/hostname"
    # namespaces the selector applies to; empty means the OWNING pod's own
    # namespace (core/v1 PodAffinityTerm.namespaces default)
    namespaces: List[str] = field(default_factory=list)


@dataclass
class PreferredPodTerm:
    """preferredDuringSchedulingIgnoredDuringExecution inter-pod affinity
    (core/v1 WeightedPodAffinityTerm, matchLabels form): candidate nodes
    gain `weight` per matching pod in their topology domain. Negative
    weight expresses preferred ANTI-affinity."""

    weight: int = 1
    selector: Dict[str, str] = field(default_factory=dict)
    topology_key: str = "kubernetes.io/hostname"
    namespaces: List[str] = field(default_factory=list)


@dataclass
class TopologySpreadConstraint:
    """core/v1 TopologySpreadConstraint (matchLabels form), evaluated by
    the vendored PodTopologySpread plugin. whenUnsatisfiable=DoNotSchedule
    filters: placing the pod in a domain must keep count(domain) + 1 -
    min(eligible domain counts) <= max_skew. ScheduleAnyway only scores:
    emptier domains rank higher (a -1 weight on the constraint's own term
    in the preferred-affinity machinery)."""

    max_skew: int = 1
    topology_key: str = "kubernetes.io/hostname"
    selector: Dict[str, str] = field(default_factory=dict)
    when_unsatisfiable: str = "DoNotSchedule"


@dataclass
class PreferredNodeTerm:
    """preferredDuringSchedulingIgnoredDuringExecution node affinity term
    (core/v1 PreferredSchedulingTerm, matchLabels form): nodes matching
    `labels` gain `weight` in the NodeAffinity score."""

    weight: int = 1
    labels: Dict[str, str] = field(default_factory=dict)


@dataclass
class PodSpec:
    node_name: str = ""
    scheduler_name: str = "koord-scheduler"
    priority: Optional[int] = None
    priority_class_name: str = ""
    requests: ResourceList = field(default_factory=ResourceList)
    limits: ResourceList = field(default_factory=ResourceList)
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity_required_node_labels: Dict[str, str] = field(default_factory=dict)
    affinity_preferred: List["PreferredNodeTerm"] = field(default_factory=list)
    pod_affinity: List["PodAffinityTerm"] = field(default_factory=list)
    pod_anti_affinity: List["PodAffinityTerm"] = field(default_factory=list)
    pod_affinity_preferred: List["PreferredPodTerm"] = field(
        default_factory=list)
    topology_spread: List["TopologySpreadConstraint"] = field(
        default_factory=list)
    tolerations: List[Tuple[str, str]] = field(default_factory=list)  # (key, value)
    overhead: ResourceList = field(default_factory=ResourceList)
    restart_policy: str = "Always"
    termination_grace_period_seconds: int = 30
    # container hostPorts as (protocol, port) — the vendored NodePorts
    # filter's conflict identity (hostIP treated as the 0.0.0.0 wildcard:
    # conservative, a conflict on any IP blocks the node)
    host_ports: List[Tuple[str, int]] = field(default_factory=list)
    # PVC claim names the pod mounts (volumes[].persistentVolumeClaim) —
    # drive the CSI volume-limit count and the VolumeZone filter
    pvc_names: List[str] = field(default_factory=list)
    # container images — the vendored ImageLocality score reads them
    # against node.images
    images: List[str] = field(default_factory=list)
    # desired requests of a PENDING in-place resize (KEP-1287 shape; the
    # frameworkext ResizePod path consumes it when the feature gate is on:
    # reference frameworkext_factory RunReservePluginsReserve+RunResizePod)
    resize_requests: Optional[ResourceList] = None


@dataclass
class PodCondition:
    """core v1 PodCondition subset: the scheduler writes PodScheduled
    (status False / reason Unschedulable / message with the per-stage
    breakdown) when a pod ends a cycle unbound, and flips it True at bind —
    the same status surface the scheduler framework propagates upstream."""

    type: str = "PodScheduled"
    status: str = "False"  # "True" | "False"
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0


@dataclass
class Pod:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    phase: str = "Pending"  # Pending/Running/Succeeded/Failed
    reason: str = ""        # status.reason (e.g. "OutOfCpu", "NodeShutdown")
    restart_count: int = 0  # sum of container restart counts
    conditions: List[PodCondition] = field(default_factory=list)

    def get_condition(self, ctype: str) -> Optional[PodCondition]:
        for c in self.conditions:
            if c.type == ctype:
                return c
        return None

    def set_condition(self, ctype: str, status: str, reason: str,
                      message: str, now: float) -> bool:
        """Upsert a condition; returns True when anything changed.
        last_transition_time bumps only on a STATUS flip (upstream
        semantics), so repeated identical writes are no-ops the caller can
        skip persisting."""
        cur = self.get_condition(ctype)
        if cur is None:
            self.conditions.append(PodCondition(
                type=ctype, status=status, reason=reason, message=message,
                last_transition_time=now))
            return True
        if (cur.status, cur.reason, cur.message) == (status, reason, message):
            return False
        if cur.status != status:
            cur.last_transition_time = now
        cur.status, cur.reason, cur.message = status, reason, message
        return True

    @property
    def qos_class(self) -> QoSClass:
        """QoS from the koordinator.sh/qosClass label (apis/extension/qos.go)."""
        return qos_class_by_name(self.meta.labels.get(LABEL_POD_QOS, ""))

    @property
    def is_reservation_operating_mode(self) -> bool:
        """operating_pod.go IsReservationOperatingMode."""
        return self.meta.labels.get(LABEL_POD_OPERATING_MODE) == "Reservation"

    def reservation_owners(self) -> List["ReservationOwner"]:
        """Parse the reservation-owners annotation (operating_pod.go
        SetReservationOwners): a JSON list of ReservationOwner objects; both
        the full {"labelSelector": {"matchLabels": {...}}} form and a flat
        {"labelSelector": {...}} shorthand are accepted. Malformed
        annotations yield no owners (the reservation matches nothing)."""
        import json

        raw = self.meta.annotations.get(ANNOTATION_RESERVATION_OWNERS)
        if not raw:
            return []
        try:
            data = json.loads(raw)
            if not isinstance(data, list):
                return []
            owners = []
            for entry in data:
                if not isinstance(entry, dict):
                    continue
                sel = entry.get("labelSelector") or {}
                if isinstance(sel, dict) and isinstance(
                        sel.get("matchLabels"), dict):
                    sel = sel["matchLabels"]
                if not isinstance(sel, dict):
                    continue
                owners.append(ReservationOwner(
                    label_selector={str(k): str(v) for k, v in sel.items()},
                    controller_kind=str(entry.get("controllerKind", "")),
                    controller_name=str(entry.get("controllerName", "")),
                    namespace=str(entry.get("namespace", "")),
                ))
            return owners
        except (ValueError, TypeError):
            return []

    @property
    def priority_class(self) -> PriorityClass:
        """Label override first, then numeric band (priority.go:74-84)."""
        if LABEL_POD_PRIORITY_CLASS in self.meta.labels:
            return priority_class_by_name(self.meta.labels[LABEL_POD_PRIORITY_CLASS])
        return priority_class_by_value(self.spec.priority)

    @property
    def sub_priority(self) -> int:
        """koordinator.sh/priority label (priority.go:107-116)."""
        try:
            return int(self.meta.labels.get(LABEL_POD_PRIORITY, "0") or "0")
        except ValueError:
            return 0

    def patch_copy(self) -> "Pod":
        """Cheap copy for store patches: fresh Pod/meta/spec objects with
        fresh copies of every MUTABLE container (label/annotation/selector
        dicts, ResourceLists, tolerations) — the store's update path runs the
        admission webhook, which mutates those in place, so they must not
        alias the old stored object or watch subscribers would see old==new.
        Scalar leaves are shared. A full deepcopy here was the scheduler's
        dominant host cost at 10k bindings per cycle."""
        spec = self.spec
        return replace(
            self,
            meta=replace(
                self.meta,
                labels=dict(self.meta.labels),
                annotations=dict(self.meta.annotations),
            ),
            spec=replace(
                spec,
                requests=spec.requests.copy(),
                limits=spec.limits.copy(),
                node_selector=dict(spec.node_selector),
                affinity_required_node_labels=dict(
                    spec.affinity_required_node_labels
                ),
                affinity_preferred=[
                    replace(t, labels=dict(t.labels))
                    for t in spec.affinity_preferred
                ],
                pod_affinity=[
                    replace(t, selector=dict(t.selector),
                            namespaces=list(t.namespaces))
                    for t in spec.pod_affinity
                ],
                pod_anti_affinity=[
                    replace(t, selector=dict(t.selector),
                            namespaces=list(t.namespaces))
                    for t in spec.pod_anti_affinity
                ],
                pod_affinity_preferred=[
                    replace(t, selector=dict(t.selector),
                            namespaces=list(t.namespaces))
                    for t in spec.pod_affinity_preferred
                ],
                topology_spread=[
                    replace(c, selector=dict(c.selector))
                    for c in spec.topology_spread
                ],
                tolerations=list(spec.tolerations),
                overhead=spec.overhead.copy(),
            ),
            conditions=[replace(c) for c in self.conditions],
        )

    @property
    def gang_name(self) -> str:
        return self.meta.labels.get(LABEL_POD_GROUP, "")

    @property
    def gang_key(self) -> str:
        """Namespaced gang identity: the pod-group label names a PodGroup in
        the POD's namespace (coscheduling core.go GetGangFullName), so two
        same-named gangs in different namespaces never collide."""
        name = self.meta.labels.get(LABEL_POD_GROUP, "")
        return f"{self.meta.namespace}/{name}" if name else ""

    @property
    def quota_name(self) -> str:
        return self.meta.labels.get(LABEL_QUOTA_NAME, "")

    @property
    def is_assigned(self) -> bool:
        return bool(self.spec.node_name)

    @property
    def is_terminated(self) -> bool:
        return self.phase in ("Succeeded", "Failed")

    @property
    def is_healthy(self) -> bool:
        """policy/v1 currentHealthy counts pods with the Ready condition;
        here that means scheduled and Running — a Pending/unassigned pod must
        NOT shore up a PodDisruptionBudget (disruption controller,
        pkg/controller/disruption in upstream k8s)."""
        return self.is_assigned and self.phase == "Running"


@dataclass
class Node:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    allocatable: ResourceList = field(default_factory=ResourceList)
    capacity: ResourceList = field(default_factory=ResourceList)
    unschedulable: bool = False
    taints: List[Tuple[str, str]] = field(default_factory=list)  # (key, value)
    ready: bool = True
    # node.status.images as image name -> sizeBytes (ImageLocality score)
    images: Dict[str, int] = field(default_factory=dict)
    # CSI attachable-volume limit (node.status.allocatable
    # attachable-volumes-csi-*); 0 = no limit reported
    attachable_volume_limit: int = 0

    def node_reservation(self):
        """(reserved ResourceList, reserved_cpus str, trims_allocatable) from
        the node-reservation annotation (apis/extension/node_reservation.go
        GetNodeReservation + pkg/util/node.go GetNodeReservationResources):
        reservedCPUs overrides the cpu quantity with the cpuset's core count;
        applyPolicy Default (or empty) trims schedulable allocatable,
        ReservedCPUsOnly reserves the cores without trimming. Malformed
        annotations reserve nothing (the reference logs and returns nil)."""
        raw = self.meta.annotations.get(ANNOTATION_NODE_RESERVATION)
        empty = ResourceList()
        if not raw:
            return empty, "", False
        import json

        from koordinator_tpu.api.resources import parse_quantity

        try:
            data = json.loads(raw)
            if not isinstance(data, dict):
                return empty, "", False
            resources = data.get("resources")
            if not isinstance(resources, dict):
                resources = {}
            reserved = ResourceList()
            for name, qty in resources.items():
                reserved.quantities[name] = parse_quantity(
                    str(qty), cpu=(name == "cpu"))
            cpus = str(data.get("reservedCPUs") or "")
            if cpus:
                from koordinator_tpu.utils.cpuset import CPUSet

                reserved.quantities["cpu"] = len(CPUSet.parse(cpus)) * 1000
            policy = data.get("applyPolicy") or "Default"
            return reserved, cpus, policy == "Default"
        except (ValueError, TypeError):
            return empty, "", False

    def system_qos_resource(self):
        """(cpuset str, exclusive bool) from the system-qos-resource
        annotation (apis/extension/system_qos.go GetSystemQOSResource):
        exclusive defaults to True; malformed annotations yield no cpuset."""
        raw = self.meta.annotations.get(ANNOTATION_NODE_SYSTEM_QOS)
        if not raw:
            return "", True
        import json

        try:
            data = json.loads(raw)
            if not isinstance(data, dict):
                return "", True
            cpuset = str(data.get("cpuset") or "")
            if cpuset:
                from koordinator_tpu.utils.cpuset import CPUSet

                CPUSet.parse(cpuset)  # malformed -> reserve nothing
            exclusive = data.get("cpusetExclusive")
            return cpuset, exclusive is None or bool(exclusive)
        except (ValueError, TypeError):
            return "", True


# ---------------------------------------------------------------------------
# NodeMetric CR (apis/slo/v1alpha1/nodemetric_types.go)
# ---------------------------------------------------------------------------


@dataclass
class PodMetricInfo:
    namespace: str = ""
    name: str = ""
    pod_usage: ResourceList = field(default_factory=ResourceList)
    priority_class: PriorityClass = PriorityClass.NONE


@dataclass
class NodeMetricInfo:
    node_usage: ResourceList = field(default_factory=ResourceList)
    # {duration_seconds: {"p95"|"p99"|"avg"|...: ResourceList}}
    aggregated_node_usages: Dict[int, Dict[str, ResourceList]] = field(
        default_factory=dict
    )
    # usage of system daemons outside pod cgroups
    system_usage: ResourceList = field(default_factory=ResourceList)


@dataclass
class NodeMetric:
    """Measured node utilization, reported by koordlet on an interval
    (statesinformer/impl/states_nodemetric.go:182-210) and consumed by LoadAware,
    LowNodeLoad, and the noderesource controller."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    update_time: float = 0.0
    node_metric: NodeMetricInfo = field(default_factory=NodeMetricInfo)
    pods_metric: List[PodMetricInfo] = field(default_factory=list)
    prod_reclaimable: ResourceList = field(default_factory=ResourceList)
    report_interval_seconds: int = 60
    aggregate_durations: List[int] = field(default_factory=lambda: [300, 900, 1800])


# ---------------------------------------------------------------------------
# Reservation CR (apis/scheduling/v1alpha1/reservation_types.go)
# ---------------------------------------------------------------------------


@dataclass
class ReservationOwner:
    """Owner matcher: label selector and/or controller reference
    (reservation_types.go ReservationOwner)."""

    label_selector: Dict[str, str] = field(default_factory=dict)
    controller_kind: str = ""
    controller_name: str = ""
    namespace: str = ""

    def matches(self, pod: Pod) -> bool:
        """All specified criteria must match (conjunction); an owner with no
        criteria matches every pod (reference ReservationOwnerMatcher.Match,
        pkg/util/reservation/reservation.go:402-409)."""
        if self.namespace and pod.meta.namespace != self.namespace:
            return False
        for k, v in self.label_selector.items():
            if pod.meta.labels.get(k) != v:
                return False
        if self.controller_kind and pod.meta.owner_kind != self.controller_kind:
            return False
        if self.controller_name and pod.meta.owner_name != self.controller_name:
            return False
        return True


@dataclass
class Reservation:
    """A resource pre-claim scheduled like a pod; matching pods later consume its
    reserved resources (pkg/scheduler/plugins/reservation/)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    template: PodSpec = field(default_factory=PodSpec)
    owners: List[ReservationOwner] = field(default_factory=list)
    ttl_seconds: Optional[int] = None
    expires_at: Optional[float] = None
    allocate_once: bool = True
    # status
    phase: str = "Pending"  # Pending/Available/Succeeded/Failed
    node_name: str = ""
    allocatable: ResourceList = field(default_factory=ResourceList)
    allocated: ResourceList = field(default_factory=ResourceList)
    current_owners: List[str] = field(default_factory=list)  # pod keys
    # set when this entry mirrors an operating-mode POD (operating_pod.go
    # ReservationPodOperatingMode) instead of a Reservation CR: the pod's
    # lifecycle governs it and no CR exists in the store
    from_pod_key: str = ""

    @property
    def is_available(self) -> bool:
        return self.phase == "Available" and bool(self.node_name)

    def is_expired(self, now: Optional[float] = None) -> bool:
        now = time.time() if now is None else now
        if self.expires_at is not None:
            return now >= self.expires_at
        if self.ttl_seconds is not None:
            return now >= self.meta.creation_timestamp + self.ttl_seconds
        return False

    def matches(self, pod: Pod) -> bool:
        return any(o.matches(pod) for o in self.owners)


# ---------------------------------------------------------------------------
# PodGroup CR (sigs.k8s.io scheme; plugins/coscheduling)
# ---------------------------------------------------------------------------


@dataclass
class PodGroup:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    min_member: int = 1
    schedule_timeout_seconds: int = 0  # 0 = use CoschedulingArgs.defaultTimeout
    # status
    phase: str = "Pending"
    scheduled: int = 0


# ---------------------------------------------------------------------------
# ElasticQuota CR (sigs.k8s.io scheme; plugins/elasticquota)
# ---------------------------------------------------------------------------


@dataclass
class ElasticQuota:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    min: ResourceList = field(default_factory=ResourceList)
    max: ResourceList = field(default_factory=ResourceList)

    @property
    def parent(self) -> str:
        return self.meta.labels.get(LABEL_QUOTA_PARENT, "")

    @property
    def is_parent(self) -> bool:
        return self.meta.labels.get(LABEL_QUOTA_IS_PARENT, "false") == "true"

    @property
    def shared_weight(self) -> ResourceList:
        """Fair-sharing weight; falls back to spec.max on missing/invalid/zero
        annotation (reference apis/extension/elastic_quota.go:89-99). Values are
        k8s quantity strings."""
        import json

        from koordinator_tpu.api.resources import ResourceName, parse_quantity

        raw = self.meta.annotations.get(LABEL_QUOTA_SHARED_WEIGHT)
        if raw:
            try:
                data = json.loads(raw)
                if isinstance(data, dict):
                    parsed = {
                        k: parse_quantity(v, cpu=(k == ResourceName.CPU))
                        for k, v in data.items()
                    }
                    if parsed and all(v > 0 for v in parsed.values()):
                        return ResourceList(parsed)
            except (ValueError, TypeError):
                pass
        return self.max.copy()

    @property
    def allow_lent_resource(self) -> bool:
        """Whether unused min may be lent to siblings
        (apis/extension/elastic_quota.go:70-72: anything but "false")."""
        return self.meta.labels.get(LABEL_QUOTA_ALLOW_LENT, "") != "false"

    @property
    def guaranteed(self) -> ResourceList:
        """Floor the runtime never drops below
        (apis/extension/elastic_quota.go:150-157)."""
        import json

        from koordinator_tpu.api.resources import ResourceName, parse_quantity

        raw = self.meta.annotations.get(ANNOTATION_QUOTA_GUARANTEED)
        if raw:
            try:
                data = json.loads(raw)
                if isinstance(data, dict):
                    return ResourceList({
                        k: parse_quantity(v, cpu=(k == ResourceName.CPU))
                        for k, v in data.items()
                    })
            except (ValueError, TypeError):
                pass
        return ResourceList()

    @property
    def tree_id(self) -> str:
        return self.meta.labels.get(LABEL_QUOTA_TREE_ID, "")


# ---------------------------------------------------------------------------
# Device CR (apis/scheduling/v1alpha1/device_types.go)
# ---------------------------------------------------------------------------


@dataclass
class DeviceInfo:
    type: str = "gpu"  # gpu | rdma | fpga
    uuid: str = ""
    minor: int = 0
    health: bool = True
    resources: ResourceList = field(default_factory=ResourceList)
    numa_node: int = -1


@dataclass
class Device:
    """Per-node device inventory reported by koordlet's device collectors."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)  # name == node name
    devices: List[DeviceInfo] = field(default_factory=list)


# ---------------------------------------------------------------------------
# PersistentVolumeClaim (core v1 subset consumed by the PVC informer)
# ---------------------------------------------------------------------------


@dataclass
class PersistentVolumeClaim:
    """Subset of core v1 PVC: the koordlet pvc informer needs the
    namespace/name -> bound volume name mapping (reference
    pkg/koordlet/statesinformer/impl/states_pvc.go:44-60); the scheduler's
    VolumeBinding analog (scheduler/volumebinding.py) additionally reads
    the storage class and requested capacity of unbound claims."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    volume_name: str = ""  # spec.volumeName once bound
    # for a bound claim this is status.capacity; for an unbound claim it is
    # spec.resources.requests (what a matching PV must cover)
    capacity: ResourceList = field(default_factory=ResourceList)
    storage_class_name: str = ""  # spec.storageClassName ("" = classless)
    phase: str = ""  # "", "Pending", "Bound" — volume_name wins when set

    @property
    def is_bound(self) -> bool:
        return bool(self.volume_name)


@dataclass
class PersistentVolume:
    """Subset of core v1 PV for the VolumeZone filter and the VolumeBinding
    analog: a PV carrying zone/region topology labels restricts pods
    mounting its claims to matching nodes (the vendored kube-scheduler
    VolumeZone plugin the reference inherits via
    cmd/koord-scheduler/main.go:53-62's upstream app); an Available PV is a
    static-binding candidate for unbound WaitForFirstConsumer claims
    (upstream VolumeBinding, same vendoring)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    capacity: ResourceList = field(default_factory=ResourceList)
    storage_class_name: str = ""
    claim_ref: str = ""  # "namespace/name" of the bound claim once bound
    phase: str = "Available"  # Available | Bound | Released

    ZONE_LABELS = ("topology.kubernetes.io/zone",
                   "topology.kubernetes.io/region",
                   "failure-domain.beta.kubernetes.io/zone",
                   "failure-domain.beta.kubernetes.io/region")

    def zone_pairs(self) -> List[Tuple[str, str]]:
        return [(k, v) for k, v in self.meta.labels.items()
                if k in self.ZONE_LABELS]


@dataclass
class StorageClass:
    """storage.k8s.io/v1 StorageClass subset for volume binding: the
    volumeBindingMode decides whether an unbound claim blocks scheduling
    (Immediate — the async PV controller owns it) or binds at schedule time
    (WaitForFirstConsumer), and allowedTopologies restricts where a dynamic
    provisioner may create volumes. Cluster-scoped: namespace is ""."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    provisioner: str = ""
    volume_binding_mode: str = "Immediate"  # or "WaitForFirstConsumer"
    # allowedTopologies: each term is a tuple of (key, allowed values)
    # requirements ANDed together; terms are ORed (core v1
    # TopologySelectorTerm.matchLabelExpressions)
    allowed_topologies: List[Tuple[Tuple[str, Tuple[str, ...]], ...]] = field(
        default_factory=list)


# ---------------------------------------------------------------------------
# NodeSLO CR (apis/slo/v1alpha1/nodeslo_types.go)
# ---------------------------------------------------------------------------


@dataclass
class ResourceThresholdStrategy:
    """resourceUsedThresholdWithBE: drives cpusuppress/evict
    (qosmanager/plugins/cpusuppress)."""

    enable: bool = False
    cpu_suppress_threshold_percent: int = 65
    cpu_suppress_policy: str = "cpuset"  # cpuset | cfsQuota
    memory_evict_threshold_percent: int = 70
    memory_evict_lower_percent: Optional[int] = None
    cpu_evict_be_usage_threshold_percent: int = 90


@dataclass
class ResourceQOSStrategy:
    """Per-QoS-class cgroup knobs (group identity, memory qos, resctrl, blkio)."""

    ls_enable: bool = False
    be_enable: bool = False
    ls_group_identity: int = 2    # bvt.warp_ns group for LS
    be_group_identity: int = -1   # bvt for BE
    llc_be_percent: int = 100     # resctrl LLC ways for BE
    mba_be_percent: int = 100     # resctrl memory-bandwidth for BE
    blkio_enable: bool = False    # per-QoS io weights (blkioQOS)
    ls_blkio_weight: int = 500    # io.weight / blkio.bfq.weight for LS tier
    be_blkio_weight: int = 100    # and for BE tier
    core_sched_enable: bool = False  # SMT core-sched cookies per QoS group
    net_qos_policy: str = ""      # "" disabled | "terwayQos" (NETQOSPolicy)
    net_hw_tx_bps: int = 0        # node NIC egress ceiling, bytes/s (0 = none)
    net_hw_rx_bps: int = 0        # node NIC ingress ceiling


@dataclass
class CPUBurstStrategy:
    policy: str = "none"  # none | cpuBurstOnly | cfsQuotaBurstOnly | auto
    cpu_burst_percent: int = 1000
    cfs_quota_burst_percent: int = 300
    cfs_quota_burst_period_seconds: int = -1
    shared_pool_threshold_percent: int = 50


@dataclass
class SystemStrategy:
    min_free_kbytes_factor: int = 100
    watermark_scale_factor: int = 150
    memcg_reap_enabled: bool = False


@dataclass
class NodeSLO:
    """Per-node QoS strategy rendered by the nodeslo controller from the cluster
    sloconfig ConfigMap + node overrides (pkg/slo-controller/nodeslo/)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)  # name == node name
    resource_used_threshold_with_be: ResourceThresholdStrategy = field(
        default_factory=ResourceThresholdStrategy
    )
    resource_qos_strategy: ResourceQOSStrategy = field(
        default_factory=ResourceQOSStrategy
    )
    cpu_burst_strategy: CPUBurstStrategy = field(default_factory=CPUBurstStrategy)
    system_strategy: SystemStrategy = field(default_factory=SystemStrategy)
    extensions: Dict[str, Any] = field(default_factory=dict)


def host_applications(slo: Optional["NodeSLO"]) -> List[Dict[str, Any]]:
    """Canonical accessor for the NodeSLO `hostApplications` extension
    (apis/slo/v1alpha1/nodeslo_types.go:409 HostApplications): a list of
    {name, cgroupPath, qos} entries describing non-k8s host services.
    Consumers (metricsadvisor collector, qosmanager suppress accounting,
    runtimehooks group identity) each require different fields, so this only
    normalizes the container: non-dict entries are dropped."""
    if slo is None:
        return []
    apps = (slo.extensions or {}).get("hostApplications", [])
    return [a for a in apps if isinstance(a, dict)]


# ---------------------------------------------------------------------------
# NodeResourceTopology CR (reported by koordlet statesinformer nodeTopo plugin)
# ---------------------------------------------------------------------------


@dataclass
class CPUInfo:
    cpu_id: int = 0
    core_id: int = 0
    socket_id: int = 0
    numa_node_id: int = 0


@dataclass
class NUMAZone:
    numa_id: int = 0
    allocatable: ResourceList = field(default_factory=ResourceList)


@dataclass
class NodeResourceTopology:
    meta: ObjectMeta = field(default_factory=ObjectMeta)  # name == node name
    cpus: List[CPUInfo] = field(default_factory=list)
    zones: List[NUMAZone] = field(default_factory=list)
    kubelet_cpu_manager_policy: str = "none"
    # cpus already claimed by kubelet static cpu-manager (cpu ids)
    kubelet_reserved_cpus: List[int] = field(default_factory=list)


# ---------------------------------------------------------------------------
# PodMigrationJob CR (apis/scheduling/v1alpha1/pod_migration_job_types.go)
# ---------------------------------------------------------------------------


@dataclass
class PodMigrationJob:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    pod_namespace: str = ""
    pod_name: str = ""
    mode: str = "ReservationFirst"  # ReservationFirst | EvictDirectly
    ttl_seconds: int = 300
    # status
    phase: str = "Pending"  # Pending/Running/Succeeded/Failed
    reservation_name: str = ""
    message: str = ""


@dataclass
class PodDisruptionBudget:
    """policy/v1 PDB subset the eviction helpers honor
    (pkg/descheduler/evictions respects PDBs before evicting)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Dict[str, str] = field(default_factory=dict)  # label selector
    min_available: Optional[int] = None
    max_unavailable: Optional[int] = None

    def matches(self, pod: "Pod") -> bool:
        if pod.meta.namespace != self.meta.namespace:
            return False
        return all(pod.meta.labels.get(k) == v for k, v in self.selector.items())


# ---------------------------------------------------------------------------
# ClusterColocationProfile CR (webhook/pod/mutating/cluster_colocation_profile.go)
# ---------------------------------------------------------------------------


@dataclass
class ClusterColocationProfile:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    namespace_selector: Dict[str, str] = field(default_factory=dict)
    selector: Dict[str, str] = field(default_factory=dict)
    # percent of matching pods the profile applies to (None == 100;
    # cluster_colocation_profile.go:147-154 "Probability")
    probability: Optional[int] = None
    qos_class: Optional[QoSClass] = None
    priority_class_name: str = ""
    koordinator_priority: Optional[int] = None
    scheduler_name: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)


@dataclass
class ConfigMap:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    data: Dict[str, str] = field(default_factory=dict)


@dataclass
class Namespace:
    """core/v1 Namespace (labels only): the colocation-profile webhook
    matches its namespaceSelector against these labels
    (pod/mutating/cluster_colocation_profile.go:113-130)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)


# ---------------------------------------------------------------------------
# ElasticQuotaProfile CR (pkg/quota-controller/profile)
# ---------------------------------------------------------------------------


@dataclass
class ElasticQuotaProfile:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    quota_name: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)
    quota_labels: Dict[str, str] = field(default_factory=dict)
