"""koord-manager: one composed control-plane runner.

Analog of the koord-manager binary (`cmd/koord-manager/main.go` +
`options/controllers.go:34-39`): a single process that installs the
nodemetric / noderesource / nodeslo / quota-profile controllers and the
admission webhook server, with every controller gated behind ONE leader
lease — standby replicas serve webhooks but run no control loops, exactly
like controller-runtime managers with LeaderElection enabled.

The webhook installs into the ObjectStore's admission-interceptor seam
(`store.set_admission`) immediately at construction on every replica:
admission is load-balanced across replicas in the reference too, so it is
NOT election-gated.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from koordinator_tpu.client.leaderelection import ElectedRunner, LeaderElector
from koordinator_tpu.client.store import ObjectStore
from koordinator_tpu.quotacontroller import QuotaProfileController
from koordinator_tpu.slocontroller import (
    NodeMetricController,
    NodeResourceController,
    NodeSLOController,
)
from koordinator_tpu.utils.sloconfig import ColocationConfig
from koordinator_tpu.webhook import AdmissionServer

MANAGER_LEASE = "koord-manager"


class Manager:
    """Composed koord-manager replica. `tick(now)` renews/acquires the lease
    and, while leading, reconciles every installed controller once."""

    def __init__(
        self,
        store: ObjectStore,
        identity: str = "koord-manager-0",
        config: Optional[ColocationConfig] = None,
        lease_duration_seconds: float = 15.0,
    ) -> None:
        self.store = store
        self.identity = identity
        self.webhook = AdmissionServer(store)
        # webhooks are served by every replica (leader or not)
        store.set_admission("koord-manager-webhook", self.webhook.admit)
        self.controllers = {
            "nodemetric": NodeMetricController(store, config),
            "noderesource": NodeResourceController(store, config),
            "nodeslo": NodeSLOController(store),
            "quotaprofile": QuotaProfileController(store),
        }
        self.elector = LeaderElector(
            store, MANAGER_LEASE, identity,
            lease_duration_seconds=lease_duration_seconds)
        self._runner = ElectedRunner(self.elector, self._reconcile_all)
        self.last_changes: Dict[str, int] = {}
        self.reconcile_rounds = 0

    @property
    def is_leader(self) -> bool:
        return self.elector.is_leader

    def _reconcile_all(self, now: float) -> None:
        self.last_changes = {
            "nodemetric": self.controllers["nodemetric"].reconcile(),
            "noderesource": self.controllers["noderesource"].reconcile(now),
            "nodeslo": self.controllers["nodeslo"].reconcile(),
            "quotaprofile": self.controllers["quotaprofile"].reconcile(),
        }
        self.reconcile_rounds += 1

    def tick(self, now: Optional[float] = None) -> bool:
        """One manager round: returns True iff this replica led and ran."""
        return self._runner.tick(time.time() if now is None else now)

    def stop(self, now: Optional[float] = None) -> None:
        """Graceful shutdown: release the lease (ReleaseOnCancel) and
        uninstall this replica's webhook."""
        self.elector.release(time.time() if now is None else now)
        self.store.set_admission("koord-manager-webhook", None)
