"""koord-manager: one composed control-plane runner.

Analog of the koord-manager binary (`cmd/koord-manager/main.go` +
`options/controllers.go:34-39`): a single process that installs the
nodemetric / noderesource / nodeslo / quota-profile controllers and the
admission webhook server, with every controller gated behind ONE leader
lease — standby replicas serve webhooks but run no control loops, exactly
like controller-runtime managers with LeaderElection enabled.

The webhook installs into the ObjectStore's admission-interceptor seam
(`store.set_admission`) immediately at construction on every replica:
admission is load-balanced across replicas in the reference too, so it is
NOT election-gated.

koordcolo (colo/): with ``KOORD_TPU_COLO=on`` (the default) the
noderesource reconcile runs as the DEVICE colo pass — the slo-controller
overcommit formula plus the elastic-quota runtime fold as one jitted
program over the scheduler's shared DeviceSnapshot (the third consumer),
ladder-protected with the retained host controllers as the fallback
oracle. A co-located ``scheduler`` wires the pack into the
SnapshotCache's existing subscriptions and the uploads into the
scheduler's device mirror; standalone managers own both. ``host`` pins
the host oracles (the A/B twin), ``off`` detaches the colo subsystem
entirely. Every controller reconcile is instrumented in the shared obs
Registry (manager_metrics) and the manager carries a Tracer + flight
ring for the ``--obs-port`` surfaces.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from koordinator_tpu import manager_metrics
from koordinator_tpu.client.leaderelection import ElectedRunner, LeaderElector
from koordinator_tpu.client.store import ObjectStore
from koordinator_tpu.obs import Tracer
from koordinator_tpu.quotacontroller import QuotaProfileController
from koordinator_tpu.slocontroller import (
    NodeMetricController,
    NodeResourceController,
    NodeSLOController,
)
from koordinator_tpu.utils.sloconfig import ColocationConfig
from koordinator_tpu.webhook import AdmissionServer

MANAGER_LEASE = "koord-manager"


class Manager:
    """Composed koord-manager replica. `tick(now)` renews/acquires the lease
    and, while leading, reconciles every installed controller once."""

    def __init__(
        self,
        store: ObjectStore,
        identity: str = "koord-manager-0",
        config: Optional[ColocationConfig] = None,
        lease_duration_seconds: float = 15.0,
        scheduler=None,
        colo: Optional[str] = None,
    ) -> None:
        from koordinator_tpu.colo.reconciler import colo_from_env

        self.store = store
        self.identity = identity
        self.scheduler = scheduler
        self.tracer = Tracer()
        self.webhook = AdmissionServer(store)
        # webhooks are served by every replica (leader or not)
        store.set_admission("koord-manager-webhook", self.webhook.admit)
        self.controllers = {
            "nodemetric": NodeMetricController(store, config),
            "noderesource": NodeResourceController(store, config),
            "nodeslo": NodeSLOController(store),
            "quotaprofile": QuotaProfileController(store),
        }
        self.colo_mode = colo_from_env() if colo is None else colo
        if self.colo_mode not in ("on", "off", "host"):
            raise ValueError(
                f"colo must be 'on', 'off' or 'host'; "
                f"got {self.colo_mode!r}")
        self.colo = None
        if self.colo_mode != "off":
            self.colo = self._build_colo()
        self.elector = LeaderElector(
            store, MANAGER_LEASE, identity,
            lease_duration_seconds=lease_duration_seconds)
        self._runner = ElectedRunner(self.elector, self._reconcile_all)
        self.last_changes: Dict[str, int] = {}
        self.reconcile_rounds = 0

    def _build_colo(self):
        """Wire the DeviceColoReconciler: pack from the co-located
        scheduler's SnapshotCache (one event stream, three consumers)
        and uploads through its DeviceSnapshot, or standalone pack +
        quota plugin when the manager runs alone. A co-located
        reconciler inherits the scheduler's RESOLVED mesh and dispatch
        deadline (the koordguard determinism discipline)."""
        from koordinator_tpu.colo.pack import ColoPack
        from koordinator_tpu.colo.reconciler import DeviceColoReconciler

        controller = self.controllers["noderesource"]
        config_source = controller.config_source
        scheduler = self.scheduler
        if scheduler is not None and scheduler.snapshot_cache is not None:
            pack = scheduler.snapshot_cache.colo_pack(config_source)
        else:
            pack = ColoPack(self.store, config_source, subscribe=True)
        quota_plugin = (scheduler.extender.plugin("ElasticQuota")
                        if scheduler is not None else None)
        if quota_plugin is None:
            from koordinator_tpu.scheduler.plugins.elasticquota import (
                ElasticQuotaPlugin,
            )

            quota_plugin = ElasticQuotaPlugin()
            quota_plugin.register(self.store)
        if scheduler is not None:
            mesh = getattr(scheduler, "_configured_mesh", None)
            getter = lambda: scheduler.device_snapshot  # noqa: E731
            dl = getattr(scheduler, "dispatch_deadline_seconds", None)
            deadline_ms = dl * 1000.0 if dl else 0
        else:
            from koordinator_tpu.parallel.mesh import mesh_from_env

            mesh = mesh_from_env()
            getter = None
            deadline_ms = None
        return DeviceColoReconciler(
            self.store, controller, quota_plugin, pack,
            mesh=mesh, snapshot_getter=getter,
            dispatch_deadline_ms=deadline_ms,
            tracer=self.tracer,
            engine=("on" if self.colo_mode == "on" else "host"),
            # koordwatch: the co-located colo pass records into the
            # SCHEDULER's device timeline — one device, one ring, one
            # decision-id sequence across all three consumers
            timeline=getattr(scheduler, "timeline", None))

    @property
    def is_leader(self) -> bool:
        return self.elector.is_leader

    def _reconcile_one(self, name: str, now: float) -> int:
        t0 = time.perf_counter()
        if name == "noderesource":
            if self.colo is not None:
                changes = self.colo.reconcile(now)
            else:
                # KOORD_TPU_COLO=off: the legacy reconcile still gets
                # its per-controller span (it is the one you are most
                # likely tracing during a colo incident)
                with self.tracer.span(name):
                    changes = self.controllers[name].reconcile(now)
        else:
            with self.tracer.span(name):
                changes = self.controllers[name].reconcile()
        manager_metrics.RECONCILE_SECONDS.observe(
            time.perf_counter() - t0, controller=name)
        manager_metrics.RECONCILES_TOTAL.inc(controller=name)
        return changes

    def _reconcile_all(self, now: float) -> None:
        self.last_changes = {
            name: self._reconcile_one(name, now)
            for name in ("nodemetric", "noderesource", "nodeslo",
                         "quotaprofile")
        }
        self.reconcile_rounds += 1

    def tick(self, now: Optional[float] = None) -> bool:
        """One manager round: returns True iff this replica led and ran."""
        return self._runner.tick(time.time() if now is None else now)

    def health_snapshot(self) -> dict:
        """Liveness payload for the ObsServer /healthz surface: lease
        state, reconcile rounds, and the colo ladder under "degraded"
        (the same key the scheduler serves, so one probe grammar covers
        the binaries)."""
        out = {
            "is_leader": self.is_leader,
            "reconcile_rounds": self.reconcile_rounds,
            "colo_mode": self.colo_mode,
        }
        if self.colo is not None:
            out["degraded"] = self.colo.ladder.snapshot()
            out["colo_engine"] = self.colo.last_pass_stats.get(
                "engine", "none")
        return out

    def stop(self, now: Optional[float] = None) -> None:
        """Graceful shutdown: release the lease (ReleaseOnCancel) and
        uninstall this replica's webhook."""
        self.elector.release(time.time() if now is None else now)
        self.store.set_admission("koord-manager-webhook", None)
