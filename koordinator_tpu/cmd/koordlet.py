"""koordlet binary: the node agent daemon.

Analog of reference cmd/koordlet: metrics collection, QoS enforcement,
runtime hooks, audit — all module loops behind Daemon.run. On a real node
(root, cgroupfs) run with --node NAME; for a demo/CI machine --fake-node
builds the hermetic /sys + /proc + cgroup tree (the FileTestUtil analog)
and seeds a minimal busy node so every collector has something to read."""

from __future__ import annotations

import argparse
import sys

from koordinator_tpu.cmd import (
    add_cluster_flags,
    add_loop_flags,
    build_store,
    parse_feature_gates,
)


def _seed_fake_node(fs, store, node_name: str, cores: int = 16) -> None:
    from koordinator_tpu.api.objects import Node, ObjectMeta
    from koordinator_tpu.api.resources import ResourceList
    from koordinator_tpu.client.store import KIND_NODE
    from koordinator_tpu.koordlet.util import system as sysutil

    GIB = 1024**3
    if store.get(KIND_NODE, f"/{node_name}") is None:
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name=node_name, namespace=""),
            allocatable=ResourceList.of(cpu=cores * 1000, memory=64 * GIB,
                                        pods=110)))
    fs.set_proc("stat", "cpu  1000 0 1000 8000 0 0 0 0 0 0\n")
    fs.set_proc(
        "meminfo",
        "MemTotal: %d kB\nMemFree: %d kB\nMemAvailable: %d kB\n"
        % (64 * GIB // 1024, 32 * GIB // 1024, 48 * GIB // 1024))
    fs.set_cgroup("", sysutil.CPU_PRESSURE,
                  "some avg10=0.10 avg60=0.10 avg300=0.10 total=100\n"
                  "full avg10=0.00 avg60=0.00 avg300=0.00 total=0\n")
    fs.set_cgroup("", sysutil.MEMORY_PRESSURE,
                  "some avg10=0.00 avg60=0.00 avg300=0.00 total=0\n"
                  "full avg10=0.00 avg60=0.00 avg300=0.00 total=0\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="koordlet")
    add_cluster_flags(ap)
    add_loop_flags(ap, default_interval=10.0)
    ap.add_argument("--node", default="node-0", help="this node's name")
    ap.add_argument("--fake-node", action="store_true",
                    help="hermetic fake /sys+/proc+cgroup tree (demo/CI)")
    ap.add_argument("--checkpoint-dir",
                    help="prediction/metriccache checkpoint directory")
    ap.add_argument("--feature-gates", help="Gate=bool[,Gate=bool...]")
    args = ap.parse_args(argv)

    from koordinator_tpu.koordlet.daemon import Daemon
    from koordinator_tpu.utils.features import KOORDLET_GATES

    parse_feature_gates(KOORDLET_GATES, args.feature_gates)
    store = build_store(args)
    fs = None
    config = None
    if args.fake_node:
        from koordinator_tpu.koordlet.util.system import FakeFS

        fs = FakeFS(use_cgroup_v2=True)
        _seed_fake_node(fs, store, args.node)
        config = fs.config
    daemon = Daemon(store, args.node, config,
                    checkpoint_dir=args.checkpoint_dir,
                    autodetect_cgroups=not args.fake_node)
    print(f"koordlet: node={args.node} fake={bool(fs)}", file=sys.stderr)
    try:
        daemon.run(interval_seconds=args.interval,
                   max_ticks=args.max_ticks or None)
    except KeyboardInterrupt:
        pass
    finally:
        if fs is not None:
            fs.cleanup()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
