"""koord-runtime-proxy binary: CRI or docker interception.

Analog of reference cmd/koord-runtime-proxy main.go:57-67 (the mode
switch): --mode cri serves a gRPC CRI proxy between kubelet and the
containerd socket; --mode docker serves the Engine-API reverse proxy.
Hooks dial the koordlet hook server over its unix socket; FailurePolicy
governs hook-server outages."""

from __future__ import annotations

import argparse
import sys
import threading


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="koord-runtime-proxy")
    ap.add_argument("--mode", choices=["cri", "docker"], default="cri")
    ap.add_argument("--proxy-endpoint",
                    default="/var/run/koord-runtimeproxy.sock")
    ap.add_argument("--backend-endpoint", default=None,
                    help="runtime socket (default: containerd's for "
                    "--mode cri, docker's for --mode docker)")
    ap.add_argument("--hook-server-endpoint",
                    help="koordlet hook server unix socket")
    ap.add_argument("--failure-policy", choices=["Ignore", "Fail"],
                    default="Ignore")
    args = ap.parse_args(argv)
    if args.backend_endpoint is None:
        args.backend_endpoint = (
            "/var/run/docker.sock" if args.mode == "docker"
            else "/var/run/containerd/containerd.sock")

    from koordinator_tpu.runtimeproxy.hookclient import HookClient
    from koordinator_tpu.runtimeproxy.server import FailurePolicy

    policy = (FailurePolicy.FAIL if args.failure_policy == "Fail"
              else FailurePolicy.IGNORE)
    hook = (HookClient(args.hook_server_endpoint)
            if args.hook_server_endpoint else None)
    if args.mode == "cri":
        from koordinator_tpu.runtimeproxy.criserver import CRIProxyServer

        server = CRIProxyServer(args.proxy_endpoint, args.backend_endpoint,
                                hook_client=hook, failure_policy=policy)
        server.start()  # start() replays failover() itself
    else:
        from koordinator_tpu.runtimeproxy.dockerserver import (
            DockerProxyServer,
        )

        server = DockerProxyServer(args.proxy_endpoint,
                                   args.backend_endpoint,
                                   hook_client=hook, failure_policy=policy)
        server.start()
    print(f"koord-runtime-proxy: mode={args.mode} "
          f"proxy={args.proxy_endpoint} backend={args.backend_endpoint}",
          file=sys.stderr)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
