"""koord-descheduler binary: profile runner loop.

Analog of reference cmd/koord-descheduler: periodic Deschedule/Balance
profile execution with leader-election gating and the migration
controller's arbitration."""

from __future__ import annotations

import argparse
import sys

from koordinator_tpu.cmd import (
    add_cluster_flags,
    add_loop_flags,
    build_store,
    run_ticks,
    serve_obs,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="koord-descheduler")
    add_cluster_flags(ap)
    add_loop_flags(ap, default_interval=60.0)
    ap.add_argument("--leader-elect", action="store_true")
    ap.add_argument("--identity", default="koord-descheduler-0")
    ap.add_argument("--obs-port", type=int, default=0,
                    help="serve /metrics (0 = off)")
    args = ap.parse_args(argv)

    from koordinator_tpu.client.leaderelection import LeaderElector
    from koordinator_tpu.descheduler import Descheduler

    store = build_store(args)
    elector = (
        LeaderElector(store, "koord-descheduler", args.identity)
        if args.leader_elect else None
    )
    desched = Descheduler(store, elector=elector)
    from koordinator_tpu.descheduler import metrics as descheduler_metrics

    obs_server = serve_obs(
        args.obs_port, descheduler_metrics.REGISTRY, "koord-descheduler",
        # koordwatch: the rebalance pass's device-window ring (private
        # when the descheduler runs without a co-located scheduler)
        timeline=(desched.rebalancer.timeline
                  if desched.rebalancer is not None else None))

    def tick():
        summary = desched.run_once()
        print(f"koord-descheduler: {summary}", file=sys.stderr)

    run_ticks(tick, args.interval, args.max_ticks, "koord-descheduler")
    if obs_server is not None:
        obs_server.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
