"""TPU scheduling sidecar binary: serve ScheduleBatch next to the chips.

The rebuild-specific sixth binary (SURVEY.md 5.8): a gRPC server wrapping
the fused full-chain kernel, consumed by the Python cycle driver
(--sidecar-address), by the reference's Go event loop, or by the C++
client (native/sidecar_client.cpp). Step functions cache per shape."""

from __future__ import annotations

import argparse
import sys
import threading


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="koord-sidecar")
    ap.add_argument("--listen", default="unix:///tmp/koord-sidecar.sock",
                    help="gRPC bind address (unix:///path or host:port)")
    args = ap.parse_args(argv)

    from koordinator_tpu.scheduler.sidecar import serve_sidecar

    server = serve_sidecar(args.listen)
    print(f"koord-sidecar: serving ScheduleBatch on {args.listen}",
          file=sys.stderr)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    server.stop(0)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
