"""koord-scheduler binary: the batched scheduling cycle as a daemon.

Analog of reference cmd/koord-scheduler (main.go registers the plugin set
into the upstream scheduler app; here the cycle driver IS the scheduleOne
loop). Serves the frameworkext debug/services endpoints and the scheduler
metrics registry over HTTP, gates cycles on leader election when
--leader-elect is set, and can offload the kernel pass to a TPU sidecar
(--sidecar-address) with in-process degradation."""

from __future__ import annotations

import argparse
import sys

from koordinator_tpu.cmd import (
    add_cluster_flags,
    add_loop_flags,
    build_store,
    parse_feature_gates,
    run_ticks,
    serve_obs,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="koord-scheduler")
    add_cluster_flags(ap)
    add_loop_flags(ap, default_interval=1.0)
    ap.add_argument("--leader-elect", action="store_true")
    ap.add_argument("--identity", default="koord-scheduler-0")
    ap.add_argument("--sidecar-address",
                    help="gRPC address of the TPU scheduling sidecar")
    ap.add_argument("--services-port", type=int, default=0,
                    help="serve /apis/v1/... debug endpoints (0 = off)")
    ap.add_argument("--obs-port", type=int, default=0,
                    help="serve /metrics + /traces (0 = off)")
    ap.add_argument("--feature-gates", help="Gate=bool[,Gate=bool...]")
    args = ap.parse_args(argv)

    from koordinator_tpu.client.leaderelection import LeaderElector
    from koordinator_tpu.scheduler.cycle import Scheduler
    from koordinator_tpu.utils.features import SCHEDULER_GATES

    parse_feature_gates(SCHEDULER_GATES, args.feature_gates)
    store = build_store(args)
    elector = (
        LeaderElector(store, "koord-scheduler", args.identity)
        if args.leader_elect else None
    )
    sched = Scheduler(store, elector=elector,
                      sidecar_address=args.sidecar_address)
    server = None
    if args.services_port:
        server, _thread = sched.extender.services.serve(args.services_port)
        print(f"koord-scheduler: services on "
              f"127.0.0.1:{server.server_address[1]}", file=sys.stderr)
    from koordinator_tpu.scheduler import metrics as scheduler_metrics

    obs_server = serve_obs(args.obs_port, scheduler_metrics.REGISTRY,
                           "koord-scheduler", tracer=sched.tracer,
                           health_provider=sched.health_snapshot,
                           explain_provider=sched.explain_record,
                           flight=sched.flight,
                           timeline=sched.timeline)

    def tick():
        result = sched.run_cycle()
        if result.skipped_not_leader:
            return
        print(
            f"koord-scheduler: bound={len(result.bound)} "
            f"failed={len(result.failed)} rejected={len(result.rejected)} "
            f"kernel={result.kernel_seconds * 1000:.1f}ms"
            + (f" sidecar_fallbacks={sched.sidecar_fallbacks}"
               if args.sidecar_address else ""),
            file=sys.stderr,
        )

    run_ticks(tick, args.interval, args.max_ticks, "koord-scheduler")
    if server is not None:
        server.shutdown()
    if obs_server is not None:
        obs_server.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
