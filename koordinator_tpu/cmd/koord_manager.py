"""koord-manager binary: slo-controllers + quota profiles + webhooks.

Analog of reference cmd/koord-manager: all controllers behind ONE leader
election; the admission webhook serves on every replica (store-level
interceptor here, the apiserver-webhook analog)."""

from __future__ import annotations

import argparse
import sys

from koordinator_tpu.cmd import (
    add_cluster_flags,
    add_loop_flags,
    build_store,
    parse_feature_gates,
    run_ticks,
    serve_obs,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="koord-manager")
    add_cluster_flags(ap)
    add_loop_flags(ap, default_interval=15.0)
    ap.add_argument("--identity", default="koord-manager-0")
    ap.add_argument("--feature-gates", help="Gate=bool[,Gate=bool...]")
    ap.add_argument("--obs-port", type=int, default=0,
                    help="serve /metrics + /traces + /healthz (0 = off)")
    args = ap.parse_args(argv)

    from koordinator_tpu import manager_metrics
    from koordinator_tpu.manager import Manager
    from koordinator_tpu.utils.features import MANAGER_GATES

    parse_feature_gates(MANAGER_GATES, args.feature_gates)
    store = build_store(args)
    mgr = Manager(store, identity=args.identity)
    # /healthz carries the colo ladder snapshot under "degraded" and
    # /debug/flightrecorder serves the colo flight ring (dumps on colo
    # parity mismatch / dispatch-deadline overrun)
    obs_server = serve_obs(
        args.obs_port, manager_metrics.REGISTRY, "koord-manager",
        tracer=mgr.tracer,
        health_provider=mgr.health_snapshot,
        flight=(mgr.colo.flight if mgr.colo is not None else None),
        # koordwatch: the colo pass's device-window ring
        timeline=(mgr.colo.timeline if mgr.colo is not None else None))

    def tick():
        leading = mgr.tick()
        if leading:
            print(
                f"koord-manager: round={mgr.reconcile_rounds} "
                f"changes={mgr.last_changes}", file=sys.stderr)

    run_ticks(tick, args.interval, args.max_ticks, "koord-manager")
    if obs_server is not None:
        obs_server.shutdown()
    mgr.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
