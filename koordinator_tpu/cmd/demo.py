"""All-in-one colocation demo: every binary's component on one store.

Runs the cross-component control loop (SURVEY 3.3) in a single process —
the `kind`-cluster analog for trying the framework without a cluster:

  koordlet metrics -> NodeMetric CR -> koord-manager batch allocatable ->
  admission webhook BE mutation -> batched scheduler placement ->
  koordlet cgroup enforcement (hermetic FakeFS node)

Usage: python -m koordinator_tpu.cmd.demo
"""

from __future__ import annotations

import argparse
import sys


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="koord-demo")
    ap.add_argument("--be-pods", type=int, default=3,
                    help="best-effort spark pods to co-locate")
    args = ap.parse_args(argv)

    from koordinator_tpu.api.objects import (
        LABEL_POD_QOS,
        ClusterColocationProfile,
        Node,
        ObjectMeta,
        Pod,
        PodSpec,
    )
    from koordinator_tpu.api.qos import QoSClass
    from koordinator_tpu.api.resources import ResourceList, ResourceName
    from koordinator_tpu.client.store import (
        KIND_COLOCATION_PROFILE,
        KIND_NODE,
        KIND_POD,
        ObjectStore,
    )
    from koordinator_tpu.descheduler import Descheduler
    from koordinator_tpu.koordlet.daemon import Daemon
    from koordinator_tpu.koordlet.util import system as sysutil
    from koordinator_tpu.koordlet.util.system import FakeFS
    from koordinator_tpu.manager import Manager
    from koordinator_tpu.scheduler.cycle import Scheduler

    GIB = 1024**3
    NOW = 1_000_000.0
    store = ObjectStore()
    fs = FakeFS(use_cgroup_v2=True)
    try:
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name="node-0", namespace=""),
            allocatable=ResourceList.of(cpu=16_000, memory=64 * GIB,
                                        pods=110)))
        fs.set_proc("stat", "cpu  1000 0 1000 8000 0 0 0 0 0 0\n")
        fs.set_proc(
            "meminfo",
            "MemTotal: %d kB\nMemFree: %d kB\nMemAvailable: %d kB\n"
            % (64 * GIB // 1024, 48 * GIB // 1024, 56 * GIB // 1024))
        ls = Pod(
            meta=ObjectMeta(name="web", uid="web",
                            labels={LABEL_POD_QOS: "LS"}),
            spec=PodSpec(node_name="node-0",
                         requests=ResourceList.of(cpu=4000, memory=8 * GIB),
                         limits=ResourceList.of(cpu=4000, memory=8 * GIB)),
            phase="Running")
        store.add(KIND_POD, ls)
        ls_rel = fs.config.pod_relative_path("", "web")
        fs.set_cgroup(ls_rel, sysutil.CPU_STAT, "usage_usec 10000000\n")
        fs.set_cgroup(ls_rel, sysutil.MEMORY_USAGE, str(4 * GIB))
        log("[cluster] 1 node (16 cores / 64Gi), 1 LS pod (web, 4 cores)")

        daemon = Daemon(store, "node-0", fs.config,
                        report_interval_seconds=0)
        daemon.run_once(now=NOW)
        fs.set_proc("stat", "cpu  2000 0 2000 12000 0 0 0 0 0 0\n")
        fs.set_cgroup(ls_rel, sysutil.CPU_STAT, "usage_usec 30000000\n")
        daemon.run_once(now=NOW + 10)
        log("[koordlet] metrics collected; NodeMetric CR reported")

        manager = Manager(store, identity="demo-manager")
        manager.tick(now=NOW + 11)
        node = store.get(KIND_NODE, "/node-0")
        log(f"[koord-manager] batch allocatable: "
            f"cpu={node.allocatable[ResourceName.BATCH_CPU]}m "
            f"memory={node.allocatable[ResourceName.BATCH_MEMORY] // GIB}Gi")

        store.add(KIND_COLOCATION_PROFILE, ClusterColocationProfile(
            meta=ObjectMeta(name="spark"), selector={"app": "spark"},
            qos_class=QoSClass.BE, priority_class_name="koord-batch",
            scheduler_name="koord-scheduler"))
        for i in range(args.be_pods):
            store.add(KIND_POD, Pod(
                meta=ObjectMeta(name=f"spark-{i}", uid=f"spark-{i}",
                                labels={"app": "spark"},
                                creation_timestamp=NOW + 11 + i),
                spec=PodSpec(
                    requests=ResourceList.of(cpu=2000, memory=4 * GIB),
                    limits=ResourceList.of(cpu=2000, memory=4 * GIB))))
        sample = store.get(KIND_POD, "default/spark-0")
        log(f"[webhook] spark pods mutated to BE: requests "
            f"batch-cpu={sample.spec.requests[ResourceName.BATCH_CPU]}m")

        result = Scheduler(store).run_cycle(now=NOW + 15)
        log(f"[koord-scheduler] bound {len(result.bound)} BE pods "
            f"({result.kernel_seconds * 1000:.1f}ms kernel): "
            f"{[b.pod_key for b in result.bound]}")

        for b in result.bound:
            pod = store.get(KIND_POD, b.pod_key)
            pod.phase = "Running"
            store.update(KIND_POD, pod)
            rel = fs.config.pod_relative_path(
                sysutil.QOS_BESTEFFORT, pod.meta.name)
            fs.set_cgroup(rel, sysutil.CPU_STAT, "usage_usec 0\n")
            fs.set_cgroup(rel, sysutil.MEMORY_USAGE, "0")
        daemon.run_once(now=NOW + 20)
        first = fs.config.pod_relative_path(sysutil.QOS_BESTEFFORT, "spark-0")
        log(f"[koordlet] BE cgroups enforced: cfs_quota="
            f"{daemon.executor.read(first, sysutil.CPU_CFS_QUOTA)} "
            f"bvt={daemon.executor.read(first, sysutil.CPU_BVT_WARP_NS)}")

        summary = Descheduler(store).run_once(now=NOW + 30)
        log(f"[koord-descheduler] rebalance pass: {summary}")
        log("demo complete: the full colocation loop ran end to end")
        return 0
    finally:
        fs.cleanup()


if __name__ == "__main__":
    raise SystemExit(main())
