"""Binary entrypoints — the analog of the reference's cmd/ tree.

The reference ships five cooperating binaries (koord-scheduler,
koord-descheduler, koord-manager, koordlet, koord-runtime-proxy) plus, in
this rebuild, the TPU scheduling sidecar. Each module here is a thin CLI
over the corresponding library runner, launchable as

    python -m koordinator_tpu.cmd.koord_scheduler --synth 50x200
    python -m koordinator_tpu.cmd.koord_sidecar --listen unix:///tmp/s.sock
    python -m koordinator_tpu.cmd.demo

Cluster state comes from `--state cluster.json` (the minimal schema below)
or `--synth NxP` (N nodes, P pods via the synthetic generator). The store
is in-process — the reference's cross-binary bus is the Kubernetes API
server, whose analog here is `client.store.ObjectStore`; the all-in-one
`demo` runs every component against one shared store the way the kind
cluster wires the reference's binaries to one apiserver.

state JSON schema (all fields optional):
  {"nodes": [{"name", "cpu": milli, "memory": bytes, "pods": n,
              "labels": {..}}],
   "pods":  [{"name", "namespace", "cpu": milli, "memory": bytes,
              "priority": n, "labels": {..}, "node": bound-node-or-absent}],
   "node_metrics": [{"node", "cpu": milli, "memory": bytes}]}
"""

from __future__ import annotations

import json
import signal
import sys
import threading
import time
from typing import Callable, Optional


def add_cluster_flags(ap) -> None:
    ap.add_argument("--state", help="cluster state JSON file (see schema)")
    ap.add_argument(
        "--synth", metavar="NxP",
        help="synthetic cluster: N nodes x P pending pods")


def add_loop_flags(ap, default_interval: float) -> None:
    ap.add_argument("--interval", type=float, default=default_interval,
                    help="seconds between loop ticks")
    ap.add_argument("--max-ticks", type=int, default=0,
                    help="stop after this many ticks (0 = run until signal)")


def serve_obs(port: int, metrics_registry, name: str, tracer=None,
              health_provider=None, explain_provider=None, flight=None,
              timeline=None, slo=None):
    """`--obs-port` wiring shared by the binaries: serve /metrics (and
    /traces when a tracer is given, plus the koordexplain surfaces when
    providers are given, plus the koordwatch /debug/timeline and
    /debug/slo bundles) via obs.server.ObsServer and announce the bound
    address. Returns the live server, or None when port is 0; the caller
    shuts it down after its tick loop ends."""
    if not port:
        return None
    from koordinator_tpu.obs.server import ObsServer

    server, _thread = ObsServer(
        metrics_registry, tracer, health_provider=health_provider,
        explain_provider=explain_provider, flight=flight,
        timeline=timeline, slo=slo).serve(port)
    routes = "/metrics + /traces" if tracer is not None else "/metrics"
    if explain_provider is not None:
        routes += " + /explain"
    if flight is not None:
        routes += " + /debug/flightrecorder"
    if timeline is not None:
        routes += " + /debug/timeline"
    if slo is not None:
        routes += " + /debug/slo"
    print(f"{name}: {routes} on 127.0.0.1:{server.server_address[1]}",
          file=sys.stderr)
    return server


def parse_feature_gates(gate_obj, spec: Optional[str]) -> None:
    """--feature-gates Gate1=true,Gate2=false (component main.go flag)."""
    if not spec:
        return
    values = {}
    for item in spec.split(","):
        if not item:
            continue
        name, _, raw = item.partition("=")
        values[name.strip()] = raw.strip().lower() in ("1", "true", "yes", "")
    gate_obj.set_from_map(values)


def build_store(args):
    """ObjectStore from --state / --synth (empty store otherwise)."""
    from koordinator_tpu.api.objects import (
        Node,
        NodeMetric,
        NodeMetricInfo,
        ObjectMeta,
        Pod,
        PodSpec,
    )
    from koordinator_tpu.api.resources import ResourceList
    from koordinator_tpu.client.store import (
        KIND_NODE,
        KIND_NODE_METRIC,
        KIND_POD,
        ObjectStore,
    )

    store = ObjectStore()
    if getattr(args, "synth", None):
        n_s, p_s = args.synth.lower().split("x")
        _populate_synth(store, int(n_s), int(p_s))
        return store
    if not getattr(args, "state", None):
        return store
    with open(args.state) as f:
        spec = json.load(f)
    now = time.time()
    for n in spec.get("nodes", []):
        node = Node(
            meta=ObjectMeta(name=n["name"], namespace="",
                            labels=dict(n.get("labels", {}))),
            allocatable=ResourceList.of(
                cpu=int(n.get("cpu", 4000)),
                memory=int(n.get("memory", 16 * 1024**3)),
                pods=int(n.get("pods", 110))),
        )
        store.add(KIND_NODE, node)
    for p in spec.get("pods", []):
        ns = p.get("namespace", "default")
        pod = Pod(
            meta=ObjectMeta(name=p["name"],
                            namespace=ns,
                            # uid must be cluster-unique: same-named pods in
                            # two namespaces would otherwise share cgroup
                            # paths and informer entries
                            uid=f"{ns}/{p['name']}",
                            labels=dict(p.get("labels", {})),
                            creation_timestamp=now),
            spec=PodSpec(
                priority=p.get("priority"),
                requests=ResourceList.of(
                    cpu=int(p.get("cpu", 1000)),
                    memory=int(p.get("memory", 1024**3)))),
        )
        if p.get("node"):
            pod.spec.node_name = p["node"]
            pod.phase = "Running"
        store.add(KIND_POD, pod)
    for m in spec.get("node_metrics", []):
        store.add(KIND_NODE_METRIC, NodeMetric(
            meta=ObjectMeta(name=m["node"], namespace=""),
            update_time=now,
            node_metric=NodeMetricInfo(node_usage=ResourceList.of(
                cpu=int(m.get("cpu", 0)), memory=int(m.get("memory", 0)))),
        ))
    return store


def _populate_synth(store, num_nodes: int, num_pods: int) -> None:
    from koordinator_tpu.client.store import (
        KIND_NODE,
        KIND_NODE_METRIC,
        KIND_POD,
    )
    from koordinator_tpu.testing import synth_full_cluster

    _cluster, state = synth_full_cluster(num_nodes, num_pods, seed=0)
    for node in state.nodes:
        store.add(KIND_NODE, node)
    for nm in state.node_metrics.values():
        store.add(KIND_NODE_METRIC, nm)
    for pod in state.pods_by_key.values():
        store.add(KIND_POD, pod)
    for pod in state.pending_pods:
        if store.get(KIND_POD, pod.meta.key) is None:
            store.add(KIND_POD, pod)


def run_ticks(tick: Callable[[], object], interval: float, max_ticks: int,
              name: str) -> int:
    """The shared serve loop: tick, sleep, repeat; SIGTERM/SIGINT stop it
    cleanly (the reference binaries' context-cancellation analog)."""
    stop = threading.Event()

    def _handler(_sig, _frame):
        print(f"{name}: signal received, stopping", file=sys.stderr)
        stop.set()

    previous = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            previous[sig] = signal.signal(sig, _handler)
        except ValueError:  # non-main thread (tests)
            pass
    ticks = 0
    try:
        while not stop.is_set():
            tick()
            ticks += 1
            if max_ticks and ticks >= max_ticks:
                break
            stop.wait(interval)
    finally:
        # restore: an embedding process (pytest, the demo) must keep its
        # own Ctrl-C behavior once the loop is done
        for sig, handler in previous.items():
            signal.signal(sig, handler)
    return ticks
