"""Test fixtures: synthetic clusters and trace replay.

Analog of the reference's load-bearing fixtures (SURVEY.md section 4): fake
clientset (client.ObjectStore is already in-process), scheduler-framework harness,
and workload generators standing in for the `examples/spark-jobs` colocation traces.
"""

from koordinator_tpu.testing.synth import (  # noqa: F401
    SynthCluster,
    synth_cluster,
    synth_full_cluster,
)
