"""Synthetic cluster/workload generator.

Produces pods/nodes/NodeMetrics exercising every LoadAware branch: prod/batch/mid
priority bands, BE/LS QoS, DaemonSet pods, zero-request pods (estimator defaults),
limits>requests (100% scaling), expired and missing NodeMetrics, aggregated
percentile usage, custom per-node threshold annotations, and pod metrics for the
assign-cache adjustment paths. Deterministic via seed. Stands in for the
reference's `examples/spark-jobs` trace in benchmarks (BASELINE.md configs).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

from koordinator_tpu.api.objects import (
    LABEL_POD_QOS,
    Node,
    NodeMetric,
    NodeMetricInfo,
    ObjectMeta,
    Pod,
    PodMetricInfo,
    PodSpec,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.ops.loadaware import ANNOTATION_CUSTOM_USAGE_THRESHOLDS

GIB = 1024**3
MIB = 1024**2


@dataclass
class SynthCluster:
    nodes: List[Node]
    pods: List[Pod]                      # pending pods (unassigned)
    node_metrics: Dict[str, NodeMetric]  # by node name
    pods_by_key: Dict[str, Pod]          # running pods visible to listers
    assigned: Dict[str, List[Tuple[Pod, float]]] = field(default_factory=dict)
    now: float = 1_000_000.0


def synth_cluster(
    num_nodes: int,
    num_pods: int,
    seed: int = 0,
    now: float = 1_000_000.0,
    expired_fraction: float = 0.05,
    missing_metric_fraction: float = 0.05,
    custom_threshold_fraction: float = 0.1,
    aggregated_fraction: float = 0.3,
    with_pod_metrics: bool = True,
) -> SynthCluster:
    rng = random.Random(seed)
    nodes: List[Node] = []
    node_metrics: Dict[str, NodeMetric] = {}
    pods_by_key: Dict[str, Pod] = {}

    for i in range(num_nodes):
        cores = rng.choice([16, 32, 64, 96])
        mem_gib = cores * rng.choice([2, 4, 8])
        meta = ObjectMeta(name=f"node-{i}", namespace="")
        if rng.random() < custom_threshold_fraction:
            meta.annotations[ANNOTATION_CUSTOM_USAGE_THRESHOLDS] = (
                '{"usageThresholds": {"cpu": %d, "memory": %d}}'
                % (rng.choice([50, 70, 90]), rng.choice([80, 90]))
            )
        node = Node(
            meta=meta,
            allocatable=ResourceList.of(
                cpu=cores * 1000, memory=mem_gib * GIB, pods=110
            ),
        )
        nodes.append(node)

        if rng.random() < missing_metric_fraction:
            continue
        update_time = now - rng.uniform(1, 60)
        if rng.random() < expired_fraction:
            update_time = now - rng.uniform(200, 400)  # beyond 180s default expiry
        usage_cpu = int(cores * 1000 * rng.uniform(0.05, 0.9))
        usage_mem = int(mem_gib * GIB * rng.uniform(0.05, 0.9))
        info = NodeMetricInfo(
            node_usage=ResourceList.of(cpu=usage_cpu, memory=usage_mem)
        )
        if rng.random() < aggregated_fraction:
            info.aggregated_node_usages = {
                300: {
                    "p95": ResourceList.of(
                        cpu=int(usage_cpu * 1.1), memory=int(usage_mem * 1.05)
                    )
                },
                1800: {
                    "p95": ResourceList.of(
                        cpu=int(usage_cpu * 1.2), memory=int(usage_mem * 1.1)
                    ),
                    "p50": ResourceList.of(
                        cpu=int(usage_cpu * 0.8), memory=int(usage_mem * 0.9)
                    ),
                },
            }
        nm = NodeMetric(
            meta=ObjectMeta(name=f"node-{i}", namespace=""),
            update_time=update_time,
            node_metric=info,
        )
        if with_pod_metrics:
            for j in range(rng.randint(0, 4)):
                pod_name = f"running-{i}-{j}"
                prio = rng.choice([9500, 9500, 5500, 7500])
                running = Pod(
                    meta=ObjectMeta(name=pod_name, namespace="default"),
                    spec=PodSpec(node_name=f"node-{i}", priority=prio),
                    phase="Running",
                )
                pods_by_key[running.meta.key] = running
                nm.pods_metric.append(
                    PodMetricInfo(
                        namespace="default",
                        name=pod_name,
                        pod_usage=ResourceList.of(
                            cpu=rng.randint(50, 2000),
                            memory=rng.randint(64, 4096) * MIB,
                        ),
                    )
                )
        node_metrics[f"node-{i}"] = nm

    pods: List[Pod] = []
    for i in range(num_pods):
        kind = rng.random()
        if kind < 0.35:  # prod LS
            prio, qos = 9500, "LS"
        elif kind < 0.45:  # mid
            prio, qos = 7500, "LS"
        elif kind < 0.85:  # batch BE
            prio, qos = 5500, "BE"
        else:  # free BE
            prio, qos = 3500, "BE"
        cpu = rng.choice([0, 100, 250, 500, 1000, 2000, 4000])
        mem = rng.choice([0, 128, 256, 512, 1024, 4096, 8192]) * MIB
        limits = ResourceList()
        if rng.random() < 0.2 and cpu:
            limits = ResourceList.of(cpu=cpu * 2, memory=mem * 2 if mem else 0)
        meta = ObjectMeta(
            name=f"pod-{i}",
            namespace="default",
            labels={LABEL_POD_QOS: qos},
            creation_timestamp=now - rng.uniform(0, 3600),
        )
        if rng.random() < 0.05:
            meta.owner_kind = "DaemonSet"
            meta.owner_name = "ds"
        pods.append(
            Pod(
                meta=meta,
                spec=PodSpec(
                    priority=prio,
                    requests=ResourceList.of(cpu=cpu, memory=mem),
                    limits=limits,
                ),
            )
        )

    return SynthCluster(
        nodes=nodes,
        pods=pods,
        node_metrics=node_metrics,
        pods_by_key=pods_by_key,
        now=now,
    )


def synth_full_cluster(
    num_nodes: int,
    num_pods: int,
    seed: int = 0,
    num_quotas: int = 8,
    num_gangs: int = 12,
    topology_fraction: float = 0.7,
    lsr_fraction: float = 0.15,
    taint_fraction: float = 0.0,
    **kwargs,
):
    """SynthCluster + ClusterState exercising the full chain: NUMA topologies,
    3-level quota tree, PodGroups, LSR cpuset pods (BASELINE configs 2-4)."""
    import json

    import numpy as np

    from koordinator_tpu.api.objects import (
        LABEL_POD_GROUP,
        LABEL_QUOTA_NAME,
        LABEL_QUOTA_PARENT,
        LABEL_QUOTA_SHARED_WEIGHT,
        ElasticQuota,
        NodeResourceTopology,
        NUMAZone,
        PodGroup,
    )
    from koordinator_tpu.scheduler.cpu_topology import CPUAllocationState, CPUTopology
    from koordinator_tpu.scheduler.snapshot import ClusterState

    rng = random.Random(seed + 1000)
    cluster = synth_cluster(num_nodes, num_pods, seed=seed, **kwargs)

    topologies = {}
    cpu_states = {}
    for node in cluster.nodes:
        if rng.random() >= topology_fraction:
            continue
        cores_total = node.allocatable[("cpu")] // 1000 or 16
        cores_per_numa = max(2, int(cores_total) // (2 * 2))  # 2 numa, 2 threads
        topo = CPUTopology.build(1, 2, cores_per_numa, 2)
        mem = node.allocatable[("memory")]
        cr = NodeResourceTopology(
            meta=type(node.meta)(name=node.meta.name),
            cpus=topo.cpus,
            zones=[
                NUMAZone(
                    numa_id=k,
                    allocatable=ResourceList.of(
                        cpu=(len(topo.cpus) // 2) * 1000, memory=mem // 2
                    ),
                )
                for k in range(2)
            ],
            kubelet_cpu_manager_policy=rng.choice(
                ["none", "best-effort", "restricted", "single-numa-node"]
            ),
        )
        topologies[node.meta.name] = cr
        cpu_states[node.meta.name] = CPUAllocationState(topo)

    # 3-level quota tree: root -> team-i -> job-j
    quotas = []
    leaf_names = []
    if num_quotas > 0:
        quotas.append(
            ElasticQuota(
                meta=type(cluster.nodes[0].meta)(name="root"),
                min=ResourceList.of(cpu=0),
                max=ResourceList.of(cpu=10**9, memory=2**60),
            )
        )
        teams = max(1, num_quotas // 4)
        for t in range(teams):
            meta = type(cluster.nodes[0].meta)(name=f"team-{t}")
            meta.labels[LABEL_QUOTA_PARENT] = "root"
            meta.annotations[LABEL_QUOTA_SHARED_WEIGHT] = json.dumps(
                {"cpu": str(rng.randint(1, 5)), "memory": f"{rng.randint(64, 512)}Gi"}
            )
            quotas.append(
                ElasticQuota(
                    meta=meta,
                    min=ResourceList.of(
                        cpu=rng.randint(8, 64) * 1000,
                        memory=rng.randint(16, 128) * GIB,
                    ),
                    max=ResourceList.of(cpu=10**9, memory=2**60),
                )
            )
        for q in range(num_quotas - teams - 1):
            meta = type(cluster.nodes[0].meta)(name=f"job-{q}")
            meta.labels[LABEL_QUOTA_PARENT] = f"team-{q % teams}"
            quotas.append(
                ElasticQuota(
                    meta=meta,
                    min=ResourceList.of(
                        cpu=rng.randint(0, 32) * 1000,
                        memory=rng.randint(0, 64) * GIB,
                    ),
                    max=ResourceList.of(
                        cpu=rng.randint(64, 256) * 1000,
                        memory=rng.randint(256, 1024) * GIB,
                    ),
                )
            )
            leaf_names.append(meta.name)

    pod_groups = [
        PodGroup(
            meta=type(cluster.nodes[0].meta)(name=f"gang-{g}"),
            min_member=rng.randint(2, 6),
        )
        for g in range(num_gangs)
    ]

    # decorate pods: quotas, gangs, LSR cpuset pods
    from koordinator_tpu.api.objects import LABEL_POD_QOS

    for pod in cluster.pods:
        r = rng.random()
        if leaf_names and r < 0.5:
            pod.meta.labels[LABEL_QUOTA_NAME] = rng.choice(leaf_names)
        if pod_groups and rng.random() < 0.3:
            pod.meta.labels[LABEL_POD_GROUP] = rng.choice(pod_groups).meta.name
        if rng.random() < lsr_fraction:
            pod.meta.labels[LABEL_POD_QOS] = "LSR"
            cores = rng.choice([2, 4])
            pod.spec.requests = ResourceList.of(
                cpu=cores * 1000, memory=pod.spec.requests[("memory")] or GIB
            )
            pod.spec.limits = ResourceList()

    # taints: a fraction of nodes dedicated to a pool; a fraction of pods
    # tolerate each pool (TaintToleration coverage)
    if taint_fraction > 0:
        pools = ["infra", "gpu"]
        for node in cluster.nodes:
            if rng.random() < taint_fraction:
                node.taints = [("dedicated", rng.choice(pools))]
        for pod in cluster.pods:
            r = rng.random()
            if r < 0.2:
                pod.spec.tolerations = [("dedicated", rng.choice(pools))]
            elif r < 0.25:
                pod.spec.tolerations = [("dedicated", "")]  # wildcard

    state = ClusterState(
        nodes=cluster.nodes,
        pending_pods=cluster.pods,
        node_metrics=cluster.node_metrics,
        pods_by_key=cluster.pods_by_key,
        assigned=cluster.assigned,
        topologies=topologies,
        cpu_states=cpu_states,
        quotas=quotas,
        pod_groups=pod_groups,
        now=cluster.now,
    )
    return cluster, state
