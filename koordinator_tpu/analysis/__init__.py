"""koordlint: AST-based static analysis for this repo's hazard classes.

Run as ``python -m koordinator_tpu.analysis <paths...>``; see README
"Static analysis". Public API:

  * all_rules() — the registry (name -> Rule)
  * analyze_source(src, path) — lint one source text (tests/fixtures)
  * analyze_paths(paths, baseline) — lint files/trees minus the baseline
  * load_baseline / write_baseline — the grandfathered-finding file
"""

from koordinator_tpu.analysis.core import (  # noqa: F401
    Finding,
    ModuleContext,
    Rule,
    all_rules,
    analyze_paths,
    analyze_source,
    load_baseline,
    register,
    suppressed_lines,
    write_baseline,
)

__all__ = [
    "Finding", "ModuleContext", "Rule", "all_rules", "analyze_paths",
    "analyze_source", "load_baseline", "register", "suppressed_lines",
    "write_baseline",
]
