"""koordrace guard analysis: the whole-program lock-discipline layer.

koordlint's per-function rules cannot see that a field guarded by
``_lock`` at nine call sites is touched bare at a tenth, or that two
code paths acquire the same two locks in opposite orders. This module
adds the missing program-level view, in three stages that stay inside
the plain-AST contract (no imports of the analyzed code, no jax):

1. **Fact extraction** (:func:`collect_module_facts`) — one pass per
   module producing a picklable :class:`ModuleFacts`: lock definitions
   (``self._lock = threading.Lock()`` and module-level ``_x = Lock()``),
   field touches (every ``self.<attr>`` read/write with the lexically
   held lock set), lock acquisitions, calls made while holding locks,
   guard annotations, and the declared canonical lock order. Picklable
   facts are what lets the CLI fan file parsing out to a worker pool
   while the whole-program passes still run once, in the parent.

2. **Guard-map inference** (:func:`build_guard_map`) — which attribute
   is protected by which lock. An explicit annotation on the
   field-defining assignment wins::

       self._ring = []  # koordlint: guarded-by(_lock)

   (``guarded-by(none)`` pins a field as deliberately unguarded).
   Unannotated fields are inferred by majority vote over their non-init
   touches: a field is guarded by lock L when at least
   ``_INFER_MIN_LOCKED`` touches happen under L and they form a strict
   majority of all touches. ``__init__``/``_init*`` bodies are excluded
   (construction happens-before any thread spawn, same stance as
   rules/concurrency.py).

3. **Lock graph + discipline checks** — consumed by
   ``analysis/rules/race.py``: per-touch guard violations, the
   inter-procedural acquisition graph (lexical nesting plus calls into
   methods whose transitive bodies acquire), cycle detection, the
   declared canonical order (``CANONICAL_LOCK_ORDER`` in
   ``obs/lockorder.py``, parsed from source — never imported), blocking
   calls under a lock, and the orphan-lock self-check behind
   ``python -m koordinator_tpu.analysis --check-locks``.

Scope: the modules that genuinely face more than one thread — the
guard scan gates on :data:`GUARD_SCAN_RE` so import-time registries
elsewhere stay out of the map.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

# modules whose fields enter the guard map: the scheduler cycle driver
# and its caches, the observability rings, the balance/colo consumers,
# the event-sourced store, the sim harness (it spawns the racecheck
# threads), and the metrics registry the canonical lock order ends at
GUARD_SCAN_RE = re.compile(
    r"((^|/)(scheduler|obs|balance|colo|sim)/"
    r"|(^|/)client/store\.py"
    r"|(^|/)koordlet/metrics\.py)")

# the single documented home of the declared lock order (satellite 2);
# the analyzer PARSES this module, it never imports it
CANONICAL_ORDER_MODULE_RE = re.compile(r"(^|/)obs/lockorder\.py$")
CANONICAL_ORDER_NAME = "CANONICAL_LOCK_ORDER"

GUARD_MAP_SCHEMA = "koordlint-guard-map"
GUARD_MAP_VERSION = 1

_GUARDED_BY_RE = re.compile(
    r"#\s*koordlint:\s*guarded-by\(\s*([A-Za-z_][A-Za-z0-9_]*|none)\s*\)")

# on a lock DEFINITION line: the lock protects a named external
# resource (a file, a subprocess, ...) rather than instance attributes,
# so the orphan-lock self-check must not flag it
_GUARDS_RE = re.compile(
    r"#\s*koordlint:\s*guards\(\s*([A-Za-z0-9_.\-/]+)\s*\)")

_LOCK_CTORS = {"Lock", "RLock"}

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)

MODULE_OWNER = "<module>"

# a field needs at least this many locked touches, forming a strict
# majority, before the guard is inferred (annotation overrides)
_INFER_MIN_LOCKED = 2


def is_guard_scanned_path(path: str) -> bool:
    return GUARD_SCAN_RE.search(path) is not None


# ---------------------------------------------------------------------------
# picklable per-module facts
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class LockDef:
    """A lock-valued attribute: ``self.attr = threading.Lock()`` inside
    `owner`, or a module-level ``attr = Lock()`` (owner == MODULE_OWNER).
    ``alias_of`` names the module-level lock when the assignment re-binds
    one (``self._lock = _index_lock``) instead of constructing."""

    owner: str
    attr: str
    line: int
    kind: str                      # "Lock" | "RLock"
    alias_of: str = ""
    resource: str = ""             # from ``# koordlint: guards(x)``


@dataclasses.dataclass(frozen=True)
class Annotation:
    owner: str
    field: str
    guard: str                     # lock attr name, or "none"
    line: int


@dataclasses.dataclass(frozen=True)
class FieldTouch:
    owner: str
    field: str
    method: str
    line: int
    write: bool
    held: Tuple[str, ...]          # lock names lexically held at the touch
    in_init: bool


@dataclasses.dataclass(frozen=True)
class AcquireEvent:
    owner: str
    method: str
    lock: str
    line: int
    held: Tuple[str, ...]          # locks already held when acquiring


@dataclasses.dataclass(frozen=True)
class CallEvent:
    """A call made while inside a method: ``target`` is the dotted
    source head ("self._helper", "self.timeline.close", "time.sleep")."""

    owner: str
    method: str
    target: str
    line: int
    held: Tuple[str, ...]


@dataclasses.dataclass
class ModuleFacts:
    path: str
    locks: List[LockDef] = dataclasses.field(default_factory=list)
    annotations: List[Annotation] = dataclasses.field(default_factory=list)
    touches: List[FieldTouch] = dataclasses.field(default_factory=list)
    acquires: List[AcquireEvent] = dataclasses.field(default_factory=list)
    calls: List[CallEvent] = dataclasses.field(default_factory=list)
    # owner -> attr -> class name, from `self.x = ClassName(...)`
    attr_types: Dict[str, Dict[str, str]] = dataclasses.field(
        default_factory=dict)
    # class name -> method names (distinguishes self.m() calls from
    # self.field reads of stored callables)
    class_methods: Dict[str, Set[str]] = dataclasses.field(
        default_factory=dict)
    canonical_order: Tuple[str, ...] = ()


# ---------------------------------------------------------------------------
# extraction
# ---------------------------------------------------------------------------

def _call_name_tail(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _dotted(expr: ast.AST) -> str:
    """'self.timeline.close' for the matching Attribute/Name chain,
    '' when the expression is not a plain dotted path."""
    parts: List[str] = []
    cur = expr
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return ""


class _OwnerScanner:
    """Extracts facts for one owner: a class body or the module level."""

    def __init__(self, facts: ModuleFacts, owner: str,
                 lock_names: Set[str], module_locks: Set[str],
                 method_names: Set[str],
                 annotated_lines: Dict[int, str]) -> None:
        self.facts = facts
        self.owner = owner
        self.lock_names = lock_names          # this owner's lock attrs
        self.module_locks = module_locks      # module-level lock names
        self.method_names = method_names
        self.annotated_lines = annotated_lines

    def _held_at(self, parents: Dict[ast.AST, ast.AST],
                 node: ast.AST, fn: ast.AST) -> Tuple[str, ...]:
        """Lock names lexically held at `node` inside `fn`: every
        enclosing ``with self.<lock>`` / ``with <module-lock>``."""
        held: List[str] = []
        cur: Optional[ast.AST] = parents.get(node)
        while cur is not None and cur is not fn:
            if isinstance(cur, (ast.With, ast.AsyncWith)):
                for item in cur.items:
                    name = self._lock_expr_name(item.context_expr)
                    if name and name not in held:
                        held.append(name)
            cur = parents.get(cur)
        return tuple(held)

    def _lock_expr_name(self, expr: ast.AST) -> str:
        if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name) and expr.value.id == "self":
            if expr.attr in self.lock_names:
                return expr.attr
        elif isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return expr.id
        return ""

    def scan_function(self, fn: ast.AST, parents: Dict[ast.AST, ast.AST],
                      in_init: bool) -> None:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                self._scan_call(node, fn, parents)
            elif (isinstance(node, ast.Attribute)
                  and isinstance(node.value, ast.Name)
                  and node.value.id == "self"
                  and self.owner != MODULE_OWNER):
                self._scan_self_attr(node, fn, parents, in_init)

    def _scan_call(self, node: ast.Call, fn: ast.AST,
                   parents: Dict[ast.AST, ast.AST]) -> None:
        target = _dotted(node.func)
        if not target:
            return
        held = self._held_at(parents, node, fn)
        self.facts.calls.append(CallEvent(
            owner=self.owner, method=fn.name, target=target,
            line=node.lineno, held=held))

    def _record_acquires(self, fn: ast.AST,
                         parents: Dict[ast.AST, ast.AST]) -> None:
        for node in ast.walk(fn):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                name = self._lock_expr_name(item.context_expr)
                if not name:
                    continue
                held = self._held_at(parents, node, fn)
                self.facts.acquires.append(AcquireEvent(
                    owner=self.owner, method=fn.name, lock=name,
                    line=node.lineno,
                    held=tuple(h for h in held if h != name)))

    def _scan_self_attr(self, node: ast.Attribute, fn: ast.AST,
                        parents: Dict[ast.AST, ast.AST],
                        in_init: bool) -> None:
        attr = node.attr
        if attr in self.lock_names:
            return  # the lock itself is not a guarded field
        parent = parents.get(node)
        # `self.method(...)` — a call on a defined method, not a field
        if (isinstance(parent, ast.Call) and parent.func is node
                and attr in self.method_names):
            return
        write = isinstance(node.ctx, (ast.Store, ast.Del))
        if not write and parent is not None:
            # `self.x[...] = v`, `self.x += v`, `self.x.append(v)` all
            # mutate through a Load of the attribute
            from koordinator_tpu.analysis.rules.concurrency import (
                _mutation_target,
            )
            write = _mutation_target(parent) is node
        held = self._held_at(parents, node, fn)
        self.facts.touches.append(FieldTouch(
            owner=self.owner, field=attr, method=fn.name,
            line=node.lineno, write=write, held=held, in_init=in_init))
        if write and in_init:
            guard = self.annotated_lines.get(node.lineno)
            if guard:
                self.facts.annotations.append(Annotation(
                    owner=self.owner, field=attr, guard=guard,
                    line=node.lineno))



def _annotation_lines(source: str) -> Dict[int, str]:
    """line -> guard name for every ``# koordlint: guarded-by(x)``.
    A pragma alone on a line applies to the next line (mirrors the
    suppression comment convention in core.py)."""
    out: Dict[int, str] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _GUARDED_BY_RE.search(line)
        if not m:
            continue
        target = i + 1 if line.strip().startswith("#") else i
        out[target] = m.group(1)
    return out


def _resource_lines(source: str) -> Dict[int, str]:
    """line -> resource name for every ``# koordlint: guards(x)``; same
    next-line convention as :func:`_annotation_lines`."""
    out: Dict[int, str] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _GUARDS_RE.search(line)
        if not m:
            continue
        target = i + 1 if line.strip().startswith("#") else i
        out[target] = m.group(1)
    return out


def _module_level_locks(tree: ast.Module,
                        resources: Dict[int, str]) -> List[LockDef]:
    out: List[LockDef] = []
    for stmt in tree.body:
        if not isinstance(stmt, ast.Assign) or not isinstance(
                stmt.value, ast.Call):
            continue
        tail = _call_name_tail(stmt.value)
        if tail not in _LOCK_CTORS:
            continue
        for t in stmt.targets:
            if isinstance(t, ast.Name):
                out.append(LockDef(
                    owner=MODULE_OWNER, attr=t.id, line=stmt.lineno,
                    kind=tail,
                    resource=resources.get(stmt.lineno, "")))
    return out


def _module_level_fields(tree: ast.Module,
                         lock_names: Set[str]) -> Set[str]:
    """Module-level names that look like shared mutable state: assigned
    at top level (to anything) and re-bound or mutated from function
    scope. Import-time constants never re-touched stay out."""
    assigned: Set[str] = set()
    for stmt in tree.body:
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, ast.AnnAssign):
            targets = [stmt.target]
        for t in targets:
            if isinstance(t, ast.Name) and t.id not in lock_names:
                assigned.add(t.id)
    return assigned


def _class_lock_defs(cls: ast.ClassDef, module_locks: Set[str],
                     resources: Dict[int, str]) -> List[LockDef]:
    out: List[LockDef] = []
    for fn in cls.body:
        if not isinstance(fn, _FUNC_DEFS):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                if isinstance(node.value, ast.Call):
                    tail = _call_name_tail(node.value)
                    if tail in _LOCK_CTORS:
                        out.append(LockDef(
                            owner=cls.name, attr=t.attr,
                            line=node.lineno, kind=tail,
                            resource=resources.get(node.lineno, "")))
                elif (isinstance(node.value, ast.Name)
                      and node.value.id in module_locks):
                    out.append(LockDef(
                        owner=cls.name, attr=t.attr, line=node.lineno,
                        kind="Lock", alias_of=node.value.id,
                        resource=resources.get(node.lineno, "")))
    return out


def _parse_canonical_order(tree: ast.Module) -> Tuple[str, ...]:
    for stmt in tree.body:
        targets: List[ast.AST] = []
        value: Optional[ast.AST] = None
        if isinstance(stmt, ast.Assign):
            targets, value = list(stmt.targets), stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        if not any(isinstance(t, ast.Name)
                   and t.id == CANONICAL_ORDER_NAME for t in targets):
            continue
        if isinstance(value, (ast.Tuple, ast.List)):
            out = []
            for e in value.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out.append(e.value)
            return tuple(out)
    return ()


def collect_module_facts(path: str, source: str,
                         tree: ast.Module) -> Optional[ModuleFacts]:
    """One module's concurrency facts, or None when the path is outside
    the guard scan set (and declares no canonical order)."""
    path = path.replace("\\", "/")
    canonical = (_parse_canonical_order(tree)
                 if CANONICAL_ORDER_MODULE_RE.search(path) else ())
    if not is_guard_scanned_path(path) and not canonical:
        return None
    facts = ModuleFacts(path=path, canonical_order=canonical)
    annotated = _annotation_lines(source)
    resources = _resource_lines(source)
    mod_locks = _module_level_locks(tree, resources)
    facts.locks.extend(mod_locks)
    module_lock_names = {d.attr for d in mod_locks}

    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node

    classes = [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]
    for cls in classes:
        methods = {f.name for f in cls.body if isinstance(f, _FUNC_DEFS)}
        facts.class_methods[cls.name] = methods
        lock_defs = _class_lock_defs(cls, module_lock_names, resources)
        facts.locks.extend(lock_defs)
        lock_names = {d.attr for d in lock_defs}
        scanner = _OwnerScanner(facts, cls.name, lock_names,
                                module_lock_names, methods, annotated)
        attr_types: Dict[str, str] = {}
        for fn in cls.body:
            if not isinstance(fn, _FUNC_DEFS):
                continue
            in_init = fn.name == "__init__" or fn.name.startswith("_init")
            scanner.scan_function(fn, parents, in_init)
            scanner._record_acquires(fn, parents)
            if in_init:
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Assign) or not isinstance(
                            node.value, ast.Call):
                        continue
                    tail = _call_name_tail(node.value)
                    if not tail or tail in _LOCK_CTORS:
                        continue
                    for t in node.targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"
                                and tail[:1].isupper()):
                            attr_types[t.attr] = tail
        if attr_types:
            facts.attr_types[cls.name] = attr_types

    # module-level functions: track touches of module-level shared names
    module_fields = _module_level_fields(tree, module_lock_names)
    _collect_module_scope(facts, tree, parents, module_fields,
                          module_lock_names, annotated)
    return facts


def _collect_module_scope(facts: ModuleFacts, tree: ast.Module,
                          parents: Dict[ast.AST, ast.AST],
                          module_fields: Set[str],
                          module_lock_names: Set[str],
                          annotated: Dict[int, str]) -> None:
    """Touches/acquires/calls of module-scope state inside module-level
    (and method) function bodies. Methods count too: warmup.py mutates
    module-level ladder state from WarmupRunner methods."""
    from koordinator_tpu.analysis.rules.concurrency import (
        _locally_bound_names,
        _mutation_target,
    )
    scanner = _OwnerScanner(facts, MODULE_OWNER, set(),
                            module_lock_names, set(), annotated)
    # annotation on the module-level defining assignment
    for stmt in tree.body:
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, ast.AnnAssign):
            targets = [stmt.target]
        guard = annotated.get(stmt.lineno) if targets else None
        if guard:
            for t in targets:
                if isinstance(t, ast.Name) and t.id in module_fields:
                    facts.annotations.append(Annotation(
                        owner=MODULE_OWNER, field=t.id, guard=guard,
                        line=stmt.lineno))
    for fn in ast.walk(tree):
        if not isinstance(fn, _FUNC_DEFS):
            continue
        in_method = _enclosing_class(fn, parents) is not None
        local = _locally_bound_names(fn)
        declared_global: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                declared_global.update(node.names)
        if not in_method:
            # methods already contributed acquires/calls under their
            # class owner; re-recording them here would double-count
            # graph edges under a bogus module owner
            scanner._record_acquires(fn, parents)
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    scanner._scan_call(node, fn, parents)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Name):
                continue
            name = node.id
            if name not in module_fields or name in module_lock_names:
                continue
            if name in local and name not in declared_global:
                continue
            write = isinstance(node.ctx, (ast.Store, ast.Del))
            parent = parents.get(node)
            if not write and parent is not None:
                write = _mutation_target(parent) is node
            if not write and isinstance(parent, ast.Attribute):
                gp = parents.get(parent)
                if gp is not None and _mutation_target(gp) is parent:
                    # `_cache.pop(...)` resolves _mutation_target to the
                    # Attribute `_cache.pop`'s value — already handled —
                    # but `_live_threads.remove(t)` shapes land here
                    write = True
            held = scanner._held_at(parents, node, fn)
            facts.touches.append(FieldTouch(
                owner=MODULE_OWNER, field=name, method=fn.name,
                line=node.lineno, write=write, held=held,
                in_init=False))


# ---------------------------------------------------------------------------
# guard map
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class GuardedField:
    owner: str
    field: str
    guard: Optional[str]           # None == explicitly/effectively bare
    source: str                    # "annotation" | "inferred" | "unguarded"
    reads: int = 0
    writes: int = 0
    bare: int = 0                  # non-init touches without the guard


@dataclasses.dataclass
class ModuleGuards:
    path: str
    locks: List[LockDef]
    fields: List[GuardedField]


class GuardMap:
    """The program-wide guard map plus the raw facts it was built from."""

    def __init__(self, facts_list: List[ModuleFacts]) -> None:
        self.facts_list = facts_list
        self.modules: Dict[str, ModuleGuards] = {}
        # (path, owner, field) -> GuardedField
        self.fields: Dict[Tuple[str, str, str], GuardedField] = {}
        self.canonical_order: Tuple[str, ...] = ()
        for facts in facts_list:
            if facts.canonical_order:
                self.canonical_order = facts.canonical_order
            self._build_module(facts)

    def _build_module(self, facts: ModuleFacts) -> None:
        lock_by_owner: Dict[str, Set[str]] = {}
        for d in facts.locks:
            lock_by_owner.setdefault(d.owner, set()).add(d.attr)
        ann: Dict[Tuple[str, str], Annotation] = {
            (a.owner, a.field): a for a in facts.annotations}
        by_field: Dict[Tuple[str, str], List[FieldTouch]] = {}
        for t in facts.touches:
            by_field.setdefault((t.owner, t.field), []).append(t)
        out: List[GuardedField] = []
        for (owner, field), touches in sorted(by_field.items()):
            own_locks = lock_by_owner.get(owner, set()) | \
                lock_by_owner.get(MODULE_OWNER, set())
            live = [t for t in touches if not t.in_init]
            a = ann.get((owner, field))
            if a is not None:
                guard = None if a.guard == "none" else a.guard
                source = "annotation"
            else:
                guard, source = self._infer(live, own_locks)
            gf = GuardedField(owner=owner, field=field, guard=guard,
                             source=source)
            for t in live:
                if t.write:
                    gf.writes += 1
                else:
                    gf.reads += 1
                if guard is not None and guard not in t.held:
                    gf.bare += 1
            out.append(gf)
            self.fields[(facts.path, owner, field)] = gf
        self.modules[facts.path] = ModuleGuards(
            path=facts.path, locks=sorted(
                facts.locks, key=lambda d: (d.owner, d.attr)),
            fields=out)

    @staticmethod
    def _infer(touches: List[FieldTouch],
               own_locks: Set[str]) -> Tuple[Optional[str], str]:
        if not touches:
            return None, "unguarded"
        counts: Dict[str, int] = {}
        for t in touches:
            for h in t.held:
                if h in own_locks:
                    counts[h] = counts.get(h, 0) + 1
        if not counts:
            return None, "unguarded"
        guard, n = max(counts.items(), key=lambda kv: (kv[1], kv[0]))
        if n >= _INFER_MIN_LOCKED and n > len(touches) - n:
            return guard, "inferred"
        return None, "unguarded"

    # -- queries ------------------------------------------------------

    def guard_for(self, path: str, owner: str,
                  field: str) -> Optional[GuardedField]:
        return self.fields.get((path, owner, field))

    def guarded_touchpoints(self) -> Iterator[Tuple[ModuleFacts,
                                                    FieldTouch,
                                                    GuardedField]]:
        """Every non-init touch of a guarded field, with its guard."""
        for facts in self.facts_list:
            for t in facts.touches:
                if t.in_init:
                    continue
                gf = self.fields.get((facts.path, t.owner, t.field))
                if gf is not None and gf.guard is not None:
                    yield facts, t, gf

    def orphan_locks(self) -> List[Tuple[str, LockDef]]:
        """(path, lock) pairs for locks that guard nothing: neither
        annotated as a guard nor inferred for any field. Every shipped
        lock must earn its place in the map (or the map is lying about
        coverage)."""
        guards_in_use: Dict[str, Set[str]] = {}
        for (path, owner, _field), gf in self.fields.items():
            if gf.guard is not None:
                guards_in_use.setdefault(path, set()).add(gf.guard)
        out = []
        for facts in self.facts_list:
            used = guards_in_use.get(facts.path, set())
            aliased = {d.alias_of for d in facts.locks if d.alias_of}
            resourced = {d.attr for d in facts.locks if d.resource}
            for d in facts.locks:
                if d.resource:  # declares an external resource
                    continue
                if d.attr in used or d.attr in aliased:
                    continue
                # an alias points at a module lock: the alias earns its
                # keep when the aliased name guards something (and vice
                # versa — `self._lock = _index_lock` counts for both),
                # including a declared external resource
                if d.alias_of and (d.alias_of in used
                                   or d.alias_of in resourced):
                    continue
                out.append((facts.path, d))
        return sorted(out, key=lambda pd: (pd[0], pd[1].owner, pd[1].attr))

    def to_dict(self) -> Dict[str, object]:
        modules = []
        for path in sorted(self.modules):
            mg = self.modules[path]
            owners: Dict[str, Dict[str, object]] = {}
            for d in mg.locks:
                o = owners.setdefault(d.owner, {"owner": d.owner,
                                                "locks": [], "fields": []})
                o["locks"].append({
                    "attr": d.attr, "line": d.line, "kind": d.kind,
                    **({"alias_of": d.alias_of} if d.alias_of else {}),
                    **({"resource": d.resource} if d.resource else {})})
            for gf in mg.fields:
                o = owners.setdefault(gf.owner, {"owner": gf.owner,
                                                 "locks": [], "fields": []})
                o["fields"].append({
                    "name": gf.field, "guard": gf.guard,
                    "source": gf.source, "reads": gf.reads,
                    "writes": gf.writes, "bare": gf.bare})
            modules.append({
                "path": path,
                "owners": [owners[k] for k in sorted(owners)],
            })
        return {
            "schema": GUARD_MAP_SCHEMA,
            "version": GUARD_MAP_VERSION,
            "canonical_lock_order": list(self.canonical_order),
            "modules": modules,
        }


# ---------------------------------------------------------------------------
# inter-procedural lock graph
# ---------------------------------------------------------------------------

def _enclosing_class(fn: ast.AST,
                     parents: Dict[ast.AST, ast.AST]) -> Optional[str]:
    cur = parents.get(fn)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur.name
        cur = parents.get(cur)
    return None


def lock_key(path: str, owner: str, attr: str) -> str:
    if owner == MODULE_OWNER:
        return f"{path}::{attr}"
    return f"{owner}.{attr}"


def _resolve_lock_key(facts: ModuleFacts, owner: str, name: str) -> str:
    """A method saying ``with _ladder_lock:`` holds the MODULE's lock,
    not a class attribute — key it where the lock is defined."""
    for d in facts.locks:
        if d.owner == owner and d.attr == name:
            return lock_key(facts.path, owner, name)
    for d in facts.locks:
        if d.owner == MODULE_OWNER and d.attr == name:
            return lock_key(facts.path, MODULE_OWNER, name)
    return lock_key(facts.path, owner, name)


@dataclasses.dataclass(frozen=True)
class LockEdge:
    src: str                       # lock key held
    dst: str                       # lock key acquired under it
    path: str
    line: int
    via: str                       # "nested-with" | "call:<target>"


class LockGraph:
    """Acquisition-order edges: src held while dst acquired. Lexical
    nesting contributes direct edges; calls into methods of known
    classes contribute one level of inter-procedural edges through the
    callee's transitive (intra-class) acquisition closure."""

    def __init__(self, guard_map: GuardMap) -> None:
        self.guard_map = guard_map
        self.edges: List[LockEdge] = []
        self._build()

    def _build(self) -> None:
        facts_list = self.guard_map.facts_list
        # class name -> method -> resolved lock keys its body (or a
        # same-class callee) acquires
        closures: Dict[str, Dict[str, Set[str]]] = {}
        for facts in facts_list:
            for cls, methods in facts.class_methods.items():
                closures[cls] = _method_lock_closure(facts, cls, methods)
        for facts in facts_list:
            for ev in facts.acquires:
                dst = _resolve_lock_key(facts, ev.owner, ev.lock)
                for h in ev.held:
                    self.edges.append(LockEdge(
                        src=_resolve_lock_key(facts, ev.owner, h), dst=dst,
                        path=facts.path, line=ev.line, via="nested-with"))
            for call in facts.calls:
                if not call.held:
                    continue
                callee = _resolve_call(facts, call)
                if callee is None:
                    continue
                cls, method = callee
                locks = closures.get(cls, {}).get(method, set())
                for dst in sorted(locks):
                    for h in call.held:
                        src = _resolve_lock_key(facts, call.owner, h)
                        if src != dst:
                            self.edges.append(LockEdge(
                                src=src, dst=dst, path=facts.path,
                                line=call.line,
                                via=f"call:{call.target}"))

    def cycles(self) -> List[Tuple[Tuple[str, ...], LockEdge]]:
        """Distinct lock-order cycles as (canonical key tuple, witness
        edge). Reported once per cycle, anchored at its first edge."""
        adj: Dict[str, List[LockEdge]] = {}
        for e in self.edges:
            adj.setdefault(e.src, []).append(e)
        seen: Set[Tuple[str, ...]] = set()
        out: List[Tuple[Tuple[str, ...], LockEdge]] = []
        for start in sorted(adj):
            stack: List[Tuple[str, Tuple[str, ...], Optional[LockEdge]]] = [
                (start, (start,), None)]
            while stack:
                node, trail, first = stack.pop()
                for e in adj.get(node, ()):  # noqa: B023
                    w = first or e
                    if e.dst == start:
                        cyc = trail
                        # canonical rotation so A->B->A and B->A->B dedup
                        i = cyc.index(min(cyc))
                        key = cyc[i:] + cyc[:i]
                        if key not in seen:
                            seen.add(key)
                            out.append((key, w))
                    elif e.dst not in trail and len(trail) < 6:
                        stack.append((e.dst, trail + (e.dst,), w))
        return out

    def declared_violations(self) -> List[LockEdge]:
        order = self.guard_map.canonical_order
        if not order:
            return []
        idx = {name: i for i, name in enumerate(order)}
        out = []
        for e in self.edges:
            si, di = idx.get(e.src), idx.get(e.dst)
            if si is not None and di is not None and si > di:
                out.append(e)
        return out


def _method_lock_closure(facts: ModuleFacts, cls: str,
                         methods: Set[str]) -> Dict[str, Set[str]]:
    """method -> resolved lock keys acquired in its body or
    (transitively) in same-class methods it calls."""
    direct: Dict[str, Set[str]] = {m: set() for m in methods}
    calls: Dict[str, Set[str]] = {m: set() for m in methods}
    for ev in facts.acquires:
        if ev.owner == cls and ev.method in direct:
            direct[ev.method].add(_resolve_lock_key(facts, cls, ev.lock))
    for call in facts.calls:
        if call.owner != cls or call.method not in calls:
            continue
        parts = call.target.split(".")
        if len(parts) == 2 and parts[0] == "self" and parts[1] in methods:
            calls[call.method].add(parts[1])
    closure = {m: set(v) for m, v in direct.items()}
    changed = True
    while changed:
        changed = False
        for m in methods:
            for callee in calls[m]:
                before = len(closure[m])
                closure[m] |= closure[callee]
                changed = changed or len(closure[m]) != before
    return closure


def _resolve_call(facts: ModuleFacts,
                  call: CallEvent) -> Optional[Tuple[str, str]]:
    """'self.timeline.close' -> ('DeviceTimeline', 'close') via the
    owner's attr-type map; 'self._helper' -> (owner, '_helper')."""
    parts = call.target.split(".")
    if parts[0] != "self":
        return None
    if len(parts) == 2:
        if parts[1] in facts.class_methods.get(call.owner, set()):
            return call.owner, parts[1]
        return None
    if len(parts) == 3:
        cls = facts.attr_types.get(call.owner, {}).get(parts[1])
        if cls is not None:
            return cls, parts[2]
    return None


# ---------------------------------------------------------------------------
# inter-procedural held-lock propagation (for the field rule)
# ---------------------------------------------------------------------------

def caller_held_locks(facts: ModuleFacts) -> Dict[Tuple[str, str],
                                                  Set[str]]:
    """(owner, method) -> locks provably held by EVERY caller. Only
    private methods (leading underscore) qualify — a public method is
    an external entry point and can always be entered bare. Standard
    narrowing dataflow: start private methods with >=1 same-class call
    site at the full lock set, intersect over call sites to fixpoint."""
    module_locks = {d.attr for d in facts.locks
                    if d.owner == MODULE_OWNER}
    all_locks: Dict[str, Set[str]] = {}
    for d in facts.locks:
        all_locks.setdefault(d.owner, set(module_locks)).add(d.attr)
    sites: Dict[Tuple[str, str], List[CallEvent]] = {}
    for call in facts.calls:
        parts = call.target.split(".")
        if (len(parts) == 2 and parts[0] == "self"
                and parts[1] in facts.class_methods.get(call.owner, set())):
            sites.setdefault((call.owner, parts[1]), []).append(call)
    held: Dict[Tuple[str, str], Set[str]] = {}
    for key, call_list in sites.items():
        owner, method = key
        if method.startswith("_") and not method.startswith("__"):
            held[key] = set(all_locks.get(owner, set()))
    for _ in range(len(held) + 1):
        changed = False
        for key, call_list in sites.items():
            if key not in held:
                continue
            acc: Optional[Set[str]] = None
            for c in call_list:
                h = set(c.held) | held.get((c.owner, c.method), set())
                acc = h if acc is None else (acc & h)
            acc = acc or set()
            if acc != held[key]:
                held[key] = acc
                changed = True
        if not changed:
            break
    return held


# ---------------------------------------------------------------------------
# program-level entry points (used by the CLI and racecheck)
# ---------------------------------------------------------------------------

def collect_facts_for_paths(paths: Iterable[str]) -> List[ModuleFacts]:
    """Parse + extract facts for every python file under `paths` (no
    rules, no baseline — the guard-map dump path)."""
    from koordinator_tpu.analysis.core import (
        _canonical_path,
        iter_python_files,
    )
    out: List[ModuleFacts] = []
    for f in iter_python_files(paths):
        try:
            source = f.read_text()
            tree = ast.parse(source)
        except (OSError, SyntaxError):
            continue
        facts = collect_module_facts(_canonical_path(f), source, tree)
        if facts is not None:
            out.append(facts)
    return out


def build_guard_map(facts_list: List[ModuleFacts]) -> GuardMap:
    return GuardMap(facts_list)
