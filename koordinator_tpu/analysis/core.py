"""koordlint core: findings, rule registry, suppressions, baseline, engine.

The analyzer is a plain-AST framework (no imports of the analyzed code, no
jax dependency): each rule receives a parsed module plus shared lexical
context and yields findings. The engine layers three noise controls on top:

  * inline suppressions — ``# koordlint: disable=<rule>[,<rule>...]`` on the
    offending line (or alone on the line above) silences those rules there;
    ``disable=all`` silences every rule for that line;
  * a JSON baseline of grandfathered findings (keyed path:rule:line) so a
    new rule can land strict for NEW code while existing debt is burned
    down incrementally (ROADMAP tracks the burn-down);
  * per-rule severity (error/warning) — informational only; the exit-code
    contract fails on ANY non-baselined, non-suppressed finding so CI
    stays binary.

Rules register themselves via the ``@register`` decorator at import time of
``koordinator_tpu.analysis.rules``; the registry is the single source the
CLI, the tests, and the README rule catalog all enumerate.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
import tokenize
from io import StringIO
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set

SEVERITIES = ("error", "warning")

# generated protobuf modules are not hand-maintained; linting them is noise
_SKIP_FILE_RE = re.compile(r"(_pb2\.py|_pb2_grpc\.py)$")

_SUPPRESS_RE = re.compile(
    r"#\s*koordlint:\s*disable=([A-Za-z0-9_,\-\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    severity: str
    path: str          # as given to the engine (posix-normalized)
    line: int          # 1-based
    message: str

    @property
    def key(self) -> str:
        """Stable identity used by baseline matching (message excluded so
        rewording a diagnostic does not churn the baseline)."""
        return f"{self.path}:{self.rule}:{self.line}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.severity}: "
                f"[{self.rule}] {self.message}")


class ModuleContext:
    """Everything a rule may consult about one source file."""

    def __init__(self, path: str, source: str, tree: ast.Module) -> None:
        self.path = path.replace("\\", "/")
        self.source = source
        self.tree = tree
        self.lines = source.splitlines()
        self._traced: Optional[Set[ast.AST]] = None
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None

    # ---- shared lexical helpers ------------------------------------
    def parent_map(self) -> Dict[ast.AST, ast.AST]:
        if self._parents is None:
            self._parents = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    self._parents[child] = node
        return self._parents

    def traced_functions(self) -> Set[ast.AST]:
        """Function defs reachable from a jax tracing entry point — see
        rules/jaxtrace.py for the discovery algorithm."""
        if self._traced is None:
            from koordinator_tpu.analysis.rules.jaxtrace import (
                find_traced_functions,
            )
            self._traced = find_traced_functions(self.tree)
        return self._traced


class Rule:
    """Base class; subclasses set name/severity/description and implement
    check()."""

    name: str = ""
    severity: str = "error"
    description: str = ""

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: ModuleContext, node: ast.AST,
                message: str) -> Finding:
        return Finding(rule=self.name, severity=self.severity,
                       path=ctx.path, line=getattr(node, "lineno", 1),
                       message=message)


class ProgramContext:
    """Everything a whole-program rule may consult: the concurrency
    facts of every scanned module (see analysis/guards.py) plus the
    guard map and lock graph derived from them, built lazily and shared
    across the program rules."""

    def __init__(self, facts_list) -> None:
        self.facts_list = list(facts_list)
        self._guard_map = None
        self._lock_graph = None
        self._caller_held: Dict[str, Dict] = {}

    @property
    def guard_map(self):
        if self._guard_map is None:
            from koordinator_tpu.analysis.guards import build_guard_map
            self._guard_map = build_guard_map(self.facts_list)
        return self._guard_map

    @property
    def lock_graph(self):
        if self._lock_graph is None:
            from koordinator_tpu.analysis.guards import LockGraph
            self._lock_graph = LockGraph(self.guard_map)
        return self._lock_graph

    def caller_held(self, path: str) -> Dict:
        """(owner, method) -> locks provably held by every caller, for
        the module at `path` (see guards.caller_held_locks)."""
        if path not in self._caller_held:
            from koordinator_tpu.analysis.guards import caller_held_locks
            facts = next((f for f in self.facts_list if f.path == path),
                         None)
            self._caller_held[path] = (
                caller_held_locks(facts) if facts is not None else {})
        return self._caller_held[path]


class ProgramRule(Rule):
    """A rule that needs the whole program: it sees every module's facts
    at once instead of one ModuleContext. Per-module check() is a no-op;
    the engine calls check_program() after the per-file pass."""

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        return iter(())

    def check_program(self, program: ProgramContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding_at(self, path: str, line: int, message: str) -> Finding:
        return Finding(rule=self.name, severity=self.severity,
                       path=path, line=line, message=message)


_REGISTRY: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and add to the global registry."""
    rule = cls()
    if not rule.name:
        raise ValueError(f"rule {cls.__name__} has no name")
    if rule.severity not in SEVERITIES:
        raise ValueError(f"rule {rule.name}: bad severity {rule.severity!r}")
    if rule.name in _REGISTRY:
        raise ValueError(f"duplicate rule name {rule.name!r}")
    _REGISTRY[rule.name] = rule
    return cls


def all_rules() -> Dict[str, Rule]:
    """name -> rule, importing the rule modules on first use."""
    import koordinator_tpu.analysis.rules  # noqa: F401  (registration)

    return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

def suppressed_lines(source: str) -> Dict[int, Set[str]]:
    """line number -> set of rule names disabled there ('all' wildcard).

    A ``# koordlint: disable=...`` trailing a statement applies to its own
    line; a comment ALONE on a line applies to the next line (so long
    statements can carry the pragma above themselves).
    """
    out: Dict[int, Set[str]] = {}
    try:
        tokens = list(tokenize.generate_tokens(StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        tokens = []
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        line = tok.start[0]
        standalone = tok.string.strip() == tok.line.strip()
        target = line + 1 if standalone else line
        out.setdefault(target, set()).update(rules)
    return out


def is_suppressed(finding: Finding,
                  suppress: Dict[int, Set[str]]) -> bool:
    rules = suppress.get(finding.line)
    if not rules:
        return False
    return "all" in rules or finding.rule in rules


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

BASELINE_VERSION = 1


def load_baseline(path) -> Set[str]:
    """Baseline file -> set of finding keys. Missing file == empty."""
    p = Path(path)
    if not p.exists():
        return set()
    data = json.loads(p.read_text())
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {p}: unsupported version {data.get('version')!r}")
    return {
        f"{e['path']}:{e['rule']}:{e['line']}" for e in data["findings"]
    }


def write_baseline(path, findings: Sequence[Finding]) -> None:
    entries = [
        {"path": f.path, "rule": f.rule, "line": f.line,
         "message": f.message}
        for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule))
    ]
    Path(path).write_text(json.dumps(
        {"version": BASELINE_VERSION, "findings": entries}, indent=2)
        + "\n")


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------

def iter_python_files(paths: Iterable[str]) -> Iterator[Path]:
    for raw in paths:
        p = Path(raw)
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not _SKIP_FILE_RE.search(f.name):
                    yield f
        elif p.suffix == ".py":
            yield p


def _module_findings(ctx: ModuleContext, suppress: Dict[int, Set[str]],
                     rules: Dict[str, Rule]) -> List[Finding]:
    out: List[Finding] = []
    seen: Set[Finding] = set()
    for rule in rules.values():
        for f in rule.check(ctx):
            # dedup identical reports (e.g. a jit call inside two nested
            # loops is one site, not two findings)
            if not is_suppressed(f, suppress) and f not in seen:
                seen.add(f)
                out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def program_findings(facts_list,
                     suppress_by_path: Dict[str, Dict[int, Set[str]]],
                     rules: Optional[Dict[str, Rule]] = None
                     ) -> List[Finding]:
    """Run the whole-program rules over the collected facts; the
    per-file suppression maps apply at whatever line a program finding
    lands on."""
    rules = all_rules() if rules is None else rules
    program = ProgramContext([f for f in facts_list if f is not None])
    out: List[Finding] = []
    seen: Set[Finding] = set()
    for rule in rules.values():
        if not isinstance(rule, ProgramRule):
            continue
        for f in rule.check_program(program):
            sup = suppress_by_path.get(f.path, {})
            if not is_suppressed(f, sup) and f not in seen:
                seen.add(f)
                out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def analyze_source(source: str, path: str = "<memory>",
                   rules: Optional[Dict[str, Rule]] = None) -> List[Finding]:
    """Run the rule set over one source text (suppressions applied,
    baseline NOT applied — that is the caller's policy layer). The
    whole-program rules run over this single module, so a snippet test
    exercises them without a directory walk."""
    rules = all_rules() if rules is None else rules
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding(rule="parse-error", severity="error",
                        path=path.replace("\\", "/"),
                        line=e.lineno or 1,
                        message=f"could not parse: {e.msg}")]
    ctx = ModuleContext(path, source, tree)
    suppress = suppressed_lines(source)
    out = _module_findings(ctx, suppress, rules)
    from koordinator_tpu.analysis.guards import collect_module_facts

    facts = collect_module_facts(ctx.path, source, tree)
    out.extend(program_findings([facts], {ctx.path: suppress}, rules))
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def _canonical_path(p: Path) -> str:
    """CWD-relative posix path when the file lives under CWD, else the
    path as given. Baseline keys embed this string, so `koordinator_tpu/
    foo.py`, `./koordinator_tpu/foo.py` and the absolute spelling must
    all produce the same key or grandfathered findings resurface."""
    try:
        rel = p.resolve().relative_to(Path.cwd())
        return rel.as_posix()
    except (ValueError, OSError):
        return p.as_posix()


def _scan_file(path_str: str):
    """Worker unit: per-file findings + concurrency facts + suppression
    map. Top-level (and returning only picklable dataclasses/dicts) so a
    ProcessPoolExecutor can run it; the whole-program passes consume the
    facts back in the parent."""
    from koordinator_tpu.analysis.guards import collect_module_facts

    rules = all_rules()
    p = Path(path_str)
    source = p.read_text()
    cpath = _canonical_path(p)
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        f = Finding(rule="parse-error", severity="error", path=cpath,
                    line=e.lineno or 1, message=f"could not parse: {e.msg}")
        return [f], None, {}
    ctx = ModuleContext(cpath, source, tree)
    suppress = suppressed_lines(source)
    findings = _module_findings(ctx, suppress, rules)
    facts = collect_module_facts(cpath, source, tree)
    return findings, facts, suppress


def default_jobs(n_files: int) -> int:
    """Worker count for the per-file pass: KOORDLINT_JOBS wins, else
    scale with the machine but keep small scans serial (pool startup
    costs more than it saves under ~2 dozen files)."""
    import os

    env = os.environ.get("KOORDLINT_JOBS", "")
    if env.strip():
        try:
            return max(1, int(env))
        except ValueError:
            pass
    if n_files < 24:
        return 1
    return max(1, min(8, os.cpu_count() or 1))


def analyze_paths(paths: Iterable[str],
                  baseline: Optional[Set[str]] = None,
                  jobs: Optional[int] = None) -> List[Finding]:
    """Analyze files/directories; findings present in `baseline` are
    dropped. The per-file pass fans out to `jobs` worker processes
    (default: scale with the machine; finding order is identical to the
    serial run — workers return results in input order and the
    whole-program passes always run once, in the parent)."""
    all_rules()  # fail fast on registration errors before forking
    baseline = baseline or set()
    files = [str(f) for f in iter_python_files(paths)]
    jobs = default_jobs(len(files)) if jobs is None else max(1, jobs)
    results = None
    if jobs > 1 and len(files) > 1:
        try:
            from concurrent.futures import ProcessPoolExecutor
            from concurrent.futures.process import BrokenProcessPool

            with ProcessPoolExecutor(max_workers=jobs) as pool:
                results = list(pool.map(_scan_file, files, chunksize=4))
        except (OSError, ImportError, BrokenProcessPool):
            # sandboxes without working process pools fall back to the
            # serial path rather than failing the lint run
            results = None
    if results is None:
        results = [_scan_file(f) for f in files]

    out: List[Finding] = []
    facts_list = []
    suppress_by_path: Dict[str, Dict[int, Set[str]]] = {}
    for (findings, facts, suppress) in results:
        for finding in findings:
            if finding.key not in baseline:
                out.append(finding)
        if facts is not None:
            facts_list.append(facts)
            suppress_by_path[facts.path] = suppress
    for finding in program_findings(facts_list, suppress_by_path):
        if finding.key not in baseline:
            out.append(finding)
    return out
