"""koordlint core: findings, rule registry, suppressions, baseline, engine.

The analyzer is a plain-AST framework (no imports of the analyzed code, no
jax dependency): each rule receives a parsed module plus shared lexical
context and yields findings. The engine layers three noise controls on top:

  * inline suppressions — ``# koordlint: disable=<rule>[,<rule>...]`` on the
    offending line (or alone on the line above) silences those rules there;
    ``disable=all`` silences every rule for that line;
  * a JSON baseline of grandfathered findings (keyed path:rule:line) so a
    new rule can land strict for NEW code while existing debt is burned
    down incrementally (ROADMAP tracks the burn-down);
  * per-rule severity (error/warning) — informational only; the exit-code
    contract fails on ANY non-baselined, non-suppressed finding so CI
    stays binary.

Rules register themselves via the ``@register`` decorator at import time of
``koordinator_tpu.analysis.rules``; the registry is the single source the
CLI, the tests, and the README rule catalog all enumerate.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
import tokenize
from io import StringIO
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set

SEVERITIES = ("error", "warning")

# generated protobuf modules are not hand-maintained; linting them is noise
_SKIP_FILE_RE = re.compile(r"(_pb2\.py|_pb2_grpc\.py)$")

_SUPPRESS_RE = re.compile(
    r"#\s*koordlint:\s*disable=([A-Za-z0-9_,\-\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    severity: str
    path: str          # as given to the engine (posix-normalized)
    line: int          # 1-based
    message: str

    @property
    def key(self) -> str:
        """Stable identity used by baseline matching (message excluded so
        rewording a diagnostic does not churn the baseline)."""
        return f"{self.path}:{self.rule}:{self.line}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.severity}: "
                f"[{self.rule}] {self.message}")


class ModuleContext:
    """Everything a rule may consult about one source file."""

    def __init__(self, path: str, source: str, tree: ast.Module) -> None:
        self.path = path.replace("\\", "/")
        self.source = source
        self.tree = tree
        self.lines = source.splitlines()
        self._traced: Optional[Set[ast.AST]] = None
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None

    # ---- shared lexical helpers ------------------------------------
    def parent_map(self) -> Dict[ast.AST, ast.AST]:
        if self._parents is None:
            self._parents = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    self._parents[child] = node
        return self._parents

    def traced_functions(self) -> Set[ast.AST]:
        """Function defs reachable from a jax tracing entry point — see
        rules/jaxtrace.py for the discovery algorithm."""
        if self._traced is None:
            from koordinator_tpu.analysis.rules.jaxtrace import (
                find_traced_functions,
            )
            self._traced = find_traced_functions(self.tree)
        return self._traced


class Rule:
    """Base class; subclasses set name/severity/description and implement
    check()."""

    name: str = ""
    severity: str = "error"
    description: str = ""

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: ModuleContext, node: ast.AST,
                message: str) -> Finding:
        return Finding(rule=self.name, severity=self.severity,
                       path=ctx.path, line=getattr(node, "lineno", 1),
                       message=message)


_REGISTRY: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and add to the global registry."""
    rule = cls()
    if not rule.name:
        raise ValueError(f"rule {cls.__name__} has no name")
    if rule.severity not in SEVERITIES:
        raise ValueError(f"rule {rule.name}: bad severity {rule.severity!r}")
    if rule.name in _REGISTRY:
        raise ValueError(f"duplicate rule name {rule.name!r}")
    _REGISTRY[rule.name] = rule
    return cls


def all_rules() -> Dict[str, Rule]:
    """name -> rule, importing the rule modules on first use."""
    import koordinator_tpu.analysis.rules  # noqa: F401  (registration)

    return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

def suppressed_lines(source: str) -> Dict[int, Set[str]]:
    """line number -> set of rule names disabled there ('all' wildcard).

    A ``# koordlint: disable=...`` trailing a statement applies to its own
    line; a comment ALONE on a line applies to the next line (so long
    statements can carry the pragma above themselves).
    """
    out: Dict[int, Set[str]] = {}
    try:
        tokens = list(tokenize.generate_tokens(StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        tokens = []
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        line = tok.start[0]
        standalone = tok.string.strip() == tok.line.strip()
        target = line + 1 if standalone else line
        out.setdefault(target, set()).update(rules)
    return out


def is_suppressed(finding: Finding,
                  suppress: Dict[int, Set[str]]) -> bool:
    rules = suppress.get(finding.line)
    if not rules:
        return False
    return "all" in rules or finding.rule in rules


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

BASELINE_VERSION = 1


def load_baseline(path) -> Set[str]:
    """Baseline file -> set of finding keys. Missing file == empty."""
    p = Path(path)
    if not p.exists():
        return set()
    data = json.loads(p.read_text())
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {p}: unsupported version {data.get('version')!r}")
    return {
        f"{e['path']}:{e['rule']}:{e['line']}" for e in data["findings"]
    }


def write_baseline(path, findings: Sequence[Finding]) -> None:
    entries = [
        {"path": f.path, "rule": f.rule, "line": f.line,
         "message": f.message}
        for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule))
    ]
    Path(path).write_text(json.dumps(
        {"version": BASELINE_VERSION, "findings": entries}, indent=2)
        + "\n")


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------

def iter_python_files(paths: Iterable[str]) -> Iterator[Path]:
    for raw in paths:
        p = Path(raw)
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not _SKIP_FILE_RE.search(f.name):
                    yield f
        elif p.suffix == ".py":
            yield p


def analyze_source(source: str, path: str = "<memory>",
                   rules: Optional[Dict[str, Rule]] = None) -> List[Finding]:
    """Run the rule set over one source text (suppressions applied,
    baseline NOT applied — that is the caller's policy layer)."""
    rules = all_rules() if rules is None else rules
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding(rule="parse-error", severity="error",
                        path=path.replace("\\", "/"),
                        line=e.lineno or 1,
                        message=f"could not parse: {e.msg}")]
    ctx = ModuleContext(path, source, tree)
    suppress = suppressed_lines(source)
    out: List[Finding] = []
    seen: Set[Finding] = set()
    for rule in rules.values():
        for f in rule.check(ctx):
            # dedup identical reports (e.g. a jit call inside two nested
            # loops is one site, not two findings)
            if not is_suppressed(f, suppress) and f not in seen:
                seen.add(f)
                out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def _canonical_path(p: Path) -> str:
    """CWD-relative posix path when the file lives under CWD, else the
    path as given. Baseline keys embed this string, so `koordinator_tpu/
    foo.py`, `./koordinator_tpu/foo.py` and the absolute spelling must
    all produce the same key or grandfathered findings resurface."""
    try:
        rel = p.resolve().relative_to(Path.cwd())
        return rel.as_posix()
    except (ValueError, OSError):
        return p.as_posix()


def analyze_paths(paths: Iterable[str],
                  baseline: Optional[Set[str]] = None) -> List[Finding]:
    """Analyze files/directories; findings present in `baseline` are
    dropped."""
    rules = all_rules()
    baseline = baseline or set()
    out: List[Finding] = []
    for f in iter_python_files(paths):
        source = f.read_text()
        for finding in analyze_source(source, _canonical_path(f), rules):
            if finding.key not in baseline:
                out.append(finding)
    return out
