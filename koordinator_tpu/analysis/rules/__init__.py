"""Rule modules; importing this package registers every rule."""

from koordinator_tpu.analysis.rules import (  # noqa: F401
    balance,
    colo,
    concurrency,
    jaxtrace,
    loops,
    pipeline,
    wire,
)
