"""Rule modules; importing this package registers every rule."""

from koordinator_tpu.analysis.rules import (  # noqa: F401
    balance,
    colo,
    compilecache,
    concurrency,
    demotion,
    jaxtrace,
    loops,
    pipeline,
    race,
    wire,
)
