"""Steady-state compile hygiene (koordlint rule 20, the AST half).

The warm-up ladder (scheduler/warmup.py) promises that after startup a
scheduler never compiles in the hot path: every step build must route
through the KEYED step-cache chokepoints (``_get_step`` /
``_get_fused_step`` / ``_get_chain_step`` and the rebalance/colo
``_get_step`` twins), because those are the only sites that (a) consult
the in-memory cache the warm-up pre-populated, (b) count hits/misses,
and (c) record the persistent warm-up rung for the next process. A
``build_*_step`` call ANYWHERE ELSE in the driver packages is a compile
the cache layer cannot see — it would recompile on every call, dodge
the steady-state miss guard, and silently undo the cold-start work.

The runtime half lives in the sim harness: after warm-up completes, a
step-cache miss outside the warmup/ladder-transition/restart contexts
bumps ``koord_scheduler_steady_state_compiles_total`` and the report's
flag counters, which the coldstart gate asserts stay flat to the first
bind.

Scope: ``scheduler/``, ``balance/`` and ``colo/`` driver modules — the
builders themselves live in ``models/``/``ops/``/``parallel/`` and
compose freely there, and ``scheduler/warmup.py`` replays rungs through
builders by design. A deliberate exception takes ``# koordlint:
disable=compile-in-steady-state`` with rationale.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from koordinator_tpu.analysis.core import (
    Finding,
    ModuleContext,
    Rule,
    register,
)

# driver packages whose step compiles must be keyed-cache-routed
_DRIVER_PATH_RE = re.compile(r"(scheduler|balance|colo)/[^/]+\.py$")
# the warm-up ladder replays rungs through the builders by design
_EXEMPT_PATH_RE = re.compile(r"scheduler/warmup\.py$")
# a step-builder callable, by name: build_rebalance_step,
# build_sharded_full_chain_step, build_best_full_chain_step, ...
_BUILDER_RE = re.compile(r"^build_\w*step$")
# the keyed chokepoints: _get_step, _get_fused_step, _get_chain_step...
_CHOKEPOINT_RE = re.compile(r"^_get_\w*step$")

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _call_name(node: ast.Call) -> str:
    func = node.func
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


@register
class CompileInSteadyState(Rule):
    name = "compile-in-steady-state"
    severity = "error"
    description = (
        "a step builder (build_*_step) called outside the keyed "
        "step-cache chokepoints (_get_*step) in a driver module: the "
        "compile bypasses the in-memory cache the warm-up ladder "
        "pre-populated, the hit/miss counters, the steady-state miss "
        "guard AND the persistent warm-up rung index — route it "
        "through the module's _get_*step, or pragma a deliberate "
        "exception")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not _DRIVER_PATH_RE.search(ctx.path):
            return
        if _EXEMPT_PATH_RE.search(ctx.path):
            return
        parents = ctx.parent_map()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if not _BUILDER_RE.match(name):
                continue
            # walk up through ALL enclosing functions; any _get_*step
            # frame on the way legitimizes the call (a retry/span
            # closure inside a chokepoint is still chokepoint-routed)
            cur = node
            enclosing = None
            routed = False
            while cur in parents:
                cur = parents[cur]
                if isinstance(cur, _FUNC_DEFS):
                    if enclosing is None:
                        enclosing = cur
                    if _CHOKEPOINT_RE.match(cur.name):
                        routed = True
                        break
            if routed:
                continue
            where = (f"inside {enclosing.name!r}" if enclosing is not None
                     else "at module scope")
            yield self.finding(
                ctx, node,
                f"{name}() {where}: step compile outside the keyed "
                f"step-cache chokepoints (_get_*step) — in steady "
                f"state this recompiles on every call and bypasses the "
                f"warm-up/miss-guard machinery")
