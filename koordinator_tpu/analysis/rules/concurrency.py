"""Concurrency-discipline rules.

Scope: the modules that actually face more than one thread — the scheduler
cycle driver and its caches (cycle.py, snapshot_cache.py, frameworkext.py),
the event-sourced object store, the koordlet daemon tree (metrics
collectors, hook server, states informer all run threads), the
runtimeproxy servers, and the obs/ tracing layer (its finished-root ring
is shared across every traced thread). Everywhere else a module-level
dict is usually an import-time registry and flagging it would be noise,
so the rules gate on the module path.

Rules:

  * shared-mutable-global — a module-level mutable container that some
    function in the module writes (subscript/augassign/mutating method)
    outside any ``with <lock>`` block. Import-time registration patterns
    live outside the gated paths and stay legal.
  * unlocked-shared-mutation — inside a class that starts threads/timers,
    a method (other than __init__/_init*, which run happens-before the
    spawn) mutating ``self.<attr>`` outside a ``with <lock-ish>`` block.
  * except-swallow — a bare ``except:`` or an ``except Exception`` whose
    whole body is pass/continue/...: the scheduler's correctness story
    leans on loud failure (parity tests, exactness contracts); silently
    eating BaseException-adjacent errors hides the exact bugs the rest of
    this linter exists to surface.
  * silent-exception-swallow — the ERROR-severity version for the
    dispatch-critical paths (scheduler/, obs/, parallel/, sim/): a broad
    handler whose body only pass/continue/returns-a-constant, with no
    raise, no log, no metric. The degradation ladder turned "dispatch
    failure" into control flow there, so an unobserved swallow doesn't
    just hide a bug — it can mask the exact signal the ladder, the
    flight recorder, and the sim's fault plan exist to surface (the
    koordlet device probe swallowed exactly this way for six PRs).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional, Set

from koordinator_tpu.analysis.core import (
    Finding,
    ModuleContext,
    Rule,
    register,
)

# path fragments that mark a module as concurrency-sensitive
_CONCURRENT_PATH_RE = re.compile(
    r"(koordlet/|runtimeproxy/|(^|/)obs/|client/store\.py"
    r"|scheduler/cycle\.py"
    r"|scheduler/snapshot_cache\.py|scheduler/frameworkext\.py)")

_LOCKISH_RE = re.compile(r"(lock|mutex|cond|sem|rlock)", re.IGNORECASE)

_MUTATING_METHODS = {
    "append", "add", "update", "pop", "setdefault", "clear", "extend",
    "remove", "insert", "popitem", "discard", "appendleft",
}

_MUTABLE_CTORS = {"dict", "list", "set", "defaultdict", "deque",
                  "OrderedDict", "Counter"}

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def is_concurrent_path(path: str) -> bool:
    return _CONCURRENT_PATH_RE.search(path) is not None


def _is_mutable_literal(node: ast.AST) -> bool:
    if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        f = node.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else "")
        return name in _MUTABLE_CTORS
    return False


def _lock_held(ctx: ModuleContext, node: ast.AST) -> bool:
    """Is `node` lexically inside a ``with <something lock-ish>`` block?"""
    parents = ctx.parent_map()
    cur: Optional[ast.AST] = node
    while cur is not None:
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            for item in cur.items:
                expr = item.context_expr
                # with self._lock:  /  with lock:  /  with self.lock.gen():
                for sub in ast.walk(expr):
                    name = ""
                    if isinstance(sub, ast.Attribute):
                        name = sub.attr
                    elif isinstance(sub, ast.Name):
                        name = sub.id
                    if name and _LOCKISH_RE.search(name):
                        return True
        cur = parents.get(cur)
    return False


def _mutation_target(node: ast.AST) -> Optional[ast.AST]:
    """If `node` writes a container, return the expression it writes."""
    if isinstance(node, ast.Assign):
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                return t.value
    elif isinstance(node, ast.AugAssign):
        if isinstance(node.target, ast.Subscript):
            return node.target.value
        return node.target
    elif (isinstance(node, ast.Call)
          and isinstance(node.func, ast.Attribute)
          and node.func.attr in _MUTATING_METHODS):
        return node.func.value
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                return t.value
    return None


def _locally_bound_names(fn: ast.AST) -> Set[str]:
    """Names that are locals of `fn` per Python scoping: parameters plus
    plain-name binding targets (assign/annassign/for/with/walrus), minus
    anything declared `global`."""
    bound: Set[str] = set(
        a.arg for a in (fn.args.args + fn.args.kwonlyargs
                        + fn.args.posonlyargs))
    for extra in (fn.args.vararg, fn.args.kwarg):
        if extra is not None:
            bound.add(extra.arg)
    declared_global: Set[str] = set()
    for node in ast.walk(fn):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.For, ast.NamedExpr)):
            targets = [node.target]
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            targets = [i.optional_vars for i in node.items
                       if i.optional_vars is not None]
        elif isinstance(node, ast.Global):
            declared_global.update(node.names)
        for t in targets:
            # only NAME targets bind; a subscript/attribute store
            # (_cache[k] = v) mutates the existing object, it does not
            # rebind the name
            stack = [t]
            while stack:
                sub = stack.pop()
                if isinstance(sub, ast.Name):
                    bound.add(sub.id)
                elif isinstance(sub, (ast.Tuple, ast.List)):
                    stack.extend(sub.elts)
                elif isinstance(sub, ast.Starred):
                    stack.append(sub.value)
    return bound - declared_global


@register
class SharedMutableGlobal(Rule):
    name = "shared-mutable-global"
    severity = "error"
    description = (
        "module-level mutable container written from function scope "
        "without a lock in a concurrency-sensitive module (scheduler "
        "cycle/caches, store, koordlet, runtimeproxy): interleaved "
        "writers corrupt shared scheduler state")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not is_concurrent_path(ctx.path):
            return
        globals_: Set[str] = set()
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.Assign) and _is_mutable_literal(
                    stmt.value):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        globals_.add(t.id)
            elif (isinstance(stmt, ast.AnnAssign) and stmt.value is not None
                  and _is_mutable_literal(stmt.value)
                  and isinstance(stmt.target, ast.Name)):
                globals_.add(stmt.target.id)
        if not globals_:
            return
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, _FUNC_DEFS):
                continue
            # names shadowed by params/local assignment are locals, not
            # the module global — unless a `global` statement says so
            shadowed = _locally_bound_names(fn)
            for node in ast.walk(fn):
                target = _mutation_target(node)
                if target is None or not isinstance(target, ast.Name):
                    continue
                name = target.id
                if name not in globals_ or name in shadowed:
                    continue
                if _lock_held(ctx, node):
                    continue
                yield self.finding(
                    ctx, node,
                    f"module-level mutable {name!r} mutated in "
                    f"{fn.name!r} without holding a lock")


def _class_spawns_threads(cls: ast.ClassDef) -> bool:
    for node in ast.walk(cls):
        if isinstance(node, ast.Call):
            f = node.func
            tail = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else "")
            if tail in ("Thread", "Timer", "ThreadPoolExecutor"):
                return True
    return False


@register
class UnlockedSharedMutation(Rule):
    name = "unlocked-shared-mutation"
    severity = "warning"
    description = (
        "in a thread-spawning class (concurrency-sensitive modules only), "
        "a non-__init__ method mutates self.<container> outside a 'with "
        "<lock>' block: the spawned thread and its owner race on the "
        "attribute")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not is_concurrent_path(ctx.path):
            return
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            if not _class_spawns_threads(cls):
                continue
            for fn in cls.body:
                if not isinstance(fn, _FUNC_DEFS):
                    continue
                if fn.name == "__init__" or fn.name.startswith("_init"):
                    continue  # construction happens-before thread spawn
                for node in ast.walk(fn):
                    target = _mutation_target(node)
                    if (target is None
                            or not isinstance(target, ast.Attribute)
                            or not isinstance(target.value, ast.Name)
                            or target.value.id != "self"):
                        continue
                    if _LOCKISH_RE.search(target.attr):
                        continue  # mutating the lock container itself
                    if _lock_held(ctx, node):
                        continue
                    yield self.finding(
                        ctx, node,
                        f"self.{target.attr} mutated in "
                        f"{cls.name}.{fn.name} outside a lock while the "
                        f"class spawns threads")


@register
class ExceptSwallow(Rule):
    name = "except-swallow"
    severity = "warning"
    description = (
        "bare 'except:' or an 'except Exception' handler whose entire "
        "body is pass/continue: swallows the loud failures (parity "
        "mismatches, exactness violations) the test strategy depends on")

    _BROAD = {"Exception", "BaseException"}

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                yield self.finding(
                    ctx, node,
                    "bare 'except:' catches KeyboardInterrupt/SystemExit "
                    "too; name the exception")
                continue
            names = set()
            types = (node.type.elts if isinstance(node.type, ast.Tuple)
                     else [node.type])
            for t in types:
                if isinstance(t, ast.Name):
                    names.add(t.id)
                elif isinstance(t, ast.Attribute):
                    names.add(t.attr)
            if not (names & self._BROAD):
                continue
            if all(isinstance(s, (ast.Pass, ast.Continue))
                   or (isinstance(s, ast.Expr)
                       and isinstance(s.value, ast.Constant))
                   for s in node.body):
                yield self.finding(
                    ctx, node,
                    "except Exception with an empty body silently "
                    "swallows every error; log or narrow it")


# the dispatch-critical packages where an unobserved swallow can mask the
# very failure signal the degradation ladder / flight recorder / sim
# fault plan are built around
_SWALLOW_GATED_RE = re.compile(r"(^|/)(scheduler|obs|parallel|sim)/")

_BROAD = {"Exception", "BaseException"}


def _is_broad_handler(node: ast.ExceptHandler) -> bool:
    if node.type is None:
        return True
    types = (node.type.elts if isinstance(node.type, ast.Tuple)
             else [node.type])
    for t in types:
        name = (t.id if isinstance(t, ast.Name)
                else t.attr if isinstance(t, ast.Attribute) else "")
        if name in _BROAD:
            return True
    return False


def _is_trivial_swallow_stmt(s: ast.stmt) -> bool:
    """pass / continue / a bare docstring-style constant / `return` of a
    constant or empty literal — shapes that discard the error without a
    trace. Anything else (a call, an assignment, a raise) counts as
    handling and is left to human review."""
    if isinstance(s, (ast.Pass, ast.Continue)):
        return True
    if isinstance(s, ast.Expr) and isinstance(s.value, ast.Constant):
        return True
    if isinstance(s, ast.Return):
        v = s.value
        if v is None or isinstance(v, ast.Constant):
            return True
        if isinstance(v, (ast.List, ast.Dict, ast.Tuple, ast.Set)):
            return not (getattr(v, "elts", None)
                        or getattr(v, "keys", None))
    return False


@register
class SilentExceptionSwallow(Rule):
    name = "silent-exception-swallow"
    severity = "error"
    description = (
        "in a dispatch-critical package (scheduler/, obs/, parallel/, "
        "sim/), a bare 'except:' / 'except Exception' whose whole body "
        "is pass/continue/return-constant — no raise, no log, no "
        "metric: the degradation ladder, flight recorder and sim fault "
        "plan all depend on failures being observable there; swallow "
        "deliberately only with a pragma explaining why")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not _SWALLOW_GATED_RE.search(ctx.path):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad_handler(node):
                continue
            if all(_is_trivial_swallow_stmt(s) for s in node.body):
                yield self.finding(
                    ctx, node,
                    "broad except handler discards the error without a "
                    "trace in a dispatch-critical path; log it, count "
                    "it, re-raise, or pragma the intent")
