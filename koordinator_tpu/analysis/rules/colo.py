"""koordcolo discipline: the control-plane pass stays a tensor pass.

The whole point of ``koordinator_tpu/colo/`` is ONE batched device
program over ONE shared encode of the cluster (the scheduler's
SnapshotCache feeds the pack; the DeviceSnapshot is the single mirror —
three consumers now). Two regressions would quietly rebuild the
per-node reconcile loops this subsystem replaced:

  * a per-node/per-quota Python ``for`` loop on the pass path — the
    whole-cluster overcommit degrades back to the reference's per-node
    reconcile iteration;
  * a second encode — ``store.list`` walks inside colo/ re-pack state
    the SnapshotCache-fed pack (or the quota plugin's epoch-memoized
    tree) already maintains, breaking the one-upload-three-consumers
    invariant.

Event-maintenance loops (the pack's dirty-row refresh) are legitimate
and carry pragmas documenting why they are event-driven, not per-pass.
The writeback itself routes through the host oracle's
``NodeResourceController.apply`` (slocontroller/), which is outside
this package on purpose — store writes are the oracle's job.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from koordinator_tpu.analysis.core import (
    Finding,
    ModuleContext,
    Rule,
    register,
)

_COLO_PATH_RE = re.compile(r"(^|/)colo/[^/]+\.py$")


def _is_store_receiver(node: ast.AST) -> bool:
    if isinstance(node, ast.Attribute):
        return node.attr in ("store", "_store")
    if isinstance(node, ast.Name):
        return node.id in ("store", "_store")
    return False


@register
class HostReconcileInColoPath(Rule):
    name = "host-reconcile-in-colo-path"
    severity = "error"
    description = (
        "per-node/per-quota Python loop or a second state encode inside "
        "koordinator_tpu/colo/: the colo pass is ONE batched tensor "
        "program over the pack-memo-shared snapshot — a host `for` loop "
        "re-grows the per-node reconcile loops it replaced, and a "
        "store.list walk re-encodes state the SnapshotCache-fed pack "
        "already maintains (one upload, three consumers); "
        "event-maintenance loops must carry a # koordlint: disable "
        "pragma documenting why they are event-driven, not per-pass")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not _COLO_PATH_RE.search(ctx.path):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.For):
                yield self.finding(
                    ctx, node,
                    "host for-loop in the colo path — express it as a "
                    "batched array op (or pragma a deliberate "
                    "event-maintenance loop)")
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "list"
                    and _is_store_receiver(node.func.value)):
                yield self.finding(
                    ctx, node,
                    "store.list inside colo/ is a second state encode — "
                    "consume the SnapshotCache-shared ColoPack view (or "
                    "the quota plugin's epoch-memoized tree) instead")
