"""Unbounded-scan heuristic.

The de-quadratification work of earlier rounds (packed prefilters in
preempt.py, the event-driven snapshot cache) exists because per-pod x
per-node Python loops inside the scheduling cycle are exactly what
collapses at 10k pods x 5k nodes. This rule flags the shape that keeps
trying to creep back in: inside scheduler modules, a ``for`` loop over a
fleet-sized iterable (pods/nodes/candidates/...) whose body contains
ANOTHER loop or comprehension over a fleet-sized iterable, with no
``break`` anywhere in the outer body — i.e. an uncapped full cross
product. A cap-with-break (preempt.py's candidate window) or a vectorized
escape satisfies the rule.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from koordinator_tpu.analysis.core import (
    Finding,
    ModuleContext,
    Rule,
    register,
)

_SCHED_PATH_RE = re.compile(r"(scheduler/|descheduler/)")

# fleet-sized iterable names (exact or plural-suffixed)
_FLEET_RE = re.compile(
    r"^(all_)?(nodes?|pods?|cands?|candidates?|feasible|live|victims?"
    r"|assigned|failed|rejected|bindings?)$")

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _fleet_name(it: ast.AST) -> Optional[str]:
    """The fleet-ish name an iterable expression loops over, if any."""
    if isinstance(it, ast.Name) and _FLEET_RE.match(it.id):
        return it.id
    if isinstance(it, ast.Attribute) and _FLEET_RE.match(it.attr):
        return it.attr
    # nodes.values() / by_node.get(name, []) style: look one level in
    if isinstance(it, ast.Call):
        f = it.func
        if isinstance(f, ast.Attribute):
            return _fleet_name(f.value)
    return None


def _inner_fleet_loop(outer: ast.For) -> Optional[ast.AST]:
    """A nested for/comprehension over a fleet iterable inside `outer`."""
    for node in ast.walk(outer):
        if node is outer:
            continue
        if isinstance(node, ast.For) and _fleet_name(node.iter):
            return node
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            for gen in node.generators:
                if _fleet_name(gen.iter):
                    return node
    return None


def _has_break(outer: ast.For) -> bool:
    for node in ast.walk(outer):
        if isinstance(node, ast.Break):
            return True
    return False


@register
class UnboundedScan(Rule):
    name = "unbounded-scan"
    severity = "warning"
    description = (
        "uncapped per-pod x per-node Python cross product inside a "
        "scheduler module: an outer loop over a fleet-sized iterable "
        "nests another fleet-sized loop with no break/cap — the O(P*N) "
        "shape the packed prefilters exist to avoid; add a candidate cap "
        "or vectorize")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not _SCHED_PATH_RE.search(ctx.path):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.For):
                continue
            outer_name = _fleet_name(node.iter)
            if outer_name is None:
                continue
            inner = _inner_fleet_loop(node)
            if inner is None:
                continue
            if _has_break(node):
                continue
            yield self.finding(
                ctx, node,
                f"loop over {outer_name!r} nests another fleet-sized "
                f"scan (line {inner.lineno}) with no cap/break: "
                f"O(P*N) Python work in the cycle")
