"""JAX-tracing hygiene rules.

The rebuild's hot path is batched pod x node math under ``jax.jit`` /
``pallas_call``; code inside those traces must not synchronize with the
host (``.item()``, ``np.asarray``, ``print``), must not branch in Python
on traced values (silent recompilation per shape/value, or a flat
TracerBoolConversionError at scale), and must pin dtypes on array
constructors (implicit float64 under x64 doubles HBM traffic and breaks
the kernels' f32-exactness discipline).

Traced-function discovery is lexical and per-module:

  1. defs decorated with jit/pjit/vmap/checkpoint/remat (bare, dotted, or
     wrapped in functools.partial(jax.jit, ...));
  2. local defs whose NAME is passed to a tracing entry point —
     ``jax.jit(step)``, ``pl.pallas_call(kernel, ...)``,
     ``jax.lax.scan/fori_loop/while_loop/cond``, ``jax.vmap`` — anywhere
     in the module (this repo's dominant idiom: build_x_step defines
     ``step`` then returns ``jax.jit(step)``);
  3. the transitive closure over local calls: a helper invoked from a
     traced body is itself traced.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set

from koordinator_tpu.analysis.core import (
    Finding,
    ModuleContext,
    Rule,
    register,
)

# call targets whose function-typed arguments become traced
_TRACING_ENTRY_TAILS = {
    "jit", "pjit", "vmap", "pmap", "pallas_call", "scan", "fori_loop",
    "while_loop", "cond", "checkpoint", "remat", "shard_map", "grad",
    "value_and_grad", "custom_vjp", "custom_jvp", "named_call",
}

_TRACE_DECORATOR_TAILS = {
    "jit", "pjit", "vmap", "pmap", "checkpoint", "remat", "custom_vjp",
    "custom_jvp",
}

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _dotted_tail(node: ast.AST) -> str:
    """Last attribute segment of a Name/Attribute chain ('' otherwise)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _is_tracing_call(call: ast.Call) -> bool:
    tail = _dotted_tail(call.func)
    if tail in _TRACING_ENTRY_TAILS:
        return True
    # functools.partial(jax.jit, ...) as decorator/wrapper
    if tail == "partial" and call.args:
        return _dotted_tail(call.args[0]) in _TRACING_ENTRY_TAILS
    return False


def find_traced_functions(tree: ast.Module) -> Set[ast.AST]:
    defs_by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, _FUNC_DEFS):
            defs_by_name.setdefault(node.name, []).append(node)

    traced: Set[ast.AST] = set()

    for node in ast.walk(tree):
        if isinstance(node, _FUNC_DEFS):
            for dec in node.decorator_list:
                tail = _dotted_tail(dec)
                if tail in _TRACE_DECORATOR_TAILS:
                    traced.add(node)
                elif isinstance(dec, ast.Call) and _is_tracing_call(dec):
                    traced.add(node)
        elif isinstance(node, ast.Call) and _is_tracing_call(node):
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name):
                    for d in defs_by_name.get(arg.id, []):
                        traced.add(d)
                elif isinstance(arg, ast.Lambda):
                    traced.add(arg)

    # transitive closure over same-module calls from traced bodies
    changed = True
    while changed:
        changed = False
        for fn in list(traced):
            body = fn.body if isinstance(fn, _FUNC_DEFS) else [fn.body]
            for node in ast.walk(ast.Module(body=list(body),
                                            type_ignores=[])):
                if isinstance(node, ast.Call):
                    name = (node.func.id
                            if isinstance(node.func, ast.Name) else "")
                    for d in defs_by_name.get(name, []):
                        if d not in traced:
                            traced.add(d)
                            changed = True
    return traced


def _body_nodes(fn: ast.AST, skip: Set[ast.AST] = frozenset()
                ) -> Iterator[ast.AST]:
    """Walk a traced callable's body (lambda bodies included), without
    descending into nested defs in `skip` — they are traced functions in
    their own right and report their own findings once."""
    roots = (list(fn.body) if isinstance(fn, _FUNC_DEFS)
             else [fn.body] if isinstance(fn, ast.Lambda) else [])
    stack = list(roots)
    while stack:
        node = stack.pop()
        if node in skip:
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _contains_shape_or_len(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in (
                "shape", "ndim", "size", "dtype"):
            return True
        if (isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name)
                and sub.func.id == "len"):
            return True
    return False


def _under_isinstance_guard(ctx: ModuleContext, node: ast.AST) -> bool:
    """Is `node` inside an if/elif whose test calls isinstance()? Such
    branches are runtime-type dispatch (e.g. 'not a Tracer' fast paths)
    where host materialization is deliberate."""
    parents = ctx.parent_map()
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.If, ast.IfExp)):
            for sub in ast.walk(cur.test):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Name)
                        and sub.func.id == "isinstance"):
                    return True
        cur = parents.get(cur)
    return False


@register
class HostSyncInTrace(Rule):
    name = "jax-host-sync"
    severity = "error"
    description = (
        "host synchronization inside a jit/pallas-traced function: "
        ".item()/.tolist()/np.asarray/float()/int() forces a device->host "
        "readback (or fails outright on tracers), serializing the batched "
        "Filter/Score pipeline")

    _SYNC_METHODS = {"item", "tolist", "block_until_ready"}
    _HOST_NUMPY = {"asarray", "array"}
    _CASTS = {"float", "int", "bool"}

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        traced = ctx.traced_functions()
        for fn in traced:
            jnp_names = _jnp_derived_names(fn, traced)
            for node in _body_nodes(fn, skip=traced):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if (isinstance(func, ast.Attribute)
                        and func.attr in self._SYNC_METHODS
                        and not node.args):
                    yield self.finding(
                        ctx, node,
                        f".{func.attr}() inside traced function "
                        f"{_fn_name(fn)!r} forces a host sync")
                elif (isinstance(func, ast.Attribute)
                      and isinstance(func.value, ast.Name)
                      and func.value.id in ("np", "numpy")
                      and func.attr in self._HOST_NUMPY
                      and not _under_isinstance_guard(ctx, node)):
                    yield self.finding(
                        ctx, node,
                        f"np.{func.attr}() inside traced function "
                        f"{_fn_name(fn)!r} materializes on host; use jnp")
                elif (isinstance(func, ast.Name)
                      and func.id in self._CASTS and len(node.args) == 1
                      # only values that flowed through jnp/lax ops are
                      # (likely) tracers; float() on static Python config
                      # is trace-time metaprogramming and legal
                      and _expr_is_jnp(node.args[0], jnp_names)
                      and not _contains_shape_or_len(node.args[0])
                      and not _under_isinstance_guard(ctx, node)):
                    yield self.finding(
                        ctx, node,
                        f"{func.id}() on a jnp-derived value inside traced "
                        f"function {_fn_name(fn)!r} concretizes the tracer")


@register
class TracedValueBranch(Rule):
    name = "jax-traced-branch"
    severity = "error"
    description = (
        "Python if/while/assert on a value produced by jnp ops inside a "
        "traced function: bool() on a tracer raises (or triggers "
        "per-value recompilation under static args); use jnp.where / "
        "lax.cond")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        traced = ctx.traced_functions()
        for fn in traced:
            jnp_names = _jnp_derived_names(fn, traced)
            for node in _body_nodes(fn, skip=traced):
                test = None
                if isinstance(node, (ast.If, ast.While, ast.Assert,
                                     ast.IfExp)):
                    test = node.test
                if test is None:
                    continue
                if _expr_is_jnp(test, jnp_names):
                    yield self.finding(
                        ctx, node,
                        f"Python branch on jnp-derived value inside traced "
                        f"function {_fn_name(fn)!r}; use jnp.where or "
                        f"lax.cond")


def _jnp_derived_names(fn: ast.AST, traced: Set[ast.AST]) -> Set[str]:
    """Names assigned (directly or through arithmetic) from jnp.*/lax.*
    calls within the function body. A subscripted store taints only the
    container, never the index (numa[k] = jnp... must not taint k)."""
    derived: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for node in _body_nodes(fn, skip=traced):
            if not isinstance(node, (ast.Assign, ast.AugAssign)):
                continue
            if not _expr_is_jnp(node.value, derived):
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                while isinstance(t, (ast.Subscript, ast.Starred,
                                     ast.Attribute)):
                    t = t.value
                names = ([t] if isinstance(t, ast.Name)
                         else [e for e in getattr(t, "elts", [])
                               if isinstance(e, ast.Name)])
                for n in names:
                    if n.id not in derived:
                        derived.add(n.id)
                        changed = True
    return derived


def _expr_is_jnp(node: ast.AST, derived: Set[str]) -> bool:
    """Does this expression produce a (likely) traced array — a jnp.* /
    lax.* call or arithmetic over names already known to?"""
    if isinstance(node, ast.Call):
        f = node.func
        while isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name) and f.value.id in (
                    "jnp", "lax"):
                return True
            f = f.value
        return False
    if isinstance(node, ast.BinOp):
        return (_expr_is_jnp(node.left, derived)
                or _expr_is_jnp(node.right, derived))
    if isinstance(node, ast.UnaryOp):
        return _expr_is_jnp(node.operand, derived)
    if isinstance(node, ast.Compare):
        return any(_expr_is_jnp(c, derived)
                   for c in [node.left] + node.comparators)
    if isinstance(node, (ast.Subscript, ast.Attribute)):
        return _expr_is_jnp(node.value, derived)
    if isinstance(node, ast.Name):
        return node.id in derived
    return False


def _fn_name(fn: ast.AST) -> str:
    return getattr(fn, "name", "<lambda>")


@register
class ImplicitDtype(Rule):
    name = "jax-implicit-dtype"
    severity = "warning"
    description = (
        "jnp array constructor without an explicit dtype=: the result "
        "dtype then depends on jax_enable_x64 / weak-type promotion, and "
        "an accidental float64 doubles HBM traffic and breaks f32 "
        "exactness parity with the serial floor")

    _CONSTRUCTORS = {"zeros", "ones", "full", "empty", "arange",
                     "linspace", "eye"}

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not (isinstance(func, ast.Attribute)
                    and isinstance(func.value, ast.Name)
                    and func.value.id == "jnp"
                    and func.attr in self._CONSTRUCTORS):
                continue
            if any(kw.arg == "dtype" for kw in node.keywords):
                continue
            # positional dtype: zeros(shape, dtype) / full(shape, v, dtype)
            npos = {"zeros": 2, "ones": 2, "empty": 2, "eye": 2,
                    "full": 3, "arange": 4, "linspace": 7}
            if len(node.args) >= npos.get(func.attr, 99):
                continue
            yield self.finding(
                ctx, node,
                f"jnp.{func.attr}() without dtype=; pin the dtype "
                f"(implicit float64 drift)")


@register
class JitInLoop(Rule):
    name = "jax-jit-in-loop"
    severity = "warning"
    description = (
        "jax.jit/pallas_call invoked inside a Python loop: every "
        "iteration builds and compiles a fresh program (cache keyed on "
        "function identity), turning a hot loop into a recompilation "
        "storm; hoist the jit out or cache the compiled callable")

    _TAILS = {"jit", "pjit", "pallas_call"}

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        loops: List[ast.AST] = [
            n for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.For, ast.While))
        ]
        for loop in loops:
            for node in ast.walk(loop):
                if node is loop:
                    continue
                if (isinstance(node, ast.Call)
                        and _dotted_tail(node.func) in self._TAILS
                        # a def inside the loop is only a definition;
                        # flag direct calls in the loop body
                        and not _inside_def(loop, node)):
                    yield self.finding(
                        ctx, node,
                        f"{_dotted_tail(node.func)}() inside a loop "
                        f"recompiles every iteration; hoist or memoize")


def _inside_def(loop: ast.AST, node: ast.AST) -> bool:
    """Is `node` under a function definition nested inside `loop`?"""
    for sub in ast.walk(loop):
        if isinstance(sub, _FUNC_DEFS) and sub is not loop:
            for inner in ast.walk(sub):
                if inner is node:
                    return True
    return False


@register
class PrintInTrace(Rule):
    name = "jax-print-in-jit"
    severity = "warning"
    description = (
        "print() inside a traced function executes at TRACE time only "
        "(silent at run time) — or forces a host callback; use "
        "jax.debug.print for runtime values")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        traced = ctx.traced_functions()
        for fn in traced:
            for node in _body_nodes(fn, skip=traced):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id == "print"):
                    yield self.finding(
                        ctx, node,
                        f"print() inside traced function "
                        f"{_fn_name(fn)!r}; use jax.debug.print")
