"""Pipelined-cycle readback discipline.

The cycle pipeline (scheduler/cycle.py CyclePipeline) exists because
``np.asarray`` on a device value is a host-blocking sync: the serial path
used to block the host for the whole kernel duration doing nothing. The
overlap only survives if the pipelined region keeps a SINGLE designated
sync point. This rule flags ``np.asarray`` / ``block_until_ready`` calls
lexically inside scheduler/cycle.py's pipelined region — the bodies of
``tracer.span("kernel")`` / ``tracer.span("overlap_wait")`` blocks —
unless the line carries a ``# koordlint: disable`` pragma documenting why
that sync is intended. A drive-by readback added "for debugging" would
silently serialize the pipeline again; with this rule it cannot land
without a visible pragma.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from koordinator_tpu.analysis.core import (
    Finding,
    ModuleContext,
    Rule,
    register,
)

# the pipelined region lives in the cycle driver only
_CYCLE_PATH_RE = re.compile(r"scheduler/cycle\.py$")

# span names whose with-bodies form the pipelined region (dispatch ..
# readback): host code here runs while the device executes
_REGION_SPANS = {"kernel", "overlap_wait"}

_BLOCKING_TAILS = {"asarray", "block_until_ready"}


def _dotted_tail(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _is_region_item(item: ast.withitem) -> bool:
    call = item.context_expr
    return (isinstance(call, ast.Call)
            and _dotted_tail(call.func) == "span"
            and bool(call.args)
            and isinstance(call.args[0], ast.Constant)
            and call.args[0].value in _REGION_SPANS)


@register
class BlockingReadbackInPipeline(Rule):
    name = "blocking-readback-in-pipeline"
    severity = "error"
    description = (
        "np.asarray / block_until_ready inside scheduler/cycle.py's "
        "pipelined region (the span(\"kernel\")/span(\"overlap_wait\") "
        "bodies) without a pragma: every readback is a host-blocking "
        "device sync, and an undeclared one silently re-serializes the "
        "cycle pipeline; keep the single designated sync point or mark "
        "the new one with # koordlint: disable")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not _CYCLE_PATH_RE.search(ctx.path):
            return
        seen = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.With):
                continue
            if not any(_is_region_item(item) for item in node.items):
                continue
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Call)
                        and _dotted_tail(sub.func) in _BLOCKING_TAILS
                        and id(sub) not in seen):
                    seen.add(id(sub))
                    yield self.finding(
                        ctx, sub,
                        f"{_dotted_tail(sub.func)} blocks the host inside "
                        "the pipelined kernel region — the overlap dies "
                        "silently; move it past the designated sync point "
                        "or annotate the intent with a pragma")
