"""Pipelined-cycle readback discipline.

The cycle pipeline (scheduler/cycle.py CyclePipeline) exists because
``np.asarray`` on a device value is a host-blocking sync: the serial path
used to block the host for the whole kernel duration doing nothing. The
overlap only survives if the pipelined region keeps a SINGLE designated
sync point. This rule flags ``np.asarray`` / ``block_until_ready`` calls
lexically inside scheduler/cycle.py's pipelined region — the bodies of
``tracer.span("kernel")`` / ``tracer.span("overlap_wait")`` blocks —
unless the line carries a ``# koordlint: disable`` pragma documenting why
that sync is intended. A drive-by readback added "for debugging" would
silently serialize the pipeline again; with this rule it cannot land
without a visible pragma.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from koordinator_tpu.analysis.core import (
    Finding,
    ModuleContext,
    Rule,
    register,
)

# the pipelined region lives in the cycle driver only
_CYCLE_PATH_RE = re.compile(r"scheduler/cycle\.py$")

# span names whose with-bodies form the pipelined region (dispatch ..
# readback): host code here runs while the device executes
_REGION_SPANS = {"kernel", "overlap_wait"}

_BLOCKING_TAILS = {"asarray", "block_until_ready"}


def _dotted_tail(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _is_region_item(item: ast.withitem) -> bool:
    call = item.context_expr
    return (isinstance(call, ast.Call)
            and _dotted_tail(call.func) == "span"
            and bool(call.args)
            and isinstance(call.args[0], ast.Constant)
            and call.args[0].value in _REGION_SPANS)


# the wave-kernel modules: pure device code end to end. A host transfer
# anywhere inside them runs INSIDE the fused dispatch's trace (or worse,
# per wave), destroying exactly the dispatch amortization the fused
# multi-wave design exists to buy.
_WAVE_PATH_RE = re.compile(r"models/(fused_waves|wave_chain)\.py$")

_WAVE_TRANSFER_TAILS = {"asarray", "item", "device_get",
                        "block_until_ready"}


def _is_device_asarray(func: ast.AST) -> bool:
    """jnp.asarray is a device-side dtype coercion, not a host transfer —
    only numpy's asarray (np./numpy./bare) pulls the value to host.
    Covers the spellings jnp.asarray and jax.numpy.asarray."""
    if not (isinstance(func, ast.Attribute) and func.attr == "asarray"):
        return False
    value = func.value
    if isinstance(value, ast.Name):
        return value.id == "jnp"
    return (isinstance(value, ast.Attribute) and value.attr == "numpy"
            and isinstance(value.value, ast.Name)
            and value.value.id == "jax")


@register
class ReadbackInWaveBody(Rule):
    name = "readback-in-wave-body"
    severity = "error"
    description = (
        "host transfer (np.asarray / .item() / jax.device_get / "
        "block_until_ready) inside a wave-kernel module "
        "(models/fused_waves.py, models/wave_chain.py): the wave body is "
        "traced into ONE fused device program precisely to amortize "
        "dispatch/readback overhead over K rounds — a host transfer "
        "inside it either breaks tracing or silently re-serializes every "
        "wave; keep all readback in the cycle driver's designated sync "
        "point or mark a deliberate exception with # koordlint: disable")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not _WAVE_PATH_RE.search(ctx.path):
            return
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Call)
                    and _dotted_tail(node.func) in _WAVE_TRANSFER_TAILS
                    and not _is_device_asarray(node.func)):
                yield self.finding(
                    ctx, node,
                    f"{_dotted_tail(node.func)} transfers to host inside "
                    "a wave-kernel module — the fused dispatch must stay "
                    "a single device program; read back in the cycle "
                    "driver instead")


# the mesh dispatch path: every host<->device transfer must route through
# the sharding-aware helpers (put_on_mesh pads + places per the mesh
# layout; merge_readback merges the compacted buffers from the per-shard
# copies with byte accounting). A bare device_put silently commits to ONE
# device — the first sharded consumer then pays a full reshard — and a
# bare asarray readback bypasses the per-shard observability.
_MESH_PATH_RE = re.compile(r"parallel/[^/]+\.py$")
_MESH_WRAPPERS = {"put_on_mesh", "merge_readback", "pad_for_sharding"}
_MESH_TRANSFER_TAILS = {"asarray", "device_put"}


@register
class UnshardedTransferInMeshPath(Rule):
    name = "unsharded-transfer-in-mesh-path"
    severity = "error"
    description = (
        "bare jax.device_put / np.asarray inside parallel/ or a mesh-path "
        "function of scheduler/cycle.py: mesh-dispatch transfers must go "
        "through put_on_mesh (pads non-divisible axes and places per the "
        "mesh sharding — a bare device_put commits to one device and "
        "forces a full reshard) and readbacks through merge_readback (the "
        "compacted per-shard merge with byte accounting); mark a "
        "deliberate exception with # koordlint: disable")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        in_parallel = bool(_MESH_PATH_RE.search(ctx.path))
        in_cycle = bool(_CYCLE_PATH_RE.search(ctx.path))
        if not (in_parallel or in_cycle):
            return
        # function scope map: parallel/ is mesh path everywhere except
        # inside the blessed wrapper definitions themselves; cycle.py's
        # mesh branch is its mesh-named functions
        wrapper_nodes = set()
        scopes = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if in_parallel and node.name in _MESH_WRAPPERS:
                    wrapper_nodes.add(node)
                elif in_cycle and "mesh" in node.name:
                    scopes.append(node)
        if in_parallel:
            exempt = set()
            for w in wrapper_nodes:
                exempt.update(id(n) for n in ast.walk(w))
            roots = [n for n in ast.walk(ctx.tree)
                     if id(n) not in exempt]
        else:
            roots = [n for s in scopes for n in ast.walk(s)]
        for node in roots:
            if (isinstance(node, ast.Call)
                    and _dotted_tail(node.func) in _MESH_TRANSFER_TAILS
                    and not _is_device_asarray(node.func)):
                yield self.finding(
                    ctx, node,
                    f"{_dotted_tail(node.func)} bypasses the mesh "
                    "sharding helpers — use put_on_mesh for uploads and "
                    "merge_readback for the compacted readback, or "
                    "annotate the intent with a pragma")


# the fused wave-replay loop (scheduler/cycle.py _replay_*/_fused_wave_*
# functions): per-pod store writes inside it are exactly what the
# overlapped-replay architecture batches away — a bind patch or condition
# write issued per pod re-serializes the replay against the store (lock +
# event fan-out per object) while the next wave executes. All writes must
# route through the designated batched flush sites (store.update_many
# bind transactions, the deferred-condition flush), which carry pragmas.
_SCHED_PATH_RE = re.compile(r"scheduler/[^/]+\.py$")
_REPLAY_FUNC_RE = re.compile(r"(replay|fused_wave|fused_no_node)")
_STORE_WRITE_TAILS = {"update", "add", "upsert", "delete", "update_many"}


def _is_store_receiver(node: ast.AST) -> bool:
    """self.store.update(...) / store.update(...) / self._store.add(...)."""
    if isinstance(node, ast.Attribute):
        return node.attr in ("store", "_store")
    if isinstance(node, ast.Name):
        return node.id in ("store", "_store")
    return False


@register
class StoreWriteInWaveReplayLoop(Rule):
    name = "store-write-in-wave-replay-loop"
    severity = "error"
    description = (
        "per-pod store write inside the fused wave-replay loop "
        "(scheduler/ functions named *replay*/*fused_wave*): the "
        "overlapped replay drains host work while the device executes "
        "the next wave, and per-object store calls re-serialize it "
        "against the store's lock and event fan-out — route bind patches "
        "and condition writes through the batched flush "
        "(store.update_many / the deferred-condition flush) or mark a "
        "designated flush site with # koordlint: disable")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not _SCHED_PATH_RE.search(ctx.path):
            return
        seen = set()
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _REPLAY_FUNC_RE.search(fn.name):
                continue
            for node in ast.walk(fn):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _STORE_WRITE_TAILS
                        and _is_store_receiver(node.func.value)
                        and id(node) not in seen):
                    seen.add(id(node))
                    yield self.finding(
                        ctx, node,
                        f"store.{node.func.attr} inside the wave-replay "
                        "loop — per-pod writes re-serialize the "
                        "overlapped replay; batch through update_many or "
                        "the deferred-condition flush (pragma the "
                        "designated flush site)")


# koordguard dispatch deadlines (scheduler/deadline.py): every blocking
# device sync in the dispatch paths must route through the deadline
# watchdog (Scheduler._readback_sync / DeviceRebalancer's monitored
# sync_readback), or a slow-not-dead device wedges the cycle with the
# watchdog none the wiser. Two shapes are flagged: bare
# ``block_until_ready`` anywhere in scheduler/, parallel/ or balance/
# (the unambiguous device sync), and ``np.asarray`` readbacks lexically
# inside a ``span("readback")`` body (the rebalance pass's sync site) —
# the designated drain/merge sites carry pragmas.
_DEADLINE_DIR_RE = re.compile(r"(^|/)(scheduler|parallel|balance)/[^/]+\.py$")
_READBACK_SPANS = {"readback"}


def _is_readback_span_item(item: ast.withitem) -> bool:
    call = item.context_expr
    return (isinstance(call, ast.Call)
            and _dotted_tail(call.func) == "span"
            and bool(call.args)
            and isinstance(call.args[0], ast.Constant)
            and call.args[0].value in _READBACK_SPANS)


@register
class NakedDeviceSyncWithoutDeadline(Rule):
    name = "naked-device-sync-without-deadline"
    severity = "error"
    description = (
        "bare device sync (block_until_ready, or np.asarray inside a "
        "span(\"readback\") body) in a scheduler/, parallel/ or "
        "balance/ dispatch path: blocking syncs must route through the "
        "dispatch-deadline watchdog (Scheduler._readback_sync / the "
        "rebalancer's monitored sync closure) so a slow-not-dead device "
        "demotes the ladder instead of wedging the cycle "
        "(KOORD_TPU_DISPATCH_DEADLINE_MS); mark a designated "
        "drain/merge site with # koordlint: disable")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not _DEADLINE_DIR_RE.search(ctx.path):
            return
        seen = set()
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Call)
                    and _dotted_tail(node.func) == "block_until_ready"):
                seen.add(id(node))
                yield self.finding(
                    ctx, node,
                    "block_until_ready outside the deadline watchdog — "
                    "a slow-not-dead device blocks here forever; route "
                    "the sync through the monitored readback or pragma "
                    "the designated drain site")
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.With):
                continue
            if not any(_is_readback_span_item(i) for i in node.items):
                continue
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Call)
                        and _dotted_tail(sub.func) == "asarray"
                        and not _is_device_asarray(sub.func)
                        and id(sub) not in seen):
                    seen.add(id(sub))
                    yield self.finding(
                        ctx, sub,
                        "np.asarray readback inline in a "
                        "span(\"readback\") body — run the sync through "
                        "the deadline watchdog (a monitored closure) or "
                        "pragma the designated site")


@register
class BlockingReadbackInPipeline(Rule):
    name = "blocking-readback-in-pipeline"
    severity = "error"
    description = (
        "np.asarray / block_until_ready inside scheduler/cycle.py's "
        "pipelined region (the span(\"kernel\")/span(\"overlap_wait\") "
        "bodies) without a pragma: every readback is a host-blocking "
        "device sync, and an undeclared one silently re-serializes the "
        "cycle pipeline; keep the single designated sync point or mark "
        "the new one with # koordlint: disable")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not _CYCLE_PATH_RE.search(ctx.path):
            return
        seen = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.With):
                continue
            if not any(_is_region_item(item) for item in node.items):
                continue
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Call)
                        and _dotted_tail(sub.func) in _BLOCKING_TAILS
                        and id(sub) not in seen):
                    seen.add(id(sub))
                    yield self.finding(
                        ctx, sub,
                        f"{_dotted_tail(sub.func)} blocks the host inside "
                        "the pipelined kernel region — the overlap dies "
                        "silently; move it past the designated sync point "
                        "or annotate the intent with a pragma")
