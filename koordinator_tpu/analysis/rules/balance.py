"""koordbalance discipline: the rebalance path stays a tensor pass.

The whole point of ``koordinator_tpu/balance/`` is ONE batched device
program over ONE shared encode of the cluster (the scheduler's
SnapshotCache feeds the pack; the DeviceSnapshot is the single mirror).
Two regressions would quietly rebuild the per-node Go loops this
subsystem replaced:

  * a per-node/per-pod Python ``for`` loop on the pass path — the
    10k-pod victim selection degrades back to host iteration;
  * a second pod encode — ``store.list(KIND_POD)`` walks inside
    balance/ re-pack the cluster the SnapshotCache already maintains,
    breaking the one-upload-two-consumers invariant.

Event-maintenance loops (the pack's node-table refresh, the
string->index remap) are legitimate and carry pragmas documenting why
they are event-driven, not per-pass.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from koordinator_tpu.analysis.core import (
    Finding,
    ModuleContext,
    Rule,
    register,
)

_BALANCE_PATH_RE = re.compile(r"(^|/)balance/[^/]+\.py$")


def _is_store_receiver(node: ast.AST) -> bool:
    if isinstance(node, ast.Attribute):
        return node.attr in ("store", "_store")
    if isinstance(node, ast.Name):
        return node.id in ("store", "_store")
    return False


@register
class HostLoopInRebalancePath(Rule):
    name = "host-loop-in-rebalance-path"
    severity = "error"
    description = (
        "per-node Python loop or a second pod encode inside "
        "koordinator_tpu/balance/: the rebalance pass is ONE batched "
        "tensor program over the pack-memo-shared snapshot — a host "
        "`for` loop re-grows the per-node Go loops it replaced, and a "
        "store.list(KIND_POD) walk re-encodes the cluster the "
        "SnapshotCache already maintains (one upload, two consumers); "
        "event-maintenance loops must carry a # koordlint: disable "
        "pragma documenting why they are event-driven, not per-pass")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not _BALANCE_PATH_RE.search(ctx.path):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.For):
                yield self.finding(
                    ctx, node,
                    "host for-loop in the rebalance path — express it "
                    "as a batched array op (or pragma a deliberate "
                    "event-maintenance loop)")
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "list"
                    and _is_store_receiver(node.func.value)
                    and node.args
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id == "KIND_POD"):
                yield self.finding(
                    ctx, node,
                    "store.list(KIND_POD) inside balance/ is a second "
                    "pod encode — consume the SnapshotCache-shared "
                    "RebalancePack view instead")
