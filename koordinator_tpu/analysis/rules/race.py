"""koordrace rules: whole-program lock-discipline checks.

Three ProgramRules over the guard map and lock graph that
analysis/guards.py builds from every scanned module at once (the scope
gate lives in the fact extraction — see guards.GUARD_SCAN_RE):

  * unguarded-shared-field — a field the guard map says is protected
    (annotated ``# koordlint: guarded-by(<lock>)`` or majority-inferred
    from ``with self._lock:`` bodies) read or written without that lock
    held, lexically or by every caller of the enclosing private method.
  * lock-order-inversion — the inter-procedural acquisition graph has
    either a cycle (two paths take the same locks in opposite orders:
    the classic ABBA deadlock) or an edge against the DECLARED canonical
    order in obs/lockorder.py (DeviceSnapshot mirror -> timeline ring ->
    metrics registry); the declared order is enforced as written, never
    re-inferred from whoever happened to nest first.
  * blocking-call-under-lock — a designated blocking operation (device
    syncs ``block_until_ready``/``device_get``, ``store.update_many``,
    an HTTP handler body via a ``*Server`` attribute, ``time.sleep``,
    ``serve_forever``) executed while holding a registry/ring lock:
    every other thread needing that lock stalls behind device/IO
    latency, which is exactly the convoy the dispatch-window discipline
    exists to prevent.

The runtime half lives in sim/racecheck.py: it drives the seeded sim
smoke scenario with forced preemption at the touchpoints this map
derives, and hack/check_races.py fails when the static findings and the
dynamic witnesses disagree.
"""

from __future__ import annotations

from typing import Iterator, Tuple

from koordinator_tpu.analysis.core import (
    Finding,
    ProgramContext,
    ProgramRule,
    register,
)
from koordinator_tpu.analysis.guards import MODULE_OWNER

# call tails that block: device syncs, the store's batched write (N
# notifications under the store lock), the HTTP serve loop, sleeps
_BLOCKING_TAILS = {"block_until_ready", "device_get", "update_many",
                   "serve_forever"}


def _owner_label(owner: str, field: str) -> str:
    if owner == MODULE_OWNER:
        return f"module-level {field!r}"
    return f"{owner}.{field}"


@register
class UnguardedSharedField(ProgramRule):
    name = "unguarded-shared-field"
    severity = "error"
    description = (
        "a field the guard map protects (guarded-by annotation or "
        "majority-inferred from 'with <lock>:' bodies) is read/written "
        "without its lock held — the tenth bare touch that undoes nine "
        "disciplined ones; annotate guarded-by(none) only for state "
        "with a documented single-writer story")

    def check_program(self, program: ProgramContext) -> Iterator[Finding]:
        gm = program.guard_map
        for facts, touch, gf in gm.guarded_touchpoints():
            if gf.guard in touch.held:
                continue
            held_by_callers = program.caller_held(facts.path).get(
                (touch.owner, touch.method), set())
            if gf.guard in held_by_callers:
                continue
            kind = "written" if touch.write else "read"
            yield self.finding_at(
                facts.path, touch.line,
                f"{_owner_label(touch.owner, touch.field)} is guarded by "
                f"{gf.guard!r} ({gf.source}) but {kind} in "
                f"{touch.method!r} without holding it")


@register
class LockOrderInversion(ProgramRule):
    name = "lock-order-inversion"
    severity = "error"
    description = (
        "two code paths acquire the same locks in opposite orders "
        "(ABBA deadlock), or an acquisition contradicts the canonical "
        "order declared in obs/lockorder.py (DeviceSnapshot mirror -> "
        "timeline ring -> metrics registry) — the declared order is "
        "enforced as written, not re-inferred")

    def check_program(self, program: ProgramContext) -> Iterator[Finding]:
        graph = program.lock_graph
        order = program.guard_map.canonical_order
        for edge in graph.declared_violations():
            yield self.finding_at(
                edge.path, edge.line,
                f"acquires {edge.dst} while holding {edge.src} "
                f"({edge.via}), against the declared canonical lock "
                f"order {' -> '.join(order)}")
        for cycle, witness in graph.cycles():
            chain = " -> ".join(cycle + (cycle[0],))
            yield self.finding_at(
                witness.path, witness.line,
                f"lock-order cycle {chain}: opposite-order acquisition "
                f"deadlocks under contention (witness edge "
                f"{witness.src} -> {witness.dst}, {witness.via})")


def _blocking_reason(facts, call) -> str:
    parts = call.target.split(".")
    tail = parts[-1]
    if tail in _BLOCKING_TAILS:
        return f"{call.target}() blocks"
    if tail == "sleep" and parts[0] == "time":
        return "time.sleep() parks the thread"
    if (tail == "handle" and len(parts) == 3 and parts[0] == "self"):
        cls = facts.attr_types.get(call.owner, {}).get(parts[1], "")
        if cls.endswith("Server"):
            return f"HTTP handler body {call.target}() runs under it"
    return ""


@register
class BlockingCallUnderLock(ProgramRule):
    name = "blocking-call-under-lock"
    severity = "error"
    description = (
        "a designated blocking operation (device sync, "
        "store.update_many, HTTP handler body, time.sleep, "
        "serve_forever) runs while a registry/ring lock is held: every "
        "thread needing that lock convoys behind device/IO latency")

    def check_program(self, program: ProgramContext) -> Iterator[Finding]:
        for facts in program.facts_list:
            caller_held = None
            for call in facts.calls:
                held: Tuple[str, ...] = call.held
                if not held:
                    if caller_held is None:
                        caller_held = program.caller_held(facts.path)
                    held = tuple(sorted(caller_held.get(
                        (call.owner, call.method), set())))
                if not held:
                    continue
                reason = _blocking_reason(facts, call)
                if not reason:
                    continue
                yield self.finding_at(
                    facts.path, call.line,
                    f"{reason} while {call.owner}.{call.method} holds "
                    f"{', '.join(repr(h) for h in held)}")
