"""Silent-demotion heuristic (koordwatch rule 19).

The ROADMAP's top open item — burning down the fused-wave demotion list —
was unmeasurable for four PRs because every demotion branch silently
``return 1``'d. PR 13 routed every such branch through ONE chokepoint
(``Scheduler._note_demotion(reason, value)``) that emits a structured
reason, a metric and the flight-record entry; this rule is the ROADMAP's
"koordlint pins that no new demotion branches appear unreviewed" pin.

Inside scheduler modules, a *demotion-resolving function* (name starts
with ``_effective_``: ``_effective_waves``, ``_effective_explain``, and
whatever joins them) may not:

  * ``return`` a bare constant (``return 1`` / ``return None`` / a bare
    ``return``) — a demoted level with no reason attached, or
  * assign a constant to a name the function later returns — the same
    silent demotion split across two statements.

Pass-throughs stay legal: ``return k`` / ``return self.explain_spec``
return the *resolved* value, and the chokepoint form
``return self._note_demotion("reason", 1)`` is a Call, not a constant.
A deliberate exception takes ``# koordlint: disable=silent-demotion-
branch`` with rationale.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Set

from koordinator_tpu.analysis.core import (
    Finding,
    ModuleContext,
    Rule,
    register,
)

_SCHED_PATH_RE = re.compile(r"scheduler/")
_RESOLVER_RE = re.compile(r"^_effective_")

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _own_nodes(fn: ast.AST):
    """The function's OWN statement tree: every descendant except those
    inside nested function definitions (a local helper has its own
    contract and must not be flagged against the resolver)."""
    nested: Set[int] = set()
    for node in ast.walk(fn):
        if isinstance(node, _FUNC_DEFS) and node is not fn:
            for sub in ast.walk(node):
                nested.add(id(sub))
    for node in ast.walk(fn):
        if node is fn or id(node) in nested:
            continue
        yield node


def _returned_names(fn: ast.AST) -> Set[str]:
    """Names the function returns directly (``return k``) — constant
    assignments to these are the two-statement silent-demotion shape."""
    out: Set[str] = set()
    for node in _own_nodes(fn):
        if isinstance(node, ast.Return) and isinstance(node.value, ast.Name):
            out.add(node.value.id)
    return out


@register
class SilentDemotionBranch(Rule):
    name = "silent-demotion-branch"
    severity = "error"
    description = (
        "constant return (or constant assignment to a returned name) "
        "inside a demotion-resolving scheduler function (_effective_*): "
        "a branch that lowers the wave/explain level without routing "
        "through the reason-emitting chokepoint "
        "(Scheduler._note_demotion) is a silent demotion — exactly the "
        "unmeasured fallbacks the ROADMAP burn-down needs attributed; "
        "wrap the fallback value in _note_demotion(reason, value) or "
        "mark a deliberate exception with # koordlint: disable")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not _SCHED_PATH_RE.search(ctx.path):
            return
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, _FUNC_DEFS):
                continue
            if not _RESOLVER_RE.match(fn.name):
                continue
            returned = _returned_names(fn)
            for node in _own_nodes(fn):
                if isinstance(node, ast.Return):
                    if node.value is None or isinstance(node.value,
                                                        ast.Constant):
                        yield self.finding(
                            ctx, node,
                            f"{fn.name} returns a bare constant: a "
                            f"demotion with no structured reason — "
                            f"route it through "
                            f"self._note_demotion(reason, value)")
                elif isinstance(node, ast.Assign) and isinstance(
                        node.value, ast.Constant):
                    for target in node.targets:
                        if (isinstance(target, ast.Name)
                                and target.id in returned):
                            yield self.finding(
                                ctx, node,
                                f"{fn.name} assigns a constant to "
                                f"{target.id!r}, which it returns: the "
                                f"two-statement silent demotion — "
                                f"route the fallback through "
                                f"self._note_demotion(reason, value)")
