"""Wire-decode safety rule.

The exemplar bug class is scheduler/config_v1beta2.py pre-fix: deep inside
``decode_component_config`` the code called ``entry.get("name")`` and
``args_obj.get("kind")`` on values that came off the YAML/JSON wire via
``profile.get("pluginConfig")`` — a wire payload of ``pluginConfig:
["oops"]`` or ``args: "foo"`` raised AttributeError out of a module whose
contract is "malformed wire input surfaces as ConfigValidationError".

The rule is a per-function heuristic over decode-shaped functions (name
starting with decode_/parse_/load_/from_): it tracks names that are
WIRE-DERIVED — bound by iterating a container read off another wire value
(``for entry in profile.get(...)``) or assigned from a ``.get()`` call —
and flags dict-protocol use of such a name (``.get``/``.items``/
``.keys``/``.values``/``.setdefault`` calls, or subscripting) unless the
function body guards that name with ``isinstance(name, dict)`` (Mapping
accepted). Top-level parameters are NOT flagged: the function signature is
the caller's contract; it is the nested, unvalidated layers that bite.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, Set

from koordinator_tpu.analysis.core import (
    Finding,
    ModuleContext,
    Rule,
    register,
)

_DECODE_NAME_RE = re.compile(r"^(decode|parse|load|from)_")

_DICT_METHODS = {"get", "items", "keys", "values", "setdefault"}

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _is_get_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get")


def _contains_get_or_subscript(node: ast.AST) -> bool:
    return any(
        _is_get_call(sub) or isinstance(sub, ast.Subscript)
        for sub in ast.walk(node))


_MAPPING_TYPE_NAMES = {"dict", "Mapping", "MutableMapping", "OrderedDict"}


def _names_a_mapping_type(node: ast.AST) -> bool:
    types = node.elts if isinstance(node, ast.Tuple) else [node]
    for t in types:
        name = (t.id if isinstance(t, ast.Name)
                else t.attr if isinstance(t, ast.Attribute) else "")
        if name in _MAPPING_TYPE_NAMES:
            return True
    return False


def _isinstance_guarded(fn: ast.AST) -> Set[str]:
    """Names checked with isinstance(name, dict) — Mapping flavors
    accepted — anywhere in the function. Dominance is not computed (this
    is a lint heuristic: a dict guard anywhere signals the author
    considered the type), but the guard must actually name a mapping
    type; isinstance(x, str) narrowing does NOT license x.get()."""
    guarded: Set[str] = set()
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "isinstance"
                and len(node.args) == 2
                and isinstance(node.args[0], ast.Name)
                and _names_a_mapping_type(node.args[1])):
            guarded.add(node.args[0].id)
    return guarded


@register
class UnguardedWireAccess(Rule):
    name = "wire-unguarded-access"
    severity = "error"
    description = (
        "dict-protocol access (.get()/subscript) on a nested wire value "
        "inside a decode function without an isinstance guard: malformed "
        "YAML/JSON raises AttributeError/TypeError instead of the decode "
        "path's validation error")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, _FUNC_DEFS):
                continue
            if not _DECODE_NAME_RE.match(fn.name):
                continue
            yield from self._check_fn(ctx, fn)

    def _check_fn(self, ctx: ModuleContext,
                  fn: ast.AST) -> Iterator[Finding]:
        guarded = _isinstance_guarded(fn)
        derived: Dict[str, str] = {}  # name -> how it was derived
        # pass 1: collect wire-derived bindings (loop targets over wire
        # reads, and assignments from .get())
        for node in ast.walk(fn):
            if (isinstance(node, ast.For)
                    and isinstance(node.target, ast.Name)
                    and _contains_get_or_subscript(node.iter)):
                derived.setdefault(
                    node.target.id,
                    f"for {node.target.id} in <wire container>")
            elif isinstance(node, ast.Assign) and _is_get_call(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        derived.setdefault(
                            t.id, f"{t.id} = <wire>.get(...)")
        if not derived:
            return
        # pass 2: flag unguarded dict-protocol use of derived names
        for node in ast.walk(fn):
            name = None
            use = None
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.attr in _DICT_METHODS):
                name = node.func.value.id
                use = f".{node.func.attr}()"
            elif (isinstance(node, ast.Subscript)
                  and isinstance(node.value, ast.Name)
                  and isinstance(node.ctx, ast.Load)):
                name = node.value.id
                use = "[...] subscript"
            if name is None or name not in derived or name in guarded:
                continue
            yield self.finding(
                ctx, node,
                f"{use} on wire-derived value {name!r} "
                f"({derived[name]}) in {fn.name!r} without "
                f"isinstance(..., dict) guard — malformed wire input "
                f"raises AttributeError instead of a validation error")
