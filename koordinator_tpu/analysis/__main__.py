"""koordlint CLI.

    python -m koordinator_tpu.analysis [paths...]
        [--baseline FILE] [--write-baseline] [--json] [--sarif]
        [--list-rules] [--guards] [--check-locks] [--jobs N]

Exit codes (the CI contract tests/test_static_analysis.py pins):
    0  no non-baselined, non-suppressed findings
    1  findings reported (or orphan locks under --check-locks)
    2  usage error / unreadable baseline

Default paths: ``koordinator_tpu bench.py`` (the shipped tree). Default
baseline: ``koordlint_baseline.json`` next to the first scanned tree's
repo root (CWD), used only when it exists; pass ``--baseline ''`` to
force a no-baseline run.

``--guards`` dumps the inferred guard map (which attribute is protected
by which lock — see analysis/guards.py) as JSON so drift is reviewable
in diffs; ``--check-locks`` additionally fails when any
``threading.Lock()``/``RLock()`` attribute in the scanned modules guards
nothing (an orphan lock is either dead weight or a guard the map failed
to learn — both deserve a look). ``--sarif`` emits SARIF 2.1.0 for
external CI consumers; ``--jobs`` sizes the per-file worker pool
(KOORDLINT_JOBS env works too; finding order is identical either way).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from koordinator_tpu.analysis.core import (
    all_rules,
    analyze_paths,
    load_baseline,
    write_baseline,
)

DEFAULT_BASELINE = "koordlint_baseline.json"

SARIF_VERSION = "2.1.0"


def to_sarif(findings, rules) -> dict:
    """Findings as a SARIF 2.1.0 log (one run, one driver)."""
    return {
        "version": SARIF_VERSION,
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "runs": [{
            "tool": {"driver": {
                "name": "koordlint",
                "informationUri": ("https://github.com/koordinator-sh/"
                                   "koordinator"),
                "rules": [
                    {"id": name,
                     "shortDescription": {"text": rules[name].description}}
                    for name in sorted(rules)
                ],
            }},
            "results": [
                {"ruleId": f.rule,
                 "level": "error" if f.severity == "error" else "warning",
                 "message": {"text": f.message},
                 "locations": [{"physicalLocation": {
                     "artifactLocation": {"uri": f.path},
                     "region": {"startLine": f.line},
                 }}]}
                for f in findings
            ],
        }],
    }


def _guard_map_for(paths):
    from koordinator_tpu.analysis.guards import (
        build_guard_map,
        collect_facts_for_paths,
    )

    return build_guard_map(collect_facts_for_paths(paths))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m koordinator_tpu.analysis",
        description="koordlint: static analysis for JAX-tracing, "
                    "wire-decode and concurrency invariants")
    ap.add_argument("paths", nargs="*",
                    default=["koordinator_tpu", "bench.py"],
                    help="files/directories to scan "
                         "(default: koordinator_tpu bench.py)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline JSON (default: ./{DEFAULT_BASELINE} "
                         f"if present; '' disables)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write all current findings to the baseline "
                         "file and exit 0")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as a JSON array")
    ap.add_argument("--sarif", action="store_true",
                    help="emit findings as SARIF 2.1.0")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--guards", action="store_true",
                    help="emit the inferred guard map as JSON and exit")
    ap.add_argument("--check-locks", action="store_true",
                    help="with --guards semantics: exit 1 when any "
                         "Lock/RLock attribute guards nothing (orphan)")
    ap.add_argument("--jobs", type=int, default=None,
                    help="per-file worker processes (default: auto; "
                         "KOORDLINT_JOBS env overrides)")
    args = ap.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for name in sorted(rules):
            r = rules[name]
            print(f"{name} [{r.severity}]\n    {r.description}")
        return 0

    missing = [p for p in args.paths if not Path(p).exists()]
    if missing:
        print(f"koordlint: no such path: {', '.join(missing)}",
              file=sys.stderr)
        return 2
    # a path that exists but matches no .py files (a typo'd extensionless
    # file, an empty dir) must not produce a false-clean exit 0
    from koordinator_tpu.analysis.core import iter_python_files

    empty = [p for p in args.paths
             if not any(True for _ in iter_python_files([p]))]
    if empty:
        print(f"koordlint: no Python files under: {', '.join(empty)}",
              file=sys.stderr)
        return 2

    if args.guards or args.check_locks:
        gm = _guard_map_for(args.paths)
        print(json.dumps(gm.to_dict(), indent=2, sort_keys=True))
        if args.check_locks:
            orphans = gm.orphan_locks()
            if orphans:
                for path, d in orphans:
                    print(f"koordlint: orphan lock: {path}:{d.line} "
                          f"{d.owner}.{d.attr} ({d.kind}) guards no field",
                          file=sys.stderr)
                return 1
        return 0

    baseline_path = args.baseline
    if baseline_path is None:
        baseline_path = (DEFAULT_BASELINE
                         if Path(DEFAULT_BASELINE).exists() else "")
    baseline = set()
    if baseline_path and not args.write_baseline:
        try:
            baseline = load_baseline(baseline_path)
        except (ValueError, OSError, KeyError, json.JSONDecodeError) as e:
            print(f"koordlint: bad baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2

    findings = analyze_paths(args.paths, baseline=baseline,
                             jobs=args.jobs)

    if args.write_baseline:
        target = baseline_path or DEFAULT_BASELINE
        write_baseline(target, findings)
        print(f"koordlint: wrote {len(findings)} finding(s) to {target}")
        return 0

    if args.sarif:
        print(json.dumps(to_sarif(findings, rules), indent=2))
    elif args.as_json:
        print(json.dumps([
            {"rule": f.rule, "severity": f.severity, "path": f.path,
             "line": f.line, "message": f.message}
            for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        n_err = sum(1 for f in findings if f.severity == "error")
        n_warn = len(findings) - n_err
        print(f"koordlint: {len(findings)} finding(s) "
              f"({n_err} error, {n_warn} warning) across "
              f"{len(rules)} rules"
              + (f", {len(baseline)} baselined" if baseline else ""))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
