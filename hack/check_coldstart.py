#!/usr/bin/env python
"""Coldstart gate (PR 15): the persistent compile cache must pay for
itself across a crash-restart, without moving a single decision.

Runs the crash-restart scenario (waves pinned to 4, the fused-chain
compile ladder) as a process pair:

  * COLD — no compile-cache dir: the restart pays the full on-demand
    compile ladder before its first bind (the pre-PR-15 world);
  * WARM — KOORD_TPU_COMPILE_CACHE_DIR armed + KOORD_TPU_WARMUP=sync:
    the restart replays the rung index recorded by its own pre-restart
    cycles, XLA compiles disk-served, the first cycle an in-memory
    step-cache HIT.

Asserts (all from the report JSON):

  * binding logs BYTE-IDENTICAL across the pair — the cache is a
    latency lever, never a decision change;
  * zero invariant breaches in both worlds;
  * the warm restart binds its first pod with ZERO steady-state
    recompiles (restart.steady_state_compiles == [0]) and a complete
    warm-up ladder with every valid rung warmed;
  * warm restart-to-first-bind wall-clock strictly below cold. Wall
    clocks on a noisy box can invert at sim scale (the margin is the
    XLA-backend share of the compile, which silicon-scale programs
    dominate but ~1s sim programs do not), so a single inversion
    re-measures the pair once before failing.

Usage: check_coldstart.py [--cache-dir DIR] [--retries 1] [--json OUT]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile


def run_crash_restart(env_extra, label):
    """Run the crash-restart scenario (waves pinned to 4) in a fresh
    subprocess under a scrubbed cache env + ``env_extra``. Returns
    (report dict | None, process wall seconds). ONE implementation for
    this gate AND bench.py --coldstart (which imports it), so the
    cold/warm subprocess protocol can never drift between the two."""
    import time

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("KOORD_TPU_COMPILE_CACHE_DIR", None)
    env.pop("KOORD_TPU_WARMUP", None)
    env.update(env_extra)
    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as f:
        out_path = f.name
    try:
        t0 = time.perf_counter()
        proc = subprocess.run(
            [sys.executable, "-m", "koordinator_tpu.sim", "crash-restart",
             "--waves", "4", "--quiet", "--max-breaches", "0",
             "--out", out_path],
            capture_output=True, text=True,
            cwd=os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".."),
            env=env)
        wall = time.perf_counter() - t0
        if proc.returncode != 0:
            print(f"FAIL {label} run exited {proc.returncode}:\n"
                  f"{proc.stderr[-2000:]}", file=sys.stderr)
            return None, wall
        with open(out_path) as f:
            return json.load(f), wall
    finally:
        try:
            os.unlink(out_path)
        except OSError:
            pass


def warm_env(cache_dir):
    return {"KOORD_TPU_COMPILE_CACHE_DIR": cache_dir,
            "KOORD_TPU_WARMUP": "sync"}


def report_restart_wall(rep):
    walls = rep["restart"]["to_first_bind_wall_seconds"]
    return max(walls) if walls else 0.0


def measure_pair(cache_dir):
    cold, _w = run_crash_restart({}, "cold")
    warm, _w = run_crash_restart(warm_env(cache_dir), "warm")
    return cold, warm


def main(argv) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cache-dir", default=None,
                    help="compile-cache dir for the warm run (default: "
                    "a fresh temp dir)")
    ap.add_argument("--retries", type=int, default=1,
                    help="wall-clock inversion re-measures this many "
                    "times before failing (default 1)")
    ap.add_argument("--json", default=None,
                    help="write the pair summary JSON here")
    args = ap.parse_args(argv)

    restart_wall = report_restart_wall

    def validate(cold, warm):
        """The STRUCTURAL contract — checked on every measured pair,
        retries included (a re-measured pair must re-prove everything,
        not just the wall ordering)."""
        errors = []
        if cold["binding_log_sha256"] != warm["binding_log_sha256"]:
            errors.append(
                f"binding logs differ: cold "
                f"{cold['binding_log_sha256'][:16]} vs warm "
                f"{warm['binding_log_sha256'][:16]} — the compile "
                f"cache moved a decision")
        for label, rep in (("cold", cold), ("warm", warm)):
            if rep["invariant_breaches"]:
                errors.append(
                    f"{label} run had {rep['invariant_breaches']} "
                    f"invariant breaches")
        wu = warm.get("warmup", {})
        if not wu.get("complete"):
            errors.append("warm run's warm-up ladder did not complete")
        elif wu.get("failed", 0) or wu.get("invalidated", 0):
            errors.append(f"warm-up rungs failed/invalidated: {wu}")
        elif (wu.get("warmed", 0) + wu.get("built", 0)
              != wu.get("rungs", -1)):
            errors.append(f"not every recorded rung was warmed: {wu}")
        steady = warm["restart"].get("steady_state_compiles", [])
        if steady != [0] * warm["restart"]["count"]:
            errors.append(
                f"warm restart compiled in steady state: {steady} — "
                f"the first bind must be an in-memory step-cache hit")
        return errors

    cache_dir = args.cache_dir or tempfile.mkdtemp(prefix="koord_cc_")
    tries = 0
    while True:
        cold, warm = measure_pair(cache_dir)
        if cold is None or warm is None:
            return 1
        errors = validate(cold, warm)
        cold_wall, warm_wall = restart_wall(cold), restart_wall(warm)
        if errors or warm_wall < cold_wall or tries >= args.retries:
            break
        # structural contract held but the wall ordering inverted: a
        # noisy-box artifact at sim scale — re-measure the whole pair
        tries += 1
        print(f"coldstart: wall inversion (cold {cold_wall:.2f}s vs "
              f"warm {warm_wall:.2f}s); re-measuring pair "
              f"({tries}/{args.retries})", file=sys.stderr)
        import shutil

        shutil.rmtree(cache_dir, ignore_errors=True)
        os.makedirs(cache_dir, exist_ok=True)
    if not errors and warm_wall >= cold_wall:
        errors.append(
            f"warm restart-to-first-bind wall ({warm_wall:.2f}s) not "
            f"below cold ({cold_wall:.2f}s) after {tries} retries")
    wu = warm.get("warmup", {})
    steady = warm["restart"].get("steady_state_compiles", [])

    summary = {
        "cold_restart_wall_seconds": cold_wall,
        "warm_restart_wall_seconds": warm_wall,
        "cold_restart_compile_seconds":
            cold["restart"]["restart_wall_compile_seconds"],
        "warm_restart_compile_seconds":
            warm["restart"]["restart_wall_compile_seconds"],
        "warm_restart_pack_seconds":
            warm["restart"]["restart_wall_pack_seconds"],
        "warmup": wu,
        "steady_state_compiles": steady,
        "binding_log_sha256": cold["binding_log_sha256"],
        "pair_deterministic":
            cold["binding_log_sha256"] == warm["binding_log_sha256"],
    }
    if args.json:
        with open(args.json, "w") as f:
            json.dump(summary, f, indent=2, sort_keys=True)
            f.write("\n")
    if errors:
        for e in errors:
            print(f"FAIL coldstart: {e}", file=sys.stderr)
        return 1
    print(f"ok coldstart: logs identical "
          f"({cold['binding_log_sha256'][:16]}…), warm restart "
          f"{warm_wall:.2f}s < cold {cold_wall:.2f}s, warm-up "
          f"{wu.get('warmed', 0)}+{wu.get('built', 0)}/{wu.get('rungs', 0)}"
          f" rungs, 0 steady-state recompiles", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
