#!/usr/bin/env python
"""Micro-benchmarks mirroring the reference's three go-bench harnesses
(SURVEY.md section 4): reservation snapshot restore
(transformer_benchmark_test.go), quota-tree update
(group_quota_manager_test.go), and cpuset accumulator take
(cpu_accumulator_test.go). The reference records no numbers — these
harnesses exist so regressions in the host-side hot paths are measurable
here too. Prints one JSON line per bench on stdout.

Usage: PYTHONPATH=. python hack/microbench.py [--iters N]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def _bench(name: str, fn, iters: int, unit_count: int, unit: str) -> None:
    fn()  # warm
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    med = float(np.median(times))
    print(json.dumps({
        "bench": name,
        "median_ms": round(med * 1000, 3),
        "per_sec": round(unit_count / med, 1),
        "unit": unit,
        "iters": iters,
    }))


def bench_reservation_restore(iters: int) -> None:
    """Reservation snapshot restore: nominate against a cache of available
    reservations (transformer restore-prep analog)."""
    from koordinator_tpu.api.objects import (
        ObjectMeta,
        Pod,
        PodSpec,
        Reservation,
        ReservationOwner,
    )
    from koordinator_tpu.api.resources import ResourceList
    from koordinator_tpu.client.store import KIND_RESERVATION, ObjectStore
    from koordinator_tpu.scheduler.plugins.reservation import (
        ReservationPlugin,
    )

    GIB = 1024**3
    store = ObjectStore()
    plugin = ReservationPlugin()
    plugin.register(store)
    n_res = 500
    for i in range(n_res):
        res = Reservation(
            meta=ObjectMeta(name=f"res-{i}", namespace="",
                            creation_timestamp=1.0),
            template=PodSpec(requests=ResourceList.of(cpu=2000,
                                                      memory=4 * GIB)),
            owners=[ReservationOwner(label_selector={"app": f"a{i % 50}"})],
            node_name=f"node-{i % 100}",
            phase="Available",
        )
        res.allocatable = res.template.requests.copy()
        store.add(KIND_RESERVATION, res)
    pods = [
        Pod(meta=ObjectMeta(name=f"p-{j}", uid=f"p-{j}",
                            labels={"app": f"a{j % 50}"}),
            spec=PodSpec(requests=ResourceList.of(cpu=1000, memory=GIB)))
        for j in range(200)
    ]

    def run():
        hits = 0
        for pod in pods:
            if plugin.nominate(pod, now=10.0) is not None:
                hits += 1
        assert hits > 0

    _bench("reservation_nominate_200pods_500res", run, iters, 200, "pods")


def bench_quota_tree(iters: int) -> None:
    """Quota-tree rebuild + water-filling runtime computation (the
    GroupQuotaManager update path)."""
    from koordinator_tpu.api.objects import (
        LABEL_QUOTA_PARENT,
        ElasticQuota,
        ObjectMeta,
    )
    from koordinator_tpu.api.resources import NUM_RESOURCES, ResourceList
    from koordinator_tpu.ops.quota import (
        build_quota_tree,
        compute_runtime_quotas,
    )

    GIB = 1024**3
    quotas = []
    for p in range(10):
        quotas.append(ElasticQuota(
            meta=ObjectMeta(name=f"parent-{p}", namespace=""),
            min=ResourceList.of(cpu=20_000, memory=64 * GIB),
            max=ResourceList.of(cpu=100_000, memory=256 * GIB)))
        for c in range(20):
            q = ElasticQuota(
                meta=ObjectMeta(name=f"q-{p}-{c}", namespace=""),
                min=ResourceList.of(cpu=1000, memory=2 * GIB),
                max=ResourceList.of(cpu=50_000, memory=128 * GIB))
            q.meta.labels[LABEL_QUOTA_PARENT] = f"parent-{p}"
            quotas.append(q)
    rng = np.random.default_rng(3)
    req = {
        f"q-{p}-{c}": np.asarray(
            rng.integers(0, 8000, NUM_RESOURCES), np.float32)
        for p in range(10) for c in range(20)
    }
    total = np.full(NUM_RESOURCES, 1e6, np.float32)

    def run():
        tree = build_quota_tree(quotas, req, {})
        runtime = compute_runtime_quotas(tree, total)
        assert runtime.shape[0] == len(tree.names)

    _bench("quota_tree_update_210groups", run, iters, 210, "groups")


def bench_cpu_accumulator(iters: int) -> None:
    """cpuset accumulator take: sorted free-core allocation on a 2-socket
    topology (cpu_accumulator.go take semantics)."""
    from koordinator_tpu.scheduler.cpu_topology import (
        CPUAllocationState,
        CPUTopology,
        FULL_PCPUS,
        take_cpus,
    )

    topo = CPUTopology.build(num_sockets=2, nodes_per_socket=1,
                             cores_per_node=32, threads_per_core=2)

    def run():
        state = CPUAllocationState(topo)
        got = 0
        for _ in range(30):
            cpus = take_cpus(state, num_cpus=4, bind_policy=FULL_PCPUS)
            if cpus:
                got += len(cpus)
        assert got > 0

    _bench("cpu_accumulator_take_30x4cpus_128cpu_node", run, iters, 30,
           "takes")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=20)
    args = ap.parse_args()
    for fn in (bench_reservation_restore, bench_quota_tree,
               bench_cpu_accumulator):
        try:
            fn(args.iters)
        except Exception as e:  # keep the other benches running
            print(f"{fn.__name__}: FAILED {e!r}", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
