"""On-chip step profiling: isolate H2D transfer vs kernel math.

Runs the headline 10k x 5k fixture through the pallas full-chain step in
three modes and prints per-mode medians:
  numpy   — inputs as numpy arrays (what bench.py timed through round 4):
            every call pays host->device transfer of the whole snapshot
  device  — inputs jax.device_put once; calls consume device arrays
  device+nobal — device-resident AND balanced-allocation score compiled out
            (semantics change: diagnostic only, not a bench configuration)

Usage: python hack/profile_step.py [--pods P] [--nodes N] [--iters K]
"""
from __future__ import annotations

import argparse
import sys
import time

import numpy as np


def log(m):
    print(m, file=sys.stderr, flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pods", type=int, default=10_000)
    ap.add_argument("--nodes", type=int, default=5_000)
    ap.add_argument("--iters", type=int, default=20)
    a = ap.parse_args()

    import jax

    from koordinator_tpu.models.full_chain import build_best_full_chain_step
    from koordinator_tpu.ops.loadaware import LoadAwareArgs
    from koordinator_tpu.scheduler.snapshot import (
        build_full_chain_inputs,
        reduce_to_active_axes,
    )
    from koordinator_tpu.testing import synth_full_cluster

    la = LoadAwareArgs()
    log(f"devices: {jax.devices()}")
    cluster, state = synth_full_cluster(
        a.nodes, a.pods, seed=42,
        num_quotas=max(8, a.pods // 100), num_gangs=max(4, a.pods // 50))
    fc, pods, nodes, tree, gang_index, ng, ngroups = build_full_chain_inputs(
        state, la)
    fc, active = reduce_to_active_axes(fc)

    def bench(step, inputs, label):
        out = step(inputs)
        jax.block_until_ready(out[0])
        times = []
        for _ in range(a.iters):
            t0 = time.perf_counter()
            out = step(inputs)
            jax.block_until_ready(out[0])
            times.append(time.perf_counter() - t0)
        med = float(np.median(times))
        log(f"{label:16s} median {med*1000:8.2f} ms  "
            f"({pods.num_valid/med:,.0f} pods/s)")
        return np.asarray(out[0]), med

    step = build_best_full_chain_step(la, ng, ngroups, active_axes=active)
    chosen_np, t_np = bench(step, fc, "numpy-inputs")

    fc_dev = jax.tree.map(jax.device_put, fc)
    jax.block_until_ready(fc_dev.base.allocatable)
    chosen_dev, t_dev = bench(step, fc_dev, "device-resident")
    assert (chosen_np == chosen_dev).all(), "device-resident bindings differ!"

    # diagnostic: balanced-allocation compiled out (forces bal_idx = (-1,-1))
    import koordinator_tpu.models.full_chain as fcmod

    orig = fcmod.resolve_balance_idx
    fcmod.resolve_balance_idx = lambda active_axes: (-1, -1)
    try:
        step2 = build_best_full_chain_step(la, ng, ngroups,
                                           active_axes=active)
        bench(step2, fc_dev, "device+nobal")
    finally:
        fcmod.resolve_balance_idx = orig
    log(f"h2d share of numpy-input step: {(t_np - t_dev)*1000:.2f} ms")


if __name__ == "__main__":
    main()
