#!/usr/bin/env python
"""Demotion-budget gate (PR 14): the fused-wave burn-down must not rot.

Runs a seeded soak-derived koordsim scenario through the REAL Scheduler
and asserts the demoted-cycle fraction stays within budget (the pre-PR-14
soak demoted 61.1% of cycles — claim-pods 478 / ladder 130 / sidecar 3,
CHURN_r04/r05; post burn-down the only legitimate demotions left are the
degradation ladder's fault responses, the sidecar, non-expressible
transformers and claim entanglement, none of which this scenario
triggers at scale). A future PR reintroducing a data-driven demotion
branch fails here fast, with the per-reason profile printed for the
post-mortem.

Usage: check_demotion_budget.py [--budget 0.15] [--cycles 150]
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget", type=float, default=0.15,
                    help="max fraction of cycles demoted (default 0.15)")
    ap.add_argument("--cycles", type=int, default=150,
                    help="soak-scenario cycle budget for the gate run")
    args = ap.parse_args(argv)

    import dataclasses
    import os

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from koordinator_tpu.sim.harness import run_scenario
    from koordinator_tpu.sim.scenarios import SCENARIOS

    sc = dataclasses.replace(SCENARIOS["soak"], cycles=args.cycles)
    report = run_scenario(sc).to_dict()
    demo = report["demotions"]
    frac = demo["fraction_of_cycles"]
    line = (f"demotion budget: {demo['cycles_demoted']}/{report['cycles']} "
            f"cycles demoted ({frac:.1%}) vs budget {args.budget:.0%}; "
            f"profile {json.dumps(demo['by_reason'])}")
    if frac > args.budget:
        print(f"FAIL {line}", file=sys.stderr)
        return 1
    print(f"ok {line}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
