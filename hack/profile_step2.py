"""Second-pass on-chip profiling with full distributions.

Prints every iteration time for: numpy-inputs dispatch, device-resident
(same VOL variant forced), and the bal-less diagnostic, plus a pure
replay of bench.py's exact timing pattern.
"""
from __future__ import annotations

import argparse
import sys
import time

import numpy as np


def log(m):
    print(m, file=sys.stderr, flush=True)


def dist(times):
    a = np.asarray(times) * 1000
    return (f"med {np.median(a):7.2f}  min {a.min():7.2f}  "
            f"max {a.max():7.2f}  all " +
            " ".join(f"{x:.0f}" for x in a))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pods", type=int, default=10_000)
    ap.add_argument("--nodes", type=int, default=5_000)
    ap.add_argument("--iters", type=int, default=15)
    a = ap.parse_args()

    import jax

    from koordinator_tpu.models.full_chain import build_best_full_chain_step
    from koordinator_tpu.ops.loadaware import LoadAwareArgs
    from koordinator_tpu.ops.pallas_full_chain import (
        build_pallas_full_chain_step,
    )
    from koordinator_tpu.scheduler.snapshot import (
        build_full_chain_inputs,
        reduce_to_active_axes,
    )
    from koordinator_tpu.testing import synth_full_cluster

    la = LoadAwareArgs()
    log(f"devices: {jax.devices()}")
    cluster, state = synth_full_cluster(
        a.nodes, a.pods, seed=42,
        num_quotas=max(8, a.pods // 100), num_gangs=max(4, a.pods // 50))
    fc, pods, nodes, tree, gang_index, ng, ngroups = build_full_chain_inputs(
        state, la)
    fc, active = reduce_to_active_axes(fc)

    def bench(step, inputs, label):
        out = step(inputs)
        jax.block_until_ready(out[0])
        times = []
        for _ in range(a.iters):
            t0 = time.perf_counter()
            out = step(inputs)
            jax.block_until_ready(out[0])
            times.append(time.perf_counter() - t0)
        log(f"{label:18s} {dist(times)}")
        return np.asarray(out[0])

    # exact pallas variant, volume machinery OFF (the bench headline path)
    pstep = build_pallas_full_chain_step(la, ng, ngroups, active_axes=active,
                                         enable_volumes=False)
    c1 = bench(pstep, fc, "pallas-novol-numpy")
    fc_dev = jax.tree.map(jax.device_put, fc)
    jax.block_until_ready(fc_dev.base.allocatable)
    c2 = bench(pstep, fc_dev, "pallas-novol-dev")
    assert (c1 == c2).all()

    # dispatch wrapper as bench.py uses it
    dstep = build_best_full_chain_step(la, ng, ngroups, active_axes=active)
    bench(dstep, fc, "dispatch-numpy")
    log(f"dispatch backend: {dstep.last_backend}")


if __name__ == "__main__":
    main()
