#!/usr/bin/env python
"""README metric-catalog drift gate (koordwatch satellite).

Every metric name registered in code must appear in the README's
"### Metric catalog" table, and every non-wildcard catalog row must
correspond to a registered metric — so the catalog can never rot again.

Code side: a plain AST scan (koordlint discipline — no imports of the
scanned code, no jax) over ``koordinator_tpu/`` for
``<registry>.counter("koord...") / .gauge(...) / .histogram(...)`` calls
whose first argument is a string literal starting with ``koord`` (test
registries use short names and are excluded by that prefix and by path).

README side: the first backtick-quoted token of each table row's first
cell. A token ending in ``*`` is a prefix wildcard (the koordlet row
covers its long tail of per-strategy gauges/counters).

Exit 0 clean; exit 1 with the drift diff otherwise.
"""

from __future__ import annotations

import ast
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
REGISTER_METHODS = {"counter", "gauge", "histogram"}


def registered_names() -> set:
    names = set()
    for path in sorted((REPO / "koordinator_tpu").rglob("*.py")):
        if "_pb2" in path.name:
            continue
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not (isinstance(func, ast.Attribute)
                    and func.attr in REGISTER_METHODS):
                continue
            if not node.args:
                continue
            arg = node.args[0]
            if (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                    and arg.value.startswith("koord")):
                names.add(arg.value)
    return names


def catalog_names() -> set:
    readme = (REPO / "README.md").read_text()
    m = re.search(r"### Metric catalog\n(.*?)\n###", readme, re.S)
    if m is None:
        m = re.search(r"### Metric catalog\n(.*?)\n## ", readme, re.S)
    if m is None:
        print("check_metrics_catalog: no '### Metric catalog' section "
              "in README.md", file=sys.stderr)
        sys.exit(1)
    names = set()
    for line in m.group(1).splitlines():
        if not line.startswith("|"):
            continue
        cell = line.split("|")[1].strip()
        token = re.match(r"`([^`]+)`", cell)
        if token:
            names.add(token.group(1))
    return names


def main() -> int:
    code = registered_names()
    catalog = catalog_names()
    wildcards = {c[:-1] for c in catalog if c.endswith("*")}
    exact = {c for c in catalog if not c.endswith("*")}

    def covered(name: str) -> bool:
        return name in exact or any(name.startswith(w) for w in wildcards)

    missing_from_readme = sorted(n for n in code if not covered(n))
    stale_in_readme = sorted(n for n in exact if n not in code)
    if missing_from_readme:
        print("metrics registered in code but MISSING from the README "
              "metric catalog:", file=sys.stderr)
        for n in missing_from_readme:
            print(f"  {n}", file=sys.stderr)
    if stale_in_readme:
        print("README metric-catalog rows with no registration in code:",
              file=sys.stderr)
        for n in stale_in_readme:
            print(f"  {n}", file=sys.stderr)
    if missing_from_readme or stale_in_readme:
        return 1
    print(f"metric catalog in sync: {len(code)} registered names, "
          f"{len(exact)} catalog rows + {len(wildcards)} wildcard(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
