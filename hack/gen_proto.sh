#!/usr/bin/env bash
# Regenerate protobuf message classes (analog of reference hack/generate-runtime.sh).
# grpc service stubs are hand-wired (no grpc_tools in the image), so only
# --python_out is needed.
set -euo pipefail
cd "$(dirname "$0")/../koordinator_tpu/runtimeproxy"
protoc --python_out=. -I. api.proto
protoc --python_out=. -I. cri.proto
cd ../koordlet
protoc --python_out=. -I. nri.proto
cd ../scheduler
protoc --python_out=. -I. sidecar.proto
echo "generated api_pb2.py + cri_pb2.py + nri_pb2.py + sidecar_pb2.py"
