#!/usr/bin/env python
"""koordrace gate: the deterministic interleaving race harness, run at
two fixed preemption seeds, plus the static/dynamic agreement check.

Per seed (sim/racecheck.py):

  * the smoke scenario runs with pipeline overlap, an armed (never
    firing) dispatch watchdog, and background warm-up, under seeded
    thread preemption at every guarded-field touchpoint from the static
    guard map;
  * every touchpoint is witness-checked (guard lock actually held);
  * canonical-lock-order (obs/lockorder.py) acquisitions are checked
    at runtime;
  * scraper threads hammer /metrics and /debug/timeline the whole run —
    every response must parse (no torn exposition).

Across the pair:

  * the binding logs must be BYTE-IDENTICAL (sha256): preemption shakes
    the schedule, never the decisions.

Agreement:

  * the static race rules (unguarded-shared-field, lock-order-inversion,
    blocking-call-under-lock) must report ZERO findings over the shipped
    tree, and any runtime witness is cross-checked against the static
    map — a dynamic-only witness means the analyzer has a blind spot and
    fails the gate on its own line.

Usage: check_races.py [--cycles N] [--seeds A,B] [--json OUT]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# silence the accelerator probe chatter before jax import
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

RACE_RULES = ("unguarded-shared-field", "lock-order-inversion",
              "blocking-call-under-lock")


def static_race_findings():
    """The static half, in-process: the three race rules over the
    shipped tree, no baseline."""
    from koordinator_tpu.analysis.core import analyze_paths

    findings = analyze_paths(["koordinator_tpu", "bench.py"])
    return [f for f in findings if f.rule in RACE_RULES]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="deterministic interleaving race gate")
    ap.add_argument("--cycles", type=int, default=24)
    ap.add_argument("--seeds", default="101,202",
                    help="comma-separated preemption seeds (two fixed "
                         "seeds in the lint gate)")
    ap.add_argument("--json", dest="json_out", default=None,
                    help="write the per-seed reports as JSON")
    args = ap.parse_args(argv)
    seeds = [int(s) for s in args.seeds.split(",") if s.strip()]

    from koordinator_tpu.sim.racecheck import run_racecheck

    failures = []
    reports = []
    for seed in seeds:
        rep = run_racecheck(preempt_seed=seed, cycles=args.cycles)
        reports.append(rep)
        print(f"check_races: seed={seed} bindings={rep.bindings} "
              f"sha={rep.binding_log_sha256[:12]} touches={rep.touches} "
              f"preemptions={rep.preemptions} scrapes={rep.scrapes} "
              f"witnesses={len(rep.witnesses)} "
              f"order_violations={len(rep.order_violations)} "
              f"scrape_errors={len(rep.scrape_errors)}")
        for w in rep.witnesses[:10]:
            failures.append(
                f"seed {seed}: unguarded touch {w['path']}:{w['line']} "
                f"{w['owner']}.{w['field']} (guard {w['guard']}, "
                f"thread {w['thread']})")
        for v in rep.order_violations[:10]:
            failures.append(
                f"seed {seed}: lock-order inversion: acquired "
                f"{v['acquired']} while holding {v['held']} "
                f"(thread {v['thread']})")
        for e in rep.scrape_errors[:10]:
            failures.append(f"seed {seed}: torn scrape: {e}")
        if rep.touches == 0:
            failures.append(
                f"seed {seed}: zero touchpoints observed — the harness "
                f"is not instrumenting (guard map empty or trace dead)")

    shas = {r.binding_log_sha256 for r in reports}
    if len(shas) > 1:
        failures.append(
            "binding log diverged across preemption seeds: "
            + ", ".join(f"seed {r.preempt_seed}={r.binding_log_sha256[:12]}"
                        for r in reports))

    # static/dynamic agreement
    static = static_race_findings()
    for f in static[:10]:
        failures.append(
            f"static race finding (must be empty): {f.path}:{f.line} "
            f"[{f.rule}] {f.message}")
    static_sites = {(f.path, f.line) for f in static}
    for rep in reports:
        for w in rep.witnesses:
            if (w["path"], w["line"]) not in static_sites:
                failures.append(
                    f"DYNAMIC-ONLY witness (analyzer blind spot): "
                    f"{w['path']}:{w['line']} {w['owner']}.{w['field']}")

    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump([r.to_dict() for r in reports], f, indent=2,
                      sort_keys=True)

    if failures:
        print("check_races: FAIL", file=sys.stderr)
        for msg in failures:
            print(f"  {msg}", file=sys.stderr)
        return 1
    print(f"check_races: OK ({len(seeds)} seeds, binding log "
          f"{reports[0].binding_log_sha256[:12]} byte-stable)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
