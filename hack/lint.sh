#!/usr/bin/env bash
# Repo lint gate: koordlint (AST static analysis, see README "Static
# analysis") + a bytecode-compile sweep + the koordtrace JSONL schema pin.
# Mirrors what tier-1 enforces via tests/test_static_analysis.py and
# tests/test_obs.py so it can run pre-push without pytest.
set -euo pipefail

cd "$(dirname "$0")/.."

echo "== koordlint =="
python -m koordinator_tpu.analysis koordinator_tpu bench.py

echo "== koordlint guard map + orphan-lock self-check =="
# the whole-program lock-discipline pass (analysis/guards.py): dumps the
# inferred guard map and fails on any Lock/RLock attribute that guards
# nothing — every shipped lock must earn its place in the map (or carry
# a `# koordlint: guards(<resource>)` declaration)
python -m koordinator_tpu.analysis --guards --check-locks koordinator_tpu \
    > /dev/null

echo "== compileall =="
python -m compileall -q koordinator_tpu bench.py tests hack/microbench.py \
    hack/check_metrics_catalog.py hack/check_races.py

echo "== serial-vs-pipelined + fused-wave + explain + mesh cycle parity =="
# same store fixture through the strictly serial path, the CyclePipeline,
# AND the fused multi-wave path at K in {1,2,4,8}: bindings, failure sets
# and PodScheduled conditions must be byte-identical — a fused-K cycle is
# K sequential single-round cycles (tier-1 runs the same fixtures via
# tests/test_cycle_pipeline.py and tests/test_fused_waves.py; the
# readback-in-wave-body rule above keeps the wave kernels device-pure).
# Also gates koordexplain: the kernel-counts formatter must reproduce the
# legacy diagnose messages string-for-string, and the pipeline/fused
# parity properties must hold with KOORD_TPU_EXPLAIN=counts enabled.
# Also gates the mesh-backed dispatch (KOORD_TPU_MESH): the production
# cycle sharded over 1/2/4/8-device meshes — serial, fused K=4, and with
# explain=counts on top — must be byte-identical to single-device (the
# harness forces the 8-way virtual CPU device split itself).
# Also gates the overlapped wave replay (KOORD_TPU_REPLAY_OVERLAP):
# run_replay_overlap_parity diffs the chained in-flight replay against
# the serial-replay twin at K in {1,2,4,8}; the env pin below makes the
# fused-wave + mesh gates run WITH overlap enabled (both worlds), so
# every parity property above holds under the overlap architecture too.
# Also gates koordcolo (colo/): run_colo_parity runs the device
# control-plane pass (slo-controller batch/mid overcommit + the
# elastic-quota runtime fold as ONE jitted program over the shared
# DeviceSnapshot) against the retained host oracles — batch/mid
# allocatable vectors, degraded-node sets, runtime-quota matrices,
# revoke-victim lists (order included) and binding logs must be
# decision-identical at single-device and mesh 1/2/4/8.
# PR 15: the pack-overlap parity gates ride this run (overlap is
# default-on, and run_pack_overlap_parity diffs the twin at the
# ScheduleInputs level), AND the whole suite runs with the persistent
# compile cache armed — every parity property must hold with on-disk
# executables serving the deserialized side. The warm-up ladder is
# pinned OFF here: the suite builds dozens of differently-configured
# schedulers in one process and each would redundantly replay the
# shared rung index; the ladder has its own gate (check_coldstart.py).
_KOORD_CC_DIR="$(mktemp -d)"
KOORD_TPU_REPLAY_OVERLAP=1 KOORD_TPU_COMPILE_CACHE_DIR="$_KOORD_CC_DIR" \
    KOORD_TPU_WARMUP=off JAX_PLATFORMS=cpu \
    python -m koordinator_tpu.scheduler.pipeline_parity
rm -rf "$_KOORD_CC_DIR"

echo "== obs trace schema (golden fixture) =="
# the CLI exits non-zero on any schema drift against the checked-in trace;
# a deliberate format change must regenerate the fixture AND bump
# TRACE_SCHEMA_VERSION in koordinator_tpu/obs/__init__.py
python -m koordinator_tpu.obs tests/fixtures/trace_golden.jsonl > /dev/null

echo "== flight-recorder bundle schema (golden fixture) =="
# same pin for the koordexplain flight recorder (obs/flight.py): schema
# drift against the checked-in bundle must be a conscious
# FLIGHT_SCHEMA_VERSION bump + fixture regeneration
python -m koordinator_tpu.obs flight tests/fixtures/flight_golden.jsonl > /dev/null

echo "== koordwatch timeline bundle schema (golden fixture) =="
# the koordwatch device-timeline JSONL (obs/timeline.py, the
# /debug/timeline body): drift must be a conscious
# TIMELINE_SCHEMA_VERSION bump + fixture regeneration
python -m koordinator_tpu.obs timeline tests/fixtures/timeline_golden.jsonl > /dev/null

echo "== koordwatch slo bundle schema (golden fixture) =="
# the koordwatch SLO registry JSONL (obs/slo.py, the /debug/slo body)
python -m koordinator_tpu.obs slo tests/fixtures/slo_golden.jsonl > /dev/null

echo "== README metric-catalog drift gate =="
# every metric name registered in code must appear in the README metric
# catalog and vice versa (hack/check_metrics_catalog.py) — the catalog
# can never rot again
python hack/check_metrics_catalog.py > /dev/null

echo "== demotion-budget gate (fused-wave burn-down, PR 14) =="
# the soak-derived seeded scenario through the REAL Scheduler: the
# demoted-cycle fraction must stay <= 15% (pre-PR-14 soak demoted 61.1%
# of cycles, CHURN_r04/r05 — claim-pods/reservations/prod/transformer
# are carried device state now). A PR reintroducing a data-driven
# demotion branch fails here fast, with the per-reason profile printed.
KOORD_TPU_REPLAY_OVERLAP=1 JAX_PLATFORMS=cpu \
    python hack/check_demotion_budget.py --budget 0.15 --cycles 150

echo "== koordsim seeded smoke scenario (determinism + invariants) =="
# the fixed-seed smoke scenario through the REAL Scheduler (~50 cycles:
# Poisson churn, a gang storm cadence, a node drain, metric flips, and a
# dispatch-fault burst that demotes the degradation ladder to the host
# fallback and back). --check-determinism runs it TWICE and requires a
# byte-identical binding log; --max-breaches 0 fails the gate on ANY
# store-level invariant breach (koordinator_tpu/sim/invariants.py). This
# keeps the gate structural — wall-clock numbers stay in bench.py.
# overlap pinned on: the byte-stability of the seeded scenario must hold
# under the overlapped-replay architecture (decisions are parity-gated
# identical, so the binding log cannot move)
KOORD_TPU_REPLAY_OVERLAP=1 JAX_PLATFORMS=cpu python -m koordinator_tpu.sim smoke \
    --check-determinism --max-breaches 0 --quiet > /dev/null

echo "== koordrace deterministic interleaving gate (two fixed seeds) =="
# the dynamic half of the lock-discipline pass (sim/racecheck.py): the
# smoke scenario with pipeline overlap, an armed dispatch watchdog and
# background warm-up, under seeded thread preemption at every
# guarded-field touchpoint from the static guard map. Two fixed
# preemption seeds; binding logs must be byte-identical across them,
# with zero unguarded-touch witnesses, zero canonical-lock-order
# inversions, zero torn /metrics or /debug/timeline scrapes, and
# static/dynamic agreement (a runtime witness the analyzer missed is
# its own failure class).
python hack/check_races.py

echo "== koordsim crash-restart scenario (recovery determinism + invariants) =="
# koordguard's crash-restart gate: the scheduler is torn down mid-run
# (device state, step caches, pack memo dropped; its store watches
# severed) and rebuilt against the surviving store. Run TWICE with
# --check-determinism: the binding logs must be byte-identical across
# the restart boundary, with zero invariant breaches (the double-booking
# and gang checks see both sides of the boundary every cycle). The
# restart-to-first-bind SLO verdict rides the report JSON; bench.py
# --churn fault-ladder is the citable wall-clock pair.
KOORD_TPU_REPLAY_OVERLAP=1 JAX_PLATFORMS=cpu python -m koordinator_tpu.sim crash-restart \
    --check-determinism --max-breaches 0 --quiet > /dev/null

echo "== coldstart gate (persistent compile cache + warm-up ladder) =="
# PR 15: the crash-restart scenario as a cold/warm process pair — cold
# pays the full on-demand compile ladder at restart, warm replays the
# recorded rung index against the persistent cache (KOORD_TPU_WARMUP=
# sync). Binding logs must be byte-identical, the warm restart must
# bind its first pod with ZERO steady-state recompiles, and the warm
# restart-to-first-bind wall must be strictly below cold (one noise
# re-measure allowed; the margin is the XLA-backend share, which real
# silicon-scale programs dominate). bench.py --coldstart is the citable
# number pair (COLDSTART_r01).
python hack/check_coldstart.py

echo "== koordsim overcommit-shift scenario (colo closed loop) =="
# koordcolo's soak gate: a co-located koord-manager recomputes batch/mid
# overcommit on device every cycle while batch-class pods consume it and
# prod-usage surges shrink/restore it mid-run. Run TWICE with
# --check-determinism (byte-identical binding logs) and zero breaches —
# the batch-bind discipline (new binds never exceed the CURRENT
# overcommit) and the metric-write-to-observing-dispatch staleness SLO
# both count as invariants here. The device-vs-host-oracle engine pair
# (logs must also be identical ACROSS engines) is bench.py --colo.
KOORD_TPU_REPLAY_OVERLAP=1 JAX_PLATFORMS=cpu python -m koordinator_tpu.sim overcommit-shift \
    --check-determinism --max-breaches 0 --quiet > /dev/null

echo "lint OK"
