#!/usr/bin/env bash
# Repo lint gate: koordlint (AST static analysis, see README "Static
# analysis") + a bytecode-compile sweep. Mirrors what tier-1 enforces via
# tests/test_static_analysis.py so it can run pre-push without pytest.
set -euo pipefail

cd "$(dirname "$0")/.."

echo "== koordlint =="
python -m koordinator_tpu.analysis koordinator_tpu bench.py

echo "== compileall =="
python -m compileall -q koordinator_tpu bench.py tests hack/microbench.py

echo "lint OK"
