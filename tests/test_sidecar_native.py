"""Compiled-language sidecar client + unavailability fallback.

The sidecar's whole point (SURVEY.md 5.8: keep the reference's Go event loop
untouched, offload the fused kernel over gRPC — the runtime-proxy proto
pattern, /root/reference/apis/runtime/v1alpha1/api.proto:148-171) is that a
NON-Python host consumes ScheduleBatch. native/sidecar_client.cpp is that
host: a C++ binary speaking raw h2c gRPC framing with protoc-generated C++
messages. Its bindings must match the in-process step bit-for-bit over a
real unix socket.

And when the sidecar dies, the cycle must DEGRADE to the in-process path,
never wedge (load_aware.go:144-147 stance for a missing dependency).
"""

import os
import subprocess

import numpy as np
import pytest

from koordinator_tpu.models.full_chain import build_full_chain_step
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.scheduler.sidecar import (
    SidecarClient,
    pack_request,
    schedule_batch_or_fallback,
    serve_sidecar,
    tensor_to_np,
)
from koordinator_tpu.scheduler.snapshot import build_full_chain_inputs
from koordinator_tpu.testing import synth_full_cluster

NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "koordinator_tpu", "native")
CLIENT_BIN = os.path.join(NATIVE_DIR, "koord_sidecar_client")


def _fixture(seed=3, nodes=12, pods=16):
    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(nodes, pods, seed=seed)
    fc, pods_b, nb, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    return args, fc, pods_b, ng, ngroups


def _build_client() -> bool:
    try:
        subprocess.run(
            ["make", "-C", NATIVE_DIR, "-s", "koord_sidecar_client"],
            check=True, capture_output=True, timeout=180)
        return os.path.exists(CLIENT_BIN)
    except (subprocess.SubprocessError, OSError):
        return False


def test_cpp_client_end_to_end(tmp_path):
    """C++ binary -> UDS -> gRPC server -> kernel -> C++ binary: bindings
    identical to the in-process step."""
    pytest.importorskip("grpc")
    if not os.path.exists(CLIENT_BIN) and not _build_client():
        pytest.skip("C++ toolchain/protobuf unavailable")
    args, fc, pods_b, ng, ngroups = _fixture(seed=5)
    direct = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])

    sock = tmp_path / "sidecar.sock"
    server = serve_sidecar(f"unix://{sock}")
    try:
        from koordinator_tpu.scheduler import sidecar_pb2

        req_file = tmp_path / "request.pb"
        resp_file = tmp_path / "response.pb"
        req = pack_request(fc, ng, ngroups, args, snapshot_version=11)
        req_file.write_bytes(req.SerializeToString())
        proc = subprocess.run(
            [CLIENT_BIN, str(sock), str(req_file), str(resp_file), "300"],
            capture_output=True, timeout=300)
        assert proc.returncode == 0, proc.stderr.decode()
        resp = sidecar_pb2.ScheduleBatchResponse()
        resp.ParseFromString(resp_file.read_bytes())
        np.testing.assert_array_equal(tensor_to_np(resp.chosen), direct)
        assert resp.snapshot_version == 11
        assert resp.kernel_seconds > 0
    finally:
        server.stop(0)


def test_cpp_client_rejects_garbage_request(tmp_path):
    if not os.path.exists(CLIENT_BIN) and not _build_client():
        pytest.skip("C++ toolchain/protobuf unavailable")
    req_file = tmp_path / "bad.pb"
    req_file.write_bytes(b"\xff" * 64)
    proc = subprocess.run(
        [CLIENT_BIN, "/nonexistent.sock", str(req_file),
         str(tmp_path / "out.pb"), "5"],
        capture_output=True, timeout=60)
    assert proc.returncode != 0


def test_unreachable_sidecar_degrades_to_in_process(tmp_path):
    """A dead/never-started sidecar must not wedge the cycle: the call
    degrades to the local step and returns identical bindings."""
    grpc = pytest.importorskip("grpc")
    args, fc, pods_b, ng, ngroups = _fixture(seed=7)
    direct = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    client = SidecarClient(f"unix://{tmp_path}/never-started.sock",
                           timeout_seconds=2.0)
    try:
        chosen, requested, quota_used, used_fallback = (
            schedule_batch_or_fallback(client, fc, ng, ngroups, args))
    finally:
        client.close()
    assert used_fallback
    np.testing.assert_array_equal(chosen, direct)


def test_killed_sidecar_degrades_to_in_process(tmp_path):
    """The sidecar answering once then DYING mid-operation degrades too —
    the same client object keeps working through the fallback."""
    grpc = pytest.importorskip("grpc")
    args, fc, pods_b, ng, ngroups = _fixture(seed=9)
    direct = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    address = f"unix://{tmp_path}/sidecar.sock"
    server = serve_sidecar(address)
    client = SidecarClient(address, timeout_seconds=30.0)
    try:
        chosen, _, _, used_fallback = schedule_batch_or_fallback(
            client, fc, ng, ngroups, args)
        assert not used_fallback
        np.testing.assert_array_equal(chosen, direct)
        server.stop(0)  # sidecar dies
        client._timeout = 2.0
        chosen2, _, _, used_fallback2 = schedule_batch_or_fallback(
            client, fc, ng, ngroups, args)
        assert used_fallback2
        np.testing.assert_array_equal(chosen2, direct)
    finally:
        client.close()


def _small_store():
    from koordinator_tpu.api.objects import Node, ObjectMeta, Pod, PodSpec
    from koordinator_tpu.api.resources import ResourceList
    from koordinator_tpu.client.store import KIND_NODE, KIND_POD, ObjectStore

    GIB = 1024**3
    store = ObjectStore()
    for i in range(4):
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name=f"n{i}", namespace=""),
            allocatable=ResourceList.of(cpu=8000, memory=32 * GIB, pods=20)))
    for i in range(6):
        store.add(KIND_POD, Pod(
            meta=ObjectMeta(name=f"p{i}", uid=f"p{i}",
                            creation_timestamp=float(i)),
            spec=PodSpec(requests=ResourceList.of(cpu=1000, memory=GIB))))
    return store


def test_cycle_driver_runs_through_the_sidecar(tmp_path):
    """SURVEY 7 step 6 end-to-end: the cycle driver's kernel pass rides
    the gRPC sidecar; bindings match the in-process driver exactly."""
    pytest.importorskip("grpc")
    from koordinator_tpu.scheduler.cycle import Scheduler

    address = f"unix://{tmp_path}/sidecar.sock"
    server = serve_sidecar(address)
    try:
        remote = Scheduler(_small_store(), sidecar_address=address)
        r_remote = remote.run_cycle(now=1_000_000.0)
        local = Scheduler(_small_store())
        r_local = local.run_cycle(now=1_000_000.0)
        assert remote.sidecar_fallbacks == 0
        assert ({b.pod_key: b.node_name for b in r_remote.bound}
                == {b.pod_key: b.node_name for b in r_local.bound})
        assert len(r_remote.bound) == 6
    finally:
        server.stop(0)


def test_cycle_driver_degrades_when_sidecar_dead(tmp_path):
    pytest.importorskip("grpc")
    from koordinator_tpu.scheduler.cycle import Scheduler

    sched = Scheduler(
        _small_store(),
        sidecar_address=f"unix://{tmp_path}/never-started.sock")
    sched._sidecar_client._timeout = 2.0
    result = sched.run_cycle(now=1_000_000.0)
    assert sched.sidecar_fallbacks == 1
    assert len(result.bound) == 6  # the cycle completed via the local step


def test_explicit_zero_weight_survives_the_wire():
    """A resource axis configured with weight 0 must reach the server as an
    EXPLICIT key (not vanish into 'unset') — consumers iterate the key
    set."""
    from koordinator_tpu.api.resources import ResourceName
    from koordinator_tpu.scheduler.sidecar import unpack_request

    args = LoadAwareArgs(resource_weights={ResourceName.CPU: 2,
                                           ResourceName.MEMORY: 0})
    cluster, state = synth_full_cluster(8, 8, seed=13)
    fc, pods_b, nb, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    fc2, args2 = unpack_request(pack_request(fc, ng, ngroups, args))
    assert args2.resource_weights == {ResourceName.CPU: 2.0,
                                      ResourceName.MEMORY: 0.0}
