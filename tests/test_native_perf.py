"""Native perf binding: build, load, graceful degradation, and (when the kernel
permits) real counter reads."""

import os
import subprocess

import pytest

from koordinator_tpu.native import perf

LIB_DIR = os.path.dirname(os.path.abspath(perf.__file__))


class TestNativePerf:
    def test_library_builds_and_loads(self):
        subprocess.run(["make", "-C", LIB_DIR, "-s"], check=True, timeout=120)
        assert os.path.exists(os.path.join(LIB_DIR, "libkoordperf.so"))
        assert perf._load() is not None

    def test_graceful_degradation(self):
        """open_self either works or returns None — never raises."""
        g = perf.PerfGroup.open_self()
        if g is None:
            assert perf.available() is False
            return
        sample = g.read()
        g.close()
        if sample is None:
            assert perf.available() is False

    @pytest.mark.skipif(not perf.available(), reason="perf_event_open denied")
    def test_real_counters_monotonic(self):
        import math

        g = perf.PerfGroup.open_self()
        assert g is not None
        _ = sum(math.sin(i) for i in range(100_000))
        a = g.read()
        _ = sum(math.sin(i) for i in range(100_000))
        b = g.read()
        g.close()
        assert b[0] > a[0] and b[1] > a[1]
        cycles, instructions = b
        assert 0.05 < cycles / instructions < 20.0

    def test_collector_stays_off_without_perf(self):
        """The CPI collector path must be inert when perf is unavailable."""
        reader = perf.build_cgroup_perf_reader(None) if not perf.available() else "skip"
        if reader == "skip":
            pytest.skip("perf available; covered by real-counter test")
        assert reader is None
