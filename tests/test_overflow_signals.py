"""Encoding-budget overflows are first-class signals: a scheduler metric
rises and the cycle surfaces a specific failure reason (the analog of the
reference surfacing filter failures in pod status), instead of a pod
sitting pending with only a log line to explain it."""

import numpy as np

from koordinator_tpu.api.objects import (
    Node,
    ObjectMeta,
    Pod,
    PodAffinityTerm,
    PodSpec,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import KIND_NODE, KIND_POD, ObjectStore
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.ops.podaffinity import MAX_TERMS
from koordinator_tpu.scheduler.cycle import Scheduler
from koordinator_tpu.scheduler.metrics import (
    ADMISSION_DEGRADED_NODES,
    ENCODING_OVERFLOW_PODS,
)
from koordinator_tpu.scheduler.snapshot import build_full_chain_inputs
from koordinator_tpu.testing import synth_full_cluster

HOST_KEY = "kubernetes.io/hostname"


def test_affinity_overflow_increments_metric_and_reason():
    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(10, MAX_TERMS + 5, seed=9)
    for node in state.nodes:
        node.meta.labels[HOST_KEY] = node.meta.name
    for i, pod in enumerate(state.pending_pods):
        pod.spec.pod_anti_affinity.append(PodAffinityTerm(
            selector={"uniq": f"u{i}"}, topology_key=HOST_KEY))
    before = ENCODING_OVERFLOW_PODS.get(kind="affinity_terms") or 0.0
    fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    after = ENCODING_OVERFLOW_PODS.get(kind="affinity_terms") or 0.0
    assert after - before >= 5
    assert len(pods.unschedulable_reasons) >= 5
    assert all("affinity term budget" in r
               for r in pods.unschedulable_reasons.values())


def test_cycle_reports_overflow_reason_not_no_feasible_node():
    GIB = 1024**3
    store = ObjectStore()
    for i in range(3):
        node = Node(meta=ObjectMeta(name=f"n{i}", namespace=""),
                    allocatable=ResourceList.of(cpu=32000, memory=64 * GIB,
                                                pods=200))
        node.meta.labels[HOST_KEY] = f"n{i}"
        store.add(KIND_NODE, node)
    for i in range(MAX_TERMS + 3):
        pod = Pod(meta=ObjectMeta(name=f"p{i}", uid=f"p{i}",
                                  creation_timestamp=float(i)),
                  spec=PodSpec(requests=ResourceList.of(cpu=100,
                                                        memory=GIB // 8)))
        pod.spec.pod_anti_affinity.append(PodAffinityTerm(
            selector={"uniq": f"u{i}"}, topology_key=HOST_KEY))
        store.add(KIND_POD, pod)
    sched = Scheduler(store)
    result = sched.run_cycle(now=1_000_000.0)
    # the overflowed pods carry the SPECIFIC reason in the failure trail
    reasons = [r for _k, r in sched.extender.error_handlers.failures]
    assert any("affinity term budget" in r for r in reasons)
    # and no victims were drained for them (encoding cuts skip preemption)
    assert result.preempted_victims == []


def test_admission_degradation_gauge():
    args = LoadAwareArgs()
    n_nodes = 30
    cluster, state = synth_full_cluster(n_nodes, n_nodes, seed=3)
    for i, node in enumerate(state.nodes):
        node.meta.labels[HOST_KEY] = node.meta.name
    for i, pod in enumerate(state.pending_pods):
        pod.spec.node_selector[HOST_KEY] = state.nodes[
            i % n_nodes].meta.name
    build_full_chain_inputs(state, args)
    assert (ADMISSION_DEGRADED_NODES.get() or 0.0) > 0
