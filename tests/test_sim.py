"""koordsim + the degradation ladder: the robustness tentpole's gates.

Three layers:

  * DegradationLadder unit mechanics (no jax): retry-once policy, rung
    skipping, exponential re-promotion backoff.
  * Seeded scenarios through the REAL Scheduler: the smoke scenario is
    clean and deterministic; the fault-ladder scenario walks mesh ->
    single-device -> serial -> no-explain -> host-fallback and back
    while binding pods with ZERO invariant breaches (the acceptance
    pin); store-write and sidecar faults degrade without wedging.
  * The 1000-cycle soak rides the `slow` marker (hack/lint.sh runs the
    smoke determinism gate; bench.py --churn runs any scenario as an
    A/B pair).
"""

import dataclasses

import pytest

from koordinator_tpu.scheduler.degrade import (
    LEVEL_FULL,
    LEVEL_HOST_FALLBACK,
    LEVEL_NO_EXPLAIN,
    LEVEL_NO_MESH,
    LEVEL_PARTIAL_MESH,
    LEVEL_SERIAL_WAVES,
    DegradationLadder,
)
from koordinator_tpu.sim import (
    DeviceLossFault,
    Fault,
    FaultPlan,
    InjectedFault,
    Scenario,
    SCENARIOS,
    check_invariants,
)
from koordinator_tpu.sim.harness import ChurnSimulator, run_scenario

ALL_FEATURES = {"mesh": True, "waves": True, "explain": True}
NO_FEATURES = {"mesh": False, "waves": False, "explain": False}


# ---------------------------------------------------------------------------
# ladder unit mechanics
# ---------------------------------------------------------------------------


class TestDegradationLadder:
    def test_retry_once_then_demote_walks_every_rung(self):
        ladder = DegradationLadder(promote_after=4)
        ladder.begin_pass()
        seen = []
        for _ in range(8):  # 2 failures per rung: retry, then demote
            seen.append(ladder.on_failure(ALL_FEATURES, error="boom"))
        assert seen == ["retry", "demoted"] * 4
        assert ladder.level == LEVEL_HOST_FALLBACK
        assert [t["to_level"] for t in ladder.transitions] == [
            LEVEL_NO_MESH, LEVEL_SERIAL_WAVES, LEVEL_NO_EXPLAIN,
            LEVEL_HOST_FALLBACK]
        # the bottom rung has nothing below it
        assert ladder.on_failure(ALL_FEATURES) == "retry"
        assert ladder.on_failure(ALL_FEATURES) == "exhausted"

    def test_meaningless_rungs_are_skipped(self):
        ladder = DegradationLadder(promote_after=4)
        ladder.begin_pass()
        ladder.on_failure(NO_FEATURES)
        assert ladder.on_failure(NO_FEATURES) == "demoted"
        # nothing is configured: the only rung that changes anything is
        # the host fallback
        assert ladder.level == LEVEL_HOST_FALLBACK
        # and the promotion mirror jumps straight back to full (the
        # failing cycle itself does not count clean: 1 + promote_after)
        for _ in range(5):
            ladder.note_cycle()
        assert ladder.level == LEVEL_FULL

    def test_promotion_probes_one_rung_per_window(self):
        ladder = DegradationLadder(promote_after=3)
        ladder.begin_pass()
        for _ in range(8):
            ladder.on_failure(ALL_FEATURES)
        assert ladder.level == LEVEL_HOST_FALLBACK
        levels = []
        for _ in range(13):
            ladder.note_cycle()
            levels.append(ladder.level)
        # note 1 retires the failed cycle (not clean), then every 3 clean
        # cycles climb one rung; the final climb from no-mesh skips the
        # partial-mesh rung (no attributable failure engaged it) straight
        # to full
        assert levels == [5, 5, 5, 4, 4, 4, 3, 3, 3, 2, 2, 2, 0]

    def test_failed_probe_doubles_the_backoff(self):
        ladder = DegradationLadder(promote_after=2, max_promote_after=8)
        ladder.begin_pass()
        for _ in range(8):
            ladder.on_failure(ALL_FEATURES)
        for _ in range(3):  # failed cycle + 2 clean
            ladder.note_cycle()
        assert ladder.level == LEVEL_NO_EXPLAIN  # promoted: probation on
        # the probe fails inside the probation window
        ladder.begin_pass()
        ladder.on_failure(ALL_FEATURES)
        ladder.on_failure(ALL_FEATURES)
        assert ladder.level == LEVEL_HOST_FALLBACK
        assert ladder.promote_after == 4  # doubled
        for _ in range(5):  # failed cycle + 4 clean
            ladder.note_cycle()
        assert ladder.level == LEVEL_NO_EXPLAIN
        # fail the next probe too -> doubled again, capped at 8
        ladder.begin_pass()
        ladder.on_failure(ALL_FEATURES)
        ladder.on_failure(ALL_FEATURES)
        assert ladder.promote_after == 8

    def test_surviving_probation_resets_the_backoff(self):
        ladder = DegradationLadder(promote_after=2, max_promote_after=64)
        ladder.begin_pass()
        for _ in range(4):
            ladder.on_failure(NO_FEATURES)  # -> host fallback
        for _ in range(3):  # failed cycle + 2 clean -> promote to full
            ladder.note_cycle()
        assert ladder.level == LEVEL_FULL
        ladder.begin_pass()
        ladder.on_failure(NO_FEATURES)
        ladder.on_failure(NO_FEATURES)  # probe failed -> backoff doubles
        assert ladder.promote_after == 4
        for _ in range(5):  # failed cycle + 4 clean -> promote to full
            ladder.note_cycle()
        assert ladder.level == LEVEL_FULL
        # probation = base (2) clean cycles, then the backoff resets
        ladder.note_cycle()
        ladder.note_cycle()
        assert ladder.promote_after == 2

    def test_failed_cycle_does_not_count_clean(self):
        ladder = DegradationLadder(promote_after=2)
        ladder.begin_pass()
        ladder.on_failure(NO_FEATURES)
        ladder.on_failure(NO_FEATURES)
        assert ladder.level == LEVEL_HOST_FALLBACK
        ladder.note_cycle()  # the cycle that failed: not clean
        ladder.note_cycle()
        assert ladder.level == LEVEL_HOST_FALLBACK  # only 1 clean so far
        ladder.note_cycle()
        assert ladder.level == LEVEL_FULL

    # ---- koordguard: the partial-mesh rung ---------------------------
    def test_attributable_failure_takes_the_partial_mesh_rung(self):
        ladder = DegradationLadder(promote_after=4)
        ladder.begin_pass()
        feats = dict(ALL_FEATURES, partial_mesh=True)
        assert ladder.on_failure(feats, error="dev 3 down") == "retry"
        assert ladder.on_failure(feats, error="dev 3 down") == "demoted"
        assert ladder.level == LEVEL_PARTIAL_MESH
        # a later ANONYMOUS fault cannot pick survivors: it skips past
        # partial-mesh to no-mesh
        ladder.begin_pass()
        ladder.on_failure(ALL_FEATURES)
        assert ladder.on_failure(ALL_FEATURES) == "demoted"
        assert ladder.level == LEVEL_NO_MESH

    def test_partial_mesh_shrinks_in_place_on_new_loss(self):
        ladder = DegradationLadder(promote_after=4)
        ladder.begin_pass()
        feats = dict(ALL_FEATURES, partial_mesh=True)
        ladder.on_failure(feats)
        ladder.on_failure(feats)
        assert ladder.level == LEVEL_PARTIAL_MESH
        # a NEW attributable loss while already partial sheds more
        # devices at the same rung (same-level transition) instead of
        # dropping the whole mesh
        ladder.begin_pass()
        shrink = dict(feats, partial_mesh_shrink=True)
        ladder.on_failure(shrink)
        assert ladder.on_failure(shrink) == "demoted"
        assert ladder.level == LEVEL_PARTIAL_MESH
        last = ladder.transitions[-1]
        assert (last["from"], last["to"]) == ("partial-mesh",
                                              "partial-mesh")

    def test_promotion_from_partial_mesh_probes_full(self):
        ladder = DegradationLadder(promote_after=2)
        ladder.begin_pass()
        feats = dict(ALL_FEATURES, partial_mesh=True)
        ladder.on_failure(feats)
        ladder.on_failure(feats)
        assert ladder.level == LEVEL_PARTIAL_MESH
        for _ in range(3):  # failed cycle + 2 clean
            ladder.note_cycle()
        # the probe goes straight to FULL (the owner clears its lost set
        # and re-probes the whole configured mesh)
        assert ladder.level == LEVEL_FULL


# ---------------------------------------------------------------------------
# fault plan mechanics
# ---------------------------------------------------------------------------


def test_fault_plan_budgets_fire_at_their_cycle():
    plan = FaultPlan([Fault(cycle=2, kind="dispatch", count=2)])
    plan.begin_cycle(0)
    plan.dispatch_hook("serial")  # no budget: no raise
    plan.begin_cycle(2)
    with pytest.raises(InjectedFault):
        plan.dispatch_hook("serial")
    with pytest.raises(InjectedFault):
        plan.dispatch_hook("fused")
    plan.dispatch_hook("serial")  # budget exhausted
    assert [f["kind"] for f in plan.injected] == ["dispatch", "dispatch"]


def test_invariant_checker_catches_seeded_breaches():
    from koordinator_tpu.api.objects import Node, ObjectMeta, Pod, PodSpec
    from koordinator_tpu.api.resources import ResourceList
    from koordinator_tpu.client.store import KIND_NODE, KIND_POD, ObjectStore

    GIB = 1024 ** 3
    store = ObjectStore()
    store.add(KIND_NODE, Node(meta=ObjectMeta(name="n0", namespace=""),
                              allocatable=ResourceList.of(
                                  cpu=1000, memory=GIB, pods=10)))
    for i in range(2):
        pod = Pod(meta=ObjectMeta(name=f"p{i}", namespace="sim",
                                  uid=f"p{i}"),
                  spec=PodSpec(requests=ResourceList.of(cpu=800,
                                                        memory=GIB // 2)))
        pod.spec.node_name = "n0"
        pod.spec.host_ports.append(("TCP", 80))
        store.add(KIND_POD, pod)
    breaches = check_invariants(store)
    assert any("overcommitted" in b for b in breaches)
    assert any("double-bound" in b for b in breaches)


# ---------------------------------------------------------------------------
# seeded scenarios through the real Scheduler
# ---------------------------------------------------------------------------


def _mini(name, **kw):
    base = dict(name=name, seed=23, cycles=8, nodes=6, arrival_rate=4.0,
                departure_rate=1.0, be_fraction=0.3, queue_cap=64,
                ttb_slo_seconds=600.0, promote_after=3)
    base.update(kw)
    return Scenario(**base)


def test_smoke_scenario_zero_breaches_and_ladder_round_trip():
    sc = dataclasses.replace(SCENARIOS["smoke"], cycles=35)
    report = run_scenario(sc)
    assert report.invariant_breaches == []
    assert report.cycle_exceptions == []
    assert report.pods_bound > 50
    # the cycle-20 dispatch-fault burst demoted (no mesh/waves/explain
    # configured, so straight to the host fallback) and promoted back
    walked = [(t["from"], t["to"]) for t in report.ladder_transitions]
    assert ("full", "host-fallback") in walked
    assert report.final_level == "full"
    assert report.cycles_at_level.get("host-fallback", 0) > 0
    # the degraded window kept binding (the whole point of the ladder)
    degraded_cycles = {c for c in range(20, 27)}
    assert any(int(line.split("\t")[0]) in degraded_cycles
               for line in report.binding_log)
    assert report.flight_dumps >= 2  # one per transition at least
    # SLO surface is populated
    assert report.ttb_seconds and report.percentile(99) >= 0.0


def test_smoke_scenario_is_deterministic():
    sc = dataclasses.replace(SCENARIOS["smoke"], cycles=12)
    a = run_scenario(sc)
    b = run_scenario(sc)
    assert a.binding_log == b.binding_log
    assert a.binding_log_sha256 == b.binding_log_sha256
    assert a.pods_created == b.pods_created


def test_fault_ladder_walks_koordguard_rungs(cpu_devices):
    """The koordguard acceptance pin: with mesh + fused waves + explain
    on and a dispatch deadline armed, (1) a device loss NAMING its dead
    device lands the ladder on partial-mesh (the surviving submesh,
    still a mesh dispatch) and re-promotes to the FULL mesh after clean
    cycles; (2) a slow-not-dead device (latency injection > deadline)
    demotes via the watchdog within one cycle instead of wedging;
    (3) an anonymous fault storm still walks the remaining rungs to the
    host fallback — binding pods throughout with zero invariant
    breaches, every transition flight-dumped."""
    from koordinator_tpu.scheduler import metrics as scheduler_metrics

    base = scheduler_metrics.DISPATCH_DEADLINE_OVERRUNS
    overruns0 = sum(v for _l, v in base.samples()) if base.samples() else 0
    sc = dataclasses.replace(SCENARIOS["fault-ladder"], cycles=42)
    report = run_scenario(sc)
    assert report.invariant_breaches == []
    assert report.cycle_exceptions == []
    walk = [(t["from"], t["to"]) for t in report.ladder_transitions]
    assert walk[:4] == [
        # cycle 8: attributable loss -> the partial-mesh rung, then the
        # full-mesh probe succeeds after 5 clean cycles
        ("full", "partial-mesh"),
        ("partial-mesh", "full"),
        # cycle 22: deadline overrun (slow-not-dead) — anonymous, so it
        # skips partial-mesh; demoted within the SAME cycle, then back
        ("full", "no-mesh"),
        ("no-mesh", "full"),
    ]
    # cycle 34: the anonymous storm walks the rest of the ladder down
    assert walk[4:8] == [
        ("full", "no-mesh"),
        ("no-mesh", "serial-waves"),
        ("serial-waves", "no-explain"),
        ("no-explain", "host-fallback"),
    ]
    # the slow-device demotion came from the WATCHDOG: two monitored
    # syncs overran (retry, then demote) — the cycle never wedged
    assert report.deadline_overruns == 2
    overruns1 = sum(
        v for _l, v in scheduler_metrics.DISPATCH_DEADLINE_OVERRUNS.samples())
    assert overruns1 - overruns0 == 2
    # every koordguard rung was lived in AND pods bound while degraded
    for level in ("partial-mesh", "no-mesh", "host-fallback"):
        assert report.cycles_at_level.get(level, 0) > 0, level
    degraded = {c for c in range(8, 14)} | {c for c in range(34, 40)}
    assert any(int(line.split("\t")[0]) in degraded
               for line in report.binding_log)
    assert report.flight_dumps >= len(report.ladder_transitions)
    retries = dict(
        (labels["stage"], v)
        for labels, v in scheduler_metrics.DISPATCH_RETRIES.samples())
    assert retries.get("fused", 0) + retries.get("serial", 0) >= 8


def test_partial_mesh_survives_losing_two_of_eight_devices(cpu_devices):
    """The acceptance pin for partial-mesh survival: an 8-device mesh
    loses 2 named devices -> the ladder lands on partial-mesh with the
    6 SURVIVING devices, binds continue on the submesh, decisions are
    byte-identical to a fault-free single-device twin (mesh parity =
    the host-oracle-grade reference), and clean cycles re-promote to
    the full 8-device mesh."""
    import dataclasses as dc

    sc = Scenario(
        name="partial-mesh-8to6", seed=29, cycles=16, nodes=8,
        arrival_rate=5.0, departure_rate=1.0, queue_cap=96,
        ttb_slo_seconds=600.0, mesh=8, promote_after=4,
        faults=(Fault(cycle=4, kind="device_loss", count=2,
                      devices=(6, 7), message="two chips lost"),))
    sim = ChurnSimulator(sc)
    sizes = {}
    for cycle in range(sc.cycles):
        sim._run_one_cycle(cycle)
        mesh = sim.sched.mesh
        sizes[cycle] = mesh.devices.size if mesh is not None else 0
    report = sim.run_report()
    assert report.invariant_breaches == []
    assert report.cycle_exceptions == []
    # before the loss: 8 devices; after: exactly the 6 survivors
    assert sizes[3] == 8
    assert sizes[4] == 6
    walk = [(t["from"], t["to"]) for t in report.ladder_transitions]
    assert walk[0] == ("full", "partial-mesh")
    assert ("partial-mesh", "full") in walk  # the full mesh came back
    assert sizes[sc.cycles - 1] == 8
    # binds continued WHILE on the submesh
    partial_window = {c for c in range(4, 9)}
    assert any(int(line.split("\t")[0]) in partial_window
               for line in report.binding_log)
    # submesh parity: the same scenario minus the fault, single-device,
    # produces a byte-identical binding log (mesh size never changes
    # decisions — the submesh inherits the proven mesh-parity property)
    twin = run_scenario(dc.replace(sc, mesh=None, faults=()))
    assert twin.binding_log == report.binding_log


def test_crash_restart_meets_slo_with_clean_invariants():
    """The acceptance pin for crash-restart recovery: the scheduler is
    torn down mid-soak (device state, step caches, pack memo all
    dropped; its store watches severed), a fresh scheduler against the
    surviving store re-derives assumed/quota/gang state from
    store-visible binds, meets the restart-to-first-bind SLO, and the
    double-booking/capacity/gang invariants hold across the boundary."""
    from koordinator_tpu.client.store import KIND_POD

    sc = SCENARIOS["crash-restart"]
    sim = ChurnSimulator(sc)
    for cycle in range(sc.cycles):
        sim._run_one_cycle(cycle)
        if cycle == sc.restart_at[0]:
            # the fresh scheduler re-derived gang state from the store:
            # its assumed counts equal the store-visible bound members
            gang = sim.sched.extender.plugin("Coscheduling")
            bound = {}
            for p in sim.store.list(KIND_POD):
                if p.gang_key and p.is_assigned and not p.is_terminated:
                    bound[p.gang_key] = bound.get(p.gang_key, 0) + 1
            for name, count in bound.items():
                assert gang.assumed.get(name, 0) == count, name
    report = sim.run_report()
    assert report.invariant_breaches == []
    assert report.cycle_exceptions == []
    assert report.restarts == 1
    rd = report.to_dict()["restart"]
    assert rd["met"], rd
    assert rd["to_first_bind_seconds"]["count"] == 1
    assert rd["to_first_bind_seconds"]["p99"] <= sc.restart_slo_seconds
    # bindings happened on BOTH sides of the boundary
    cycles_bound = {int(line.split("\t")[0]) for line in report.binding_log}
    assert any(c < sc.restart_at[0] for c in cycles_bound)
    assert any(c >= sc.restart_at[0] for c in cycles_bound)
    # the dead scheduler's watches were severed: its snapshot cache no
    # longer receives events from the surviving store
    assert sim.sched_store._subs  # the LIVE scheduler's watches remain


def test_crash_restart_scenario_is_deterministic():
    sc = dataclasses.replace(SCENARIOS["crash-restart"], cycles=22)
    a = run_scenario(sc)
    b = run_scenario(sc)
    assert a.binding_log == b.binding_log
    assert a.restarts == b.restarts == 1


def test_store_write_fault_dumps_and_recovers():
    sc = _mini("store-fault", faults=(
        Fault(cycle=3, kind="store_write", count=1),))
    report = run_scenario(sc)
    # the ladder deliberately does NOT absorb store-write failures: the
    # cycle raised, flight-dumped, and the next cycle carried on
    assert len(report.cycle_exceptions) == 1
    assert "InjectedFault" in report.cycle_exceptions[0]
    assert report.invariant_breaches == []
    assert report.flight_dumps >= 1
    assert any(int(line.split("\t")[0]) > 3 for line in report.binding_log)


def test_sidecar_fault_degrades_to_local_step():
    sc = _mini("sidecar-fault", faults=(
        Fault(cycle=2, kind="sidecar", count=2),))
    report = run_scenario(sc)
    assert report.sidecar_fallbacks == 2
    assert report.invariant_breaches == []
    assert report.cycle_exceptions == []
    assert report.pods_bound > 0


def test_backpressure_sheds_and_requeues():
    sc = _mini("backpressure", cycles=10, arrival_rate=2.0,
               queue_cap=8, overflow_cap=10,
               burst_every=2, burst_size=40)
    report = run_scenario(sc)
    assert report.max_pending <= 8
    assert report.pods_shed > 0
    assert report.pods_requeued > 0
    assert report.max_overflow <= 10
    assert report.invariant_breaches == []


def test_drain_and_spot_reclaim_keep_invariants():
    sc = _mini("churny", cycles=14, nodes=8, arrival_rate=5.0,
               drain_every=4, drain_uncordon_after=3,
               spot_reclaim_every=3, spot_reclaim_count=3,
               metric_flip_every=5, quota_rebalance_every=6,
               gang_every=5, gang_size=3, descheduler_every=4)
    report = run_scenario(sc)
    assert report.invariant_breaches == []
    assert report.pods_drained > 0
    assert report.pods_reclaimed > 0
    assert report.pods_bound > 0
    assert report.descheduler_runs > 0  # the REAL descheduler rode along


def test_host_fallback_holds_invariants_under_permanent_device_loss():
    """Device never comes back: every cycle runs the pure-host pass.
    Capacity/hostPort invariants must hold through sustained churn."""
    sc = _mini("dead-device", cycles=12, arrival_rate=6.0,
               faults=(Fault(cycle=0, kind="dispatch", count=10**6),))
    report = run_scenario(sc)
    assert report.invariant_breaches == []
    assert report.final_level == "host-fallback"
    assert report.pods_bound > 20  # the fallback really binds
    assert report.cycle_exceptions == []


@pytest.mark.slow
def test_soak_1000_cycles_clean():
    """The acceptance soak: 1000 cycles of sustained churn with gang
    storms, drains, spot reclamation, metric flips, quota rebalances and
    dispatch/store-write/sidecar faults mid-soak. Zero invariant
    breaches; the SLO report (p99 time-to-bind) is the CHURN_r01.json
    deliverable (python -m koordinator_tpu.sim soak --out CHURN_r01.json).
    """
    report = run_scenario(SCENARIOS["soak"])
    assert report.invariant_breaches == []
    # the store-write fault is the ONLY expected cycle exception
    assert len(report.cycle_exceptions) <= 1
    assert report.pods_bound > 2000
    assert report.final_level == "full"
    assert report.descheduler_runs > 0
    # koordbalance: the descheduler's rebalance work is ASSERTED, not
    # just wired — hotspot events fire, migration jobs get created, and
    # every flagged node dissipates by soak end
    assert report.hotspot_events > 0
    assert report.migration_jobs_created > 0
    assert report.hotspots_open == 0
    # the p99 time-to-bind SLO verdict is REPORTED (CHURN_r01.json);
    # pass/fail against the target is load- and backend-dependent data,
    # not a structural gate
    assert report.ttb_seconds and report.percentile(99) > 0.0


def test_hotspot_scenario_dissipates_within_slo():
    """The koordbalance scenario family: a hotspot event marks the
    most-loaded nodes' pods HOT; the migration closed loop (job ->
    reservation -> next dispatch -> evict -> respread) must bring every
    flagged node back under the high thresholds within the SLO, with
    zero invariant breaches (incl. the new migration-job and
    reservation double-booking checks)."""
    sc = SCENARIOS["hotspot"].resolved(cycles=55)
    report = run_scenario(sc)
    assert report.invariant_breaches == []
    assert report.hotspot_events >= 1
    assert report.migration_jobs_created > 0
    assert report.pods_migrated > 0
    assert report.hotspots_open == 0
    assert report.dissipate_cycles
    assert max(report.dissipate_cycles) <= sc.hotspot_dissipate_slo_cycles


def test_drain_storm_scenario_rebalances_clean():
    """Mass cordon + migration under arrival pressure: several nodes
    cordoned per drain event, their load concentrating on the
    survivors; the descheduler keeps creating migration work and no
    store-level invariant (capacity, hostPort, reservation
    double-booking) breaks."""
    report = run_scenario(SCENARIOS["drain-storm"].resolved(cycles=55))
    assert report.invariant_breaches == []
    assert report.pods_drained > 0
    assert report.migration_jobs_created > 0


def test_cli_list_and_usage_contract(capsys):
    from koordinator_tpu.sim.__main__ import main

    assert main(["--list"]) == 0
    out = capsys.readouterr().out
    for name in SCENARIOS:
        assert name in out
    assert main(["no-such-scenario"]) == 4
    assert main([]) == 4  # no scenario given: usage error after catalog
