"""Cross-feature parity fuzz: randomized clusters mixing EVERY scheduling
feature — taints, nodeSelector/affinity, required+preferred pod affinity,
both spread modes, NUMA, quota, gangs, node reservation — diffed across the
XLA step, the numpy oracle, the wave kernel, the C++ floor, and (one seed)
the Pallas interpreter. Single-feature parity suites can miss interactions;
this is the combinatorial net."""

import json

import numpy as np
import pytest

from koordinator_tpu.api.objects import (
    ANNOTATION_NODE_RESERVATION,
    PodAffinityTerm,
    PreferredNodeTerm,
    PreferredPodTerm,
    TopologySpreadConstraint,
)
from koordinator_tpu.models.full_chain import build_full_chain_step
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.scheduler.parity import diff_bindings, serial_schedule_full
from koordinator_tpu.scheduler.snapshot import build_full_chain_inputs
from koordinator_tpu.testing import synth_full_cluster

ZONE = "topology.kubernetes.io/zone"


def _mixed_fixture(seed: int):
    import random

    rng = random.Random(seed)
    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(
        30, 60, seed=seed, taint_fraction=0.2)
    for j, node in enumerate(state.nodes):
        node.meta.labels[ZONE] = f"z{j % 4}"
        node.meta.labels["pool"] = rng.choice(["gold", "silver"])
        node.meta.labels["disk"] = rng.choice(["ssd", "hdd"])
        if rng.random() < 0.1:
            node.meta.annotations[ANNOTATION_NODE_RESERVATION] = json.dumps(
                {"resources": {"cpu": "1", "memory": "1Gi"}})
    MB = 1024 * 1024
    for j, node in enumerate(state.nodes):
        if rng.random() < 0.2:
            node.attachable_volume_limit = rng.choice([2, 4])
        if rng.random() < 0.4:
            node.images["registry/web:v2"] = 300 * MB
    apps = ["web", "db", "cache"]
    # existing assigned pods with anti terms exercise SYMMETRIC
    # anti-affinity (their domains must repel matching incoming pods);
    # existing hostPorts seed the NodePorts state
    for pod in state.pods_by_key.values():
        if pod.is_assigned and not pod.is_terminated and rng.random() < 0.1:
            pod.spec.pod_anti_affinity.append(PodAffinityTerm(
                selector={"app": rng.choice(apps)}, topology_key=ZONE))
        if pod.is_assigned and not pod.is_terminated and rng.random() < 0.1:
            pod.spec.host_ports.append(("TCP", rng.choice([80, 443, 8080])))
    for i, pod in enumerate(state.pending_pods):
        r = rng.random()
        app = rng.choice(apps)
        pod.meta.labels["app"] = app
        if rng.random() < 0.15:
            pod.spec.host_ports.append(("TCP", rng.choice([80, 443, 8080])))
        if rng.random() < 0.15:
            pod.spec.pvc_names = [f"claim-{i}"]
        elif rng.random() < 0.1:
            # mount a claim an ASSIGNED pod already attached somewhere:
            # the node's attached set intersects the pending batch's
            # claims -> VG > 1 volume groups (the already-attached
            # exemption encoding) flow through every backend
            donors = [p for p in state.pods_by_key.values()
                      if p.is_assigned and not p.is_terminated]
            if donors:
                donor = rng.choice(donors)
                if not donor.spec.pvc_names:
                    donor.spec.pvc_names = [f"shared-{i}"]
                pod.spec.pvc_names = list(donor.spec.pvc_names)
                pod.meta.namespace = donor.meta.namespace
        if rng.random() < 0.2:
            pod.spec.images = ["registry/web:v2"]
        if r < 0.15:
            pod.spec.node_selector["pool"] = rng.choice(["gold", "silver"])
        elif r < 0.3:
            pod.spec.pod_anti_affinity.append(PodAffinityTerm(
                selector={"app": app}, topology_key=ZONE))
        elif r < 0.45:
            pod.spec.pod_affinity.append(PodAffinityTerm(
                selector={"app": rng.choice(apps)}, topology_key=ZONE))
        elif r < 0.6:
            pod.spec.topology_spread.append(TopologySpreadConstraint(
                max_skew=rng.choice([1, 2]), topology_key=ZONE,
                selector={"app": app},
                when_unsatisfiable=rng.choice(
                    ["DoNotSchedule", "ScheduleAnyway"])))
        elif r < 0.75:
            pod.spec.affinity_preferred.append(PreferredNodeTerm(
                weight=rng.randint(1, 100), labels={"disk": "ssd"}))
        elif r < 0.9:
            pod.spec.pod_affinity_preferred.append(PreferredPodTerm(
                weight=rng.choice([-50, 40, 80]),
                selector={"app": rng.choice(apps)}, topology_key=ZONE))
    fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    return args, fc, pods, ng, ngroups


@pytest.mark.parametrize("seed", [101, 202, 303, 404, 505, 606, 717, 828])
def test_fuzz_all_backends_agree(seed):
    from koordinator_tpu.models.wave_chain import build_wave_full_chain_step
    from koordinator_tpu.native import floor as native_floor

    args, fc, pods, ng, ngroups = _mixed_fixture(seed)
    chosen = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    serial = serial_schedule_full(fc, args)
    n = len(pods.keys)
    diffs = diff_bindings(serial[:n], chosen[:n], pods.keys)
    assert not diffs, f"seed {seed}: {len(diffs)} mismatches: {diffs[:5]}"
    chosen_w = np.asarray(build_wave_full_chain_step(
        args, ng, ngroups, wave=16)(fc)[0])
    np.testing.assert_array_equal(chosen, chosen_w, err_msg=f"wave seed {seed}")
    if native_floor.available() or native_floor.build():
        chosen_nat = native_floor.serial_schedule_full_native(
            fc, args, num_groups=ngroups)
        np.testing.assert_array_equal(
            chosen[:n], chosen_nat[:n], err_msg=f"floor seed {seed}")
    assert (chosen[:n] >= 0).sum() > n // 3  # the fixture actually schedules


def test_fuzz_pallas_interpret_agrees():
    from koordinator_tpu.ops.pallas_full_chain import (
        build_pallas_full_chain_step,
    )

    args, fc, pods, ng, ngroups = _mixed_fixture(707)
    chosen = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    chosen_p = np.asarray(build_pallas_full_chain_step(
        args, ng, ngroups, interpret=True)(fc)[0])
    np.testing.assert_array_equal(chosen, chosen_p)
