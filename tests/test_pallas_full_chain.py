"""Parity: the full-chain Pallas kernel must bit-match the XLA full-chain
step (which bit-matches the serial reference emulator) across NUMA + quota +
gang configurations."""

import numpy as np
import pytest

from koordinator_tpu.models.full_chain import build_full_chain_step
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.ops.pallas_full_chain import build_pallas_full_chain_step
from koordinator_tpu.scheduler.snapshot import build_full_chain_inputs
from koordinator_tpu.testing import synth_full_cluster


def _compare(seed, num_nodes=24, num_pods=48, **kw):
    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(num_nodes, num_pods, seed=seed, **kw)
    fc, pods, nodes, tree, gang_index, ng, ngroups = build_full_chain_inputs(
        state, args)
    chosen_x, req_x, qused_x = build_full_chain_step(args, ng, ngroups)(fc)
    chosen_p, req_p, qused_p = build_pallas_full_chain_step(
        args, ng, ngroups, interpret=True)(fc)
    np.testing.assert_array_equal(np.asarray(chosen_x), np.asarray(chosen_p))
    np.testing.assert_allclose(np.asarray(req_x), np.asarray(req_p), atol=1e-3)
    np.testing.assert_allclose(np.asarray(qused_x), np.asarray(qused_p),
                               atol=1e-3)
    return np.asarray(chosen_x)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_pallas_full_chain_matches_xla(seed):
    chosen = _compare(seed)
    assert (chosen >= 0).sum() > 0


def test_pallas_full_chain_no_quota_no_gang():
    _compare(9, num_quotas=0, num_gangs=0)


def test_pallas_full_chain_crosses_pod_block():
    """160 pods > POD_BLOCK=128: at least two pod-column blocks stream
    through the grid, exercising the block index map and the lane-wrap
    (`(i * UNROLL) % POD_BLOCK`) math that a single-block case never
    evaluates past block 0."""
    chosen = _compare(6, num_nodes=40, num_pods=160)
    assert (chosen >= 0).sum() > 0


def test_pallas_full_chain_all_topology():
    _compare(5, topology_fraction=1.0, lsr_fraction=0.4)


def test_pallas_full_chain_with_active_axes_reduction():
    """The production cycle slices inputs to active resource axes; parity
    must hold on the reduced shapes too."""
    from koordinator_tpu.scheduler.snapshot import reduce_to_active_axes

    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(20, 40, seed=4)
    fc, pods, nodes, tree, gang_index, ng, ngroups = build_full_chain_inputs(
        state, args)
    fc, active = reduce_to_active_axes(fc)
    chosen_x, req_x, _ = build_full_chain_step(
        args, ng, ngroups, active_axes=active)(fc)
    chosen_p, req_p, _ = build_pallas_full_chain_step(
        args, ng, ngroups, interpret=True, active_axes=active)(fc)
    np.testing.assert_array_equal(np.asarray(chosen_x), np.asarray(chosen_p))
    np.testing.assert_allclose(np.asarray(req_x), np.asarray(req_p), atol=1e-3)


def test_pallas_full_chain_with_taints():
    chosen = _compare(21, taint_fraction=0.4)
    assert (chosen >= 0).sum() > 0
