"""In-place pod resize behind the ResizePod feature gate (the reference's
frameworkext factory runs Reserve + ResizePod instead of a scheduling pass
when the gate is on)."""

import numpy as np
import pytest

from koordinator_tpu.api.objects import (
    LABEL_POD_QOS,
    Node,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.api.resources import ResourceList, ResourceName
from koordinator_tpu.client.store import KIND_NODE, KIND_POD, ObjectStore
from koordinator_tpu.scheduler.cycle import Scheduler
from koordinator_tpu.utils.features import SCHEDULER_GATES

GIB = 1024**3


@pytest.fixture(autouse=True)
def _gate():
    SCHEDULER_GATES.set_from_map({"ResizePod": True})
    yield
    SCHEDULER_GATES.reset()


def _store(cores=8):
    store = ObjectStore()
    store.add(KIND_NODE, Node(
        meta=ObjectMeta(name="n0", namespace=""),
        allocatable=ResourceList.of(cpu=cores * 1000, memory=32 * GIB,
                                    pods=20)))
    return store


def _running(store, name, cpu, mem_gib=4):
    pod = Pod(meta=ObjectMeta(name=name, uid=name, creation_timestamp=1.0),
              spec=PodSpec(node_name="n0",
                           requests=ResourceList.of(cpu=cpu,
                                                    memory=mem_gib * GIB)))
    pod.phase = "Running"
    store.add(KIND_POD, pod)
    return pod


def test_resize_granted_when_node_fits():
    store = _store(cores=8)
    pod = _running(store, "web", cpu=2000)
    pod.spec.resize_requests = ResourceList.of(cpu=4000, memory=8 * GIB)
    store.update(KIND_POD, pod)
    result = Scheduler(store).run_cycle(now=1_000_000.0)
    assert result.resized == ["default/web"]
    stored = store.get(KIND_POD, "default/web")
    assert stored.spec.requests[ResourceName.CPU] == 4000
    assert stored.spec.resize_requests is None


def test_resize_pending_when_node_full():
    store = _store(cores=8)
    _running(store, "neighbor", cpu=5000)
    pod = _running(store, "web", cpu=2000)
    pod.spec.resize_requests = ResourceList.of(cpu=4000, memory=4 * GIB)
    store.update(KIND_POD, pod)
    result = Scheduler(store).run_cycle(now=1_000_000.0)
    assert result.resized == []
    assert result.resize_pending == ["default/web"]
    stored = store.get(KIND_POD, "default/web")
    assert stored.spec.requests[ResourceName.CPU] == 2000  # unchanged
    assert stored.spec.resize_requests is not None  # retries next cycle
    result2 = Scheduler(store).run_cycle(now=1_000_001.0)
    assert result2.resize_pending == ["default/web"]


def test_resize_sequence_respects_earlier_grants():
    """Two resizes on one node: the second sees the first's grant in the
    fit base, so they cannot jointly overcommit."""
    store = _store(cores=8)
    a = _running(store, "a", cpu=3000)
    b = _running(store, "b", cpu=3000)
    a.spec.resize_requests = ResourceList.of(cpu=5000, memory=4 * GIB)
    b.spec.resize_requests = ResourceList.of(cpu=5000, memory=4 * GIB)
    store.update(KIND_POD, a)
    store.update(KIND_POD, b)
    result = Scheduler(store).run_cycle(now=1_000_000.0)
    assert len(result.resized) == 1
    assert len(result.resize_pending) == 1


def test_cpuset_bound_pod_refused():
    store = _store(cores=8)
    pod = _running(store, "pinned", cpu=2000)
    pod.meta.labels[LABEL_POD_QOS] = "LSR"  # integer-cpu cpuset pod
    pod.spec.resize_requests = ResourceList.of(cpu=4000, memory=4 * GIB)
    store.update(KIND_POD, pod)
    result = Scheduler(store).run_cycle(now=1_000_000.0)
    assert result.resized == []
    assert result.resize_pending == ["default/pinned"]


def test_resize_to_integer_cpu_lsr_refused():
    """A fractional-cpu LSR pod resizing TO integer cpu would become
    cpuset-bound without a core allocation — refused (guard checks the
    resized shape, not just the current one)."""
    store = _store(cores=8)
    pod = _running(store, "frac", cpu=1500)
    pod.meta.labels[LABEL_POD_QOS] = "LSR"  # not integer-cpu yet
    pod.spec.resize_requests = ResourceList.of(cpu=4000, memory=4 * GIB)
    store.update(KIND_POD, pod)
    result = Scheduler(store).run_cycle(now=1_000_000.0)
    assert result.resized == []
    assert result.resize_pending == ["default/frac"]


def test_resize_counts_available_reservations():
    """An Available reservation's held capacity is part of the fit base: a
    resize that would eat into it stays pending."""
    from koordinator_tpu.api.objects import Reservation, ReservationOwner
    from koordinator_tpu.client.store import KIND_RESERVATION

    store = _store(cores=8)
    pod = _running(store, "web", cpu=2000)
    res = Reservation(
        meta=ObjectMeta(name="hold", namespace="", creation_timestamp=1.0),
        template=PodSpec(requests=ResourceList.of(cpu=5000, memory=4 * GIB)),
        owners=[ReservationOwner(label_selector={"app": "later"})],
        node_name="n0", phase="Available")
    res.allocatable = res.template.requests.copy()
    store.add(KIND_RESERVATION, res)
    pod.spec.resize_requests = ResourceList.of(cpu=4000, memory=4 * GIB)
    store.update(KIND_POD, pod)
    result = Scheduler(store).run_cycle(now=1_000_000.0)
    assert result.resized == []
    assert result.resize_pending == ["default/web"]


def test_resize_ignores_other_schedulers_pods():
    store = _store(cores=8)
    pod = _running(store, "foreign", cpu=2000)
    pod.spec.scheduler_name = "other-scheduler"
    pod.spec.resize_requests = ResourceList.of(cpu=4000, memory=4 * GIB)
    store.update(KIND_POD, pod)
    result = Scheduler(store).run_cycle(now=1_000_000.0)
    assert result.resized == [] and result.resize_pending == []
    assert store.get(KIND_POD, "default/foreign").spec.resize_requests \
        is not None


def test_resize_missing_node_surfaces_reason():
    store = _store(cores=8)
    pod = _running(store, "orphan", cpu=2000)
    pod.spec.node_name = "gone-node"
    pod.spec.resize_requests = ResourceList.of(cpu=4000, memory=4 * GIB)
    store.update(KIND_POD, pod)
    sched = Scheduler(store)
    result = sched.run_cycle(now=1_000_000.0)
    assert result.resize_pending == ["default/orphan"]
    assert any("node not found" in r
               for _k, r in sched.extender.error_handlers.failures)


def test_gate_off_ignores_resize():
    SCHEDULER_GATES.reset()  # default: ResizePod off
    store = _store()
    pod = _running(store, "web", cpu=2000)
    pod.spec.resize_requests = ResourceList.of(cpu=4000, memory=4 * GIB)
    store.update(KIND_POD, pod)
    result = Scheduler(store).run_cycle(now=1_000_000.0)
    assert result.resized == [] and result.resize_pending == []
    assert store.get(KIND_POD,
                     "default/web").spec.requests[ResourceName.CPU] == 2000
