"""Descheduler plugin framework: profiles, the four plugin interfaces, the
defaultevictor chain, and the vendored-style plugins
(ref pkg/descheduler/framework/types.go:32-110, profile/)."""

import pytest

from koordinator_tpu.api.objects import (
    Node,
    ObjectMeta,
    Pod,
    PodDisruptionBudget,
    PodSpec,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_PDB,
    KIND_POD,
    ObjectStore,
)
from koordinator_tpu.descheduler.descheduler import Descheduler
from koordinator_tpu.descheduler.framework import (
    Profile,
    ProfileConfig,
    registered_plugins,
)

GIB = 1024**3
NOW = 1_000_000.0


def _node(store, name, labels=None, unschedulable=False):
    store.add(KIND_NODE, Node(
        meta=ObjectMeta(name=name, namespace="", labels=labels or {}),
        allocatable=ResourceList.of(cpu=16000, memory=64 * GIB, pods=110),
        unschedulable=unschedulable,
    ))


def _pod(store, name, node=None, owner=("ReplicaSet", "web"), selector=None,
         labels=None, created=NOW - 100.0):
    pod = Pod(
        meta=ObjectMeta(name=name, labels=labels or {},
                        owner_kind=owner[0] if owner else "",
                        owner_name=owner[1] if owner else "",
                        creation_timestamp=created),
        spec=PodSpec(requests=ResourceList.of(cpu=1000, memory=GIB),
                     node_selector=selector or {}),
    )
    if node:
        pod.spec.node_name = node
        pod.phase = "Running"
    store.add(KIND_POD, pod)
    return pod


def test_builtin_plugins_registered():
    names = registered_plugins()
    for expect in ("DefaultEvictor", "LowNodeLoad", "RemoveDuplicates",
                   "RemovePodsViolatingNodeAffinity"):
        assert expect in names


def test_unknown_plugin_rejected():
    store = ObjectStore()
    with pytest.raises(ValueError, match="not registered"):
        Profile(ProfileConfig(deschedule=["NoSuchPlugin"]), store)


class TestNodeAffinityPlugin:
    def _store(self):
        store = ObjectStore()
        _node(store, "node-a", labels={"zone": "east"})
        _node(store, "node-b", labels={"zone": "west"})
        return store

    def test_evicts_when_affinity_violated_and_alternative_exists(self):
        store = self._store()
        pod = _pod(store, "p", node="node-a", selector={"zone": "west"})
        profile = Profile(ProfileConfig(
            deschedule=["RemovePodsViolatingNodeAffinity"]), store)
        profile.run(NOW)
        assert store.get(KIND_POD, pod.meta.key).is_terminated

    def test_keeps_pod_when_no_alternative(self):
        store = self._store()
        pod = _pod(store, "p", node="node-a", selector={"zone": "north"})
        profile = Profile(ProfileConfig(
            deschedule=["RemovePodsViolatingNodeAffinity"]), store)
        profile.run(NOW)
        assert not store.get(KIND_POD, pod.meta.key).is_terminated

    def test_keeps_matching_pod(self):
        store = self._store()
        pod = _pod(store, "p", node="node-a", selector={"zone": "east"})
        profile = Profile(ProfileConfig(
            deschedule=["RemovePodsViolatingNodeAffinity"]), store)
        profile.run(NOW)
        assert not store.get(KIND_POD, pod.meta.key).is_terminated


class TestRemoveDuplicates:
    def test_extra_replicas_evicted(self):
        store = ObjectStore()
        _node(store, "node-a")
        _node(store, "node-b")
        oldest = _pod(store, "r0", node="node-a", created=NOW - 500)
        _pod(store, "r1", node="node-a")
        _pod(store, "r2", node="node-a")
        profile = Profile(ProfileConfig(balance=["RemoveDuplicates"]), store)
        profile.run(NOW)
        survivors = [p for p in store.list(KIND_POD) if not p.is_terminated]
        assert [p.meta.name for p in survivors] == ["r0"]
        assert oldest.meta.key == survivors[0].meta.key

    def test_single_node_cluster_untouched(self):
        store = ObjectStore()
        _node(store, "node-a")
        _pod(store, "r0", node="node-a")
        _pod(store, "r1", node="node-a")
        profile = Profile(ProfileConfig(balance=["RemoveDuplicates"]), store)
        profile.run(NOW)
        assert all(not p.is_terminated for p in store.list(KIND_POD))

    def test_no_eviction_when_no_other_node_matches(self):
        """Duplicates pinned by selector to their node are left alone —
        evicting them would only churn (scheduler puts them right back)."""
        store = ObjectStore()
        _node(store, "node-a", labels={"zone": "east"})
        _node(store, "node-b", labels={"zone": "west"})
        _pod(store, "r0", node="node-a", selector={"zone": "east"})
        _pod(store, "r1", node="node-a", selector={"zone": "east"})
        profile = Profile(ProfileConfig(balance=["RemoveDuplicates"]), store)
        profile.run(NOW)
        assert all(not p.is_terminated for p in store.list(KIND_POD))

    def test_bare_pods_ignored(self):
        store = ObjectStore()
        _node(store, "node-a")
        _node(store, "node-b")
        _pod(store, "b0", node="node-a", owner=None)
        _pod(store, "b1", node="node-a", owner=None)
        profile = Profile(ProfileConfig(balance=["RemoveDuplicates"]), store)
        profile.run(NOW)
        assert all(not p.is_terminated for p in store.list(KIND_POD))


class TestEvictorChain:
    def test_pdb_blocks_through_handle(self):
        """The profile Handle runs Filter -> PreEvictionFilter -> Evict;
        a tight PDB stops the eviction."""
        store = ObjectStore()
        _node(store, "node-a")
        _node(store, "node-b")
        _pod(store, "r0", node="node-a", labels={"app": "web"})
        _pod(store, "r1", node="node-a", labels={"app": "web"})
        store.add(KIND_PDB, PodDisruptionBudget(
            meta=ObjectMeta(name="pdb", namespace="default"),
            selector={"app": "web"}, min_available=2))
        profile = Profile(ProfileConfig(balance=["RemoveDuplicates"]), store)
        profile.run(NOW)
        assert all(not p.is_terminated for p in store.list(KIND_POD))


class TestTwoProfiles:
    def test_per_profile_plugin_sets(self):
        """Two profiles with disjoint plugin sets both run in one pass."""
        store = ObjectStore()
        _node(store, "node-a", labels={"zone": "east"})
        _node(store, "node-b", labels={"zone": "west"})
        # affinity violation for profile 1
        moved = _pod(store, "moved", node="node-a", selector={"zone": "west"},
                     owner=("ReplicaSet", "api"))
        # duplicates for profile 2
        _pod(store, "r0", node="node-b", created=NOW - 500)
        _pod(store, "r1", node="node-b")
        desched = Descheduler(store, profiles=[
            ProfileConfig(name="affinity",
                          deschedule=["RemovePodsViolatingNodeAffinity"]),
            ProfileConfig(name="dedupe", balance=["RemoveDuplicates"]),
        ])
        out = desched.run_once(now=NOW)
        assert out["evicted"]["affinity"] == 1
        assert out["evicted"]["dedupe"] == 1
        assert store.get(KIND_POD, moved.meta.key).is_terminated
        survivors = sorted(
            p.meta.name for p in store.list(KIND_POD) if not p.is_terminated
        )
        assert survivors == ["r0"]
        assert "affinity" in out["profiles"] and "dedupe" in out["profiles"]


class TestPodLifeTime:
    def test_old_pods_evicted_states_filtered(self):
        store = ObjectStore()
        _node(store, "node-a")
        old = _pod(store, "old", node="node-a", created=NOW - 7200)
        young = _pod(store, "young", node="node-a", created=NOW - 60)
        old_pending = Pod(meta=ObjectMeta(name="old-pending",
                                          creation_timestamp=NOW - 7200),
                          spec=PodSpec(node_name="node-a"))
        store.add(KIND_POD, old_pending)  # phase Pending
        profile = Profile(ProfileConfig(
            deschedule=["PodLifeTime"],
            plugin_args={"PodLifeTime": {"maxPodLifeTimeSeconds": 3600,
                                         "states": ["Running"]}},
        ), store)
        profile.run(NOW)
        assert store.get(KIND_POD, old.meta.key).is_terminated
        assert not store.get(KIND_POD, young.meta.key).is_terminated
        assert not store.get(KIND_POD, old_pending.meta.key).is_terminated


class TestRemoveFailedPods:
    def test_failed_pods_evicted_with_filters(self):
        store = ObjectStore()
        _node(store, "node-a")
        failed = _pod(store, "failed", node="node-a", created=NOW - 600)
        failed.phase, failed.reason = "Failed", "OutOfCpu"
        store.update(KIND_POD, failed)
        wrong_reason = _pod(store, "wrong-reason", node="node-a",
                            created=NOW - 600)
        wrong_reason.phase, wrong_reason.reason = "Failed", "Evicted"
        store.update(KIND_POD, wrong_reason)
        excluded = _pod(store, "excluded", node="node-a", created=NOW - 600,
                        owner=("DaemonSet", "ds"))
        excluded.phase, excluded.reason = "Failed", "OutOfCpu"
        store.update(KIND_POD, excluded)
        running = _pod(store, "running", node="node-a")
        recent = _pod(store, "recent", node="node-a", created=NOW - 60)
        recent.phase, recent.reason = "Failed", "OutOfCpu"
        store.update(KIND_POD, recent)
        profile = Profile(ProfileConfig(
            deschedule=["RemoveFailedPods"],
            plugin_args={"RemoveFailedPods": {
                "reasons": ["OutOfCpu"],
                "minPodLifetimeSeconds": 300,
                "excludeOwnerKinds": ["DaemonSet"],
            }},
        ), store)
        profile.run(NOW)
        # the matching failed pod is DELETED (controller recreates it)
        assert store.get(KIND_POD, failed.meta.key) is None
        # filtered pods survive: wrong reason, excluded owner, too recent
        assert store.get(KIND_POD, wrong_reason.meta.key) is not None
        assert store.get(KIND_POD, excluded.meta.key) is not None
        assert store.get(KIND_POD, recent.meta.key) is not None
        assert not store.get(KIND_POD, running.meta.key).is_terminated


class TestTooManyRestarts:
    def test_crashlooping_pod_evicted(self):
        store = ObjectStore()
        _node(store, "node-a")
        looping = _pod(store, "looping", node="node-a")
        looping.restart_count = 12
        store.update(KIND_POD, looping)
        healthy = _pod(store, "healthy", node="node-a")
        profile = Profile(ProfileConfig(
            deschedule=["RemovePodsHavingTooManyRestarts"],
            plugin_args={"RemovePodsHavingTooManyRestarts": {
                "podRestartThreshold": 10}},
        ), store)
        profile.run(NOW)
        assert store.get(KIND_POD, looping.meta.key).is_terminated
        assert not store.get(KIND_POD, healthy.meta.key).is_terminated


class TestNodeTaints:
    def test_untolerated_pod_evicted(self):
        store = ObjectStore()
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name="tainted", namespace=""),
            allocatable=ResourceList.of(cpu=16000, memory=64 * GIB),
            taints=[("dedicated", "infra")],
        ))
        _node(store, "clean")
        victim = _pod(store, "victim", node="tainted")
        tolerant = _pod(store, "tolerant", node="tainted")
        tolerant.spec.tolerations = [("dedicated", "infra")]
        store.update(KIND_POD, tolerant)
        wildcard = _pod(store, "wildcard", node="tainted")
        wildcard.spec.tolerations = [("dedicated", "")]
        store.update(KIND_POD, wildcard)
        elsewhere = _pod(store, "elsewhere", node="clean")
        profile = Profile(ProfileConfig(
            deschedule=["RemovePodsViolatingNodeTaints"]), store)
        profile.run(NOW)
        assert store.get(KIND_POD, victim.meta.key).is_terminated
        assert not store.get(KIND_POD, tolerant.meta.key).is_terminated
        assert not store.get(KIND_POD, wildcard.meta.key).is_terminated
        assert not store.get(KIND_POD, elsewhere.meta.key).is_terminated

    def test_opt_out_and_bare_pods_protected(self):
        store = ObjectStore()
        _node(store, "node-a")
        opted_out = _pod(store, "opted-out", node="node-a", created=NOW - 600)
        opted_out.phase = "Failed"
        opted_out.meta.annotations[
            "descheduler.koordinator.sh/evictable"] = "false"
        store.update(KIND_POD, opted_out)
        bare = _pod(store, "bare", node="node-a", created=NOW - 600,
                    owner=None)
        bare.phase = "Failed"
        store.update(KIND_POD, bare)
        profile = Profile(ProfileConfig(deschedule=["RemoveFailedPods"]),
                          store)
        profile.run(NOW)
        assert store.get(KIND_POD, opted_out.meta.key) is not None
        assert store.get(KIND_POD, bare.meta.key) is not None
        assert profile.handle.evicted_count == 0

        # bare-pod deletion is opt-in (EvictFailedBarePods)
        profile2 = Profile(ProfileConfig(
            deschedule=["RemoveFailedPods"],
            plugin_args={"RemoveFailedPods": {"evictFailedBarePods": True}},
        ), store)
        profile2.run(NOW)
        assert store.get(KIND_POD, bare.meta.key) is None
        assert profile2.handle.evicted_count == 1

    def test_no_eviction_without_tolerable_alternative(self):
        store = ObjectStore()
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name="tainted-a", namespace=""),
            allocatable=ResourceList.of(cpu=16000, memory=64 * GIB),
            taints=[("dedicated", "infra")],
        ))
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name="tainted-b", namespace=""),
            allocatable=ResourceList.of(cpu=16000, memory=64 * GIB),
            taints=[("dedicated", "gpu")],
        ))
        stuck = _pod(store, "stuck", node="tainted-a")
        profile = Profile(ProfileConfig(
            deschedule=["RemovePodsViolatingNodeTaints"]), store)
        profile.run(NOW)
        # every other node is also intolerable: evicting would churn forever
        assert not store.get(KIND_POD, stuck.meta.key).is_terminated

    def test_evictability_guards_apply_to_failed_pods(self):
        """The full filter chain (minus the terminated check) still guards
        deletion: DaemonSet and system-critical Failed pods survive."""
        store = ObjectStore()
        _node(store, "node-a")
        ds = _pod(store, "ds-pod", node="node-a", created=NOW - 600,
                  owner=("DaemonSet", "logger"))
        ds.phase = "Failed"
        store.update(KIND_POD, ds)
        critical = _pod(store, "critical", node="node-a", created=NOW - 600)
        critical.phase = "Failed"
        critical.spec.priority = 2_000_001_000
        store.update(KIND_POD, critical)
        profile = Profile(ProfileConfig(deschedule=["RemoveFailedPods"]),
                          store)
        profile.run(NOW)
        assert store.get(KIND_POD, ds.meta.key) is not None
        assert store.get(KIND_POD, critical.meta.key) is not None


def test_podlifetime_requires_max_seconds():
    store = ObjectStore()
    with pytest.raises(ValueError, match="maxPodLifeTimeSeconds"):
        Profile(ProfileConfig(deschedule=["PodLifeTime"]), store)


class TestAffinitySpreadPlugins:
    def test_anti_affinity_violation_evicted(self):
        from koordinator_tpu.api.objects import PodAffinityTerm

        store = ObjectStore()
        _node(store, "node-a", labels={"zone": "z0"})
        _node(store, "node-b", labels={"zone": "z1"})
        solo = _pod(store, "solo", node="node-a", labels={"app": "db"})
        solo.spec.pod_anti_affinity.append(PodAffinityTerm(
            selector={"app": "db"}, topology_key="zone"))
        store.update(KIND_POD, solo)
        intruder = _pod(store, "intruder", node="node-a",
                        labels={"app": "db"})
        clean = _pod(store, "clean", node="node-b", labels={"app": "db"})
        profile = Profile(ProfileConfig(
            deschedule=["RemovePodsViolatingInterPodAntiAffinity"]), store)
        profile.run(NOW)
        assert store.get(KIND_POD, solo.meta.key).is_terminated
        assert not store.get(KIND_POD, intruder.meta.key).is_terminated
        assert not store.get(KIND_POD, clean.meta.key).is_terminated

    def test_anti_affinity_namespace_scoped(self):
        from koordinator_tpu.api.objects import PodAffinityTerm

        store = ObjectStore()
        _node(store, "node-a", labels={"zone": "z0"})

        def ns_pod(name, ns):
            pod = Pod(meta=ObjectMeta(name=name, namespace=ns,
                                      labels={"app": "db"},
                                      creation_timestamp=NOW - 100),
                      spec=PodSpec(node_name="node-a"), phase="Running")
            store.add(KIND_POD, pod)
            return pod

        guarded = ns_pod("guarded", "ns-a")
        guarded.spec.pod_anti_affinity.append(PodAffinityTerm(
            selector={"app": "db"}, topology_key="zone"))
        ns_pod("foreign", "ns-b")
        profile = Profile(ProfileConfig(
            deschedule=["RemovePodsViolatingInterPodAntiAffinity"]), store)
        profile.run(NOW)
        # the only same-namespace match is itself: no violation
        assert not store.get(KIND_POD, guarded.meta.key).is_terminated

    def test_topology_spread_violation_evicted(self):
        from koordinator_tpu.api.objects import TopologySpreadConstraint

        store = ObjectStore()
        _node(store, "node-a", labels={"zone": "z0"})
        _node(store, "node-b", labels={"zone": "z1"})
        crowded = []
        for i in range(4):
            p = _pod(store, f"crowd-{i}", node="node-a",
                     labels={"app": "web"}, created=NOW - 100 + i)
            p.spec.topology_spread.append(TopologySpreadConstraint(
                max_skew=1, topology_key="zone", selector={"app": "web"}))
            store.update(KIND_POD, p)
            crowded.append(p)
        lone = _pod(store, "lone", node="node-b", labels={"app": "web"})
        profile = Profile(ProfileConfig(
            balance=["RemovePodsViolatingTopologySpreadConstraint"]), store)
        profile.run(NOW)
        # z0 has 4, z1 has 1: skew 3 > 1 -> evict 2 newest from z0
        evicted = [p.meta.name for p in crowded
                   if store.get(KIND_POD, p.meta.key).is_terminated]
        assert evicted == ["crowd-2", "crowd-3"]
        assert not store.get(KIND_POD, lone.meta.key).is_terminated

    def test_high_node_utilization_consolidates(self):
        from koordinator_tpu.api.objects import NodeMetric, NodeMetricInfo
        from koordinator_tpu.client.store import KIND_NODE_METRIC

        store = ObjectStore()
        _node(store, "node-idle")
        _node(store, "node-busy")
        for name, cpu in (("node-idle", 800), ("node-busy", 12_000)):
            store.add(KIND_NODE_METRIC, NodeMetric(
                meta=ObjectMeta(name=name, namespace=""),
                node_metric=NodeMetricInfo(
                    node_usage=ResourceList.of(cpu=cpu)),
                update_time=NOW))
        idle_pod = _pod(store, "on-idle", node="node-idle")
        busy_pod = _pod(store, "on-busy", node="node-busy")
        profile = Profile(ProfileConfig(
            balance=["HighNodeUtilization"],
            plugin_args={"HighNodeUtilization":
                         {"cpu_threshold_percent": 20}}), store)
        profile.run(NOW)
        assert store.get(KIND_POD, idle_pod.meta.key).is_terminated
        assert not store.get(KIND_POD, busy_pod.meta.key).is_terminated

    def test_anti_affinity_mutual_violation_evicts_only_one(self):
        from koordinator_tpu.api.objects import PodAffinityTerm

        store = ObjectStore()
        _node(store, "node-a", labels={"zone": "z0"})
        pair = []
        for name in ("a", "b"):
            p = _pod(store, name, node="node-a", labels={"app": "db"})
            p.spec.pod_anti_affinity.append(PodAffinityTerm(
                selector={"app": "db"}, topology_key="zone"))
            store.update(KIND_POD, p)
            pair.append(p)
        profile = Profile(ProfileConfig(
            deschedule=["RemovePodsViolatingInterPodAntiAffinity"]), store)
        profile.run(NOW)
        terminated = [p for p in pair
                      if store.get(KIND_POD, p.meta.key).is_terminated]
        assert len(terminated) == 1  # evicting one resolves the violation

    def test_spread_plugin_min_ignores_ineligible_domains(self):
        from koordinator_tpu.api.objects import TopologySpreadConstraint

        store = ObjectStore()
        _node(store, "node-a", labels={"zone": "z0", "allowed": "yes"})
        _node(store, "node-b", labels={"zone": "z1", "allowed": "yes"})
        _node(store, "node-c", labels={"zone": "z2", "allowed": "no"})
        for i in range(6):
            node = "node-a" if i % 2 == 0 else "node-b"
            p = _pod(store, f"w{i}", node=node, labels={"app": "web"},
                     selector={"allowed": "yes"})
            p.spec.topology_spread.append(TopologySpreadConstraint(
                max_skew=1, topology_key="zone", selector={"app": "web"}))
            store.update(KIND_POD, p)
        profile = Profile(ProfileConfig(
            balance=["RemovePodsViolatingTopologySpreadConstraint"]), store)
        profile.run(NOW)
        # 3/3 across the two ELIGIBLE zones is balanced; the empty forbidden
        # z2 must not pin the minimum at 0 and trigger evictions
        assert all(not p.is_terminated for p in store.list(KIND_POD))

    def test_high_node_utilization_respects_absorb_capacity(self):
        from koordinator_tpu.api.objects import NodeMetric, NodeMetricInfo
        from koordinator_tpu.client.store import KIND_NODE_METRIC

        store = ObjectStore()
        _node(store, "node-idle")
        # busy node with almost no spare cpu: 15 pods x 1000m of 16000m
        _node(store, "node-busy")
        for i in range(15):
            _pod(store, f"busy-{i}", node="node-busy")
        for name, cpu in (("node-idle", 800), ("node-busy", 15_000)):
            store.add(KIND_NODE_METRIC, NodeMetric(
                meta=ObjectMeta(name=name, namespace=""),
                node_metric=NodeMetricInfo(
                    node_usage=ResourceList.of(cpu=cpu)),
                update_time=NOW))
        idle_pods = [_pod(store, f"idle-{i}", node="node-idle")
                     for i in range(4)]
        profile = Profile(ProfileConfig(
            balance=["HighNodeUtilization"],
            plugin_args={"HighNodeUtilization":
                         {"cpu_threshold_percent": 20}}), store)
        profile.run(NOW)
        evicted = sum(store.get(KIND_POD, p.meta.key).is_terminated
                      for p in idle_pods)
        # only 1000m spare on node-busy -> exactly one pod may move
        assert evicted == 1
