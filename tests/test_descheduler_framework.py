"""Descheduler plugin framework: profiles, the four plugin interfaces, the
defaultevictor chain, and the vendored-style plugins
(ref pkg/descheduler/framework/types.go:32-110, profile/)."""

import pytest

from koordinator_tpu.api.objects import (
    Node,
    ObjectMeta,
    Pod,
    PodDisruptionBudget,
    PodSpec,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_PDB,
    KIND_POD,
    ObjectStore,
)
from koordinator_tpu.descheduler.descheduler import Descheduler
from koordinator_tpu.descheduler.framework import (
    Profile,
    ProfileConfig,
    registered_plugins,
)

GIB = 1024**3
NOW = 1_000_000.0


def _node(store, name, labels=None, unschedulable=False):
    store.add(KIND_NODE, Node(
        meta=ObjectMeta(name=name, namespace="", labels=labels or {}),
        allocatable=ResourceList.of(cpu=16000, memory=64 * GIB, pods=110),
        unschedulable=unschedulable,
    ))


def _pod(store, name, node=None, owner=("ReplicaSet", "web"), selector=None,
         labels=None, created=NOW - 100.0):
    pod = Pod(
        meta=ObjectMeta(name=name, labels=labels or {},
                        owner_kind=owner[0] if owner else "",
                        owner_name=owner[1] if owner else "",
                        creation_timestamp=created),
        spec=PodSpec(requests=ResourceList.of(cpu=1000, memory=GIB),
                     node_selector=selector or {}),
    )
    if node:
        pod.spec.node_name = node
        pod.phase = "Running"
    store.add(KIND_POD, pod)
    return pod


def test_builtin_plugins_registered():
    names = registered_plugins()
    for expect in ("DefaultEvictor", "LowNodeLoad", "RemoveDuplicates",
                   "RemovePodsViolatingNodeAffinity"):
        assert expect in names


def test_unknown_plugin_rejected():
    store = ObjectStore()
    with pytest.raises(ValueError, match="not registered"):
        Profile(ProfileConfig(deschedule=["NoSuchPlugin"]), store)


class TestNodeAffinityPlugin:
    def _store(self):
        store = ObjectStore()
        _node(store, "node-a", labels={"zone": "east"})
        _node(store, "node-b", labels={"zone": "west"})
        return store

    def test_evicts_when_affinity_violated_and_alternative_exists(self):
        store = self._store()
        pod = _pod(store, "p", node="node-a", selector={"zone": "west"})
        profile = Profile(ProfileConfig(
            deschedule=["RemovePodsViolatingNodeAffinity"]), store)
        profile.run(NOW)
        assert store.get(KIND_POD, pod.meta.key).is_terminated

    def test_keeps_pod_when_no_alternative(self):
        store = self._store()
        pod = _pod(store, "p", node="node-a", selector={"zone": "north"})
        profile = Profile(ProfileConfig(
            deschedule=["RemovePodsViolatingNodeAffinity"]), store)
        profile.run(NOW)
        assert not store.get(KIND_POD, pod.meta.key).is_terminated

    def test_keeps_matching_pod(self):
        store = self._store()
        pod = _pod(store, "p", node="node-a", selector={"zone": "east"})
        profile = Profile(ProfileConfig(
            deschedule=["RemovePodsViolatingNodeAffinity"]), store)
        profile.run(NOW)
        assert not store.get(KIND_POD, pod.meta.key).is_terminated


class TestRemoveDuplicates:
    def test_extra_replicas_evicted(self):
        store = ObjectStore()
        _node(store, "node-a")
        _node(store, "node-b")
        oldest = _pod(store, "r0", node="node-a", created=NOW - 500)
        _pod(store, "r1", node="node-a")
        _pod(store, "r2", node="node-a")
        profile = Profile(ProfileConfig(balance=["RemoveDuplicates"]), store)
        profile.run(NOW)
        survivors = [p for p in store.list(KIND_POD) if not p.is_terminated]
        assert [p.meta.name for p in survivors] == ["r0"]
        assert oldest.meta.key == survivors[0].meta.key

    def test_single_node_cluster_untouched(self):
        store = ObjectStore()
        _node(store, "node-a")
        _pod(store, "r0", node="node-a")
        _pod(store, "r1", node="node-a")
        profile = Profile(ProfileConfig(balance=["RemoveDuplicates"]), store)
        profile.run(NOW)
        assert all(not p.is_terminated for p in store.list(KIND_POD))

    def test_no_eviction_when_no_other_node_matches(self):
        """Duplicates pinned by selector to their node are left alone —
        evicting them would only churn (scheduler puts them right back)."""
        store = ObjectStore()
        _node(store, "node-a", labels={"zone": "east"})
        _node(store, "node-b", labels={"zone": "west"})
        _pod(store, "r0", node="node-a", selector={"zone": "east"})
        _pod(store, "r1", node="node-a", selector={"zone": "east"})
        profile = Profile(ProfileConfig(balance=["RemoveDuplicates"]), store)
        profile.run(NOW)
        assert all(not p.is_terminated for p in store.list(KIND_POD))

    def test_bare_pods_ignored(self):
        store = ObjectStore()
        _node(store, "node-a")
        _node(store, "node-b")
        _pod(store, "b0", node="node-a", owner=None)
        _pod(store, "b1", node="node-a", owner=None)
        profile = Profile(ProfileConfig(balance=["RemoveDuplicates"]), store)
        profile.run(NOW)
        assert all(not p.is_terminated for p in store.list(KIND_POD))


class TestEvictorChain:
    def test_pdb_blocks_through_handle(self):
        """The profile Handle runs Filter -> PreEvictionFilter -> Evict;
        a tight PDB stops the eviction."""
        store = ObjectStore()
        _node(store, "node-a")
        _node(store, "node-b")
        _pod(store, "r0", node="node-a", labels={"app": "web"})
        _pod(store, "r1", node="node-a", labels={"app": "web"})
        store.add(KIND_PDB, PodDisruptionBudget(
            meta=ObjectMeta(name="pdb", namespace="default"),
            selector={"app": "web"}, min_available=2))
        profile = Profile(ProfileConfig(balance=["RemoveDuplicates"]), store)
        profile.run(NOW)
        assert all(not p.is_terminated for p in store.list(KIND_POD))


class TestTwoProfiles:
    def test_per_profile_plugin_sets(self):
        """Two profiles with disjoint plugin sets both run in one pass."""
        store = ObjectStore()
        _node(store, "node-a", labels={"zone": "east"})
        _node(store, "node-b", labels={"zone": "west"})
        # affinity violation for profile 1
        moved = _pod(store, "moved", node="node-a", selector={"zone": "west"},
                     owner=("ReplicaSet", "api"))
        # duplicates for profile 2
        _pod(store, "r0", node="node-b", created=NOW - 500)
        _pod(store, "r1", node="node-b")
        desched = Descheduler(store, profiles=[
            ProfileConfig(name="affinity",
                          deschedule=["RemovePodsViolatingNodeAffinity"]),
            ProfileConfig(name="dedupe", balance=["RemoveDuplicates"]),
        ])
        out = desched.run_once(now=NOW)
        assert out["evicted"]["affinity"] == 1
        assert out["evicted"]["dedupe"] == 1
        assert store.get(KIND_POD, moved.meta.key).is_terminated
        survivors = sorted(
            p.meta.name for p in store.list(KIND_POD) if not p.is_terminated
        )
        assert survivors == ["r0"]
        assert "affinity" in out["profiles"] and "dedupe" in out["profiles"]
