"""Concurrency stress: the `-race` analog (reference Makefile runs `go test
-race` across the repo). Every multi-threaded koordlet component is hammered
by >=8 threads with invariants asserted afterwards: MetricCache
add/flush/restore, the live KoordletServer under parallel paged queries, and
ResourceUpdateExecutor batch updates against the fake cgroup tree."""

import json
import os
import threading
import urllib.request

import pytest

from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.metriccache import MetricCache
from koordinator_tpu.koordlet.resourceexecutor import (
    ResourceUpdater,
    ResourceUpdateExecutor,
)
from koordinator_tpu.koordlet.server import KoordletServer
from koordinator_tpu.koordlet.util.system import FakeFS

NOW = 1_000_000.0
THREADS = 8
OPS = 300


def run_threads(targets):
    """Start all, join all, re-raise the first exception from any thread."""
    errors = []

    def wrap(fn):
        def inner():
            try:
                fn()
            except BaseException as exc:  # noqa: BLE001 - reported via errors
                errors.append(exc)

        return inner

    threads = [threading.Thread(target=wrap(fn)) for fn in targets]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "stress thread hung"
    if errors:
        raise errors[0]


def test_metriccache_concurrent_add_flush_restore(tmp_path):
    path = os.fspath(tmp_path / "cache.pkl")
    cache = MetricCache(storage_path=path, retention_seconds=1e9)
    stop = threading.Event()

    def writer(tid):
        def run():
            for i in range(OPS):
                ts = NOW + i
                cache.add_sample(mc.POD_CPU_USAGE, float(i), timestamp=ts,
                                 pod=f"default/pod-{tid}")
                cache.add_sample(mc.NODE_CPU_USAGE, float(tid), timestamp=ts)
                if i % 50 == 0:
                    cache.set_kv(f"kv-{tid}", i)

        return run

    def flusher():
        while not stop.is_set():
            cache.flush(NOW)

    def reader():
        while not stop.is_set():
            cache.query(mc.NODE_CPU_USAGE, "p95", window=None, now=NOW + OPS)
            cache.series_labels(mc.POD_CPU_USAGE)

    workers = [writer(t) for t in range(THREADS)]
    aux = [threading.Thread(target=flusher) for _ in range(2)] + [
        threading.Thread(target=reader) for _ in range(2)
    ]
    for t in aux:
        t.start()
    try:
        run_threads(workers)
    finally:
        stop.set()
        for t in aux:
            t.join(timeout=60)
            assert not t.is_alive()

    # every writer's series is complete, the contended series saw every write
    for tid in range(THREADS):
        count = cache.query(mc.POD_CPU_USAGE, "count", now=NOW + OPS,
                            pod=f"default/pod-{tid}")
        assert count == OPS, f"writer {tid} lost samples: {count}"
    assert cache.query(mc.NODE_CPU_USAGE, "count", now=NOW + OPS) == THREADS * OPS

    # a final flush + cold restore reproduces the full state
    assert cache.flush(NOW)
    restored = MetricCache(storage_path=path, retention_seconds=1e9)
    for tid in range(THREADS):
        assert restored.query(mc.POD_CPU_USAGE, "count", now=NOW + OPS,
                              pod=f"default/pod-{tid}") == OPS
        assert restored.get_kv(f"kv-{tid}") == OPS - 50


def test_koordlet_server_under_parallel_queries():
    auditor = Auditor(capacity=100_000)
    server = KoordletServer(auditor)
    httpd, thread = server.serve(port=0)
    port = httpd.server_address[1]
    total_events = THREADS * OPS
    try:
        def recorder(tid):
            def run():
                for i in range(OPS):
                    auditor.record("info", f"group-{tid}", "cgroup_write",
                                   op=str(i))

            return run

        def pager():
            def run():
                token, seen = 0, 0
                while seen < total_events:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/apis/v1/audit"
                        f"?token={token}&size=200", timeout=10
                    ) as rsp:
                        assert rsp.status == 200
                        page = json.loads(rsp.read())
                    events = page["events"]
                    seqs = [e["seq"] for e in events]
                    # strictly increasing within a page, no duplicates
                    assert seqs == sorted(set(seqs))
                    seen += len(events)
                    token = page["next_token"]
                assert seen == total_events

            return run

        def health():
            for _ in range(OPS):
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=10
                ) as rsp:
                    assert rsp.read() == b"ok"

        run_threads([recorder(t) for t in range(THREADS)]
                    + [pager() for _ in range(4)] + [health] * 2)
    finally:
        httpd.shutdown()
        httpd.server_close()
        thread.join(timeout=10)

    events, _ = auditor.query(token=0, limit=total_events + 1)
    assert len(events) == total_events


@pytest.fixture
def fakefs():
    fs = FakeFS(use_cgroup_v2=True)
    yield fs
    fs.cleanup()


def test_resource_executor_concurrent_batches(fakefs):
    auditor = Auditor(capacity=100_000)
    executor = ResourceUpdateExecutor(fakefs.config, auditor)

    def worker(tid):
        def run():
            for i in range(OPS):
                # private file per thread + one contended shared file
                executor.update(ResourceUpdater(
                    f"kubepods/pod-{tid}", "cpu.max", f"{100000 + i} 100000",
                    level=1,
                ))
                executor.leveled_update_batch([
                    ResourceUpdater("kubepods", "cpu.weight", str(100 + i % 7),
                                    level=0),
                    ResourceUpdater(f"kubepods/pod-{tid}/ctr", "cpu.weight",
                                    str(i % 13), level=2),
                ])

        return run

    run_threads([worker(t) for t in range(THREADS)])

    # cache must be coherent with the files actually on disk — a torn or lost
    # write would leave them divergent and poison future redundant-write skips
    checked = 0
    for tid in range(THREADS):
        for rel, res in ((f"kubepods/pod-{tid}", "cpu.max"),
                         (f"kubepods/pod-{tid}/ctr", "cpu.weight")):
            cached = executor.cached_value(rel, res)
            assert cached is not None
            assert executor.read(rel, res) == cached
            checked += 1
    shared = executor.cached_value("kubepods", "cpu.weight")
    assert shared is not None and executor.read("kubepods", "cpu.weight") == shared
    assert checked == THREADS * 2
    # every successful write was audited
    events, _ = auditor.query(token=0, limit=100_000)
    assert all(e.operation == "cgroup_write" for e in events)
    assert len(events) >= THREADS * 2


def test_cri_proxy_under_parallel_kubelet_calls():
    """The CRI proxy's pod/container stores are hit by 8 parallel kubelet
    streams (create/start/update/stop across distinct sandboxes) — state must
    stay consistent and every forwarded request must carry its own pod's
    context."""
    import os
    import tempfile

    from koordinator_tpu.runtimeproxy import api_pb2, cri_pb2
    from koordinator_tpu.runtimeproxy.criserver import (
        CRIClient,
        CRIProxyServer,
        FakeContainerdServer,
    )
    from koordinator_tpu.runtimeproxy.hookclient import serve_hook_service

    class EchoHooks:
        """Returns the pod name back as an annotation so forwarded requests
        prove which pod context the hook saw."""

        def PreRunPodSandboxHook(self, request):
            res = api_pb2.PodSandboxHookResponse()
            res.annotations["seen"] = request.pod_meta.name
            return res

        def __getattr__(self, name):
            if name.endswith("Hook"):
                return lambda request: (
                    api_pb2.PodSandboxHookResponse() if "Sandbox" in name
                    else api_pb2.ContainerResourceHookResponse()
                )
            raise AttributeError(name)

    with tempfile.TemporaryDirectory() as tmp:
        proxy_sock = os.path.join(tmp, "p.sock")
        backend_sock = os.path.join(tmp, "b.sock")
        hook_sock = os.path.join(tmp, "h.sock")
        from koordinator_tpu.runtimeproxy.hookclient import HookClient

        hooks = serve_hook_service(EchoHooks(), hook_sock)
        backend = FakeContainerdServer(backend_sock)
        proxy = None
        results = {}

        def kubelet_stream(tid):
            def run():
                client = CRIClient(proxy_sock)
                try:
                    ids = []
                    for i in range(10):
                        req = cri_pb2.RunPodSandboxRequest()
                        req.config.metadata.name = f"pod-{tid}-{i}"
                        req.config.metadata.uid = f"uid-{tid}-{i}"
                        sandbox = client.call("RunPodSandbox", req)
                        creq = cri_pb2.CreateContainerRequest(
                            pod_sandbox_id=sandbox.pod_sandbox_id)
                        creq.config.metadata.name = "main"
                        created = client.call("CreateContainer", creq)
                        client.call("StartContainer",
                                    cri_pb2.StartContainerRequest(
                                        container_id=created.container_id))
                        ids.append((sandbox.pod_sandbox_id,
                                    created.container_id))
                    for sandbox_id, container_id in ids[:5]:
                        client.call(
                            "UpdateContainerResources",
                            cri_pb2.UpdateContainerResourcesRequest(
                                container_id=container_id,
                                linux=cri_pb2.LinuxContainerResources(
                                    cpu_quota=100000),
                            ),
                        )
                        client.call("StopContainer",
                                    cri_pb2.StopContainerRequest(
                                        container_id=container_id))
                        client.call("StopPodSandbox",
                                    cri_pb2.StopPodSandboxRequest(
                                        pod_sandbox_id=sandbox_id))
                    results[tid] = ids
                finally:
                    client.close()

            return run

        try:
            backend.start()
            proxy = CRIProxyServer(proxy_sock, backend_sock,
                                   hook_client=HookClient(hook_sock))
            proxy.start()
            run_threads([kubelet_stream(t) for t in range(THREADS)])
        finally:
            if proxy is not None:
                proxy.stop()
            backend.stop()
            hooks.stop(grace=None)

        # every stream completed its full lifecycle
        assert len(results) == THREADS
        # proxy stores: 5 sandboxes/containers alive per stream
        assert len(proxy.pod_store) == THREADS * 5
        assert len(proxy.container_store) == THREADS * 5
        # each forwarded sandbox carried ITS OWN pod's hook annotation
        for method, request in backend.requests:
            if method == "RunPodSandbox":
                assert (request.config.annotations["seen"]
                        == request.config.metadata.name)
