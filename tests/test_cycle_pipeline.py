"""Pipelined cycle (scheduler/cycle.CyclePipeline) semantics.

The pipeline reorders WHEN host work runs (non-blocking kernel dispatch,
condition writes deferred into the next cycle's kernel window); these
tests pin that it never changes WHAT the scheduler produces — the
serial-vs-pipelined parity harness (scheduler/pipeline_parity.py, also a
hack/lint.sh gate) plus targeted deferral/flush/env-gate behaviors."""

import numpy as np

from koordinator_tpu.api.objects import Node, ObjectMeta, Pod, PodSpec
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import KIND_NODE, KIND_POD, ObjectStore
from koordinator_tpu.scheduler.cycle import (
    CyclePipeline,
    Scheduler,
    pipeline_enabled_from_env,
)
from koordinator_tpu.scheduler.pipeline_parity import run_pipeline_parity

GIB = 1024 ** 3
NOW = 1_000_000.0


def make_store(num_nodes=3, cpu=8000):
    store = ObjectStore()
    for i in range(num_nodes):
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name=f"n{i}", namespace=""),
            allocatable=ResourceList.of(cpu=cpu, memory=32 * GIB, pods=20)))
    return store


def pend_pod(store, name, cpu=1000):
    pod = Pod(
        meta=ObjectMeta(name=name, uid=name, creation_timestamp=NOW),
        spec=PodSpec(requests=ResourceList.of(cpu=cpu, memory=GIB)))
    store.add(KIND_POD, pod)
    return pod


def cond(store, key):
    return store.get(KIND_POD, key).get_condition("PodScheduled")


def test_serial_vs_pipelined_parity_fixture():
    """The lint-gate fixture: identical bindings, failure sets and
    PodScheduled conditions through churn rounds (flush included)."""
    report = run_pipeline_parity()
    assert report["ok"], report["mismatches"]
    assert report["conditions_checked"] > 0


def test_condition_writes_defer_until_flush():
    store = make_store(num_nodes=1, cpu=2000)
    pend_pod(store, "fits", cpu=1000)
    pend_pod(store, "too-big", cpu=64000)  # no node can hold it
    sched = Scheduler(store)
    pipeline = CyclePipeline(sched, enabled=True)
    res = pipeline.run_cycle(now=NOW)
    # the verdict itself is computed in-cycle...
    assert "default/too-big" in res.failed
    assert [b.pod_key for b in res.bound] == ["default/fits"]
    # ...but the condition write is deferred (no kernel window ran after)
    assert cond(store, "default/too-big") is None
    assert len(sched._deferred_diagnose) == 1
    pipeline.flush()
    c = cond(store, "default/too-big")
    assert c is not None and c.status == "False"
    assert c.reason == "Unschedulable"
    assert not sched._deferred_diagnose


def test_deferred_flush_runs_in_next_kernel_window():
    store = make_store(num_nodes=1, cpu=2000)
    pend_pod(store, "too-big", cpu=64000)
    sched = Scheduler(store)
    pipeline = CyclePipeline(sched, enabled=True)
    pipeline.run_cycle(now=NOW)
    assert cond(store, "default/too-big") is None
    # next cycle has a kernel pass (a new pod arrives): the deferred write
    # lands during its overlap window without an explicit flush
    pend_pod(store, "late", cpu=500)
    pipeline.run_cycle(now=NOW + 2)
    c = cond(store, "default/too-big")
    assert c is not None and c.status == "False"
    # the condition carries cycle N's timestamp, not the flush time
    assert c.last_transition_time == NOW


def test_deferred_verdict_superseded_by_bind_is_skipped():
    """A pod that fails cycle N but binds in cycle N+1 must end with
    PodScheduled=True — the deferred False write never clobbers it."""
    store = make_store(num_nodes=1, cpu=2000)
    pend_pod(store, "wants-cap", cpu=4000)
    sched = Scheduler(store)
    pipeline = CyclePipeline(sched, enabled=True)
    res = pipeline.run_cycle(now=NOW)
    assert "default/wants-cap" in res.failed
    # capacity arrives; N+1 binds the pod, then flush drains N's verdict
    store.add(KIND_NODE, Node(
        meta=ObjectMeta(name="big", namespace=""),
        allocatable=ResourceList.of(cpu=64000, memory=64 * GIB, pods=20)))
    res2 = pipeline.run_cycle(now=NOW + 2)
    assert [b.pod_key for b in res2.bound] == ["default/wants-cap"]
    pipeline.flush()
    c = cond(store, "default/wants-cap")
    assert c is not None and c.status == "True"


def test_env_gate_disables_pipeline(monkeypatch):
    monkeypatch.setenv("KOORD_TPU_PIPELINE", "0")
    assert pipeline_enabled_from_env() is False
    store = make_store()
    sched = Scheduler(store)
    pipeline = CyclePipeline(sched)  # enabled=None -> env decides
    assert pipeline.enabled is False
    assert sched.pipeline_mode is False
    # serial fallback writes conditions inline, exactly the old behavior
    pend_pod(store, "too-big", cpu=64000)
    pipeline.run_cycle(now=NOW)
    c = cond(store, "default/too-big")
    assert c is not None and c.status == "False"
    monkeypatch.delenv("KOORD_TPU_PIPELINE")
    assert pipeline_enabled_from_env() is True


def test_pipeline_spans_and_device_busy():
    store = make_store()
    pend_pod(store, "a", cpu=500)
    sched = Scheduler(store)
    pipeline = CyclePipeline(sched, enabled=True)
    res = pipeline.run_cycle(now=NOW)
    assert res.device_busy_seconds > 0
    root = sched.tracer.roots(limit=1)[0]
    assert root.find("pack_incremental") is not None
    kernel = root.find("kernel")
    assert kernel is not None
    assert kernel.find("overlap_wait") is not None


def test_pack_incremental_counters_and_upload_gauges():
    from koordinator_tpu.scheduler import metrics as m

    store = make_store()
    for i in range(4):
        pend_pod(store, f"p{i}", cpu=500)
    sched = Scheduler(store)
    pipeline = CyclePipeline(sched, enabled=True)
    pipeline.run_cycle(now=NOW)
    # steady state: a carried-over pending pod must reuse its packed row
    reused_before = sched.snapshot_cache.stats["pod_row_hits"]
    pend_pod(store, "fresh", cpu=64000)  # stays pending across cycles
    pipeline.run_cycle(now=NOW + 2)
    pipeline.run_cycle(now=NOW + 4)
    assert sched.snapshot_cache.stats["pod_row_hits"] > reused_before
    # pack counters + upload gauges land in the Prometheus exposition
    text = m.REGISTRY.expose()
    assert "koord_scheduler_pack_rows_reused_total" in text
    assert "koord_scheduler_pack_rows_repacked_total" in text
    assert "koord_scheduler_upload_fields_reused_total" in text
    assert "koord_scheduler_upload_bytes_put_total" in text
    pipeline.flush()


def test_carried_deferred_drains_on_kernel_less_cycle():
    """A cycle with no kernel window (empty pending queue) must drain
    carried-over deferred writes instead of letting them linger."""
    store = make_store(num_nodes=1, cpu=2000)
    pend_pod(store, "too-big", cpu=64000)
    sched = Scheduler(store)
    pipeline = CyclePipeline(sched, enabled=True)
    pipeline.run_cycle(now=NOW)
    assert len(sched._deferred_diagnose) == 1
    # the failed pod leaves the queue entirely; the next cycle has nothing
    # to schedule and therefore no overlap window
    store.delete(KIND_POD, "default/too-big")
    pipeline.run_cycle(now=NOW + 2)
    assert not sched._deferred_diagnose, (
        "kernel-less cycles must not strand deferred writes")


def test_deferred_write_skips_recreated_pod_with_new_uid():
    """Delete + recreate under the same key between cycles: the old
    incarnation's deferred verdict must not stamp the new pod."""
    store = make_store(num_nodes=1, cpu=2000)
    pend_pod(store, "stateful-0", cpu=64000)
    sched = Scheduler(store)
    pipeline = CyclePipeline(sched, enabled=True)
    res = pipeline.run_cycle(now=NOW)
    assert "default/stateful-0" in res.failed
    store.delete(KIND_POD, "default/stateful-0")
    fresh = Pod(
        meta=ObjectMeta(name="stateful-0", uid="reborn",
                        creation_timestamp=NOW + 1),
        spec=PodSpec(requests=ResourceList.of(cpu=64000, memory=GIB)))
    store.add(KIND_POD, fresh)
    # flush the OLD verdict explicitly: the uid guard must skip the write
    pipeline.flush()
    assert cond(store, "default/stateful-0") is None
    # the recreated pod earns its OWN verdict with its own timestamp
    pipeline.run_cycle(now=NOW + 4)
    pipeline.flush()
    c = cond(store, "default/stateful-0")
    assert c is not None and c.status == "False"
    assert c.last_transition_time == NOW + 4
