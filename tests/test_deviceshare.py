"""DeviceShare: per-type handlers (GPU/RDMA/FPGA), fractional + whole-GPU
mixes, memory-only requests, NUMA hints, and joint GPU+RDMA allocation
(ref plugins/deviceshare/device_allocator.go, topology_hint.go)."""

import json

import pytest

from koordinator_tpu.api.objects import (
    ANNOTATION_DEVICE_ALLOCATED,
    Device,
    DeviceInfo,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.api.resources import ResourceList, ResourceName
from koordinator_tpu.client.store import KIND_DEVICE, ObjectStore
from koordinator_tpu.scheduler.frameworkext import CycleContext
from koordinator_tpu.scheduler.plugins.deviceshare import (
    DeviceSharePlugin,
    pod_device_requests,
)
from koordinator_tpu.scheduler.topologymanager import BitMask, NUMATopologyHint

GIB = 1024**3


def _plugin(num_gpus=4, num_rdma=2, gpu_numa=None, rdma_numa=None,
            gpu_mem=16 * GIB):
    store = ObjectStore()
    plugin = DeviceSharePlugin()
    plugin.register(store)
    devices = []
    for i in range(num_gpus):
        numa = gpu_numa[i] if gpu_numa else -1
        devices.append(DeviceInfo(
            type="gpu", minor=i, numa_node=numa,
            resources=ResourceList.of(gpu_core=100, gpu_memory=gpu_mem)))
    for i in range(num_rdma):
        numa = rdma_numa[i] if rdma_numa else -1
        devices.append(DeviceInfo(type="rdma", minor=i, numa_node=numa))
    store.add(KIND_DEVICE, Device(meta=ObjectMeta(name="node-0", namespace=""),
                                  devices=devices))
    return plugin, store


def _pod(name="p", **resources):
    return Pod(meta=ObjectMeta(name=name),
               spec=PodSpec(requests=ResourceList.of(**resources)))


class TestRequests:
    def test_whole_gpu_form(self):
        pod = _pod(gpu=2)
        want = pod_device_requests(pod)
        assert want == {"gpu": {"core": 200, "memory_ratio": 200}}

    def test_rdma_fpga_counts(self):
        pod = _pod(rdma=1, fpga=2)
        assert pod_device_requests(pod) == {
            "rdma": {"count": 1}, "fpga": {"count": 2}}


class TestGPUAllocation:
    def test_whole_plus_fractional_mix(self):
        """Fractional pods pack (MostAllocated) so whole-GPU pods still fit."""
        plugin, _ = _plugin(num_gpus=2)
        ctx = CycleContext(now=0.0)
        frac_a = _pod("frac-a", gpu_core=30, gpu_memory_ratio=30)
        frac_b = _pod("frac-b", gpu_core=30, gpu_memory_ratio=30)
        assert plugin.reserve(frac_a, "node-0", ctx) is None
        assert plugin.reserve(frac_b, "node-0", ctx) is None
        # both fractions packed onto one GPU
        a = plugin.by_pod[frac_a.meta.key]["gpu"][0]["minor"]
        b = plugin.by_pod[frac_b.meta.key]["gpu"][0]["minor"]
        assert a == b
        whole = _pod("whole", gpu=1)
        assert plugin.reserve(whole, "node-0", ctx) is None
        w = plugin.by_pod[whole.meta.key]["gpu"][0]
        assert w["minor"] != a and w["core"] == 100

    def test_whole_gpu_skips_partially_used(self):
        """A 2-GPU request must not strand on partially-free GPUs."""
        plugin, _ = _plugin(num_gpus=3)
        ctx = CycleContext(now=0.0)
        assert plugin.reserve(
            _pod("frac", gpu_core=10, gpu_memory_ratio=10), "node-0", ctx
        ) is None
        two = _pod("two", gpu=2)
        assert plugin.reserve(two, "node-0", ctx) is None
        minors = {p["minor"] for p in plugin.by_pod[two.meta.key]["gpu"]}
        assert len(minors) == 2
        frac_minor = plugin.by_pod["default/frac"]["gpu"][0]["minor"]
        assert frac_minor not in minors

    def test_insufficient_whole_gpus(self):
        plugin, _ = _plugin(num_gpus=2)
        ctx = CycleContext(now=0.0)
        assert plugin.reserve(
            _pod("frac", gpu_core=10, gpu_memory_ratio=10), "node-0", ctx
        ) is None
        err = plugin.reserve(_pod("two", gpu=2), "node-0", ctx)
        assert err == "insufficient whole gpus"
        # failed reserve rolled back: nothing leaked
        assert "default/two" not in plugin.by_pod

    def test_memory_only_request_allocates(self):
        """gpu-memory without gpu-core must still pick a device
        (round-1 gap: memory-only requests bypassed allocation)."""
        plugin, _ = _plugin(num_gpus=2, gpu_mem=16 * GIB)
        ctx = CycleContext(now=0.0)
        pod = _pod("memonly", gpu_memory=8 * GIB)
        assert plugin.reserve(pod, "node-0", ctx) is None
        pick = plugin.by_pod[pod.meta.key]["gpu"][0]
        assert pick["memory"] == 8 * GIB and pick["core"] == 0

    def test_memory_only_capacity_respected(self):
        plugin, _ = _plugin(num_gpus=1, gpu_mem=8 * GIB)
        ctx = CycleContext(now=0.0)
        assert plugin.reserve(
            _pod("m1", gpu_memory=6 * GIB), "node-0", ctx) is None
        err = plugin.reserve(_pod("m2", gpu_memory=6 * GIB), "node-0", ctx)
        assert err == "insufficient gpu capacity"

    def test_memory_only_blocks_whole_gpu(self):
        """Memory and memory-ratio are views of one capacity: a memory-only
        grant must stop a later whole-GPU grant on the same device."""
        plugin, _ = _plugin(num_gpus=1, gpu_mem=16 * GIB)
        ctx = CycleContext(now=0.0)
        assert plugin.reserve(
            _pod("memonly", gpu_memory=8 * GIB), "node-0", ctx) is None
        err = plugin.reserve(_pod("whole", gpu=1), "node-0", ctx)
        assert err == "insufficient gpu capacity"

    def test_ratio_and_memory_axes_stay_in_sync(self):
        """A ratio grant books the equivalent bytes and vice versa, so the
        two forms cannot double-book the device's memory."""
        plugin, _ = _plugin(num_gpus=1, gpu_mem=16 * GIB)
        ctx = CycleContext(now=0.0)
        assert plugin.reserve(
            _pod("ratio", gpu_core=50, gpu_memory_ratio=75), "node-0", ctx
        ) is None
        # 75% of 16GiB booked as bytes too: a 8GiB memory-only ask must fail
        err = plugin.reserve(_pod("mem", gpu_memory=8 * GIB), "node-0", ctx)
        assert err == "insufficient gpu capacity"

    def test_invalid_core_above_100(self):
        plugin, _ = _plugin()
        err = plugin.reserve(
            _pod("bad", gpu_core=150, gpu_memory_ratio=150), "node-0",
            CycleContext(now=0.0))
        assert "multiple of 100" in err

    def test_unreserve_releases(self):
        plugin, _ = _plugin(num_gpus=1)
        ctx = CycleContext(now=0.0)
        pod = _pod("p", gpu=1)
        assert plugin.reserve(pod, "node-0", ctx) is None
        plugin.unreserve(pod, "node-0", ctx)
        assert plugin.reserve(_pod("q", gpu=1), "node-0", ctx) is None


class TestRDMAAndJoint:
    def test_rdma_whole_device(self):
        plugin, _ = _plugin(num_rdma=2)
        ctx = CycleContext(now=0.0)
        pod = _pod("r", rdma=1)
        assert plugin.reserve(pod, "node-0", ctx) is None
        assert len(plugin.by_pod[pod.meta.key]["rdma"]) == 1
        assert plugin.reserve(_pod("r2", rdma=2), "node-0", ctx) == (
            "insufficient rdma devices")

    def test_joint_gpu_rdma_numa_aligned(self):
        """RDMA picks prefer the NUMA node of the allocated GPUs
        (jointAllocate, device_allocator.go:278-331)."""
        plugin, _ = _plugin(
            num_gpus=2, num_rdma=2, gpu_numa=[0, 1], rdma_numa=[0, 1])
        ctx = CycleContext(now=0.0)
        # force the GPU onto numa 1 by occupying gpu 0
        assert plugin.reserve(
            _pod("filler", gpu_core=100, gpu_memory_ratio=100), "node-0", ctx
        ) is None
        pod = _pod("joint", gpu=1, rdma=1)
        assert plugin.reserve(pod, "node-0", ctx) is None
        gpu_pick = plugin.by_pod[pod.meta.key]["gpu"][0]
        rdma_pick = plugin.by_pod[pod.meta.key]["rdma"][0]
        assert gpu_pick["minor"] == 1
        assert rdma_pick["minor"] == 1  # numa 1, same as the gpu

    def test_prebind_annotation_covers_all_types(self):
        plugin, _ = _plugin(num_gpus=1, num_rdma=1)
        ctx = CycleContext(now=0.0)
        pod = _pod("j", gpu=1, rdma=1)
        assert plugin.reserve(pod, "node-0", ctx) is None
        ann = {}
        plugin.pre_bind(pod, "node-0", ctx, ann)
        alloc = json.loads(ann[ANNOTATION_DEVICE_ALLOCATED])
        assert alloc["gpu"][0]["core"] == 100
        assert alloc["rdma"][0]["minor"] == 0


class TestTopologyHints:
    def test_hints_prefer_single_numa(self):
        plugin, _ = _plugin(num_gpus=4, gpu_numa=[0, 0, 1, 1])
        hints = plugin.get_pod_topology_hints(_pod("p", gpu=2), "node-0")
        gpu_hints = hints["device/gpu"]
        masks = {tuple(h.affinity.get_bits()): h.preferred for h in gpu_hints}
        # both single-node placements fit and are preferred
        assert masks[(0,)] and masks[(1,)]
        assert not masks[(0, 1)]

    def test_hints_widen_when_single_node_cannot_fit(self):
        plugin, _ = _plugin(num_gpus=2, gpu_numa=[0, 1])
        hints = plugin.get_pod_topology_hints(_pod("p", gpu=2), "node-0")
        gpu_hints = hints["device/gpu"]
        assert len(gpu_hints) == 1
        assert tuple(gpu_hints[0].affinity.get_bits()) == (0, 1)
        assert gpu_hints[0].preferred

    def test_no_topology_is_dont_care(self):
        plugin, _ = _plugin(num_gpus=2)  # numa_node -1 everywhere
        hints = plugin.get_pod_topology_hints(_pod("p", gpu=1), "node-0")
        assert hints["device/gpu"] is None

    def test_memory_only_hints_respect_memory(self):
        """Hints must not prefer a NUMA node whose GPUs are memory-full."""
        plugin, _ = _plugin(num_gpus=2, gpu_numa=[0, 1], gpu_mem=16 * GIB)
        ctx = CycleContext(now=0.0)
        # fill gpu 0's memory (numa 0)
        assert plugin.reserve(
            _pod("filler", gpu_memory=16 * GIB), "node-0", ctx) is None
        assert plugin.by_pod["default/filler"]["gpu"][0]["minor"] == 0
        hints = plugin.get_pod_topology_hints(
            _pod("p", gpu_memory=8 * GIB), "node-0")
        masks = {tuple(h.affinity.get_bits()) for h in hints["device/gpu"]
                 if h.preferred and h.affinity.count() == 1}
        assert masks == {(1,)}

    def test_affinity_restricts_reserve(self):
        """The merged affinity from the topologymanager confines picks."""
        plugin, _ = _plugin(num_gpus=2, gpu_numa=[0, 1])
        ctx = CycleContext(now=0.0)
        pod = _pod("pinned", gpu=1)
        plugin.allocate(pod, "node-0", NUMATopologyHint(BitMask([1]), True))
        assert plugin.reserve(pod, "node-0", ctx) is None
        assert plugin.by_pod[pod.meta.key]["gpu"][0]["minor"] == 1
