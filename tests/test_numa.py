"""NUMA admit kernel + host cpuset accumulator tests."""

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.resources import (
    NUM_RESOURCES,
    RESOURCE_INDEX,
    ResourceList,
    ResourceName,
)
from koordinator_tpu.ops.numa import (
    POLICY_BEST_EFFORT,
    POLICY_NONE,
    POLICY_SINGLE_NUMA_NODE,
    numa_admit_row,
    numa_spread_fill,
)
from koordinator_tpu.scheduler.cpu_topology import (
    EXCLUSIVE_NUMA,
    EXCLUSIVE_PCPU,
    FULL_PCPUS,
    SPREAD_BY_PCPUS,
    CPUAllocationState,
    CPUTopology,
    take_cpus,
)

CPU = RESOURCE_INDEX[ResourceName.CPU]


def _numa_free(per_node_zones):
    """[N, K, R] from list of lists of cpu-milli frees."""
    n = len(per_node_zones)
    k = max(len(z) for z in per_node_zones)
    arr = np.zeros((n, k, NUM_RESOURCES), np.float32)
    for i, zones in enumerate(per_node_zones):
        for j, cpu in enumerate(zones):
            arr[i, j, CPU] = cpu
    return jnp.asarray(arr)


class TestNUMAAdmit:
    def test_single_numa_node_policy(self):
        free = _numa_free([[4000, 1000], [2000, 2000]])
        req = jnp.asarray(ResourceList.of(cpu=3000).to_vector())
        ok, zone = numa_admit_row(
            req, jnp.bool_(True), free, jnp.asarray([POLICY_SINGLE_NUMA_NODE] * 2)
        )
        assert list(np.asarray(ok)) == [True, False]  # node1: no single zone fits
        assert int(zone[0]) == 0

    def test_total_fit_policies(self):
        free = _numa_free([[2000, 2000]])
        req = jnp.asarray(ResourceList.of(cpu=3000).to_vector())
        for policy in (POLICY_BEST_EFFORT, POLICY_NONE):
            ok, zone = numa_admit_row(
                req, jnp.bool_(True), free, jnp.asarray([policy])
            )
            assert bool(ok[0])
            assert int(zone[0]) == -1

    def test_not_subject_pods_skip(self):
        free = _numa_free([[0, 0]])
        req = jnp.asarray(ResourceList.of(cpu=3000).to_vector())
        ok, _ = numa_admit_row(
            req, jnp.bool_(False), free, jnp.asarray([POLICY_SINGLE_NUMA_NODE])
        )
        assert bool(ok[0])

    def test_spread_fill_waterfall(self):
        free = np.zeros((2, NUM_RESOURCES), np.float32)
        free[0, CPU], free[1, CPU] = 2000, 3000
        req = np.zeros(NUM_RESOURCES, np.float32)
        req[CPU] = 2500
        out = np.asarray(
            numa_spread_fill(jnp.asarray(free), jnp.asarray(req), jnp.int32(-1))
        )
        assert out[0, CPU] == 0.0 and out[1, CPU] == 2500.0

    def test_single_zone_fill(self):
        free = np.zeros((2, NUM_RESOURCES), np.float32)
        free[0, CPU], free[1, CPU] = 4000, 3000
        req = np.zeros(NUM_RESOURCES, np.float32)
        req[CPU] = 2000
        out = np.asarray(
            numa_spread_fill(jnp.asarray(free), jnp.asarray(req), jnp.int32(1))
        )
        assert out[0, CPU] == 4000.0 and out[1, CPU] == 1000.0


class TestCPUAccumulator:
    def topo(self):
        # 1 socket, 2 numa nodes, 4 cores/node, 2 threads -> 16 cpus
        return CPUTopology.build(1, 2, 4, 2)

    def test_full_pcpus_takes_whole_cores(self):
        topo = self.topo()
        state = CPUAllocationState(topo)
        got = take_cpus(state, 4, bind_policy=FULL_PCPUS)
        assert got is not None and len(got) == 4
        cores = {topo.by_id[c].core_id for c in got}
        assert len(cores) == 2  # 2 full cores of 2 threads
        for core in cores:
            assert all(c in got for c in topo.cores()[core])

    def test_spread_by_pcpus(self):
        topo = self.topo()
        state = CPUAllocationState(topo)
        got = take_cpus(state, 4, bind_policy=SPREAD_BY_PCPUS)
        assert got is not None and len(got) == 4
        cores = {topo.by_id[c].core_id for c in got}
        assert len(cores) == 4  # one cpu per core

    def test_exclusive_pcpu_avoids_taken_cores(self):
        topo = self.topo()
        state = CPUAllocationState(topo)
        first = take_cpus(state, 2, FULL_PCPUS, EXCLUSIVE_PCPU)
        state.add("pod-a", first, EXCLUSIVE_PCPU)
        second = take_cpus(state, 2, FULL_PCPUS, EXCLUSIVE_PCPU)
        assert second is not None
        assert not first.intersection(second)
        first_cores = {topo.by_id[c].core_id for c in first}
        second_cores = {topo.by_id[c].core_id for c in second}
        assert not first_cores & second_cores

    def test_exclusive_numa_level(self):
        topo = self.topo()
        state = CPUAllocationState(topo)
        first = take_cpus(state, 8, FULL_PCPUS, EXCLUSIVE_NUMA)
        state.add("pod-a", first, EXCLUSIVE_NUMA)
        numa_a = {topo.by_id[c].numa_node_id for c in first}
        assert len(numa_a) == 1
        second = take_cpus(state, 8, FULL_PCPUS, EXCLUSIVE_NUMA)
        assert second is not None
        numa_b = {topo.by_id[c].numa_node_id for c in second}
        assert not numa_a & numa_b
        # no room for a third exclusive numa allocation
        state.add("pod-b", second, EXCLUSIVE_NUMA)
        assert take_cpus(state, 2, FULL_PCPUS, EXCLUSIVE_NUMA) is None

    def test_numa_affinity_restriction(self):
        topo = self.topo()
        state = CPUAllocationState(topo)
        got = take_cpus(state, 4, FULL_PCPUS, numa_affinity=[1])
        assert got is not None
        assert {topo.by_id[c].numa_node_id for c in got} == {1}
        assert take_cpus(state, 10, FULL_PCPUS, numa_affinity=[1]) is None

    def test_insufficient_returns_none(self):
        state = CPUAllocationState(self.topo())
        assert take_cpus(state, 17) is None

    def test_max_ref_count_sharing(self):
        topo = self.topo()
        state = CPUAllocationState(topo, max_ref_count=2)
        a = take_cpus(state, 16, FULL_PCPUS)
        state.add("pod-a", a, "")
        b = take_cpus(state, 8, FULL_PCPUS)
        assert b is not None and len(b) == 8  # shares up to refcount 2
        state.add("pod-b", b, "")
        state.remove("pod-a")
        c = take_cpus(state, 16, FULL_PCPUS)
        assert c is not None


def test_num_available_matches_set_even_with_foreign_ids():
    """num_available() == len(available_cpus()) including when allocation
    book-keeping holds cpu ids absent from the topology (inconsistent CR)."""
    from koordinator_tpu.scheduler.cpu_topology import (
        CPUAllocationState,
        CPUTopology,
    )
    from koordinator_tpu.utils.cpuset import CPUSet

    topo = CPUTopology.build(1, 1, 4, 2)  # 8 cpus
    state = CPUAllocationState(topo)
    state.add("default/p", CPUSet([0, 1]), "none")
    state.add("reserved", CPUSet([99]), "none")  # id not in the topology
    assert state.num_available() == len(state.available_cpus()) == 6
