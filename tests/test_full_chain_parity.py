"""Full-chain parity: fused kernel vs scalar oracle over NUMA + quota + gang
configs (BASELINE configs 2-4 shapes, scaled down)."""

import numpy as np
import pytest

from koordinator_tpu.models.full_chain import build_full_chain_step
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.scheduler.parity import diff_bindings, serial_schedule_full
from koordinator_tpu.scheduler.snapshot import build_full_chain_inputs
from koordinator_tpu.testing import synth_full_cluster


def _run(seed, num_nodes=30, num_pods=60, args=None, **kw):
    args = args or LoadAwareArgs()
    cluster, state = synth_full_cluster(num_nodes, num_pods, seed=seed, **kw)
    fc, pods, nodes, tree, gang_index, ng, ngroups = build_full_chain_inputs(
        state, args
    )
    step = build_full_chain_step(args, ng, ngroups)
    chosen_tpu, requested, quota_used = step(fc)
    chosen_tpu = np.asarray(chosen_tpu)
    chosen_serial = serial_schedule_full(fc, args)
    return pods, chosen_tpu, chosen_serial


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_full_chain_bindings_match(seed):
    pods, chosen_tpu, chosen_serial = _run(seed)
    diffs = diff_bindings(
        chosen_serial[: len(pods.keys)], chosen_tpu[: len(pods.keys)], pods.keys
    )
    assert not diffs, f"{len(diffs)} mismatches: {diffs[:10]}"
    assert (chosen_serial >= 0).sum() > 0


def test_full_chain_no_quota_no_gang():
    pods, chosen_tpu, chosen_serial = _run(9, num_quotas=0, num_gangs=0)
    diffs = diff_bindings(
        chosen_serial[: len(pods.keys)], chosen_tpu[: len(pods.keys)], pods.keys
    )
    assert not diffs, diffs[:10]


def test_full_chain_all_topology():
    pods, chosen_tpu, chosen_serial = _run(5, topology_fraction=1.0, lsr_fraction=0.4)
    diffs = diff_bindings(
        chosen_serial[: len(pods.keys)], chosen_tpu[: len(pods.keys)], pods.keys
    )
    assert not diffs, diffs[:10]


def test_quota_constrains_admission():
    """A tight quota must reduce scheduled count vs no quota."""
    from koordinator_tpu.api.resources import ResourceList

    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(20, 60, seed=11, num_gangs=0)
    # clamp every leaf quota max to ~1 small pod
    for q in state.quotas:
        if q.meta.name.startswith("job-"):
            q.max = ResourceList.of(cpu=300, memory=2**60)
            q.min = ResourceList.of(cpu=0)
    fc, pods, nodes, tree, gi, ng, ngr = build_full_chain_inputs(state, args)
    chosen = np.asarray(build_full_chain_step(args, ng, ngr)(fc)[0])
    quota_ids = np.asarray(fc.quota_id)[: len(pods.keys)]
    in_quota = quota_ids >= 0
    sched_in_quota = (chosen[: len(pods.keys)] >= 0) & in_quota
    # most quota-bound pods must be rejected by admission
    assert sched_in_quota.sum() < in_quota.sum() / 2
    # parity still holds under pressure
    chosen_serial = serial_schedule_full(fc, args)
    assert not diff_bindings(
        chosen_serial[: len(pods.keys)], chosen[: len(pods.keys)], pods.keys
    )


def test_gang_all_or_nothing_end_to_end():
    """Gangs that can't reach min member must be fully struck: every gang ends
    with 0 scheduled members or at least min_member."""
    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(4, 40, seed=13)
    fc, pods, nodes, tree, gang_index, ng, ngroups = build_full_chain_inputs(
        state, args
    )
    chosen = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    chosen_serial = serial_schedule_full(fc, args)
    assert (chosen[: len(pods.keys)] == chosen_serial[: len(pods.keys)]).all()

    gang_id = np.asarray(fc.gang_id)[: len(pods.keys)]
    gang_min = np.asarray(fc.gang_min_member)
    counts = np.zeros(ng)
    members = np.zeros(ng)
    for i in range(len(pods.keys)):
        if gang_id[i] >= 0:
            members[gang_id[i]] += 1
            if chosen[i] >= 0:
                counts[gang_id[i]] += 1
    assert members.sum() > 0, "synth produced no gang members"
    struck = 0
    for g in range(ng):
        if members[g] == 0:
            continue
        assert counts[g] == 0 or counts[g] >= gang_min[g], (
            f"gang {g}: {counts[g]} scheduled < min {gang_min[g]}"
        )
        if counts[g] == 0:
            struck += 1
    # on a tiny 4-node cluster some gangs must actually fail (else the barrier
    # was never exercised)
    assert struck > 0


def test_active_axis_reduction_preserves_bindings():
    """Slicing to active resource axes must not change bindings."""
    from koordinator_tpu.scheduler.snapshot import reduce_to_active_axes

    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(25, 50, seed=21)
    fc, pods, nodes, tree, gi, ng, ngr = build_full_chain_inputs(state, args)
    full = np.asarray(build_full_chain_step(args, ng, ngr)(fc)[0])
    fc_red, active = reduce_to_active_axes(fc)
    assert len(active) < fc.requests.shape[-1]
    red = np.asarray(
        build_full_chain_step(args, ng, ngr, active_axes=active)(fc_red)[0]
    )
    np.testing.assert_array_equal(full, red)
    # and the serial oracle agrees on the reduced arrays too
    serial = serial_schedule_full(fc_red, args, active_axes=active)
    np.testing.assert_array_equal(red[: len(pods.keys)], serial[: len(pods.keys)])


def test_full_chain_with_taints():
    """TaintToleration in the chain: tainted nodes reject intolerant pods in
    kernel, oracle, and the C++ floor identically."""
    import numpy as np

    from koordinator_tpu.native import floor as native_floor

    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(24, 60, seed=21, taint_fraction=0.4)
    assert any(n.taints for n in state.nodes), "fixture produced no taints"
    fc, pods, nodes, tree, gang_index, ng, ngroups = build_full_chain_inputs(
        state, args
    )
    step = build_full_chain_step(args, ng, ngroups)
    chosen_tpu = np.asarray(step(fc)[0])
    chosen_serial = serial_schedule_full(fc, args)
    diffs = diff_bindings(
        chosen_serial[: len(pods.keys)], chosen_tpu[: len(pods.keys)],
        pods.keys,
    )
    assert not diffs, f"{len(diffs)} mismatches: {diffs[:10]}"

    # no pod landed on a node whose taints it does not tolerate
    from koordinator_tpu.ops.taints import tolerates_taints

    pods_by_key = {p.meta.key: p for p in state.pending_pods}
    placements = 0
    tainted_placements = 0
    for i, key in enumerate(pods.keys):
        n = chosen_tpu[i]
        if n < 0:
            continue
        placements += 1
        node = state.nodes[n]
        if node.taints:
            tainted_placements += 1
            assert tolerates_taints(pods_by_key[key].spec.tolerations,
                                    node.taints), (key, node.meta.name)
    assert placements > 0
    assert tainted_placements > 0, "no tolerant pod exercised a tainted node"

    if native_floor.available() or native_floor.build():
        chosen_native = native_floor.serial_schedule_full_native(fc, args)
        np.testing.assert_array_equal(chosen_serial, chosen_native)


def test_full_chain_with_node_selector():
    """NodeAffinity (nodeSelector) rides the admission-group bit test: pods
    with a selector bind only to label-matching nodes, bit-identically in
    kernel, oracle, and the C++ floor."""
    from koordinator_tpu.native import floor as native_floor

    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(24, 60, seed=33)
    # carve the cluster into two label pools and pin a third of the pods
    for j, node in enumerate(state.nodes):
        node.meta.labels["pool"] = "gold" if j % 3 == 0 else "silver"
    pending = state.pending_pods
    for i, pod in enumerate(pending):
        if i % 3 == 0:
            pod.spec.node_selector["pool"] = "gold"
    fc, pods, nodes, tree, gang_index, ng, ngroups = build_full_chain_inputs(
        state, args
    )
    step = build_full_chain_step(args, ng, ngroups)
    chosen_tpu = np.asarray(step(fc)[0])
    chosen_serial = serial_schedule_full(fc, args)
    diffs = diff_bindings(
        chosen_serial[: len(pods.keys)], chosen_tpu[: len(pods.keys)],
        pods.keys,
    )
    assert not diffs, f"{len(diffs)} mismatches: {diffs[:10]}"

    pods_by_key = {p.meta.key: p for p in pending}
    selector_placements = 0
    for i, key in enumerate(pods.keys):
        n = chosen_tpu[i]
        if n < 0:
            continue
        pod = pods_by_key[key]
        node = state.nodes[n]
        for k, v in pod.spec.node_selector.items():
            assert node.meta.labels.get(k) == v, (key, node.meta.name)
        if pod.spec.node_selector:
            selector_placements += 1
    assert selector_placements > 0, "no selector pod was placed"

    if native_floor.available() or native_floor.build():
        chosen_native = native_floor.serial_schedule_full_native(fc, args)
        np.testing.assert_array_equal(chosen_serial, chosen_native)
