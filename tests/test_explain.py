"""koordexplain (PR 5): on-device decision attribution, the per-pod
explain surfaces and the cycle flight recorder.

The acceptance gates live here: formatter-over-kernel-counts must match
the legacy host-numpy diagnose_unbound string-for-string on a churn
workload (serial AND fused), attribution must not perturb a single
decision, and the flight-recorder bundle must validate against its
schema — plus the HTTP/CLI surfaces and the new metrics."""

import json

import numpy as np
import pytest

from koordinator_tpu.client.store import KIND_POD
from koordinator_tpu.obs.flight import (
    FLIGHT_SCHEMA_VERSION,
    FlightRecorder,
    load_bundle,
    validate_cycle_record,
    validate_header,
)
from koordinator_tpu.obs.server import ObsServer
from koordinator_tpu.scheduler import metrics as scheduler_metrics
from koordinator_tpu.scheduler.cycle import (
    CyclePipeline,
    Scheduler,
    cycle_deadline_from_env,
    explain_from_env,
)
from koordinator_tpu.scheduler.pipeline_parity import (
    apply_round_delta,
    build_store_from_state,
    run_explain_parity,
    run_fused_wave_parity,
    run_pipeline_parity,
)
from koordinator_tpu.testing import synth_full_cluster

NOW = 1_000_000.0


def make_world(nodes=16, pods=50, seed=5):
    _cluster, state = synth_full_cluster(
        nodes, pods, seed=seed, num_quotas=3, num_gangs=4,
        topology_fraction=0.5, lsr_fraction=0.2)
    return state, build_store_from_state(state)


# ---------------------------------------------------------------------------
# acceptance gates: kernel counts vs legacy diagnosis, byte-for-byte
# ---------------------------------------------------------------------------


def test_explain_parity_serial_churn():
    """Formatter-over-kernel-counts == legacy host-numpy diagnose_unbound
    string-for-string on a churn workload (the tier-1 pin)."""
    report = run_explain_parity()
    assert report["ok"], report["mismatches"]
    assert report["conditions_checked"] > 0


def test_explain_parity_fused_waves():
    report = run_explain_parity(waves=4, rounds=2)
    assert report["ok"], report["mismatches"]


def test_pipeline_parity_with_explain_enabled():
    """The PR 3 gate must stay byte-identical with explain=counts on."""
    report = run_pipeline_parity(rounds=2, explain="counts")
    assert report["ok"], report["mismatches"]


def test_fused_wave_parity_with_explain_enabled():
    """The PR 4 gate must stay byte-identical with explain=counts on."""
    report = run_fused_wave_parity(4, explain="counts")
    assert report["ok"], report["mismatches"]


# ---------------------------------------------------------------------------
# attribution content: /explain records, terms, metrics
# ---------------------------------------------------------------------------


def _run_rounds(sched, store, state, rounds=3, arrivals=8):
    results = []
    for r in range(rounds):
        if r:
            apply_round_delta(store, r, state.now, arrivals)
        results.append(sched.run_cycle(now=state.now + 2 * r))
    return results


def test_explain_index_and_full_terms():
    state, store = make_world()
    sched = Scheduler(store, waves=1, explain="full")
    results = _run_rounds(sched, store, state)
    assert any(r.bound for r in results)
    bound_recs = [v for v in sched.explain_index.values()
                  if v["verdict"] == "bound"]
    assert bound_recs, "bound pods must be attributed"
    with_terms = [v for v in bound_recs if "terms" in v]
    assert with_terms, "full level must attach score terms"
    terms = with_terms[0]["terms"]
    assert set(terms) == {"LoadAware", "NodeNUMAResource", "Preferred",
                          "best_score", "runner_up"}
    # the plugin terms must reconstruct the winning score exactly
    assert terms["best_score"] == pytest.approx(
        terms["LoadAware"] + terms["NodeNUMAResource"] + terms["Preferred"])
    assert with_terms[0]["margin"] == pytest.approx(
        terms["best_score"] - terms["runner_up"])
    # per-pod lookup API (the /explain provider)
    key = next(k for k, v in sched.explain_index.items()
               if v["verdict"] == "bound")
    rec = sched.explain_record(key)
    assert rec is not None and rec["node"]
    assert sched.explain_record("no/such-pod") is None


def test_unschedulable_attribution_and_rejection_metric():
    state, store = make_world(nodes=6, pods=40, seed=9)
    before = {}
    sched = Scheduler(store, waves=1, explain="counts")
    m = scheduler_metrics.FILTER_REJECTIONS
    before = {lbl["stage"]: v for lbl, v in m.samples()}
    results = _run_rounds(sched, store, state)
    assert any(r.failed or r.rejected for r in results), \
        "fixture must leave pods unbound"
    unbound = [v for v in sched.explain_index.values()
               if v["verdict"] == "unschedulable"]
    assert unbound
    with_stages = [v for v in unbound if v.get("stages")]
    assert with_stages, "kernel counts must back unschedulable records"
    assert any(v.get("message", "").startswith("0/")
               or "PreFilter" in v.get("message", "")
               for v in with_stages)
    after = {lbl["stage"]: v for lbl, v in m.samples()}
    grew = {s for s in after
            if after[s] > before.get(s, 0.0)}
    assert grew, "filter_rejections_total must grow for some stage"


def test_explain_off_records_nothing():
    state, store = make_world(nodes=6, pods=20, seed=2)
    sched = Scheduler(store, waves=1, explain="off")
    _run_rounds(sched, store, state, rounds=2)
    assert sched.explain_index == {}
    assert sched.explain_record("anything") is None


def test_deferred_diagnose_metrics():
    gauge = scheduler_metrics.DIAGNOSE_DEFERRED_DEPTH
    total = scheduler_metrics.DIAGNOSE_DEFERRED_TOTAL
    t0 = total.get() or 0.0
    state, store = make_world(nodes=6, pods=40, seed=9)
    sched = Scheduler(store, waves=1, explain="off")
    pipeline = CyclePipeline(sched, enabled=True)
    _run_rounds(sched, store, state, rounds=2)
    pipeline.flush()
    assert (total.get() or 0.0) > t0, "pipeline must defer diagnose items"
    assert gauge.get() == 0.0, "flush must drain the deferred queue"


# ---------------------------------------------------------------------------
# env plumbing
# ---------------------------------------------------------------------------


def test_explain_from_env(monkeypatch):
    for raw, want in [("off", None), ("", None), ("0", None),
                      ("counts", "counts"), ("on", "counts"),
                      ("full", "full"), ("bogus", None)]:
        monkeypatch.setenv("KOORD_TPU_EXPLAIN", raw)
        assert explain_from_env() == want, raw
    monkeypatch.delenv("KOORD_TPU_EXPLAIN")
    assert explain_from_env() is None


def test_cycle_deadline_from_env(monkeypatch):
    monkeypatch.delenv("KOORD_TPU_CYCLE_DEADLINE_MS", raising=False)
    assert cycle_deadline_from_env() is None
    monkeypatch.setenv("KOORD_TPU_CYCLE_DEADLINE_MS", "250")
    assert cycle_deadline_from_env() == pytest.approx(0.25)
    monkeypatch.setenv("KOORD_TPU_CYCLE_DEADLINE_MS", "0")
    assert cycle_deadline_from_env() is None
    monkeypatch.setenv("KOORD_TPU_CYCLE_DEADLINE_MS", "nope")
    assert cycle_deadline_from_env() is None


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_ring_bounded_and_dump_validates(tmp_path):
    fr = FlightRecorder(capacity=4, dump_dir=str(tmp_path))
    for seq in range(7):
        fr.record_cycle({
            "v": FLIGHT_SCHEMA_VERSION, "kind": "cycle", "seq": seq,
            "ts": float(seq), "duration_ms": 1.0, "waves": 1,
            "bound": [], "failed": [], "rejected": [], "preempted": [],
            "metrics": {}, "spans": [],
        })
    assert len(fr) == 4
    body = fr.dump("unit")
    header, records, errors = load_bundle(body.splitlines())
    assert not errors, errors
    assert header["reason"] == "unit" and header["cycles"] == 4
    assert [r["seq"] for r in records] == [3, 4, 5, 6]
    assert fr.dumps == 1
    assert fr.last_dump_path and fr.last_dump_path.startswith(str(tmp_path))
    with open(fr.last_dump_path) as f:
        assert f.read() == body


def test_flight_schema_rejects_drift():
    assert validate_header({"v": 99}), "bad header must fail"
    good = {"v": FLIGHT_SCHEMA_VERSION, "kind": "cycle", "seq": 1,
            "ts": 0.0, "duration_ms": 1.0, "waves": 1, "bound": [],
            "failed": [], "rejected": [], "preempted": [], "metrics": {},
            "spans": []}
    assert validate_cycle_record(good) == []
    for mutate in [
        {"waves": -1}, {"bound": [{"pod": 1}]}, {"preempted": [1]},
        {"metrics": {"x": "y"}}, {"spans": [{"bogus": True}]},
        {"failed": [{"pod": "a", "stages": {"s": "notint"}}]},
    ]:
        assert validate_cycle_record({**good, **mutate}), mutate


def test_scheduler_cycles_land_in_flight_ring():
    state, store = make_world(nodes=6, pods=20, seed=2)
    sched = Scheduler(store, waves=1, explain="counts")
    _run_rounds(sched, store, state, rounds=2)
    assert len(sched.flight) == 2
    body = sched.flight.dump("unit")
    header, records, errors = load_bundle(body.splitlines())
    assert not errors, errors
    rec = records[0]
    assert rec["bound"] and {"pod", "node"} <= set(rec["bound"][0])
    assert any(s["name"] == "cycle" for s in rec["spans"])
    assert rec["metrics"]["pods_bound"] == len(rec["bound"])


def test_cycle_exception_triggers_dump(monkeypatch):
    state, store = make_world(nodes=6, pods=10, seed=2)
    sched = Scheduler(store, waves=1, explain="off")
    sched.run_cycle(now=state.now)
    dumps_before = sched.flight.dumps

    def boom(*a, **k):
        raise RuntimeError("kaboom")

    monkeypatch.setattr(sched, "_run_cycle_traced", boom)
    with pytest.raises(RuntimeError, match="kaboom"):
        sched.run_cycle(now=state.now + 2)
    assert sched.flight.dumps == dumps_before + 1
    records = sched.flight.snapshot()
    assert records[-1]["error"].startswith("RuntimeError")
    # the wreck record still validates against the bundle schema
    _h, recs, errors = load_bundle(
        sched.flight.dump("post_mortem").splitlines())
    assert not errors, errors


def test_deadline_overrun_triggers_dump():
    state, store = make_world(nodes=6, pods=10, seed=2)
    sched = Scheduler(store, waves=1, explain="off")
    sched.cycle_deadline_seconds = 0.0  # every real cycle overruns
    before = sched.flight.dumps
    sched.run_cycle(now=state.now)
    assert sched.flight.dumps == before + 1


def test_golden_fixture_validates():
    with open("tests/fixtures/flight_golden.jsonl") as f:
        header, records, errors = load_bundle(f.readlines())
    assert not errors, errors
    assert header["cycles"] == len(records) > 0


# ---------------------------------------------------------------------------
# HTTP surfaces
# ---------------------------------------------------------------------------


def test_obs_server_explain_and_flight_routes():
    state, store = make_world(nodes=6, pods=20, seed=2)
    sched = Scheduler(store, waves=1, explain="full")
    _run_rounds(sched, store, state, rounds=2)
    srv = ObsServer(metrics_registry=scheduler_metrics.REGISTRY,
                    tracer=sched.tracer,
                    health_provider=sched.health_snapshot,
                    explain_provider=sched.explain_record,
                    flight=sched.flight)
    # healthz: liveness payload, not a bare ok
    status, ctype, body = srv.handle("/healthz")
    assert status == 200 and ctype == "application/json"
    health = json.loads(body)
    assert health["cycles"] == 2
    assert health["last_cycle_age_seconds"] >= 0.0
    assert health["last_cycle_waves"] == 1
    # explain: found / not found / missing param
    key = next(k for k, v in sched.explain_index.items()
               if v["verdict"] == "bound")
    status, ctype, body = srv.handle("/explain", {"pod": key})
    assert status == 200 and json.loads(body)["node"]
    assert srv.handle("/explain", {"pod": "no/such"})[0] == 404
    assert srv.handle("/explain")[0] == 400
    # flight recorder: GET status, POST dumps
    status, _ctype, body = srv.handle("/debug/flightrecorder")
    assert status == 200 and json.loads(body)["cycles"] == 2
    status, ctype, body = srv.handle("/debug/flightrecorder",
                                     method="POST")
    assert status == 200 and ctype == "application/x-ndjson"
    _h, recs, errors = load_bundle(body.splitlines())
    assert not errors and len(recs) == 2
    # metrics exposition carries the new series
    body = srv.handle("/metrics")[2]
    assert "koord_flight_recorder_dumps_total" in body
    assert "koord_scheduler_diagnose_deferred_depth" in body


def test_obs_server_healthz_default_unchanged():
    srv = ObsServer()
    assert srv.handle("/healthz") == (200, "text/plain", "ok")
    # no providers: the explain/flight routes stay 404
    assert srv.handle("/explain", {"pod": "x"})[0] == 404
    assert srv.handle("/debug/flightrecorder")[0] == 404


def test_obs_server_post_over_http():
    state, store = make_world(nodes=6, pods=10, seed=2)
    sched = Scheduler(store, waves=1, explain="counts")
    sched.run_cycle(now=state.now)
    srv = ObsServer(flight=sched.flight,
                    health_provider=sched.health_snapshot)
    server, _thread = srv.serve(0)
    try:
        import urllib.request

        port = server.server_address[1]
        with urllib.request.urlopen(
                urllib.request.Request(
                    f"http://127.0.0.1:{port}/debug/flightrecorder",
                    method="POST"), timeout=10) as resp:
            lines = resp.read().decode().splitlines()
        _h, recs, errors = load_bundle(lines)
        assert not errors and len(recs) == 1
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def test_cli_flight_and_explain(capsys):
    from koordinator_tpu.obs.__main__ import main

    assert main(["flight", "tests/fixtures/flight_golden.jsonl"]) == 0
    out = capsys.readouterr().out
    assert "flight bundle" in out and "cycle 1" in out
    with open("tests/fixtures/flight_golden.jsonl") as f:
        rec = json.loads(f.readlines()[1])
    pod = rec["bound"][0]["pod"]
    assert main(["explain", "tests/fixtures/flight_golden.jsonl", pod]) == 0
    out = capsys.readouterr().out
    assert "verdict: bound" in out
    assert main(["explain", "tests/fixtures/flight_golden.jsonl",
                 "no/such-pod"]) == 1
    assert main(["flight", "/does/not/exist.jsonl"]) == 2


def test_cli_flight_rejects_bad_bundle(tmp_path, capsys):
    from koordinator_tpu.obs.__main__ import main

    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"v": 1, "kind": "header"}\n')
    assert main(["flight", str(bad)]) == 1
    assert "schema error" in capsys.readouterr().err
