"""VMEM-budget fallback: the backend selectors must degrade to the XLA step
when the Pallas kernel's VMEM-resident state would not fit on-chip, instead
of failing to compile (the kernels pin all node/NUMA/quota state in VMEM —
ops/pallas_step.py documents ~20k nodes at R=16 as the reach)."""

import jax
import numpy as np
import pytest

import koordinator_tpu.models.full_chain as fc_mod
import koordinator_tpu.models.scheduler_model as sm_mod
from koordinator_tpu.ops import pallas_common as pc
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.ops.pallas_full_chain import (
    estimate_vmem_bytes as fc_vmem,
)
from koordinator_tpu.ops.pallas_step import estimate_vmem_bytes as step_vmem
from koordinator_tpu.scheduler.snapshot import build_full_chain_inputs
from koordinator_tpu.testing import synth_full_cluster


class TestEstimates:
    def test_flagship_shape_fits_default_budget(self):
        # the headline bench config (10k pods x 5k nodes, R=16, K=2,
        # G=64) must stay on the Pallas path
        assert fc_vmem(5_000, 16, 2, 64, 10_000) <= pc.DEFAULT_VMEM_BUDGET_BYTES
        assert step_vmem(5_000, 16, 10_000) <= pc.DEFAULT_VMEM_BUDGET_BYTES

    def test_50k_nodes_exceeds_default_budget(self):
        assert fc_vmem(50_000, 16, 2, 64, 10_000) > pc.DEFAULT_VMEM_BUDGET_BYTES

    def test_monotonic_in_every_dim(self):
        base = fc_vmem(1_000, 16, 2, 64, 2_000)
        assert fc_vmem(2_000, 16, 2, 64, 2_000) > base
        assert fc_vmem(1_000, 32, 2, 64, 2_000) > base
        assert fc_vmem(1_000, 16, 4, 64, 2_000) > base
        assert fc_vmem(1_000, 16, 2, 300, 2_000) > base
        assert fc_vmem(1_000, 16, 2, 64, 4_000) > base

    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("KOORD_TPU_VMEM_BUDGET_BYTES", "123456")
        assert pc.vmem_budget_bytes() == 123456
        monkeypatch.setenv("KOORD_TPU_VMEM_BUDGET_BYTES", "not-a-number")
        assert pc.vmem_budget_bytes() == pc.DEFAULT_VMEM_BUDGET_BYTES


class TestDispatch:
    """Force the TPU selection path on CPU and check which step runs."""

    def _inputs(self):
        args = LoadAwareArgs()
        _, state = synth_full_cluster(16, 24, seed=3)
        fc, *_, ng, ngroups = build_full_chain_inputs(state, args)
        return args, fc, ng, ngroups

    def test_over_budget_uses_xla_and_matches(self, monkeypatch):
        args, fc, ng, ngroups = self._inputs()
        monkeypatch.setattr(fc_mod.jax, "default_backend", lambda: "tpu")
        step = fc_mod.build_best_full_chain_step(
            args, ng, ngroups, vmem_budget_bytes=0)
        chosen, req, qused = step(fc)
        assert step.last_backend == "xla"
        ref_chosen, ref_req, ref_qused = fc_mod.build_full_chain_step(
            args, ng, ngroups)(fc)
        np.testing.assert_array_equal(np.asarray(chosen),
                                      np.asarray(ref_chosen))
        np.testing.assert_allclose(np.asarray(req), np.asarray(ref_req),
                                   atol=1e-3)

    def test_under_budget_selects_pallas(self, monkeypatch):
        args, fc, ng, ngroups = self._inputs()
        monkeypatch.setattr(fc_mod.jax, "default_backend", lambda: "tpu")
        import koordinator_tpu.ops.pallas_full_chain as pfc

        calls = []
        real_build = pfc.build_pallas_full_chain_step

        def fake_build(*a, **kw):
            real = real_build(*a, interpret=True, **kw)
            return lambda x: calls.append(1) or real(x)

        monkeypatch.setattr(
            "koordinator_tpu.ops.pallas_full_chain."
            "build_pallas_full_chain_step", fake_build)
        step = fc_mod.build_best_full_chain_step(
            args, ng, ngroups, vmem_budget_bytes=1 << 40)
        step(fc)
        assert step.last_backend == "pallas" and calls

    def test_schedule_step_over_budget_uses_xla(self, monkeypatch):
        from koordinator_tpu.ops.loadaware import build_loadaware_node_state
        from koordinator_tpu.ops.packing import pack_nodes, pack_pods
        from koordinator_tpu.testing import synth_cluster

        args = LoadAwareArgs()
        cluster = synth_cluster(num_nodes=16, num_pods=24, seed=7)
        pods = pack_pods(cluster.pods, args.resource_weights,
                         args.estimated_scaling_factors)
        nodes = pack_nodes(cluster.nodes)
        nodes.extras = build_loadaware_node_state(
            cluster.nodes, cluster.node_metrics, cluster.pods_by_key,
            cluster.assigned, args, cluster.now, pad_to=nodes.padded_size)
        inputs = sm_mod.make_inputs(pods, nodes, args)
        monkeypatch.setattr(sm_mod.jax, "default_backend", lambda: "tpu")
        step = sm_mod.build_best_schedule_step(args, vmem_budget_bytes=0)
        chosen, req = step(inputs)
        assert step.last_backend == "xla"
        ref_chosen, _ = sm_mod.build_schedule_step(args)(inputs)
        np.testing.assert_array_equal(np.asarray(chosen),
                                      np.asarray(ref_chosen))


def test_smem_estimate_guards_high_vg_batches():
    """The flattened volume-group SMEM rows grow with VG; the estimator
    must admit the measured-good shapes (10k pods, VG=1) and reject the
    combination that would blow the 1 MB Mosaic budget (10k pods, VG=16)
    so the dispatch degrades to XLA instead of failing to compile."""
    from koordinator_tpu.ops.pallas_full_chain import (
        SMEM_BUDGET_BYTES,
        estimate_smem_bytes,
    )

    assert estimate_smem_bytes(10_000, VG=1, T=8) <= SMEM_BUDGET_BYTES
    assert estimate_smem_bytes(10_000, VG=16, T=8) > SMEM_BUDGET_BYTES
    # small batches afford the full group budget
    assert estimate_smem_bytes(1_000, VG=16, T=8) <= SMEM_BUDGET_BYTES


def test_volume_less_high_vg_batch_stays_on_pallas_budget():
    """A high-VG batch whose pods mount no new PVCs compiles the volume
    machinery out (1-float placeholder), so the SMEM estimate must admit
    it where the volume-carrying shape would not."""
    from koordinator_tpu.ops.pallas_full_chain import (
        SMEM_BUDGET_BYTES,
        estimate_smem_bytes,
    )

    assert estimate_smem_bytes(10_000, VG=0, T=8) <= SMEM_BUDGET_BYTES
    assert estimate_smem_bytes(10_000, VG=16, T=8) > SMEM_BUDGET_BYTES
