"""Fused multi-wave scheduling (models/fused_waves.py + the cycle
driver's per-wave replay): K rounds per device dispatch must be
byte-identical to K sequential single-round cycles, with compacted
readback and carried on-device state.

The kernel-level contract (wave 1 == the serial step, bit-exact) plus
the driver-level contract (fuzz parity through churn, the genuine
multi-wave retry channel, truncation semantics, auto-K policy and its
demotions, metrics/spans)."""

import numpy as np
import pytest

from koordinator_tpu.api.objects import (
    Node,
    ObjectMeta,
    Pod,
    PodGroup,
    PodSpec,
    Reservation,
    TopologySpreadConstraint,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_POD,
    KIND_POD_GROUP,
    KIND_RESERVATION,
    ObjectStore,
)
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.scheduler.cycle import CyclePipeline, Scheduler
from koordinator_tpu.scheduler.pipeline_parity import run_fused_wave_parity

GIB = 1024 ** 3
NOW = 1_000_000.0
GANG_LABEL = "pod-group.scheduling.sigs.k8s.io"


# ---------------------------------------------------------------------------
# kernel level
# ---------------------------------------------------------------------------

def _packed_fixture(num_nodes=24, num_pods=70, seed=11):
    from koordinator_tpu.scheduler.snapshot import (
        build_full_chain_inputs,
        reduce_to_active_axes,
    )
    from koordinator_tpu.testing import synth_full_cluster

    la = LoadAwareArgs()
    _cluster, state = synth_full_cluster(
        num_nodes, num_pods, seed=seed, num_quotas=3, num_gangs=4,
        topology_fraction=0.5, lsr_fraction=0.2)
    fc, pods, nodes, _tree, _gi, ng, ngroups = build_full_chain_inputs(
        state, la)
    ex = nodes.extras
    fc, active = reduce_to_active_axes(fc)
    idx = np.asarray(active)
    est = np.take(ex["la_est_nonprod"], idx, axis=-1)
    adj = np.take(ex["la_adj_nonprod"], idx, axis=-1)
    return la, fc, pods, ng, ngroups, active, est, adj


def test_la_term_split_is_exact():
    """la_term_nonprod == la_est_nonprod + la_adj_nonprod bit-for-bit —
    the invariant the fused kernel's carried est_sum rests on."""
    from koordinator_tpu.testing import synth_full_cluster
    from koordinator_tpu.ops.loadaware import build_loadaware_node_state

    _cluster, state = synth_full_cluster(16, 40, seed=3)
    ex = build_loadaware_node_state(
        state.nodes, state.node_metrics, state.pods_by_key, state.assigned,
        LoadAwareArgs(), state.now, pad_to=16)
    assert np.array_equal(
        ex["la_term_nonprod"],
        ex["la_est_nonprod"] + ex["la_adj_nonprod"])


def test_fused_wave1_matches_serial_step_bitwise():
    """K=1 fused bindings == the serial single-round step, row for row
    (the evaluator and commit path are shared code — this pins it)."""
    from koordinator_tpu.models.full_chain import build_full_chain_step
    from koordinator_tpu.models.fused_waves import build_fused_wave_step

    from koordinator_tpu.models.fused_waves import plain_sides

    la, fc, pods, ng, ngroups, active, est, adj = _packed_fixture()
    chosen = np.asarray(
        build_full_chain_step(la, ng, ngroups, active_axes=active)(fc)[0])
    out = build_fused_wave_step(la, ng, ngroups, waves=1,
                                active_axes=active)(fc,
                                                    plain_sides(est, adj))
    n = int(np.asarray(out.wave_counts)[0])
    fused = np.full_like(chosen, -1)
    fused[np.asarray(out.bind_pods)[:n]] = np.asarray(out.bind_nodes)[:n]
    assert int(out.waves_run) == 1
    assert np.array_equal(fused, chosen)


def test_fused_kernel_early_exits_on_fixpoint():
    """A wave that commits nothing proves the fixpoint: waves_run stops
    there instead of burning the full K on device."""
    from koordinator_tpu.models.fused_waves import (
        build_fused_wave_step,
        plain_sides,
    )

    la, fc, pods, ng, ngroups, active, est, adj = _packed_fixture()
    out = build_fused_wave_step(la, ng, ngroups, waves=8,
                                active_axes=active)(fc,
                                                    plain_sides(est, adj))
    counts = np.asarray(out.wave_counts)
    waves_run = int(out.waves_run)
    assert waves_run < 8
    assert counts[waves_run - 1] == 0  # the exit wave committed nothing
    assert (counts[waves_run:] == 0).all()


def test_fused_step_rejects_bad_waves_and_prod_mismatch():
    from koordinator_tpu.models.fused_waves import build_fused_wave_step

    with pytest.raises(ValueError):
        build_fused_wave_step(LoadAwareArgs(), 1, 1, waves=0)
    with pytest.raises(ValueError):
        build_fused_wave_step(LoadAwareArgs(), 1, 1, waves=9)
    # prod-mode args REQUIRE the prod side split (and vice versa): the
    # carry's est_sum_prod slot presence must match prod_mode
    with pytest.raises(ValueError):
        build_fused_wave_step(
            LoadAwareArgs(score_according_prod_usage=True), 1, 1, waves=2)
    with pytest.raises(ValueError):
        build_fused_wave_step(LoadAwareArgs(), 1, 1, waves=2, prod=True)


# ---------------------------------------------------------------------------
# driver level: parity through churn
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("k", [2, 8])
def test_fused_k_equals_k_serial_cycles_through_churn(k):
    """The pipeline_parity gate fixture (quotas, gangs, NUMA topology,
    cpuset pods, per-round arrival/metric churn): fused-K bound
    sequences, failure/rejection lists, PodScheduled conditions and final
    assignments must be byte-identical to K sequential single-round
    cycles. hack/lint.sh runs all of K in {1,2,4,8}."""
    report = run_fused_wave_parity(k)
    assert report["ok"], report["mismatches"]
    assert report["conditions_checked"] > 0


# ---------------------------------------------------------------------------
# driver level: the genuine multi-wave retry channel
# ---------------------------------------------------------------------------

def _spread_retry_store():
    """Two zones; gang member b1 (Permit always fails -> reverts every
    round) holds n0 in wave 1 and shadows p; kept pod c raises zone za's
    spread count, so wave 2's re-evaluation pushes b1 to zone zb and p
    binds n0 — the topology-spread channel is non-additive, which is what
    makes a LATER round differ from re-running the first."""
    store = ObjectStore()
    for name, zone in (("n0", "za"), ("n1", "zb")):
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name=name, namespace="", labels={"zone": zone}),
            allocatable=ResourceList.of(cpu=6000, memory=32 * GIB, pods=20)))
    store.add(KIND_POD_GROUP, PodGroup(
        meta=ObjectMeta(name="gb", namespace="default"), min_member=2))
    store.add(KIND_POD, Pod(
        meta=ObjectMeta(name="b1", uid="b1", creation_timestamp=NOW,
                        labels={GANG_LABEL: "gb", "app": "red"}),
        spec=PodSpec(priority=9000,
                     requests=ResourceList.of(cpu=3000, memory=GIB, pods=1),
                     topology_spread=[TopologySpreadConstraint(
                         max_skew=1, topology_key="zone",
                         selector={"app": "red"})])))
    store.add(KIND_POD, Pod(
        meta=ObjectMeta(name="b2", uid="b2", creation_timestamp=NOW,
                        labels={GANG_LABEL: "gb"}),
        spec=PodSpec(priority=9000,
                     requests=ResourceList.of(cpu=900_000, memory=GIB,
                                              pods=1))))
    store.add(KIND_POD, Pod(
        meta=ObjectMeta(name="c", uid="c", creation_timestamp=NOW + 1,
                        labels={"app": "red"}),
        spec=PodSpec(priority=5000, node_selector={"zone": "za"},
                     requests=ResourceList.of(cpu=1000, memory=GIB,
                                              pods=1))))
    store.add(KIND_POD, Pod(
        meta=ObjectMeta(name="p", uid="p", creation_timestamp=NOW + 2),
        spec=PodSpec(priority=1000, node_selector={"zone": "za"},
                     requests=ResourceList.of(cpu=3000, memory=GIB,
                                              pods=1))))
    return store


def test_wave2_binds_pod_rejected_in_wave1():
    """One fused dispatch does what took two serial cycles: p fails the
    first round (capacity held by the reverting gang member), binds in
    the second (the kept commit moved the gang member's choice)."""
    sched = Scheduler(_spread_retry_store(), waves=4)
    res = sched.run_cycle(now=NOW)
    bound = [(b.pod_key, b.node_name) for b in res.bound]
    assert bound == [("default/c", "n0"), ("default/p", "n0")]
    # logical cycle 1 recorded p's transient failure, like serial c1 did
    assert "default/p" in res.failed
    assert res.waves >= 2


def test_fused_spread_scenario_matches_serial_exactly():
    """The same store through 3 serial cycles vs one fused K=3 cycle:
    concatenated bound/failed/rejected and final store state identical."""
    s_ser = Scheduler(_spread_retry_store(), waves=1)
    ser_bound, ser_failed, ser_rejected = [], [], []
    for _ in range(3):
        r = s_ser.run_cycle(now=NOW)
        ser_bound += [(b.pod_key, b.node_name) for b in r.bound]
        ser_failed += r.failed
        ser_rejected += r.rejected
    s_f = Scheduler(_spread_retry_store(), waves=3)
    rf = s_f.run_cycle(now=NOW)
    assert [(b.pod_key, b.node_name) for b in rf.bound] == ser_bound
    assert rf.failed == ser_failed
    assert rf.rejected == ser_rejected
    assert rf.waves == 3
    for key in ("default/c", "default/p", "default/b1"):
        a = s_ser.store.get(KIND_POD, key)
        b = s_f.store.get(KIND_POD, key)
        assert a.spec.node_name == b.spec.node_name
        ca, cb = (x.get_condition("PodScheduled") for x in (a, b))
        assert (ca is None) == (cb is None)
        if ca is not None:
            assert (ca.status, ca.reason, ca.message) == (
                cb.status, cb.reason, cb.message)


# ---------------------------------------------------------------------------
# driver level: waves policy
# ---------------------------------------------------------------------------

def _plain_store(num_nodes=2):
    store = ObjectStore()
    for i in range(num_nodes):
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name=f"n{i}", namespace=""),
            allocatable=ResourceList.of(cpu=64000, memory=64 * GIB,
                                        pods=500)))
    return store


def _pend(store, name, cpu=500, pvcs=()):
    pod = Pod(
        meta=ObjectMeta(name=name, uid=name, creation_timestamp=NOW),
        spec=PodSpec(requests=ResourceList.of(cpu=cpu, memory=GIB, pods=1),
                     pvc_names=list(pvcs)))
    store.add(KIND_POD, pod)
    return pod


def test_auto_waves_policy_scales_with_queue_depth():
    from koordinator_tpu.scheduler.cycle import _auto_waves

    assert _auto_waves(10) == 1
    assert _auto_waves(256) == 2
    assert _auto_waves(1024) == 4
    assert _auto_waves(4096) == 8


def test_effective_waves_demotions():
    """The PR-14 burn-down: reservations, claims and prod scoring no
    longer demote; only the narrow residues do."""
    store = _plain_store()
    sched = Scheduler(store, waves=8)
    pods = [_pend(store, f"p{i}") for i in range(4)]
    assert sched._effective_waves(pods, {}) == 8
    # pending Reservation CRs: carried as reservation rows + in-kernel
    # nomination — fused stays on
    res = Reservation(meta=ObjectMeta(name="r", namespace="__reservation__"))
    assert sched._effective_waves(pods, {"__reservation__/r": res}) == 8
    # claim-carrying pods: the hot-claim factorization carries the
    # volume-group regrouping (opaque-token mode: nothing is entangled)
    pvc_pod = _pend(store, "with-claim", pvcs=["claim-a"])
    assert sched._effective_waves(pods + [pvc_pod], {}) == 8
    # prod-usage scoring rides the est/adj prod split
    prod_sched = Scheduler(
        _plain_store(), args=LoadAwareArgs(score_according_prod_usage=True),
        waves=8)
    assert prod_sched._effective_waves(pods, {}) == 8
    # explicit K=1 and env-auto shallow queues stay serial
    assert Scheduler(_plain_store(), waves=1)._effective_waves(
        pods, {}) == 1
    assert Scheduler(_plain_store(), waves="auto")._effective_waves(
        pods, {}) == 1


def test_effective_waves_residual_demotions():
    """The remaining data-driven demotions: host-only ScoreTransformers
    and claim entanglement; retired reasons raise at the chokepoint."""
    from koordinator_tpu.api.objects import (
        PersistentVolumeClaim,
        StorageClass,
    )
    from koordinator_tpu.client.store import KIND_PVC, KIND_STORAGECLASS
    from koordinator_tpu.scheduler.frameworkext import ScoreTransformer

    store = _plain_store()
    sched = Scheduler(store, waves=8)
    pods = [_pend(store, f"p{i}") for i in range(4)]

    class HostOnly(ScoreTransformer):
        name = "host-only"

    sched.extender.register_transformer(HostOnly())
    assert sched._effective_waves(pods, {}) == 1
    assert "non-expressible-transformer" in sched._cycle_demotions

    # volume-aware store + two pods with unbound WFFC claims: entangled
    store2 = _plain_store()
    sched2 = Scheduler(store2, waves=8)
    store2.add(KIND_STORAGECLASS, StorageClass(
        meta=ObjectMeta(name="sc", namespace=""),
        provisioner="csi.example", volume_binding_mode="WaitForFirstConsumer"))
    for i in range(2):
        store2.add(KIND_PVC, PersistentVolumeClaim(
            meta=ObjectMeta(name=f"c{i}", namespace="default"),
            storage_class_name="sc"))
    claim_pods = [_pend(store2, f"q{i}", pvcs=[f"c{i}"]) for i in range(2)]
    filler = [_pend(store2, f"f{i}") for i in range(2)]
    assert sched2._effective_waves(claim_pods + filler, {}) == 1
    assert "claim-entangled" in sched2._cycle_demotions
    # ONE unbound-claim pod is carriable (its own bind removes it)
    sched3 = Scheduler(store2, waves=8)
    assert sched3._effective_waves([claim_pods[0]] + filler, {}) == 8

    # the chokepoint refuses retired reasons loudly
    with pytest.raises(ValueError):
        sched._note_demotion("claim-pods", 1)
    with pytest.raises(ValueError):
        sched._note_demotion("not-a-registered-reason", 1)


def test_waves_env_spec(monkeypatch):
    from koordinator_tpu.scheduler.cycle import waves_from_env

    monkeypatch.setenv("KOORD_TPU_WAVES", "4")
    assert waves_from_env() == 4
    monkeypatch.setenv("KOORD_TPU_WAVES", "99")
    assert waves_from_env() == 8  # clamped to MAX_WAVES
    monkeypatch.setenv("KOORD_TPU_WAVES", "auto")
    assert waves_from_env() == "auto"
    monkeypatch.setenv("KOORD_TPU_WAVES", "bogus")
    assert waves_from_env() == "auto"
    monkeypatch.delenv("KOORD_TPU_WAVES")
    assert waves_from_env() == "auto"


# ---------------------------------------------------------------------------
# driver level: observability
# ---------------------------------------------------------------------------

def test_fused_cycle_metrics_and_wave_spans():
    """Default (overlapped-replay) trace layout: the kernel span carries
    the wave budget + overlap marker, and the per-wave host replay rides
    wave_replay[i] spans under replay_drain."""
    from koordinator_tpu.scheduler import metrics as m

    store = _spread_retry_store()
    sched = Scheduler(store, waves=4)
    assert sched.replay_overlap  # the default
    res = sched.run_cycle(now=NOW)
    assert res.waves >= 2
    text = m.REGISTRY.expose()
    assert "koord_scheduler_waves_per_dispatch_bucket" in text
    assert "koord_scheduler_readback_bytes_total" in text
    assert "koord_scheduler_pipeline_occupancy" in text
    root = sched.tracer.roots(limit=1)[0]
    kernel = root.find("kernel")
    assert kernel is not None
    assert kernel.attributes.get("waves") == "4"
    assert kernel.attributes.get("overlap") == "1"
    drain = root.find("replay_drain")
    assert drain is not None
    waves = [s for s in drain.children if s.name == "wave_replay"]
    assert len(waves) >= 2
    assert waves[0].attributes.get("index") == "0"
    assert "bound" in waves[0].attributes


def test_fused_cycle_wave_spans_serial_replay_twin():
    """KOORD_TPU_REPLAY_OVERLAP=0: the single-program fused dispatch
    keeps the original retrospective wave markers under the kernel span
    — the parity twin's trace shape is part of 'today's exact path'."""
    store = _spread_retry_store()
    sched = Scheduler(store, waves=4, replay_overlap=False)
    res = sched.run_cycle(now=NOW)
    assert res.waves >= 2
    root = sched.tracer.roots(limit=1)[0]
    kernel = root.find("kernel")
    assert kernel is not None
    assert kernel.attributes.get("waves") == "4"
    assert kernel.attributes.get("overlap") is None
    waves = [s for s in kernel.children if s.name == "wave"]
    assert len(waves) >= 2
    assert waves[0].attributes.get("index") == "0"
    assert "bound" in waves[0].attributes


def test_serial_path_reports_one_wave():
    store = _plain_store()
    _pend(store, "a")
    sched = Scheduler(store)  # auto -> shallow queue -> serial
    res = sched.run_cycle(now=NOW)
    assert res.waves == 1
    assert [b.pod_key for b in res.bound] == ["default/a"]


# ---------------------------------------------------------------------------
# PR 14 carried state: reservations/claims through the fused dispatch
# ---------------------------------------------------------------------------

def test_reservation_consumed_by_wave2_of_same_dispatch():
    """The ISSUE-14 headline: a Reservation CR bound in wave 1 is
    consumed by an owner pod in wave 2 of the SAME dispatch (in-kernel
    nomination), with the consume annotation and the allocate-once
    Succeeded transition at the next reconcile."""
    from koordinator_tpu.api.objects import (
        ANNOTATION_RESERVATION_ALLOCATED,
    )
    from koordinator_tpu.scheduler.pipeline_parity import (
        _reservation_world,
    )

    now, store = _reservation_world()
    sched = Scheduler(store, waves=4)
    res = sched.run_cycle(now=now)
    assert res.demotions == []
    assert res.waves >= 2
    bound = {b.pod_key: b for b in res.bound}
    # the pseudo-pod bound its CR in wave 1...
    assert "__reservation__/resv-a" in bound
    r = store.get(KIND_RESERVATION, "/resv-a")
    assert r.phase == "Available"
    # ... and the selector-blocked owner consumed it IN THE SAME CYCLE
    assert bound["default/own-a"].node_name == bound[
        "__reservation__/resv-a"].node_name
    assert bound["default/own-a"].annotations[
        ANNOTATION_RESERVATION_ALLOCATED] == "resv-a"
    # multi-consumer (allocate_once=False): both owners rode resv-b
    for key in ("default/own-b1", "default/own-b2"):
        assert bound[key].annotations[
            ANNOTATION_RESERVATION_ALLOCATED] == "resv-b"
    # next cycle's reconcile retires the consumed allocate-once CR
    sched.run_cycle(now=now + 1)
    assert store.get(KIND_RESERVATION, "/resv-a").phase == "Succeeded"
    assert store.get(KIND_RESERVATION, "/resv-b").phase == "Available"


def test_carried_dispatch_ladder_demotion_lands_serial_identical():
    """Satellite: a fused dispatch carrying reservations + claims whose
    device window faults down the ladder mid-dispatch must land on the
    serial path with binds identical to a fault-free serial twin (the
    FusedDispatchDemoted re-run), and the transitions flight-dump."""
    from koordinator_tpu.scheduler.pipeline_parity import (
        _reservation_world,
    )

    def twin(inject: bool):
        now, store = _reservation_world()
        # a claim pod rides along so BOTH carried features are in play
        _pend(store, "claimer", pvcs=["c-x"])
        sched = Scheduler(store, waves=4)
        if inject:
            calls = {"n": 0}

            def inj(stage):
                calls["n"] += 1
                if stage == "fused" and calls["n"] <= 2:
                    raise RuntimeError("injected fused fault")

            sched.fault_injector = inj
        seq = []
        for c in range(4):
            r = sched.run_cycle(now=now + c)
            seq.extend((b.pod_key, b.node_name) for b in r.bound)
        return sched, seq

    sched_f, seq_f = twin(inject=True)
    _sched_c, seq_c = twin(inject=False)
    # the faulted world demoted below fused waves (retry once, then the
    # ladder's serial rung — later clean cycles may re-promote, so pin
    # the DEMOTED-cycle accounting, not the final level) and re-ran the
    # SAME pass serially with identical binds
    demoted = [r for r in sched_f.flight.snapshot()
               if "ladder-serial-waves" in r.get("demotions", [])]
    assert demoted, "the injected faults never demoted the fused dispatch"
    assert seq_f == seq_c


def test_crash_restart_rederives_reservation_state_from_replay():
    """Satellite: a fresh Scheduler on a surviving store (the koordguard
    crash-restart shape) re-derives reservation carry state — Available
    rows, consumed remainders via consumer annotations — purely from
    subscribe-replay, and the next fused dispatch nominates within the
    REMAINING capacity only."""
    from koordinator_tpu.api.objects import (
        ANNOTATION_RESERVATION_ALLOCATED,
        ReservationOwner,
    )

    store = _plain_store(num_nodes=1)
    node = store.list(KIND_NODE)[0]
    # an Available reservation with one PRE-CRASH consumer recorded only
    # through the consumer pod's annotation (the store truth)
    res = Reservation(
        meta=ObjectMeta(name="surv", namespace="",
                        creation_timestamp=NOW - 50),
        template=PodSpec(requests=ResourceList.of(cpu=2000, memory=GIB,
                                                   pods=2)),
        owners=[ReservationOwner(label_selector={"app": "w"})],
        allocate_once=False,
        phase="Available",
        node_name=node.meta.name,
        allocatable=ResourceList.of(cpu=2000, memory=GIB, pods=2),
        allocated=ResourceList.of(cpu=1500, pods=1),
        current_owners=["default/old-consumer"])
    store.add(KIND_RESERVATION, res)
    old = Pod(
        meta=ObjectMeta(name="old-consumer", uid="old",
                        creation_timestamp=NOW - 40, labels={"app": "w"},
                        annotations={
                            ANNOTATION_RESERVATION_ALLOCATED: "surv"}),
        spec=PodSpec(node_name=node.meta.name,
                     requests=ResourceList.of(cpu=1500, memory=GIB,
                                              pods=1)))
    store.add(KIND_POD, old)
    # two fresh owner pods, selector-blocked: only the reservation's
    # REMAINDER (500m) can host them — exactly one fits
    for name in ("w1", "w2"):
        pod = Pod(meta=ObjectMeta(name=name, uid=name,
                                  creation_timestamp=NOW,
                                  labels={"app": "w"}),
                  spec=PodSpec(requests=ResourceList.of(
                      cpu=400, memory=GIB, pods=1)))
        pod.spec.node_selector = {"reserved-only": "true"}
        store.add(KIND_POD, pod)
    # the RESTARTED scheduler: fresh object graph over the old store
    sched = Scheduler(store, waves=4)
    plugin = sched.extender.plugin("Reservation")
    assert "surv" in plugin.by_name  # subscribe-replay rebuilt the cache
    r = sched.run_cycle(now=NOW)
    bound = {b.pod_key for b in r.bound}
    # the host pre-pass (cycle start: already Available) nominates w1
    # within the replayed remainder; w2 (400 > 100 left) cannot fit
    assert "default/w1" in bound
    assert "default/w2" not in bound
    assert store.get(KIND_RESERVATION, "/surv").allocated.to_vector()[
        0] > 0


def test_opaque_claim_pods_bind_and_count_csi_slots():
    """The VolumeBinding opaque-token mode fix: pvc_names without any
    PVC/PV/StorageClass objects are CSI count tokens — pods BIND (no
    Reserve veto) and the attachable-volume limit still gates them.
    Pre-PR-14 these pods were immortal queue residents, which is why
    claim-pods dominated the soak demotion profile."""
    store = ObjectStore()
    node = Node(meta=ObjectMeta(name="n0", namespace=""),
                allocatable=ResourceList.of(cpu=64000, memory=64 * GIB,
                                            pods=50))
    node.attachable_volume_limit = 2
    store.add(KIND_NODE, node)
    for i in range(3):
        _pend(store, f"q{i}", pvcs=[f"c{i}"])
    sched = Scheduler(store, waves=4)
    res = sched.run_cycle(now=NOW)
    assert res.demotions == []
    bound = [b.pod_key for b in res.bound]
    # two claims fill the CSI limit; the third pod stays pending on the
    # volume filter — in BOTH the fused and next serial cycles
    assert len(bound) == 2
    assert len(res.failed) >= 1
    res2 = sched.run_cycle(now=NOW + 1)
    assert not res2.bound


def test_pipeline_defers_conditions_across_fused_cycle():
    """Fused cycles compose with the CyclePipeline: a transient wave-1
    failure that a later wave resolves must end PodScheduled=True after
    flush (the deferred False verdict is superseded by the bind)."""
    store = _spread_retry_store()
    pipeline = CyclePipeline(Scheduler(store, waves=4), enabled=True)
    res = pipeline.run_cycle(now=NOW)
    assert ("default/p", "n0") in [
        (b.pod_key, b.node_name) for b in res.bound]
    pipeline.flush()
    cond = store.get(KIND_POD, "default/p").get_condition("PodScheduled")
    assert cond is not None and cond.status == "True"
