"""Fused multi-wave scheduling (models/fused_waves.py + the cycle
driver's per-wave replay): K rounds per device dispatch must be
byte-identical to K sequential single-round cycles, with compacted
readback and carried on-device state.

The kernel-level contract (wave 1 == the serial step, bit-exact) plus
the driver-level contract (fuzz parity through churn, the genuine
multi-wave retry channel, truncation semantics, auto-K policy and its
demotions, metrics/spans)."""

import numpy as np
import pytest

from koordinator_tpu.api.objects import (
    Node,
    ObjectMeta,
    Pod,
    PodGroup,
    PodSpec,
    Reservation,
    TopologySpreadConstraint,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_POD,
    KIND_POD_GROUP,
    KIND_RESERVATION,
    ObjectStore,
)
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.scheduler.cycle import CyclePipeline, Scheduler
from koordinator_tpu.scheduler.pipeline_parity import run_fused_wave_parity

GIB = 1024 ** 3
NOW = 1_000_000.0
GANG_LABEL = "pod-group.scheduling.sigs.k8s.io"


# ---------------------------------------------------------------------------
# kernel level
# ---------------------------------------------------------------------------

def _packed_fixture(num_nodes=24, num_pods=70, seed=11):
    from koordinator_tpu.scheduler.snapshot import (
        build_full_chain_inputs,
        reduce_to_active_axes,
    )
    from koordinator_tpu.testing import synth_full_cluster

    la = LoadAwareArgs()
    _cluster, state = synth_full_cluster(
        num_nodes, num_pods, seed=seed, num_quotas=3, num_gangs=4,
        topology_fraction=0.5, lsr_fraction=0.2)
    fc, pods, nodes, _tree, _gi, ng, ngroups = build_full_chain_inputs(
        state, la)
    ex = nodes.extras
    fc, active = reduce_to_active_axes(fc)
    idx = np.asarray(active)
    est = np.take(ex["la_est_nonprod"], idx, axis=-1)
    adj = np.take(ex["la_adj_nonprod"], idx, axis=-1)
    return la, fc, pods, ng, ngroups, active, est, adj


def test_la_term_split_is_exact():
    """la_term_nonprod == la_est_nonprod + la_adj_nonprod bit-for-bit —
    the invariant the fused kernel's carried est_sum rests on."""
    from koordinator_tpu.testing import synth_full_cluster
    from koordinator_tpu.ops.loadaware import build_loadaware_node_state

    _cluster, state = synth_full_cluster(16, 40, seed=3)
    ex = build_loadaware_node_state(
        state.nodes, state.node_metrics, state.pods_by_key, state.assigned,
        LoadAwareArgs(), state.now, pad_to=16)
    assert np.array_equal(
        ex["la_term_nonprod"],
        ex["la_est_nonprod"] + ex["la_adj_nonprod"])


def test_fused_wave1_matches_serial_step_bitwise():
    """K=1 fused bindings == the serial single-round step, row for row
    (the evaluator and commit path are shared code — this pins it)."""
    from koordinator_tpu.models.full_chain import build_full_chain_step
    from koordinator_tpu.models.fused_waves import build_fused_wave_step

    la, fc, pods, ng, ngroups, active, est, adj = _packed_fixture()
    chosen = np.asarray(
        build_full_chain_step(la, ng, ngroups, active_axes=active)(fc)[0])
    out = build_fused_wave_step(la, ng, ngroups, waves=1,
                                active_axes=active)(fc, est, adj)
    n = int(np.asarray(out.wave_counts)[0])
    fused = np.full_like(chosen, -1)
    fused[np.asarray(out.bind_pods)[:n]] = np.asarray(out.bind_nodes)[:n]
    assert int(out.waves_run) == 1
    assert np.array_equal(fused, chosen)


def test_fused_kernel_early_exits_on_fixpoint():
    """A wave that commits nothing proves the fixpoint: waves_run stops
    there instead of burning the full K on device."""
    from koordinator_tpu.models.fused_waves import build_fused_wave_step

    la, fc, pods, ng, ngroups, active, est, adj = _packed_fixture()
    out = build_fused_wave_step(la, ng, ngroups, waves=8,
                                active_axes=active)(fc, est, adj)
    counts = np.asarray(out.wave_counts)
    waves_run = int(out.waves_run)
    assert waves_run < 8
    assert counts[waves_run - 1] == 0  # the exit wave committed nothing
    assert (counts[waves_run:] == 0).all()


def test_fused_step_rejects_bad_waves_and_prod_mode():
    from koordinator_tpu.models.fused_waves import build_fused_wave_step

    with pytest.raises(ValueError):
        build_fused_wave_step(LoadAwareArgs(), 1, 1, waves=0)
    with pytest.raises(ValueError):
        build_fused_wave_step(LoadAwareArgs(), 1, 1, waves=9)
    with pytest.raises(ValueError):
        build_fused_wave_step(
            LoadAwareArgs(score_according_prod_usage=True), 1, 1, waves=2)


# ---------------------------------------------------------------------------
# driver level: parity through churn
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("k", [2, 8])
def test_fused_k_equals_k_serial_cycles_through_churn(k):
    """The pipeline_parity gate fixture (quotas, gangs, NUMA topology,
    cpuset pods, per-round arrival/metric churn): fused-K bound
    sequences, failure/rejection lists, PodScheduled conditions and final
    assignments must be byte-identical to K sequential single-round
    cycles. hack/lint.sh runs all of K in {1,2,4,8}."""
    report = run_fused_wave_parity(k)
    assert report["ok"], report["mismatches"]
    assert report["conditions_checked"] > 0


# ---------------------------------------------------------------------------
# driver level: the genuine multi-wave retry channel
# ---------------------------------------------------------------------------

def _spread_retry_store():
    """Two zones; gang member b1 (Permit always fails -> reverts every
    round) holds n0 in wave 1 and shadows p; kept pod c raises zone za's
    spread count, so wave 2's re-evaluation pushes b1 to zone zb and p
    binds n0 — the topology-spread channel is non-additive, which is what
    makes a LATER round differ from re-running the first."""
    store = ObjectStore()
    for name, zone in (("n0", "za"), ("n1", "zb")):
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name=name, namespace="", labels={"zone": zone}),
            allocatable=ResourceList.of(cpu=6000, memory=32 * GIB, pods=20)))
    store.add(KIND_POD_GROUP, PodGroup(
        meta=ObjectMeta(name="gb", namespace="default"), min_member=2))
    store.add(KIND_POD, Pod(
        meta=ObjectMeta(name="b1", uid="b1", creation_timestamp=NOW,
                        labels={GANG_LABEL: "gb", "app": "red"}),
        spec=PodSpec(priority=9000,
                     requests=ResourceList.of(cpu=3000, memory=GIB, pods=1),
                     topology_spread=[TopologySpreadConstraint(
                         max_skew=1, topology_key="zone",
                         selector={"app": "red"})])))
    store.add(KIND_POD, Pod(
        meta=ObjectMeta(name="b2", uid="b2", creation_timestamp=NOW,
                        labels={GANG_LABEL: "gb"}),
        spec=PodSpec(priority=9000,
                     requests=ResourceList.of(cpu=900_000, memory=GIB,
                                              pods=1))))
    store.add(KIND_POD, Pod(
        meta=ObjectMeta(name="c", uid="c", creation_timestamp=NOW + 1,
                        labels={"app": "red"}),
        spec=PodSpec(priority=5000, node_selector={"zone": "za"},
                     requests=ResourceList.of(cpu=1000, memory=GIB,
                                              pods=1))))
    store.add(KIND_POD, Pod(
        meta=ObjectMeta(name="p", uid="p", creation_timestamp=NOW + 2),
        spec=PodSpec(priority=1000, node_selector={"zone": "za"},
                     requests=ResourceList.of(cpu=3000, memory=GIB,
                                              pods=1))))
    return store


def test_wave2_binds_pod_rejected_in_wave1():
    """One fused dispatch does what took two serial cycles: p fails the
    first round (capacity held by the reverting gang member), binds in
    the second (the kept commit moved the gang member's choice)."""
    sched = Scheduler(_spread_retry_store(), waves=4)
    res = sched.run_cycle(now=NOW)
    bound = [(b.pod_key, b.node_name) for b in res.bound]
    assert bound == [("default/c", "n0"), ("default/p", "n0")]
    # logical cycle 1 recorded p's transient failure, like serial c1 did
    assert "default/p" in res.failed
    assert res.waves >= 2


def test_fused_spread_scenario_matches_serial_exactly():
    """The same store through 3 serial cycles vs one fused K=3 cycle:
    concatenated bound/failed/rejected and final store state identical."""
    s_ser = Scheduler(_spread_retry_store(), waves=1)
    ser_bound, ser_failed, ser_rejected = [], [], []
    for _ in range(3):
        r = s_ser.run_cycle(now=NOW)
        ser_bound += [(b.pod_key, b.node_name) for b in r.bound]
        ser_failed += r.failed
        ser_rejected += r.rejected
    s_f = Scheduler(_spread_retry_store(), waves=3)
    rf = s_f.run_cycle(now=NOW)
    assert [(b.pod_key, b.node_name) for b in rf.bound] == ser_bound
    assert rf.failed == ser_failed
    assert rf.rejected == ser_rejected
    assert rf.waves == 3
    for key in ("default/c", "default/p", "default/b1"):
        a = s_ser.store.get(KIND_POD, key)
        b = s_f.store.get(KIND_POD, key)
        assert a.spec.node_name == b.spec.node_name
        ca, cb = (x.get_condition("PodScheduled") for x in (a, b))
        assert (ca is None) == (cb is None)
        if ca is not None:
            assert (ca.status, ca.reason, ca.message) == (
                cb.status, cb.reason, cb.message)


# ---------------------------------------------------------------------------
# driver level: waves policy
# ---------------------------------------------------------------------------

def _plain_store(num_nodes=2):
    store = ObjectStore()
    for i in range(num_nodes):
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name=f"n{i}", namespace=""),
            allocatable=ResourceList.of(cpu=64000, memory=64 * GIB,
                                        pods=500)))
    return store


def _pend(store, name, cpu=500, pvcs=()):
    pod = Pod(
        meta=ObjectMeta(name=name, uid=name, creation_timestamp=NOW),
        spec=PodSpec(requests=ResourceList.of(cpu=cpu, memory=GIB, pods=1),
                     pvc_names=list(pvcs)))
    store.add(KIND_POD, pod)
    return pod


def test_auto_waves_policy_scales_with_queue_depth():
    from koordinator_tpu.scheduler.cycle import _auto_waves

    assert _auto_waves(10) == 1
    assert _auto_waves(256) == 2
    assert _auto_waves(1024) == 4
    assert _auto_waves(4096) == 8


def test_effective_waves_demotions():
    store = _plain_store()
    sched = Scheduler(store, waves=8)
    pods = [_pend(store, f"p{i}") for i in range(4)]
    assert sched._effective_waves(pods, {}) == 8
    # pending Reservation CRs: wave-1 CR binds feed the NEXT cycle's
    # nomination pre-pass — not carryable
    res = Reservation(meta=ObjectMeta(name="r", namespace="__reservation__"))
    assert sched._effective_waves(pods, {"__reservation__/r": res}) == 1
    # claim-carrying pods: volume groups refactor between cycles
    pvc_pod = _pend(store, "with-claim", pvcs=["claim-a"])
    assert sched._effective_waves(pods + [pvc_pod], {}) == 1
    # prod-usage scoring: the prod term is not carried in split form
    prod_sched = Scheduler(
        _plain_store(), args=LoadAwareArgs(score_according_prod_usage=True),
        waves=8)
    assert prod_sched._effective_waves(pods, {}) == 1
    # explicit K=1 and env-auto shallow queues stay serial
    assert Scheduler(_plain_store(), waves=1)._effective_waves(
        pods, {}) == 1
    assert Scheduler(_plain_store(), waves="auto")._effective_waves(
        pods, {}) == 1


def test_waves_env_spec(monkeypatch):
    from koordinator_tpu.scheduler.cycle import waves_from_env

    monkeypatch.setenv("KOORD_TPU_WAVES", "4")
    assert waves_from_env() == 4
    monkeypatch.setenv("KOORD_TPU_WAVES", "99")
    assert waves_from_env() == 8  # clamped to MAX_WAVES
    monkeypatch.setenv("KOORD_TPU_WAVES", "auto")
    assert waves_from_env() == "auto"
    monkeypatch.setenv("KOORD_TPU_WAVES", "bogus")
    assert waves_from_env() == "auto"
    monkeypatch.delenv("KOORD_TPU_WAVES")
    assert waves_from_env() == "auto"


# ---------------------------------------------------------------------------
# driver level: observability
# ---------------------------------------------------------------------------

def test_fused_cycle_metrics_and_wave_spans():
    """Default (overlapped-replay) trace layout: the kernel span carries
    the wave budget + overlap marker, and the per-wave host replay rides
    wave_replay[i] spans under replay_drain."""
    from koordinator_tpu.scheduler import metrics as m

    store = _spread_retry_store()
    sched = Scheduler(store, waves=4)
    assert sched.replay_overlap  # the default
    res = sched.run_cycle(now=NOW)
    assert res.waves >= 2
    text = m.REGISTRY.expose()
    assert "koord_scheduler_waves_per_dispatch_bucket" in text
    assert "koord_scheduler_readback_bytes_total" in text
    assert "koord_scheduler_pipeline_occupancy" in text
    root = sched.tracer.roots(limit=1)[0]
    kernel = root.find("kernel")
    assert kernel is not None
    assert kernel.attributes.get("waves") == "4"
    assert kernel.attributes.get("overlap") == "1"
    drain = root.find("replay_drain")
    assert drain is not None
    waves = [s for s in drain.children if s.name == "wave_replay"]
    assert len(waves) >= 2
    assert waves[0].attributes.get("index") == "0"
    assert "bound" in waves[0].attributes


def test_fused_cycle_wave_spans_serial_replay_twin():
    """KOORD_TPU_REPLAY_OVERLAP=0: the single-program fused dispatch
    keeps the original retrospective wave markers under the kernel span
    — the parity twin's trace shape is part of 'today's exact path'."""
    store = _spread_retry_store()
    sched = Scheduler(store, waves=4, replay_overlap=False)
    res = sched.run_cycle(now=NOW)
    assert res.waves >= 2
    root = sched.tracer.roots(limit=1)[0]
    kernel = root.find("kernel")
    assert kernel is not None
    assert kernel.attributes.get("waves") == "4"
    assert kernel.attributes.get("overlap") is None
    waves = [s for s in kernel.children if s.name == "wave"]
    assert len(waves) >= 2
    assert waves[0].attributes.get("index") == "0"
    assert "bound" in waves[0].attributes


def test_serial_path_reports_one_wave():
    store = _plain_store()
    _pend(store, "a")
    sched = Scheduler(store)  # auto -> shallow queue -> serial
    res = sched.run_cycle(now=NOW)
    assert res.waves == 1
    assert [b.pod_key for b in res.bound] == ["default/a"]


def test_pipeline_defers_conditions_across_fused_cycle():
    """Fused cycles compose with the CyclePipeline: a transient wave-1
    failure that a later wave resolves must end PodScheduled=True after
    flush (the deferred False verdict is superseded by the bind)."""
    store = _spread_retry_store()
    pipeline = CyclePipeline(Scheduler(store, waves=4), enabled=True)
    res = pipeline.run_cycle(now=NOW)
    assert ("default/p", "n0") in [
        (b.pod_key, b.node_name) for b in res.bound]
    pipeline.flush()
    cond = store.get(KIND_POD, "default/p").get_condition("PodScheduled")
    assert cond is not None and cond.status == "True"
