"""Persistent compile cache + AOT warm-up ladder (scheduler/warmup.py).

Covers the PR 15 cold-start contract:

  * record -> restart -> replay: a fresh Scheduler against the same
    cache dir rebuilds every recorded rung through the keyed step-cache
    chokepoints, so its first cycle is an in-memory HIT — zero
    steady-state recompiles up to (and past) the first bind;
  * fingerprint discipline: a simulated code-version bump
    (KOORD_TPU_PROGRAM_FINGERPRINT) must MISS — rungs count
    ``invalidated``, nothing replays, and the on-demand compile still
    works;
  * corruption: a truncated/garbage index and truncated XLA cache
    entries must degrade to a clean compile — the ladder never crashes
    the scheduler;
  * the aval-spec roundtrip the index records call shapes with.

The cache config is process-global in jax, so this module owns ONE
session dir; decision determinism under the armed cache is separately
pinned by the parity gates (hack/lint.sh runs them with the cache on).
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from koordinator_tpu.scheduler import metrics as scheduler_metrics
from koordinator_tpu.scheduler import warmup as wu
from koordinator_tpu.scheduler.cycle import CyclePipeline, Scheduler
from koordinator_tpu.scheduler.pipeline_parity import (
    apply_round_delta,
    build_store_from_state,
)
from koordinator_tpu.testing import synth_full_cluster


@pytest.fixture(scope="module")
def cache_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("compile_cache"))
    wu.configure_compile_cache(d)
    # jax's cache config is process-global: later configure calls with a
    # different dir are ignored (first wins), so every test here shares
    # this one dir
    assert wu.configure_compile_cache(d) == wu._configured_dir
    return wu._configured_dir


def _world(seed=7, pods=40):
    _cluster, state = synth_full_cluster(
        16, pods, seed=seed, num_quotas=2, num_gangs=2,
        topology_fraction=0.5, lsr_fraction=0.2)
    return state, build_store_from_state(state)


def _run_rounds(sched, store, now, rounds=3, arrivals=7):
    pipe = CyclePipeline(sched, enabled=True)
    for r in range(rounds):
        if r:
            apply_round_delta(store, r, now, arrivals)
        pipe.run_cycle(now=now + 2 * r)
    pipe.flush()


class TestAvalSpec:
    def test_roundtrip_arrays_tuples_none_values(self):
        from koordinator_tpu.models.scheduler_model import ScheduleInputs

        spec = wu.aval_spec((np.zeros((3, 4), np.float32), None,
                             np.int32(5), (np.ones(2, bool),)))
        out = wu.zeros_from_spec(spec)
        assert out[1] is None
        assert out[0].shape == (3, 4) and out[0].dtype == np.float32
        assert out[2] == 5  # scalars record BY VALUE
        assert out[3][0].dtype == bool
        # namedtuples rebuild through the registry
        n_fields = len(ScheduleInputs._fields)
        si = ScheduleInputs(*([np.zeros((2, 2), np.float32)] * n_fields))
        out = wu.zeros_from_spec(wu.aval_spec(si))
        assert isinstance(out, ScheduleInputs)
        assert out[0].shape == (2, 2)

    def test_unregistered_namedtuple_rejected(self):
        import collections

        Odd = collections.namedtuple("OddTuple", "x")
        with pytest.raises(TypeError):
            wu.aval_spec(Odd(x=np.zeros(1)))


class TestIndex:
    def test_corrupt_index_loads_empty(self, cache_dir, tmp_path):
        idx = wu.CompileCacheIndex(str(tmp_path))
        with open(idx.path, "w") as f:
            f.write('{"v": 1, "entries": {"x"')  # truncated JSON
        assert idx.load() == {}
        # a record after the corruption rewrites a clean index
        idx.record("serial", {"signature": [16, 16, 1]}, [])
        assert len(idx.load()) == 1

    def test_stale_fingerprint_purged_on_write(self, tmp_path,
                                               monkeypatch):
        idx = wu.CompileCacheIndex(str(tmp_path))
        monkeypatch.setenv("KOORD_TPU_PROGRAM_FINGERPRINT", "v1")
        idx.record("serial", {"signature": [16, 16, 1]}, [])
        assert len(idx.load()) == 1
        monkeypatch.setenv("KOORD_TPU_PROGRAM_FINGERPRINT", "v2")
        idx.record("serial", {"signature": [32, 16, 1]}, [])
        entries = idx.load()
        # the v1 entry is gone; only the v2 rung remains
        assert len(entries) == 1
        assert all(e["fp"] == "v2" for e in entries.values())


class TestWarmupLadder:
    def test_record_then_restart_replays_with_zero_steady_misses(
            self, cache_dir):
        state, store = _world()
        sched = Scheduler(store, waves=4, explain="off", warmup="off")
        _run_rounds(sched, store, state.now)
        entries = wu.CompileCacheIndex(cache_dir).load()
        assert entries, "dispatch compiles must record rungs"
        assert {e["kind"] for e in entries.values()} <= {
            "serial", "fused", "chain", "rebalance", "colo"}

        # the "restarted" scheduler: same store world, sync warm-up
        state2, store2 = _world()
        sched2 = Scheduler(store2, waves=4, explain="off", warmup="sync")
        stats = sched2.warmup.stats
        assert stats["complete"] is True
        assert stats["warmed"] == stats["rungs"] > 0  # every rung HIT
        assert stats["failed"] == stats["invalidated"] == 0
        assert sched2._steady_state is True

        # first cycle binds with ZERO steady-state recompiles: the
        # in-memory step cache already holds every rung
        flagged = []
        sched2.compile_miss_hook = flagged.append
        m0 = scheduler_metrics.COMPILE_CACHE_MISSES.get()
        pipe = CyclePipeline(sched2, enabled=True)
        res = pipe.run_cycle(now=state2.now)
        pipe.flush()
        assert res.bound, "the warm scheduler must actually bind"
        assert scheduler_metrics.COMPILE_CACHE_MISSES.get() == m0
        assert flagged == []

    def test_fingerprint_bump_invalidates_and_recompiles(
            self, cache_dir, monkeypatch):
        state, store = _world(seed=9)
        sched = Scheduler(store, waves=1, explain="off", warmup="off")
        _run_rounds(sched, store, state.now, rounds=2)
        # simulated code-version bump: every recorded rung must MISS
        monkeypatch.setenv("KOORD_TPU_PROGRAM_FINGERPRINT",
                           "bumped-version")
        state2, store2 = _world(seed=9)
        sched2 = Scheduler(store2, waves=1, explain="off", warmup="sync")
        stats = sched2.warmup.stats
        assert stats["warmed"] == 0 and stats["built"] == 0
        assert stats["invalidated"] == stats["rungs"] > 0
        # ...and the on-demand compile path still works (recompiled)
        m0 = scheduler_metrics.COMPILE_CACHE_MISSES.get()
        res = sched2.run_cycle(now=state2.now)
        assert res.bound
        assert scheduler_metrics.COMPILE_CACHE_MISSES.get() > m0

    def test_corrupted_cache_entries_fall_back_cleanly(self, cache_dir):
        state, store = _world(seed=13)
        sched = Scheduler(store, waves=4, explain="off", warmup="off")
        _run_rounds(sched, store, state.now, rounds=2)
        # truncate every on-disk XLA entry AND garbage the index tail:
        # warm-up must still complete and the scheduler must still bind
        for name in os.listdir(cache_dir):
            if name.endswith("-cache"):
                path = os.path.join(cache_dir, name)
                with open(path, "r+b") as f:
                    f.truncate(64)
        state2, store2 = _world(seed=13)
        sched2 = Scheduler(store2, waves=4, explain="off", warmup="sync")
        stats = sched2.warmup.stats
        assert stats["complete"] is True  # never crashes the ladder
        res = sched2.run_cycle(now=state2.now)
        assert res.bound

        # a fully garbage index degrades to an empty ladder
        idx_path = os.path.join(cache_dir, wu.INDEX_NAME)
        with open(idx_path, "w") as f:
            f.write("\x00not json at all")
        state3, store3 = _world(seed=13)
        sched3 = Scheduler(store3, waves=4, explain="off", warmup="sync")
        assert sched3.warmup.stats["rungs"] == 0
        assert sched3.warmup.stats["complete"] is True
        assert sched3.run_cycle(now=state3.now).bound

    def test_ladder_transition_drops_steady_state_guard(self, cache_dir):
        state, store = _world(seed=21)
        sched = Scheduler(store, waves=1, explain="off", warmup="off")
        sched.note_warmup_complete(
            {"warmed": 2, "built": 0, "rungs": 2, "seconds": 0.1,
             "skipped": 0, "failed": 0, "invalidated": 0})
        assert sched._steady_state is True
        sched._on_ladder_transition(
            {"from": "full", "to": "no-mesh", "from_level": 0,
             "to_level": 2, "reason": "test"})
        assert sched._steady_state is False

    def test_empty_ladder_never_arms_the_guard(self, cache_dir):
        """A first boot against an index that covered nothing (empty,
        or all rungs invalidated) promised nothing — its legitimate
        cold compiles must not be flagged as steady-state misses."""
        state, store = _world(seed=25)
        sched = Scheduler(store, waves=1, explain="off", warmup="off")
        sched.note_warmup_complete(
            {"warmed": 0, "built": 0, "rungs": 0, "seconds": 0.0,
             "skipped": 0, "failed": 0, "invalidated": 0})
        assert sched._steady_state is False
        flagged = []
        sched.compile_miss_hook = flagged.append
        assert sched.run_cycle(now=state.now).bound
        assert flagged == []
