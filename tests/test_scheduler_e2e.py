"""End-to-end scheduler tests: store -> cycles -> bindings + annotations.

The scheduler-framework harness tier of the reference's test strategy
(SURVEY.md section 4): full Filter/Score cycles in-process against the fake
store, including reservations, cpuset allocation, gangs, and quota admission."""

import json

import numpy as np
import pytest

from koordinator_tpu.api.objects import (
    ANNOTATION_DEVICE_ALLOCATED,
    ANNOTATION_RESERVATION_ALLOCATED,
    ANNOTATION_RESOURCE_STATUS,
    LABEL_POD_GROUP,
    LABEL_POD_QOS,
    LABEL_QUOTA_NAME,
    Device,
    DeviceInfo,
    Node,
    NodeMetric,
    NodeMetricInfo,
    NodeResourceTopology,
    NUMAZone,
    ObjectMeta,
    Pod,
    PodGroup,
    PodSpec,
    Reservation,
    ReservationOwner,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import (
    KIND_DEVICE,
    KIND_ELASTIC_QUOTA,
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_NODE_TOPOLOGY,
    KIND_POD,
    KIND_POD_GROUP,
    KIND_RESERVATION,
    ObjectStore,
)
from koordinator_tpu.scheduler.cpu_topology import CPUTopology
from koordinator_tpu.scheduler.cycle import Scheduler

GIB = 1024**3
NOW = 1_000_000.0


def make_store(num_nodes=4, cores=16, mem_gib=64, with_topology=True,
               with_metrics=True):
    store = ObjectStore()
    for i in range(num_nodes):
        store.add(
            KIND_NODE,
            Node(
                meta=ObjectMeta(name=f"node-{i}", namespace=""),
                allocatable=ResourceList.of(
                    cpu=cores * 1000, memory=mem_gib * GIB, pods=110
                ),
            ),
        )
        if with_metrics:
            store.add(
                KIND_NODE_METRIC,
                NodeMetric(
                    meta=ObjectMeta(name=f"node-{i}", namespace=""),
                    update_time=NOW - 10,
                    node_metric=NodeMetricInfo(
                        node_usage=ResourceList.of(cpu=1000, memory=2 * GIB)
                    ),
                ),
            )
        if with_topology:
            topo = CPUTopology.build(1, 2, cores // 4, 2)
            store.add(
                KIND_NODE_TOPOLOGY,
                NodeResourceTopology(
                    meta=ObjectMeta(name=f"node-{i}", namespace=""),
                    cpus=topo.cpus,
                    zones=[
                        NUMAZone(
                            numa_id=k,
                            allocatable=ResourceList.of(
                                cpu=cores * 500, memory=mem_gib * GIB // 2
                            ),
                        )
                        for k in range(2)
                    ],
                ),
            )
    return store


def pend_pod(store, name, cpu=1000, mem=GIB, qos="LS", prio=9500, labels=None):
    pod = Pod(
        meta=ObjectMeta(
            name=name, labels={LABEL_POD_QOS: qos, **(labels or {})},
            creation_timestamp=NOW,
        ),
        spec=PodSpec(priority=prio, requests=ResourceList.of(cpu=cpu, memory=mem)),
    )
    store.add(KIND_POD, pod)
    return pod


class TestSchedulerE2E:
    def test_basic_binding(self):
        store = make_store()
        sched = Scheduler(store)
        for i in range(8):
            pend_pod(store, f"p{i}")
        result = sched.run_cycle(now=NOW)
        assert len(result.bound) == 8
        for pod in store.list(KIND_POD):
            assert pod.spec.node_name.startswith("node-")

    def test_spreading_by_load(self):
        store = make_store(num_nodes=4)
        sched = Scheduler(store)
        for i in range(8):
            pend_pod(store, f"p{i}", cpu=4000, mem=8 * GIB)
        sched.run_cycle(now=NOW)
        per_node = {}
        for pod in store.list(KIND_POD):
            per_node[pod.spec.node_name] = per_node.get(pod.spec.node_name, 0) + 1
        assert len(per_node) == 4  # least-allocated spreads

    def test_lsr_pod_gets_cpuset_annotation(self):
        store = make_store()
        sched = Scheduler(store)
        pend_pod(store, "lsr-pod", cpu=4000, qos="LSR")
        result = sched.run_cycle(now=NOW)
        assert len(result.bound) == 1
        pod = store.list(KIND_POD)[0]
        status = json.loads(pod.meta.annotations[ANNOTATION_RESOURCE_STATUS])
        from koordinator_tpu.utils.cpuset import CPUSet

        cpus = CPUSet.parse(status["cpuset"])
        assert len(cpus) == 4

    def test_second_cycle_sees_first_assignments(self):
        store = make_store(num_nodes=2, cores=8, mem_gib=16)
        sched = Scheduler(store)
        pend_pod(store, "a", cpu=6000, mem=12 * GIB)
        sched.run_cycle(now=NOW)
        pend_pod(store, "b", cpu=6000, mem=12 * GIB)
        sched.run_cycle(now=NOW)
        nodes = {p.spec.node_name for p in store.list(KIND_POD)}
        assert len(nodes) == 2  # b cannot fit next to a

    def test_unschedulable_pod_stays_pending(self):
        store = make_store(num_nodes=1, cores=4, mem_gib=8)
        sched = Scheduler(store)
        pend_pod(store, "huge", cpu=64000, mem=256 * GIB)
        result = sched.run_cycle(now=NOW)
        assert result.bound == []
        assert "default/huge" in result.failed
        assert store.list(KIND_POD)[0].spec.node_name == ""

    def test_reservation_lifecycle(self):
        store = make_store(num_nodes=2, cores=8, mem_gib=16)
        sched = Scheduler(store)
        store.add(
            KIND_RESERVATION,
            Reservation(
                meta=ObjectMeta(name="resv-web", namespace="",
                                creation_timestamp=NOW),
                template=PodSpec(
                    priority=9500,
                    requests=ResourceList.of(cpu=6000, memory=12 * GIB),
                ),
                owners=[ReservationOwner(label_selector={"app": "web"})],
                allocate_once=True,
            ),
        )
        # cycle 1: reservation gets scheduled and becomes Available
        r1 = sched.run_cycle(now=NOW)
        res = store.list(KIND_RESERVATION)[0]
        assert res.phase == "Available"
        assert res.node_name
        reserved_node = res.node_name

        # filler pods cannot take the reserved capacity
        for i in range(2):
            pend_pod(store, f"filler-{i}", cpu=6000, mem=12 * GIB)
        sched.run_cycle(now=NOW)
        fillers = [p for p in store.list(KIND_POD) if "filler" in p.meta.name]
        assert all(p.spec.node_name != reserved_node for p in fillers if p.is_assigned)

        # the owner pod consumes the reservation on its node
        pend_pod(store, "web-pod", cpu=6000, mem=12 * GIB,
                 labels={"app": "web"})
        sched.run_cycle(now=NOW)
        web = next(p for p in store.list(KIND_POD) if p.meta.name == "web-pod")
        assert web.spec.node_name == reserved_node
        assert web.meta.annotations[ANNOTATION_RESERVATION_ALLOCATED] == "resv-web"
        res = store.list(KIND_RESERVATION)[0]
        assert "default/web-pod" in res.current_owners

    def test_reservation_expiry(self):
        store = make_store(num_nodes=1)
        sched = Scheduler(store)
        store.add(
            KIND_RESERVATION,
            Reservation(
                meta=ObjectMeta(name="resv-old", namespace="",
                                creation_timestamp=NOW - 500),
                template=PodSpec(requests=ResourceList.of(cpu=1000, memory=GIB)),
                owners=[ReservationOwner(label_selector={"app": "x"})],
                ttl_seconds=100,
            ),
        )
        sched.run_cycle(now=NOW)
        assert store.list(KIND_RESERVATION)[0].phase == "Failed"

    def test_gang_waits_for_min_member(self):
        store = make_store(num_nodes=2, cores=8, mem_gib=16)
        sched = Scheduler(store)
        store.add(
            KIND_POD_GROUP,
            PodGroup(meta=ObjectMeta(name="g1", namespace="default"),
                     min_member=3),
        )
        for i in range(2):  # only 2 of 3 members exist -> gang invalid
            pend_pod(store, f"gang-{i}", cpu=1000,
                     labels={LABEL_POD_GROUP: "g1"})
        result = sched.run_cycle(now=NOW)
        assert len(result.bound) == 0
        # third member arrives -> whole gang schedules
        pend_pod(store, "gang-2", cpu=1000, labels={LABEL_POD_GROUP: "g1"})
        result = sched.run_cycle(now=NOW)
        assert len(result.bound) == 3
        pg = store.list(KIND_POD_GROUP)[0]
        assert pg.phase == "Scheduled"

    def test_quota_admission_blocks_overuse(self):
        from koordinator_tpu.api.objects import ElasticQuota

        store = make_store(num_nodes=4)
        sched = Scheduler(store)
        store.add(
            KIND_ELASTIC_QUOTA,
            ElasticQuota(
                meta=ObjectMeta(name="small-q", namespace="default"),
                min=ResourceList.of(cpu=0),
                max=ResourceList.of(cpu=2000, memory=4 * GIB),
            ),
        )
        for i in range(4):
            pend_pod(store, f"q-{i}", cpu=1000, mem=GIB,
                     labels={LABEL_QUOTA_NAME: "small-q"})
        result = sched.run_cycle(now=NOW)
        assert len(result.bound) == 2  # max cpu 2000 admits exactly 2
        assert len(result.rejected) == 2

    def test_gpu_pod_gets_device_annotation(self):
        store = make_store(num_nodes=1)
        node = store.list(KIND_NODE)[0]
        node.allocatable = node.allocatable.add(
            ResourceList.of(gpu_core=200, gpu_memory=32 * GIB, gpu_memory_ratio=200)
        )
        store.update(KIND_NODE, node)
        store.add(
            KIND_DEVICE,
            Device(
                meta=ObjectMeta(name="node-0", namespace=""),
                devices=[
                    DeviceInfo(type="gpu", minor=0,
                               resources=ResourceList.of(gpu_core=100)),
                    DeviceInfo(type="gpu", minor=1,
                               resources=ResourceList.of(gpu_core=100)),
                ],
            ),
        )
        sched = Scheduler(store)
        pod = Pod(
            meta=ObjectMeta(name="gpu-pod", labels={LABEL_POD_QOS: "LS"},
                            creation_timestamp=NOW),
            spec=PodSpec(
                priority=9500,
                requests=ResourceList.of(
                    cpu=1000, memory=GIB, gpu_core=50, gpu_memory_ratio=50
                ),
            ),
        )
        store.add(KIND_POD, pod)
        result = sched.run_cycle(now=NOW)
        assert len(result.bound) == 1
        alloc = json.loads(
            store.list(KIND_POD)[0].meta.annotations[ANNOTATION_DEVICE_ALLOCATED]
        )
        assert alloc["gpu"][0]["core"] == 50

    def test_joint_gpu_rdma_pod_end_to_end(self):
        """Full cycle with a GPU+RDMA pod: kernel coarse-fit on the rdma axis,
        joint NUMA-aligned device picks, annotation carries both types."""
        store = make_store(num_nodes=1)
        node = store.list(KIND_NODE)[0]
        node.allocatable = node.allocatable.add(
            ResourceList.of(gpu=2, gpu_core=200, gpu_memory=32 * GIB,
                            gpu_memory_ratio=200, rdma=2)
        )
        store.update(KIND_NODE, node)
        store.add(
            KIND_DEVICE,
            Device(
                meta=ObjectMeta(name="node-0", namespace=""),
                devices=[
                    DeviceInfo(type="gpu", minor=0, numa_node=0,
                               resources=ResourceList.of(
                                   gpu_core=100, gpu_memory=16 * GIB)),
                    DeviceInfo(type="gpu", minor=1, numa_node=1,
                               resources=ResourceList.of(
                                   gpu_core=100, gpu_memory=16 * GIB)),
                    DeviceInfo(type="rdma", minor=0, numa_node=0),
                    DeviceInfo(type="rdma", minor=1, numa_node=1),
                ],
            ),
        )
        sched = Scheduler(store)
        pod = Pod(
            meta=ObjectMeta(name="joint-pod", labels={LABEL_POD_QOS: "LS"},
                            creation_timestamp=NOW),
            spec=PodSpec(
                priority=9500,
                requests=ResourceList.of(
                    cpu=1000, memory=GIB, gpu=1, rdma=1
                ),
            ),
        )
        store.add(KIND_POD, pod)
        result = sched.run_cycle(now=NOW)
        assert len(result.bound) == 1
        alloc = json.loads(
            store.list(KIND_POD)[0].meta.annotations[ANNOTATION_DEVICE_ALLOCATED]
        )
        assert alloc["gpu"][0]["core"] == 100
        # joint allocation: rdma rides the gpu's numa node
        assert alloc["rdma"][0]["minor"] == alloc["gpu"][0]["minor"]

    def test_monitor_records_cycles(self):
        store = make_store(num_nodes=1)
        sched = Scheduler(store)
        pend_pod(store, "p")
        sched.run_cycle(now=NOW)
        assert len(sched.extender.monitor.history) == 1
        assert sched.extender.monitor.slow_cycles == 0


def test_taint_toleration_end_to_end():
    """Dedicated (tainted) nodes accept only tolerant pods, through the whole
    cycle driver (kube TaintToleration semantics)."""
    from koordinator_tpu.api.objects import Node, ObjectMeta, Pod, PodSpec
    from koordinator_tpu.api.resources import ResourceList
    from koordinator_tpu.client.store import KIND_NODE, KIND_POD, ObjectStore
    from koordinator_tpu.scheduler.cycle import Scheduler

    GIB = 1024**3
    store = ObjectStore()
    store.add(KIND_NODE, Node(
        meta=ObjectMeta(name="dedicated", namespace=""),
        allocatable=ResourceList.of(cpu=64000, memory=256 * GIB, pods=100),
        taints=[("dedicated", "infra")],
    ))
    store.add(KIND_NODE, Node(
        meta=ObjectMeta(name="open", namespace=""),
        allocatable=ResourceList.of(cpu=2000, memory=8 * GIB, pods=100),
    ))
    now = 1_000_000.0
    # intolerant pods must squeeze onto the small open node even though the
    # dedicated node is bigger and emptier
    for i in range(2):
        store.add(KIND_POD, Pod(
            meta=ObjectMeta(name=f"plain-{i}", uid=f"plain-{i}",
                            creation_timestamp=now),
            spec=PodSpec(requests=ResourceList.of(cpu=500, memory=GIB)),
        ))
    tolerant = Pod(
        meta=ObjectMeta(name="infra", uid="infra", creation_timestamp=now),
        spec=PodSpec(requests=ResourceList.of(cpu=4000, memory=4 * GIB),
                     tolerations=[("dedicated", "infra")]),
    )
    store.add(KIND_POD, tolerant)
    # an intolerant pod too big for the open node stays pending
    store.add(KIND_POD, Pod(
        meta=ObjectMeta(name="too-big", uid="too-big", creation_timestamp=now),
        spec=PodSpec(requests=ResourceList.of(cpu=8000, memory=GIB)),
    ))
    result = Scheduler(store).run_cycle(now=now)
    by_pod = {b.pod_key: b.node_name for b in result.bound}
    assert by_pod["default/plain-0"] == "open"
    assert by_pod["default/plain-1"] == "open"
    assert by_pod["default/infra"] == "dedicated"
    assert "default/too-big" not in by_pod
    assert "default/too-big" in result.failed


def test_affinity_spread_selector_end_to_end():
    """The production cycle driver honors nodeSelector, required inter-pod
    anti-affinity, and DoNotSchedule topology spread together: zone-pinned
    HA replicas spread one-per-zone inside their pool, web replicas spread
    evenly, and a co-location pair lands together."""
    from koordinator_tpu.api.objects import (
        PodAffinityTerm,
        TopologySpreadConstraint,
    )

    store = ObjectStore()
    for i in range(6):
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name=f"n{i}", namespace="", labels={
                "zone": f"z{i % 3}",
                "pool": "gold" if i < 4 else "silver",
            }),
            allocatable=ResourceList.of(cpu=32000, memory=128 * GIB,
                                        pods=100),
        ))
    now = NOW

    def add(name, labels=None, **spec_kw):
        pod = Pod(meta=ObjectMeta(name=name, uid=name, creation_timestamp=now,
                                  labels=labels or {}),
                  spec=PodSpec(requests=ResourceList.of(cpu=1000, memory=GIB),
                               **spec_kw))
        store.add(KIND_POD, pod)
        return pod

    # 3 HA replicas: anti-affinity per zone, pinned to the gold pool
    for i in range(3):
        p = add(f"ha-{i}", labels={"app": "ha"},
                node_selector={"pool": "gold"})
        p.spec.pod_anti_affinity.append(PodAffinityTerm(
            selector={"app": "ha"}, topology_key="zone"))
    # 6 web replicas: spread maxSkew=1 over zones
    for i in range(6):
        p = add(f"web-{i}", labels={"app": "web"})
        p.spec.topology_spread.append(TopologySpreadConstraint(
            max_skew=1, topology_key="zone", selector={"app": "web"}))
    # co-location pair: follower requires the leader's zone
    add("leader", labels={"app": "pair"})
    f = add("follower")
    f.spec.pod_affinity.append(PodAffinityTerm(
        selector={"app": "pair"}, topology_key="zone"))

    scheduler = Scheduler(store)
    result = scheduler.run_cycle(now=now)
    by_pod = {b.pod_key: b.node_name for b in result.bound}
    if "default/follower" not in by_pod:
        # the follower may precede the leader in queue order; like upstream
        # it stays pending until a match EXISTS — the next cycle binds it
        result2 = scheduler.run_cycle(now=now + 1)
        by_pod.update({b.pod_key: b.node_name for b in result2.bound})
    nodes = {n.meta.name: n for n in store.list(KIND_NODE)}

    ha_zones = [nodes[by_pod[f"default/ha-{i}"]].meta.labels["zone"]
                for i in range(3)]
    assert sorted(ha_zones) == ["z0", "z1", "z2"]
    for i in range(3):
        assert nodes[by_pod[f"default/ha-{i}"]].meta.labels["pool"] == "gold"

    from collections import Counter

    web_zones = Counter(
        nodes[by_pod[f"default/web-{i}"]].meta.labels["zone"]
        for i in range(6))
    # 6 replicas over 3 zones at maxSkew=1 admit exactly one outcome —
    # a skew check over only the POPULATED zones would pass a total
    # spread failure (all six in one zone has skew 0 over itself)
    assert dict(web_zones) == {"z0": 2, "z1": 2, "z2": 2}

    leader_zone = nodes[by_pod["default/leader"]].meta.labels["zone"]
    follower_zone = nodes[by_pod["default/follower"]].meta.labels["zone"]
    assert leader_zone == follower_zone


def test_node_reservation_trims_allocatable_end_to_end():
    """node.koordinator.sh/reservation reserves resources for system daemons
    (apis/extension/node_reservation.go + pkg/util/node.go trim): the
    scheduler must not hand reserved capacity to pods, and reservedCPUs
    never enter cpuset allocations."""
    import json as _json

    from koordinator_tpu.api.objects import ANNOTATION_NODE_RESERVATION

    store = make_store(num_nodes=1, cores=8, mem_gib=16)
    node = store.list(KIND_NODE)[0]
    node.meta.annotations[ANNOTATION_NODE_RESERVATION] = _json.dumps(
        {"reservedCPUs": "0-3"})  # 4 of 8 cores reserved
    store.update(KIND_NODE, node)  # fire the reservation re-sync
    sched = Scheduler(store)
    # LSR pod first, while capacity is free: it MUST bind and its cpuset
    # must avoid the reserved cores
    pend_pod(store, "lsr", cpu=2000, qos="LSR")
    r1 = sched.run_cycle(now=NOW)
    assert any(b.pod_key == "default/lsr" for b in r1.bound)
    lsr = next(p for p in store.list(KIND_POD) if p.meta.name == "lsr")
    status = json.loads(lsr.meta.annotations[ANNOTATION_RESOURCE_STATUS])
    from koordinator_tpu.utils.cpuset import CPUSet

    cpus = CPUSet.parse(status["cpuset"])
    assert len(cpus) == 2
    assert not (set(cpus) & {0, 1, 2, 3}), status["cpuset"]
    # capacity trim: 8 cores raw - 4 reserved - 2 (lsr) leaves 2 cores
    for i in range(2):
        pend_pod(store, f"p{i}", cpu=2000, mem=GIB)
    r2 = sched.run_cycle(now=NOW + 1)
    bound2 = {b.pod_key for b in r2.bound}
    assert len(bound2) == 1  # only one more 2-core pod fits


def test_node_reservation_cpus_only_policy_keeps_allocatable():
    """applyPolicy=ReservedCPUsOnly reserves the cores for cpuset purposes
    without trimming schedulable allocatable."""
    import json as _json

    from koordinator_tpu.api.objects import ANNOTATION_NODE_RESERVATION
    from koordinator_tpu.ops.estimator import estimate_node_allocatable

    store = make_store(num_nodes=1, cores=8, mem_gib=16)
    node = store.list(KIND_NODE)[0]
    node.meta.annotations[ANNOTATION_NODE_RESERVATION] = _json.dumps(
        {"reservedCPUs": "0-3", "applyPolicy": "ReservedCPUsOnly"})
    vec = estimate_node_allocatable(node)
    assert vec[0] == 8000  # untrimmed
    node.meta.annotations[ANNOTATION_NODE_RESERVATION] = _json.dumps(
        {"resources": {"cpu": "2", "memory": "4Gi"}})
    vec2 = estimate_node_allocatable(node)
    assert vec2[0] == 6000
    assert vec2[1] == 12 * 1024  # memory packs in MiB wire units
    # malformed annotation reserves nothing
    node.meta.annotations[ANNOTATION_NODE_RESERVATION] = "not-json"
    assert estimate_node_allocatable(node)[0] == 8000


def test_operating_mode_pod_acts_as_reservation():
    """A pod labeled operating-mode=Reservation schedules like a pod, then
    its resources serve its declared owners: the owner pod lands on the
    reservation pod's node consuming its footprint (no double count), and
    non-owners cannot nominate it (operating_pod.go semantics)."""
    import json as _json

    from koordinator_tpu.api.objects import (
        ANNOTATION_RESERVATION_ALLOCATED,
        ANNOTATION_RESERVATION_CURRENT_OWNER,
        ANNOTATION_RESERVATION_OWNERS,
        LABEL_POD_OPERATING_MODE,
    )

    store = make_store(num_nodes=3, cores=8, mem_gib=16)
    sched = Scheduler(store)
    placeholder = pend_pod(store, "placeholder", cpu=6000, mem=12 * GIB)
    placeholder.meta.labels[LABEL_POD_OPERATING_MODE] = "Reservation"
    placeholder.meta.annotations[ANNOTATION_RESERVATION_OWNERS] = _json.dumps(
        [{"labelSelector": {"matchLabels": {"app": "web"}}}])
    store.update(KIND_POD, placeholder)
    r1 = sched.run_cycle(now=NOW)
    placeholder = store.get(KIND_POD, "default/placeholder")
    assert placeholder.is_assigned
    reserved_node = placeholder.spec.node_name

    # fill the other nodes so the reserved node is the only one with room
    # for a 6-core pod — which only the owner may use
    for i in range(2):
        pend_pod(store, f"filler-{i}", cpu=6000, mem=12 * GIB)
    sched.run_cycle(now=NOW + 1)

    owner = pend_pod(store, "web-pod", cpu=4000, mem=8 * GIB,
                     labels={"app": "web"})
    sched.run_cycle(now=NOW + 2)
    owner = store.get(KIND_POD, "default/web-pod")
    assert owner.spec.node_name == reserved_node
    assert owner.meta.annotations[
        ANNOTATION_RESERVATION_ALLOCATED] == "pod:default/placeholder"
    placeholder = store.get(KIND_POD, "default/placeholder")
    owners = _json.loads(placeholder.meta.annotations[
        ANNOTATION_RESERVATION_CURRENT_OWNER])
    assert owners == ["default/web-pod"]
