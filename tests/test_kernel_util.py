"""Tests for the kernel interface layer: resctrl, core-sched, kidled,
machine-info discovery, cgroup drivers (reference pkg/koordlet/util/system)."""

import os

import pytest

from koordinator_tpu.koordlet.util import coresched, kidled, machineinfo, resctrl
from koordinator_tpu.koordlet.util import system as sysutil


@pytest.fixture()
def fs():
    f = sysutil.FakeFS()
    yield f
    f.cleanup()


class TestResctrl:
    def test_parse_and_format_schemata(self):
        s = resctrl.parse_schemata("L3:0=fffff;1=fffff\nMB:0=100;1=100\n")
        assert s.l3_masks == {0: 0xFFFFF, 1: 0xFFFFF}
        assert s.mb_percents == {0: 100, 1: 100}
        assert s.l3_num_ways == 20
        round_trip = resctrl.parse_schemata(s.format())
        assert round_trip.l3_masks == s.l3_masks
        assert round_trip.mb_percents == s.mb_percents

    def test_l3_mask_full_range(self):
        assert resctrl.calculate_l3_mask(20, 0, 100) == 0xFFFFF

    def test_l3_mask_be_slice_contiguous_and_nonempty(self):
        mask = resctrl.calculate_l3_mask(20, 0, 30)
        assert mask == 0x3F  # ceil(20*0.3)=6 ways
        tiny = resctrl.calculate_l3_mask(4, 0, 1)
        assert tiny == 0x1  # at least one way
        # contiguity: mask+lsb must be a power of two
        m = resctrl.calculate_l3_mask(11, 40, 80)
        lsb = m & -m
        assert ((m // lsb) + 1) & (m // lsb) == 0

    def test_l3_mask_invalid_range(self):
        with pytest.raises(ValueError):
            resctrl.calculate_l3_mask(20, 50, 50)

    def test_group_lifecycle_on_fakefs(self, fs):
        iface = resctrl.ResctrlInterface(fs.config)
        assert not iface.available()
        root_schemata = resctrl.Schemata(l3_masks={0: 0xFFF}, mb_percents={0: 100})
        sysutil.write_file(
            os.path.join(iface.group_dir(""), "schemata"), root_schemata.format())
        assert iface.available()
        assert iface.num_l3_ways() == 12

        be = resctrl.Schemata(
            l3_masks={0: resctrl.calculate_l3_mask(12, 0, 30)},
            mb_percents={0: 30})
        assert iface.write_schemata(resctrl.BE_GROUP, be)
        got = iface.read_schemata(resctrl.BE_GROUP)
        assert got.l3_masks == {0: 0xF}
        assert got.mb_percents == {0: 30}

        assert iface.add_tasks(resctrl.BE_GROUP, [101, 102])
        assert iface.add_tasks(resctrl.BE_GROUP, [103])
        assert iface.read_tasks(resctrl.BE_GROUP) == [101, 102, 103]


class TestCoreSched:
    def test_fake_cookie_lifecycle(self):
        cs = coresched.FakeCoreSched()
        assert cs.supported()
        assert cs.get_cookie(1) == 0
        assert cs.create_cookie(1)
        c1 = cs.get_cookie(1)
        assert c1 != 0
        assert cs.share_from(1, [2, 3]) == []
        assert cs.get_cookie(2) == c1 == cs.get_cookie(3)
        assert cs.create_cookie(4)
        assert cs.get_cookie(4) != c1
        assert cs.clear_cookie(2)
        assert cs.get_cookie(2) == 0

    def test_share_from_unknown_source_fails_all(self):
        cs = coresched.FakeCoreSched()
        assert cs.share_from(99, [1, 2]) == [1, 2]

    def test_default_interface_is_real_and_probes(self):
        iface = coresched.default_interface()
        assert isinstance(iface, coresched.SystemCoreSched)
        # supported() must not raise regardless of kernel capability
        assert iface.supported() in (True, False)


class TestKidled:
    STATS = (
        "# version: 1.0\n"
        "# scans: 1380\n"
        "# scan_period_in_seconds: 120\n"
        "# buckets: 1,2,5,15,30,60,120,240\n"
        "#   page_scans   idle_pages\n"
        "csei 0 0 0 0 0 0 0 1048576\n"
        "dsei 0 0 0 0 0 0 0 0\n"
        "cfei 262144 0 0 0 0 524288 0 2097152\n"
    )

    def test_parse(self):
        s = kidled.parse_idle_page_stats(self.STATS)
        assert s.scan_period_s == 120
        assert s.scans == 1380
        assert s.buckets == [1, 2, 5, 15, 30, 60, 120, 240]
        assert s.rows["csei"][-1] == 1048576

    def test_cold_bytes_boundary(self):
        s = kidled.parse_idle_page_stats(self.STATS)
        # boundary 3600s -> buckets >= 30 periods (30*120=3600)
        assert s.cold_bytes(3600) == 1048576 + 524288 + 2097152
        # boundary above max bucket age -> only the 240-period column
        assert s.cold_bytes(240 * 120) == 1048576 + 2097152
        # boundary beyond any bucket -> nothing
        assert s.cold_bytes(10**9) == 0

    def test_interface_on_fakefs(self, fs):
        iface = kidled.KidledInterface(fs.config)
        assert not iface.supported()
        assert iface.enable(scan_period_s=120)
        assert iface.supported() and iface.enabled()
        assert iface.scan_period_s() == 120
        rel = fs.config.pod_relative_path(sysutil.QOS_BESTEFFORT, "uid1")
        fs.set_cgroup(rel, kidled.IDLE_PAGE_STATS, self.STATS)
        assert iface.pod_cold_bytes(rel, cold_boundary_s=3600) == 3670016


class TestMachineInfo:
    def test_discover_fake_machine(self, fs):
        machineinfo.write_fake_machine(
            fs, num_sockets=2, nodes_per_socket=2, cores_per_node=4)
        info = machineinfo.discover(fs.config)
        assert info is not None
        topo = info.topology
        assert topo.num_cpus == 2 * 2 * 4 * 2
        assert topo.num_numa_nodes == 4
        assert topo.cpus_per_core == 2
        # SMT siblings stay on one core and one numa node
        for core_id, cpus in topo.cores().items():
            assert len(cpus) == 2
        assert len(info.numa_mem) == 4
        assert all(m.total_bytes == 32 << 30 for m in info.numa_mem.values())

    def test_discover_missing_tree(self, fs):
        assert machineinfo.discover(fs.config) is None


class TestPlegSystemd:
    def test_pleg_sees_systemd_pod_slices(self):
        from koordinator_tpu.koordlet.pleg import Pleg

        f = sysutil.FakeFS()
        try:
            f.config.cgroup_driver = sysutil.DRIVER_SYSTEMD
            pleg = Pleg(f.config)
            assert pleg.tick() == []  # baseline scan
            rel = f.config.pod_relative_path(sysutil.QOS_BESTEFFORT, "ab-12")
            f.set_cgroup(rel, sysutil.CPU_WEIGHT, "10")
            events = pleg.tick()
            assert [e.event_type for e in events] == ["pod_added"]
            assert "podab_12.slice" in events[0].pod_dir
        finally:
            f.cleanup()


class TestCgroupDriver:
    def test_systemd_paths(self):
        cfg = sysutil.SystemConfig(cgroup_driver=sysutil.DRIVER_SYSTEMD)
        rel = cfg.pod_relative_path(sysutil.QOS_BESTEFFORT, "ab-12")
        assert rel == ("kubepods.slice/kubepods-besteffort.slice/"
                       "kubepods-besteffort-podab_12.slice")
        cdir = cfg.container_relative_path(sysutil.QOS_BESTEFFORT, "ab-12", "c1")
        assert cdir.endswith("cri-containerd-c1.scope")
        # guaranteed sits right under kubepods.slice
        assert cfg.pod_relative_path("", "x") == (
            "kubepods.slice/kubepods-podx.slice")

    def test_detect_driver_and_version(self, fs):
        cfg = fs.config
        assert sysutil.detect_cgroup_driver(cfg) == sysutil.DRIVER_CGROUPFS
        os.makedirs(os.path.join(cfg.cgroup_root_dir, "kubepods.slice"))
        assert sysutil.detect_cgroup_driver(cfg) == sysutil.DRIVER_SYSTEMD
        assert not sysutil.detect_cgroup_version(cfg)
        sysutil.write_file(
            os.path.join(cfg.cgroup_root_dir, "cgroup.controllers"),
            "cpu io memory")
        assert sysutil.detect_cgroup_version(cfg)


class TestAdmissionGrouping:
    """ops/taints.py pair-based admission signatures: high-cardinality keys
    must not fragment the cluster, and budget exhaustion must never hurt
    selector-less pods."""

    def _mk_node(self, name, labels=None, taints=()):
        from koordinator_tpu.api.objects import Node, ObjectMeta

        n = Node(meta=ObjectMeta(name=name, namespace="",
                                 labels=dict(labels or {})))
        n.taints = list(taints)
        return n

    def _mk_pod(self, name, selector=None):
        from koordinator_tpu.api.objects import ObjectMeta, Pod, PodSpec

        return Pod(meta=ObjectMeta(name=name),
                   spec=PodSpec(node_selector=dict(selector or {})))

    def test_hostname_pin_splits_two_groups(self):
        from koordinator_tpu.ops.taints import (
            admission_mask,
            group_node_admission,
            selector_pairs_of,
        )

        nodes = [self._mk_node(f"n{i}", {"kubernetes.io/hostname": f"n{i}"})
                 for i in range(200)]
        pinned = self._mk_pod("p", {"kubernetes.io/hostname": "n7"})
        free = self._mk_pod("q")
        pairs = selector_pairs_of([pinned, free])
        ids, groups = group_node_admission(nodes, pairs)
        assert len(groups) == 2  # pinned node vs everyone else — no 200-way
        pin_mask = int(admission_mask(pinned, groups))
        free_mask = int(admission_mask(free, groups))
        for i, node in enumerate(nodes):
            pin_ok = bool((pin_mask >> ids[i]) & 1)
            assert pin_ok == (node.meta.name == "n7")
            assert (free_mask >> ids[i]) & 1  # selector-less: everywhere

    def test_budget_exhaustion_spares_selectorless_pods(self):
        from koordinator_tpu.ops.taints import (
            MAX_TAINT_GROUPS,
            admission_mask,
            group_node_admission,
            selector_pairs_of,
        )

        n = MAX_TAINT_GROUPS + 10
        nodes = [self._mk_node(f"n{i}", {"host": f"n{i}"}) for i in range(n)]
        pods = [self._mk_pod(f"p{i}", {"host": f"n{i}"}) for i in range(n)]
        free = self._mk_pod("free")
        pairs = selector_pairs_of(pods + [free])
        ids, groups = group_node_admission(nodes, pairs)
        assert len(groups) <= MAX_TAINT_GROUPS - 1
        free_mask = int(admission_mask(free, groups))
        placeable = unplaceable = 0
        for i, pod in enumerate(pods):
            mask = int(admission_mask(pod, groups))
            ok = bool((mask >> ids[i]) & 1)
            # a pinned pod is either exactly placeable on its node or
            # conservatively unschedulable (label-unknown bucket) — never
            # admitted to a WRONG node
            for j in range(n):
                if (mask >> ids[j]) & 1:
                    assert nodes[j].meta.labels["host"] == f"n{i}"
            placeable += ok
            unplaceable += not ok
        assert placeable > 0 and unplaceable > 0  # degrade path exercised
        # selector-less pods keep the WHOLE cluster, unknown buckets included
        for j in range(n):
            assert (free_mask >> ids[j]) & 1
