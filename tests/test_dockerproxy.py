"""Docker backend path of koord-runtime-proxy: kubelet(dockershim)-shaped
HTTP client -> DockerProxyServer (UDS) -> hook chain -> FakeDockerDaemon,
mirroring the reference pkg/runtimeproxy/server/docker/ capability the CRI
path already covers over gRPC."""

import json

import pytest

from koordinator_tpu.runtimeproxy import api_pb2
from koordinator_tpu.runtimeproxy.dockerserver import (
    DockerProxyServer,
    FakeDockerDaemon,
    _UnixHTTPConnection,
)
from koordinator_tpu.runtimeproxy.hookclient import InProcessHookClient
from koordinator_tpu.runtimeproxy.server import FailurePolicy


class _Hooks:
    """koordlet-side hook handler: pins BE containers to cpuset 0-3 and
    halves cpu shares on create; bumps memory on update."""

    def __getattr__(self, name):
        if name.endswith("Hook"):
            return lambda req: api_pb2.ContainerResourceHookResponse()
        raise AttributeError(name)

    def PreCreateContainerHook(self, req):
        assert req.pod_meta.name == "web-0"
        assert req.container_meta.name == "app"
        return api_pb2.ContainerResourceHookResponse(
            resources=api_pb2.LinuxContainerResources(
                cpu_shares=512, cpuset_cpus="0-3"))

    def PreUpdateContainerResourcesHook(self, req):
        assert req.container_meta.id
        return api_pb2.ContainerResourceHookResponse(
            resources=api_pb2.LinuxContainerResources(
                memory_limit_bytes=2 * 1024**3))


def _post(sock, path, payload):
    conn = _UnixHTTPConnection(str(sock))
    body = json.dumps(payload).encode()
    conn.request("POST", path, body=body,
                 headers={"Content-Type": "application/json",
                          "Content-Length": str(len(body))})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, json.loads(data) if data else None


def _get(sock, path):
    conn = _UnixHTTPConnection(str(sock))
    conn.request("GET", path)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, json.loads(data) if data else None


@pytest.fixture
def stack(tmp_path):
    backend_sock = tmp_path / "dockerd.sock"
    proxy_sock = tmp_path / "proxy.sock"
    daemon = FakeDockerDaemon(str(backend_sock))
    daemon.start()
    proxy = DockerProxyServer(str(proxy_sock), str(backend_sock),
                              hook_client=InProcessHookClient(_Hooks()))
    proxy.start()
    yield proxy_sock, daemon, proxy
    proxy.stop()
    daemon.stop()


CREATE = {
    "Image": "registry/app:v1",
    "Labels": {
        "io.kubernetes.pod.name": "web-0",
        "io.kubernetes.pod.namespace": "default",
        "io.kubernetes.pod.uid": "uid-1",
        "io.kubernetes.container.name": "app",
    },
    "HostConfig": {"CpuShares": 1024, "Memory": 1024**3},
}


def test_create_runs_hook_and_mutates_host_config(stack):
    proxy_sock, daemon, proxy = stack
    status, resp = _post(proxy_sock,
                         "/v1.43/containers/create?name=k8s_app_web-0",
                         CREATE)
    assert status == 201
    cid = resp["Id"]
    ctr = daemon.containers[cid]
    # the hook's resources overlaid the request before the daemon saw it
    assert ctr["HostConfig"]["CpuShares"] == 512
    assert ctr["HostConfig"]["CpusetCpus"] == "0-3"
    assert ctr["HostConfig"]["Memory"] == 1024**3  # untouched field kept
    # id -> meta binding for later lifecycle hooks
    assert cid in proxy.container_store


def test_update_intercepted_and_merged(stack):
    proxy_sock, daemon, proxy = stack
    _status, resp = _post(proxy_sock, "/v1.43/containers/create", CREATE)
    cid = resp["Id"]
    status, _ = _post(proxy_sock, f"/v1.43/containers/{cid}/update",
                      {"CpuQuota": 50000})
    assert status == 200
    hc = daemon.containers[cid]["HostConfig"]
    assert hc["CpuQuota"] == 50000
    assert hc["Memory"] == 2 * 1024**3  # hook's bump merged in


def test_start_stop_pass_through_with_hooks(stack):
    proxy_sock, daemon, proxy = stack
    _status, resp = _post(proxy_sock, "/v1.43/containers/create", CREATE)
    cid = resp["Id"]
    status, _ = _post(proxy_sock, f"/v1.43/containers/{cid}/start", {})
    assert status == 204
    assert daemon.containers[cid]["State"]["Status"] == "running"
    status, _ = _post(proxy_sock, f"/v1.43/containers/{cid}/stop", {})
    assert status == 204
    assert daemon.containers[cid]["State"]["Status"] == "exited"
    # post-stop hook ran AFTER the daemon confirmed; meta dropped (no leak)
    assert cid not in proxy.container_store


def test_unintercepted_paths_pass_through(stack):
    proxy_sock, daemon, proxy = stack
    status, body = _get(proxy_sock, "/v1.43/_ping")
    assert status == 200 and body == "OK"
    _status, resp = _post(proxy_sock, "/v1.43/containers/create", CREATE)
    status, ctr = _get(proxy_sock, f"/v1.43/containers/{resp['Id']}/json")
    assert status == 200 and ctr["Id"] == resp["Id"]


class _DeadHooks:
    def call(self, method, request):
        raise ConnectionError("hook server down")


def test_failure_policy_fail_aborts_and_ignore_forwards(tmp_path):
    backend_sock = tmp_path / "dockerd.sock"
    daemon = FakeDockerDaemon(str(backend_sock))
    daemon.start()
    try:
        fail_sock = tmp_path / "fail.sock"
        proxy_fail = DockerProxyServer(
            str(fail_sock), str(backend_sock), hook_client=_DeadHooks(),
            failure_policy=FailurePolicy.FAIL)
        proxy_fail.start()
        status, _ = _post(fail_sock, "/v1.43/containers/create", CREATE)
        assert status == 502
        assert not daemon.containers  # never reached the daemon
        proxy_fail.stop()

        ign_sock = tmp_path / "ignore.sock"
        proxy_ign = DockerProxyServer(
            str(ign_sock), str(backend_sock), hook_client=_DeadHooks(),
            failure_policy=FailurePolicy.IGNORE)
        proxy_ign.start()
        status, resp = _post(ign_sock, "/v1.43/containers/create", CREATE)
        assert status == 201
        # degraded: the ORIGINAL request went through unmutated
        assert daemon.containers[resp["Id"]]["HostConfig"]["CpuShares"] == 1024
        proxy_ign.stop()
    finally:
        daemon.stop()


def test_attach_upgrade_streams_bytes_bidirectionally(stack):
    """kubectl exec/attach shape: a Connection-Upgrade request tunnels
    through the proxy byte-for-byte — 101 from the daemon, then multiple
    echo round-trips on the hijacked duplex stream."""
    import socket

    proxy_sock, daemon, proxy = stack
    _post(proxy_sock, "/v1.41/containers/create?name=k8s_app", CREATE)
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.settimeout(10.0)
    s.connect(str(proxy_sock))
    s.sendall(b"POST /v1.41/containers/ctr-1/attach?stream=1 HTTP/1.1\r\n"
              b"Host: docker\r\nConnection: Upgrade\r\nUpgrade: tcp\r\n"
              b"Content-Length: 0\r\n\r\n")
    head = b""
    while b"\r\n\r\n" not in head:
        head += s.recv(4096)
    assert head.startswith(b"HTTP/1.1 101"), head
    assert b"application/vnd.docker.raw-stream" in head
    stream_tail = head.split(b"\r\n\r\n", 1)[1]
    for payload in (b"hello", b"stdin-bytes-2", b"\x00\x01binary\xff"):
        s.sendall(payload)
        want = b"echo:" + payload
        buf = stream_tail
        stream_tail = b""
        while len(buf) < len(want):
            chunk = s.recv(4096)
            assert chunk, f"stream closed early, got {buf!r}"
            buf += chunk
        assert buf == want
    s.close()


def test_attach_upgrade_backend_down_returns_502(tmp_path):
    import socket

    proxy_sock = tmp_path / "proxy.sock"
    proxy = DockerProxyServer(str(proxy_sock), str(tmp_path / "nope.sock"))
    proxy.start()
    try:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(10.0)
        s.connect(str(proxy_sock))
        s.sendall(b"POST /v1.41/containers/x/attach HTTP/1.1\r\n"
                  b"Host: d\r\nConnection: Upgrade\r\nUpgrade: tcp\r\n"
                  b"Content-Length: 0\r\n\r\n")
        head = b""
        while b"\r\n\r\n" not in head:
            head += s.recv(4096)
        assert b"502" in head.split(b"\r\n", 1)[0]
        s.close()
    finally:
        proxy.stop()


def test_restart_after_unclean_shutdown_rebinds_stale_socket(tmp_path):
    """allow_reuse_address is a no-op for unix sockets: a stale socket file
    from an unclean shutdown must be unlinked on start, not crash it."""
    import socket

    backend_sock = tmp_path / "dockerd.sock"
    proxy_sock = tmp_path / "proxy.sock"
    # plant a stale bound-then-abandoned socket file at the proxy path
    stale = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    stale.bind(str(proxy_sock))
    stale.close()  # file remains on disk
    daemon = FakeDockerDaemon(str(backend_sock))
    daemon.start()
    proxy = DockerProxyServer(str(proxy_sock), str(backend_sock))
    proxy.start()  # must not raise 'Address already in use'
    try:
        status, _ = _get(proxy_sock, "/v1.41/_ping")
        assert status == 200
    finally:
        proxy.stop()
        daemon.stop()


def test_stop_404_drops_container_store_entry(stack):
    """A stop answered 404 (container already gone daemon-side) must clean
    the proxy's meta entry — no later DELETE is guaranteed to come."""
    proxy_sock, daemon, proxy = stack
    _post(proxy_sock, "/v1.41/containers/create?name=k8s_app", CREATE)
    assert "ctr-1" in proxy.container_store
    with daemon._lock:
        del daemon.containers["ctr-1"]  # daemon-side disappearance
    status, _ = _post(proxy_sock, "/v1.41/containers/ctr-1/stop", {})
    assert status == 404
    assert "ctr-1" not in proxy.container_store


def test_double_start_does_not_destroy_live_proxy(tmp_path):
    """The stale-socket unlink probes for liveness first: a second instance
    must fail its bind, not silently unlink a live proxy's endpoint."""
    backend_sock = tmp_path / "dockerd.sock"
    proxy_sock = tmp_path / "proxy.sock"
    daemon = FakeDockerDaemon(str(backend_sock))
    daemon.start()
    proxy_a = DockerProxyServer(str(proxy_sock), str(backend_sock))
    proxy_a.start()
    proxy_b = DockerProxyServer(str(proxy_sock), str(backend_sock))
    try:
        with pytest.raises(OSError):
            proxy_b.start()
        status, _ = _get(proxy_sock, "/v1.41/_ping")  # A still serves
        assert status == 200
    finally:
        proxy_b.stop()
        proxy_a.stop()
        daemon.stop()


def test_stop_retry_after_404_fires_no_blank_hook(stack):
    """A stop retried after an earlier 404 (entry already popped) must not
    deliver a second PostStop hook with blank metadata."""
    proxy_sock, daemon, proxy = stack
    fired = []
    orig = proxy._call_hook

    def spy(method, request):
        if method == "PostStopContainerHook":
            fired.append(request)
        return orig(method, request)

    proxy._call_hook = spy
    _post(proxy_sock, "/v1.41/containers/create?name=k8s_app", CREATE)
    with daemon._lock:
        del daemon.containers["ctr-1"]
    _post(proxy_sock, "/v1.41/containers/ctr-1/stop", {})  # 404: hook fires
    _post(proxy_sock, "/v1.41/containers/ctr-1/stop", {})  # retry: no hook
    _post(proxy_sock, "/v1.41/containers/never-tracked/stop", {})
    assert len(fired) == 1
    assert fired[0].pod_meta.name == "web-0"  # real meta, never blank
