"""CRI proxy e2e over real gRPC/UDS sockets: kubelet-shaped client -> proxy
socket -> hook server socket -> backend (fake containerd) socket, matching the
reference koord-runtime-proxy topology (pkg/runtimeproxy/server/cri/)."""

import os
import tempfile

import grpc
import pytest

from koordinator_tpu.runtimeproxy import api_pb2, cri_pb2
from koordinator_tpu.runtimeproxy.criserver import (
    CRIClient,
    CRIProxyServer,
    FakeContainerdServer,
)
from koordinator_tpu.runtimeproxy.hookclient import HookClient, serve_hook_service
from koordinator_tpu.runtimeproxy.server import FailurePolicy


class RecordingHookHandler:
    """Hook server that tags everything it can rewrite, and records requests."""

    def __init__(self):
        self.calls = []

    def _record(self, method, request):
        self.calls.append((method, request))

    def PreRunPodSandboxHook(self, request):
        self._record("PreRunPodSandboxHook", request)
        res = api_pb2.PodSandboxHookResponse(cgroup_parent="/kubepods/ls")
        res.annotations["koordinator.sh/hooked"] = "true"
        return res

    def PostStopPodSandboxHook(self, request):
        self._record("PostStopPodSandboxHook", request)
        return api_pb2.PodSandboxHookResponse()

    def PreCreateContainerHook(self, request):
        self._record("PreCreateContainerHook", request)
        res = api_pb2.ContainerResourceHookResponse(
            resources=api_pb2.LinuxContainerResources(
                cpu_shares=512, cpuset_cpus="0-3", cpu_bvt_warp_ns=2
            )
        )
        res.env["KOORD_QOS"] = "LS"
        return res

    def PreStartContainerHook(self, request):
        self._record("PreStartContainerHook", request)
        return api_pb2.ContainerResourceHookResponse()

    def PostStartContainerHook(self, request):
        self._record("PostStartContainerHook", request)
        return api_pb2.ContainerResourceHookResponse()

    def PreUpdateContainerResourcesHook(self, request):
        self._record("PreUpdateContainerResourcesHook", request)
        return api_pb2.ContainerResourceHookResponse(
            resources=api_pb2.LinuxContainerResources(cpu_quota=150000)
        )

    def PostStopContainerHook(self, request):
        self._record("PostStopContainerHook", request)
        return api_pb2.ContainerResourceHookResponse()


@pytest.fixture
def sockets():
    with tempfile.TemporaryDirectory() as tmp:
        yield (os.path.join(tmp, "proxy.sock"),
               os.path.join(tmp, "containerd.sock"),
               os.path.join(tmp, "hooks.sock"))


@pytest.fixture
def topology(sockets):
    """hook server + fake containerd + proxy, all on real UDS gRPC."""
    proxy_sock, backend_sock, hook_sock = sockets
    handler = RecordingHookHandler()
    hook_server = serve_hook_service(handler, hook_sock)
    backend = FakeContainerdServer(backend_sock)
    backend.start()
    proxy = CRIProxyServer(proxy_sock, backend_sock,
                           hook_client=HookClient(hook_sock))
    proxy.start()
    kubelet = CRIClient(proxy_sock)
    yield kubelet, proxy, backend, handler, hook_server, sockets
    kubelet.close()
    proxy.stop()
    backend.stop()
    hook_server.stop(grace=None)


def run_sandbox_request(name="web-0", uid="uid-1"):
    req = cri_pb2.RunPodSandboxRequest()
    req.config.metadata.name = name
    req.config.metadata.namespace = "default"
    req.config.metadata.uid = uid
    req.config.labels["app"] = name
    req.config.linux.cgroup_parent = "/kubepods/burstable"
    return req


def create_container_request(sandbox_id, name="main"):
    req = cri_pb2.CreateContainerRequest(pod_sandbox_id=sandbox_id)
    req.config.metadata.name = name
    req.config.envs.add(key="PATH", value="/bin")
    req.config.envs.add(key="KOORD_QOS", value="BE")  # hook must override
    req.config.linux.resources.cpu_shares = 1024
    req.config.linux.resources.memory_limit_in_bytes = 1 << 30
    return req


def test_full_lifecycle_through_real_sockets(topology):
    kubelet, proxy, backend, handler, _, _ = topology

    sandbox = kubelet.call("RunPodSandbox", run_sandbox_request())
    assert sandbox.pod_sandbox_id == "sandbox-1"
    method, forwarded = backend.requests[-1]
    assert method == "RunPodSandbox"
    # hook mutations arrived at containerd
    assert forwarded.config.annotations["koordinator.sh/hooked"] == "true"
    assert forwarded.config.linux.cgroup_parent == "/kubepods/ls"

    created = kubelet.call(
        "CreateContainer", create_container_request(sandbox.pod_sandbox_id)
    )
    method, forwarded = backend.requests[-1]
    res = forwarded.config.linux.resources
    assert res.cpu_shares == 512               # hook override
    assert res.memory_limit_in_bytes == 1 << 30  # original preserved
    assert res.cpuset_cpus == "0-3"
    assert res.unified["cpu.bvt_warp_ns"] == "2"
    env = {kv.key: kv.value for kv in forwarded.config.envs}
    # PATH preserved; pre-existing KOORD_QOS=BE overridden by the hook's LS
    assert env == {"PATH": "/bin", "KOORD_QOS": "LS"}
    assert len(forwarded.config.envs) == 2  # override, not duplicate
    # the hook saw the pod context resolved from the proxy's store
    hook_req = handler.calls[-1][1]
    assert hook_req.pod_meta.name == "web-0"
    assert hook_req.pod_meta.cgroup_parent == "/kubepods/ls"

    kubelet.call("StartContainer",
                 cri_pb2.StartContainerRequest(container_id=created.container_id))
    assert handler.calls[-1][0] == "PreStartContainerHook"

    kubelet.call(
        "UpdateContainerResources",
        cri_pb2.UpdateContainerResourcesRequest(
            container_id=created.container_id,
            linux=cri_pb2.LinuxContainerResources(cpu_quota=100000),
        ),
    )
    method, forwarded = backend.requests[-1]
    assert forwarded.linux.cpu_quota == 150000  # hook override

    kubelet.call("StopContainer",
                 cri_pb2.StopContainerRequest(container_id=created.container_id))
    assert handler.calls[-1][0] == "PostStopContainerHook"
    assert handler.calls[-1][1].container_meta.id == created.container_id

    kubelet.call("StopPodSandbox",
                 cri_pb2.StopPodSandboxRequest(
                     pod_sandbox_id=sandbox.pod_sandbox_id))
    assert handler.calls[-1][0] == "PostStopPodSandboxHook"
    assert handler.calls[-1][1].pod_meta.name == "web-0"


def test_unknown_methods_pass_through_as_raw_bytes(topology):
    kubelet, _, backend, _, _, _ = topology
    payload = cri_pb2.VersionRequest(version="v1").SerializeToString()
    raw = kubelet.call_raw("Version", payload)
    version = cri_pb2.VersionResponse.FromString(raw)
    assert version.runtime_name == "fake-containerd"
    assert backend.raw_calls == [("Version", payload)]


def test_hook_server_death_ignore_policy(topology):
    kubelet, proxy, backend, _, hook_server, _ = topology
    hook_server.stop(grace=None)
    sandbox = kubelet.call("RunPodSandbox", run_sandbox_request())
    created = kubelet.call(
        "CreateContainer", create_container_request(sandbox.pod_sandbox_id)
    )
    assert created.container_id
    _, forwarded = backend.requests[-1]
    # no hook: original request forwarded untouched
    assert forwarded.config.linux.resources.cpu_shares == 1024
    assert forwarded.config.linux.resources.cpuset_cpus == ""


def test_hook_server_death_fail_policy(sockets):
    proxy_sock, backend_sock, hook_sock = sockets
    hook_server = serve_hook_service(RecordingHookHandler(), hook_sock)
    backend = FakeContainerdServer(backend_sock)
    backend.start()
    proxy = CRIProxyServer(proxy_sock, backend_sock,
                           hook_client=HookClient(hook_sock),
                           failure_policy=FailurePolicy.FAIL)
    proxy.start()
    kubelet = CRIClient(proxy_sock)
    try:
        hook_server.stop(grace=None)
        with pytest.raises(grpc.RpcError) as err:
            kubelet.call("RunPodSandbox", run_sandbox_request())
        assert err.value.code() == grpc.StatusCode.INTERNAL
        # nothing beyond the startup failover List* reached containerd
        assert [m for m, _ in backend.requests] == [
            "ListPodSandbox", "ListContainers"
        ]
    finally:
        kubelet.close()
        proxy.stop()
        backend.stop()


def test_failover_rebuilds_store_from_backend(sockets):
    """Proxy restart: the new instance replays List* from the backend so hook
    requests keep their pod/container context (criserver.go failOver)."""
    proxy_sock, backend_sock, hook_sock = sockets
    handler = RecordingHookHandler()
    hook_server = serve_hook_service(handler, hook_sock)
    backend = FakeContainerdServer(backend_sock)
    backend.start()

    proxy = CRIProxyServer(proxy_sock, backend_sock,
                           hook_client=HookClient(hook_sock))
    proxy.start()
    kubelet = CRIClient(proxy_sock)
    sandbox = kubelet.call("RunPodSandbox", run_sandbox_request())
    created = kubelet.call(
        "CreateContainer", create_container_request(sandbox.pod_sandbox_id)
    )
    kubelet.close()
    proxy.stop()

    proxy2_sock = proxy_sock + "2"
    proxy2 = CRIProxyServer(proxy2_sock, backend_sock,
                            hook_client=HookClient(hook_sock))
    proxy2.start()
    kubelet2 = CRIClient(proxy2_sock)
    try:
        assert sandbox.pod_sandbox_id in proxy2.pod_store
        kubelet2.call(
            "UpdateContainerResources",
            cri_pb2.UpdateContainerResourcesRequest(
                container_id=created.container_id,
                linux=cri_pb2.LinuxContainerResources(cpu_quota=50000),
            ),
        )
        method, hook_req = handler.calls[-1]
        assert method == "PreUpdateContainerResourcesHook"
        assert hook_req.pod_meta.name == "web-0"  # context survived restart
        assert hook_req.container_meta.name == "main"
    finally:
        kubelet2.close()
        proxy2.stop()
        backend.stop()
        hook_server.stop(grace=None)
