"""Tests for the descheduler eviction machinery: controllerfinder,
evictability filter, PDB enforcement, evictor variants (reference
pkg/descheduler/evictions, controllers/migration/{evictor,controllerfinder})."""

import pytest

from koordinator_tpu.api.objects import (
    LABEL_POD_QOS,
    ObjectMeta,
    Pod,
    PodDisruptionBudget,
    PodMigrationJob,
    PodSpec,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import (
    KIND_PDB,
    KIND_POD,
    KIND_POD_MIGRATION_JOB,
    ObjectStore,
)
from koordinator_tpu.descheduler.evictions import (
    ANNOTATION_EVICTABLE,
    ANNOTATION_SOFT_EVICTION,
    ControllerFinder,
    DeleteEvictor,
    EvictionAPIEvictor,
    EvictionBlocked,
    SoftEvictor,
    check_pdbs,
    is_evictable,
)
from koordinator_tpu.descheduler.migration import MigrationController

GIB = 1024**3
NOW = 1_000_000.0


def mk_pod(name, owner=("ReplicaSet", "rs1"), labels=None, phase="Running",
           node="n1", prio=5500, annotations=None):
    return Pod(
        meta=ObjectMeta(name=name, owner_kind=owner[0], owner_name=owner[1],
                        labels={LABEL_POD_QOS: "BE", **(labels or {})},
                        annotations=annotations or {},
                        creation_timestamp=NOW),
        spec=PodSpec(node_name=node, priority=prio,
                     requests=ResourceList.of(cpu=1000, memory=GIB)),
        phase=phase)


class TestControllerFinder:
    def test_workload_members_and_health(self):
        store = ObjectStore()
        for i, phase in enumerate(["Running", "Running", "Failed"]):
            store.add(KIND_POD, mk_pod(f"p{i}", phase=phase))
        store.add(KIND_POD, mk_pod("other", owner=("ReplicaSet", "rs2")))
        finder = ControllerFinder(store)
        wl = finder.workload_of(store.get(KIND_POD, "default/p0"))
        assert wl.workload == "ReplicaSet/rs1"
        assert wl.replicas == 3
        assert wl.healthy == 2

    def test_bare_pod(self):
        store = ObjectStore()
        pod = mk_pod("solo", owner=("", ""))
        store.add(KIND_POD, pod)
        wl = ControllerFinder(store).workload_of(pod)
        assert wl.workload == "" and wl.replicas == 1 and wl.healthy == 1


class TestEvictability:
    def test_filter_chain(self):
        assert is_evictable(mk_pod("ok"))[0]
        assert not is_evictable(mk_pod("ds", owner=("DaemonSet", "d")))[0]
        assert not is_evictable(mk_pod("bare", owner=("", "")))[0]
        assert not is_evictable(mk_pod("crit", prio=2_000_000_000))[0]
        assert not is_evictable(mk_pod("done", phase="Succeeded"))[0]
        # explicit annotation overrides in both directions
        assert is_evictable(mk_pod("forced", owner=("", ""),
                                   annotations={ANNOTATION_EVICTABLE: "true"}))[0]
        assert not is_evictable(mk_pod("pinned",
                                       annotations={ANNOTATION_EVICTABLE: "false"}))[0]


class TestPDB:
    def _store(self, n_healthy, min_available=None, max_unavailable=None):
        store = ObjectStore()
        for i in range(n_healthy):
            store.add(KIND_POD, mk_pod(f"p{i}", labels={"app": "web"}))
        store.add(KIND_PDB, PodDisruptionBudget(
            meta=ObjectMeta(name="pdb"),
            selector={"app": "web"},
            min_available=min_available, max_unavailable=max_unavailable))
        return store

    def test_min_available_blocks(self):
        store = self._store(2, min_available=2)
        pod = store.get(KIND_POD, "default/p0")
        assert check_pdbs(store, pod) is not None
        with pytest.raises(EvictionBlocked):
            EvictionAPIEvictor(store).evict(pod, "test")

    def test_min_available_allows_with_headroom(self):
        store = self._store(3, min_available=2)
        pod = store.get(KIND_POD, "default/p0")
        assert check_pdbs(store, pod) is None
        EvictionAPIEvictor(store).evict(pod, "test")
        assert store.get(KIND_POD, "default/p0").phase == "Failed"

    def test_max_unavailable(self):
        store = self._store(2, max_unavailable=1)
        pod = store.get(KIND_POD, "default/p0")
        assert check_pdbs(store, pod) is None  # 0+1 <= 1
        EvictionAPIEvictor(store).evict(pod, "test")
        other = store.get(KIND_POD, "default/p1")
        assert check_pdbs(store, other) is not None  # 1+1 > 1

    def test_non_matching_pdb_ignored(self):
        store = self._store(1, min_available=1)
        outsider = mk_pod("out", labels={"app": "db"})
        store.add(KIND_POD, outsider)
        assert check_pdbs(store, outsider) is None

    def test_pending_pods_are_not_healthy(self):
        # 2 running + 2 pending, minAvailable=2: policy/v1 counts only ready
        # pods as healthy, so evicting a running pod must be blocked even
        # though 4 pods are "not terminated".
        store = self._store(2, min_available=2)
        for i in range(2):
            store.add(KIND_POD, mk_pod(f"pend{i}", labels={"app": "web"},
                                       phase="Pending", node=""))
        pod = store.get(KIND_POD, "default/p0")
        assert check_pdbs(store, pod) is not None

    def test_unassigned_running_phase_not_healthy(self):
        store = self._store(2, min_available=2)
        # phase says Running but never scheduled: still not healthy
        store.add(KIND_POD, mk_pod("ghost", labels={"app": "web"}, node=""))
        pod = store.get(KIND_POD, "default/p0")
        assert check_pdbs(store, pod) is not None

    def test_evicting_unhealthy_pod_consumes_no_budget(self):
        # 2 running + 1 pending, minAvailable=2: the pending victim does not
        # lower the healthy count, so its eviction must be ALLOWED even
        # though the budget has zero headroom
        store = self._store(2, min_available=2)
        pending = mk_pod("pend", labels={"app": "web"}, phase="Pending",
                         node="")
        store.add(KIND_POD, pending)
        assert check_pdbs(store, pending) is None
        # same for maxUnavailable: an already-unavailable victim adds nothing
        # (2 running + 1 pending, maxUnavailable=1: the pending pod already
        # uses the budget, so only its own zero-cost eviction is allowed)
        store2 = self._store(2, max_unavailable=1)
        pending2 = mk_pod("pend", labels={"app": "web"}, phase="Pending",
                          node="")
        store2.add(KIND_POD, pending2)
        assert check_pdbs(store2, pending2) is None
        assert check_pdbs(store2, store2.get(KIND_POD, "default/p0")) is not None


class TestEvictorVariants:
    def test_delete_evictor_removes_pod_and_skips_pdb(self):
        store = ObjectStore()
        store.add(KIND_POD, mk_pod("p0", labels={"app": "web"}))
        store.add(KIND_PDB, PodDisruptionBudget(
            meta=ObjectMeta(name="pdb"), selector={"app": "web"},
            min_available=1))
        pod = store.get(KIND_POD, "default/p0")
        DeleteEvictor(store).evict(pod, "forced")
        assert store.get(KIND_POD, "default/p0") is None

    def test_soft_evictor_annotates_only(self):
        store = ObjectStore()
        store.add(KIND_POD, mk_pod("p0"))
        pod = store.get(KIND_POD, "default/p0")
        SoftEvictor(store).evict(pod, "drain")
        got = store.get(KIND_POD, "default/p0")
        assert got.phase == "Running"
        assert got.meta.annotations[ANNOTATION_SOFT_EVICTION] == "drain"


class TestMigrationEvictionIntegration:
    def test_pdb_blocked_migration_fails_job(self):
        store = ObjectStore()
        for i in range(2):
            store.add(KIND_POD, mk_pod(f"p{i}", labels={"app": "web"}))
        store.add(KIND_PDB, PodDisruptionBudget(
            meta=ObjectMeta(name="pdb"), selector={"app": "web"},
            min_available=2))
        store.add(KIND_POD_MIGRATION_JOB, PodMigrationJob(
            meta=ObjectMeta(name="job", creation_timestamp=NOW),
            pod_namespace="default", pod_name="p0", mode="EvictDirectly"))
        ctl = MigrationController(store)
        ctl.reconcile(now=NOW)
        job = store.get(KIND_POD_MIGRATION_JOB, "default/job")
        assert job.phase == "Failed"
        assert "pdb" in job.message

    def test_single_replica_guard(self):
        store = ObjectStore()
        store.add(KIND_POD, mk_pod("only"))
        store.add(KIND_POD_MIGRATION_JOB, PodMigrationJob(
            meta=ObjectMeta(name="job", creation_timestamp=NOW),
            pod_namespace="default", pod_name="only", mode="EvictDirectly"))
        ctl = MigrationController(store)
        ctl.reconcile(now=NOW)
        job = store.get(KIND_POD_MIGRATION_JOB, "default/job")
        assert job.phase == "Failed"
        assert "single healthy replica" in job.message
