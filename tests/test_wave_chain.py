"""Wave-parallel kernel parity: build_wave_full_chain_step must produce
bit-identical bindings and state rollups to the serial kernel on every config
the parity suite covers, at several wave widths (including degenerate W=1,
which IS the serial walk, and tiny W that forces many cuts)."""

import numpy as np
import pytest

from koordinator_tpu.models.full_chain import build_full_chain_step
from koordinator_tpu.models.wave_chain import build_wave_full_chain_step
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.scheduler.snapshot import build_full_chain_inputs
from koordinator_tpu.testing import synth_full_cluster


def _build(seed, num_nodes=30, num_pods=60, args=None, **kw):
    args = args or LoadAwareArgs()
    cluster, state = synth_full_cluster(num_nodes, num_pods, seed=seed, **kw)
    fc, pods, nodes, tree, gang_index, ng, ngroups = build_full_chain_inputs(
        state, args
    )
    return args, fc, pods, ng, ngroups


def _assert_match(args, fc, ng, ngroups, wave):
    serial = build_full_chain_step(args, ng, ngroups)
    wave_step = build_wave_full_chain_step(args, ng, ngroups, wave=wave)
    chosen_s, requested_s, quota_s = serial(fc)
    chosen_w, requested_w, quota_w = wave_step(fc)
    np.testing.assert_array_equal(np.asarray(chosen_s), np.asarray(chosen_w))
    np.testing.assert_allclose(
        np.asarray(requested_s), np.asarray(requested_w), rtol=0, atol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(quota_s), np.asarray(quota_w), rtol=0, atol=1e-4
    )
    return np.asarray(chosen_s)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_wave_matches_serial_mixed_configs(seed):
    args, fc, pods, ng, ngroups = _build(seed)
    chosen = _assert_match(args, fc, ng, ngroups, wave=64)
    assert (chosen[: len(pods.keys)] >= 0).sum() > 0


@pytest.mark.parametrize("wave", [1, 7, 64, 512])
def test_wave_widths_agree(wave):
    args, fc, pods, ng, ngroups = _build(2)
    _assert_match(args, fc, ng, ngroups, wave=wave)


def test_wave_all_topology():
    args, fc, pods, ng, ngroups = _build(
        5, topology_fraction=1.0, lsr_fraction=0.4
    )
    _assert_match(args, fc, ng, ngroups, wave=32)


def test_wave_no_quota_no_gang():
    args, fc, pods, ng, ngroups = _build(9, num_quotas=0, num_gangs=0)
    _assert_match(args, fc, ng, ngroups, wave=32)


def test_wave_tiny_cluster_heavy_contention():
    """4 nodes x 40 pods: nearly every wave hits a node collision, driving
    the cut machinery hard."""
    args, fc, pods, ng, ngroups = _build(13, num_nodes=4, num_pods=40)
    _assert_match(args, fc, ng, ngroups, wave=16)


def test_wave_tight_quota_forces_flips():
    """Shrunken quota runtimes: in-wave usage exhausts groups mid-window, so
    the exact prefix re-admission must cut (not just chain overlap)."""
    args, fc, pods, ng, ngroups = _build(7, num_nodes=20, num_pods=80)
    fc = fc._replace(
        quota_runtime=(np.asarray(fc.quota_runtime) * 0.15).astype(np.float32)
    )
    chosen = _assert_match(args, fc, ng, ngroups, wave=64)
    # the squeeze must actually reject some quota pods
    quota_pods = np.asarray(fc.quota_id)[: len(pods.keys)] >= 0
    assert (chosen[: len(pods.keys)][quota_pods] < 0).any()


def test_wave_with_taints():
    args, fc, pods, ng, ngroups = _build(21, num_nodes=24, num_pods=60,
                                         taint_fraction=0.4)
    _assert_match(args, fc, ng, ngroups, wave=32)
