"""frameworkext transformer extension point: custom Before/After
PreFilter/Filter/Score hooks rewrite pod and node views without touching
snapshot code (reference pkg/scheduler/frameworkext/interface.go:78-97)."""

import dataclasses

import numpy as np
import pytest

from koordinator_tpu.api.objects import Node, ObjectMeta, Pod, PodSpec
from koordinator_tpu.api.resources import ResourceList, ResourceName
from koordinator_tpu.client.store import KIND_NODE, KIND_POD, ObjectStore
from koordinator_tpu.scheduler.cycle import Scheduler
from koordinator_tpu.scheduler.frameworkext import (
    FilterTransformer,
    PreFilterTransformer,
    ScoreTransformer,
)

GIB = 1024**3
NOW = 1_700_000_000.0


def make_cluster(n_nodes=2, cpu=8000):
    store = ObjectStore()
    for i in range(n_nodes):
        store.add(
            KIND_NODE,
            Node(meta=ObjectMeta(name=f"node-{i}", namespace=""),
                 allocatable=ResourceList.of(cpu=cpu, memory=32 * GIB, pods=110)),
        )
    return store


def make_pod(name="p0", cpu=4000, annotations=None):
    return Pod(
        meta=ObjectMeta(name=name, uid=f"uid-{name}",
                        annotations=dict(annotations or {})),
        spec=PodSpec(requests=ResourceList.of(cpu=cpu, memory=GIB)),
    )


class HalveOverRequestTransformer(PreFilterTransformer):
    """Rewrites the pod VIEW: pods annotated half-me schedule with half their
    cpu request. Never mutates the stored pod."""

    name = "HalveOverRequest"

    def before_prefilter(self, pod, ctx):
        if pod.meta.annotations.get("example.com/half-me") != "true":
            return None
        view = dataclasses.replace(
            pod,
            spec=dataclasses.replace(
                pod.spec,
                requests=ResourceList.of(
                    cpu=pod.spec.requests[ResourceName.CPU] // 2,
                    memory=pod.spec.requests[ResourceName.MEMORY],
                ),
            ),
        )
        return view


def test_prefilter_transformer_rewrites_pod_view_without_snapshot_changes():
    store = make_cluster(n_nodes=1, cpu=5000)
    scheduler = Scheduler(store)
    scheduler.extender.register_transformer(HalveOverRequestTransformer())
    # requests 8000m > node 5000m: only the transformed (4000m) view can fit
    pod = make_pod(cpu=8000, annotations={"example.com/half-me": "true"})
    store.add(KIND_POD, pod)
    result = scheduler.run_cycle(now=NOW)
    assert [b.node_name for b in result.bound] == ["node-0"]
    # the transform was a cycle-local view: the stored pod keeps its request
    stored = store.get(KIND_POD, pod.meta.key)
    assert stored.spec.requests[ResourceName.CPU] == 8000
    assert stored.spec.node_name == "node-0"


def test_prefilter_transformer_not_applied_without_annotation():
    store = make_cluster(n_nodes=1, cpu=5000)
    scheduler = Scheduler(store)
    scheduler.extender.register_transformer(HalveOverRequestTransformer())
    store.add(KIND_POD, make_pod(cpu=8000))
    result = scheduler.run_cycle(now=NOW)
    assert result.bound == []
    assert len(result.failed) == 1


class DrainNodeTransformer(FilterTransformer):
    """Rewrites the node view: marks one node's capacity as fully assigned
    (a custom drain) without any snapshot/cycle code knowing about it."""

    name = "DrainNode"

    def __init__(self, node_name):
        self.node_name = node_name

    def before_filter(self, state, ctx):
        for node in state.nodes:
            if node.meta.name == self.node_name:
                state.assigned_requests[self.node_name] = (
                    node.allocatable.to_vector().astype(np.float32)
                )


def test_filter_transformer_rewrites_node_view():
    store = make_cluster(n_nodes=2)
    scheduler = Scheduler(store)
    scheduler.extender.register_transformer(DrainNodeTransformer("node-0"))
    for i in range(3):
        store.add(KIND_POD, make_pod(name=f"p{i}", cpu=1000))
    result = scheduler.run_cycle(now=NOW)
    assert len(result.bound) == 3
    assert {b.node_name for b in result.bound} == {"node-1"}


class PinToNodeScoreTransformer(ScoreTransformer):
    """Rewrites the packed inputs before the kernel: masks node_ok so only
    the pinned node stays eligible (all candidate-set rewrites ride here)."""

    name = "PinToNode"

    def __init__(self, node_idx):
        self.node_idx = node_idx

    def before_score(self, inputs, ctx):
        node_ok = np.asarray(inputs.base.node_ok).copy()
        node_ok[: self.node_idx] = False
        node_ok[self.node_idx + 1:] = False
        return inputs._replace(base=inputs.base._replace(node_ok=node_ok))


def test_score_transformer_rewrites_packed_inputs():
    store = make_cluster(n_nodes=4)
    scheduler = Scheduler(store)
    scheduler.extender.register_transformer(PinToNodeScoreTransformer(2))
    store.add(KIND_POD, make_pod(cpu=500))
    result = scheduler.run_cycle(now=NOW)
    assert [b.node_name for b in result.bound] == ["node-2"]


def test_transformers_chain_in_registration_order():
    store = make_cluster(n_nodes=1)
    scheduler = Scheduler(store)
    calls = []

    class Recorder(PreFilterTransformer):
        def __init__(self, tag):
            self.tag = tag

        def before_prefilter(self, pod, ctx):
            calls.append((self.tag, "before", pod.meta.name))
            return None

        def after_prefilter(self, state, ctx):
            calls.append((self.tag, "after", len(state.pending_pods)))

    scheduler.extender.register_transformer(Recorder("a"))
    scheduler.extender.register_transformer(Recorder("b"))
    store.add(KIND_POD, make_pod(cpu=500))
    scheduler.run_cycle(now=NOW)
    assert calls == [
        ("a", "before", "p0"), ("b", "before", "p0"),
        ("a", "after", 1), ("b", "after", 1),
    ]


def test_preemption_retry_does_not_double_transform():
    """The quota-preemption retry pass must re-run transformers over the
    ORIGINAL queued pods, not the first pass's transformed views — a
    non-idempotent rewrite applied twice would corrupt the view."""
    from koordinator_tpu.api.objects import ElasticQuota, LABEL_QUOTA_NAME
    from koordinator_tpu.client.store import KIND_ELASTIC_QUOTA

    store = make_cluster(n_nodes=1, cpu=4000)
    store.add(KIND_ELASTIC_QUOTA, ElasticQuota(
        meta=ObjectMeta(name="team", namespace="default"),
        max=ResourceList.of(cpu=4000, memory=2 * GIB),
        min=ResourceList.of(cpu=4000, memory=2 * GIB),
    ))
    scheduler = Scheduler(store)
    seen = []

    class TagOnce(PreFilterTransformer):
        name = "TagOnce"

        def before_prefilter(self, pod, ctx):
            seen.append(pod.meta.annotations.get("example.com/transformed"))
            view = dataclasses.replace(
                pod,
                meta=dataclasses.replace(
                    pod.meta,
                    annotations={**pod.meta.annotations,
                                 "example.com/transformed": "true"},
                ),
            )
            return view

    scheduler.extender.register_transformer(TagOnce())
    victim = Pod(
        meta=ObjectMeta(name="victim", uid="uid-victim",
                        labels={LABEL_QUOTA_NAME: "team"},
                        creation_timestamp=NOW - 100),
        spec=PodSpec(node_name="node-0", priority=6000,
                     requests=ResourceList.of(cpu=4000, memory=GIB)),
        phase="Running",
    )
    store.add(KIND_POD, victim)
    contender = Pod(
        meta=ObjectMeta(name="contender", uid="uid-contender",
                        labels={LABEL_QUOTA_NAME: "team"},
                        creation_timestamp=NOW),
        spec=PodSpec(priority=9500,
                     requests=ResourceList.of(cpu=4000, memory=GIB)),
    )
    store.add(KIND_POD, contender)
    result = scheduler.run_cycle(now=NOW)
    assert result.preempted_victims == ["default/victim"]
    assert [b.pod_key for b in result.bound] == ["default/contender"]
    # the retry pass saw the original (untagged) pod, never a tagged view
    assert seen == [None, None]


def test_reservation_restore_registered_as_transformer():
    """The built-in reservation restore now rides the declared extension
    point instead of being hard-coded in the snapshot builder."""
    store = make_cluster()
    scheduler = Scheduler(store)
    assert any(
        t.name == "ReservationRestore" for t in scheduler.extender.transformers
    )
