"""2-process `jax.distributed.initialize()` test: the sharded full-chain step
runs over a global mesh spanning two OS processes (4 virtual CPU devices
each), with gloo collectives crossing the process boundary — the CI-runnable
proof of the DCN/multi-host claim in parallel/mesh.py."""

import os
import socket
import subprocess
import sys

import pytest

WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_full_chain():
    port = _free_port()
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(i), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for proc in procs:
            try:
                out, err = proc.communicate(timeout=300)
            except subprocess.TimeoutExpired:
                pytest.fail("multihost worker timed out")
            assert proc.returncode == 0, f"worker failed:\n{out}\n{err}"
            outs.append(out)
    finally:
        # a failed worker must not strand its sibling in the gloo handshake
        for p in procs:
            if p.poll() is None:
                p.kill()
    digests = [
        line.split()[1]
        for out in outs
        for line in out.splitlines()
        if line.startswith("MULTIHOST_OK")
    ]
    assert len(digests) == 2, f"missing MULTIHOST_OK lines: {outs}"
    # both processes computed identical global bindings
    assert digests[0] == digests[1]
