"""2-process `jax.distributed.initialize()` test: the sharded full-chain step
runs over a global mesh spanning two OS processes (4 virtual CPU devices
each), with gloo collectives crossing the process boundary — the CI-runnable
proof of the DCN/multi-host claim in parallel/mesh.py."""

import os
import socket
import subprocess
import sys

import pytest

WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(num_procs: int, local_devices: int, timeout: int = 420):
    port = _free_port()
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(i), str(num_procs), str(port),
             str(local_devices)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        )
        for i in range(num_procs)
    ]
    outs = []
    try:
        for proc in procs:
            try:
                out, err = proc.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                pytest.fail("multihost worker timed out")
            assert proc.returncode == 0, f"worker failed:\n{out}\n{err}"
            outs.append(out)
    finally:
        # a failed worker must not strand its siblings in the gloo handshake
        for p in procs:
            if p.poll() is None:
                p.kill()
    digests = [
        line.split()[1]
        for out in outs
        for line in out.splitlines()
        if line.startswith("MULTIHOST_OK")
    ]
    assert len(digests) == num_procs, f"missing MULTIHOST_OK lines: {outs}"
    # every process computed identical global results
    assert len(set(digests)) == 1
    return outs


def test_two_process_distributed_full_chain():
    _run_workers(num_procs=2, local_devices=4)


def test_four_process_distributed_2d():
    """4 OS processes x 2 virtual devices = an 8-device (pods=2, nodes=4)
    global mesh where BOTH batch axes shard across process boundaries: the
    full chain's flat node sharding AND the one-shot score matrix's 2-D
    pods x nodes sharding run over gloo, padded 512 x 256 shapes crossing
    every shard boundary, bindings + quota rollups + matrix diffed against
    local single-device runs in each process."""
    outs = _run_workers(num_procs=4, local_devices=2, timeout=600)
    assert any("mesh={'pods': 2, 'nodes': 4}" in o for o in outs), outs
