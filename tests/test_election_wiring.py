"""Leader election wired into the control planes: only leaders act, standbys
take over after lease expiry mid-workload, and no cycle runs twice (ref
cmd/koord-scheduler/app/server.go:227-256, cmd/koord-manager)."""

import json

from koordinator_tpu.api.objects import (
    LABEL_POD_QOS,
    Node,
    NodeMetric,
    NodeMetricInfo,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client import LeaderElector
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_POD,
    ObjectStore,
)
from koordinator_tpu.descheduler.descheduler import Descheduler
from koordinator_tpu.manager import Manager
from koordinator_tpu.scheduler.cycle import Scheduler

GIB = 1024**3
NOW = 1_000_000.0
LEASE_S = 15.0


def _cluster(store, num_nodes=2, num_pods=3):
    for i in range(num_nodes):
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name=f"node-{i}", namespace=""),
            allocatable=ResourceList.of(cpu=16_000, memory=64 * GIB, pods=110),
        ))
        store.add(KIND_NODE_METRIC, NodeMetric(
            meta=ObjectMeta(name=f"node-{i}", namespace=""),
            update_time=NOW - 10,
            node_metric=NodeMetricInfo(
                node_usage=ResourceList.of(cpu=1000, memory=2 * GIB)),
        ))
    for i in range(num_pods):
        store.add(KIND_POD, Pod(
            meta=ObjectMeta(name=f"pod-{i}", labels={LABEL_POD_QOS: "LS"},
                            creation_timestamp=NOW - 100),
            spec=PodSpec(priority=9000,
                         requests=ResourceList.of(cpu=1000, memory=GIB)),
        ))


class TestSchedulerElection:
    def _make(self, store, ident):
        elector = LeaderElector(store, "koord-scheduler", ident,
                                lease_duration_seconds=LEASE_S)
        return Scheduler(store, elector=elector)

    def test_only_leader_schedules_and_no_double_binding(self):
        store = ObjectStore()
        _cluster(store)
        s1 = self._make(store, "sched-1")
        s2 = self._make(store, "sched-2")
        r1 = s1.run_cycle(now=NOW)       # acquires the lease
        r2 = s2.run_cycle(now=NOW + 1)   # standby: must not act
        assert not r1.skipped_not_leader and len(r1.bound) == 3
        assert r2.skipped_not_leader and not r2.bound
        # every pod bound exactly once
        assigned = [p for p in store.list(KIND_POD) if p.is_assigned]
        assert len(assigned) == 3

    def test_standby_takes_over_after_lease_expiry(self):
        store = ObjectStore()
        _cluster(store, num_pods=2)
        s1 = self._make(store, "sched-1")
        s2 = self._make(store, "sched-2")
        r1 = s1.run_cycle(now=NOW)
        assert len(r1.bound) == 2
        # new work arrives; the leader dies (stops renewing)
        store.add(KIND_POD, Pod(
            meta=ObjectMeta(name="late", labels={LABEL_POD_QOS: "LS"},
                            creation_timestamp=NOW),
            spec=PodSpec(priority=9000,
                         requests=ResourceList.of(cpu=1000, memory=GIB)),
        ))
        r2 = s2.run_cycle(now=NOW + 5)
        assert r2.skipped_not_leader  # lease still held
        r2 = s2.run_cycle(now=NOW + LEASE_S + 6)
        assert not r2.skipped_not_leader
        assert [b.pod_key for b in r2.bound] == ["default/late"]
        # the old leader notices it lost the lease and stands down
        r1b = s1.run_cycle(now=NOW + LEASE_S + 7)
        assert r1b.skipped_not_leader


class TestDeschedulerElection:
    def test_only_leader_runs(self):
        store = ObjectStore()
        _cluster(store)
        d1 = Descheduler(store, elector=LeaderElector(
            store, "koord-descheduler", "d1", lease_duration_seconds=LEASE_S))
        d2 = Descheduler(store, elector=LeaderElector(
            store, "koord-descheduler", "d2", lease_duration_seconds=LEASE_S))
        out1 = d1.run_once(now=NOW)
        out2 = d2.run_once(now=NOW + 1)
        assert "skipped_not_leader" not in out1
        assert out2["skipped_not_leader"]
        out2 = d2.run_once(now=NOW + LEASE_S + 2)
        assert "skipped_not_leader" not in out2


class TestManagerElection:
    def test_two_replicas_one_leader_and_failover(self):
        store = ObjectStore()
        _cluster(store)
        m1 = Manager(store, identity="mgr-1",
                     lease_duration_seconds=LEASE_S)
        m2 = Manager(store, identity="mgr-2",
                     lease_duration_seconds=LEASE_S)
        assert m1.tick(now=NOW) is True
        assert m2.tick(now=NOW + 1) is False
        assert m1.is_leader and not m2.is_leader
        assert m1.reconcile_rounds == 1 and m2.reconcile_rounds == 0
        # all four controllers ran under the leader
        assert set(m1.last_changes) == {
            "nodemetric", "noderesource", "nodeslo", "quotaprofile"}
        # leader dies mid-workload; standby takes over after expiry
        assert m2.tick(now=NOW + LEASE_S + 2) is True
        assert m2.is_leader and m2.reconcile_rounds == 1
        # the dead leader's replica, revived, stands down
        assert m1.tick(now=NOW + LEASE_S + 3) is False
        assert not m1.is_leader

    def test_webhook_served_by_standby_too(self):
        from koordinator_tpu.utils.features import MANAGER_GATES

        store = ObjectStore()
        m1 = Manager(store, identity="mgr-1")
        m2 = Manager(store, identity="mgr-2")
        m1.tick(now=NOW)
        assert not m2.is_leader
        # admission rides the store seam regardless of leadership: a node
        # with an amplification ratio is mutated on add
        MANAGER_GATES.set_from_map({"NodeMutatingWebhook": True})
        try:
            ann = {AdmissionServerRatio: json.dumps({"cpu": 2.0})}
            node = Node(meta=ObjectMeta(name="n-adm", namespace="",
                                        annotations=ann),
                        allocatable=ResourceList.of(cpu=8_000, memory=GIB))
            store.add(KIND_NODE, node)
            from koordinator_tpu.api.resources import ResourceName

            assert node.allocatable.get(ResourceName.CPU) == 16_000
        finally:
            MANAGER_GATES.reset()

    def test_stop_releases_lease(self):
        store = ObjectStore()
        m1 = Manager(store, identity="mgr-1")
        m2 = Manager(store, identity="mgr-2")
        m1.tick(now=NOW)
        m1.stop(now=NOW + 1)
        # released lease: the standby acquires on its next tick, no wait
        assert m2.tick(now=NOW + 2) is True


from koordinator_tpu.webhook import AdmissionServer  # noqa: E402

AdmissionServerRatio = AdmissionServer.AMPLIFICATION_RATIO_ANNOTATION
