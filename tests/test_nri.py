"""NRI-mode runtime hooks e2e: fake containerd drives the plugin over UDS.

Reference pkg/koordlet/runtimehooks/nri/server.go: the plugin dials the
runtime's NRI socket, registers, negotiates the event mask via Configure,
then serves RunPodSandbox / CreateContainer / UpdateContainer. These tests
run the REAL hook chain (groupidentity/cpuset/batchresource/... against the
fake cgroup tree) behind a real unix-socket round trip.
"""

import json
import os

import pytest

from koordinator_tpu.api.objects import (
    LABEL_POD_QOS,
    Node,
    ObjectMeta,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import KIND_NODE, ObjectStore
from koordinator_tpu.koordlet import nri_pb2
from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.metriccache import MetricCache
from koordinator_tpu.koordlet.nri import (
    M_CREATE_CONTAINER,
    M_RUN_POD_SANDBOX,
    M_SYNCHRONIZE,
    M_UPDATE_CONTAINER,
    PLUGIN_IDX,
    PLUGIN_NAME,
    FakeContainerdNri,
    NriPlugin,
    event_mask,
)
from koordinator_tpu.koordlet.resourceexecutor import ResourceUpdateExecutor
from koordinator_tpu.koordlet.runtimehooks import RuntimeHooks
from koordinator_tpu.koordlet.statesinformer import StatesInformer
from koordinator_tpu.koordlet.util.system import FakeFS
from koordinator_tpu.runtimeproxy.server import FailurePolicy

GIB = 1024 ** 3


@pytest.fixture
def world(tmp_path):
    fs = FakeFS(use_cgroup_v2=False)
    store = ObjectStore()
    store.add(KIND_NODE, Node(
        meta=ObjectMeta(name="node-0", namespace=""),
        allocatable=ResourceList.of(cpu=32000, memory=64 * GIB)))
    informer = StatesInformer(store, "node-0", MetricCache())
    executor = ResourceUpdateExecutor(fs.config, Auditor())
    hooks = RuntimeHooks(informer, executor)
    sock = str(tmp_path / "nri.sock")
    runtime = FakeContainerdNri(sock)
    plugin = NriPlugin(sock, hooks)
    plugin.start()
    reg = runtime.accept_plugin()
    yield fs, runtime, plugin, reg
    plugin.stop()
    runtime.close()


def _be_sandbox(fs) -> nri_pb2.PodSandbox:
    rel = "kubepods.slice/kubepods-besteffort.slice/pod-be-1"
    fs.set_cgroup(rel, "cgroup.procs", "")
    return nri_pb2.PodSandbox(
        id="sb-1", name="be-pod", namespace="default", uid="be-1",
        labels={LABEL_POD_QOS: "BE"},
        annotations={},
        cgroup_parent=rel,
    )


def test_register_and_configure_mask(world):
    _fs, runtime, plugin, reg = world
    assert (reg.plugin_name, reg.plugin_idx) == (PLUGIN_NAME, PLUGIN_IDX)
    # empty config: plugin answers with its default subscription
    resp = runtime.configure()
    assert resp.events == event_mask(
        ["RunPodSandbox", "CreateContainer", "UpdateContainer"])
    # runtime-provided config narrows the mask (Configure, server.go:124-142)
    resp = runtime.configure(config=json.dumps(
        {"events": ["CreateContainer"]}))
    assert resp.events == event_mask(["CreateContainer"])


def test_run_pod_sandbox_applies_pod_level_writes(world):
    fs, runtime, plugin, _reg = world
    runtime.configure()
    sb = _be_sandbox(fs)
    ok, _ = runtime.call(M_RUN_POD_SANDBOX,
                         nri_pb2.RunPodSandboxRequest(pod=sb))
    assert ok
    assert plugin.handled["RunPodSandbox"] == 1
    # groupidentity wrote the BE bvt value straight through the executor
    # (podCtx.NriDone applies pod-level writes locally)
    from koordinator_tpu.koordlet.util import system as sysutil

    assert fs.get_cgroup(sb.cgroup_parent,
                         sysutil.CPU_BVT_WARP_NS).strip() == "-1"


def test_create_container_returns_adjustment(world):
    fs, runtime, plugin, _reg = world
    runtime.configure()
    rel = "kubepods.slice/pod-ls-1"
    fs.set_cgroup(rel, "cgroup.procs", "")
    sb = nri_pb2.PodSandbox(
        id="sb-2", name="ls-pod", namespace="default", uid="ls-1",
        labels={LABEL_POD_QOS: "LS"},
        annotations={
            "scheduling.koordinator.sh/resource-status": json.dumps(
                {"cpuset": "0-3"}),
        },
        cgroup_parent=rel,
    )
    ctr = nri_pb2.Container(
        id="ctr-1", pod_sandbox_id="sb-2", name="main",
        cgroup_parent=rel + "/ctr-1")
    ok, payload = runtime.call(
        M_CREATE_CONTAINER,
        nri_pb2.CreateContainerRequest(pod=sb, container=ctr))
    assert ok
    resp = nri_pb2.CreateContainerResponse.FromString(payload)
    # the scheduler's cpuset annotation came back as an NRI adjustment,
    # not a local write (containerCtx.NriDone)
    assert resp.adjust.resources.cpuset_cpus == "0-3"


def test_update_container_returns_update(world):
    fs, runtime, plugin, _reg = world
    runtime.configure()
    sb = _be_sandbox(fs)
    ctr = nri_pb2.Container(
        id="ctr-9", pod_sandbox_id=sb.id, name="main",
        cgroup_parent=sb.cgroup_parent + "/ctr-9")
    ok, payload = runtime.call(
        M_UPDATE_CONTAINER,
        nri_pb2.UpdateContainerRequest(pod=sb, container=ctr))
    assert ok
    resp = nri_pb2.UpdateContainerResponse.FromString(payload)
    assert len(resp.updates) == 1
    assert resp.updates[0].container_id == "ctr-9"


def test_synchronize_noop(world):
    _fs, runtime, plugin, _reg = world
    ok, payload = runtime.call(M_SYNCHRONIZE, nri_pb2.SynchronizeRequest())
    assert ok
    assert nri_pb2.SynchronizeResponse.FromString(payload).updates == []


def test_failure_policy_fail_surfaces_hook_error(world, tmp_path):
    fs, runtime, plugin, _reg = world

    class BoomHook:
        name = "Boom"

        def apply(self, ctx):
            raise RuntimeError("boom")

    plugin.hooks.hooks.insert(0, BoomHook())
    plugin.failure_policy = FailurePolicy.FAIL
    sb = _be_sandbox(fs)
    ok, payload = runtime.call(M_RUN_POD_SANDBOX,
                               nri_pb2.RunPodSandboxRequest(pod=sb))
    assert not ok
    assert "boom" in nri_pb2.Error.FromString(payload).message
    # IGNORE: same event succeeds, error recorded (server.go:154-160)
    plugin.failure_policy = FailurePolicy.IGNORE
    ok, _ = runtime.call(M_RUN_POD_SANDBOX,
                         nri_pb2.RunPodSandboxRequest(pod=sb))
    assert ok
    assert any("boom" in e for e in plugin.errors)


def test_start_fails_fast_without_socket(tmp_path):
    fs = FakeFS(use_cgroup_v2=False)
    store = ObjectStore()
    informer = StatesInformer(store, "node-0", MetricCache())
    executor = ResourceUpdateExecutor(fs.config, Auditor())
    plugin = NriPlugin(str(tmp_path / "missing.sock"),
                       RuntimeHooks(informer, executor))
    with pytest.raises(FileNotFoundError):
        plugin.start()
