"""Leader election: only the lease holder runs control loops; a standby takes
over when the leader stops renewing (ref cmd/koord-scheduler/app/server.go:227-256)."""

from koordinator_tpu.api.objects import Node, NodeMetric, NodeMetricInfo, ObjectMeta
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.leaderelection import (
    ElectedRunner,
    Lease,
    LeaderElector,
)
from koordinator_tpu.client.store import (
    KIND_LEASE,
    KIND_NODE,
    KIND_NODE_METRIC,
    ObjectStore,
)

NOW = 1_000_000.0
GIB = 1024**3


def _electors(store, n=2, duration=15.0, **kw):
    return [
        LeaderElector(store, "koord-scheduler", f"replica-{i}",
                      lease_duration_seconds=duration, **kw)
        for i in range(n)
    ]


class TestLeaderElector:
    def test_first_tick_acquires(self):
        store = ObjectStore()
        a, b = _electors(store)
        assert a.tick(NOW) is True
        assert b.tick(NOW) is False
        lease = store.get(KIND_LEASE, "/koord-scheduler")
        assert lease.holder_identity == "replica-0"

    def test_leader_renews_and_standby_waits(self):
        store = ObjectStore()
        a, b = _electors(store)
        a.tick(NOW)
        for t in range(1, 10):
            assert a.tick(NOW + t) is True
            assert b.tick(NOW + t) is False
        assert store.get(KIND_LEASE, "/koord-scheduler").renew_time == NOW + 9

    def test_failover_on_lease_expiry(self):
        store = ObjectStore()
        a, b = _electors(store, duration=15.0)
        a.tick(NOW)
        # leader dies (stops ticking); standby keeps polling
        assert b.tick(NOW + 10) is False          # not yet expired
        assert b.tick(NOW + 16) is True           # took over
        lease = store.get(KIND_LEASE, "/koord-scheduler")
        assert lease.holder_identity == "replica-1"
        assert lease.lease_transitions == 1
        # the old leader comes back: renew CAS fails, it demotes itself
        assert a.tick(NOW + 17) is False

    def test_voluntary_release_hands_off_immediately(self):
        store = ObjectStore()
        a, b = _electors(store)
        a.tick(NOW)
        a.release(NOW + 1)
        assert a.is_leader is False
        assert b.tick(NOW + 1) is True

    def test_callbacks_fire_on_transitions(self):
        store = ObjectStore()
        events = []
        a = LeaderElector(store, "l", "a",
                          lease_duration_seconds=10,
                          on_started_leading=lambda: events.append("a-start"),
                          on_stopped_leading=lambda: events.append("a-stop"))
        b = LeaderElector(store, "l", "b", lease_duration_seconds=10,
                          on_started_leading=lambda: events.append("b-start"))
        a.tick(NOW)
        b.tick(NOW)
        b.tick(NOW + 11)   # takes over
        a.tick(NOW + 12)   # discovers loss
        assert events == ["a-start", "b-start", "a-stop"]


class TestElectedScheduler:
    """Two Scheduler instances, one store: only the leader runs cycles;
    failover moves the cycle-running to the standby."""

    def _store(self):
        store = ObjectStore()
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name="node-0", namespace=""),
            allocatable=ResourceList.of(cpu=16000, memory=64 * GIB, pods=110)))
        store.add(KIND_NODE_METRIC, NodeMetric(
            meta=ObjectMeta(name="node-0", namespace=""),
            update_time=NOW - 10,
            node_metric=NodeMetricInfo(
                node_usage=ResourceList.of(cpu=1000, memory=GIB))))
        return store

    def test_only_leader_schedules_and_failover_works(self):
        from koordinator_tpu.api.objects import (
            LABEL_POD_QOS, Pod, PodSpec)
        from koordinator_tpu.client.store import KIND_POD
        from koordinator_tpu.scheduler.cycle import Scheduler

        store = self._store()
        sched_a = Scheduler(store)
        sched_b = Scheduler(store)
        runner_a = ElectedRunner(
            LeaderElector(store, "koord-scheduler", "a",
                          lease_duration_seconds=15),
            lambda now: sched_a.run_cycle(now=now))
        runner_b = ElectedRunner(
            LeaderElector(store, "koord-scheduler", "b",
                          lease_duration_seconds=15),
            lambda now: sched_b.run_cycle(now=now))

        def pend(name):
            store.add(KIND_POD, Pod(
                meta=ObjectMeta(name=name, labels={LABEL_POD_QOS: "LS"},
                                creation_timestamp=NOW),
                spec=PodSpec(priority=9500,
                             requests=ResourceList.of(cpu=1000, memory=GIB))))

        pend("p0")
        assert runner_a.tick(NOW) is True
        assert runner_b.tick(NOW) is False
        assert store.get(KIND_POD, "default/p0").is_assigned
        assert (runner_a.runs, runner_b.runs) == (1, 0)

        # replica A dies; B picks up the next pod after the lease expires
        pend("p1")
        assert runner_b.tick(NOW + 5) is False
        assert not store.get(KIND_POD, "default/p1").is_assigned
        assert runner_b.tick(NOW + 20) is True
        assert store.get(KIND_POD, "default/p1").is_assigned
        assert runner_b.runs == 1
