"""koord-manager components: slo controllers, quota profile controller,
admission webhooks."""

import json

import pytest

from koordinator_tpu.api.objects import (
    ANNOTATION_EXTENDED_RESOURCE_SPEC,
    ClusterColocationProfile,
    ConfigMap,
    ElasticQuota,
    LABEL_POD_QOS,
    LABEL_QUOTA_IS_PARENT,
    LABEL_QUOTA_PARENT,
    ElasticQuotaProfile,
    Node,
    NodeMetric,
    NodeMetricInfo,
    ObjectMeta,
    Pod,
    PodMetricInfo,
    PodSpec,
)
from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.api.resources import ResourceList, ResourceName
from koordinator_tpu.client.store import (
    KIND_COLOCATION_PROFILE,
    KIND_CONFIG_MAP,
    KIND_ELASTIC_QUOTA,
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_NODE_SLO,
    KIND_POD,
    KIND_QUOTA_PROFILE,
    ObjectStore,
)
from koordinator_tpu.quotacontroller import QuotaProfileController
from koordinator_tpu.slocontroller import (
    NodeMetricController,
    NodeResourceController,
    NodeSLOController,
)
from koordinator_tpu.utils.sloconfig import ColocationConfig, ColocationStrategy
from koordinator_tpu.webhook import AdmissionError, AdmissionServer

GIB = 1024**3
NOW = 1_000_000.0


def _node(store, name="node-0", cores=100, mem_gib=400, labels=None):
    node = Node(
        meta=ObjectMeta(name=name, namespace="", labels=labels or {}),
        allocatable=ResourceList.of(cpu=cores * 1000, memory=mem_gib * GIB),
        capacity=ResourceList.of(cpu=cores * 1000, memory=mem_gib * GIB),
    )
    store.add(KIND_NODE, node)
    return node


class TestNodeMetricController:
    def test_creates_and_gc(self):
        store = ObjectStore()
        _node(store, "a")
        _node(store, "b")
        ctrl = NodeMetricController(store)
        assert ctrl.reconcile() == 2
        assert store.get(KIND_NODE_METRIC, "/a") is not None
        store.delete(KIND_NODE, "/b")
        assert ctrl.reconcile() == 1
        assert store.get(KIND_NODE_METRIC, "/b") is None


class TestNodeResourceController:
    def _with_metric(self, store, node, cpu_used=50_000, mem_used=200 * GIB,
                     pods=()):
        nm = NodeMetric(
            meta=ObjectMeta(name=node.meta.name, namespace=""),
            update_time=NOW - 60,
            node_metric=NodeMetricInfo(
                node_usage=ResourceList.of(cpu=cpu_used, memory=mem_used)
            ),
            pods_metric=list(pods),
        )
        store.add(KIND_NODE_METRIC, nm)
        return nm

    def test_batch_formula(self):
        store = ObjectStore()
        node = _node(store)  # 100 cores, 400 GiB
        # one prod pod using 30 cores / 100 GiB, requesting 40 cores / 150 GiB
        pod = Pod(
            meta=ObjectMeta(name="prod", labels={LABEL_POD_QOS: "LS"}),
            spec=PodSpec(
                node_name="node-0",
                priority=9500,
                requests=ResourceList.of(cpu=40_000, memory=150 * GIB),
            ),
            phase="Running",
        )
        store.add(KIND_POD, pod)
        self._with_metric(
            store, node, cpu_used=35_000, mem_used=120 * GIB,
            pods=[
                PodMetricInfo(
                    namespace="default", name="prod",
                    pod_usage=ResourceList.of(cpu=30_000, memory=100 * GIB),
                )
            ],
        )
        cfg = ColocationConfig(
            cluster_strategy=ColocationStrategy(
                enable=True,
                cpu_reclaim_threshold_percent=65,
                memory_reclaim_threshold_percent=65,
            )
        )
        ctrl = NodeResourceController(store, cfg)
        assert ctrl.reconcile(now=NOW) == 1
        node = store.get(KIND_NODE, "/node-0")
        # batch cpu = 100000*0.65 - systemUsed(35000-30000=5000) - podHPUsed(30000)
        assert node.allocatable[ResourceName.BATCH_CPU] == 65_000 - 5_000 - 30_000
        # batch mem = 400GiB*0.65 - (120-100)GiB - 100GiB = 140 GiB
        expected_mem = int(400 * 0.65 - 20 - 100)
        assert node.allocatable[ResourceName.BATCH_MEMORY] == expected_mem * GIB

    def test_degrade_on_stale_metric(self):
        store = ObjectStore()
        node = _node(store)
        nm = self._with_metric(store, node)
        nm.update_time = NOW - 3600  # stale beyond 15min degrade window
        store.update(KIND_NODE_METRIC, nm)
        ctrl = NodeResourceController(
            store, ColocationConfig(ColocationStrategy(enable=True))
        )
        ctrl.reconcile(now=NOW)
        node = store.get(KIND_NODE, "/node-0")
        assert node.allocatable[ResourceName.BATCH_CPU] == 0
        assert node.allocatable[ResourceName.BATCH_MEMORY] == 0

    def test_request_policy_for_memory(self):
        store = ObjectStore()
        node = _node(store)
        pod = Pod(
            meta=ObjectMeta(name="prod", labels={LABEL_POD_QOS: "LS"}),
            spec=PodSpec(
                node_name="node-0", priority=9500,
                requests=ResourceList.of(cpu=40_000, memory=150 * GIB),
            ),
            phase="Running",
        )
        store.add(KIND_POD, pod)
        self._with_metric(store, node, cpu_used=35_000, mem_used=120 * GIB,
                          pods=[PodMetricInfo(namespace="default", name="prod",
                                              pod_usage=ResourceList.of(cpu=30_000, memory=100 * GIB))])
        cfg = ColocationConfig(
            ColocationStrategy(enable=True, memory_calculate_policy="request",
                               memory_reclaim_threshold_percent=100)
        )
        NodeResourceController(store, cfg).reconcile(now=NOW)
        node = store.get(KIND_NODE, "/node-0")
        # by request: 400GiB - podHPReq(150GiB) = 250GiB
        assert node.allocatable[ResourceName.BATCH_MEMORY] == 250 * GIB


class TestNodeSLOController:
    def test_render_from_configmap(self):
        store = ObjectStore()
        _node(store, "a", labels={"pool": "batch"})
        _node(store, "b")
        store.add(
            KIND_CONFIG_MAP,
            ConfigMap(
                meta=ObjectMeta(name="slo-controller-config",
                                namespace="koordinator-system"),
                data={
                    "resource-threshold-config": json.dumps(
                        {
                            "clusterStrategy": {
                                "enable": True,
                                "cpuSuppressThresholdPercent": 60,
                            },
                            "nodeStrategies": [
                                {
                                    "nodeSelector": {"pool": "batch"},
                                    "cpuSuppressThresholdPercent": 80,
                                }
                            ],
                        }
                    )
                },
            ),
        )
        ctrl = NodeSLOController(store)
        assert ctrl.reconcile() == 2
        slo_a = store.get(KIND_NODE_SLO, "/a")
        slo_b = store.get(KIND_NODE_SLO, "/b")
        assert slo_a.resource_used_threshold_with_be.cpu_suppress_threshold_percent == 80
        assert slo_b.resource_used_threshold_with_be.cpu_suppress_threshold_percent == 60
        assert slo_a.resource_used_threshold_with_be.enable
        # idempotent
        assert ctrl.reconcile() == 0


class TestQuotaProfileController:
    def test_generate_quota_from_node_group(self):
        store = ObjectStore()
        _node(store, "a", cores=10, mem_gib=40, labels={"zone": "z1"})
        _node(store, "b", cores=10, mem_gib=40, labels={"zone": "z1"})
        _node(store, "c", cores=10, mem_gib=40, labels={"zone": "z2"})
        profile = ElasticQuotaProfile(
            meta=ObjectMeta(name="profile-z1", namespace="default",
                            annotations={"quota.scheduling.koordinator.sh/total-resource-ratio": "0.9"}),
            quota_name="quota-z1",
            node_selector={"zone": "z1"},
        )
        store.add(KIND_QUOTA_PROFILE, profile)
        ctrl = QuotaProfileController(store)
        assert ctrl.reconcile() == 1
        quota = store.get(KIND_ELASTIC_QUOTA, "default/quota-z1")
        assert quota is not None
        assert quota.min[ResourceName.CPU] == int(20_000 * 0.9)
        assert quota.is_parent
        # node change refreshes
        _node(store, "d", cores=10, mem_gib=40, labels={"zone": "z1"})
        assert ctrl.reconcile() == 1
        assert store.get(
            KIND_ELASTIC_QUOTA, "default/quota-z1"
        ).min[ResourceName.CPU] == int(30_000 * 0.9)


class TestQuotaProfileLifecycle:
    """The thin seed controller's update/delete/clamp paths (koordcolo
    satellite: these feed the quota tree the device fold consumes)."""

    def _profile(self, store, ratio=None, quota_name="quota-z1"):
        ann = {}
        if ratio is not None:
            ann["quota.scheduling.koordinator.sh/total-resource-ratio"] = ratio
        profile = ElasticQuotaProfile(
            meta=ObjectMeta(name="profile-z1", namespace="default",
                            annotations=ann),
            quota_name=quota_name,
            node_selector={"zone": "z1"},
        )
        store.add(KIND_QUOTA_PROFILE, profile)
        return profile

    def test_ratio_update_rematerializes(self):
        store = ObjectStore()
        _node(store, "a", cores=10, mem_gib=40, labels={"zone": "z1"})
        profile = self._profile(store, ratio="1.0")
        ctrl = QuotaProfileController(store)
        assert ctrl.reconcile() == 1
        assert store.get(KIND_ELASTIC_QUOTA,
                         "default/quota-z1").min[ResourceName.CPU] == 10_000
        profile.meta.annotations[
            "quota.scheduling.koordinator.sh/total-resource-ratio"] = "0.5"
        store.update(KIND_QUOTA_PROFILE, profile)
        assert ctrl.reconcile() == 1
        assert store.get(KIND_ELASTIC_QUOTA,
                         "default/quota-z1").min[ResourceName.CPU] == 5_000
        # idempotent once converged
        assert ctrl.reconcile() == 0

    def test_invalid_and_out_of_range_ratio_clamped(self):
        store = ObjectStore()
        _node(store, "a", cores=10, mem_gib=40, labels={"zone": "z1"})
        self._profile(store, ratio="7.5")  # clamped to 1.0
        ctrl = QuotaProfileController(store)
        ctrl.reconcile()
        assert store.get(KIND_ELASTIC_QUOTA,
                         "default/quota-z1").min[ResourceName.CPU] == 10_000
        store2 = ObjectStore()
        _node(store2, "a", cores=10, mem_gib=40, labels={"zone": "z1"})
        self._profile(store2, ratio="not-a-number")
        QuotaProfileController(store2).reconcile()
        assert store2.get(KIND_ELASTIC_QUOTA,
                          "default/quota-z1").min[ResourceName.CPU] == 10_000

    def test_profile_delete_stops_tracking(self):
        store = ObjectStore()
        _node(store, "a", cores=10, mem_gib=40, labels={"zone": "z1"})
        self._profile(store)
        ctrl = QuotaProfileController(store)
        assert ctrl.reconcile() == 1
        store.delete(KIND_QUOTA_PROFILE, "default/profile-z1")
        # quota is retained (the reference does not GC generated quotas)
        # but nothing tracks node changes anymore
        _node(store, "b", cores=10, mem_gib=40, labels={"zone": "z1"})
        assert ctrl.reconcile() == 0
        assert store.get(KIND_ELASTIC_QUOTA,
                         "default/quota-z1").min[ResourceName.CPU] == 10_000

    def test_profile_name_fallback(self):
        store = ObjectStore()
        _node(store, "a", cores=10, mem_gib=40, labels={"zone": "z1"})
        self._profile(store, quota_name="")
        QuotaProfileController(store).reconcile()
        assert store.get(KIND_ELASTIC_QUOTA,
                         "default/profile-z1") is not None


class TestNodeMetricSpec:
    def test_report_interval_follows_config(self):
        store = ObjectStore()
        _node(store, "a")
        cfg = ColocationConfig(cluster_strategy=ColocationStrategy(
            metric_aggregate_duration_seconds=600))
        ctrl = NodeMetricController(store, cfg)
        assert ctrl.reconcile() == 1
        nm = store.get(KIND_NODE_METRIC, "/a")
        assert nm.report_interval_seconds == max(60, 600 // 5)
        # idempotent; a fresh node materializes on the next round
        assert ctrl.reconcile() == 0
        _node(store, "b")
        assert ctrl.reconcile() == 1


class TestNodeSLOUpdatePath:
    def test_config_change_updates_existing_slo(self):
        store = ObjectStore()
        _node(store, "a")
        cm = ConfigMap(
            meta=ObjectMeta(name="slo-controller-config",
                            namespace="koordinator-system"),
            data={"resource-threshold-config": json.dumps(
                {"clusterStrategy": {"enable": True,
                                     "cpuSuppressThresholdPercent": 60}})})
        store.add(KIND_CONFIG_MAP, cm)
        ctrl = NodeSLOController(store)
        assert ctrl.reconcile() == 1
        slo = store.get(KIND_NODE_SLO, "/a")
        rv = slo.meta.resource_version
        # hot reload: the SAME CR is updated in place, not re-added
        cm.data["resource-threshold-config"] = json.dumps(
            {"clusterStrategy": {"enable": True,
                                 "cpuSuppressThresholdPercent": 45}})
        store.update(KIND_CONFIG_MAP, cm)
        assert ctrl.reconcile() == 1
        slo2 = store.get(KIND_NODE_SLO, "/a")
        assert (slo2.resource_used_threshold_with_be
                .cpu_suppress_threshold_percent == 45)
        assert slo2.meta.resource_version > rv

    def test_cpu_burst_and_system_strategies_render(self):
        store = ObjectStore()
        _node(store, "a")
        store.add(KIND_CONFIG_MAP, ConfigMap(
            meta=ObjectMeta(name="slo-controller-config",
                            namespace="koordinator-system"),
            data={
                "cpu-burst-config": json.dumps(
                    {"clusterStrategy": {"policy": "auto",
                                         "cpuBurstPercent": 500}}),
                "system-config": json.dumps(
                    {"clusterStrategy": {"minFreeKbytesFactor": 200}}),
            }))
        NodeSLOController(store).reconcile()
        slo = store.get(KIND_NODE_SLO, "/a")
        assert slo.cpu_burst_strategy.policy == "auto"
        assert slo.cpu_burst_strategy.cpu_burst_percent == 500
        assert slo.system_strategy.min_free_kbytes_factor == 200


class TestWebhooks:
    def test_colocation_profile_mutation(self):
        store = ObjectStore()
        store.add(
            KIND_COLOCATION_PROFILE,
            ClusterColocationProfile(
                meta=ObjectMeta(name="batch-profile"),
                selector={"koordinator-colocation": "true"},
                qos_class=QoSClass.BE,
                priority_class_name="koord-batch",
                scheduler_name="koord-scheduler",
                labels={"injected": "yes"},
            ),
        )
        server = AdmissionServer(store)
        pod = Pod(
            meta=ObjectMeta(name="spark", labels={"koordinator-colocation": "true"}),
            spec=PodSpec(requests=ResourceList.of(cpu=4000, memory=8 * GIB),
                         limits=ResourceList.of(cpu=4000, memory=8 * GIB)),
        )
        server.admit_pod_create(pod)
        assert pod.qos_class is QoSClass.BE
        assert pod.spec.priority == 5999
        assert pod.meta.labels["injected"] == "yes"
        # requests translated to batch resources
        assert pod.spec.requests[ResourceName.CPU] == 0
        assert pod.spec.requests[ResourceName.BATCH_CPU] == 4000
        assert pod.spec.requests[ResourceName.BATCH_MEMORY] == 8 * GIB
        assert ANNOTATION_EXTENDED_RESOURCE_SPEC in pod.meta.annotations

    def test_pod_validation_rules(self):
        server = AdmissionServer(ObjectStore())
        bad = Pod(
            meta=ObjectMeta(name="x", labels={LABEL_POD_QOS: "BE"}),
            spec=PodSpec(priority=9500),
        )
        with pytest.raises(AdmissionError):
            server.validate_pod(bad)
        frac = Pod(
            meta=ObjectMeta(name="y", labels={LABEL_POD_QOS: "LSR"}),
            spec=PodSpec(priority=9500,
                         requests=ResourceList.of(cpu=1500)),
        )
        with pytest.raises(AdmissionError):
            server.validate_pod(frac)
        ok = Pod(
            meta=ObjectMeta(name="z", labels={LABEL_POD_QOS: "LSR"}),
            spec=PodSpec(priority=9500, requests=ResourceList.of(cpu=2000)),
        )
        server.validate_pod(ok)

    def test_quota_validation(self):
        store = ObjectStore()
        server = AdmissionServer(store)
        with pytest.raises(AdmissionError):
            server.validate_elastic_quota(
                ElasticQuota(
                    meta=ObjectMeta(name="bad"),
                    min=ResourceList.of(cpu=2000),
                    max=ResourceList.of(cpu=1000),
                )
            )
        orphan = ElasticQuota(
            meta=ObjectMeta(name="child",
                            labels={LABEL_QUOTA_PARENT: "nonexistent"}),
        )
        with pytest.raises(AdmissionError):
            server.validate_elastic_quota(orphan)
        store.add(
            KIND_ELASTIC_QUOTA,
            ElasticQuota(
                meta=ObjectMeta(name="parent", namespace="default",
                                labels={LABEL_QUOTA_IS_PARENT: "true"}),
                min=ResourceList.of(cpu=10_000),
            ),
        )
        child = ElasticQuota(
            meta=ObjectMeta(name="child", namespace="default",
                            labels={LABEL_QUOTA_PARENT: "parent"}),
            min=ResourceList.of(cpu=5000),
        )
        server.validate_elastic_quota(child)

    def test_configmap_validation(self):
        server = AdmissionServer(ObjectStore())
        bad = ConfigMap(
            meta=ObjectMeta(name="slo-controller-config"),
            data={"colocation-config": json.dumps(
                {"cpuReclaimThresholdPercent": 150}
            )},
        )
        with pytest.raises(AdmissionError):
            server.validate_config_map(bad)
        good = ConfigMap(
            meta=ObjectMeta(name="slo-controller-config"),
            data={"colocation-config": json.dumps({"enable": True})},
        )
        server.validate_config_map(good)
