"""Fixture twin of koordinator_tpu/obs/lockorder.py: the analyzer
parses any ``obs/lockorder.py`` for the declared order, so the golden
dump pins the ``canonical_lock_order`` field shape too."""

CANONICAL_LOCK_ORDER = (
    "Sampler._lock",
    "Sampler._alias",
)
