"""Guard-map golden fixture: pins the ``--guards`` dump schema.

Deliberately exercises every shape the dump can emit: an annotated
field, an inferred guard (majority vote over locked touches), a
``guarded-by(none)`` pin, a module-level lock guarding a module global,
an instance alias of a module lock, and a ``guards(<resource>)``
declaration. koordlint itself never scans this directory — only the
schema-pin test (tests/test_static_analysis.py) drives ``--guards``
over it and diffs the dump against tests/fixtures/guardmap_golden.json.
Any field added, renamed or re-typed in the dump is schema drift and
must be a conscious GUARD_MAP_VERSION bump + fixture regeneration.
"""

import threading

_mod_lock = threading.Lock()
_file_lock = threading.Lock()  # koordlint: guards(sample-file)

# koordlint: guarded-by(_mod_lock)
_events = []


def record(ev):
    with _mod_lock:
        _events.append(ev)


def drain():
    with _mod_lock:
        out = list(_events)
        _events.clear()
    return out


class Sampler:
    def __init__(self):
        self._lock = threading.Lock()
        self._alias = _mod_lock
        self.count = 0  # koordlint: guarded-by(_lock)
        self.window = []
        self.label = ""  # koordlint: guarded-by(none)

    def bump(self):
        with self._lock:
            self.count += 1
            self.window.append(self.count)

    def rotate(self):
        with self._lock:
            self.window = self.window[-8:]

    def read(self):
        with self._lock:
            return list(self.window)

    def name(self):
        return self.label
