"""Sharded execution on the 8-device virtual CPU mesh: bindings must be identical
to single-device execution at any mesh size."""

import numpy as np

from koordinator_tpu.models.scheduler_model import (
    build_schedule_step,
    build_score_matrix,
    make_inputs,
)
from koordinator_tpu.ops.loadaware import LoadAwareArgs, build_loadaware_node_state
from koordinator_tpu.ops.packing import pack_nodes, pack_pods
from koordinator_tpu.parallel import (
    build_sharded_schedule_step,
    build_sharded_score_matrix,
    make_mesh,
    shard_inputs_2d,
    shard_inputs_nodewise,
)
from koordinator_tpu.testing import synth_cluster


def _inputs(num_nodes=48, num_pods=64, seed=0):
    cluster = synth_cluster(num_nodes=num_nodes, num_pods=num_pods, seed=seed)
    args = LoadAwareArgs()
    pods = pack_pods(cluster.pods, args.resource_weights, args.estimated_scaling_factors)
    nodes = pack_nodes(cluster.nodes)
    nodes.extras = build_loadaware_node_state(
        cluster.nodes,
        cluster.node_metrics,
        cluster.pods_by_key,
        cluster.assigned,
        args,
        cluster.now,
        pad_to=nodes.padded_size,
    )
    return args, pods, make_inputs(pods, nodes, args)


def test_mesh_shape(cpu_devices):
    mesh = make_mesh(cpu_devices)
    assert mesh.devices.size == 8
    assert mesh.axis_names == ("pods", "nodes")
    assert mesh.devices.shape == (2, 4)


def test_sharded_serial_step_matches_single_device(cpu_devices):
    args, pods, inputs = _inputs()
    chosen_single = np.asarray(build_schedule_step(args)(inputs)[0])

    mesh = make_mesh(cpu_devices)
    sharded_inputs = shard_inputs_nodewise(inputs, mesh)
    step = build_sharded_schedule_step(args, mesh)
    chosen_sharded = np.asarray(step(sharded_inputs)[0])

    np.testing.assert_array_equal(chosen_single, chosen_sharded)


def test_sharded_score_matrix_matches(cpu_devices):
    args, pods, inputs = _inputs(seed=3)
    feasible_1, score_1 = build_score_matrix(args)(inputs)

    mesh = make_mesh(cpu_devices)
    sharded_inputs = shard_inputs_2d(inputs, mesh)
    fn = build_sharded_score_matrix(args, mesh)
    feasible_8, score_8 = fn(sharded_inputs)

    np.testing.assert_array_equal(np.asarray(feasible_1), np.asarray(feasible_8))
    np.testing.assert_array_equal(np.asarray(score_1), np.asarray(score_8))
