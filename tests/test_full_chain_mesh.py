"""Sharded full-chain step on the 8-device virtual CPU mesh: bindings (and the
quota rollup) must be identical to the single-device step.

This is the multi-chip variant of the flagship kernel — the distributed analog
of the reference's per-node Filter/Score fan-out
(/root/reference/pkg/scheduler/frameworkext/framework_extender.go:204) — with
NUMA topologies, a 3-level quota tree, and gangs all active.
"""

import numpy as np
import pytest

from koordinator_tpu.models.full_chain import build_full_chain_step
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.parallel import (
    build_sharded_full_chain_step,
    make_mesh,
    shard_full_chain_inputs,
)
from koordinator_tpu.scheduler.snapshot import build_full_chain_inputs
from koordinator_tpu.testing import synth_full_cluster


def _build(seed, num_nodes=30, num_pods=60, **kw):
    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(num_nodes, num_pods, seed=seed, **kw)
    fc, pods, nodes, tree, gang_index, ng, ngroups = build_full_chain_inputs(
        state, args
    )
    return args, fc, pods, ng, ngroups


@pytest.mark.parametrize(
    "seed,kw",
    [
        (0, {}),                                        # mixed: NUMA+quota+gang
        (7, {"topology_fraction": 1.0, "lsr_fraction": 0.4}),  # all-topology
        (11, {"num_nodes": 40, "num_pods": 96}),        # bigger batch
        (13, {"num_nodes": 4, "num_pods": 40}),         # tiny cluster, gang strikes
    ],
)
def test_sharded_full_chain_matches_single_device(cpu_devices, seed, kw):
    args, fc, pods, ng, ngroups = _build(seed, **kw)

    chosen_1, requested_1, quota_used_1 = build_full_chain_step(args, ng, ngroups)(fc)

    mesh = make_mesh(cpu_devices)
    step = build_sharded_full_chain_step(args, ng, ngroups, mesh)
    chosen_8, requested_8, quota_used_8 = step(shard_full_chain_inputs(fc, mesh))

    np.testing.assert_array_equal(np.asarray(chosen_1), np.asarray(chosen_8))
    np.testing.assert_allclose(
        np.asarray(requested_1), np.asarray(requested_8), rtol=0, atol=0
    )
    np.testing.assert_allclose(
        np.asarray(quota_used_1), np.asarray(quota_used_8), rtol=0, atol=0
    )
    # the config must actually exercise the chain
    assert (np.asarray(chosen_1)[: len(pods.keys)] >= 0).sum() > 0


def test_sharded_full_chain_large_shape(cpu_devices):
    """Bucket/pad/shard interplay at non-toy scale: the full chain at
    2048 x 1024 under the 8-device mesh must bind identically to the
    single-device step (shard-boundary bugs the tiny fixtures cannot
    catch). Axes are reduced to the active set like the cycle driver and
    the bench do."""
    from koordinator_tpu.scheduler.snapshot import reduce_to_active_axes

    args, fc, pods, ng, ngroups = _build(1, num_nodes=1024, num_pods=2048)
    fc, axes = reduce_to_active_axes(fc)
    chosen_1 = np.asarray(build_full_chain_step(
        args, ng, ngroups, active_axes=axes)(fc)[0])
    mesh = make_mesh(cpu_devices)
    step = build_sharded_full_chain_step(args, ng, ngroups, mesh,
                                         active_axes=axes)
    chosen_8 = np.asarray(step(shard_full_chain_inputs(fc, mesh))[0])
    np.testing.assert_array_equal(chosen_1, chosen_8)
    assert (chosen_1[: len(pods.keys)] >= 0).sum() >= 1024
    assert len(pods.keys) >= 2048


def test_sharded_full_chain_gang_and_quota_active(cpu_devices):
    """The sharded run must show gang/quota machinery engaged, not vacuously on."""
    args, fc, pods, ng, ngroups = _build(0)
    mesh = make_mesh(cpu_devices)
    step = build_sharded_full_chain_step(args, ng, ngroups, mesh)
    chosen, _, quota_used = step(shard_full_chain_inputs(fc, mesh))
    chosen = np.asarray(chosen)[: len(pods.keys)]
    gang_id = np.asarray(fc.gang_id)[: len(pods.keys)]
    quota_id = np.asarray(fc.quota_id)[: len(pods.keys)]
    assert (gang_id >= 0).any(), "synth produced no gang members"
    assert (quota_id >= 0).any(), "synth produced no quota-bound pods"
    # quota rollup reflects scheduled quota-bound pods
    sched_q = ((chosen >= 0) & (quota_id >= 0)).sum()
    assert sched_q > 0
    assert np.asarray(quota_used).sum() > np.asarray(fc.quota_used).sum()
