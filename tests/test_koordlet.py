"""koordlet tests against the fake /sys + /proc + cgroup tree (the reference's
FileTestUtil pattern): metrics pipeline, NodeMetric reporting, QoS enforcement,
runtime hooks, prediction, pleg, audit."""

import json

import pytest

from koordinator_tpu.api.objects import (
    ANNOTATION_RESOURCE_STATUS,
    LABEL_POD_QOS,
    Node,
    NodeSLO,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceThresholdStrategy,
)
from koordinator_tpu.api.resources import ResourceList, ResourceName
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_NODE_SLO,
    KIND_POD,
    ObjectStore,
)
from koordinator_tpu.koordlet.daemon import Daemon
from koordinator_tpu.koordlet.util import system as sysutil
from koordinator_tpu.koordlet.util.system import FakeFS

GIB = 1024**3
NOW = 1_000_000.0


@pytest.fixture
def fs():
    f = FakeFS(use_cgroup_v2=True)
    yield f
    f.cleanup()


def setup_node(store, fs, cores=16, mem_gib=64):
    store.add(
        KIND_NODE,
        Node(
            meta=ObjectMeta(name="node-0", namespace=""),
            allocatable=ResourceList.of(cpu=cores * 1000, memory=mem_gib * GIB),
        ),
    )
    # /proc/stat: user nice system idle ... (jiffies)
    fs.set_proc("stat", "cpu  1000 0 1000 8000 0 0 0 0 0 0\n")
    fs.set_proc(
        "meminfo",
        "MemTotal: %d kB\nMemFree: %d kB\nMemAvailable: %d kB\n"
        % (mem_gib * GIB // 1024, 32 * GIB // 1024, 48 * GIB // 1024),
    )
    fs.set_cgroup("", sysutil.CPU_PRESSURE,
                  "some avg10=1.50 avg60=1.00 avg300=0.50 total=12345\n"
                  "full avg10=0.50 avg60=0.30 avg300=0.10 total=2345\n")
    fs.set_cgroup("", sysutil.MEMORY_PRESSURE,
                  "some avg10=0.00 avg60=0.00 avg300=0.00 total=0\n"
                  "full avg10=0.00 avg60=0.00 avg300=0.00 total=0\n")


def add_pod(store, fs, name, qos="LS", cpu=2000, mem=2 * GIB, uid=None,
            cpu_usage_us=10_000_000, mem_usage=GIB, annotations=None):
    uid = uid or name
    pod = Pod(
        meta=ObjectMeta(name=name, uid=uid, labels={LABEL_POD_QOS: qos},
                        annotations=annotations or {}),
        spec=PodSpec(
            node_name="node-0",
            requests=ResourceList.of(cpu=cpu, memory=mem),
            limits=ResourceList.of(cpu=cpu, memory=mem),
        ),
        phase="Running",
    )
    store.add(KIND_POD, pod)
    qos_dir = sysutil.QOS_BESTEFFORT if qos == "BE" else ""
    rel = fs.config.pod_relative_path(qos_dir, uid)
    fs.set_cgroup(rel, sysutil.CPU_STAT, f"usage_usec {cpu_usage_us}\n")
    fs.set_cgroup(rel, sysutil.MEMORY_USAGE, str(mem_usage))
    return pod


class TestMetricsPipeline:
    def test_node_and_pod_metrics_collected(self, fs):
        store = ObjectStore()
        setup_node(store, fs)
        add_pod(store, fs, "p1", cpu_usage_us=10_000_000)
        daemon = Daemon(store, "node-0", fs.config, report_interval_seconds=0)
        daemon.run_once(now=NOW)
        # advance counters: +2 cores of pod usage over 10s; node 50% busy
        # (delta total = 8000 jiffies, delta idle = 4000)
        fs.set_proc("stat", "cpu  3000 0 3000 12000 0 0 0 0 0 0\n")
        rel = fs.config.pod_relative_path("", "p1")
        fs.set_cgroup(rel, sysutil.CPU_STAT, f"usage_usec {10_000_000 + 20_000_000}\n")
        daemon.run_once(now=NOW + 10)

        from koordinator_tpu.koordlet import metriccache as mc

        pod_cpu = daemon.metric_cache.query(
            mc.POD_CPU_USAGE, "latest", pod="default/p1"
        )
        assert pod_cpu == pytest.approx(2.0, rel=0.01)
        node_cpu = daemon.metric_cache.query(mc.NODE_CPU_USAGE, "latest")
        assert node_cpu == pytest.approx(16 * 0.5, rel=0.01)  # 50% busy of 16
        psi = daemon.metric_cache.query(mc.NODE_CPU_PSI_FULL_AVG10, "latest")
        assert psi == 0.5

    def test_node_metric_cr_reported(self, fs):
        store = ObjectStore()
        setup_node(store, fs)
        add_pod(store, fs, "p1")
        daemon = Daemon(store, "node-0", fs.config, report_interval_seconds=0)
        daemon.run_once(now=NOW)
        fs.set_proc("stat", "cpu  3000 0 3000 12000 0 0 0 0 0 0\n")
        daemon.run_once(now=NOW + 10)
        nm = store.get(KIND_NODE_METRIC, "/node-0")
        assert nm is not None
        assert nm.update_time == NOW + 10
        assert nm.node_metric.node_usage.get("cpu") > 0
        assert any(pm.name == "p1" for pm in nm.pods_metric)
        assert 300 in nm.node_metric.aggregated_node_usages
        assert "p95" in nm.node_metric.aggregated_node_usages[300]


class TestQoSManager:
    def test_cpusuppress_writes_be_cpuset(self, fs):
        store = ObjectStore()
        setup_node(store, fs)
        slo = NodeSLO(
            meta=ObjectMeta(name="node-0", namespace=""),
            resource_used_threshold_with_be=ResourceThresholdStrategy(
                enable=True, cpu_suppress_threshold_percent=65
            ),
        )
        store.add(KIND_NODE_SLO, slo)
        add_pod(store, fs, "ls", qos="LS", cpu_usage_us=0)
        add_pod(store, fs, "be", qos="BE", cpu_usage_us=0)
        be_rel = fs.config.qos_relative_path(sysutil.QOS_BESTEFFORT)
        fs.set_cgroup(be_rel, sysutil.CPU_STAT, "usage_usec 0\n")
        daemon = Daemon(store, "node-0", fs.config, report_interval_seconds=0)
        daemon.run_once(now=NOW)
        fs.set_proc("stat", "cpu  5000 0 5000 8000 0 0 0 0 0 0\n")  # ~55% busy
        daemon.run_once(now=NOW + 10)
        raw = fs.get_cgroup(be_rel, sysutil.CPUSET_CPUS)
        assert raw is not None
        from koordinator_tpu.utils.cpuset import CPUSet

        got = len(CPUSet.parse(raw))
        # suppress = 16*0.65 - nonBE used (~8.8 cores) ~ 1.6 -> min 2
        assert 2 <= got < 16

    def test_memory_evict_gated_by_feature(self, fs):
        store = ObjectStore()
        setup_node(store, fs)
        slo = NodeSLO(
            meta=ObjectMeta(name="node-0", namespace=""),
            resource_used_threshold_with_be=ResourceThresholdStrategy(
                enable=True, memory_evict_threshold_percent=10
            ),
        )
        store.add(KIND_NODE_SLO, slo)
        add_pod(store, fs, "be", qos="BE")
        daemon = Daemon(store, "node-0", fs.config, report_interval_seconds=0)
        daemon.run_once(now=NOW)  # gate off by default -> no eviction
        assert daemon.qos_manager.evictor.evicted == []

        from koordinator_tpu.utils.features import KOORDLET_GATES

        KOORDLET_GATES.set_from_map({"BEMemoryEvict": True})
        try:
            daemon.run_once(now=NOW + 10)
            assert "default/be" in daemon.qos_manager.evictor.evicted
        finally:
            KOORDLET_GATES.reset()


class TestRuntimeHooks:
    def test_reconciler_applies_bvt_cpuset_batch(self, fs):
        store = ObjectStore()
        setup_node(store, fs)
        pod = add_pod(
            store, fs, "lsr", qos="LSR",
            annotations={ANNOTATION_RESOURCE_STATUS: json.dumps({"cpuset": "0-3"})},
        )
        be = Pod(
            meta=ObjectMeta(name="batch", uid="batch",
                            labels={LABEL_POD_QOS: "BE"}),
            spec=PodSpec(
                node_name="node-0",
                requests=ResourceList.of(batch_cpu=2000, batch_memory=GIB),
                limits=ResourceList.of(batch_cpu=2000, batch_memory=GIB),
            ),
            phase="Running",
        )
        store.add(KIND_POD, be)
        daemon = Daemon(store, "node-0", fs.config, report_interval_seconds=0)
        daemon.run_once(now=NOW)
        lsr_rel = fs.config.pod_relative_path("", "lsr")
        assert fs.get_cgroup(lsr_rel, sysutil.CPU_BVT_WARP_NS) == "2"
        assert fs.get_cgroup(lsr_rel, sysutil.CPUSET_CPUS) == "0-3"
        be_rel = fs.config.pod_relative_path(sysutil.QOS_BESTEFFORT, "batch")
        assert fs.get_cgroup(be_rel, sysutil.CPU_BVT_WARP_NS) == "-1"
        assert fs.get_cgroup(be_rel, sysutil.CPU_CFS_QUOTA) == "200000"
        assert fs.get_cgroup(be_rel, sysutil.MEMORY_LIMIT) == str(GIB)

    def test_gpu_env_injection(self, fs):
        from koordinator_tpu.api.objects import ANNOTATION_DEVICE_ALLOCATED
        from koordinator_tpu.koordlet.runtimehooks import ContainerContext

        store = ObjectStore()
        setup_node(store, fs)
        pod = add_pod(
            store, fs, "gpu", qos="LS",
            annotations={
                ANNOTATION_DEVICE_ALLOCATED: json.dumps(
                    {"gpu": [{"minor": 1, "core": 50}]}
                )
            },
        )
        daemon = Daemon(store, "node-0", fs.config, report_interval_seconds=0)
        ctx = ContainerContext(pod=pod, cgroup_parent="x")
        daemon.runtime_hooks.run_hooks(ctx)
        assert ctx.env["NVIDIA_VISIBLE_DEVICES"] == "1"
        assert ctx.env["CUDA_MPS_ACTIVE_THREAD_PERCENTAGE"] == "50"


class TestInfraPieces:
    def test_executor_cache_suppresses_redundant_writes(self, fs):
        from koordinator_tpu.koordlet.resourceexecutor import (
            ResourceUpdateExecutor,
            ResourceUpdater,
        )

        ex = ResourceUpdateExecutor(fs.config)
        up = ResourceUpdater("kubepods", sysutil.CPU_SHARES, "1024")
        assert ex.update(up) is True
        assert ex.update(up) is False  # cached
        assert ex.update(up, force=True) is True
        assert len(ex.auditor) == 2

    def test_pleg_detects_pod_dirs(self, fs):
        from koordinator_tpu.koordlet.pleg import Pleg

        pleg = Pleg(fs.config)
        events = []
        pleg.add_handler(events.append)
        fs.set_cgroup("kubepods/podx", sysutil.CPU_SHARES, "2")
        pleg.tick()  # baseline
        fs.set_cgroup("kubepods/pody", sysutil.CPU_SHARES, "2")
        out = pleg.tick()
        assert [e.event_type for e in out] == ["pod_added"]
        assert "pody" in out[0].pod_dir

    def test_prediction_checkpoint_roundtrip(self, tmp_path):
        from koordinator_tpu.koordlet.prediction import PeakPredictServer

        p = PeakPredictServer(str(tmp_path))
        for i in range(100):
            p.update("uid-1", 2.0, 4 * GIB, timestamp=NOW + i * 60)
        peak = p.predict_peak("uid-1", now=NOW + 100 * 60)
        assert peak is not None
        assert peak[0] >= 2.0
        p.checkpoint()
        p2 = PeakPredictServer(str(tmp_path))
        assert p2.predict_peak("uid-1", now=NOW + 100 * 60) == peak

    def test_prediction_cold_start(self):
        from koordinator_tpu.koordlet.prediction import PeakPredictServer

        p = PeakPredictServer()
        p.update("uid-1", 1.0, GIB, timestamp=NOW)
        assert p.predict_peak("uid-1", now=NOW + 60) is None  # cold start

    def test_psi_parse(self):
        psi = sysutil.parse_psi(
            "some avg10=1.50 avg60=1.00 avg300=0.50 total=12345\n"
            "full avg10=0.25 avg60=0.10 avg300=0.05 total=999\n"
        )
        assert psi.some_avg10 == 1.5
        assert psi.full_total_us == 999

    def test_cgroup_v1_paths(self):
        cfg = sysutil.SystemConfig(cgroup_root_dir="/cg", use_cgroup_v2=False)
        assert (
            cfg.cgroup_file_path("kubepods/besteffort", sysutil.CPUSET_CPUS)
            == "/cg/cpuset/kubepods/besteffort/cpuset.cpus"
        )
        cfg2 = sysutil.SystemConfig(cgroup_root_dir="/cg", use_cgroup_v2=True)
        assert (
            cfg2.cgroup_file_path("kubepods", sysutil.MEMORY_LIMIT)
            == "/cg/kubepods/memory.max"
        )

    def test_daemon_auditor_receives_executor_writes(self, fs):
        """Regression: passing an (empty, falsy) Auditor must not be replaced
        by a fresh one inside the executor."""
        store = ObjectStore()
        setup_node(store, fs)
        add_pod(store, fs, "p1")
        daemon = Daemon(store, "node-0", fs.config, report_interval_seconds=0)
        daemon.run_once(now=NOW)
        assert len(daemon.auditor) > 0
        events, _ = daemon.auditor.query()
        assert any(e.operation == "cgroup_write" for e in events)


class TestHostApplicationAccounting:
    def test_be_host_app_usage_not_suppressing(self, fs):
        """A host application declared BE in NodeSLO must come out of the
        non-BE side of the suppress formula (helpers/calculator.go
        NonBEHostAppFilter): with 4 BE host-app cores in use, the BE share
        grows by ~4 cores over the baseline min."""
        from koordinator_tpu.koordlet import metriccache as mc
        from koordinator_tpu.utils.cpuset import CPUSet

        store = ObjectStore()
        setup_node(store, fs)
        slo = NodeSLO(
            meta=ObjectMeta(name="node-0", namespace=""),
            resource_used_threshold_with_be=ResourceThresholdStrategy(
                enable=True, cpu_suppress_threshold_percent=65
            ),
        )
        slo.extensions = {"hostApplications": [{"name": "hb", "qos": "BE"}]}
        store.add(KIND_NODE_SLO, slo)
        add_pod(store, fs, "ls", qos="LS", cpu_usage_us=0)
        add_pod(store, fs, "be", qos="BE", cpu_usage_us=0)
        be_rel = fs.config.qos_relative_path(sysutil.QOS_BESTEFFORT)
        fs.set_cgroup(be_rel, sysutil.CPU_STAT, "usage_usec 0\n")
        daemon = Daemon(store, "node-0", fs.config, report_interval_seconds=0)
        daemon.run_once(now=NOW)
        fs.set_proc("stat", "cpu  5000 0 5000 8000 0 0 0 0 0 0\n")
        daemon.metric_cache.add_sample(
            mc.HOST_APP_CPU_USAGE, 10.0, NOW + 10, app="hb")
        daemon.run_once(now=NOW + 10)
        raw = fs.get_cgroup(be_rel, sysutil.CPUSET_CPUS)
        got = len(CPUSet.parse(raw))
        # the fixture's node usage saturates (~16 cores busy), so without
        # the host-app reclassification suppress floors at 2; moving 10
        # cores of usage to the BE side yields 16*0.65 - (16-10) = 4.4 -> 5
        assert 4 <= got <= 6


class TestSystemQOSSuppress:
    def test_be_suppress_skips_exclusive_system_cores(self, fs):
        """BE cpuset suppression must not hand out the node's exclusive
        SYSTEM-QoS cores (cpu_suppress.go system-qos path)."""
        import json as _json

        from koordinator_tpu.api.objects import ANNOTATION_NODE_SYSTEM_QOS
        from koordinator_tpu.client.store import KIND_NODE
        from koordinator_tpu.utils.cpuset import CPUSet

        store = ObjectStore()
        setup_node(store, fs)
        node = store.get(KIND_NODE, "/node-0")
        node.meta.annotations[ANNOTATION_NODE_SYSTEM_QOS] = _json.dumps(
            {"cpuset": "0-1"})
        store.update(KIND_NODE, node)
        slo = NodeSLO(
            meta=ObjectMeta(name="node-0", namespace=""),
            resource_used_threshold_with_be=ResourceThresholdStrategy(
                enable=True, cpu_suppress_threshold_percent=65
            ),
        )
        store.add(KIND_NODE_SLO, slo)
        add_pod(store, fs, "be", qos="BE", cpu_usage_us=0)
        be_rel = fs.config.qos_relative_path(sysutil.QOS_BESTEFFORT)
        fs.set_cgroup(be_rel, sysutil.CPU_STAT, "usage_usec 0\n")
        daemon = Daemon(store, "node-0", fs.config, report_interval_seconds=0)
        daemon.run_once(now=NOW)
        fs.set_proc("stat", "cpu  5000 0 5000 8000 0 0 0 0 0 0\n")
        daemon.run_once(now=NOW + 10)
        got = CPUSet.parse(fs.get_cgroup(be_rel, sysutil.CPUSET_CPUS))
        assert not (set(got) & {0, 1}), got.format()
        assert len(got) >= 2
