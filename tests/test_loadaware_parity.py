"""Parity: batched TPU kernel vs serial reference-semantics emulator.

The binding-parity harness of SURVEY.md section 7 step 3: identical inputs through
(a) the fused lax.fori_loop scheduling step and (b) the scalar per-pod/per-node
emulator; bindings must be IDENTICAL. Several seeds/configs exercise expired
metrics, aggregated percentiles, prod thresholds, daemonset pods, and estimator
default paths.
"""

import numpy as np
import pytest

from koordinator_tpu.api.resources import RESOURCE_INDEX, ResourceName
from koordinator_tpu.models.scheduler_model import (
    build_schedule_step,
    build_score_matrix,
    make_inputs,
)
from koordinator_tpu.ops.loadaware import (
    LoadAwareArgs,
    build_loadaware_node_state,
)
from koordinator_tpu.ops.packing import bucket_size, pack_nodes, pack_pods
from koordinator_tpu.scheduler.parity import diff_bindings, serial_schedule
from koordinator_tpu.testing import synth_cluster


def _make_inputs(cluster, args):
    pods = pack_pods(
        cluster.pods, args.resource_weights, args.estimated_scaling_factors
    )
    nodes = pack_nodes(cluster.nodes)
    nodes.extras = build_loadaware_node_state(
        cluster.nodes,
        cluster.node_metrics,
        cluster.pods_by_key,
        cluster.assigned,
        args,
        cluster.now,
        pad_to=nodes.padded_size,
    )
    return pods, nodes, make_inputs(pods, nodes, args)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_bindings_match_default_args(seed):
    cluster = synth_cluster(num_nodes=40, num_pods=80, seed=seed)
    args = LoadAwareArgs()
    pods, nodes, inputs = _make_inputs(cluster, args)
    step = build_schedule_step(args)
    chosen_tpu, requested = step(inputs)
    chosen_tpu = np.asarray(chosen_tpu)
    chosen_serial = serial_schedule(inputs, args)
    diffs = diff_bindings(chosen_serial, chosen_tpu[: len(pods.keys)], pods.keys)
    assert not diffs, f"{len(diffs)} binding mismatches: {diffs[:10]}"
    # at least some pods must actually schedule for the test to mean anything
    assert (chosen_serial >= 0).sum() > len(pods.keys) // 2


def test_bindings_match_prod_mode():
    cluster = synth_cluster(num_nodes=30, num_pods=60, seed=7)
    args = LoadAwareArgs(
        prod_usage_thresholds={ResourceName.CPU: 60},
        score_according_prod_usage=True,
    )
    pods, nodes, inputs = _make_inputs(cluster, args)
    chosen_tpu = np.asarray(build_schedule_step(args)(inputs)[0])
    chosen_serial = serial_schedule(inputs, args)
    diffs = diff_bindings(chosen_serial, chosen_tpu[: len(pods.keys)], pods.keys)
    assert not diffs, diffs[:10]


def test_bindings_match_aggregated_filter_and_score():
    cluster = synth_cluster(num_nodes=30, num_pods=60, seed=11, aggregated_fraction=0.9)
    args = LoadAwareArgs(
        agg_usage_thresholds={ResourceName.CPU: 70, ResourceName.MEMORY: 95},
        agg_usage_aggregation_type="p95",
        agg_score_aggregation_type="p95",
        agg_score_duration_seconds=1800,
    )
    pods, nodes, inputs = _make_inputs(cluster, args)
    chosen_tpu = np.asarray(build_schedule_step(args)(inputs)[0])
    chosen_serial = serial_schedule(inputs, args)
    diffs = diff_bindings(chosen_serial, chosen_tpu[: len(pods.keys)], pods.keys)
    assert not diffs, diffs[:10]


def test_sequential_contract_visible():
    """Pod i+1 must see pod i's assignment (assign-cache estimate + Fit state):
    schedule two identical big pods onto a 2-node cluster; they must spread."""
    cluster = synth_cluster(
        num_nodes=2,
        num_pods=2,
        seed=3,
        missing_metric_fraction=0.0,
        expired_fraction=0.0,
        custom_threshold_fraction=0.0,
        with_pod_metrics=False,
    )
    # identical nodes & pods
    for node in cluster.nodes:
        node.allocatable = cluster.nodes[0].allocatable.copy()
    from koordinator_tpu.api.resources import ResourceList

    for nm in cluster.node_metrics.values():
        nm.node_metric.node_usage = ResourceList.of(cpu=1000, memory=1024**3)
    for pod in cluster.pods:
        pod.spec.requests = ResourceList.of(cpu=8000, memory=16 * 1024**3)
        pod.spec.limits = ResourceList()
        pod.meta.owner_kind = ""
    args = LoadAwareArgs()
    pods, nodes, inputs = _make_inputs(cluster, args)
    chosen = np.asarray(build_schedule_step(args)(inputs)[0])[:2]
    assert chosen[0] != chosen[1], f"both pods landed on node {chosen[0]}"
    assert (chosen >= 0).all()


def test_score_matrix_consistent_with_serial_first_pod():
    """The one-shot score matrix must agree with the serial emulator's first-pod
    view (before any assignment feedback)."""
    cluster = synth_cluster(num_nodes=20, num_pods=10, seed=5)
    args = LoadAwareArgs()
    pods, nodes, inputs = _make_inputs(cluster, args)
    feasible, score = build_score_matrix(args)(inputs)
    feasible, score = np.asarray(feasible), np.asarray(score)

    chosen_serial = serial_schedule(inputs, args)
    p = 0
    if feasible[p].any():
        best = int(np.argmax(np.where(feasible[p], score[p], -1.0)))
        assert chosen_serial[p] == best


def test_bucketing():
    assert bucket_size(1) == 16
    assert bucket_size(16) == 16
    assert bucket_size(17) == 32
    assert bucket_size(10000) == 10240
    assert bucket_size(5000) == 5120
    assert bucket_size(1024) == 1024
    assert bucket_size(1025) == 1280
    assert bucket_size(10240) == 10240


def test_estimator_defaults_zero_request():
    """Zero-request pods estimate to 250 milli CPU / 200 MiB (default_estimator.go:35-38)."""
    from koordinator_tpu.api.objects import ObjectMeta, Pod, PodSpec
    from koordinator_tpu.ops.estimator import estimate_pod_used

    pod = Pod(meta=ObjectMeta(name="x"), spec=PodSpec(priority=9500))
    est = estimate_pod_used(pod, {"cpu": 1, "memory": 1}, {"cpu": 85, "memory": 70})
    assert est[RESOURCE_INDEX[ResourceName.CPU]] == 250.0
    assert est[RESOURCE_INDEX[ResourceName.MEMORY]] == 200.0


def test_estimator_limit_beats_request():
    """limit > request -> 100% of limit (default_estimator.go:73-79)."""
    from koordinator_tpu.api.objects import ObjectMeta, Pod, PodSpec
    from koordinator_tpu.api.resources import ResourceList
    from koordinator_tpu.ops.estimator import estimate_pod_used

    pod = Pod(
        meta=ObjectMeta(name="x"),
        spec=PodSpec(
            priority=9500,
            requests=ResourceList.of(cpu=1000),
            limits=ResourceList.of(cpu=4000),
        ),
    )
    est = estimate_pod_used(pod, {"cpu": 1}, {"cpu": 85})
    assert est[RESOURCE_INDEX[ResourceName.CPU]] == 4000.0


def test_estimator_scaling_factor():
    """request only -> scaled by factor (85% cpu default)."""
    from koordinator_tpu.api.objects import ObjectMeta, Pod, PodSpec
    from koordinator_tpu.api.resources import ResourceList
    from koordinator_tpu.ops.estimator import estimate_pod_used

    pod = Pod(
        meta=ObjectMeta(name="x"),
        spec=PodSpec(priority=9500, requests=ResourceList.of(cpu=1000)),
    )
    est = estimate_pod_used(pod, {"cpu": 1}, {"cpu": 85})
    assert est[RESOURCE_INDEX[ResourceName.CPU]] == 850.0
