"""Versioned componentconfig round-trip: v1beta2 external form with pointer
defaulting, strict decoding, and lossless encode/decode (reference
pkg/scheduler/apis/config/v1beta2/ register+defaults+conversion)."""

import pytest

from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.scheduler.config import (
    ConfigValidationError,
    SchedulerConfiguration,
)
from koordinator_tpu.scheduler.config_v1beta2 import (
    API_VERSION,
    decode_args,
    decode_component_config,
    encode_args,
    encode_component_config,
)


def test_roundtrip_defaults():
    cfg = SchedulerConfiguration()
    assert decode_component_config(encode_component_config(cfg)) == cfg


def test_roundtrip_non_defaults():
    cfg = SchedulerConfiguration()
    cfg.load_aware = LoadAwareArgs(
        node_metric_expiration_seconds=60.0,
        resource_weights={"cpu": 2, "memory": 1},
        score_according_prod_usage=True,
        agg_usage_thresholds={"cpu": 70},
        agg_usage_aggregation_type="p95",
    )
    cfg.coscheduling.default_timeout_seconds = 120.0
    cfg.device_share.scoring_strategy = "LeastAllocated"
    ext = encode_component_config(cfg)
    assert decode_component_config(ext) == cfg
    # double round-trip is stable (normalized form)
    assert encode_component_config(decode_component_config(ext)) == ext


def test_pointer_defaulting_absent_vs_explicit():
    """Absent/null fields take the v1beta2 default; an explicitly present
    falsy value is kept (the Go nil-pointer vs zero-value distinction)."""
    plugin, args = decode_args({
        "apiVersion": API_VERSION, "kind": "LoadAwareSchedulingArgs",
        "nodeMetricExpirationSeconds": None,       # null -> default
        "filterExpiredNodeMetrics": False,         # explicit falsy kept
        "resourceWeights": {},                     # explicit empty kept
    })
    assert plugin == "LoadAwareScheduling"
    assert args.node_metric_expiration_seconds == 180.0
    assert args.filter_expired_node_metrics is False
    assert args.resource_weights == {}
    # untouched fields keep their defaults
    assert args.usage_thresholds == {"cpu": 65, "memory": 95}


def test_aggregated_nesting():
    ext = encode_args(LoadAwareArgs(agg_usage_aggregation_type="p90",
                                    agg_usage_thresholds={"cpu": 60}))
    assert ext["aggregated"]["usageAggregationType"] == "p90"
    assert "aggUsageAggregationType" not in ext
    _plugin, back = decode_args(ext)
    assert back.agg_usage_aggregation_type == "p90"
    assert back.agg_usage_thresholds == {"cpu": 60}


def test_camel_case_acronyms():
    ext = encode_args(SchedulerConfiguration().node_numa_resource)
    assert ext["kind"] == "NodeNUMAResourceArgs"
    assert "defaultCPUBindPolicy" in ext
    assert "numaAllocateStrategy" in ext


def test_strict_unknown_field_and_kind():
    with pytest.raises(ConfigValidationError, match="unknown field"):
        decode_args({"apiVersion": API_VERSION, "kind": "ReservationArgs",
                     "gcDurationSeconds": 10, "bogus": 1})
    with pytest.raises(ConfigValidationError, match="unknown kind"):
        decode_args({"apiVersion": API_VERSION, "kind": "NopeArgs"})
    with pytest.raises(ConfigValidationError, match="unknown apiVersion"):
        decode_args({"apiVersion": "v9", "kind": "ReservationArgs"})


def test_component_config_guards():
    base = encode_component_config(SchedulerConfiguration())
    dup = dict(base)
    entry = base["profiles"][0]["pluginConfig"][0]
    dup["profiles"] = [{
        "schedulerName": "koord-scheduler",
        "pluginConfig": [entry, entry],
    }]
    with pytest.raises(ConfigValidationError, match="duplicate"):
        decode_component_config(dup)
    mismatch = {
        "apiVersion": API_VERSION, "kind": "KubeSchedulerConfiguration",
        "profiles": [{"schedulerName": "koord-scheduler", "pluginConfig": [
            {"name": "Coscheduling",
             "args": {"apiVersion": API_VERSION, "kind": "ReservationArgs"}},
        ]}],
    }
    with pytest.raises(ConfigValidationError, match="does not match"):
        decode_component_config(mismatch)


def test_other_profiles_ignored():
    raw = encode_component_config(SchedulerConfiguration())
    raw["profiles"].insert(0, {
        "schedulerName": "default-scheduler",
        "pluginConfig": [{"name": "Coscheduling", "args": {
            "apiVersion": API_VERSION, "kind": "CoschedulingArgs",
            "defaultTimeoutSeconds": 5.0}}],
    })
    cfg = decode_component_config(raw)
    assert cfg.coscheduling.default_timeout_seconds == 600.0  # untouched


def test_validation_runs_after_decode():
    raw = {
        "apiVersion": API_VERSION, "kind": "KubeSchedulerConfiguration",
        "profiles": [{"schedulerName": "koord-scheduler", "pluginConfig": [
            {"name": "DeviceShare", "args": {
                "apiVersion": API_VERSION, "kind": "DeviceShareArgs",
                "scoringStrategy": "Bogus"}},
        ]}],
    }
    with pytest.raises(ConfigValidationError, match="scoringStrategy"):
        decode_component_config(raw)


def test_decoded_config_drives_scheduler():
    """The versioned form plugs into the Scheduler constructor end-to-end."""
    from koordinator_tpu.client.store import ObjectStore
    from koordinator_tpu.scheduler.cycle import Scheduler

    raw = encode_component_config(SchedulerConfiguration())
    for entry in raw["profiles"][0]["pluginConfig"]:
        if entry["name"] == "Coscheduling":
            entry["args"]["defaultTimeoutSeconds"] = 42.0
    cfg = decode_component_config(raw)
    sched = Scheduler(ObjectStore(), config=cfg)
    gang = sched.extender.plugin("Coscheduling")
    assert gang.default_timeout_seconds == 42.0


def test_upstream_and_argless_entries_pass_through():
    """A profile can carry upstream kube-scheduler args (not koordinator
    kinds) and args-less entries; both are passed over, not rejected."""
    raw = {
        "apiVersion": API_VERSION, "kind": "KubeSchedulerConfiguration",
        "profiles": [{"schedulerName": "koord-scheduler", "pluginConfig": [
            {"name": "NodeResourcesFit", "args": {
                "apiVersion": API_VERSION, "kind": "NodeResourcesFitArgs",
                "scoringStrategy": {"type": "LeastAllocated"}}},
            {"name": "Coscheduling"},  # args-less == defaults
            {"name": "Reservation", "args": {
                "apiVersion": API_VERSION, "kind": "ReservationArgs",
                "gcDurationSeconds": 3600}},
        ]}],
    }
    cfg = decode_component_config(raw)
    assert cfg.reservation.gc_duration_seconds == 3600
    assert cfg.coscheduling.default_timeout_seconds == 600.0


def test_wrong_wire_types_are_validation_errors():
    with pytest.raises(ConfigValidationError, match="expected float"):
        decode_args({"apiVersion": API_VERSION, "kind": "ReservationArgs",
                     "gcDurationSeconds": "ten"})
    with pytest.raises(ConfigValidationError, match="expected dict"):
        decode_args({"apiVersion": API_VERSION,
                     "kind": "LoadAwareSchedulingArgs",
                     "resourceWeights": ["cpu"]})
    with pytest.raises(ConfigValidationError, match="expected bool"):
        decode_args({"apiVersion": API_VERSION,
                     "kind": "LoadAwareSchedulingArgs",
                     "filterExpiredNodeMetrics": 1})
    with pytest.raises(ConfigValidationError, match="expected object"):
        decode_args({"apiVersion": API_VERSION,
                     "kind": "LoadAwareSchedulingArgs",
                     "aggregated": [1]})


def test_dict_element_types_are_validation_errors():
    raw = {
        "apiVersion": API_VERSION, "kind": "KubeSchedulerConfiguration",
        "profiles": [{"schedulerName": "koord-scheduler", "pluginConfig": [
            {"name": "LoadAwareScheduling", "args": {
                "apiVersion": API_VERSION,
                "kind": "LoadAwareSchedulingArgs",
                "resourceWeights": {"cpu": "high"}}},
        ]}],
    }
    with pytest.raises(ConfigValidationError, match="resourceWeights"):
        decode_component_config(raw)


def test_malformed_wire_containers_are_validation_errors():
    """Non-dict profiles, pluginConfig entries, and args values must
    surface as ConfigValidationError, never AttributeError (the
    config_v1beta2.py:200 bug class koordlint's wire-unguarded-access
    rule now guards)."""
    base = {"apiVersion": API_VERSION, "kind": "KubeSchedulerConfiguration"}
    with pytest.raises(ConfigValidationError, match="profiles\\[0\\]"):
        decode_component_config({**base, "profiles": ["not-an-object"]})
    with pytest.raises(ConfigValidationError,
                       match="pluginConfig\\[0\\]: expected object"):
        decode_component_config({**base, "profiles": [
            {"schedulerName": "koord-scheduler",
             "pluginConfig": ["oops"]}]})
    with pytest.raises(ConfigValidationError, match="args must be an"):
        decode_component_config({**base, "profiles": [
            {"schedulerName": "koord-scheduler",
             "pluginConfig": [{"name": "Reservation", "args": "foo"}]}]})
    # several malformed layers accumulate into one error list
    with pytest.raises(ConfigValidationError) as ei:
        decode_component_config({**base, "profiles": [
            17,
            {"schedulerName": "koord-scheduler",
             "pluginConfig": [{"args": [1, 2]}, "bad-entry"]},
        ]})
    assert len(ei.value.errors) == 3


def test_non_list_wire_containers_are_validation_errors():
    """profiles/pluginConfig that are not lists (or are strings, which
    would otherwise iterate per character) fail as one validation error."""
    base = {"apiVersion": API_VERSION, "kind": "KubeSchedulerConfiguration"}
    with pytest.raises(ConfigValidationError, match="profiles: expected"):
        decode_component_config({**base, "profiles": 17})
    with pytest.raises(ConfigValidationError, match="profiles: expected"):
        decode_component_config({**base, "profiles": "text"})
    with pytest.raises(ConfigValidationError) as ei:
        decode_component_config({**base, "profiles": [
            {"schedulerName": "koord-scheduler", "pluginConfig": "oops"}]})
    assert ei.value.errors == [
        "profiles[0].pluginConfig: expected list, got str"]
