"""Descheduler: node classification, migration arbitration, and the full
reserve-then-evict loop interlocking with the scheduler."""

import numpy as np

from koordinator_tpu.api.objects import (
    LABEL_POD_QOS,
    Node,
    NodeMetric,
    NodeMetricInfo,
    ObjectMeta,
    Pod,
    PodMigrationJob,
    PodSpec,
)
from koordinator_tpu.api.resources import ResourceList, ResourceName
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_POD,
    KIND_POD_MIGRATION_JOB,
    KIND_RESERVATION,
    ObjectStore,
)
from koordinator_tpu.descheduler import Arbitrator, Descheduler, MigrationController
from koordinator_tpu.descheduler.lownodeload import (
    LowNodeLoad,
    LowNodeLoadArgs,
    classify_nodes,
)
from koordinator_tpu.descheduler.migration import ArbitratorArgs
from koordinator_tpu.scheduler.cycle import Scheduler

GIB = 1024**3
NOW = 1_000_000.0


def _node(store, name, cores=16, usage_frac=None):
    node = Node(
        meta=ObjectMeta(name=name, namespace=""),
        allocatable=ResourceList.of(cpu=cores * 1000, memory=64 * GIB, pods=110),
    )
    store.add(KIND_NODE, node)
    if usage_frac is not None:
        store.add(
            KIND_NODE_METRIC,
            NodeMetric(
                meta=ObjectMeta(name=name, namespace=""),
                update_time=NOW - 10,
                node_metric=NodeMetricInfo(
                    node_usage=ResourceList.of(
                        cpu=int(cores * 1000 * usage_frac),
                        memory=int(64 * GIB * 0.3),
                    )
                ),
            ),
        )
    return node


def _running_pod(store, name, node, cpu=2000, prio=5500, owner=("ReplicaSet", "rs1")):
    pod = Pod(
        meta=ObjectMeta(name=name, labels={LABEL_POD_QOS: "BE"},
                        owner_kind=owner[0], owner_name=owner[1],
                        creation_timestamp=NOW),
        spec=PodSpec(node_name=node, priority=prio,
                     requests=ResourceList.of(cpu=cpu, memory=4 * GIB)),
        phase="Running",
    )
    store.add(KIND_POD, pod)
    return pod


class TestClassification:
    def test_classify(self):
        from koordinator_tpu.api.resources import NUM_RESOURCES, RESOURCE_INDEX

        usage = np.zeros((3, NUM_RESOURCES), np.float32)
        cpu = RESOURCE_INDEX[ResourceName.CPU]
        usage[0, cpu] = 30.0   # low
        usage[1, cpu] = 60.0   # mid
        usage[2, cpu] = 90.0   # high
        low_thr = np.zeros(NUM_RESOURCES, np.float32)
        high_thr = np.zeros(NUM_RESOURCES, np.float32)
        low_thr[cpu], high_thr[cpu] = 45.0, 70.0
        low, high = classify_nodes(
            usage, np.ones(3, bool), low_thr, high_thr
        )
        assert list(low) == [True, False, False]
        assert list(high) == [False, False, True]

    def test_no_metric_not_classified(self):
        from koordinator_tpu.api.resources import NUM_RESOURCES

        low, high = classify_nodes(
            np.zeros((1, NUM_RESOURCES), np.float32),
            np.zeros(1, bool),
            np.full(NUM_RESOURCES, 45, np.float32),
            np.full(NUM_RESOURCES, 70, np.float32),
        )
        assert not low[0] and not high[0]


class TestLowNodeLoad:
    def test_creates_jobs_for_high_nodes(self):
        store = ObjectStore()
        _node(store, "hot", usage_frac=0.9)
        _node(store, "cold", usage_frac=0.2)
        for i in range(3):
            _running_pod(store, f"be-{i}", "hot", owner=("ReplicaSet", f"rs{i}"))
        jobs = LowNodeLoad(store).balance(now=NOW)
        assert jobs, "no migration jobs created for the hot node"
        assert all(
            store.get(KIND_POD, f"{j.pod_namespace}/{j.pod_name}").spec.node_name
            == "hot"
            for j in jobs
        )

    def test_no_jobs_without_low_nodes(self):
        store = ObjectStore()
        _node(store, "hot1", usage_frac=0.9)
        _node(store, "hot2", usage_frac=0.9)
        _running_pod(store, "p", "hot1")
        assert LowNodeLoad(store).balance(now=NOW) == []


class TestArbitrator:
    def test_rate_limits(self):
        store = ObjectStore()
        _node(store, "n1", usage_frac=0.9)
        pods = [
            _running_pod(store, f"p{i}", "n1", owner=("ReplicaSet", "shared-rs"))
            for i in range(4)
        ]
        jobs = [
            PodMigrationJob(
                meta=ObjectMeta(name=f"j{i}", namespace="koordinator-system",
                                creation_timestamp=NOW + i),
                pod_namespace="default", pod_name=f"p{i}",
            )
            for i in range(4)
        ]
        arb = Arbitrator(store, ArbitratorArgs(max_migrating_per_node=2,
                                               max_migrating_per_workload=1))
        admitted = arb.arbitrate(jobs)
        # workload cap (1) binds before the node cap (2)
        assert len(admitted) == 1
        assert admitted[0].meta.name == "j0"  # earliest first


class TestMigrationEndToEnd:
    def test_reserve_then_evict_with_scheduler(self):
        store = ObjectStore()
        from tests.test_scheduler_e2e import make_store  # reuse fixtures

        # hot node with a movable BE pod + cold empty node with metrics
        store = make_store(num_nodes=2, cores=16, mem_gib=64)
        hot_metric = store.get(KIND_NODE_METRIC, "/node-0")
        hot_metric.node_metric.node_usage = ResourceList.of(
            cpu=15_000, memory=20 * GIB
        )
        store.update(KIND_NODE_METRIC, hot_metric)
        victim = _running_pod(store, "victim", "node-0", cpu=4000)
        # second healthy replica: the controllerfinder guard refuses to evict
        # a workload's only member
        _running_pod(store, "victim-peer", "node-1", cpu=1000)

        desched = Descheduler(store)
        sched = Scheduler(store)

        out1 = desched.run_once(now=NOW)
        assert out1["jobs_created"] == 1
        # job running, reservation created but not yet scheduled
        desched.run_once(now=NOW + 1)
        res = store.list(KIND_RESERVATION)[0]
        assert res.phase == "Pending"

        sched.run_cycle(now=NOW + 2)  # scheduler binds the reservation
        res = store.list(KIND_RESERVATION)[0]
        assert res.is_available
        assert res.node_name == "node-1"  # not the hot source

        desched.run_once(now=NOW + 3)  # now the victim is evicted
        job = store.list(KIND_POD_MIGRATION_JOB)[0]
        assert job.phase == "Succeeded"
        victim = store.get(KIND_POD, "default/victim")
        assert victim.phase == "Failed"
        assert "migration" in victim.meta.annotations["koordinator.sh/evicted"]

    def test_job_timeout(self):
        store = ObjectStore()
        _node(store, "n1", usage_frac=0.5)
        _running_pod(store, "p", "n1")
        job = PodMigrationJob(
            meta=ObjectMeta(name="j", namespace="koordinator-system",
                            creation_timestamp=NOW),
            pod_namespace="default", pod_name="p", ttl_seconds=100,
        )
        store.add(KIND_POD_MIGRATION_JOB, job)
        ctrl = MigrationController(store)
        ctrl.reconcile(now=NOW + 1)   # admitted -> Running
        ctrl.reconcile(now=NOW + 200)  # TTL exceeded
        assert store.list(KIND_POD_MIGRATION_JOB)[0].phase == "Failed"


def test_balance_victim_set_matches_compiled_floor_non_dyadic():
    """The vectorized selection must pick the IDENTICAL victim set as the
    serial C++ floor even with non-power-of-two requests, where a global
    float32 cumsum would drift at the still_over threshold (per-segment
    sequential accumulation is the contract)."""
    import random

    from koordinator_tpu.descheduler.lownodeload import pack_floor_inputs
    from koordinator_tpu.native import floor as native_floor

    if not (native_floor.available() or native_floor.build()):
        return
    rng = random.Random(5)
    store = ObjectStore()
    for i in range(40):
        frac = 0.85 if i % 2 == 0 else 0.2
        _node(store, f"n{i}", cores=32, usage_frac=frac)
    for p in range(600):
        _running_pod(
            store, f"p{p}", f"n{p % 40}",
            cpu=rng.choice([100, 300, 700, 1100, 1300]),
            prio=rng.choice([100, 5500, 9000]))
    plugin = LowNodeLoad(store)
    jobs = plugin.balance(now=NOW)
    assert jobs

    pods_l, floor_arrays = pack_floor_inputs(store, plugin, NOW)
    victim = native_floor.lownodeload_floor_native(**floor_arrays)
    floor_victims = {f"{pods_l[i].meta.namespace}/{pods_l[i].meta.name}"
                     for i in np.nonzero(victim)[0]}
    plugin_victims = {f"{j.pod_namespace}/{j.pod_name}" for j in jobs}
    assert floor_victims == plugin_victims


def test_eviction_cost_orders_and_opts_out():
    """scheduling.koordinator.sh/eviction-cost: cheaper pods migrate first;
    int32-max opts the pod out of migration entirely."""
    from koordinator_tpu.api.objects import (
        Node,
        ObjectMeta,
        Pod,
        PodMigrationJob,
        PodSpec,
    )
    from koordinator_tpu.api.resources import ResourceList
    from koordinator_tpu.client.store import (
        KIND_NODE,
        KIND_POD,
        ObjectStore,
    )
    from koordinator_tpu.descheduler.migration import Arbitrator, ArbitratorArgs

    store = ObjectStore()
    store.add(KIND_NODE, Node(meta=ObjectMeta(name="n0", namespace=""),
                              allocatable=ResourceList.of(cpu=64000)))
    jobs = []
    for name, cost in (("cheap", "1"), ("pricy", "100"),
                       ("never", str(2**31 - 1)), ("free", None)):
        ann = {}
        if cost is not None:
            ann["scheduling.koordinator.sh/eviction-cost"] = cost
        pod = Pod(meta=ObjectMeta(name=name, annotations=ann,
                                  creation_timestamp=100.0),
                  spec=PodSpec(node_name="n0",
                               requests=ResourceList.of(cpu=1000)),
                  phase="Running")
        store.add(KIND_POD, pod)
        job = PodMigrationJob(meta=ObjectMeta(name=f"mj-{name}"),
                              pod_namespace="default", pod_name=name)
        jobs.append(job)
    arb = Arbitrator(store, ArbitratorArgs(max_migrating_per_node=10))
    admitted = arb.arbitrate(jobs)
    names = [j.pod_name for j in admitted]
    assert names == ["free", "cheap", "pricy"]  # cost asc; opted-out absent
