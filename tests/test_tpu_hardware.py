"""Compiled (non-interpret) Pallas kernel parity on REAL TPU hardware.

CI runs the Pallas kernels interpret-mode only (no chip); bench-time parity
covers the flagship path but only when the bench runs. These tests make
hardware coverage systematic: run `KOORD_TPU_TESTS=1 python -m pytest
tests/test_tpu_hardware.py` on a machine with the chip and the compiled
kernels are diffed binding-for-binding against the XLA step; everywhere
else they auto-skip (conftest marker gate)."""

import numpy as np
import pytest

from koordinator_tpu.models.full_chain import build_full_chain_step
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.scheduler.snapshot import build_full_chain_inputs
from koordinator_tpu.testing import synth_full_cluster

pytestmark = pytest.mark.requires_tpu

ZONE = "topology.kubernetes.io/zone"


def _mixed_state(seed, nodes=48, pods=96):
    from koordinator_tpu.api.objects import PodAffinityTerm

    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(nodes, pods, seed=seed)
    for j, node in enumerate(state.nodes):
        node.meta.labels[ZONE] = f"z{j % 4}"
    for i, pod in enumerate(state.pending_pods):
        pod.meta.labels["app"] = f"a{i % 3}"
        if i % 5 == 0:
            pod.spec.pod_anti_affinity.append(PodAffinityTerm(
                selector={"app": pod.meta.labels["app"]}, topology_key=ZONE))
        if i % 7 == 0:
            pod.spec.host_ports.append(("TCP", 8080))
    fc, pods_b, nb, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    return args, fc, pods_b, ng, ngroups


def test_pallas_full_chain_compiled_parity():
    from koordinator_tpu.ops.pallas_full_chain import (
        build_pallas_full_chain_step,
    )

    args, fc, pods_b, ng, ngroups = _mixed_state(seed=3)
    ref = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    compiled = np.asarray(
        build_pallas_full_chain_step(args, ng, ngroups, interpret=False)(
            fc)[0])
    np.testing.assert_array_equal(compiled, ref)


def test_pallas_full_chain_compiled_parity_second_seed():
    from koordinator_tpu.ops.pallas_full_chain import (
        build_pallas_full_chain_step,
    )

    args, fc, pods_b, ng, ngroups = _mixed_state(seed=11, nodes=64, pods=128)
    ref = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    compiled = np.asarray(
        build_pallas_full_chain_step(args, ng, ngroups, interpret=False)(
            fc)[0])
    np.testing.assert_array_equal(compiled, ref)


def test_pallas_loadaware_step_compiled_parity():
    from koordinator_tpu.models.scheduler_model import (
        build_best_schedule_step,
        build_schedule_step,
        make_inputs,
    )
    from koordinator_tpu.ops.loadaware import build_loadaware_node_state
    from koordinator_tpu.ops.packing import pack_nodes, pack_pods
    from koordinator_tpu.testing import synth_cluster

    args = LoadAwareArgs()
    cluster = synth_cluster(num_nodes=64, num_pods=96, seed=7)
    pods = pack_pods(cluster.pods, args.resource_weights,
                     args.estimated_scaling_factors)
    nodes = pack_nodes(cluster.nodes)
    nodes.extras = build_loadaware_node_state(
        cluster.nodes, cluster.node_metrics, cluster.pods_by_key,
        cluster.assigned, args, cluster.now, pad_to=nodes.padded_size)
    inputs = make_inputs(pods, nodes, args)
    ref = np.asarray(build_schedule_step(args)(inputs)[0])
    best = np.asarray(build_best_schedule_step(args)(inputs)[0])
    np.testing.assert_array_equal(best, ref)
