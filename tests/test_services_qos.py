"""Tests for the frameworkext services engine + error-handler dispatcher and
the blkio/sysreconcile QoS plugins (reference frameworkext/services,
errorhandler_dispatcher.go, qosmanager plugins blkio + sysreconcile)."""

import json
import urllib.request

import pytest

from koordinator_tpu.api.objects import (
    LABEL_POD_QOS,
    Node,
    NodeSLO,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceQOSStrategy,
    SystemStrategy,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_NODE_SLO,
    KIND_POD,
    ObjectStore,
)
from koordinator_tpu.koordlet.daemon import Daemon
from koordinator_tpu.koordlet.util import system as sysutil
from koordinator_tpu.koordlet.util.system import FakeFS
from koordinator_tpu.scheduler.cycle import Scheduler
from koordinator_tpu.scheduler.frameworkext import ErrorHandlerDispatcher
from koordinator_tpu.utils.features import KOORDLET_GATES

GIB = 1024**3
NOW = 1_000_000.0


@pytest.fixture
def fs():
    f = FakeFS(use_cgroup_v2=True)
    yield f
    f.cleanup()


def _mk_slo(**kwargs):
    return NodeSLO(meta=ObjectMeta(name="node-0", namespace=""), **kwargs)


def _mk_node_env(store, fs, mem_gib=64):
    store.add(KIND_NODE, Node(
        meta=ObjectMeta(name="node-0", namespace=""),
        allocatable=ResourceList.of(cpu=16_000, memory=mem_gib * GIB)))
    fs.set_proc("stat", "cpu  1000 0 1000 8000 0 0 0 0 0 0\n")
    fs.set_proc("meminfo",
                f"MemTotal: {mem_gib * GIB // 1024} kB\n"
                f"MemFree: {mem_gib * GIB // 2048} kB\n")


class TestBlkIOReconcile:
    def test_writes_per_tier_weights(self, fs):
        store = ObjectStore()
        _mk_node_env(store, fs)
        store.add(KIND_NODE_SLO, _mk_slo(
            resource_qos_strategy=ResourceQOSStrategy(
                blkio_enable=True, ls_blkio_weight=500, be_blkio_weight=50)))
        daemon = Daemon(store, "node-0", fs.config, report_interval_seconds=0)
        KOORDLET_GATES.set_from_map({"BlkIOReconcile": True})
        try:
            daemon.run_once(now=NOW)
        finally:
            KOORDLET_GATES.reset()
        be_rel = fs.config.qos_relative_path(sysutil.QOS_BESTEFFORT)
        burstable_rel = fs.config.qos_relative_path(sysutil.QOS_BURSTABLE)
        # v2 tree: blkio.bfq.weight translates to io.weight
        assert fs.get_cgroup(be_rel, sysutil.BLKIO_WEIGHT) == "50"
        assert fs.get_cgroup(burstable_rel, sysutil.BLKIO_WEIGHT) == "500"

    def test_disabled_without_gate_or_strategy(self, fs):
        store = ObjectStore()
        _mk_node_env(store, fs)
        store.add(KIND_NODE_SLO, _mk_slo(
            resource_qos_strategy=ResourceQOSStrategy(blkio_enable=True)))
        daemon = Daemon(store, "node-0", fs.config, report_interval_seconds=0)
        daemon.run_once(now=NOW)  # gate off by default
        be_rel = fs.config.qos_relative_path(sysutil.QOS_BESTEFFORT)
        assert fs.get_cgroup(be_rel, sysutil.BLKIO_WEIGHT) is None


class TestSystemReconcile:
    def test_writes_vm_knobs(self, fs):
        store = ObjectStore()
        _mk_node_env(store, fs, mem_gib=64)
        store.add(KIND_NODE_SLO, _mk_slo(
            system_strategy=SystemStrategy(
                min_free_kbytes_factor=100, watermark_scale_factor=200)))
        daemon = Daemon(store, "node-0", fs.config, report_interval_seconds=0)
        KOORDLET_GATES.set_from_map({"SystemConfig": True})
        try:
            daemon.run_once(now=NOW)
        finally:
            KOORDLET_GATES.reset()
        total_kb = 64 * GIB // 1024
        want_min_free = total_kb * 100 // 10_000
        assert sysutil.read_file(
            fs.config.proc_path("sys/vm/min_free_kbytes")) == str(want_min_free)
        assert sysutil.read_file(
            fs.config.proc_path("sys/vm/watermark_scale_factor")) == "200"


class TestErrorHandlerDispatcher:
    def _pod(self, name):
        return Pod(meta=ObjectMeta(name=name))

    def test_chain_and_default(self):
        d = ErrorHandlerDispatcher()
        seen = []
        d.register(lambda pod, r: (seen.append(("h1", pod.meta.name)),
                                   r == "handled-by-1")[1])
        fallback = []
        d.default_handler = lambda pod, r: fallback.append(pod.meta.name)
        d.dispatch(self._pod("a"), "handled-by-1")
        d.dispatch(self._pod("b"), "unhandled")
        assert [s[1] for s in seen] == ["a", "b"]
        assert fallback == ["b"]
        assert [f[1] for f in d.failures] == ["handled-by-1", "unhandled"]

    def test_cycle_dispatches_unschedulable(self):
        store = ObjectStore()
        # node too small for the pod -> no feasible node
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name="node-0", namespace=""),
            allocatable=ResourceList.of(cpu=1000, memory=GIB)))
        sched = Scheduler(store)
        reasons = []
        sched.extender.error_handlers.register(
            lambda pod, r: (reasons.append((pod.meta.name, r)), True)[1])
        store.add(KIND_POD, Pod(
            meta=ObjectMeta(name="big", labels={LABEL_POD_QOS: "LS"}),
            spec=PodSpec(requests=ResourceList.of(cpu=64_000, memory=GIB))))
        result = sched.run_cycle(now=NOW)
        assert result.failed == ["default/big"]
        assert reasons and reasons[0][0] == "big"


class TestServicesEngine:
    def _sched(self):
        store = ObjectStore()
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name="node-0", namespace=""),
            allocatable=ResourceList.of(cpu=8000, memory=16 * GIB)))
        store.add(KIND_POD, Pod(
            meta=ObjectMeta(name="p1", labels={LABEL_POD_QOS: "LS"}),
            spec=PodSpec(node_name="node-0",
                         requests=ResourceList.of(cpu=1000, memory=GIB)),
            phase="Running"))
        return Scheduler(store)

    def test_node_dump(self):
        sched = self._sched()
        out = sched.extender.services.handle("/apis/v1/nodes/node-0")
        assert out["name"] == "node-0"
        assert out["pods"] == ["default/p1"]
        assert out["allocatable"]["cpu"] == 8000

    def test_plugin_endpoints(self):
        sched = self._sched()
        quotas = sched.extender.services.handle(
            "/apis/v1/plugins/ElasticQuota/quotas")
        assert quotas == {}
        gangs = sched.extender.services.handle(
            "/apis/v1/plugins/Coscheduling/gangs")
        assert gangs == {}
        with pytest.raises(KeyError):
            sched.extender.services.handle("/apis/v1/plugins/Nope/x")
        with pytest.raises(KeyError):
            sched.extender.services.handle("/apis/v1/plugins/ElasticQuota/nope")

    def test_http_serving(self):
        sched = self._sched()
        server, _ = sched.extender.services.serve(port=0)
        try:
            port = server.server_address[1]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/apis/v1/nodes/node-0") as resp:
                body = json.load(resp)
            assert body["pods"] == ["default/p1"]
            # 404 on unknown path
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/apis/v1/nodes/ghost")
                assert False, "expected 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            server.shutdown()
