"""Topology-manager hint-merge tests, mirroring the reference's
frameworkext/topologymanager/policy_*_test.go cases."""

import numpy as np

from koordinator_tpu.api.objects import (
    CPUInfo,
    NodeResourceTopology,
    NUMAZone,
    ObjectMeta,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.scheduler.topologymanager import (
    POLICY_BEST_EFFORT,
    POLICY_NONE,
    POLICY_RESTRICTED,
    POLICY_SINGLE_NUMA_NODE,
    NUMATopologyHint,
    TopologyManager,
    generate_fit_hints,
    merge_hints,
)
from koordinator_tpu.utils.bitmask import BitMask


def hint(bits, preferred=True, score=0):
    return NUMATopologyHint(BitMask(bits), preferred, score)


class TestMergeHints:
    def test_none_policy_always_admits(self):
        best, admit = merge_hints(
            POLICY_NONE, [0, 1], [{"cpu": [hint([1], preferred=False)]}]
        )
        assert admit
        assert best.affinity is None

    def test_best_effort_always_admits(self):
        # no provider can place -> still admitted, non-preferred default hint
        best, admit = merge_hints(POLICY_BEST_EFFORT, [0, 1], [{"cpu": []}])
        assert admit
        assert not best.preferred

    def test_restricted_requires_preferred(self):
        best, admit = merge_hints(
            POLICY_RESTRICTED, [0, 1], [{"cpu": [hint([0], preferred=False)]}]
        )
        assert not admit
        best, admit = merge_hints(
            POLICY_RESTRICTED, [0, 1], [{"cpu": [hint([0], preferred=True)]}]
        )
        assert admit
        assert best.affinity == BitMask([0])

    def test_narrowest_preferred_wins(self):
        best, admit = merge_hints(
            POLICY_BEST_EFFORT,
            [0, 1],
            [{"cpu": [hint([0, 1]), hint([1])]}],
        )
        assert admit
        assert best.affinity == BitMask([1])

    def test_cross_provider_and(self):
        # provider A can use {0} or {0,1}; provider B only {0,1}:
        # the AND of {0} x {0,1} = {0} is the narrowest preferred merge
        best, admit = merge_hints(
            POLICY_RESTRICTED,
            [0, 1],
            [
                {"cpu": [hint([0]), hint([0, 1])]},
                {"gpu": [hint([0, 1])]},
            ],
        )
        assert admit
        assert best.affinity == BitMask([0])

    def test_conflicting_single_zones_not_preferred(self):
        # A wants zone 0 only, B wants zone 1 only -> empty AND is skipped;
        # merged best falls back to non-preferred -> restricted rejects
        best, admit = merge_hints(
            POLICY_RESTRICTED,
            [0, 1],
            [{"cpu": [hint([0])]}, {"gpu": [hint([1])]}],
        )
        assert not admit

    def test_single_numa_node_filters_wide_hints(self):
        # only a two-zone placement fits -> single-numa-node rejects
        best, admit = merge_hints(
            POLICY_SINGLE_NUMA_NODE, [0, 1], [{"cpu": [hint([0, 1])]}]
        )
        assert not admit

    def test_single_numa_node_admits_one_zone(self):
        best, admit = merge_hints(
            POLICY_SINGLE_NUMA_NODE,
            [0, 1],
            [{"cpu": [hint([0, 1]), hint([1])]}],
        )
        assert admit
        assert best.affinity == BitMask([1])

    def test_single_numa_dont_care_collapses_to_none(self):
        # provider has no preference -> default affinity collapses to nil hint
        best, admit = merge_hints(POLICY_SINGLE_NUMA_NODE, [0, 1], [None])
        assert admit
        assert best.affinity is None

    def test_missing_provider_hints_are_dont_care(self):
        best, admit = merge_hints(
            POLICY_RESTRICTED, [0, 1], [None, {"cpu": [hint([1])]}]
        )
        assert admit
        assert best.affinity == BitMask([1])

    def test_score_breaks_width_ties(self):
        # reference semantics (policy.go:171-177): a later equal-width hint
        # replaces the best only when it is NOT narrower yet scores higher;
        # a narrower (lower-bit) hint replaces unconditionally.
        best, admit = merge_hints(
            POLICY_BEST_EFFORT,
            [0, 1],
            [{"cpu": [hint([0], score=1), hint([1], score=9)]}],
        )
        assert admit
        assert best.affinity == BitMask([1])  # same width, higher score wins
        best, admit = merge_hints(
            POLICY_BEST_EFFORT,
            [0, 1],
            [{"cpu": [hint([1], score=9), hint([0], score=1)]}],
        )
        assert best.affinity == BitMask([0])  # narrower-by-bit replaces


class TestGenerateFitHints:
    def test_minimal_width_preferred(self):
        zone_free = np.zeros((2, 16), np.float32)
        zone_free[0, 0] = 2000.0
        zone_free[1, 0] = 4000.0
        req = np.zeros(16, np.float32)
        req[0] = 3000.0
        hints = generate_fit_hints(req, zone_free, [0, 1])
        by_mask = {h.affinity.to_int(): h for h in hints}
        assert by_mask[0b10].preferred  # zone 1 alone fits -> minimal width
        assert not by_mask[0b11].preferred

    def test_no_fit_returns_empty(self):
        zone_free = np.zeros((2, 16), np.float32)
        req = np.zeros(16, np.float32)
        req[0] = 1000.0
        assert generate_fit_hints(req, zone_free, [0, 1]) == []


class TestPluginIntegration:
    def _topology(self, name, zone_cpus):
        zones = [
            NUMAZone(numa_id=i, allocatable=ResourceList.of(cpu=c))
            for i, c in enumerate(zone_cpus)
        ]
        cpus = [
            CPUInfo(cpu_id=i, core_id=i, socket_id=0, numa_node_id=0)
            for i in range(4)
        ]
        return NodeResourceTopology(
            meta=ObjectMeta(name=name), cpus=cpus, zones=zones
        )

    def _make(self, policy, zone_cpus):
        from koordinator_tpu.api.objects import Node
        from koordinator_tpu.client.store import (
            KIND_NODE,
            KIND_NODE_TOPOLOGY,
            ObjectStore,
        )
        from koordinator_tpu.scheduler.plugins.nodenumaresource import (
            NodeNUMAResourcePlugin,
        )
        from koordinator_tpu.scheduler.snapshot import (
            LABEL_NUMA_TOPOLOGY_POLICY,
        )

        store = ObjectStore()
        plugin = NodeNUMAResourcePlugin()
        plugin.register(store)
        node = Node(meta=ObjectMeta(name="n0", namespace="", labels={
            LABEL_NUMA_TOPOLOGY_POLICY: policy,
        }))
        store.add(KIND_NODE, node)
        store.add(KIND_NODE_TOPOLOGY, self._topology("n0", zone_cpus))
        return store, plugin

    def _pod(self, cpu_milli):
        from koordinator_tpu.api.objects import Pod, PodSpec

        return Pod(
            meta=ObjectMeta(name="p0", namespace="default"),
            spec=PodSpec(requests=ResourceList.of(cpu=cpu_milli)),
        )

    def test_restricted_rejects_unfittable(self):
        from koordinator_tpu.scheduler.frameworkext import CycleContext

        store, plugin = self._make("restricted", [1000, 1000])
        err = plugin.reserve(self._pod(8000), "n0", CycleContext(now=0.0))
        assert err is not None and "NUMA" in err

    def test_single_numa_allocates_into_chosen_zone(self):
        from koordinator_tpu.scheduler.frameworkext import CycleContext

        store, plugin = self._make("single-numa-node", [1000, 4000])
        ctx = CycleContext(now=0.0)
        pod = self._pod(3000)
        assert plugin.reserve(pod, "n0", ctx) is None
        alloc = plugin.numa_allocated["n0"]
        # zone 1 is the only single zone that fits
        assert alloc[1, 0] == 3000.0
        assert alloc[0, 0] == 0.0
        plugin.unreserve(pod, "n0", ctx)
        assert plugin.numa_allocated["n0"].sum() == 0.0

    def test_none_policy_skips_admit(self):
        from koordinator_tpu.scheduler.frameworkext import CycleContext

        store, plugin = self._make("", [1000, 1000])
        # kubelet policy "none": a request larger than any zone still reserves
        assert plugin.reserve(self._pod(1500), "n0", CycleContext(now=0.0)) is None
