"""Direct coverage for scheduler/diagnose.py: golden messages per reason
branch, the counts/formatter split, and the kernel attribution pass
(models/full_chain.explain_stage_counts) against the host oracle on each
crafted branch — previously this module was only exercised indirectly
through cycle tests."""

import numpy as np
import pytest

from koordinator_tpu.models.full_chain import (
    EXPLAIN_STAGE_GANG,
    EXPLAIN_STAGE_QUOTA,
    EXPLAIN_STAGES,
    NUM_EXPLAIN_STAGES,
    FullChainInputs,
    explain_stage_counts,
    make_pod_evaluator,
    resolve_weight_idx,
)
from koordinator_tpu.models.scheduler_model import ScheduleInputs
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.scheduler.diagnose import (
    GANG_MESSAGE,
    QUOTA_MESSAGE,
    diagnose_unbound,
    format_stage_counts,
    host_stage_counts,
)

N, P, R, T, K, PT = 3, 1, 2, 1, 2, 1


def make_fc(**over) -> FullChainInputs:
    """A minimal 1-pod x 3-node batch where EVERY stage passes; each test
    flips exactly the arrays that trigger its reason branch."""
    f32, i32 = np.float32, np.int32

    base = ScheduleInputs(
        fit_requests=np.ones((P, R), f32),
        estimated=np.ones((P, R), f32),
        is_prod=np.zeros(P, bool),
        is_daemonset=np.zeros(P, bool),
        pod_valid=np.ones(P, bool),
        allocatable=np.full((N, R), 10.0, f32),
        requested=np.zeros((N, R), f32),
        node_ok=np.ones(N, bool),
        la_filter_usage=np.zeros((N, R), f32),
        la_has_filter_usage=np.zeros(N, bool),
        la_filter_thresholds=np.zeros((N, R), f32),
        la_prod_thresholds=np.zeros((N, R), f32),
        la_prod_pod_usage=np.zeros((N, R), f32),
        la_term_nonprod=np.zeros((N, R), f32),
        la_term_prod=np.zeros((N, R), f32),
        la_score_valid=np.ones(N, bool),
        la_filter_skip=np.zeros(N, bool),
        weights=np.asarray(LoadAwareArgs().weight_vector()[:R], f32),
    )
    base = base._replace(**{k: np.asarray(v)
                            for k, v in over.items()
                            if k in base._fields})
    fc_over = {k: np.asarray(v) for k, v in over.items()
               if k not in base._fields}
    fc = FullChainInputs(
        base=base,
        requests=np.ones((P, R), f32),
        gang_id=np.full(P, -1, i32),
        quota_id=np.full(P, -1, i32),
        needs_numa=np.zeros(P, bool),
        needs_bind=np.zeros(P, bool),
        cores_needed=np.zeros(P, f32),
        full_pcpus=np.zeros(P, bool),
        pod_taint_mask=np.ones(P, f32),       # bit 0 set
        pod_aff_req=np.zeros((P, T), bool),
        pod_anti_req=np.zeros((P, T), bool),
        pod_aff_match=np.zeros((P, T), bool),
        pod_spread_skew=np.zeros((P, T), f32),
        pod_pref_id=np.full(P, -1, i32),
        pod_ppref_id=np.full(P, -1, i32),
        pod_ppref_mask=np.zeros((P, T), bool),
        pod_port_wants=np.zeros((P, PT), bool),
        vol_needed=np.zeros((P, 1), f32),
        pod_img_id=np.full(P, -1, i32),
        node_taint_group=np.zeros(N, i32),    # group 0 -> bit 0
        aff_dom=np.zeros((N, T), f32),        # all nodes in domain 0
        aff_count=np.zeros((N, T), f32),
        anti_cover=np.zeros((N, T), f32),
        aff_exists=np.zeros(T, bool),
        pref_scores=np.zeros((N, 0), f32),
        port_used=np.zeros((N, PT), f32),
        vol_free=np.full(N, np.inf, f32),
        node_vol_group=np.zeros(N, i32),
        img_scores=np.zeros((N, 1), f32),
        ppref_w=np.zeros((1, T), f32),
        numa_free=np.full((N, K, R), 10.0, f32),
        numa_capacity=np.full((N, K, R), 10.0, f32),
        numa_policy=np.zeros(N, i32),
        has_topology=np.ones(N, bool),
        bind_free=np.full(N, 8.0, f32),
        cpus_per_core=np.ones(N, f32),
        quota_ancestors=np.asarray([[0, -1]], i32),
        quota_used=np.zeros((1, R), f32),
        quota_runtime=np.full((1, R), 100.0, f32),
        gang_min_member=np.ones(1, f32),
        gang_assumed=np.zeros(1, f32),
        gang_valid=np.ones(1, bool),
        gang_group_id=np.zeros(1, i32),
    )
    return fc._replace(**fc_over)


def kernel_counts(fc: FullChainInputs) -> np.ndarray:
    """The on-device attribution pass at cycle-start state, unjitted."""
    import jax
    import jax.numpy as jnp

    # vmap indexes pod rows with tracers: inputs must be device arrays
    # (inside the jitted production step they already are)
    fc = jax.tree_util.tree_map(jnp.asarray, fc)
    evaluate = make_pod_evaluator(
        fc, resolve_weight_idx(LoadAwareArgs(), list(range(R))), False)
    state = (fc.base.requested, fc.numa_free, fc.bind_free, fc.quota_used,
             fc.aff_count, fc.anti_cover, jnp.asarray(fc.aff_exists, bool),
             fc.port_used, fc.vol_free)
    return np.asarray(explain_stage_counts(fc, evaluate, state,
                                           np.int32(N)))


# every reason branch: (name, fc overrides, expected exact message)
BRANCHES = [
    ("gang", dict(gang_id=[0], gang_valid=[False]), GANG_MESSAGE),
    ("quota", dict(quota_id=[0], quota_runtime=[[1.0, 1.0]],
                   requests=[[2.0, 2.0]]), QUOTA_MESSAGE),
    ("unschedulable_node", dict(node_ok=[False] * 3),
     "0/3 nodes are available: 3 node not schedulable."),
    ("taint_selector", dict(pod_taint_mask=[0.0]),
     "0/3 nodes are available: "
     "3 taint/selector/volume-topology mismatch."),
    ("insufficient_resources", dict(fit_requests=[[100.0, 1.0]]),
     "0/3 nodes are available: 3 insufficient resources."),
    ("load_threshold", dict(la_has_filter_usage=[True] * 3,
                            la_filter_usage=[[9.0, 9.0]] * 3,
                            la_filter_thresholds=[[50.0, 50.0]] * 3),
     "0/3 nodes are available: 3 node load over threshold."),
    ("host_port", dict(pod_port_wants=[[True]],
                       port_used=[[1.0]] * 3),
     "0/3 nodes are available: 3 hostPort in use."),
    ("csi_limit", dict(vol_needed=[[2.0]], vol_free=[1.0] * 3),
     "0/3 nodes are available: 3 CSI volume limit exceeded."),
    ("bindable_cpus", dict(needs_bind=[True], cores_needed=[4.0],
                           bind_free=[2.0] * 3),
     "0/3 nodes are available: 3 insufficient bindable CPUs."),
    ("numa_topology", dict(needs_numa=[True], numa_policy=[1] * 3,
                           requests=[[5.0, 5.0]],
                           numa_free=[[[2.0, 2.0]] * K] * 3),
     "0/3 nodes are available: 3 NUMA topology cannot fit."),
    ("affinity", dict(pod_aff_req=[[True]], aff_exists=[True]),
     "0/3 nodes are available: "
     "3 affinity/anti-affinity/spread mismatch."),
]


@pytest.mark.parametrize("name,over,expected",
                         BRANCHES, ids=[b[0] for b in BRANCHES])
def test_golden_message_per_branch(name, over, expected):
    fc = make_fc(**over)
    assert diagnose_unbound(fc, 0, N) == expected


@pytest.mark.parametrize("name,over,expected",
                         BRANCHES, ids=[b[0] for b in BRANCHES])
def test_kernel_counts_match_host_per_branch(name, over, expected):
    """The on-device attribution must agree with the host oracle on every
    crafted branch — and format to the same golden message."""
    fc = make_fc(**over)
    host = host_stage_counts(fc, 0, N)
    kern = kernel_counts(fc)[0]
    assert np.array_equal(host, kern), (host, kern)
    assert format_stage_counts(kern, N) == expected


def test_in_batch_contention_fallback():
    """All stages pass at cycle-start state -> the contention message."""
    fc = make_fc()
    assert diagnose_unbound(fc, 0, N) == (
        "0/3 nodes available after in-batch placements: "
        "capacity consumed by earlier pods this cycle")
    assert not host_stage_counts(fc, 0, N).any()


def test_gang_short_circuits_quota_and_filters():
    """The legacy early-return order: gang wins over quota and over any
    filter-stage counts riding the same vector."""
    fc = make_fc(gang_id=[0], gang_valid=[False], quota_id=[0],
                 quota_runtime=[[1.0, 1.0]], requests=[[2.0, 2.0]],
                 node_ok=[False] * 3)
    counts = host_stage_counts(fc, 0, N)
    assert counts[EXPLAIN_STAGE_GANG] == 1
    assert counts[EXPLAIN_STAGE_QUOTA] == 1
    assert counts[0] == 3  # node not schedulable still counted
    assert diagnose_unbound(fc, 0, N) == GANG_MESSAGE
    fc2 = make_fc(quota_id=[0], quota_runtime=[[1.0, 1.0]],
                  requests=[[2.0, 2.0]], node_ok=[False] * 3)
    assert diagnose_unbound(fc2, 0, N) == QUOTA_MESSAGE


def test_multi_reason_sorted_by_count_then_taxonomy():
    """Counts sort descending; equal counts keep taxonomy order (the
    legacy dict-insertion tie-break via stable sort)."""
    # 3 taint mismatches everywhere, 1 node cordoned -> taint first
    fc = make_fc(pod_taint_mask=[0.0], node_ok=[False, True, True])
    assert diagnose_unbound(fc, 0, N) == (
        "0/3 nodes are available: "
        "3 taint/selector/volume-topology mismatch, "
        "1 node not schedulable.")
    # tie at 3: taxonomy order (node not schedulable before taint)
    fc = make_fc(pod_taint_mask=[0.0], node_ok=[False] * 3)
    assert diagnose_unbound(fc, 0, N) == (
        "0/3 nodes are available: 3 node not schedulable, "
        "3 taint/selector/volume-topology mismatch.")


def test_format_stage_counts_vector_contract():
    counts = np.zeros(NUM_EXPLAIN_STAGES, np.uint32)
    counts[2] = 5  # insufficient resources
    assert format_stage_counts(counts, 7) == (
        "0/7 nodes are available: 5 insufficient resources.")
    assert len(EXPLAIN_STAGES) + 2 == NUM_EXPLAIN_STAGES


def test_stage_taxonomy_matches_legacy_labels():
    """The kernel/host shared taxonomy IS the legacy message vocabulary;
    renaming a stage is a message-format change and must be deliberate."""
    assert EXPLAIN_STAGES == (
        "node not schedulable",
        "taint/selector/volume-topology mismatch",
        "insufficient resources",
        "node load over threshold",
        "hostPort in use",
        "CSI volume limit exceeded",
        "insufficient bindable CPUs",
        "NUMA topology cannot fit",
        "affinity/anti-affinity/spread mismatch",
    )
