"""Golden tests for the API layer against reference semantics tables.

The reference's `apis/extension/*_test.go` files are the spec (SURVEY.md section 7
step 1): QoS resolution, priority band mapping, resource-name translation.
"""

import numpy as np

from koordinator_tpu.api import (
    DEFAULT_PRIORITY_BY_CLASS,
    PriorityClass,
    QoSClass,
    ResourceList,
    ResourceName,
    priority_class_by_name,
    priority_class_by_value,
    qos_class_by_name,
    translate_resource_by_priority_class,
)
from koordinator_tpu.api.objects import (
    LABEL_POD_PRIORITY_CLASS,
    LABEL_POD_QOS,
    Pod,
    PodSpec,
    ObjectMeta,
    Reservation,
    ReservationOwner,
)


class TestQoS:
    def test_known_classes(self):
        # qos.go:31-39 table
        for name in ("LSE", "LSR", "LS", "BE", "SYSTEM"):
            assert qos_class_by_name(name).label == name

    def test_unknown_resolves_none(self):
        assert qos_class_by_name("lse") is QoSClass.NONE
        assert qos_class_by_name("") is QoSClass.NONE
        assert qos_class_by_name("garbage") is QoSClass.NONE

    def test_latency_sensitive_partition(self):
        assert QoSClass.LSE.is_latency_sensitive
        assert QoSClass.LSR.is_latency_sensitive
        assert QoSClass.LS.is_latency_sensitive
        assert not QoSClass.BE.is_latency_sensitive
        assert QoSClass.BE.is_best_effort


class TestPriority:
    def test_band_mapping(self):
        # priority.go:86-104 table
        assert priority_class_by_value(9000) is PriorityClass.PROD
        assert priority_class_by_value(9999) is PriorityClass.PROD
        assert priority_class_by_value(7500) is PriorityClass.MID
        assert priority_class_by_value(5000) is PriorityClass.BATCH
        assert priority_class_by_value(3999) is PriorityClass.FREE
        assert priority_class_by_value(8500) is PriorityClass.NONE
        assert priority_class_by_value(0) is PriorityClass.NONE
        assert priority_class_by_value(None) is PriorityClass.NONE

    def test_label_resolution(self):
        assert priority_class_by_name("koord-prod") is PriorityClass.PROD
        assert priority_class_by_name("koord-batch") is PriorityClass.BATCH
        assert priority_class_by_name("bogus") is PriorityClass.NONE

    def test_label_overrides_numeric(self):
        # priority.go:74-84: label wins over spec.priority
        pod = Pod(
            meta=ObjectMeta(labels={LABEL_POD_PRIORITY_CLASS: "koord-batch"}),
            spec=PodSpec(priority=9500),
        )
        assert pod.priority_class is PriorityClass.BATCH

    def test_defaults(self):
        assert DEFAULT_PRIORITY_BY_CLASS[PriorityClass.PROD] == 9999
        assert DEFAULT_PRIORITY_BY_CLASS[PriorityClass.BATCH] == 5999


class TestResources:
    def test_translate_by_priority_class(self):
        # resource.go:40-59 table
        assert (
            translate_resource_by_priority_class(PriorityClass.BATCH, ResourceName.CPU)
            == ResourceName.BATCH_CPU
        )
        assert (
            translate_resource_by_priority_class(
                PriorityClass.MID, ResourceName.MEMORY
            )
            == ResourceName.MID_MEMORY
        )
        assert (
            translate_resource_by_priority_class(PriorityClass.PROD, ResourceName.CPU)
            == ResourceName.CPU
        )
        assert (
            translate_resource_by_priority_class(PriorityClass.NONE, ResourceName.CPU)
            == ResourceName.CPU
        )

    def test_vector_roundtrip(self):
        rl = ResourceList.of(cpu=4000, memory=8 * 1024**3, gpu_core=50, pods=110)
        vec = rl.to_vector()
        assert vec.dtype == np.float32
        back = ResourceList.from_vector(vec)
        assert back[ResourceName.CPU] == 4000
        assert back[ResourceName.MEMORY] == 8 * 1024**3
        assert back[ResourceName.GPU_CORE] == 50
        assert back[ResourceName.PODS] == 110

    def test_memory_packed_as_mib(self):
        from koordinator_tpu.api.resources import RESOURCE_INDEX

        rl = ResourceList.of(memory=512 * 1024**2)
        assert rl.to_vector()[RESOURCE_INDEX[ResourceName.MEMORY]] == 512.0

    def test_arithmetic(self):
        a = ResourceList.of(cpu=1000, memory=1024**3)
        b = ResourceList.of(cpu=250)
        assert a.add(b)[ResourceName.CPU] == 1250
        assert a.sub(b)[ResourceName.CPU] == 750
        assert a.max(ResourceList.of(cpu=2000))[ResourceName.CPU] == 2000


class TestObjects:
    def test_pod_qos_from_label(self):
        pod = Pod(meta=ObjectMeta(labels={LABEL_POD_QOS: "BE"}))
        assert pod.qos_class is QoSClass.BE

    def test_reservation_owner_matching(self):
        res = Reservation(
            owners=[ReservationOwner(label_selector={"app": "web"})],
        )
        assert res.matches(Pod(meta=ObjectMeta(labels={"app": "web"})))
        assert not res.matches(Pod(meta=ObjectMeta(labels={"app": "db"})))

    def test_reservation_expiry(self):
        res = Reservation(meta=ObjectMeta(creation_timestamp=100.0), ttl_seconds=50)
        assert not res.is_expired(now=120.0)
        assert res.is_expired(now=151.0)


class TestQuantity:
    def test_parse_quantity(self):
        from koordinator_tpu.api.resources import parse_quantity

        assert parse_quantity("10Gi") == 10 * 1024**3
        assert parse_quantity("500m", cpu=True) == 500
        assert parse_quantity("2", cpu=True) == 2000
        assert parse_quantity("2k") == 2000
        assert parse_quantity("1.5Gi") == int(1.5 * 1024**3)
        assert parse_quantity(42) == 42

    def test_shared_weight_fallback(self):
        import json

        from koordinator_tpu.api.objects import (
            LABEL_QUOTA_SHARED_WEIGHT,
            ElasticQuota,
            ObjectMeta,
        )
        from koordinator_tpu.api.resources import ResourceList, ResourceName

        q = ElasticQuota(
            meta=ObjectMeta(name="q"), max=ResourceList.of(cpu=1000)
        )
        # absent annotation -> max
        assert q.shared_weight[ResourceName.CPU] == 1000
        # quantity strings parse
        q.meta.annotations[LABEL_QUOTA_SHARED_WEIGHT] = json.dumps(
            {"cpu": "2", "memory": "10Gi"}
        )
        assert q.shared_weight[ResourceName.CPU] == 2000
        assert q.shared_weight[ResourceName.MEMORY] == 10 * 1024**3
        # invalid -> max
        q.meta.annotations[LABEL_QUOTA_SHARED_WEIGHT] = "not-json"
        assert q.shared_weight[ResourceName.CPU] == 1000

    def test_reservation_owner_conjunction(self):
        from koordinator_tpu.api.objects import (
            ObjectMeta,
            Pod,
            Reservation,
            ReservationOwner,
        )

        res = Reservation(
            owners=[
                ReservationOwner(
                    label_selector={"app": "web"}, controller_kind="StatefulSet"
                )
            ]
        )
        labeled = Pod(meta=ObjectMeta(labels={"app": "web"}, owner_kind="Deployment"))
        assert not res.matches(labeled)  # selector AND controller must both match
        both = Pod(meta=ObjectMeta(labels={"app": "web"}, owner_kind="StatefulSet"))
        assert res.matches(both)
        # empty owner matches everything
        assert Reservation(owners=[ReservationOwner()]).matches(labeled)

    def test_histogram_checkpoint_mismatch_rejected(self):
        import pytest

        from koordinator_tpu.utils.histogram import (
            DecayingHistogram,
            HistogramOptions,
        )

        h = DecayingHistogram(HistogramOptions.linear(10.0, 1.0))
        h.add_sample(5.0, 2.0, 0.0)
        with pytest.raises(ValueError):
            DecayingHistogram.from_checkpoint(
                HistogramOptions.linear(5.0, 1.0), h.to_checkpoint()
            )


def test_patch_copy_isolates_mutable_containers():
    """patch_copy must not alias any container an admission mutator can
    rewrite in place — otherwise watch subscribers diff old==new."""
    from koordinator_tpu.api.objects import ObjectMeta, Pod, PodSpec
    from koordinator_tpu.api.resources import ResourceList, ResourceName

    pod = Pod(
        meta=ObjectMeta(name="p", labels={"a": "1"}, annotations={"x": "y"}),
        spec=PodSpec(requests=ResourceList.of(cpu=1000),
                     limits=ResourceList.of(cpu=1000),
                     node_selector={"zone": "east"},
                     tolerations=[("k", "v")]),
    )
    clone = pod.patch_copy()
    clone.meta.labels["a"] = "2"
    clone.meta.annotations["x"] = "z"
    del clone.spec.requests.quantities[ResourceName.CPU]
    clone.spec.requests.quantities["kubernetes.io/batch-cpu"] = 1000
    clone.spec.node_selector["zone"] = "west"
    clone.spec.tolerations.append(("k2", "v2"))
    assert pod.meta.labels["a"] == "1"
    assert pod.meta.annotations["x"] == "y"
    assert pod.spec.requests[ResourceName.CPU] == 1000
    assert pod.spec.node_selector["zone"] == "east"
    assert pod.spec.tolerations == [("k", "v")]
