"""Min-quota auto-scaling when sibling mins exceed the parent's resource
(ref core/scale_minquota_when_over_root_res.go + its test)."""

import numpy as np

from koordinator_tpu.api.objects import ElasticQuota, ObjectMeta
from koordinator_tpu.api.resources import NUM_RESOURCES, ResourceList
from koordinator_tpu.ops.quota import (
    build_quota_tree,
    compute_runtime_quotas,
    scaled_min_level,
)

CPU, MEM = 0, 1


def _quota(name, min_cpu, max_cpu, parent=None):
    from koordinator_tpu.api.objects import LABEL_QUOTA_PARENT

    labels = {LABEL_QUOTA_PARENT: parent} if parent else {}
    return ElasticQuota(
        meta=ObjectMeta(name=name, namespace="default", labels=labels),
        min=ResourceList.of(cpu=min_cpu),
        max=ResourceList.of(cpu=max_cpu, memory=2**40),
    )


def test_no_scaling_when_min_fits():
    """Sum(min) <= total: original mins are kept (getScaledMinQuota returns
    the original when no dimension needs scaling)."""
    quotas = [_quota("a", 50, 1000), _quota("b", 50, 1000)]
    tree = build_quota_tree(quotas)
    total = np.zeros(NUM_RESOURCES, np.float32)
    total[CPU] = 200.0
    parent = tree.parent
    lvl_total = np.broadcast_to(total, tree.min.shape).copy()
    scaled = scaled_min_level(
        lvl_total, parent, tree.min, np.ones(2, bool), tree.level, 0
    )
    np.testing.assert_array_equal(scaled, tree.min)


def test_proportional_scaling_when_over_total():
    """Sum(min)=300 > total=200: each enabled child's min scales by 200/300."""
    quotas = [_quota("a", 100, 1000), _quota("b", 200, 1000)]
    tree = build_quota_tree(quotas)
    total = np.zeros(NUM_RESOURCES, np.float32)
    total[CPU] = 200.0
    lvl_total = np.broadcast_to(total, tree.min.shape).copy()
    scaled = scaled_min_level(
        lvl_total, tree.parent, tree.min, np.ones(2, bool), tree.level, 0
    )
    assert scaled[0, CPU] == np.floor(200.0 * 100 / 300)  # 66
    assert scaled[1, CPU] == np.floor(200.0 * 200 / 300)  # 133


def test_zero_total_scales_to_zero():
    quotas = [_quota("a", 100, 1000)]
    tree = build_quota_tree(quotas)
    lvl_total = np.zeros(tree.min.shape, np.float32)
    scaled = scaled_min_level(
        lvl_total, tree.parent, tree.min, np.ones(1, bool), tree.level, 0
    )
    assert scaled[0, CPU] == 0.0


def test_disabled_children_keep_min():
    """disableScale children keep min; enabled ones share the remainder
    (the ensure-disableScale-first branch)."""
    quotas = [_quota("keep", 150, 1000), _quota("scale-a", 100, 1000),
              _quota("scale-b", 100, 1000)]
    tree = build_quota_tree(quotas)
    enable = np.array([False, True, True])
    total = np.zeros(NUM_RESOURCES, np.float32)
    total[CPU] = 250.0  # sum(min)=350 > 250; avail to scalers = 100
    lvl_total = np.broadcast_to(total, tree.min.shape).copy()
    scaled = scaled_min_level(
        lvl_total, tree.parent, tree.min, enable, tree.level, 0
    )
    assert scaled[0, CPU] == 150.0          # disabled: untouched
    assert scaled[1, CPU] == 50.0           # 100 * 100/200
    assert scaled[2, CPU] == 50.0


def test_runtime_quota_respects_scaled_min():
    """End-to-end: two roots with Sum(min) > cluster total get water-filled
    from the SCALED mins, so the runtime split follows the min ratio instead
    of overcommitting the root resource."""
    quotas = [_quota("a", 300, 2**30), _quota("b", 100, 2**30)]
    # both demand far beyond min
    req = {"default-a": None}
    tree = build_quota_tree(
        quotas,
        pod_requests_by_quota={
            "a": np.full(NUM_RESOURCES, 0, np.float32),
            "b": np.full(NUM_RESOURCES, 0, np.float32),
        },
    )
    tree.request[:, CPU] = [300.0, 100.0]
    total = np.zeros(NUM_RESOURCES, np.float32)
    total[CPU] = 200.0
    runtime = compute_runtime_quotas(tree, total)
    # scaled mins: floor(200*300/400)=150, floor(200*100/400)=50
    assert runtime[0, CPU] == 150.0
    assert runtime[1, CPU] == 50.0
    # without scaling the mins would overcommit: 300+100 > 200
    runtime_off = compute_runtime_quotas(tree, total, scale_min_enabled=False)
    assert runtime_off[:, CPU].sum() > 200.0


def test_nested_level_scaling_uses_parent_runtime():
    """A child level scales against its PARENT's runtime, not the cluster
    total (the update loop walks levels top-down)."""
    quotas = [
        _quota("root", 100, 100),
        _quota("kid-a", 80, 2**30, parent="root"),
        _quota("kid-b", 80, 2**30, parent="root"),
    ]
    tree = build_quota_tree(quotas)
    tree.request[:, CPU] = [100.0, 80.0, 80.0]
    total = np.zeros(NUM_RESOURCES, np.float32)
    total[CPU] = 1000.0
    runtime = compute_runtime_quotas(tree, total)
    assert runtime[0, CPU] == 100.0
    # kids' mins (80+80=160) scale to the root's runtime 100: floor(100*80/160)
    assert runtime[1, CPU] == 50.0
    assert runtime[2, CPU] == 50.0
