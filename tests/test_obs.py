"""koordtrace + histogram metrics: the observability layer's contracts.

Four layers:
  * Histogram — exposition validity (TYPE histogram, cumulative `_bucket`
    series ending in `le="+Inf"`, `_sum`/`_count` consistency, label
    escaping through the shared `_escape_label`);
  * Tracer — nesting, thread isolation, ring wraparound, JSONL schema;
  * instrumentation — one synthetic scheduling cycle produces the
    {cycle -> snapshot, encode, kernel, bind} span tree with nonzero
    monotonic durations, and the compile-cache counters distinguish the
    first compile from steady state;
  * surfaces — ObsServer/KoordletServer routing and the replay CLI's
    golden-fixture exit-code contract (mirrored by hack/lint.sh).
"""

import json
import re
import subprocess
import sys
import threading
from pathlib import Path

import pytest

from koordinator_tpu.api.objects import (
    Node,
    NodeMetric,
    NodeMetricInfo,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_POD,
    ObjectStore,
)
from koordinator_tpu.koordlet.metrics import Histogram, Registry
from koordinator_tpu.obs import TRACE_SCHEMA_VERSION, Tracer, validate_record
from koordinator_tpu.obs.server import ObsServer
from koordinator_tpu.scheduler import metrics as scheduler_metrics
from koordinator_tpu.scheduler.cycle import Scheduler

REPO_ROOT = Path(__file__).resolve().parent.parent
GOLDEN = REPO_ROOT / "tests" / "fixtures" / "trace_golden.jsonl"
GIB = 1024**3
NOW = 1_000_000.0


# ---------------------------------------------------------------------------
# histogram exposition
# ---------------------------------------------------------------------------

class TestHistogram:
    def test_exposition_shape(self):
        reg = Registry()
        h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        text = reg.expose()
        assert "# HELP lat_seconds latency" in text
        assert "# TYPE lat_seconds histogram" in text
        assert 'lat_seconds_bucket{le="0.1"} 1' in text
        assert 'lat_seconds_bucket{le="1"} 2' in text
        assert 'lat_seconds_bucket{le="+Inf"} 3' in text
        assert "lat_seconds_count 3" in text
        assert "lat_seconds_sum 5.55" in text

    def test_buckets_cumulative_and_consistent(self):
        reg = Registry()
        h = reg.histogram("h", buckets=(0.01, 0.1, 1.0, 10.0))
        for v in (0.005, 0.005, 0.05, 0.5, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        text = reg.expose()
        counts = [float(m.group(2)) for m in re.finditer(
            r'h_bucket\{le="([^"]+)"\} (\S+)', text)]
        # cumulative: each bucket includes everything below it
        assert counts == sorted(counts)
        assert counts == [2.0, 3.0, 6.0, 7.0, 8.0]
        # +Inf bucket == _count, and _sum matches the observations
        assert counts[-1] == h.count() == 8.0
        assert h.sum() == pytest.approx(56.56)
        # boundary semantics: le is inclusive (value == bound lands in it)
        h2 = Histogram("h2", buckets=(1.0,))
        h2.observe(1.0)
        _, cum, _, _ = h2.snapshot()
        assert cum == [1.0]

    def test_label_escaping_interplay(self):
        """Histogram series carry their labels through the same
        `_escape_label` path as every other kind — including on the
        synthesized `le` label lines."""
        reg = Registry()
        h = reg.histogram("h", buckets=(1.0,))
        h.observe(0.5, pod='a"b\\c\nd')
        text = reg.expose()
        escaped = 'pod="a\\"b\\\\c\\nd"'
        bucket_lines = [ln for ln in text.splitlines() if "_bucket" in ln]
        assert len(bucket_lines) == 2  # le="1" and le="+Inf"
        for line in bucket_lines:
            assert escaped in line and 'le="' in line
        assert f"h_sum{{{escaped}}} 0.5" in text
        assert f"h_count{{{escaped}}} 1" in text

    def test_per_labelset_series_are_independent(self):
        reg = Registry()
        h = reg.histogram("h", buckets=(1.0,))
        h.observe(0.5, node="a")
        h.observe(0.5, node="a")
        h.observe(2.0, node="b")
        assert h.count(node="a") == 2.0
        assert h.count(node="b") == 1.0
        assert h.count(node="nope") == 0.0

    def test_large_counts_expose_full_precision(self):
        """%g would round counters past ~1e6 to 6 significant digits,
        making small increments invisible between scrapes."""
        reg = Registry()
        c = reg.counter("big_total")
        c.inc(1_234_567)
        h = reg.histogram("h", buckets=(1.0,))
        for _ in range(3):
            h.observe(0.5)
        text = reg.expose()
        assert "big_total 1234567" in text
        assert "e+" not in text
        c.inc()
        assert "big_total 1234568" in reg.expose()

    def test_scalar_api_rebound_not_silent(self):
        """Histogram inherits the scalar _Metric surface; clear()/get()
        must act on the real series storage and set-style mutation must
        refuse loudly instead of writing to the unused scalar dict."""
        reg = Registry()
        h = reg.histogram("h", buckets=(1.0,))
        h.observe(0.5, node="a")
        assert h.get(node="a") == 1.0
        assert h.get(node="zzz") is None
        h.clear(node="a")
        assert h.get(node="a") is None
        assert "h_bucket" not in reg.expose()
        with pytest.raises(TypeError):
            h._set({}, 1.0)
        with pytest.raises(TypeError):
            h._add({}, 1.0)

    def test_non_finite_samples_do_not_poison_exposition(self):
        """One inf/NaN sample must degrade to Prometheus' non-finite
        spellings on its own line, not crash every future scrape."""
        reg = Registry()
        g = reg.gauge("ratio")
        g.set(float("inf"), node="a")
        g.set(float("-inf"), node="b")
        g.set(float("nan"), node="c")
        g.set(0.5, node="d")
        text = reg.expose()
        assert 'ratio{node="a"} +Inf' in text
        assert 'ratio{node="b"} -Inf' in text
        assert 'ratio{node="c"} NaN' in text
        assert 'ratio{node="d"} 0.5' in text

    def test_kind_conflict_rejected(self):
        reg = Registry()
        reg.histogram("h")
        with pytest.raises(ValueError):
            reg.counter("h")
        # same-kind, same-bucket re-registration returns the existing
        # instance; a DIFFERENT bucket spec must refuse rather than
        # silently hand back mismatched buckets
        assert reg.histogram("h") is reg.get("h")
        with pytest.raises(ValueError):
            reg.histogram("h", buckets=(30.0, 60.0, 300.0))
        # an explicit +Inf bound is stripped (the +Inf series is
        # synthesized); all-non-finite buckets refuse
        h2 = reg.histogram("h2", buckets=(1.0, float("inf")))
        h2.observe(0.5)
        assert reg.expose().count('h2_bucket{le="+Inf"}') == 1
        with pytest.raises(ValueError):
            reg.histogram("h3", buckets=(float("inf"),))


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------

class TestTracer:
    def test_nesting_and_ids(self):
        t = Tracer()
        with t.span("cycle") as root:
            with t.span("kernel", compiled="1") as k:
                pass
            with t.span("bind"):
                with t.span("reserve"):
                    pass
        roots = t.roots()
        assert [r.name for r in roots] == ["cycle"]
        r = roots[0]
        assert [c.name for c in r.children] == ["kernel", "bind"]
        assert r.children[1].children[0].name == "reserve"
        # ids: children share the root's trace id and link to their parent
        for span in r.walk():
            assert span.trace_id == r.span_id
            if span is not r:
                assert span.parent_id is not None
        assert k.attributes == {"compiled": "1"}
        assert root.find("reserve") is not None

    def test_durations_monotonic_nonzero(self):
        t = Tracer()
        with t.span("outer"):
            with t.span("inner"):
                sum(range(1000))
        root = t.roots()[0]
        assert root.duration_seconds > 0
        assert root.children[0].duration_seconds > 0
        # parent covers the child
        assert root.duration_seconds >= root.children[0].duration_seconds

    def test_ring_wraparound(self):
        t = Tracer(capacity=4)
        for i in range(10):
            with t.span(f"r{i}"):
                pass
        assert len(t) == 4
        assert [r.name for r in t.roots()] == ["r6", "r7", "r8", "r9"]
        assert t.seq == 10  # total committed survives the wraparound
        assert [r.name for r in t.roots(limit=2)] == ["r8", "r9"]

    def test_per_trace_span_budget(self):
        """A 10k-pod cycle must not pin 30k spans per retained root:
        per-item spans (depth >= 2) beyond the per-trace budget are timed
        but dropped, the root says how many went missing — and the
        depth-1 stage skeleton survives even after the budget burns."""
        t = Tracer(max_spans_per_trace=3)
        with t.span("root"):
            with t.span("prepass"):
                for i in range(10):
                    with t.span(f"item{i}") as sp:
                        pass
            # stage spans opened AFTER the budget is exhausted still land
            with t.span("kernel"):
                pass
        assert sp.duration_seconds > 0  # dropped spans still time
        root = t.roots()[0]
        assert [c.name for c in root.children] == ["prepass", "kernel"]
        # skeleton spans (root + depth-1) don't consume the budget: with
        # max=3, exactly 3 per-item spans are retained and 7 dropped
        assert [c.name for c in root.children[0].children] == [
            "item0", "item1", "item2"]
        assert root.attributes["dropped_spans"] == "7"
        # the budget resets per trace
        with t.span("root2"):
            with t.span("stage"):
                with t.span("kept"):
                    pass
        root2 = t.roots()[1]
        assert root2.find("kept") is not None
        assert "dropped_spans" not in root2.attributes

    def test_exception_marks_span_and_propagates(self):
        t = Tracer()
        with pytest.raises(ValueError):
            with t.span("cycle"):
                with t.span("kernel"):
                    raise ValueError("boom")
        root = t.roots()[0]
        assert root.attributes["error"] == "ValueError"
        assert root.children[0].attributes["error"] == "ValueError"
        # the tracer stack unwound: the next span is a fresh root
        with t.span("next"):
            pass
        assert [r.name for r in t.roots()] == ["cycle", "next"]

    def test_thread_isolation(self):
        """Each thread traces its own tree; concurrent spans never nest
        across threads and every root lands in the shared ring."""
        t = Tracer()
        barrier = threading.Barrier(4)

        def work(i):
            with t.span(f"thread-{i}"):
                barrier.wait(timeout=10)  # all spans open simultaneously
                with t.span("child"):
                    pass

        threads = [threading.Thread(target=work, args=(i,)) for i in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        roots = t.roots()
        assert sorted(r.name for r in roots) == [
            f"thread-{i}" for i in range(4)]
        for r in roots:
            assert [c.name for c in r.children] == ["child"]

    def test_export_jsonl_schema(self):
        t = Tracer()
        with t.span("cycle", mode="test"):
            with t.span("kernel"):
                pass
        lines = t.export_jsonl().strip().splitlines()
        assert len(lines) == 2
        for line in lines:
            rec = json.loads(line)
            assert validate_record(rec) == []
            assert rec["v"] == TRACE_SCHEMA_VERSION
        assert t.export_jsonl(limit=5) == t.export_jsonl()

    def test_validate_record_rejects_drift(self):
        good = json.loads(
            '{"v": 1, "trace": 1, "span": 1, "parent": null, "name": "x", '
            '"start_unix": 1.0, "start_mono": 1.0, "duration_ms": 1.0, '
            '"attrs": {}}')
        assert validate_record(good) == []
        for mutation in (
            {"v": 99},
            {"name": ""},
            {"duration_ms": "fast"},
            {"parent": "root"},
            {"parent": True},
            {"trace": True},
            {"attrs": {"k": 1}},
            {"start_mono": -1.0},
        ):
            assert validate_record({**good, **mutation}), mutation
        assert validate_record([1, 2, 3])


# ---------------------------------------------------------------------------
# cycle instrumentation
# ---------------------------------------------------------------------------

def make_store(num_nodes=3):
    store = ObjectStore()
    for i in range(num_nodes):
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name=f"node-{i}", namespace=""),
            allocatable=ResourceList.of(
                cpu=16_000, memory=64 * GIB, pods=110)))
        store.add(KIND_NODE_METRIC, NodeMetric(
            meta=ObjectMeta(name=f"node-{i}", namespace=""),
            update_time=NOW - 10,
            node_metric=NodeMetricInfo(
                node_usage=ResourceList.of(cpu=1000, memory=2 * GIB))))
    return store


def pend_pod(store, name):
    pod = Pod(
        meta=ObjectMeta(name=name, creation_timestamp=NOW),
        spec=PodSpec(priority=9500,
                     requests=ResourceList.of(cpu=1000, memory=GIB)),
    )
    store.add(KIND_POD, pod)
    return pod


def _counter(metric):
    return metric.get() or 0.0


class TestCycleInstrumentation:
    def test_span_tree_and_compile_cache(self):
        store = make_store()
        sched = Scheduler(store)
        for i in range(4):
            pend_pod(store, f"p{i}")

        hits0 = _counter(scheduler_metrics.COMPILE_CACHE_HITS)
        misses0 = _counter(scheduler_metrics.COMPILE_CACHE_MISSES)
        cycles0 = scheduler_metrics.CYCLE_SECONDS.count()
        result = sched.run_cycle(now=NOW)
        assert len(result.bound) == 4

        # --- the acceptance span tree: cycle -> snapshot/encode/kernel/bind
        root = sched.tracer.roots()[-1]
        assert root.name == "cycle"
        child_names = {c.name for c in root.children}
        assert {"snapshot", "encode", "kernel", "bind"} <= child_names
        for name in ("cycle", "snapshot", "encode", "kernel", "bind"):
            span = root.find(name)
            assert span.duration_seconds > 0, name
        # first cycle is a cold compile: the step cache missed, the kernel
        # span says so, and a `compile` span recorded the build
        assert root.find("kernel").attributes["compiled"] == "1"
        assert root.find("compile") is not None
        assert _counter(scheduler_metrics.COMPILE_CACHE_MISSES) == misses0 + 1
        assert _counter(scheduler_metrics.COMPILE_CACHE_HITS) == hits0
        # per-binding spans under bind
        bind_pods = root.find_all("bind_pod")
        assert len(bind_pods) == 4
        for bp in bind_pods:
            assert {"reserve", "prebind"} == {c.name for c in bp.children}
            assert bp.attributes["node"].startswith("node-")
        # duration consolidated through the root span, and the latency
        # histogram observed the cycle
        assert result.duration_seconds == root.duration_seconds > 0
        assert scheduler_metrics.CYCLE_SECONDS.count() == cycles0 + 1
        assert scheduler_metrics.KERNEL_SECONDS.count() >= 1

        # --- steady state: same shape signature -> cache hit, no recompile
        for i in range(4):
            pend_pod(store, f"q{i}")
        result2 = sched.run_cycle(now=NOW + 1)
        assert len(result2.bound) == 4
        assert _counter(scheduler_metrics.COMPILE_CACHE_MISSES) == misses0 + 1
        assert _counter(scheduler_metrics.COMPILE_CACHE_HITS) > hits0
        root2 = sched.tracer.roots()[-1]
        assert root2.find("kernel").attributes["compiled"] == "0"
        assert root2.find("compile") is None

    def test_empty_cycle_still_stamps_duration(self):
        """The old three-site duration assignment shipped 0.0 whenever a
        return path forgot the stamp; the root span makes that
        structurally impossible — even a no-pending cycle reports how
        long the queue scan took."""
        sched = Scheduler(make_store(num_nodes=1))
        result = sched.run_cycle(now=NOW)
        assert result.bound == []
        assert result.duration_seconds > 0
        root = sched.tracer.roots()[-1]
        assert result.duration_seconds == root.duration_seconds

    def test_traces_jsonl_round_trips_through_validator(self):
        sched = Scheduler(make_store())
        pend_pod(sched.store, "p0")
        sched.run_cycle(now=NOW)
        for line in sched.tracer.export_jsonl().strip().splitlines():
            assert validate_record(json.loads(line)) == []


# ---------------------------------------------------------------------------
# component metrics
# ---------------------------------------------------------------------------

def test_descheduler_cycle_metrics():
    from koordinator_tpu.descheduler import metrics as dmetrics
    from koordinator_tpu.descheduler.descheduler import Descheduler

    before = dmetrics.CYCLE_SECONDS.count()
    Descheduler(make_store()).run_once(now=NOW)
    assert dmetrics.CYCLE_SECONDS.count() == before + 1
    # standby replicas observe nothing
    class _Standby:
        def tick(self, now):
            return False

    Descheduler(make_store(), elector=_Standby()).run_once(now=NOW)
    assert dmetrics.CYCLE_SECONDS.count() == before + 1


def test_registries_expose_histograms():
    from koordinator_tpu.descheduler import metrics as dmetrics
    from koordinator_tpu.koordlet import metrics as kmetrics

    for registry, name in (
        (scheduler_metrics.REGISTRY, "koord_scheduler_cycle_seconds"),
        (dmetrics.REGISTRY, "koord_descheduler_cycle_seconds"),
        (kmetrics.REGISTRY, "koordlet_qosmanager_cycle_seconds"),
    ):
        assert f"# TYPE {name} histogram" in registry.expose()


# ---------------------------------------------------------------------------
# HTTP surfaces
# ---------------------------------------------------------------------------

class TestObsServer:
    def _tracer(self):
        t = Tracer()
        with t.span("cycle"):
            with t.span("kernel"):
                pass
        return t

    def test_routes(self):
        reg = Registry()
        reg.histogram("x_seconds").observe(0.5)
        srv = ObsServer(reg, self._tracer())
        status, ctype, body = srv.handle("/metrics")
        assert status == 200 and "version=0.0.4" in ctype
        assert 'x_seconds_bucket{le="+Inf"} 1' in body
        status, ctype, body = srv.handle("/traces")
        assert status == 200
        lines = body.strip().splitlines()
        assert len(lines) == 2
        assert all(validate_record(json.loads(ln)) == [] for ln in lines)
        assert srv.handle("/healthz")[0] == 200
        assert srv.handle("/nope")[0] == 404
        assert srv.handle("/traces", {"limit": "x"})[0] == 400

    def test_traces_limit(self):
        t = Tracer()
        for i in range(3):
            with t.span(f"c{i}"):
                pass
        srv = ObsServer(tracer=t)
        _, _, body = srv.handle("/traces", {"limit": "1"})
        assert [json.loads(ln)["name"]
                for ln in body.strip().splitlines()] == ["c2"]
        # explicit limit=0 means zero roots, not "unset"
        assert srv.handle("/traces", {"limit": "0"})[2] == ""
        assert len(srv.handle("/traces")[2].strip().splitlines()) == 3

    def test_disabled_surfaces_404(self):
        srv = ObsServer()  # neither registry nor tracer
        assert srv.handle("/metrics")[0] == 404
        assert srv.handle("/traces")[0] == 404

    def test_koordlet_server_exposes_traces(self):
        from koordinator_tpu.koordlet.audit import Auditor
        from koordinator_tpu.koordlet.server import KoordletServer

        reg = Registry()
        reg.counter("c_total").inc()
        srv = KoordletServer(Auditor(), metrics_registry=reg,
                             tracer=self._tracer())
        assert "c_total 1" in srv.handle("/metrics")[2]
        status, _, body = srv.handle("/traces")
        assert status == 200 and '"name": "cycle"' in body
        # without a tracer the route stays dark (pre-existing behavior)
        assert KoordletServer(Auditor()).handle("/traces")[0] == 404

    def test_live_server(self):
        import urllib.request

        srv = ObsServer(Registry(), self._tracer())
        server, _thread = srv.serve(port=0)
        try:
            port = server.server_address[1]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/traces") as resp:
                assert resp.status == 200
                assert b'"name": "cycle"' in resp.read()
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
# replay CLI (the hack/lint.sh golden-fixture contract)
# ---------------------------------------------------------------------------

def _run_cli(*args, stdin=None):
    return subprocess.run(
        [sys.executable, "-m", "koordinator_tpu.obs", *args],
        cwd=REPO_ROOT, capture_output=True, text=True, input=stdin,
        timeout=120)


class TestReplayCLI:
    def test_golden_fixture_renders(self):
        proc = _run_cli(str(GOLDEN))
        assert proc.returncode == 0, proc.stderr
        assert "cycle" in proc.stdout and "█" in proc.stdout
        # nesting is visible: bind_pod indents under bind
        assert re.search(r"^\s+bind\b", proc.stdout, re.M)
        assert re.search(r"^\s+bind_pod\b", proc.stdout, re.M)

    def test_stdin_input(self):
        proc = _run_cli("-", stdin=GOLDEN.read_text())
        assert proc.returncode == 0, proc.stderr

    def test_schema_drift_fails(self, tmp_path):
        lines = GOLDEN.read_text().strip().splitlines()
        rec = json.loads(lines[0])
        del rec["duration_ms"]
        bad = tmp_path / "drift.jsonl"
        bad.write_text("\n".join([json.dumps(rec)] + lines[1:]) + "\n")
        proc = _run_cli(str(bad))
        assert proc.returncode == 1
        assert "duration_ms" in proc.stderr

    def test_dangling_parent_fails(self, tmp_path):
        rec = json.loads(GOLDEN.read_text().splitlines()[1])
        rec["parent"] = 9999
        orphan = tmp_path / "orphan.jsonl"
        orphan.write_text(
            GOLDEN.read_text().splitlines()[0] + "\n" + json.dumps(rec) + "\n")
        proc = _run_cli(str(orphan))
        assert proc.returncode == 1
        assert "dangling parent" in proc.stderr

    def test_missing_file_is_usage_error(self):
        assert _run_cli("no/such/trace.jsonl").returncode == 2
