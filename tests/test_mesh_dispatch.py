"""Mesh-backed production dispatch (KOORD_TPU_MESH): the scheduling cycle
sharded over the device mesh must be byte-identical to the single-device
path, the sharding helpers must absorb non-divisible axis sizes, and the
mesh path must be observable (devices/shard gauges, shard spans).

The heavyweight matrix (1/2/4/8 devices x serial/fused x explain) runs in
hack/lint.sh via scheduler/pipeline_parity.run_mesh_parity; tier-1 pins a
representative slice plus the unit seams (DeviceSnapshot sharded upload/
scatter, put_on_mesh padding + multi-host branch, metrics)."""

import numpy as np
import pytest

from koordinator_tpu.scheduler import metrics as scheduler_metrics
from koordinator_tpu.scheduler.pipeline_parity import run_mesh_parity


# ---------------------------------------------------------------------------
# production-path parity (the tentpole gate, tier-1 slice)
# ---------------------------------------------------------------------------

def test_mesh_parity_serial_2dev(cpu_devices):
    rep = run_mesh_parity(2)
    assert rep["ok"], rep["mismatches"]
    assert rep["conditions_checked"] > 0


def test_mesh_parity_fused_8dev(cpu_devices):
    rep = run_mesh_parity(8, waves=4)
    assert rep["ok"], rep["mismatches"]


def test_mesh_parity_explain_counts(cpu_devices):
    rep = run_mesh_parity(4, explain="counts")
    assert rep["ok"], rep["mismatches"]


def test_mesh_parity_non_divisible_mesh(cpu_devices):
    """3 devices never divide the pow2/256-granule node buckets, so every
    upload exercises pad_for_sharding inside put_on_mesh — the production
    regression for the non-divisible-axis satellite."""
    rep = run_mesh_parity(3)
    assert rep["ok"], rep["mismatches"]


# ---------------------------------------------------------------------------
# DeviceSnapshot: sharded upload + shard-aware scatter
# ---------------------------------------------------------------------------

def _mesh_of(devs, n):
    from koordinator_tpu.parallel import make_mesh

    return make_mesh(devs[:n])


def test_device_snapshot_mesh_upload_shards_node_axis(cpu_devices):
    from koordinator_tpu.scheduler.snapshot_cache import DeviceSnapshot

    mesh = _mesh_of(cpu_devices, 8)
    ds = DeviceSnapshot(mesh=mesh)
    node_arr = np.arange(64 * 3, dtype=np.float32).reshape(64, 3)
    pod_arr = np.ones((16, 3), np.float32)
    dev_node = ds._one("allocatable", node_arr)
    dev_pod = ds._one("fit_requests", pod_arr)
    # node-axis field sharded over all devices; pod field replicated
    assert len({sh.device.id for sh in dev_node.addressable_shards}) == 8
    assert dev_node.addressable_shards[0].data.shape[0] == 8  # 64 / 8
    assert np.asarray(dev_pod).shape == pod_arr.shape
    for sh in dev_pod.addressable_shards:
        assert sh.data.shape == pod_arr.shape  # replicated: full copy


def test_device_snapshot_mesh_pads_non_divisible(cpu_devices):
    from koordinator_tpu.scheduler.snapshot_cache import DeviceSnapshot

    mesh = _mesh_of(cpu_devices, 8)
    ds = DeviceSnapshot(mesh=mesh)
    node_arr = np.random.default_rng(0).random((30, 3)).astype(np.float32)
    dev = ds._one("allocatable", node_arr)
    assert dev.shape == (32, 3)  # padded to the mesh factor
    host = np.asarray(dev)
    np.testing.assert_array_equal(host[:30], node_arr)
    assert not host[30:].any()  # zero pad rows
    # unchanged re-upload reuses the buffer (pad rows never look dirty)
    before = dict(ds.stats)
    dev2 = ds._one("allocatable", node_arr)
    assert dev2 is dev
    assert ds.stats["reused"] == before["reused"] + 1


def test_device_snapshot_mesh_scatter_keeps_sharding(cpu_devices):
    from koordinator_tpu.scheduler.snapshot_cache import DeviceSnapshot

    mesh = _mesh_of(cpu_devices, 8)
    ds = DeviceSnapshot(mesh=mesh)
    rng = np.random.default_rng(1)
    node_arr = rng.random((64, 4)).astype(np.float32)
    dev = ds._one("requested", node_arr)
    sharding = dev.sharding
    # dirty two rows on different shards -> scatter path, sharding kept
    node_arr2 = node_arr.copy()
    node_arr2[3] += 1.0
    node_arr2[60] += 2.0
    dev2 = ds._one("requested", node_arr2)
    assert ds.stats["scattered"] == 1
    assert dev2.sharding == sharding
    np.testing.assert_array_equal(np.asarray(dev2), node_arr2)


def test_device_snapshot_mesh_scatter_respects_dispatch_guard(cpu_devices):
    from koordinator_tpu.scheduler.snapshot_cache import DeviceSnapshot

    mesh = _mesh_of(cpu_devices, 2)
    ds = DeviceSnapshot(mesh=mesh)
    node_arr = np.zeros((64, 4), np.float32)
    ds._one("requested", node_arr)
    ds.begin_dispatch()
    try:
        node_arr2 = node_arr.copy()
        node_arr2[5] = 1.0
        ds._one("requested", node_arr2)
    finally:
        ds.end_dispatch()
    assert ds.stats["scattered_safe"] == 1  # non-donating double-buffer


# ---------------------------------------------------------------------------
# mesh observability
# ---------------------------------------------------------------------------

def _mesh_world(num_nodes=16, num_pods=40, ndev=4, **kw):
    from koordinator_tpu.scheduler.cycle import Scheduler
    from koordinator_tpu.scheduler.pipeline_parity import (
        build_store_from_state,
    )
    from koordinator_tpu.testing import synth_full_cluster

    _cluster, state = synth_full_cluster(
        num_nodes, num_pods, seed=5, num_quotas=2, num_gangs=2)
    store = build_store_from_state(state)
    return Scheduler(store, mesh=ndev, **kw), state


def test_mesh_cycle_emits_shard_spans_and_gauges(cpu_devices):
    sched, state = _mesh_world(ndev=4, waves=1)
    assert scheduler_metrics.MESH_DEVICES.get() == 4.0
    res = sched.run_cycle(now=state.now)
    assert res.bound  # the fixture must actually schedule
    root = sched.tracer.roots(limit=1)[0]
    kernel = root.find("kernel")
    shards = [s for s in kernel.children if s.name == "shard"]
    assert len(shards) == 4
    assert [s.attributes["index"] for s in shards] == ["0", "1", "2", "3"]
    total_rows = sum(int(s.attributes["rows"]) for s in shards)
    assert total_rows == 16  # real rows split across shards
    imb = scheduler_metrics.MESH_SHARD_IMBALANCE.get()
    assert imb is not None and imb >= 1.0
    assert any(
        scheduler_metrics.MESH_SHARD_READBACK_BYTES.get(shard=str(i))
        for i in range(4))


def test_mesh_off_reports_zero_devices():
    from koordinator_tpu.client.store import ObjectStore
    from koordinator_tpu.scheduler.cycle import Scheduler

    Scheduler(ObjectStore(), mesh="off")
    assert scheduler_metrics.MESH_DEVICES.get() == 0.0


def test_mesh_from_env_parsing(cpu_devices, monkeypatch):
    from koordinator_tpu.parallel import mesh_from_env

    assert mesh_from_env(env_value="off") is None
    assert mesh_from_env(env_value="0") is None
    assert mesh_from_env(env_value="auto").devices.size == 8
    assert mesh_from_env(env_value=4).devices.size == 4
    assert mesh_from_env(env_value="1").devices.size == 1
    assert mesh_from_env(env_value="bogus") is None  # warn, stay off
    with pytest.raises(ValueError):
        mesh_from_env(env_value=99)
    monkeypatch.setenv("KOORD_TPU_MESH", "2")
    assert mesh_from_env().devices.size == 2


def test_mesh_demoted_with_sidecar(cpu_devices):
    from koordinator_tpu.client.store import ObjectStore
    from koordinator_tpu.scheduler.cycle import Scheduler

    sched = Scheduler(ObjectStore(), mesh=2,
                      sidecar_address="localhost:1")
    assert sched.mesh is None  # the sidecar protocol is single-device


# ---------------------------------------------------------------------------
# batched per-dispatch condition writes (fused replay satellite)
# ---------------------------------------------------------------------------

def test_fused_dispatch_single_condition_flush(cpu_devices):
    """A non-pipelined fused dispatch must drain ALL its logical cycles'
    PodScheduled writes in one flush after the wave replay — no condition
    write may interleave with a later wave's bind writes."""
    from koordinator_tpu.client.store import KIND_POD
    from koordinator_tpu.scheduler.cycle import Scheduler
    from koordinator_tpu.scheduler.pipeline_parity import (
        build_store_from_state,
    )
    from koordinator_tpu.testing import synth_full_cluster

    _cluster, state = synth_full_cluster(24, 70, seed=11, num_quotas=3,
                                         num_gangs=4, topology_fraction=0.5,
                                         lsr_fraction=0.2)
    store = build_store_from_state(state)
    events = []

    def on_pod(ev, pod, old):
        cond = pod.get_condition("PodScheduled")
        if cond is not None and cond.status == "False":
            events.append(("cond", pod.meta.key))
        elif pod.is_assigned and (old is None or not old.is_assigned):
            events.append(("bind", pod.meta.key))

    store.subscribe(KIND_POD, on_pod)
    sched = Scheduler(store, waves=4, mesh="off")
    assert sched.pipeline_mode is False
    res = sched.run_cycle(now=state.now)
    assert res.waves >= 1
    conds = [i for i, e in enumerate(events) if e[0] == "cond"]
    binds = [i for i, e in enumerate(events) if e[0] == "bind"]
    assert conds, "fixture produced no unschedulable pods"
    assert binds, "fixture produced no bindings"
    # one flush per dispatch: every condition write lands after the last
    # bind of the whole dispatch, not interleaved per wave
    assert min(conds) > max(binds)
    assert not sched._deferred_diagnose  # drained, not leaked
    assert sched._defer_condition_writes is False


# ---------------------------------------------------------------------------
# sharding helpers: padding + multi-host branch + dtype preservation
# ---------------------------------------------------------------------------

def test_put_on_mesh_pads_1023_node_snapshot(cpu_devices):
    """1023 nodes on 8 devices: the helpers pad to the mesh factor
    internally; bindings must match the single-device step bit-for-bit."""
    from koordinator_tpu.models.scheduler_model import (
        build_schedule_step,
        make_inputs,
    )
    from koordinator_tpu.ops.loadaware import (
        LoadAwareArgs,
        build_loadaware_node_state,
    )
    from koordinator_tpu.ops.packing import pack_nodes, pack_pods
    from koordinator_tpu.parallel import (
        build_sharded_schedule_step,
        make_mesh,
        shard_inputs_nodewise,
    )
    from koordinator_tpu.testing import synth_cluster

    args = LoadAwareArgs()
    cluster = synth_cluster(num_nodes=1023, num_pods=64, seed=2)
    pods = pack_pods(cluster.pods, args.resource_weights,
                     args.estimated_scaling_factors)
    nodes = pack_nodes(cluster.nodes, pad_to=1023)  # forced odd axis
    nodes.extras = build_loadaware_node_state(
        cluster.nodes, cluster.node_metrics, cluster.pods_by_key,
        cluster.assigned, args, cluster.now, pad_to=1023)
    inputs = make_inputs(pods, nodes, args)
    assert inputs.allocatable.shape[0] == 1023

    chosen_1, _ = build_schedule_step(args)(inputs)
    mesh = make_mesh(cpu_devices)
    sharded = shard_inputs_nodewise(inputs, mesh)
    assert sharded.allocatable.shape[0] == 1024  # padded inside the helper
    assert sharded.pod_valid.shape == inputs.pod_valid.shape  # replicated
    chosen_8, _ = build_sharded_schedule_step(args, mesh)(sharded)
    np.testing.assert_array_equal(np.asarray(chosen_1),
                                  np.asarray(chosen_8))
    assert (np.asarray(chosen_1)[: pods.num_valid] >= 0).sum() > 0


def test_shard_inputs_2d_pads_both_axes(cpu_devices):
    from jax.sharding import Mesh

    from koordinator_tpu.parallel import make_mesh, shard_inputs_2d
    from koordinator_tpu.models.scheduler_model import make_inputs
    from koordinator_tpu.ops.loadaware import (
        LoadAwareArgs,
        build_loadaware_node_state,
    )
    from koordinator_tpu.ops.packing import pack_nodes, pack_pods
    from koordinator_tpu.testing import synth_cluster

    args = LoadAwareArgs()
    cluster = synth_cluster(num_nodes=29, num_pods=17, seed=4)
    pods = pack_pods(cluster.pods, args.resource_weights,
                     args.estimated_scaling_factors, pad_to=17)
    nodes = pack_nodes(cluster.nodes, pad_to=29)
    nodes.extras = build_loadaware_node_state(
        cluster.nodes, cluster.node_metrics, cluster.pods_by_key,
        cluster.assigned, args, cluster.now, pad_to=29)
    inputs = make_inputs(pods, nodes, args)
    mesh = make_mesh(cpu_devices)  # 2 x 4: pods x 2, nodes x 4
    assert isinstance(mesh, Mesh)
    sharded = shard_inputs_2d(inputs, mesh)
    assert sharded.fit_requests.shape[0] % 2 == 0   # pods axis padded
    assert sharded.allocatable.shape[0] % 4 == 0    # nodes axis padded
    assert sharded.weights.shape == inputs.weights.shape  # replicated


def test_shard_inputs_preserve_dtypes(cpu_devices):
    """Every field of shard_inputs_nodewise / shard_inputs_2d /
    shard_full_chain_inputs keeps its host dtype — an implicit upcast
    would silently change kernel numerics on the mesh only."""
    from koordinator_tpu.ops.loadaware import LoadAwareArgs
    from koordinator_tpu.parallel import (
        make_mesh,
        shard_full_chain_inputs,
        shard_inputs_2d,
        shard_inputs_nodewise,
    )
    from koordinator_tpu.scheduler.snapshot import build_full_chain_inputs
    from koordinator_tpu.testing import synth_full_cluster

    args = LoadAwareArgs()
    _cluster, state = synth_full_cluster(12, 24, seed=6)
    fc, *_rest = build_full_chain_inputs(state, args)
    mesh = make_mesh(cpu_devices)
    for sharder, val in (
        (shard_inputs_nodewise, fc.base),
        (shard_inputs_2d, fc.base),
        (shard_full_chain_inputs, fc),
    ):
        out = sharder(val, mesh)
        for name in type(val)._fields:
            host = getattr(val, name)
            dev = getattr(out, name)
            if name == "base":
                continue  # covered by the nodewise pass above
            assert np.asarray(dev).dtype == np.asarray(host).dtype, (
                sharder.__name__, name)


def test_put_on_mesh_multihost_branch(cpu_devices):
    """The make_array_from_callback path (taken when the mesh spans
    processes): a fake non-fully-addressable sharding must still produce
    an array whose shard-local slices match the host array exactly."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from koordinator_tpu.parallel import make_mesh, put_on_mesh

    class FakeMultiHost(NamedSharding):
        """Claims not-fully-addressable, forcing the callback path."""

        @property
        def is_fully_addressable(self):
            return False

    mesh = make_mesh(cpu_devices)
    sharding = FakeMultiHost(mesh, P(("pods", "nodes")))
    rng = np.random.default_rng(7)
    for dtype in (np.float32, np.int32, bool):
        host = (rng.random((42, 3)) * 10).astype(dtype)  # 42 -> pad 48
        arr = put_on_mesh(host, sharding)
        assert arr.shape == (48, 3)
        assert arr.dtype == host.dtype
        padded = np.zeros((48, 3), dtype)
        padded[:42] = host
        for sh in arr.addressable_shards:
            np.testing.assert_array_equal(np.asarray(sh.data),
                                          padded[sh.index])


def test_pad_for_sharding_noop_when_divisible(cpu_devices):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from koordinator_tpu.parallel import make_mesh, pad_for_sharding

    mesh = make_mesh(cpu_devices)
    sharding = NamedSharding(mesh, P(("pods", "nodes")))
    arr = np.ones((64, 3), np.float32)
    out = pad_for_sharding(arr, sharding)
    assert out is arr  # divisible: pass-through, no copy
    rep = NamedSharding(mesh, P())
    odd = np.ones((7, 3), np.float32)
    assert pad_for_sharding(odd, rep) is odd  # replicated: never padded


def test_mesh_row_layout_imbalance(cpu_devices):
    from koordinator_tpu.parallel import make_mesh, mesh_row_layout

    mesh = make_mesh(cpu_devices)
    rows = mesh_row_layout(mesh, n_real=30, n_padded=32)
    assert rows == [4, 4, 4, 4, 4, 4, 4, 2]
    assert sum(rows) == 30
