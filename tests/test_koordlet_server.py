"""Metriccache persistence (tsdb_storage.go analog) and the koordlet API
server's token-paged audit endpoint (auditor.go:130-246)."""

import json
import urllib.request

from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.metriccache import MetricCache
from koordinator_tpu.koordlet.server import KoordletServer

NOW = 1_000_000.0


class TestMetricCachePersistence:
    def test_restart_keeps_aggregation_window(self, tmp_path):
        path = str(tmp_path / "metriccache.pkl")
        cache = MetricCache(storage_path=path)
        for i in range(10):
            cache.add_sample(mc.NODE_CPU_USAGE, 4.0 + i * 0.1,
                            timestamp=NOW - 100 + i * 10)
        cache.set_kv(mc.NODE_CPU_INFO_KEY, {"cores": 16})
        cache.flush(now=NOW)

        # simulated agent restart
        cache2 = MetricCache(storage_path=path)
        p95 = cache2.query(mc.NODE_CPU_USAGE, "p95", window=300, now=NOW)
        p95_orig = cache.query(mc.NODE_CPU_USAGE, "p95", window=300, now=NOW)
        assert p95 == p95_orig
        assert cache2.get_kv(mc.NODE_CPU_INFO_KEY) == {"cores": 16}

    def test_restore_drops_expired_samples(self, tmp_path):
        """Restore-time pruning: flush with a LARGE retention (both samples
        survive in the snapshot), restore with a SMALL one — the restore
        cutoff (newest sample - retention) must drop the old point."""
        path = str(tmp_path / "metriccache.pkl")
        cache = MetricCache(storage_path=path, retention_seconds=10_000)
        cache.add_sample(mc.NODE_CPU_USAGE, 1.0, timestamp=NOW - 3000)
        cache.add_sample(mc.NODE_CPU_USAGE, 2.0, timestamp=NOW)
        cache.flush(now=NOW)
        assert cache._values(mc.NODE_CPU_USAGE, None, None) == [1.0, 2.0]
        cache2 = MetricCache(storage_path=path, retention_seconds=60)
        vals = cache2._values(mc.NODE_CPU_USAGE, None, None)
        assert vals == [2.0]

    def test_flush_failure_never_raises(self, tmp_path):
        """Disk trouble degrades to a skipped snapshot, not an agent crash."""
        blocker = tmp_path / "blocker"
        blocker.write_text("a file where a directory is needed")
        cache = MetricCache(storage_path=str(blocker / "m.pkl"))
        cache.add_sample(mc.NODE_CPU_USAGE, 1.0, timestamp=NOW)
        assert cache.flush(now=NOW) is False

    def test_negative_size_clamped(self):
        auditor = Auditor()
        for i in range(5):
            auditor.record("info", "node", "w")
        server = KoordletServer(auditor)
        status, _, body = server.handle("/apis/v1/audit", {"size": "-1"})
        assert status == 200
        assert json.loads(body)["events"] == []

    def test_corrupt_snapshot_ignored(self, tmp_path):
        path = str(tmp_path / "metriccache.pkl")
        with open(path, "wb") as f:
            f.write(b"not a pickle")
        cache = MetricCache(storage_path=path)  # must not raise
        assert cache.query(mc.NODE_CPU_USAGE) is None

    def test_maybe_flush_interval(self, tmp_path):
        path = str(tmp_path / "metriccache.pkl")
        cache = MetricCache(storage_path=path, flush_interval_seconds=60)
        cache.add_sample(mc.NODE_CPU_USAGE, 1.0, timestamp=NOW)
        assert cache.maybe_flush(now=NOW) is True
        assert cache.maybe_flush(now=NOW + 10) is False
        assert cache.maybe_flush(now=NOW + 61) is True


class TestAuditEndpoint:
    def _server(self):
        auditor = Auditor()
        for i in range(5):
            auditor.record("info", "node", "cgroup_write",
                           file=f"/sys/fs/cgroup/f{i}", value=str(i))
        return KoordletServer(auditor), auditor

    def test_token_paging(self):
        server, _ = self._server()
        status, ctype, body = server.handle("/apis/v1/audit", {"size": "2"})
        assert status == 200 and ctype == "application/json"
        page1 = json.loads(body)
        assert [e["seq"] for e in page1["events"]] == [1, 2]
        token = page1["next_token"]
        _, _, body2 = server.handle(
            "/apis/v1/audit", {"token": str(token), "size": "2"})
        page2 = json.loads(body2)
        assert [e["seq"] for e in page2["events"]] == [3, 4]
        # exhausted page returns same token so pollers can resume
        _, _, body3 = server.handle(
            "/apis/v1/audit", {"token": "5", "size": "2"})
        page3 = json.loads(body3)
        assert page3["events"] == [] and page3["next_token"] == 5

    def test_bad_params(self):
        server, _ = self._server()
        status, _, _ = server.handle("/apis/v1/audit", {"token": "x"})
        assert status == 400

    def test_unknown_path_404(self):
        server, _ = self._server()
        status, _, _ = server.handle("/apis/v1/nothing", {})
        assert status == 404

    def test_live_http_roundtrip(self):
        """Real socket: curl-able audit page."""
        server, auditor = self._server()
        httpd, thread = server.serve(port=0)
        try:
            port = httpd.server_address[1]
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/apis/v1/audit?size=3", timeout=5
            ) as resp:
                page = json.loads(resp.read())
            assert len(page["events"]) == 3
            assert page["events"][0]["operation"] == "cgroup_write"
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5
            ) as resp:
                assert resp.read() == b"ok"
        finally:
            httpd.shutdown()
