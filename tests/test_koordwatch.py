"""koordwatch (PR 13): demotion accounting, device timeline, SLO engine,
decision correlation — the observability layer's acceptance contracts.

Five layers:
  * demotion accounting — every silent demotion branch routes through
    the chokepoint: structured reasons on CycleResult.demotions, the
    wave_demotions counter, the flight record, and zero unattributed
    demotions in the sim's per-scenario profile;
  * device timeline — dispatch windows from all three consumers land in
    one lock-guarded ring with outcomes, the JSONL bundle validates, and
    the /debug/timeline surface serves it under concurrent scrape load;
  * SLO engine — SloRegistry math, gauges, the /debug/slo bundle, and
    the sim report's SLO JSON pinned field-for-field against the legacy
    expressions it re-expressed;
  * decision correlation — ids join kernel spans, flight records,
    /explain output and the migration-job -> Reservation annotations;
  * satellites — the sidecar-fallback counter and pending-queue metrics
    in /metrics exposition, /healthz at every ladder level.
"""

import json
import threading

import numpy as np
import pytest

from koordinator_tpu.api.objects import (
    ANNOTATION_DECISION_ID,
    Node,
    NodeMetric,
    NodeMetricInfo,
    ObjectMeta,
    Pod,
    PodSpec,
    Reservation,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_POD,
    KIND_RESERVATION,
    ObjectStore,
)
from koordinator_tpu.obs.server import ObsServer
from koordinator_tpu.obs.slo import SloRegistry
from koordinator_tpu.obs.slo import load_bundle as load_slo_bundle
from koordinator_tpu.obs.timeline import DeviceTimeline
from koordinator_tpu.obs.timeline import load_bundle as load_timeline_bundle
from koordinator_tpu.scheduler import metrics as scheduler_metrics
from koordinator_tpu.scheduler.cycle import Scheduler
from koordinator_tpu.scheduler.degrade import (
    LEVEL_FULL,
    LEVEL_HOST_FALLBACK,
    LEVEL_NAMES,
    LEVEL_PARTIAL_MESH,
)

GIB = 1024 ** 3
NOW = 1_000_000.0


def make_store(num_nodes=3):
    store = ObjectStore()
    for i in range(num_nodes):
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name=f"node-{i}", namespace=""),
            allocatable=ResourceList.of(
                cpu=16_000, memory=64 * GIB, pods=110)))
        store.add(KIND_NODE_METRIC, NodeMetric(
            meta=ObjectMeta(name=f"node-{i}", namespace=""),
            update_time=NOW - 10,
            node_metric=NodeMetricInfo(
                node_usage=ResourceList.of(cpu=1000, memory=2 * GIB))))
    return store


def pend_pod(store, name, **spec_kwargs):
    pod = Pod(
        meta=ObjectMeta(name=name, creation_timestamp=NOW - 30),
        spec=PodSpec(priority=9500,
                     requests=ResourceList.of(cpu=500, memory=GIB),
                     **spec_kwargs),
    )
    store.add(KIND_POD, pod)
    return pod


def demotion_count(reason):
    return scheduler_metrics.WAVE_DEMOTIONS.get(reason=reason) or 0.0


# ---------------------------------------------------------------------------
# demotion accounting: the chokepoint
# ---------------------------------------------------------------------------

class TestDemotionAccounting:
    def test_clean_fused_cycle_has_no_demotions(self):
        store = make_store()
        sched = Scheduler(store, waves=4)
        for i in range(4):
            pend_pod(store, f"p{i}")
        res = sched.run_cycle(now=NOW)
        assert res.demotions == []
        assert res.waves == 4

    def test_retired_reasons_no_longer_demote(self):
        """PR 14 burn-down: pending reservations, claim pods and prod
        scoring all run FUSED now — the retired reasons never fire (and
        the chokepoint would raise if they tried)."""
        store = make_store()
        sched = Scheduler(store, waves=4)
        store.add(KIND_RESERVATION, Reservation(
            meta=ObjectMeta(name="r1", namespace=""),
            template=PodSpec(requests=ResourceList.of(cpu=100))))
        for i in range(3):
            pend_pod(store, f"p{i}")
        pend_pod(store, "claims", pvc_names=["c1"])
        res = sched.run_cycle(now=NOW)
        assert res.waves == 4
        assert res.demotions == []
        rec = sched.flight.snapshot()[-1]
        assert rec["demotions"] == []
        assert rec["decision_ids"] == res.decision_ids

        from koordinator_tpu.ops.loadaware import LoadAwareArgs

        store2 = make_store()
        sched2 = Scheduler(
            store2, args=LoadAwareArgs(score_according_prod_usage=True),
            waves=4)
        pend_pod(store2, "p0")
        res2 = sched2.run_cycle(now=NOW)
        assert res2.demotions == []

    def test_non_expressible_transformer_reason(self):
        """A host-only ScoreTransformer is the one transformer residue
        left: it demotes with its own registered reason (the retired
        'score-transformer' reason is pinned out by the registry)."""
        from koordinator_tpu.scheduler.cycle import (
            DEMOTION_REASONS,
            RETIRED_DEMOTION_REASONS,
        )
        from koordinator_tpu.scheduler.frameworkext import ScoreTransformer

        before = demotion_count("non-expressible-transformer")
        store2 = make_store()
        sched2 = Scheduler(store2, waves=4)
        sched2.extender.register_transformer(ScoreTransformer())
        for i in range(2):
            pend_pod(store2, f"q{i}")
        res2 = sched2.run_cycle(now=NOW)
        assert res2.waves == 1
        assert "non-expressible-transformer" in res2.demotions
        assert demotion_count("non-expressible-transformer") == before + 1
        # registry hygiene: the retired set and the live set are disjoint
        # and every retired reason raises at the chokepoint
        assert not (DEMOTION_REASONS & RETIRED_DEMOTION_REASONS)
        for retired in RETIRED_DEMOTION_REASONS:
            with pytest.raises(ValueError):
                sched2._note_demotion(retired, 1)

    def test_sidecar_demotes_waves_and_explain(self):
        from koordinator_tpu.sim.faults import DeadSidecarClient

        store = make_store()
        sched = Scheduler(store, waves=4, explain="counts")
        sched._sidecar_client = DeadSidecarClient()
        fallbacks0 = (scheduler_metrics.SIDECAR_FALLBACKS.get() or 0.0)
        for i in range(3):
            pend_pod(store, f"p{i}")
        res = sched.run_cycle(now=NOW)
        assert res.waves == 1
        assert "sidecar" in res.demotions
        assert "explain-sidecar" in res.demotions
        # satellite: the loose attribute is now a real counter, and the
        # dead sidecar forced the in-process fallback
        assert sched.sidecar_fallbacks >= 1
        assert (scheduler_metrics.SIDECAR_FALLBACKS.get() or 0.0) \
            == fallbacks0 + sched.sidecar_fallbacks
        text = scheduler_metrics.REGISTRY.expose()
        assert "koord_scheduler_sidecar_fallbacks_total" in text

    def test_ladder_demotion_reasons_and_mesh_off(self, cpu_devices):
        store = make_store()
        sched = Scheduler(store, waves=4, explain="counts", mesh=2)
        calls = {"n": 0}

        def inj(stage):
            calls["n"] += 1
            if calls["n"] <= 4:
                raise RuntimeError("injected dispatch fault")

        sched.fault_injector = inj
        for i in range(3):
            pend_pod(store, f"p{i}")
        # fault burst: retry, then walk no-mesh -> serial-waves ->
        # no-explain before the 5th attempt succeeds
        res = sched.run_cycle(now=NOW)
        assert sched.ladder.level >= 3  # at least serial-waves
        # next cycle runs at the demoted settings: both the mesh and the
        # wave/explain chokepoints attribute it
        pend_pod(store, "late")
        res2 = sched.run_cycle(now=NOW + 1)
        assert "mesh-off" in res2.demotions
        assert "ladder-serial-waves" in res2.demotions
        if sched.ladder.level >= 4:
            assert "explain-ladder" in res2.demotions
        del res

    def test_reasons_deduped_per_cycle(self):
        from koordinator_tpu.scheduler.frameworkext import ScoreTransformer

        store = make_store()
        sched = Scheduler(store, waves=4)
        sched.extender.register_transformer(ScoreTransformer())
        pend_pod(store, "p0")
        res = sched.run_cycle(now=NOW)
        assert res.demotions.count("non-expressible-transformer") == 1

    def test_watch_off_disables_accounting_but_not_ids(self):
        from koordinator_tpu.scheduler.frameworkext import ScoreTransformer

        store = make_store()
        sched = Scheduler(store, waves=4, watch=False)
        sched.extender.register_transformer(ScoreTransformer())
        pend_pod(store, "p0")
        res = sched.run_cycle(now=NOW)
        assert res.waves == 1          # behavior unchanged
        assert res.demotions == []     # accounting off
        assert res.decision_ids        # correlation stays wired
        assert len(sched.timeline) == 0  # ring off


# ---------------------------------------------------------------------------
# device timeline
# ---------------------------------------------------------------------------

class TestDeviceTimeline:
    def test_windows_recorded_with_outcomes(self):
        store = make_store()
        sched = Scheduler(store, waves=1)
        for i in range(2):
            pend_pod(store, f"p{i}")
        sched.run_cycle(now=NOW)
        windows = sched.timeline.snapshot()
        assert len(windows) == 1
        w = windows[0]
        assert w["consumer"] == "scheduler"
        assert w["path"] == "serial"
        assert w["outcome"] == "clean"
        assert w["duration_ms"] >= 0
        assert w["decision_id"] == sched.tracer.roots()[-1].find(
            "kernel").attributes["decision_id"]

    def test_retried_and_demoted_outcomes(self):
        store = make_store()
        sched = Scheduler(store, waves=1, explain="counts")
        calls = {"n": 0}

        def inj(stage):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("transient")

        sched.fault_injector = inj
        pend_pod(store, "p0")
        sched.run_cycle(now=NOW)
        assert sched.timeline.snapshot()[-1]["outcome"] == "retried"

        calls["n"] = -2  # two more failures: retry then demote
        pend_pod(store, "p1")

        def inj2(stage):
            calls["n"] += 1
            if calls["n"] <= 0:
                raise RuntimeError("persistent")

        sched.fault_injector = inj2
        sched.run_cycle(now=NOW + 1)
        assert sched.timeline.snapshot()[-1]["outcome"] == "demoted"

    def test_bundle_validates_and_gap_accounting(self):
        t = DeviceTimeline()
        w1 = t.open("scheduler", "serial")
        t.close(w1, "clean")
        w2 = t.open("rebalance", "serial")
        t.close(w2, "clean")
        header, records, errors = load_timeline_bundle(
            t.export_jsonl().splitlines())
        assert errors == []
        assert header["windows"] == 2
        assert [r["consumer"] for r in records] == ["scheduler",
                                                    "rebalance"]
        assert records[0]["gap_ms"] == 0.0
        assert records[1]["gap_ms"] >= 0.0
        assert 0.0 <= t.idle_fraction() <= 1.0

    def test_ring_is_bounded(self):
        t = DeviceTimeline(capacity=4)
        for i in range(10):
            t.close(t.open("scheduler", "serial"), "clean")
        assert len(t) == 4
        assert [r["seq"] for r in t.snapshot()] == [7, 8, 9, 10]

    def test_rejects_bad_outcome_and_path(self):
        from koordinator_tpu.obs.timeline import validate_window_record

        good = {"v": 1, "kind": "window", "seq": 1,
                "decision_id": "scheduler-1", "consumer": "scheduler",
                "path": "serial", "outcome": "clean", "ts": 1.0,
                "duration_ms": 1.0, "gap_ms": 0.0}
        assert validate_window_record(good) == []
        assert validate_window_record({**good, "outcome": "exploded"})
        assert validate_window_record({**good, "path": "warp"})
        assert validate_window_record({**good, "duration_ms": -1})

    def test_metrics_exported(self):
        store = make_store()
        sched = Scheduler(store, waves=1)
        pend_pod(store, "p0")
        sched.run_cycle(now=NOW)
        text = scheduler_metrics.REGISTRY.expose()
        assert "koord_device_window_seconds_bucket" in text
        assert 'consumer="scheduler"' in text
        assert "koord_device_idle_fraction" in text
        # pending-queue satellites ride the same exposition
        assert "koord_scheduler_pending_queue_depth" in text
        assert "koord_scheduler_queue_wait_seconds_bucket" in text

    def test_queue_metrics_observed(self):
        store = make_store()
        sched = Scheduler(store, waves=1)
        count0 = scheduler_metrics.QUEUE_WAIT_SECONDS.count()
        for i in range(3):
            pend_pod(store, f"p{i}")  # created at NOW - 30
        sched.run_cycle(now=NOW)
        assert scheduler_metrics.PENDING_QUEUE_DEPTH.get() == 3.0
        assert scheduler_metrics.QUEUE_WAIT_SECONDS.count() == count0 + 3


# ---------------------------------------------------------------------------
# SLO engine
# ---------------------------------------------------------------------------

class TestSloEngine:
    def test_registry_math(self):
        reg = SloRegistry()
        reg.register("ttb_p99", target=100.0, percentile=99.0)
        assert reg.objective("ttb_p99").met()  # vacuous
        reg.observe_many("ttb_p99", [10.0, 50.0, 150.0])
        o = reg.objective("ttb_p99")
        assert o.count() == 3
        assert o.overruns == 1
        expected = float(np.percentile(np.asarray([10.0, 50.0, 150.0]), 99))
        assert o.observed() == expected
        assert o.burn_rate() == pytest.approx(expected / 100.0)
        assert not o.met()
        # max-gated objective
        reg.register("recovery", target=30.0, percentile=100.0)
        reg.observe("recovery", 12.0)
        assert reg.objective("recovery").observed() == 12.0
        assert reg.objective("recovery").met()
        # report-only objective: always met, zero burn
        reg.register("advisory", target=0.0)
        reg.observe("advisory", 1e9)
        assert reg.objective("advisory").met()
        assert reg.objective("advisory").burn_rate() == 0.0
        with pytest.raises(ValueError):
            reg.register("ttb_p99", target=1.0)

    def test_gauges_refresh(self):
        reg = SloRegistry(burn_gauge=scheduler_metrics.SLO_BURN_RATE,
                          met_gauge=scheduler_metrics.SLO_MET)
        reg.register("test_obj", target=10.0, percentile=100.0)
        reg.observe("test_obj", 20.0)
        assert scheduler_metrics.SLO_BURN_RATE.get(slo="test_obj") == 2.0
        assert scheduler_metrics.SLO_MET.get(slo="test_obj") == 0.0
        text = scheduler_metrics.REGISTRY.expose()
        assert 'koord_slo_burn_rate{slo="test_obj"} 2' in text

    def test_bundle_round_trip(self):
        reg = SloRegistry()
        reg.register("a", target=10.0)
        reg.observe_many("a", [1.0, 2.0])
        reg.register("b", target=0.0, unit="cycles", percentile=100.0)
        header, records, errors = load_slo_bundle(
            reg.export_jsonl().splitlines())
        assert errors == []
        assert header["slos"] == 2
        assert [r["slo"] for r in records] == ["a", "b"]
        assert records[0]["met"] is True

    def test_sim_report_slo_shape_is_pinned_field_for_field(self):
        """The SloRegistry refactor must not move a single field of the
        report's SLO JSON: compare against the LEGACY expressions
        (copied verbatim from the pre-koordwatch to_dict)."""
        from koordinator_tpu.sim.harness import SimReport

        rep = SimReport(scenario="pin", seed=1, cycles=100,
                        slo_target_seconds=120.0,
                        dissipate_slo_cycles=30,
                        restart_slo_seconds=60.0)
        rep.ttb_seconds = [0.5, 3.0, 7.5, 130.0, 42.0]
        rep.slo_overruns = 1
        rep.restarts = 1
        rep.restart_to_first_bind_seconds = [12.5]
        rep.dissipate_cycles = [5, 28]
        rep.hotspots_open = 0
        rep.colo_staleness_cycles = [1, 2, 3]
        rep.colo_staleness_slo_cycles = 2
        d = rep.to_dict()

        def pct(vals, q):
            return float(np.percentile(np.asarray(vals), q))

        legacy_ttb = {
            "count": len(rep.ttb_seconds),
            "p50": round(pct(rep.ttb_seconds, 50), 3),
            "p90": round(pct(rep.ttb_seconds, 90), 3),
            "p99": round(pct(rep.ttb_seconds, 99), 3),
            "max": round(max(rep.ttb_seconds), 3),
            "mean": round(float(np.mean(rep.ttb_seconds)), 3),
        }
        assert d["time_to_bind_seconds"] == legacy_ttb
        assert d["slo"] == {
            "ttb_p99_target_seconds": 120.0,
            "met": legacy_ttb["p99"] <= 120.0,
            "overruns": 1,
        }
        assert d["restart"]["to_first_bind_seconds"] == {
            "count": 1,
            "p50": pct(rep.restart_to_first_bind_seconds, 50),
            "p99": pct(rep.restart_to_first_bind_seconds, 99),
            "max": max(rep.restart_to_first_bind_seconds),
        }
        assert d["restart"]["met"] is True
        assert d["rebalance"]["time_to_dissipate_cycles"] == {
            "count": 2,
            "p50": pct(rep.dissipate_cycles, 50),
            "p99": pct(rep.dissipate_cycles, 99),
            "max": 28,
        }
        assert d["rebalance"]["dissipate_slo_met"] is True
        assert d["colo"]["staleness_cycles"] == {
            "count": 3,
            "p50": pct(rep.colo_staleness_cycles, 50),
            "p99": pct(rep.colo_staleness_cycles, 99),
            "max": 3,
        }
        assert d["colo"]["staleness_slo_met"] is (
            pct(rep.colo_staleness_cycles, 99) <= 2)
        # the new slos block mirrors the same objectives with burn rates
        assert set(d["slos"]) == {"ttb_p99", "restart_to_first_bind",
                                  "hotspot_dissipate", "colo_staleness"}
        assert d["slos"]["ttb_p99"]["burn_rate"] == pytest.approx(
            pct(rep.ttb_seconds, 99) / 120.0)

    def test_empty_report_slo_blocks_match_legacy(self):
        from koordinator_tpu.sim.harness import SimReport

        rep = SimReport(scenario="empty", seed=1, cycles=10,
                        slo_target_seconds=120.0)
        d = rep.to_dict()
        assert d["time_to_bind_seconds"] == {
            "count": 0, "p50": 0.0, "p90": 0.0, "p99": 0.0,
            "max": 0.0, "mean": 0.0}
        assert d["slo"]["met"] is True
        assert d["restart"]["met"] is True
        assert d["rebalance"]["dissipate_slo_met"] is True
        assert d["colo"]["staleness_slo_met"] is True
        assert d["demotions"] == {
            "cycles_demoted": 0, "fraction_of_cycles": 0.0,
            "by_reason": {}}


# ---------------------------------------------------------------------------
# sim demotion profile: zero unattributed demotions
# ---------------------------------------------------------------------------

class TestDemotionProfile:
    def test_fault_ladder_profile_sums_exactly(self, cpu_devices,
                                               monkeypatch):
        """Zero unattributed demotions (acceptance): the fault-ladder
        scenario's demotion profile must match an INDEPENDENT per-cycle
        tally taken at the Scheduler.run_cycle boundary (class-level
        spy, so the crash-restart's fresh scheduler is covered too),
        and per-reason counts must sum to every demoted cycle."""
        from koordinator_tpu.sim.harness import ChurnSimulator
        from koordinator_tpu.sim.scenarios import SCENARIOS

        tallied = {"cycles": 0, "by_reason": {}}
        orig_run = Scheduler.run_cycle

        def spy(self, now=None, waves=None):
            res = orig_run(self, now=now, waves=waves)
            if res.demotions:
                tallied["cycles"] += 1
                reason = res.demotions[0]
                tallied["by_reason"][reason] = (
                    tallied["by_reason"].get(reason, 0) + 1)
            return res

        monkeypatch.setattr(Scheduler, "run_cycle", spy)
        ladder_reason0 = demotion_count("ladder-serial-waves")
        sc = SCENARIOS["fault-ladder"]
        sim = ChurnSimulator(sc)
        for cycle in range(sc.cycles):
            sim._run_one_cycle(cycle)
        report = sim.run_report()
        # the fault-ladder scenario MUST demote (waves=4 + the ladder
        # walk): a zero profile would mean the accounting went blind
        assert report.cycles_demoted > 0
        prof = report.to_dict()["demotions"]
        assert prof["cycles_demoted"] == report.cycles_demoted
        # EVERY demoted cycle is attributed: per-reason counts sum
        # exactly to the demoted-cycle count
        assert sum(prof["by_reason"].values()) == prof["cycles_demoted"]
        # and the profile matches the independent tally exactly
        assert prof["cycles_demoted"] == tallied["cycles"]
        assert prof["by_reason"] == tallied["by_reason"]
        # the scenario's ladder walk + koordguard events are visible:
        # mesh demotions lead the profile (noted at cycle start, so
        # first-reason attribution picks them), and the fused-wave
        # ladder reason incremented the per-reason counter (it rides
        # those same cycles as a secondary reason)
        assert "mesh-off" in prof["by_reason"]
        assert "partial-mesh" in prof["by_reason"]
        assert demotion_count("ladder-serial-waves") > ladder_reason0

    def test_soak_short_profile_consistent(self):
        import dataclasses

        from koordinator_tpu.sim.harness import run_scenario
        from koordinator_tpu.sim.scenarios import SCENARIOS

        sc = dataclasses.replace(SCENARIOS["soak"], cycles=60)
        report = run_scenario(sc)
        prof = report.to_dict()["demotions"]
        assert sum(prof["by_reason"].values()) == prof["cycles_demoted"]
        assert prof["cycles_demoted"] <= 60
        # queue visibility rides the same report
        q = report.to_dict()["queue"]
        assert len(report.queue_depth_by_cycle) == 60
        assert q["depth"]["max"] >= q["depth"]["mean"] >= 0
        assert q["oldest_wait_seconds"]["max"] >= 0


# ---------------------------------------------------------------------------
# decision correlation
# ---------------------------------------------------------------------------

class TestDecisionCorrelation:
    def test_cycle_ids_join_span_flight_and_explain(self):
        store = make_store()
        sched = Scheduler(store, waves=1, explain="counts")
        pend_pod(store, "p0")
        res = sched.run_cycle(now=NOW)
        assert len(res.decision_ids) == 1
        did = res.decision_ids[0]
        assert sched.tracer.roots()[-1].find(
            "kernel").attributes["decision_id"] == did
        rec = sched.flight.snapshot()[-1]
        assert rec["decision_ids"] == [did]
        exp = sched.explain_record(res.bound[0].pod_key)
        assert exp is not None and exp["decision_id"] == did

    def test_ids_are_deterministic(self):
        def ids():
            store = make_store()
            sched = Scheduler(store, waves=1)
            for c in range(3):
                pend_pod(store, f"p{c}")
                sched.run_cycle(now=NOW + c)
            return [w["decision_id"]
                    for w in sched.timeline.snapshot()]

        assert ids() == ids()

    def test_migration_job_and_reservation_carry_decision_id(self):
        """The koordbalance closed loop: rebalance window ->
        PodMigrationJob annotation -> replacement Reservation."""
        import dataclasses

        from koordinator_tpu.client.store import (
            KIND_POD_MIGRATION_JOB,
        )
        from koordinator_tpu.sim.harness import ChurnSimulator
        from koordinator_tpu.sim.scenarios import SCENARIOS

        sc = dataclasses.replace(SCENARIOS["hotspot"], cycles=50)
        sim = ChurnSimulator(sc)
        for cycle in range(sc.cycles):
            sim._run_one_cycle(cycle)
        jobs = sim.store.list(KIND_POD_MIGRATION_JOB)
        assert jobs, "hotspot scenario must issue migration jobs"
        stamped = [j for j in jobs
                   if ANNOTATION_DECISION_ID in j.meta.annotations]
        assert stamped, "migration jobs must carry the decision id"
        for job in stamped:
            assert job.meta.annotations[
                ANNOTATION_DECISION_ID].startswith("rebalance-")
        # jobs that reached the reservation step copied the id onto it
        linked = 0
        for job in stamped:
            if not job.reservation_name:
                continue
            res = sim.store.get(KIND_RESERVATION,
                                f"/{job.reservation_name}")
            if res is None:
                continue
            linked += 1
            assert res.meta.annotations.get(ANNOTATION_DECISION_ID) == \
                job.meta.annotations[ANNOTATION_DECISION_ID]
        assert linked > 0

    def test_shared_timeline_across_consumers(self):
        """Co-located descheduler + manager record into the SCHEDULER's
        ring: one device, one timeline, one id sequence."""
        import dataclasses

        from koordinator_tpu.sim.harness import ChurnSimulator
        from koordinator_tpu.sim.scenarios import SCENARIOS

        sc = dataclasses.replace(SCENARIOS["overcommit-shift"], cycles=12)
        sim = ChurnSimulator(sc)
        for cycle in range(sc.cycles):
            sim._run_one_cycle(cycle)
        consumers = {w["consumer"]
                     for w in sim.sched.timeline.snapshot()}
        assert "scheduler" in consumers
        assert "colo" in consumers
        assert sim.manager.colo.timeline is sim.sched.timeline


# ---------------------------------------------------------------------------
# HTTP surfaces: /debug/timeline, /debug/slo, /healthz, under load
# ---------------------------------------------------------------------------

class TestObsSurfaces:
    def test_debug_routes(self):
        t = DeviceTimeline()
        t.close(t.open("scheduler", "serial"), "clean")
        reg = SloRegistry()
        reg.register("ttb_p99", target=100.0)
        srv = ObsServer(timeline=t, slo=reg)
        status, ctype, body = srv.handle("/debug/timeline")
        assert status == 200 and "ndjson" in ctype
        assert load_timeline_bundle(body.splitlines())[2] == []
        status, _, body = srv.handle("/debug/slo")
        assert status == 200
        assert load_slo_bundle(body.splitlines())[2] == []
        # without providers the routes stay dark
        assert ObsServer().handle("/debug/timeline")[0] == 404
        assert ObsServer().handle("/debug/slo")[0] == 404

    def test_healthz_reports_every_ladder_level(self, cpu_devices):
        """The /healthz payload must identify the rung at EVERY ladder
        level, partial-mesh included — a scheduler surviving demoted
        must never look healthy."""
        store = make_store()
        sched = Scheduler(store, waves=4, explain="counts", mesh=2)
        sched._lost_device_ids = {1}  # the partial-mesh survivors' set
        srv = ObsServer(scheduler_metrics.REGISTRY, sched.tracer,
                        health_provider=sched.health_snapshot)
        pend_pod(store, "warm")
        sched.run_cycle(now=NOW)
        for level in range(LEVEL_FULL, LEVEL_HOST_FALLBACK + 1):
            sched.ladder.level = level
            sched._apply_degraded_level()
            status, _, body = srv.handle("/healthz")
            assert status == 200
            payload = json.loads(body)
            assert payload["degraded"]["level"] == level
            assert payload["degraded"]["level_name"] == LEVEL_NAMES[level]
            assert payload["cycles"] >= 1
            if level == LEVEL_PARTIAL_MESH:
                assert sched.mesh is not None
                assert sched.mesh.devices.size < 8
        # restore full for teardown sanity
        sched.ladder.level = LEVEL_FULL
        sched._apply_degraded_level()

    def test_concurrent_scrapes_during_churn(self):
        """Satellite: concurrent /metrics + /debug/timeline (+ /traces,
        /debug/slo, /healthz) scrapes while a seeded churn loop runs —
        no torn exposition, no exception."""
        from koordinator_tpu.sim.harness import ChurnSimulator
        from koordinator_tpu.sim.scenarios import SCENARIOS

        sim = ChurnSimulator(SCENARIOS["smoke"].resolved(cycles=14))
        srv = ObsServer(scheduler_metrics.REGISTRY, sim.sched.tracer,
                        health_provider=sim.sched.health_snapshot,
                        flight=sim.sched.flight,
                        timeline=sim.sched.timeline, slo=sim.slo)
        stop = threading.Event()
        errors = []
        scrapes = {"n": 0}

        def scraper(path):
            while not stop.is_set():
                try:
                    status, _, body = srv.handle(path)
                    assert status == 200, (path, status)
                    if path == "/metrics":
                        assert ("# TYPE koord_scheduler_cycle_seconds "
                                "histogram") in body
                    elif path == "/debug/timeline":
                        assert load_timeline_bundle(
                            body.splitlines())[2] == []
                    elif path == "/debug/slo":
                        assert load_slo_bundle(
                            body.splitlines())[2] == []
                    elif path == "/healthz":
                        json.loads(body)
                    scrapes["n"] += 1
                except Exception as exc:  # surfaced via the errors list
                    errors.append(f"{path}: {type(exc).__name__}: {exc}")
                    return

        threads = [threading.Thread(target=scraper, args=(p,))
                   for p in ("/metrics", "/debug/timeline", "/debug/slo",
                             "/healthz", "/traces")]
        for th in threads:
            th.start()
        try:
            for cycle in range(14):
                sim._run_one_cycle(cycle)
        finally:
            stop.set()
            for th in threads:
                th.join(timeout=30)
        assert errors == []
        assert scrapes["n"] > 0
        report = sim.run_report()
        assert report.invariant_breaches == []
