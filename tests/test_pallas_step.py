"""Parity tests: the Pallas VMEM-resident scheduling kernel must bit-match
the XLA fori_loop step (which itself bit-matches the serial reference
emulator) on randomized clusters."""

import numpy as np
import pytest

from koordinator_tpu.models.scheduler_model import (
    build_schedule_step,
    make_inputs,
)
from koordinator_tpu.ops.loadaware import LoadAwareArgs, build_loadaware_node_state
from koordinator_tpu.ops.packing import pack_nodes, pack_pods
from koordinator_tpu.ops.pallas_step import build_pallas_schedule_step
from koordinator_tpu.testing import synth_cluster


def _inputs(num_nodes, num_pods, seed, **args_kw):
    args = LoadAwareArgs(**args_kw)
    cluster = synth_cluster(num_nodes=num_nodes, num_pods=num_pods, seed=seed)
    pods = pack_pods(cluster.pods, args.resource_weights,
                     args.estimated_scaling_factors)
    nodes = pack_nodes(cluster.nodes)
    nodes.extras = build_loadaware_node_state(
        cluster.nodes, cluster.node_metrics, cluster.pods_by_key,
        cluster.assigned, args, cluster.now, pad_to=nodes.padded_size)
    return args, make_inputs(pods, nodes, args)


@pytest.mark.parametrize("seed", [0, 7])
@pytest.mark.parametrize("prod_mode", [False, True])
def test_pallas_matches_xla_step(seed, prod_mode):
    args, inputs = _inputs(24, 40, seed,
                           score_according_prod_usage=prod_mode)
    xla_step = build_schedule_step(args)
    pallas_step = build_pallas_schedule_step(args, interpret=True)
    chosen_x, req_x = xla_step(inputs)
    chosen_p, req_p = pallas_step(inputs)
    np.testing.assert_array_equal(np.asarray(chosen_x), np.asarray(chosen_p))
    np.testing.assert_allclose(np.asarray(req_x), np.asarray(req_p),
                               rtol=0, atol=1e-4)


def test_pallas_crosses_pod_block():
    """160 pods > POD_BLOCK=128: at least two pod-column blocks stream in,
    exercising the block index map and lane-wrap math."""
    args, inputs = _inputs(32, 160, seed=2)
    chosen_x, _ = build_schedule_step(args)(inputs)
    chosen_p, _ = build_pallas_schedule_step(args, interpret=True)(inputs)
    np.testing.assert_array_equal(np.asarray(chosen_x), np.asarray(chosen_p))
    assert (np.asarray(chosen_x) >= 0).sum() > 0


def test_pallas_infeasible_pods_get_minus_one():
    args, inputs = _inputs(4, 6, seed=3)
    # make every node unschedulable
    inputs = inputs._replace(node_ok=np.zeros_like(inputs.node_ok))
    pallas_step = build_pallas_schedule_step(args, interpret=True)
    chosen, _ = pallas_step(inputs)
    assert (np.asarray(chosen) == -1).all()
