"""Unit tests for shared utils (cpuset/bitmask/histogram/parallelize/features)."""

import pytest

from koordinator_tpu.utils.bitmask import BitMask
from koordinator_tpu.utils.cpuset import CPUSet
from koordinator_tpu.utils.features import FeatureGate, KOORDLET_GATES
from koordinator_tpu.utils.histogram import DecayingHistogram, HistogramOptions
from koordinator_tpu.utils.parallelize import parallel_map


class TestCPUSet:
    def test_parse_and_format(self):
        s = CPUSet.parse("0-3,7,9-11")
        assert s.to_list() == [0, 1, 2, 3, 7, 9, 10, 11]
        assert s.format() == "0-3,7,9-11"
        assert CPUSet.parse("").format() == ""
        assert CPUSet.parse("5").to_list() == [5]

    def test_invalid_range(self):
        with pytest.raises(ValueError):
            CPUSet.parse("5-2")

    def test_algebra(self):
        a, b = CPUSet.parse("0-3"), CPUSet.parse("2-5")
        assert a.union(b).format() == "0-5"
        assert a.intersection(b).format() == "2-3"
        assert a.difference(b).format() == "0-1"
        assert CPUSet.parse("2-3").is_subset_of(a)


class TestBitMask:
    def test_basic(self):
        m = BitMask([0, 2])
        assert m.count() == 2
        assert m.is_set(0) and m.is_set(2) and not m.is_set(1)
        assert m.get_bits() == [0, 2]

    def test_and_or(self):
        assert BitMask([0, 1]).and_(BitMask([1, 2])).get_bits() == [1]
        assert BitMask([0]).or_(BitMask([3])).get_bits() == [0, 3]

    def test_narrower(self):
        # fewer bits wins; ties prefer lower-numbered bits
        assert BitMask([0]).is_narrower_than(BitMask([0, 1]))
        assert BitMask([0]).is_narrower_than(BitMask([1]))
        assert not BitMask([1]).is_narrower_than(BitMask([0]))


class TestHistogram:
    def test_percentile_basic(self):
        opts = HistogramOptions.linear(max_value=100.0, bucket_size=1.0)
        h = DecayingHistogram(opts, half_life_seconds=1e9)  # effectively no decay
        for v in range(1, 101):
            h.add_sample(float(v) - 0.5, 1.0, timestamp=0.0)
        assert abs(h.percentile(0.5) - 50.0) <= 1.0
        assert abs(h.percentile(0.95) - 95.0) <= 1.0

    def test_decay(self):
        opts = HistogramOptions.linear(max_value=100.0, bucket_size=1.0)
        h = DecayingHistogram(opts, half_life_seconds=10.0)
        h.add_sample(10.0, 1.0, timestamp=0.0)
        h.add_sample(90.0, 1.0, timestamp=100.0)  # 2^10 heavier
        assert h.percentile(0.5) > 80.0

    def test_empty(self):
        opts = HistogramOptions.exponential(1e9, 1.0, 2.0)
        h = DecayingHistogram(opts)
        assert h.is_empty()
        assert h.percentile(0.99) == 0.0

    def test_checkpoint_roundtrip(self):
        opts = HistogramOptions.linear(max_value=10.0, bucket_size=1.0)
        h = DecayingHistogram(opts, half_life_seconds=100.0)
        h.add_sample(5.0, 2.0, timestamp=50.0)
        h2 = DecayingHistogram.from_checkpoint(opts, h.to_checkpoint())
        assert h2.percentile(0.5) == h.percentile(0.5)
        assert h2.total_weight == h.total_weight


class TestParallelize:
    def test_parallel_map(self):
        assert parallel_map(list(range(100)), lambda x: x * x) == [
            x * x for x in range(100)
        ]

    def test_error_propagates(self):
        def boom(x):
            raise RuntimeError("boom")

        with pytest.raises(RuntimeError):
            parallel_map([1, 2, 3], boom)


class TestFeatures:
    def test_defaults_and_overrides(self):
        g = FeatureGate({"A": True, "B": False})
        assert g.enabled("A") and not g.enabled("B")
        g.set_from_map({"B": True})
        assert g.enabled("B")
        g.reset()
        assert not g.enabled("B")

    def test_unknown_rejected(self):
        with pytest.raises(ValueError):
            FeatureGate({}).set_from_map({"nope": True})

    def test_koordlet_gate_set(self):
        assert KOORDLET_GATES.enabled("BECPUSuppress")
        assert not KOORDLET_GATES.enabled("CPICollector")
