"""koordcolo: the control plane's resource model on device.

Covers the PR's acceptance gates at test granularity:
  * decision parity vs the host oracles (single-device + mesh),
  * the closed loop: a NodeMetric shift changes batch allocatable on
    device and the VERY NEXT dispatch binds/refuses a batch pod,
  * the shared snapshot (no second watch chain, colo_* fields in the
    scheduler's DeviceSnapshot),
  * the degradation ladder + dispatch deadline around the colo pass,
  * the device quota fold against compute_runtime_quotas (including the
    AutoScaleMin exact floor-division path),
  * the epoch memos + the revoke loop consuming the device mask,
  * slo-config hot-reload reaching the policy scalars without a
    step-cache leak.
"""

import dataclasses
import json

import numpy as np
import pytest

from koordinator_tpu.api.objects import (
    ConfigMap,
    ElasticQuota,
    LABEL_QUOTA_NAME,
    Node,
    NodeMetric,
    NodeMetricInfo,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.api.resources import (
    RESOURCE_INDEX,
    ResourceList,
    ResourceName,
)
from koordinator_tpu.client.store import (
    KIND_CONFIG_MAP,
    KIND_ELASTIC_QUOTA,
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_POD,
    ObjectStore,
)
from koordinator_tpu.manager import Manager
from koordinator_tpu.scheduler.cycle import Scheduler
from koordinator_tpu.scheduler.pipeline_parity import run_colo_parity

GIB = 1024 ** 3
NOW = 1_000_000.0
BATCH_CPU = ResourceName.BATCH_CPU


def _world(nodes=4, usage_cpu=3000):
    store = ObjectStore()
    for i in range(nodes):
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name=f"n{i}", namespace=""),
            allocatable=ResourceList.of(cpu=16_000, memory=64 * GIB,
                                        pods=64)))
        store.add(KIND_NODE_METRIC, NodeMetric(
            meta=ObjectMeta(name=f"n{i}", namespace=""),
            update_time=NOW,
            node_metric=NodeMetricInfo(node_usage=ResourceList.of(
                cpu=usage_cpu, memory=8 * GIB))))
    return store


def _batch_pod(name, cpu=2000, mem_gib=2):
    return Pod(
        meta=ObjectMeta(name=name, namespace="t", uid=name,
                        creation_timestamp=NOW,
                        owner_kind="ReplicaSet", owner_name="rs"),
        spec=PodSpec(priority=5500, requests=ResourceList.of(
            batch_cpu=cpu, batch_memory=mem_gib * GIB)))


# ---------------------------------------------------------------------------
# parity gates (the hack/lint.sh module runs the same functions)
# ---------------------------------------------------------------------------

class TestColoParity:
    def test_single_device(self):
        rep = run_colo_parity()
        assert rep["ok"], rep["mismatches"]

    @pytest.mark.parametrize("ndev", [1, 2, 4, 8])
    def test_mesh(self, ndev):
        import jax

        if ndev > len(jax.devices()):
            pytest.skip(f"needs {ndev} devices")
        rep = run_colo_parity(ndev)
        assert rep["ok"], rep["mismatches"]


# ---------------------------------------------------------------------------
# the closed loop: overcommit shift -> very next dispatch
# ---------------------------------------------------------------------------

class TestClosedLoop:
    def test_metric_shift_gates_the_next_dispatch(self):
        store = _world(nodes=2, usage_cpu=2000)
        sched = Scheduler(store)
        mgr = Manager(store, scheduler=sched, colo="on")

        # tick 1: low usage -> generous batch allocatable; a batch pod
        # binds on the very next dispatch
        assert mgr.tick(now=NOW + 1)
        assert mgr.colo.last_pass_stats["engine"] == "device"
        batch0 = store.get(KIND_NODE, "/n0").allocatable[BATCH_CPU]
        assert batch0 > 0
        store.add(KIND_POD, _batch_pod("be-1", cpu=3000))
        res = sched.run_cycle(now=NOW + 2)
        assert [b.pod_key for b in res.bound] == ["t/be-1"]
        pod = store.get(KIND_POD, "t/be-1")
        pod.phase = "Running"
        store.update(KIND_POD, pod)

        # prod usage surges: the NodeMetric shift shrinks batch
        # allocatable ON DEVICE, and the very next dispatch refuses a
        # batch pod the old overcommit would have taken
        for nm in store.list(KIND_NODE_METRIC):
            nm.update_time = NOW + 10
            nm.node_metric = NodeMetricInfo(node_usage=ResourceList.of(
                cpu=15_500, memory=60 * GIB))
            store.update(KIND_NODE_METRIC, nm)
        assert mgr.tick(now=NOW + 11)
        assert mgr.colo.last_pass_stats["engine"] == "device"
        shrunk = store.get(KIND_NODE, "/n0").allocatable[BATCH_CPU]
        assert shrunk < batch0
        store.add(KIND_POD, _batch_pod("be-2", cpu=3000))
        res = sched.run_cycle(now=NOW + 12)
        assert res.bound == []
        assert "t/be-2" in res.failed

    def test_staleness_degrade_zeroes_batch(self):
        store = _world(nodes=2)
        sched = Scheduler(store)
        mgr = Manager(store, scheduler=sched, colo="on")
        assert mgr.tick(now=NOW + 1)
        assert store.get(KIND_NODE, "/n0").allocatable[BATCH_CPU] > 0
        # stale metrics degrade the node: batch resets to zero (the
        # kernel's degrade gate), exactly like the host controller
        assert mgr.tick(now=NOW + 100_000)
        stats = mgr.colo.last_pass_stats
        assert stats["engine"] == "device"
        assert np.asarray(stats["degraded"]).all()
        assert store.get(KIND_NODE, "/n0").allocatable[BATCH_CPU] == 0


# ---------------------------------------------------------------------------
# shared snapshot: one event stream, three consumers
# ---------------------------------------------------------------------------

class TestSharedSnapshot:
    def test_colo_pack_adds_no_store_subscription(self):
        store = _world()
        sched = Scheduler(store)
        counts_before = {
            kind: len(store._collections[kind].handlers)
            for kind in (KIND_POD, KIND_NODE, KIND_NODE_METRIC)}
        Manager(store, scheduler=sched, colo="on")
        counts_after = {
            kind: len(store._collections[kind].handlers)
            for kind in (KIND_POD, KIND_NODE, KIND_NODE_METRIC)}
        # the pack rides the SnapshotCache's existing chain — the ONLY
        # new watch is the quota plugin's node epoch (registered by the
        # scheduler's own plugin at construction, not by the pack)
        assert counts_before == counts_after

    def test_device_pass_uses_scheduler_device_snapshot(self):
        store = _world()
        sched = Scheduler(store)
        mgr = Manager(store, scheduler=sched, colo="on")
        snap = sched.device_snapshot
        before = dict(snap.stats)
        assert mgr.tick(now=NOW + 1)
        assert mgr.colo.last_pass_stats["engine"] == "device"
        assert snap.stats["put"] > before["put"]  # colo_* fields landed

    def test_pack_matches_host_gather(self):
        store = _world(nodes=3)
        store.add(KIND_POD, Pod(
            meta=ObjectMeta(name="prod-1", namespace="t", uid="prod-1"),
            spec=PodSpec(node_name="n1", priority=9500,
                         requests=ResourceList.of(cpu=4000,
                                                  memory=8 * GIB)),
            phase="Running"))
        nm = store.get(KIND_NODE_METRIC, "/n1")
        from koordinator_tpu.api.objects import PodMetricInfo

        nm.pods_metric = [PodMetricInfo(
            namespace="t", name="prod-1",
            pod_usage=ResourceList.of(cpu=3500, memory=6 * GIB))]
        store.update(KIND_NODE_METRIC, nm)
        sched = Scheduler(store)
        mgr = Manager(store, scheduler=sched, colo="on")
        ctl = mgr.controllers["noderesource"]
        view = mgr.colo.pack.view(NOW + 5)
        nodes = store.list(KIND_NODE)
        (capacity, node_reserved, system_reserved, node_used,
         pod_all_used, hp_used, hp_request, hp_max, prod_reclaimable,
         reclaim, mid_pct, degraded) = ctl._gather(nodes, NOW + 5)
        assert np.array_equal(view["capacity"], capacity)
        assert np.array_equal(view["node_used"], node_used)
        assert np.array_equal(view["hp_used"], hp_used)
        assert np.array_equal(view["hp_request"], hp_request)
        assert np.array_equal(view["hp_max"], hp_max)
        assert np.array_equal(view["reclaim_pct"], reclaim)
        assert list(view["degraded"]) == list(degraded)


# ---------------------------------------------------------------------------
# resilience: ladder + dispatch deadline around the colo pass
# ---------------------------------------------------------------------------

class TestColoLadder:
    def test_fault_retries_then_demotes_to_host_and_repromotes(self):
        from koordinator_tpu.scheduler.degrade import (
            LEVEL_FULL,
            LEVEL_HOST_FALLBACK,
        )

        store = _world(nodes=2)
        sched = Scheduler(store)
        mgr = Manager(store, scheduler=sched, colo="on")
        mgr.colo.ladder.promote_after = 2
        mgr.colo.ladder._base_promote_after = 2
        calls = {"n": 0}

        def boom():
            calls["n"] += 1
            raise RuntimeError("injected colo fault")

        mgr.colo.fault_injector = boom
        changes = mgr.colo.reconcile(now=NOW + 1)
        # retry once at-level, then demote straight to host fallback
        # (no mesh configured) — decisions still land
        assert calls["n"] == 2
        assert mgr.colo.ladder.level == LEVEL_HOST_FALLBACK
        assert mgr.colo.last_pass_stats["engine"] == "host"
        assert changes > 0
        assert store.get(KIND_NODE, "/n0").allocatable[BATCH_CPU] > 0
        # clean passes re-promote and the device engine returns
        mgr.colo.fault_injector = None
        mgr.colo.reconcile(now=NOW + 2)
        mgr.colo.reconcile(now=NOW + 3)
        mgr.colo.reconcile(now=NOW + 4)
        assert mgr.colo.ladder.level == LEVEL_FULL
        assert mgr.colo.last_pass_stats["engine"] == "device"

    def test_dispatch_deadline_overrun_abandons_and_demotes(self):
        import time as _time

        store = _world(nodes=2)
        sched = Scheduler(store)
        mgr = Manager(store, scheduler=sched, colo="on")
        mgr.colo.dispatch_deadline_seconds = 0.05
        mgr.colo.dispatch_watchdog.deadline_seconds = 0.05
        mgr.colo.sync_delay_injector = lambda: _time.sleep(0.4)
        dumps_before = mgr.colo.flight.dumps
        changes = mgr.colo.reconcile(now=NOW + 1)
        # two overruns (retry once, then demote) -> host oracle decisions
        assert mgr.colo.dispatch_watchdog.overruns == 2
        assert mgr.colo.flight.dumps >= dumps_before + 2
        assert mgr.colo.last_pass_stats["engine"] == "host"
        assert changes > 0

    def test_flight_dump_is_schema_valid(self, tmp_path):
        from koordinator_tpu.obs.flight import FlightRecorder, load_bundle

        store = _world(nodes=2)
        sched = Scheduler(store)
        mgr = Manager(store, scheduler=sched, colo="on")
        mgr.colo.flight = FlightRecorder(dump_dir=str(tmp_path))
        assert mgr.tick(now=NOW + 1)
        mgr.colo.flight.dump("colo_parity_mismatch")
        files = list(tmp_path.glob("*.jsonl"))
        assert len(files) == 1
        header, cycles, errors = load_bundle(
            files[0].read_text().splitlines())
        assert errors == []
        assert header["reason"] == "colo_parity_mismatch"
        assert cycles and "colo_device" in cycles[-1]["metrics"]

    def test_host_pin_and_ineligible_guard(self):
        store = _world(nodes=2)
        # a non-integer quota min demotes the pass per-pass (exactness
        # envelope), host oracle decisions intact
        store.add(KIND_ELASTIC_QUOTA, ElasticQuota(
            meta=ObjectMeta(name="frac", namespace="t"),
            min=ResourceList.of(cpu=1000, memory=GIB + 512 * 1024),
            max=ResourceList.of(cpu=2000, memory=2 * GIB)))
        sched = Scheduler(store)
        mgr = Manager(store, scheduler=sched, colo="on")
        assert mgr.tick(now=NOW + 1)
        assert mgr.colo.last_pass_stats["engine"] == "host-ineligible"
        assert store.get(KIND_NODE, "/n0").allocatable[BATCH_CPU] > 0


# ---------------------------------------------------------------------------
# the device quota fold vs compute_runtime_quotas
# ---------------------------------------------------------------------------

class TestDeviceQuotaFold:
    def _fold_pair(self, tree, total):
        import jax.numpy as jnp

        from koordinator_tpu.colo.step import device_runtime_quotas
        from koordinator_tpu.ops.quota import compute_runtime_quotas

        host = compute_runtime_quotas(tree, np.asarray(total, np.float32))
        G = len(tree.names)
        enable = (tree.enable_min_scale
                  if tree.enable_min_scale.shape[0] == G
                  else np.ones(G, bool))
        dev = device_runtime_quotas(
            jnp.asarray(tree.parent.astype(np.int32)),
            jnp.asarray(tree.level.astype(np.int32)),
            jnp.asarray(tree.min.astype(np.float32)),
            jnp.asarray(tree.max.astype(np.float32)),
            jnp.asarray(tree.shared_weight.astype(np.float32)),
            jnp.asarray(tree.guarantee.astype(np.float32)),
            jnp.asarray(tree.request.astype(np.float32)),
            jnp.asarray(enable),
            jnp.asarray(tree.allow_lent.astype(bool)),
            jnp.asarray(np.ones(G, bool)),
            jnp.asarray(np.asarray(total, np.float32)))
        return np.asarray(dev), host

    def _quota(self, name, min_cpu, max_cpu, parent=None, labels=None):
        labels = dict(labels or {})
        if parent:
            labels["quota.scheduling.koordinator.sh/parent"] = parent
        return ElasticQuota(
            meta=ObjectMeta(name=name, namespace="t", labels=labels),
            min=ResourceList.of(cpu=min_cpu, memory=min_cpu * 1024 * 1024),
            max=ResourceList.of(cpu=max_cpu, memory=max_cpu * 1024 * 1024))

    def test_scaled_min_path_is_exact(self):
        """AutoScaleMin fires when the cluster total drops below the
        root mins — the fold's one float64 site (floor(avail*min/sum))
        must match bit-for-bit via the int32 modular correction."""
        from koordinator_tpu.ops.quota import build_quota_tree

        quotas = [
            self._quota("sa", 7_000, 50_000),
            self._quota("sb", 9_000, 50_000),
            self._quota("sc", 5_000, 50_000),
        ]
        requests = {
            "sa": ResourceList.of(cpu=30_000, memory=3000 * 1024 * 1024
                                  ).to_vector(),
            "sb": ResourceList.of(cpu=10_000, memory=900 * 1024 * 1024
                                  ).to_vector(),
            "sc": ResourceList.of(cpu=2_000, memory=100 * 1024 * 1024
                                  ).to_vector(),
        }
        tree = build_quota_tree(quotas, pod_requests_by_quota=requests)
        # total BELOW the min sum (21000): scaling must engage, and the
        # 13k/21k proportions exercise non-trivial floors
        for total_cpu in (13_001, 13_003, 20_999, 21_000, 1, 6_999):
            total = np.zeros_like(tree.min[0])
            total[RESOURCE_INDEX[ResourceName.CPU]] = total_cpu
            total[RESOURCE_INDEX[ResourceName.MEMORY]] = total_cpu
            dev, host = self._fold_pair(tree, total)
            assert np.array_equal(dev, host), total_cpu

    def test_water_fill_and_tree_levels(self):
        from koordinator_tpu.ops.quota import build_quota_tree

        quotas = [
            self._quota("root", 10_000, 40_000,
                        labels={"quota.scheduling.koordinator.sh/"
                                "is-parent": "true"}),
            self._quota("wa", 4_000, 30_000, parent="root"),
            self._quota("wb", 6_000, 30_000, parent="root",
                        labels={"quota.scheduling.koordinator.sh/"
                                "allow-lent-resource": "false"}),
        ]
        requests = {
            "wa": ResourceList.of(cpu=25_000,
                                  memory=2500 * 1024 * 1024).to_vector(),
            "wb": ResourceList.of(cpu=1_000,
                                  memory=100 * 1024 * 1024).to_vector(),
        }
        tree = build_quota_tree(quotas, pod_requests_by_quota=requests)
        total = np.zeros_like(tree.min[0])
        total[RESOURCE_INDEX[ResourceName.CPU]] = 100_000
        total[RESOURCE_INDEX[ResourceName.MEMORY]] = 100_000
        dev, host = self._fold_pair(tree, total)
        assert np.array_equal(dev, host)

    def test_exact_floordiv_unit(self):
        import jax.numpy as jnp

        from koordinator_tpu.colo.step import _exact_floordiv

        rng = np.random.default_rng(7)
        a = rng.integers(0, 2 ** 24, size=512).astype(np.float32)
        s = rng.integers(1, 2 ** 24, size=512).astype(np.float32)
        m = (s * rng.random(512)).astype(np.int64).astype(np.float32)
        got = np.asarray(_exact_floordiv(
            jnp.asarray(a), jnp.asarray(m), jnp.asarray(s)))
        want = (a.astype(np.int64) * m.astype(np.int64)
                // s.astype(np.int64)).astype(np.float32)
        assert np.array_equal(got, want)


# ---------------------------------------------------------------------------
# epoch memos + the revoke loop consuming the device mask
# ---------------------------------------------------------------------------

class TestRuntimeMemoAndRevoke:
    def _quota_world(self):
        store = _world(nodes=2)
        store.add(KIND_ELASTIC_QUOTA, ElasticQuota(
            meta=ObjectMeta(name="qa", namespace="t"),
            min=ResourceList.of(cpu=1000, memory=GIB),
            max=ResourceList.of(cpu=2000, memory=2 * GIB)))
        store.add(KIND_POD, Pod(
            meta=ObjectMeta(name="hog", namespace="t", uid="hog",
                            owner_kind="ReplicaSet", owner_name="rs",
                            labels={LABEL_QUOTA_NAME: "qa"}),
            spec=PodSpec(node_name="n0", priority=9500,
                         requests=ResourceList.of(cpu=6000,
                                                  memory=6 * GIB)),
            phase="Running"))
        return store

    def test_runtime_memo_hits_on_unchanged_epochs(self, monkeypatch):
        store = self._quota_world()
        sched = Scheduler(store)
        plugin = sched.extender.plugin("ElasticQuota")
        import koordinator_tpu.scheduler.plugins.elasticquota as eq
        from koordinator_tpu.ops import quota as quota_ops

        calls = {"n": 0}
        real = quota_ops.compute_runtime_quotas

        def counted(*a, **k):
            calls["n"] += 1
            return real(*a, **k)

        monkeypatch.setattr(quota_ops, "compute_runtime_quotas", counted)
        assert eq  # silence linters
        plugin.tree_snapshot(store)
        plugin.tree_snapshot(store)
        plugin.tree_snapshot(store)
        assert calls["n"] == 1  # memoized on (tree, state, node) epochs
        # an update that does NOT move used/pending keeps the memo
        pod = store.get(KIND_POD, "t/hog")
        store.update(KIND_POD, pod)
        plugin.tree_snapshot(store)
        assert calls["n"] == 1
        # a quota member leaving moves the state epoch -> recompute
        store.delete(KIND_POD, "t/hog")
        plugin.tree_snapshot(store)
        assert calls["n"] == 2
        # a node event moves the cluster total -> recompute
        node = store.get(KIND_NODE, "/n0")
        store.update(KIND_NODE, node)
        plugin.tree_snapshot(store)
        assert calls["n"] == 3

    def test_revoke_consumes_device_mask(self):
        store = self._quota_world()
        sched = Scheduler(store)
        mgr = Manager(store, scheduler=sched, colo="on")
        plugin = sched.extender.plugin("ElasticQuota")
        args = dataclasses.replace(
            sched.config.elastic_quota, monitor_all_quotas=True,
            delay_evict_time_seconds=5.0,
            revoke_pod_interval_seconds=1.0)
        ctl = plugin.revoke_controller(store, args)
        assert mgr.tick(now=NOW + 1)
        dr = plugin.fresh_device_runtime()
        assert dr is not None
        assert bool(dr[4][dr[1].index("qa")])  # the device revoke mask
        assert ctl.reconcile(NOW + 1) == []    # grace window
        assert mgr.tick(now=NOW + 20)
        assert plugin.fresh_device_runtime() is not None
        evicted = ctl.reconcile(NOW + 20)
        assert evicted == ["t/hog"]
        # the eviction itself moved the epochs: stale publish withdrawn
        assert plugin.fresh_device_runtime() is None


# ---------------------------------------------------------------------------
# config hot-reload -> policy scalars, without a step-cache leak
# ---------------------------------------------------------------------------

class TestConfigHotReload:
    @staticmethod
    def _set_cm(store, data):
        key = "koordinator-system/slo-controller-config"
        cm = store.get(KIND_CONFIG_MAP, key)
        if cm is None:
            store.add(KIND_CONFIG_MAP, ConfigMap(
                meta=ObjectMeta(name="slo-controller-config",
                                namespace="koordinator-system"),
                data=data))
        else:
            cm.data = data
            store.update(KIND_CONFIG_MAP, cm)

    def _cm_data(self, reclaim):
        return {"colocation-config": json.dumps(
            {"cpuReclaimThresholdPercent": reclaim})}

    def test_hot_reload_reaches_policy_scalars(self):
        store = _world(nodes=2, usage_cpu=0)
        sched = Scheduler(store)
        mgr = Manager(store, scheduler=sched, colo="on")
        assert mgr.tick(now=NOW + 1)
        batch_60 = store.get(KIND_NODE, "/n0").allocatable[BATCH_CPU]
        assert batch_60 == 9600  # 16000 * 60%
        self._set_cm(store, self._cm_data(25))
        assert mgr.tick(now=NOW + 20)
        assert mgr.colo.last_pass_stats["engine"] == "device"
        assert store.get(KIND_NODE, "/n0").allocatable[BATCH_CPU] == 4000

    def test_invalid_update_keeps_last_good_config(self):
        store = _world(nodes=2, usage_cpu=0)
        sched = Scheduler(store)
        mgr = Manager(store, scheduler=sched, colo="on")
        self._set_cm(store, self._cm_data(25))
        assert mgr.tick(now=NOW + 1)
        assert store.get(KIND_NODE, "/n0").allocatable[BATCH_CPU] == 4000
        # a malformed update must NOT revert to the 60% default: the
        # last good config (25%) stays effective
        self._set_cm(store, {"colocation-config": "{not json"})
        assert mgr.tick(now=NOW + 20)
        assert store.get(KIND_NODE, "/n0").allocatable[BATCH_CPU] == 4000
        # an out-of-range value is equally held off
        self._set_cm(store, {"colocation-config": json.dumps(
            {"cpuReclaimThresholdPercent": 900})})
        assert mgr.tick(now=NOW + 40)
        assert store.get(KIND_NODE, "/n0").allocatable[BATCH_CPU] == 4000

    def test_node_update_with_fresh_instance_reaches_the_pass(self):
        """store.update may swap in a NEW node object: the pack must
        re-anchor its table entry so the fresh labels reach the device
        pass and the writeback mutates the live object."""
        import copy

        store = _world(nodes=2, usage_cpu=0)
        sched = Scheduler(store)
        mgr = Manager(store, scheduler=sched, colo="on")
        assert mgr.tick(now=NOW + 1)
        assert store.get(KIND_NODE, "/n0").allocatable[BATCH_CPU] == 9600
        fresh = copy.deepcopy(store.get(KIND_NODE, "/n0"))
        fresh.meta.labels[
            "node.koordinator.sh/cpu-reclaim-ratio"] = "0.25"
        store.update(KIND_NODE, fresh)
        assert mgr.tick(now=NOW + 20)
        assert mgr.colo.last_pass_stats["engine"] == "device"
        assert store.get(KIND_NODE, "/n0").allocatable[BATCH_CPU] == 4000

    def test_no_step_cache_leak_on_config_flips(self):
        store = _world(nodes=2, usage_cpu=0)
        sched = Scheduler(store)
        mgr = Manager(store, scheduler=sched, colo="on")
        assert mgr.tick(now=NOW + 1)
        size_after_first = len(mgr.colo._step_cache)
        # repeated threshold flips change VALUES, not shapes/policies:
        # the compiled step must be reused every time
        for i, reclaim in enumerate((25, 60, 25, 60, 25, 60)):
            self._set_cm(store, self._cm_data(reclaim))
            assert mgr.tick(now=NOW + 30 + i * 10)
        assert len(mgr.colo._step_cache) == size_after_first
        # a calculate-policy flip keys ONE new entry, then flip-flopping
        # reuses both compiled steps (shape-keyed recompile pinned)
        policy_data = {"colocation-config": json.dumps(
            {"cpuReclaimThresholdPercent": 60,
             "cpuCalculatePolicy": "request"})}
        self._set_cm(store, policy_data)
        assert mgr.tick(now=NOW + 200)
        grown = len(mgr.colo._step_cache)
        assert grown == size_after_first + 1
        for i in range(4):
            self._set_cm(store, self._cm_data(60))
            assert mgr.tick(now=NOW + 300 + i * 20)
            self._set_cm(store, policy_data)
            assert mgr.tick(now=NOW + 310 + i * 20)
        assert len(mgr.colo._step_cache) == grown
