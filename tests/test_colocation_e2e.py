"""The cross-component colocation control loop (SURVEY §3.3), end to end in
one store: koordlet metrics -> NodeMetric CR -> koord-manager noderesource
controller -> node batch allocatable -> admission webhook BE mutation ->
scheduler placement on the batch axes -> koordlet runtimehooks cgroup
enforcement. Every component is the real one; only the kernel interfaces
(FakeFS) are synthetic."""

import pytest

from koordinator_tpu.api.objects import (
    LABEL_POD_QOS,
    ClusterColocationProfile,
    Node,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.api.resources import ResourceList, ResourceName
from koordinator_tpu.client.store import (
    KIND_COLOCATION_PROFILE,
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_POD,
    ObjectStore,
)
from koordinator_tpu.koordlet.daemon import Daemon
from koordinator_tpu.koordlet.util import system as sysutil
from koordinator_tpu.koordlet.util.system import FakeFS
from koordinator_tpu.manager import Manager
from koordinator_tpu.scheduler.cycle import Scheduler

GIB = 1024**3
NOW = 1_000_000.0


@pytest.fixture
def fs():
    f = FakeFS(use_cgroup_v2=True)
    yield f
    f.cleanup()


def test_batch_colocation_loop(fs):
    store = ObjectStore()
    store.add(KIND_NODE, Node(
        meta=ObjectMeta(name="node-0", namespace=""),
        allocatable=ResourceList.of(cpu=16_000, memory=64 * GIB, pods=110),
    ))
    fs.set_proc("stat", "cpu  1000 0 1000 8000 0 0 0 0 0 0\n")
    fs.set_proc(
        "meminfo",
        "MemTotal: %d kB\nMemFree: %d kB\nMemAvailable: %d kB\n"
        % (64 * GIB // 1024, 48 * GIB // 1024, 56 * GIB // 1024),
    )

    # one latency-sensitive pod burning ~2 cores
    ls = Pod(
        meta=ObjectMeta(name="web", uid="web", labels={LABEL_POD_QOS: "LS"}),
        spec=PodSpec(node_name="node-0",
                     requests=ResourceList.of(cpu=4000, memory=8 * GIB),
                     limits=ResourceList.of(cpu=4000, memory=8 * GIB)),
        phase="Running",
    )
    store.add(KIND_POD, ls)
    ls_rel = fs.config.pod_relative_path("", "web")
    fs.set_cgroup(ls_rel, sysutil.CPU_STAT, "usage_usec 10000000\n")
    fs.set_cgroup(ls_rel, sysutil.MEMORY_USAGE, str(4 * GIB))

    # ---- 1. koordlet reports node metrics over two ticks
    daemon = Daemon(store, "node-0", fs.config, report_interval_seconds=0)
    daemon.run_once(now=NOW)
    fs.set_proc("stat", "cpu  2000 0 2000 12000 0 0 0 0 0 0\n")  # 25% busy
    fs.set_cgroup(ls_rel, sysutil.CPU_STAT, "usage_usec 30000000\n")
    daemon.run_once(now=NOW + 10)
    assert store.get(KIND_NODE_METRIC, "/node-0") is not None

    # ---- 2. koord-manager (leader) computes batch allocatable
    manager = Manager(store, identity="mgr-0")
    assert manager.tick(now=NOW + 11)
    node = store.get(KIND_NODE, "/node-0")
    batch_cpu = node.allocatable[ResourceName.BATCH_CPU]
    batch_mem = node.allocatable[ResourceName.BATCH_MEMORY]
    assert batch_cpu > 0 and batch_mem > 0
    assert batch_cpu < 16_000  # reclaimed = capacity - reserved - LS usage

    # ---- 3. a colocation profile turns incoming spark pods into BE batch
    store.add(KIND_COLOCATION_PROFILE, ClusterColocationProfile(
        meta=ObjectMeta(name="spark"),
        selector={"app": "spark"},
        qos_class=QoSClass.BE,
        priority_class_name="koord-batch",
        scheduler_name="koord-scheduler",
    ))
    spark = Pod(
        meta=ObjectMeta(name="spark-exec", uid="spark-exec",
                        labels={"app": "spark"}, creation_timestamp=NOW + 11),
        spec=PodSpec(requests=ResourceList.of(cpu=2000, memory=4 * GIB),
                     limits=ResourceList.of(cpu=2000, memory=4 * GIB)),
    )
    store.add(KIND_POD, spark)  # admission interceptor mutates on the way in
    stored = store.get(KIND_POD, "default/spark-exec")
    assert stored.qos_class is QoSClass.BE
    assert stored.spec.requests[ResourceName.CPU] == 0
    assert stored.spec.requests[ResourceName.BATCH_CPU] == 2000
    assert stored.spec.requests[ResourceName.BATCH_MEMORY] == 4 * GIB

    # ---- 4. the scheduler places it using the batch axes the controller
    # just published
    result = Scheduler(store).run_cycle(now=NOW + 12)
    assert [b.pod_key for b in result.bound] == ["default/spark-exec"]
    assert result.bound[0].node_name == "node-0"

    # ---- 5. koordlet enforces the batch limits on the pod's cgroup
    bound = store.get(KIND_POD, "default/spark-exec")
    bound.phase = "Running"
    store.update(KIND_POD, bound)
    be_rel = fs.config.pod_relative_path(sysutil.QOS_BESTEFFORT, "spark-exec")
    fs.set_cgroup(be_rel, sysutil.CPU_STAT, "usage_usec 0\n")
    fs.set_cgroup(be_rel, sysutil.MEMORY_USAGE, "0")
    daemon.run_once(now=NOW + 20)
    quota = daemon.executor.read(be_rel, sysutil.CPU_CFS_QUOTA)
    assert quota is not None
    assert int(quota) == 2000 // 1000 * 100000  # batch-cpu -> cfs quota
    mem_limit = daemon.executor.read(be_rel, sysutil.MEMORY_LIMIT)
    assert int(mem_limit) == 4 * GIB
    # group identity: BE tier bvt
    bvt = daemon.executor.read(be_rel, sysutil.CPU_BVT_WARP_NS)
    assert bvt == "-1"


def test_batch_capacity_constrains_scheduling(fs):
    """A BE pod larger than the reclaimed batch capacity must NOT schedule,
    even though raw node cpu would fit it."""
    store = ObjectStore()
    store.add(KIND_NODE, Node(
        meta=ObjectMeta(name="node-0", namespace=""),
        allocatable=ResourceList.of(cpu=16_000, memory=64 * GIB, pods=110),
    ))
    fs.set_proc("stat", "cpu  1000 0 1000 8000 0 0 0 0 0 0\n")
    fs.set_proc(
        "meminfo",
        "MemTotal: %d kB\nMemFree: %d kB\nMemAvailable: %d kB\n"
        % (64 * GIB // 1024, 48 * GIB // 1024, 56 * GIB // 1024),
    )
    daemon = Daemon(store, "node-0", fs.config, report_interval_seconds=0)
    daemon.run_once(now=NOW)
    fs.set_proc("stat", "cpu  3000 0 3000 12000 0 0 0 0 0 0\n")  # 50% busy
    daemon.run_once(now=NOW + 10)
    manager = Manager(store, identity="mgr-0")
    assert manager.tick(now=NOW + 11)
    node = store.get(KIND_NODE, "/node-0")
    batch_cpu = node.allocatable[ResourceName.BATCH_CPU]
    assert 0 < batch_cpu < 8000

    hungry = Pod(
        meta=ObjectMeta(name="hungry", uid="hungry",
                        labels={LABEL_POD_QOS: "BE",
                                "koordinator.sh/priority-class": "koord-batch"},
                        creation_timestamp=NOW + 11),
        spec=PodSpec(requests=ResourceList.of(batch_cpu=12_000),
                     priority=5500),
    )
    store.add(KIND_POD, hungry)
    result = Scheduler(store).run_cycle(now=NOW + 12)
    assert result.bound == []
    assert "default/hungry" in result.failed
