"""Quota water-filling + gang permit kernels vs scalar transcriptions of the
reference algorithms (runtime_quota_calculator.go:111-168, core/core.go:311-338)."""

import math

import numpy as np
import pytest

from koordinator_tpu.api.objects import (
    LABEL_POD_GROUP,
    LABEL_QUOTA_PARENT,
    ElasticQuota,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.api.resources import RESOURCE_INDEX, ResourceList, ResourceName
from koordinator_tpu.ops.quota import (
    MAX_QUOTA_DEPTH,
    build_quota_tree,
    compute_runtime_quotas,
)

CPU = RESOURCE_INDEX[ResourceName.CPU]
MEM = RESOURCE_INDEX[ResourceName.MEMORY]


def scalar_redistribution(children, total):
    """Direct transcription of quotaTree.redistribution (Go int64 semantics)."""
    runtime = [0.0] * len(children)
    adjustable, total_w, left = [], 0.0, total
    for i, c in enumerate(children):
        m = max(c["min"], c.get("guarantee", 0.0))
        if c["request"] > m:
            runtime[i] = m
            adjustable.append(i)
            total_w += c["weight"]
        else:
            runtime[i] = c["request"] if c.get("allow_lent", True) else m
        left -= runtime[i]

    def iterate(left, total_w, nodes):
        if total_w <= 0:
            return
        nxt, nxt_w, nxt_left = [], 0.0, 0.0
        for i in nodes:
            delta = math.floor(children[i]["weight"] * left / total_w + 0.5)
            runtime[i] += delta
            if runtime[i] < children[i]["request"]:
                nxt.append(i)
                nxt_w += children[i]["weight"]
            else:
                nxt_left += runtime[i] - children[i]["request"]
                runtime[i] = children[i]["request"]
        if nxt_left > 0 and nxt:
            iterate(nxt_left, nxt_w, nxt)

    if left > 0:
        iterate(left, total_w, adjustable)
    return runtime


def _quota(name, cpu_min, cpu_max, parent="", weight=None):
    meta = ObjectMeta(name=name)
    if parent:
        meta.labels[LABEL_QUOTA_PARENT] = parent
    if weight is not None:
        import json

        meta.annotations[
            "quota.scheduling.koordinator.sh/shared-weight"
        ] = json.dumps({"cpu": str(weight // 1000)})
    return ElasticQuota(
        meta=meta,
        min=ResourceList.of(cpu=cpu_min),
        max=ResourceList.of(cpu=cpu_max),
    )


class TestWaterFilling:
    @pytest.mark.parametrize(
        "mins,requests,weights,total",
        [
            ([10000, 20000, 30000], [50000, 40000, 10000], [10000, 20000, 30000], 100000),
            ([0, 0, 0], [70000, 50000, 30000], [1000, 1000, 2000], 100000),
            ([40000, 40000], [100000, 5000], [1000, 1000], 100000),
            ([10000], [5000], [1000], 100000),
            ([30000, 30000, 30000, 30000], [90000, 10000, 50000, 0], [3000, 1000, 2000, 1000], 120000),
            # zero-delta sibling: A's huge weight rounds B's round-1 delta to 0;
            # B must still receive A's recycled overshoot in round 2
            ([0, 0], [10, 100], [100000, 1], 50),
        ],
    )
    def test_single_parent_matches_scalar(self, mins, requests, weights, total):
        quotas = [
            _quota(f"q{i}", mins[i], 10 * total, weight=weights[i])
            for i in range(len(mins))
        ]
        req_by = {
            f"q{i}": ResourceList.of(cpu=requests[i]).to_vector()
            for i in range(len(mins))
        }
        tree = build_quota_tree(quotas, pod_requests_by_quota=req_by)
        runtime = compute_runtime_quotas(
            tree, ResourceList.of(cpu=total).to_vector()
        )
        children = [
            {"min": float(mins[i]), "request": float(requests[i]),
             "weight": float(weights[i])}
            for i in range(len(mins))
        ]
        expected = scalar_redistribution(children, float(total))
        np.testing.assert_allclose(runtime[:, CPU], expected, atol=0.5)

    def test_non_lent_sibling_keeps_min_through_redistribution(self):
        # q0 over-requests and iterates; q1 (allow-lent=false) under-requests
        # but must keep runtime = min, not be clamped to its request
        # (runtime_quota_calculator.go:128-134)
        from koordinator_tpu.api.objects import LABEL_QUOTA_ALLOW_LENT

        q0 = _quota("q0", 10000, 1000000, weight=10000)
        q1 = _quota("q1", 40000, 1000000, weight=10000)
        q1.meta.labels[LABEL_QUOTA_ALLOW_LENT] = "false"
        req_by = {
            "q0": ResourceList.of(cpu=90000).to_vector(),
            "q1": ResourceList.of(cpu=5000).to_vector(),
        }
        tree = build_quota_tree([q0, q1], pod_requests_by_quota=req_by)
        runtime = compute_runtime_quotas(
            tree, ResourceList.of(cpu=100000).to_vector()
        )
        assert runtime[1, CPU] == 40000.0  # non-lent keeps its min
        assert runtime[0, CPU] == 60000.0  # the rest goes to the over-requester

    def test_guarantee_raises_effective_min(self):
        import json

        from koordinator_tpu.api.objects import ANNOTATION_QUOTA_GUARANTEED

        q0 = _quota("q0", 10000, 1000000, weight=10000)
        q0.meta.annotations[ANNOTATION_QUOTA_GUARANTEED] = json.dumps(
            {"cpu": "30"}
        )
        q1 = _quota("q1", 10000, 1000000, weight=10000)
        req_by = {
            "q0": ResourceList.of(cpu=100000).to_vector(),
            "q1": ResourceList.of(cpu=100000).to_vector(),
        }
        tree = build_quota_tree([q0, q1], pod_requests_by_quota=req_by)
        runtime = compute_runtime_quotas(
            tree, ResourceList.of(cpu=40000).to_vector()
        )
        # guarantee=30000 floors q0's base; q1 starts at min=10000 and the
        # leftover 0 means bases stand
        assert runtime[0, CPU] == 30000.0
        assert runtime[1, CPU] == 10000.0

    def test_hierarchy_parent_runtime_feeds_children(self):
        quotas = [
            _quota("root-a", 40000, 200000, weight=1000),
            _quota("root-b", 40000, 200000, weight=1000),
            _quota("leaf-a1", 10000, 200000, parent="root-a", weight=1000),
            _quota("leaf-a2", 10000, 200000, parent="root-a", weight=3000),
        ]
        req_by = {
            "leaf-a1": ResourceList.of(cpu=60000).to_vector(),
            "leaf-a2": ResourceList.of(cpu=60000).to_vector(),
            "root-b": ResourceList.of(cpu=20000).to_vector(),
        }
        tree = build_quota_tree(quotas, pod_requests_by_quota=req_by)
        # parent request aggregates children
        assert tree.request[tree.index["root-a"], CPU] == 120000
        runtime = compute_runtime_quotas(
            tree, ResourceList.of(cpu=100000).to_vector()
        )
        roots = scalar_redistribution(
            [
                {"min": 40000.0, "request": 120000.0, "weight": 1000.0},
                {"min": 40000.0, "request": 20000.0, "weight": 1000.0},
            ],
            100000.0,
        )
        assert runtime[tree.index["root-a"], CPU] == pytest.approx(roots[0], abs=0.5)
        assert runtime[tree.index["root-b"], CPU] == pytest.approx(roots[1], abs=0.5)
        leaves = scalar_redistribution(
            [
                {"min": 10000.0, "request": 60000.0, "weight": 1000.0},
                {"min": 10000.0, "request": 60000.0, "weight": 3000.0},
            ],
            roots[0],
        )
        assert runtime[tree.index["leaf-a1"], CPU] == pytest.approx(leaves[0], abs=0.5)
        assert runtime[tree.index["leaf-a2"], CPU] == pytest.approx(leaves[1], abs=0.5)

    def test_limit_request_capping(self):
        """A child's request contribution is capped at its max (limitRequest,
        quota_info.go:196-201): an over-max group must not soak up leftover its
        sibling should receive."""
        quotas = [
            _quota("a", 0, 10000, weight=1000),
            _quota("b", 0, 100000, weight=1000),
        ]
        tree = build_quota_tree(
            quotas,
            pod_requests_by_quota={
                "a": ResourceList.of(cpu=80000).to_vector(),
                "b": ResourceList.of(cpu=60000).to_vector(),
            },
        )
        runtime = compute_runtime_quotas(tree, ResourceList.of(cpu=100000).to_vector())
        assert runtime[tree.index["a"], CPU] == 10000.0
        # b gets the rest of its request, not starved by a's phantom demand
        assert runtime[tree.index["b"], CPU] == 60000.0

    def test_runtime_capped_by_max(self):
        quotas = [_quota("q0", 0, 30000, weight=1000)]
        tree = build_quota_tree(
            quotas,
            pod_requests_by_quota={"q0": ResourceList.of(cpu=80000).to_vector()},
        )
        runtime = compute_runtime_quotas(tree, ResourceList.of(cpu=100000).to_vector())
        assert runtime[0, CPU] == 30000.0

    def test_ancestor_chain(self):
        quotas = [
            _quota("r", 0, 10**9),
            _quota("m", 0, 10**9, parent="r"),
            _quota("l", 0, 10**9, parent="m"),
        ]
        tree = build_quota_tree(quotas)
        li = tree.index["l"]
        chain = [g for g in tree.ancestors[li] if g >= 0]
        assert chain == [tree.index["l"], tree.index["m"], tree.index["r"]]
        assert tree.level[li] == 2


class TestQuotaAdmission:
    def test_admit_and_use(self):
        import jax.numpy as jnp

        from koordinator_tpu.ops.quota import quota_admit_row, quota_used_add_row

        quotas = [
            _quota("root", 0, 10**9),
            _quota("leaf", 0, 10**9, parent="root"),
        ]
        tree = build_quota_tree(quotas)
        runtime = np.zeros_like(tree.min)
        runtime[tree.index["root"], CPU] = 10000
        runtime[tree.index["leaf"], CPU] = 6000
        used = jnp.asarray(tree.used)
        req = jnp.asarray(ResourceList.of(cpu=4000).to_vector())
        leaf = jnp.int32(tree.index["leaf"])
        anc = jnp.asarray(tree.ancestors)
        rt = jnp.asarray(runtime)

        assert bool(quota_admit_row(req, leaf, anc, used, rt))
        used = quota_used_add_row(used, req, leaf, anc, jnp.bool_(True))
        # second 4000 exceeds leaf runtime 6000
        assert not bool(quota_admit_row(req, leaf, anc, used, rt))
        # no-quota pod always admitted
        assert bool(quota_admit_row(req, jnp.int32(-1), anc, used, rt))
        # root usage aggregated
        assert float(used[tree.index["root"], CPU]) == 4000.0


class TestGangPermit:
    def test_permit_barrier(self):
        import jax.numpy as jnp

        from koordinator_tpu.ops.gang import gang_permit_mask

        # gang 0 (min 2): both assigned -> pass; gang 1 (min 3): 1 assigned -> fail
        chosen = jnp.asarray([0, 1, 2, -1, 5], jnp.int32)
        gang_id = jnp.asarray([0, 0, 1, 1, -1], jnp.int32)
        keep = gang_permit_mask(
            chosen,
            gang_id,
            gang_min_member=jnp.asarray([2.0, 3.0]),
            gang_assumed=jnp.asarray([0.0, 0.0]),
            gang_group_id=jnp.asarray([0, 1], jnp.int32),
            num_gangs=2,
            num_groups=2,
        )
        assert list(np.asarray(keep)) == [True, True, False, False, True]

    def test_gang_group_all_or_nothing(self):
        import jax.numpy as jnp

        from koordinator_tpu.ops.gang import gang_permit_mask

        # two gangs in one group; gang 1 fails -> gang 0 members struck too
        chosen = jnp.asarray([0, 1, -1], jnp.int32)
        gang_id = jnp.asarray([0, 0, 1], jnp.int32)
        keep = gang_permit_mask(
            chosen,
            gang_id,
            gang_min_member=jnp.asarray([2.0, 1.0]),
            gang_assumed=jnp.asarray([0.0, 0.0]),
            gang_group_id=jnp.asarray([0, 0], jnp.int32),
            num_gangs=2,
            num_groups=1,
        )
        assert list(np.asarray(keep)) == [False, False, False]

    def test_assumed_members_count(self):
        import jax.numpy as jnp

        from koordinator_tpu.ops.gang import gang_permit_mask

        # min 3, 2 already assumed before the batch, 1 assigned now -> pass
        keep = gang_permit_mask(
            jnp.asarray([4], jnp.int32),
            jnp.asarray([0], jnp.int32),
            gang_min_member=jnp.asarray([3.0]),
            gang_assumed=jnp.asarray([2.0]),
            gang_group_id=jnp.asarray([0], jnp.int32),
            num_gangs=1,
            num_groups=1,
        )
        assert bool(keep[0])


class TestQueueSortGangGrouping:
    def test_gang_members_pack_contiguously(self):
        """coscheduling.go:118 Less: equal-priority gang members group by
        their GANG's creation/name, not their own creation time, so a gang
        never interleaves with unrelated pods in the queue."""
        from koordinator_tpu.ops.loadaware import LoadAwareArgs
        from koordinator_tpu.ops.packing import pack_pods

        args = LoadAwareArgs()
        old_gang = (100.0, "default/old-gang")
        new_gang = (300.0, "default/new-gang")
        pods = []

        def add(name, ts, gang=None, prio=5000):
            pod = Pod(
                meta=ObjectMeta(name=name, creation_timestamp=ts,
                                labels=({LABEL_POD_GROUP: gang} if gang else {})),
                spec=PodSpec(priority=prio,
                             requests=ResourceList.of(cpu=1000)),
            )
            pods.append(pod)

        # interleaved creation times across two gangs + loose pods
        add("o1", 110.0, gang="old-gang")
        add("loose-early", 50.0)
        add("n1", 310.0, gang="new-gang")
        add("o2", 400.0, gang="old-gang")  # created late, still groups early
        add("n2", 305.0, gang="new-gang")
        add("loose-late", 500.0)
        add("vip", 999.0, prio=9000)       # priority still dominates

        packed = pack_pods(
            pods, args.resource_weights, args.estimated_scaling_factors,
            gang_sort={"default/old-gang": old_gang, "default/new-gang": new_gang},
        )
        names = [k.split("/")[1] for k in packed.keys]
        assert names == ["vip", "loose-early", "o1", "o2", "n2", "n1",
                         "loose-late"]

    def test_same_named_gangs_in_different_namespaces_are_distinct(self):
        """Gang identity is namespace/name (core.go GetGangFullName): a gang
        'g' in namespace a and a gang 'g' in namespace b must not share
        min-member accounting or queue grouping."""
        import jax

        jax.config.update("jax_platforms", "cpu")
        from koordinator_tpu.api.objects import Node, PodGroup
        from koordinator_tpu.client.store import (
            KIND_NODE,
            KIND_POD,
            KIND_POD_GROUP,
            ObjectStore,
        )
        from koordinator_tpu.scheduler.cycle import Scheduler

        store = ObjectStore()
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name="n0", namespace=""),
            allocatable=ResourceList.of(cpu=16000, memory=64 << 30, pods=20),
        ))
        now = 1_000_000.0
        # ns-a gang needs 2 and has 2 -> schedules; ns-b gang (same bare
        # name!) needs 3 and has 1 -> must NOT ride ns-a's count
        store.add(KIND_POD_GROUP, PodGroup(
            meta=ObjectMeta(name="g", namespace="a", creation_timestamp=now),
            min_member=2))
        store.add(KIND_POD_GROUP, PodGroup(
            meta=ObjectMeta(name="g", namespace="b", creation_timestamp=now),
            min_member=3))
        for ns, name in (("a", "m0"), ("a", "m1"), ("b", "m0")):
            store.add(KIND_POD, Pod(
                meta=ObjectMeta(name=name, namespace=ns, uid=f"{ns}-{name}",
                                creation_timestamp=now,
                                labels={LABEL_POD_GROUP: "g"}),
                spec=PodSpec(requests=ResourceList.of(cpu=1000,
                                                      memory=1 << 30)),
            ))
        result = Scheduler(store).run_cycle(now=now)
        bound = {b.pod_key for b in result.bound}
        assert bound == {"a/m0", "a/m1"}
        assert set(result.rejected) == {"b/m0"}
