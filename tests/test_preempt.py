"""ElasticQuota PostFilter preemption (ref preempt.go): a starved
higher-priority pod reclaims quota from lower-priority same-group members
within ONE scheduling cycle."""

import numpy as np

from koordinator_tpu.api.objects import (
    LABEL_POD_QOS,
    LABEL_QUOTA_NAME,
    ElasticQuota,
    Node,
    NodeMetric,
    NodeMetricInfo,
    ObjectMeta,
    Pod,
    PodDisruptionBudget,
    PodSpec,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import (
    KIND_ELASTIC_QUOTA,
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_PDB,
    KIND_POD,
    ObjectStore,
)
from koordinator_tpu.scheduler.cycle import Scheduler
from koordinator_tpu.scheduler.preempt import LABEL_PREEMPTIBLE

GIB = 1024**3
NOW = 1_000_000.0


def _store(num_nodes=2, cores=16):
    store = ObjectStore()
    for i in range(num_nodes):
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name=f"node-{i}", namespace=""),
            allocatable=ResourceList.of(
                cpu=cores * 1000, memory=64 * GIB, pods=110),
        ))
        store.add(KIND_NODE_METRIC, NodeMetric(
            meta=ObjectMeta(name=f"node-{i}", namespace=""),
            update_time=NOW - 10,
            node_metric=NodeMetricInfo(
                node_usage=ResourceList.of(cpu=1000, memory=2 * GIB)),
        ))
    return store


def _quota(store, name="team-a", cpu=4000, mem=16 * GIB, min_cpu=4000):
    store.add(KIND_ELASTIC_QUOTA, ElasticQuota(
        meta=ObjectMeta(name=name, namespace="default"),
        min=ResourceList.of(cpu=min_cpu, memory=mem),
        max=ResourceList.of(cpu=cpu, memory=mem),
    ))


def _pod(store, name, cpu=1000, prio=9500, quota="team-a", node=None,
         labels=None, created=NOW - 100.0):
    pod = Pod(
        meta=ObjectMeta(
            name=name,
            labels={LABEL_POD_QOS: "LS", LABEL_QUOTA_NAME: quota,
                    **(labels or {})},
            creation_timestamp=created,
        ),
        spec=PodSpec(priority=prio,
                     requests=ResourceList.of(cpu=cpu, memory=GIB)),
    )
    if node is not None:
        pod.spec.node_name = node
        pod.phase = "Running"
    store.add(KIND_POD, pod)
    return pod


class TestQuotaPreemption:
    def test_starved_high_priority_pod_reclaims_in_one_cycle(self):
        store = _store()
        _quota(store, cpu=4000)
        sched = Scheduler(store)
        # fill the group's quota with low-priority members
        for i in range(4):
            _pod(store, f"low-{i}", cpu=1000, prio=6000, node="node-0")
        # a higher-priority pod arrives with zero quota headroom
        high = _pod(store, "high", cpu=2000, prio=9500)
        result = sched.run_cycle(now=NOW)
        # preemption evicted enough low-prio members and bound the pod
        assert any(b.pod_key == high.meta.key for b in result.bound)
        assert len(result.preempted_victims) == 2
        assert not result.rejected
        for key in result.preempted_victims:
            victim = store.get(KIND_POD, key)
            assert victim.is_terminated
            assert victim.meta.annotations["koordinator.sh/preempted-by"] == (
                high.meta.key
            )

    def test_minimal_victim_set(self):
        """Only as many victims as needed are evicted (reprieve pass)."""
        store = _store()
        _quota(store, cpu=4000)
        sched = Scheduler(store)
        for i in range(4):
            _pod(store, f"low-{i}", cpu=1000, prio=6000, node="node-0")
        _pod(store, "high", cpu=1000, prio=9500)
        result = sched.run_cycle(now=NOW)
        assert len(result.preempted_victims) == 1

    def test_least_important_victim_chosen(self):
        """Victims come from the bottom of the importance order."""
        store = _store()
        _quota(store, cpu=2000)
        sched = Scheduler(store)
        _pod(store, "mid", cpu=1000, prio=8000, node="node-0")
        _pod(store, "lowest", cpu=1000, prio=3000, node="node-0")
        _pod(store, "high", cpu=1000, prio=9500)
        result = sched.run_cycle(now=NOW)
        assert result.preempted_victims == ["default/lowest"]

    def test_equal_or_higher_priority_never_preempted(self):
        store = _store()
        _quota(store, cpu=2000)
        sched = Scheduler(store)
        _pod(store, "peer-a", cpu=1000, prio=9500, node="node-0")
        _pod(store, "peer-b", cpu=1000, prio=9800, node="node-0")
        _pod(store, "high", cpu=1000, prio=9500)
        result = sched.run_cycle(now=NOW)
        assert not result.preempted_victims
        assert result.rejected == ["default/high"]

    def test_non_preemptible_label_respected(self):
        store = _store()
        _quota(store, cpu=2000)
        sched = Scheduler(store)
        for i in range(2):
            _pod(store, f"low-{i}", cpu=1000, prio=6000, node="node-0",
                 labels={LABEL_PREEMPTIBLE: "false"})
        _pod(store, "high", cpu=1000, prio=9500)
        result = sched.run_cycle(now=NOW)
        assert not result.preempted_victims
        assert result.rejected == ["default/high"]

    def test_other_quota_group_never_preempted(self):
        """canPreempt requires the same quota group (preempt.go:276-294):
        cross-group reclaim rides runtime-quota recalc + overuse revoke, not
        PostFilter."""
        store = _store()
        _quota(store, "team-a", cpu=2000, min_cpu=2000)
        _quota(store, "team-b", cpu=2000, min_cpu=0)
        sched = Scheduler(store)
        _pod(store, "b-low-0", cpu=1000, prio=3000, quota="team-b",
             node="node-0")
        _pod(store, "a-full-0", cpu=1000, prio=6000, node="node-0")
        _pod(store, "a-full-1", cpu=1000, prio=6000, node="node-0")
        _pod(store, "a-high", cpu=1000, prio=9500)
        result = sched.run_cycle(now=NOW)
        # victims only from team-a, never team-b
        assert result.preempted_victims == ["default/a-full-0"] or \
            result.preempted_victims == ["default/a-full-1"]
        assert store.get(KIND_POD, "default/b-low-0").phase == "Running"

    def test_pdb_covered_pod_spared_when_alternative_exists(self):
        """PDB-violating candidates are reprieved first: the victim is the pod
        whose eviction keeps every budget intact."""
        store = _store()
        _quota(store, cpu=2000)
        sched = Scheduler(store)
        # two equal-priority members; one protected by a tight PDB
        _pod(store, "protected", cpu=1000, prio=6000, node="node-0",
             labels={"app": "web"})
        _pod(store, "expendable", cpu=1000, prio=6000, node="node-0")
        store.add(KIND_PDB, PodDisruptionBudget(
            meta=ObjectMeta(name="web-pdb", namespace="default"),
            selector={"app": "web"}, min_available=1))
        _pod(store, "high", cpu=1000, prio=9500)
        result = sched.run_cycle(now=NOW)
        assert result.preempted_victims == ["default/expendable"]
        assert store.get(KIND_POD, "default/protected").phase == "Running"

    def test_pending_pods_do_not_shore_up_pdb(self):
        """policy/v1 healthy count: a Pending pod matching the PDB selector
        must not be counted as healthy, so the budget is tighter than the raw
        pod count suggests and the protected pod stays spared."""
        store = _store()
        _quota(store, cpu=2000)
        sched = Scheduler(store)
        _pod(store, "protected", cpu=1000, prio=6000, node="node-0",
             labels={"app": "web"})
        _pod(store, "expendable", cpu=1000, prio=6000, node="node-0")
        # two Pending pods that match the selector; with the old
        # not-terminated counting they would absorb the disruption budget
        # and make "protected" look safely evictable
        for i in range(2):
            p = Pod(meta=ObjectMeta(
                name=f"pending-{i}", labels={"app": "web"},
                creation_timestamp=NOW),
                spec=PodSpec(requests=ResourceList.of(cpu=100)))
            store.add(KIND_POD, p)
        store.add(KIND_PDB, PodDisruptionBudget(
            meta=ObjectMeta(name="web-pdb", namespace="default"),
            selector={"app": "web"}, min_available=1))
        _pod(store, "high", cpu=1000, prio=9500)
        result = sched.run_cycle(now=NOW)
        assert result.preempted_victims == ["default/expendable"]
        assert store.get(KIND_POD, "default/protected").phase == "Running"

    def test_no_preemption_when_nothing_can_help(self):
        """Even evicting every candidate cannot make room -> no eviction."""
        store = _store()
        _quota(store, cpu=2000)
        sched = Scheduler(store)
        _pod(store, "low", cpu=1000, prio=6000, node="node-0")
        _pod(store, "huge", cpu=4000, prio=9500)  # exceeds group max alone
        result = sched.run_cycle(now=NOW)
        assert not result.preempted_victims
        assert store.get(KIND_POD, "default/low").phase == "Running"

    def test_two_starved_pods_each_claim_victims_in_one_cycle(self):
        """Nominated-pod accounting (PostFilterState analog): the second
        preemptor must NOT see the first one's freed headroom as its own —
        both evict their own victims and both bind in the same cycle."""
        store = _store()
        _quota(store, cpu=2000)
        sched = Scheduler(store)
        _pod(store, "low-0", cpu=1000, prio=6000, node="node-0")
        _pod(store, "low-1", cpu=1000, prio=6000, node="node-0")
        _pod(store, "high-a", cpu=1000, prio=9500)
        _pod(store, "high-b", cpu=1000, prio=9500)
        result = sched.run_cycle(now=NOW)
        assert sorted(result.preempted_victims) == [
            "default/low-0", "default/low-1"
        ]
        bound = {b.pod_key for b in result.bound}
        assert {"default/high-a", "default/high-b"} <= bound
        assert not result.rejected

    def test_node_fit_rejection_does_not_inflate_ledger(self):
        """A quota pod rejected for NODE capacity (its quota has headroom)
        must not enter the inflight ledger and trigger over-eviction for a
        genuinely starved sibling."""
        store = _store(num_nodes=1, cores=2)  # tiny node: 2000m cpu
        _quota(store, cpu=8000, min_cpu=8000)
        sched = Scheduler(store)
        # node full with a low-prio member; quota far from its limit
        _pod(store, "running", cpu=2000, prio=6000, node="node-0")
        # this pod fits the quota but no node can hold it
        _pod(store, "too-big", cpu=4000, prio=9500)
        result = sched.run_cycle(now=NOW)
        assert not result.preempted_victims
        assert store.get(KIND_POD, "default/running").phase == "Running"

    def test_quota_used_cache_rolls_after_preemption(self):
        """The quota tree sees the freed usage in the same cycle."""
        store = _store()
        _quota(store, cpu=2000)
        sched = Scheduler(store)
        _pod(store, "low", cpu=2000, prio=6000, node="node-0")
        _pod(store, "high", cpu=2000, prio=9500)
        result = sched.run_cycle(now=NOW)
        assert result.preempted_victims == ["default/low"]
        quota_plugin = sched.extender.plugin("ElasticQuota")
        used = quota_plugin.used.get("team-a")
        # only the newly-bound high-prio pod's usage remains
        assert used is not None and used[0] == 2000.0


class TestDefaultPreemption:
    """Priority preemption (vendored kube DefaultPreemption analog): pods
    with no feasible node evict lower-priority victims and bind in-cycle."""

    def _store(self, nodes=1, cores=4):
        from koordinator_tpu.api.objects import Node, ObjectMeta
        from koordinator_tpu.api.resources import ResourceList
        from koordinator_tpu.client.store import KIND_NODE, ObjectStore

        GIB = 1024**3
        store = ObjectStore()
        for i in range(nodes):
            store.add(KIND_NODE, Node(
                meta=ObjectMeta(name=f"n{i}", namespace=""),
                allocatable=ResourceList.of(
                    cpu=cores * 1000, memory=16 * GIB, pods=10)))
        return store

    def _pod(self, store, name, cpu, prio, node=None, labels=None):
        from koordinator_tpu.api.objects import ObjectMeta, Pod, PodSpec
        from koordinator_tpu.api.resources import ResourceList
        from koordinator_tpu.client.store import KIND_POD

        pod = Pod(meta=ObjectMeta(name=name, uid=name,
                                  creation_timestamp=1_000_000.0,
                                  labels=labels or {}),
                  spec=PodSpec(priority=prio,
                               requests=ResourceList.of(
                                   cpu=cpu, memory=1024**3)))
        if node:
            pod.spec.node_name = node
            pod.phase = "Running"
        store.add(KIND_POD, pod)
        return pod

    def test_high_priority_pod_preempts_and_binds(self):
        from koordinator_tpu.scheduler.cycle import Scheduler

        store = self._store()
        for i in range(4):
            self._pod(store, f"low-{i}", cpu=1000, prio=100, node="n0")
        self._pod(store, "vip", cpu=2000, prio=9000)
        result = Scheduler(store).run_cycle(now=1_000_000.0)
        assert len(result.preempted_victims) == 2  # exactly enough freed
        by_pod = {b.pod_key: b.node_name for b in result.bound}
        assert by_pod.get("default/vip") == "n0"

    def test_no_lower_priority_victims_stays_pending(self):
        from koordinator_tpu.scheduler.cycle import Scheduler

        store = self._store()
        for i in range(4):
            self._pod(store, f"peer-{i}", cpu=1000, prio=9000, node="n0")
        self._pod(store, "vip", cpu=2000, prio=9000)  # equal priority
        result = Scheduler(store).run_cycle(now=1_000_000.0)
        assert result.preempted_victims == []
        assert "default/vip" in result.failed

    def test_node_with_fewest_pdb_violations_preferred(self):
        from koordinator_tpu.api.objects import ObjectMeta, PodDisruptionBudget
        from koordinator_tpu.client.store import KIND_PDB
        from koordinator_tpu.scheduler.cycle import Scheduler

        store = self._store(nodes=2)
        # n0 victims are PDB-guarded, n1 victims are free
        for i in range(4):
            self._pod(store, f"guard-{i}", cpu=1000, prio=100, node="n0",
                      labels={"app": "guarded"})
            self._pod(store, f"free-{i}", cpu=1000, prio=100, node="n1")
        store.add(KIND_PDB, PodDisruptionBudget(
            meta=ObjectMeta(name="pdb", namespace="default"),
            selector={"app": "guarded"}, min_available=4))
        self._pod(store, "vip", cpu=2000, prio=9000)
        result = Scheduler(store).run_cycle(now=1_000_000.0)
        by_pod = {b.pod_key: b.node_name for b in result.bound}
        assert by_pod.get("default/vip") == "n1"
        assert all(k.startswith("default/free") 
                   for k in result.preempted_victims)

    def test_non_preemptible_victims_skipped(self):
        from koordinator_tpu.api.objects import QUOTA_DOMAIN_PREFIX
        from koordinator_tpu.scheduler.cycle import Scheduler

        store = self._store()
        for i in range(4):
            self._pod(store, f"pinned-{i}", cpu=1000, prio=100, node="n0",
                      labels={QUOTA_DOMAIN_PREFIX + "/preemptible": "false"})
        self._pod(store, "vip", cpu=2000, prio=9000)
        result = Scheduler(store).run_cycle(now=1_000_000.0)
        assert result.preempted_victims == []
        assert "default/vip" in result.failed

    def test_lowest_priority_victims_chosen(self):
        """Reprieve walks most-important-first, so the surviving victim set
        is the LEAST important (upstream selectVictimsOnNode)."""
        from koordinator_tpu.scheduler.cycle import Scheduler

        store = self._store()
        self._pod(store, "mid", cpu=1000, prio=5000, node="n0")
        self._pod(store, "low", cpu=1000, prio=100, node="n0")
        self._pod(store, "mid2", cpu=1000, prio=5000, node="n0")
        self._pod(store, "low2", cpu=1000, prio=100, node="n0")
        self._pod(store, "vip", cpu=2000, prio=9000)
        result = Scheduler(store).run_cycle(now=1_000_000.0)
        assert sorted(result.preempted_victims) == [
            "default/low", "default/low2"]
        by_pod = {b.pod_key: b.node_name for b in result.bound}
        assert by_pod.get("default/vip") == "n0"

    def test_inflight_ledger_between_preemptors(self):
        """Two no-fit preemptors must each claim their OWN victims — the
        second cannot count the first's freed space."""
        from koordinator_tpu.scheduler.cycle import Scheduler

        store = self._store()  # one 4-core node
        for i in range(4):
            self._pod(store, f"low-{i}", cpu=1000, prio=100, node="n0")
        self._pod(store, "vip-a", cpu=2000, prio=9000)
        self._pod(store, "vip-b", cpu=2000, prio=9000)
        result = Scheduler(store).run_cycle(now=1_000_000.0)
        # both preemptors fit only if all four victims go
        assert len(result.preempted_victims) == 4
        by_pod = {b.pod_key: b.node_name for b in result.bound}
        assert by_pod.get("default/vip-a") == "n0"
        assert by_pod.get("default/vip-b") == "n0"

    def test_preemption_consults_kernel_admission_grouping(self):
        """When the admission-signature budget overflows (>22 usable exact
        signatures), nodes degrade to their label-unknown bucket and
        selector-carrying pods become KERNEL-unschedulable there. The
        DefaultPreemption dry-run must consult that same grouping: raw
        label checks would accept the node and evict victims in vain,
        forever (the encoding disagreement is permanent)."""
        from koordinator_tpu.client.store import KIND_NODE
        from koordinator_tpu.scheduler.cycle import Scheduler

        n_nodes = 26
        store = self._store(nodes=n_nodes, cores=2)
        for i, node in enumerate(store.list(KIND_NODE)):
            node.meta.labels["kubernetes.io/hostname"] = node.meta.name
        # every node is full with one low-priority victim
        for i in range(n_nodes):
            self._pod(store, f"victim-{i}", cpu=2000, prio=100, node=f"n{i}")
        # 26 high-priority pods pinned to distinct hostnames -> 26 distinct
        # signatures; the 22-slot exact budget (24 bits - overflow - one
        # unknown bucket) interns only the first 22
        for i in range(n_nodes):
            vip = self._pod(store, f"vip-{i}", cpu=2000, prio=9000)
            vip.spec.node_selector["kubernetes.io/hostname"] = f"n{i}"
        result = Scheduler(store).run_cycle(now=1_000_000.0)
        # pods whose target node kept an exact signature preempt + bind
        by_pod = {b.pod_key: b.node_name for b in result.bound}
        bound_vips = [k for k in by_pod if k.startswith("default/vip")]
        assert len(bound_vips) >= 20
        # pods whose node degraded to the label-unknown bucket are
        # kernel-unschedulable: NO victim on those nodes may die in vain
        unbound = [f"default/vip-{i}" for i in range(n_nodes)
                   if f"default/vip-{i}" not in by_pod]
        assert unbound, "fixture must overflow the signature budget"
        unbound_nodes = {k.split("vip-")[1] for k in unbound}
        vain = [v for v in result.preempted_victims
                if v.split("victim-")[1] in unbound_nodes]
        assert vain == [], f"victims evicted in vain: {vain}"
        for k in unbound:
            assert k in result.failed

    def test_attempted_latch_stops_repeat_drain(self):
        """A preemptor the kernel still rejects after its victims died must
        not evict a fresh victim set every cycle."""
        from koordinator_tpu.api.objects import PodAffinityTerm
        from koordinator_tpu.client.store import KIND_NODE
        from koordinator_tpu.scheduler.cycle import Scheduler

        store = self._store(nodes=2)
        for n in store.list(KIND_NODE):
            n.meta.labels["zone"] = "z0"  # one domain spans both nodes
        # a high-priority anti-affinity blocker on n1 the vip cannot evict
        blocker = self._pod(store, "blocker", cpu=1000, prio=9999, node="n1",
                            labels={"app": "x"})
        for i in range(4):
            self._pod(store, f"low-{i}", cpu=1000, prio=100, node="n0")
        vip = self._pod(store, "vip", cpu=2000, prio=9000,
                        labels={"app": "x"})
        vip.spec.pod_anti_affinity.append(PodAffinityTerm(
            selector={"app": "x"}, topology_key="zone"))
        sched = Scheduler(store)
        r1 = sched.run_cycle(now=1_000_000.0)
        # the affinity dry-run already refuses every node: no victims die
        assert r1.preempted_victims == []
        assert "default/vip" in r1.failed
        r2 = sched.run_cycle(now=1_000_001.0)
        assert r2.preempted_victims == []


class TestCandidateSampling:
    """The DefaultPreemption candidate cap follows upstream's sampling
    semantics; the window must ROTATE across attempts so a blocked window
    cannot starve a preemptor forever."""

    def _blocked_fleet(self):
        """150 nodes: every node carries a low-prio victim (resource
        feasibility needs eviction everywhere); the FIRST 120 also carry a
        non-preemptible anti-affinity carrier that repels the preemptor
        (symmetric anti-affinity), so only the CONTIGUOUS last 30 nodes can
        host it — a fixed-order 100-candidate window starting at node 0
        would fail forever; rotation must reach the tail."""
        from koordinator_tpu.api.objects import (
            Node,
            ObjectMeta,
            Pod,
            PodAffinityTerm,
            PodSpec,
        )
        from koordinator_tpu.client.store import (
            KIND_NODE,
            KIND_POD,
            ObjectStore,
        )

        GIB = 1024**3
        store = ObjectStore()
        good = set()
        for i in range(150):
            node = Node(meta=ObjectMeta(name=f"n{i:03d}", namespace=""),
                        allocatable=ResourceList.of(cpu=2000, memory=8 * GIB,
                                                    pods=10))
            node.meta.labels["kubernetes.io/hostname"] = node.meta.name
            store.add(KIND_NODE, node)
            victim = Pod(
                meta=ObjectMeta(name=f"victim-{i}", uid=f"victim-{i}",
                                creation_timestamp=1.0),
                spec=PodSpec(priority=100,
                             requests=ResourceList.of(cpu=1500, memory=GIB)))
            victim.spec.node_name = node.meta.name
            victim.phase = "Running"
            store.add(KIND_POD, victim)
            if i < 120:  # contiguous blocked prefix
                carrier = Pod(
                    meta=ObjectMeta(name=f"carrier-{i}", uid=f"carrier-{i}",
                                    creation_timestamp=1.0,
                                    labels={"app": "guard"}),
                    spec=PodSpec(priority=10_000,  # never a victim
                                 requests=ResourceList.of(cpu=100,
                                                          memory=GIB // 4)))
                carrier.spec.pod_anti_affinity.append(PodAffinityTerm(
                    selector={"app": "hot"},
                    topology_key="kubernetes.io/hostname"))
                carrier.spec.node_name = node.meta.name
                carrier.phase = "Running"
                store.add(KIND_POD, carrier)
            else:
                good.add(node.meta.name)
        hot = Pod(meta=ObjectMeta(name="hot", uid="hot",
                                  creation_timestamp=2.0,
                                  labels={"app": "hot"}),
                  spec=PodSpec(priority=5000,
                               requests=ResourceList.of(cpu=1500,
                                                        memory=GIB)))
        return store, hot, good

    def test_rotating_window_reaches_unblocked_nodes(self):
        from koordinator_tpu.scheduler.preempt import DefaultPreemption

        store, hot, good = self._blocked_fleet()
        outcomes = {}
        for seed in range(8):
            preempter = DefaultPreemption(store, attempt_seed=seed)
            rounds = preempter.post_filter([hot])
            outcomes[seed] = bool(rounds)
            if rounds:
                # the victim must come from an UNBLOCKED node
                victim_key = rounds[0].victim_keys[0]
                node = store.get(
                    "Pod", victim_key).spec.node_name
                assert node in good
        # with only 20% of nodes unblocked and a 100-candidate window over
        # 150 nodes, some seeds may sample a blocked-heavy window — but
        # rotation must find an unblocked window within a few attempts
        assert any(outcomes.values()), outcomes


class TestScanBound:
    """Every VISITED node counts toward the candidate scan bound — a fleet
    where most nodes fail static admission must not walk every prefiltered
    node per failed pod (round-5 advisor, preempt.py:529). The cap is
    2 x max_candidates with max_candidates = max(100, len(nodes)//10),
    matching upstream minCandidateNodesPercentage semantics over ALL
    nodes."""

    def test_admission_failures_bounded_by_scan_cap(self):
        from koordinator_tpu.scheduler.preempt import DefaultPreemption

        store = ObjectStore()
        n_nodes = 400
        for i in range(n_nodes):
            store.add(KIND_NODE, Node(
                meta=ObjectMeta(name=f"n{i:03d}", namespace=""),
                allocatable=ResourceList.of(cpu=2000, memory=8 * GIB,
                                            pods=10)))
            victim = Pod(
                meta=ObjectMeta(name=f"v-{i}", uid=f"v-{i}",
                                creation_timestamp=1.0),
                spec=PodSpec(priority=100,
                             requests=ResourceList.of(cpu=1500,
                                                      memory=GIB)))
            victim.spec.node_name = f"n{i:03d}"
            victim.phase = "Running"
            store.add(KIND_POD, victim)
        # resource-feasible everywhere (with eviction), but static
        # admission fails everywhere: no node carries the selector label
        hot = Pod(meta=ObjectMeta(name="hot", uid="hot",
                                  creation_timestamp=2.0),
                  spec=PodSpec(priority=5000,
                               requests=ResourceList.of(cpu=1500,
                                                        memory=GIB)))
        hot.spec.node_selector["zone"] = "nowhere"

        preempter = DefaultPreemption(store)
        calls = {"n": 0}
        orig = preempter._static_admission

        def counting(pod, node):
            calls["n"] += 1
            return orig(pod, node)

        preempter._static_admission = counting
        rounds = preempter.post_filter([hot])
        assert rounds == []
        # max_candidates = max(100, 400//10) = 100 -> scan cap 200,
        # NOT all 400 prefiltered nodes
        assert calls["n"] <= 200, calls

    def test_cap_scales_with_fleet_not_prefilter(self):
        """The 10% base is the WHOLE fleet, not the prefiltered subset:
        1500 nodes -> a 150-candidate window, so when the prefilter
        narrows to 130 feasible nodes ALL of them get dry-run (a
        prefilter-based cap of max(100, 130//10) = 100 would stop at
        100)."""
        from koordinator_tpu.scheduler.preempt import DefaultPreemption

        store = ObjectStore()
        n_feasible = 130
        for i in range(1500):
            store.add(KIND_NODE, Node(
                meta=ObjectMeta(name=f"n{i:04d}", namespace=""),
                allocatable=ResourceList.of(cpu=2000, memory=8 * GIB,
                                            pods=10)))
            # first 130 nodes host an evictable low-prio pod (feasible
            # with eviction); the rest are pinned full by a higher-prio
            # occupant, so the packed prefilter excludes them
            occ_prio = 100 if i < n_feasible else 9000
            occ = Pod(
                meta=ObjectMeta(name=f"occ-{i}", uid=f"occ-{i}",
                                creation_timestamp=1.0),
                spec=PodSpec(priority=occ_prio,
                             requests=ResourceList.of(cpu=1500,
                                                      memory=GIB)))
            occ.spec.node_name = f"n{i:04d}"
            occ.phase = "Running"
            store.add(KIND_POD, occ)
        hot = Pod(meta=ObjectMeta(name="hot", uid="hot",
                                  creation_timestamp=2.0),
                  spec=PodSpec(priority=5000,
                               requests=ResourceList.of(cpu=1500,
                                                        memory=GIB)))

        preempter = DefaultPreemption(store)
        calls = {"n": 0}
        orig = preempter._static_admission

        def counting(pod, node):
            calls["n"] += 1
            return orig(pod, node)

        preempter._static_admission = counting
        rounds = preempter.post_filter([hot])
        assert rounds, "eviction must be found among the feasible nodes"
        # every prefiltered node fits in the fleet-based 150 window;
        # admission is consulted for all of them (evaluation stops at the
        # best-scoring search's natural end, not at a 100-node cap)
        assert calls["n"] == n_feasible, calls


class TestGangVictimGuard:
    """Preemption must never break a bound gang below its min_member —
    the all-or-nothing barrier the admission kernel enforced at bind
    time (found by the koordsim churn soak: priority-less gang members
    were DefaultPreemption's favorite victims)."""

    def _world(self, spare_members=0):
        """One full node: a bound gang (min_member=3) with
        3 + spare_members members, the rest filled by a non-gang low-prio
        pod, plus a high-priority preemptor that needs one slot."""
        from koordinator_tpu.api.objects import (
            LABEL_POD_GROUP,
            ObjectMeta,
            PodGroup,
        )
        from koordinator_tpu.client.store import KIND_POD_GROUP

        helper = TestDefaultPreemption()
        members = 3 + spare_members
        store = helper._store(nodes=1, cores=members + 1)
        store.add(KIND_POD_GROUP, PodGroup(
            meta=ObjectMeta(name="g", namespace="default",
                            creation_timestamp=1_000_000.0),
            min_member=3))
        for i in range(members):
            helper._pod(store, f"gm-{i}", cpu=1000, prio=100, node="n0",
                        labels={LABEL_POD_GROUP: "g"})
        helper._pod(store, "plain-low", cpu=1000, prio=50, node="n0")
        helper._pod(store, "vip", cpu=1000, prio=9000)
        return helper, store

    def test_gang_at_min_member_is_never_a_victim(self):
        from koordinator_tpu.scheduler.cycle import Scheduler

        _helper, store = self._world(spare_members=0)
        result = Scheduler(store).run_cycle(now=1_000_000.0)
        # the non-gang pod is the only admissible victim — the gang
        # stays whole even though its members are lower-priority-ordered
        # AFTER plain-low in the candidate sort
        assert result.preempted_victims == ["default/plain-low"]
        assert any(b.pod_key == "default/vip" for b in result.bound)
        from koordinator_tpu.sim.invariants import check_invariants

        assert check_invariants(store) == []

    def test_spare_gang_members_stay_preemptible(self):
        from koordinator_tpu.scheduler.preempt import (
            DefaultPreemption,
            GangVictimGuard,
        )

        helper, store = self._world(spare_members=2)
        guard = GangVictimGuard(store)
        pods = {f"gm-{i}" for i in range(5)}
        from koordinator_tpu.client.store import KIND_POD

        members = [p for p in store.list(KIND_POD)
                   if p.meta.name in pods]
        # 5 bound, min 3: two spares — individually unprotected
        assert all(not guard.protected(p) for p in members)
        # but a victim SET overdrawing the spare count is inadmissible
        assert guard.admissible(members[:2])
        assert not guard.admissible(members[:3])
        guard.commit(members[:2])
        assert all(guard.protected(p) for p in members)

    def test_quota_preemption_respects_gang_min_member(self):
        """The ElasticQuota reclaim path shares the guard, driven through
        the REAL cycle: a quota-starved high-priority pod whose only
        victims are gang members at min_member reclaims nothing (the
        gang stays whole and the pod stays pending); give the gang
        spares and the same cycle evicts exactly the spare count."""
        from koordinator_tpu.api.objects import LABEL_POD_GROUP, PodGroup
        from koordinator_tpu.client.store import KIND_POD_GROUP
        from koordinator_tpu.scheduler.cycle import Scheduler
        from koordinator_tpu.sim.invariants import check_invariants

        def world(min_member):
            store = _store(num_nodes=1, cores=4)
            _quota(store, cpu=4000)
            store.add(KIND_POD_GROUP, PodGroup(
                meta=ObjectMeta(name="g", namespace="default",
                                creation_timestamp=NOW - 500.0),
                min_member=min_member))
            for i in range(4):
                _pod(store, f"gm-{i}", cpu=1000, prio=6000, node="node-0",
                     labels={LABEL_POD_GROUP: "g"})
            high = _pod(store, "high", cpu=2000, prio=9500)
            return store, high

        # all 4 bound members needed for min_member: no victim set can
        # help without breaking all-or-nothing — refuse outright
        store, high = world(min_member=4)
        result = Scheduler(store).run_cycle(now=NOW)
        assert not result.preempted_victims
        assert not any(b.pod_key == high.meta.key for b in result.bound)
        gang_bound = [p for p in store.list(KIND_POD)
                      if p.gang_key and p.is_assigned
                      and not p.is_terminated]
        assert len(gang_bound) == 4
        assert check_invariants(store) == []

        # two spares: reclaim takes exactly the spares, never below min
        store, high = world(min_member=2)
        result = Scheduler(store).run_cycle(now=NOW)
        assert len(result.preempted_victims) == 2
        assert any(b.pod_key == high.meta.key for b in result.bound)
        gang_bound = [p for p in store.list(KIND_POD)
                      if p.gang_key and p.is_assigned
                      and not p.is_terminated]
        assert len(gang_bound) == 2
        assert check_invariants(store) == []
