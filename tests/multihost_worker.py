"""Worker process for the 2-process jax.distributed multi-host test.

Each process owns 4 virtual CPU devices; `jax.distributed.initialize()`
federates them into one 8-device global mesh (the DCN analog — process
boundary == host boundary). Both processes build the identical synthetic
cluster, run the sharded full-chain step over the GLOBAL mesh (gloo
collectives across the process boundary), and diff the bindings against a
locally-computed single-device run. Prints ``MULTIHOST_OK <digest>`` so the
parent test can also assert both processes agree.

Usage: python multihost_worker.py <process_id> <num_processes> <port>
"""

import hashlib
import os
import re
import sys


def main() -> None:
    proc_id, num_procs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    flags = os.environ.get("XLA_FLAGS", "")
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags)
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    # the runtime pre-imports jax with the axon TPU platform baked into
    # jax.config; flip it back before any backend initializes (conftest.py)
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        f"127.0.0.1:{port}", num_processes=num_procs, process_id=proc_id
    )
    assert jax.device_count() == 4 * num_procs, jax.devices()
    assert jax.local_device_count() == 4

    import numpy as np

    from koordinator_tpu.models.full_chain import build_full_chain_step
    from koordinator_tpu.ops.loadaware import LoadAwareArgs
    from koordinator_tpu.parallel import (
        build_sharded_full_chain_step,
        make_mesh,
        shard_full_chain_inputs,
    )
    from koordinator_tpu.scheduler.snapshot import build_full_chain_inputs
    from koordinator_tpu.testing import synth_full_cluster

    args = LoadAwareArgs()
    _, state = synth_full_cluster(30, 60, seed=0)
    fc, pods, _, _, _, ng, ngroups = build_full_chain_inputs(state, args)

    # single-device reference on this process's local device
    chosen_1, requested_1, quota_1 = build_full_chain_step(args, ng, ngroups)(fc)
    chosen_1 = np.asarray(chosen_1)

    # global mesh spanning both processes
    mesh = make_mesh(jax.devices())
    step = build_sharded_full_chain_step(args, ng, ngroups, mesh)
    chosen_g, requested_g, quota_g = step(shard_full_chain_inputs(fc, mesh))
    chosen_g = np.asarray(chosen_g)  # replicated -> locally addressable

    np.testing.assert_array_equal(chosen_1, chosen_g)
    np.testing.assert_array_equal(np.asarray(quota_1), np.asarray(quota_g))
    assert (chosen_1[: len(pods.keys)] >= 0).sum() > 0, "vacuous schedule"

    # second pass at a bucketed-with-PADDING shape (500 pods x 250 nodes
    # pad to 512 x 256, so pad rows actually cross the shard boundary):
    # bucket/pad/shard interplay across the real process boundary, not
    # just the toy fixture (the single-process dryrun covers 2048x1024;
    # gloo collectives over CPU bound what is CI-affordable here). Runs
    # through reduce_to_active_axes like the production cycle, and checks
    # the quota rollup parity on the reduced axes too.
    from koordinator_tpu.scheduler.snapshot import reduce_to_active_axes

    _, big_state = synth_full_cluster(250, 500, seed=1)
    big_fc, big_pods, _, _, _, bng, bngroups = build_full_chain_inputs(
        big_state, args)
    big_fc, big_axes = reduce_to_active_axes(big_fc)
    assert big_fc.base.fit_requests.shape[0] > len(big_pods.keys)  # padded
    big_ref, _, big_quota_ref = build_full_chain_step(
        args, bng, bngroups, active_axes=big_axes)(big_fc)
    big_ref = np.asarray(big_ref)
    big_step = build_sharded_full_chain_step(
        args, bng, bngroups, mesh, active_axes=big_axes)
    big_g, _, big_quota_g = big_step(shard_full_chain_inputs(big_fc, mesh))
    big_g = np.asarray(big_g)
    np.testing.assert_array_equal(big_ref, big_g)
    np.testing.assert_array_equal(
        np.asarray(big_quota_ref), np.asarray(big_quota_g))
    assert (big_g[: len(big_pods.keys)] >= 0).sum() > 100

    digest = hashlib.sha256(
        chosen_g.tobytes() + big_g.tobytes()).hexdigest()[:16]
    print(f"MULTIHOST_OK {digest}", flush=True)


if __name__ == "__main__":
    main()
