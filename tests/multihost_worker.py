"""Worker process for the jax.distributed multi-host tests (2 and 4
processes).

Each process owns `local_devices` virtual CPU devices;
`jax.distributed.initialize()` federates them into one global mesh (the
DCN analog — process boundary == host boundary). Every process builds the
identical synthetic cluster, runs the sharded full-chain step over the
GLOBAL mesh (gloo collectives across the process boundary), and diffs the
bindings against a locally-computed single-device run. In the 4-process
shape the mesh is 2-D (pods x nodes), so the one-shot score matrix shards
BOTH batch axes across the process boundary. Prints ``MULTIHOST_OK
<digest>`` so the parent test can also assert all processes agree.

Usage: python multihost_worker.py <process_id> <num_processes> <port>
       [local_devices=4]
"""

import hashlib
import os
import re
import sys


def main() -> None:
    proc_id, num_procs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    local_devices = int(sys.argv[4]) if len(sys.argv) > 4 else 4
    flags = os.environ.get("XLA_FLAGS", "")
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags)
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={local_devices}"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    # the runtime pre-imports jax with the axon TPU platform baked into
    # jax.config; flip it back before any backend initializes (conftest.py)
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        f"127.0.0.1:{port}", num_processes=num_procs, process_id=proc_id
    )
    assert jax.device_count() == local_devices * num_procs, jax.devices()
    assert jax.local_device_count() == local_devices

    import numpy as np

    from koordinator_tpu.models.full_chain import build_full_chain_step
    from koordinator_tpu.ops.loadaware import LoadAwareArgs
    from koordinator_tpu.parallel import (
        build_sharded_full_chain_step,
        make_mesh,
        shard_full_chain_inputs,
    )
    from koordinator_tpu.scheduler.snapshot import build_full_chain_inputs
    from koordinator_tpu.testing import synth_full_cluster

    args = LoadAwareArgs()
    _, state = synth_full_cluster(30, 60, seed=0)
    fc, pods, _, _, _, ng, ngroups = build_full_chain_inputs(state, args)

    # single-device reference on this process's local device
    chosen_1, requested_1, quota_1 = build_full_chain_step(args, ng, ngroups)(fc)
    chosen_1 = np.asarray(chosen_1)

    # global mesh spanning both processes
    mesh = make_mesh(jax.devices())
    step = build_sharded_full_chain_step(args, ng, ngroups, mesh)
    chosen_g, requested_g, quota_g = step(shard_full_chain_inputs(fc, mesh))
    chosen_g = np.asarray(chosen_g)  # replicated -> locally addressable

    np.testing.assert_array_equal(chosen_1, chosen_g)
    np.testing.assert_array_equal(np.asarray(quota_1), np.asarray(quota_g))
    assert (chosen_1[: len(pods.keys)] >= 0).sum() > 0, "vacuous schedule"

    # second pass at a bucketed-with-PADDING shape (500 pods x 250 nodes
    # pad to 512 x 256, so pad rows actually cross the shard boundary):
    # bucket/pad/shard interplay across the real process boundary, not
    # just the toy fixture (the single-process dryrun covers 2048x1024;
    # gloo collectives over CPU bound what is CI-affordable here). Runs
    # through reduce_to_active_axes like the production cycle, and checks
    # the quota rollup parity on the reduced axes too.
    from koordinator_tpu.scheduler.snapshot import reduce_to_active_axes

    _, big_state = synth_full_cluster(250, 500, seed=1)
    big_fc, big_pods, _, _, _, bng, bngroups = build_full_chain_inputs(
        big_state, args)
    big_fc, big_axes = reduce_to_active_axes(big_fc)
    assert big_fc.base.fit_requests.shape[0] > len(big_pods.keys)  # padded
    big_ref, _, big_quota_ref = build_full_chain_step(
        args, bng, bngroups, active_axes=big_axes)(big_fc)
    big_ref = np.asarray(big_ref)
    big_step = build_sharded_full_chain_step(
        args, bng, bngroups, mesh, active_axes=big_axes)
    big_g, _, big_quota_g = big_step(shard_full_chain_inputs(big_fc, mesh))
    big_g = np.asarray(big_g)
    np.testing.assert_array_equal(big_ref, big_g)
    np.testing.assert_array_equal(
        np.asarray(big_quota_ref), np.asarray(big_quota_g))
    assert (big_g[: len(big_pods.keys)] >= 0).sum() > 100

    # third pass: the one-shot [P, N] score matrix sharded over BOTH mesh
    # axes (pods x nodes) at the same padded 512 x 256 shape — with >= 2
    # processes per axis (the 4-process shape), every shard boundary of
    # both batch axes crosses a process boundary. Feasibility and score
    # must match the local single-device matrix bit-for-bit.
    from koordinator_tpu.models.scheduler_model import build_score_matrix
    from koordinator_tpu.parallel import (
        build_sharded_score_matrix,
        shard_inputs_2d,
    )

    matrix = build_sharded_score_matrix(args, mesh)
    feas_g, score_g = matrix(shard_inputs_2d(big_fc.base, mesh))
    # the matrix outputs stay sharded across processes (unlike the
    # replicated chosen vector): assemble the global arrays via the DCN
    # allgather before host comparison
    from jax.experimental import multihost_utils

    feas_g = np.asarray(multihost_utils.process_allgather(feas_g, tiled=True))
    score_g = np.asarray(
        multihost_utils.process_allgather(score_g, tiled=True))
    feas_1, score_1 = build_score_matrix(args)(big_fc.base)
    np.testing.assert_array_equal(np.asarray(feas_1), feas_g)
    np.testing.assert_array_equal(np.asarray(score_1), score_g)
    assert feas_g.shape[0] > len(big_pods.keys)  # padding crossed shards

    digest = hashlib.sha256(
        chosen_g.tobytes() + big_g.tobytes() + feas_g.tobytes()
        + score_g.tobytes()).hexdigest()[:16]
    mesh_shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    print(f"MULTIHOST_OK {digest} mesh={mesh_shape}", flush=True)


if __name__ == "__main__":
    main()
