"""Pod-status unschedulability propagation: a pod ending a cycle unbound
carries a store-visible PodScheduled=False/Unschedulable condition with the
specific reason class (quota exhausted / gang not satisfied / encoding
overflow / volume PreFilter / per-stage filter breakdown), and the
condition flips True at bind — the status surface kube-scheduler writes
through the framework and frameworkext's debug plumbing
(/root/reference/pkg/scheduler/frameworkext/debug.go:31-46)."""

from koordinator_tpu.api.objects import (
    LABEL_POD_GROUP,
    LABEL_POD_QOS,
    LABEL_QUOTA_NAME,
    ElasticQuota,
    Node,
    ObjectMeta,
    PersistentVolumeClaim,
    Pod,
    PodGroup,
    PodSpec,
    StorageClass,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import (
    KIND_ELASTIC_QUOTA,
    KIND_NODE,
    KIND_POD,
    KIND_POD_GROUP,
    KIND_PVC,
    KIND_STORAGECLASS,
    ObjectStore,
)
from koordinator_tpu.scheduler.cycle import Scheduler

GIB = 1024**3
NOW = 1_000_000.0


def make_store(num_nodes=3, cpu=8000):
    store = ObjectStore()
    for i in range(num_nodes):
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name=f"n{i}", namespace=""),
            allocatable=ResourceList.of(cpu=cpu, memory=32 * GIB, pods=20)))
    return store


def pend_pod(store, name, cpu=1000, labels=None):
    pod = Pod(
        meta=ObjectMeta(name=name, uid=name, creation_timestamp=NOW,
                        labels={LABEL_POD_QOS: "LS", **(labels or {})}),
        spec=PodSpec(requests=ResourceList.of(cpu=cpu, memory=GIB)))
    store.add(KIND_POD, pod)
    return pod


def scheduled_cond(store, key):
    return store.get(KIND_POD, key).get_condition("PodScheduled")


def test_insufficient_resources_breakdown():
    store = make_store(3, cpu=4000)
    pend_pod(store, "huge", cpu=64000)
    Scheduler(store).run_cycle(now=NOW)
    cond = scheduled_cond(store, "default/huge")
    assert cond.status == "False" and cond.reason == "Unschedulable"
    assert "0/3 nodes are available" in cond.message
    assert "3 insufficient resources" in cond.message


def test_selector_mismatch_breakdown():
    store = make_store(4)
    pod = pend_pod(store, "pinned")
    pod.spec.node_selector["disk"] = "nvme"  # no node carries the label
    Scheduler(store).run_cycle(now=NOW)
    cond = scheduled_cond(store, "default/pinned")
    assert cond.status == "False"
    assert "4 taint/selector/volume-topology mismatch" in cond.message


def test_quota_exhausted_reason():
    store = make_store(3)
    store.add(KIND_ELASTIC_QUOTA, ElasticQuota(
        meta=ObjectMeta(name="tiny", namespace="default"),
        min=ResourceList.of(cpu=0),
        max=ResourceList.of(cpu=500, memory=GIB)))
    pend_pod(store, "q-pod", cpu=2000, labels={LABEL_QUOTA_NAME: "tiny"})
    Scheduler(store).run_cycle(now=NOW)
    cond = scheduled_cond(store, "default/q-pod")
    assert cond.status == "False"
    assert "quota group exhausted" in cond.message


def test_gang_min_member_reason():
    store = make_store(3)
    store.add(KIND_POD_GROUP, PodGroup(
        meta=ObjectMeta(name="g1", namespace="default"), min_member=3))
    pend_pod(store, "lonely", labels={LABEL_POD_GROUP: "g1"})
    Scheduler(store).run_cycle(now=NOW)
    cond = scheduled_cond(store, "default/lonely")
    assert cond.status == "False"
    assert "gang minMember not satisfied" in cond.message


def test_volume_prefilter_reason_passthrough():
    store = make_store(2)
    store.add(KIND_STORAGECLASS, StorageClass(
        meta=ObjectMeta(name="std", namespace=""), provisioner="x"))
    store.add(KIND_PVC, PersistentVolumeClaim(
        meta=ObjectMeta(name="c", namespace="default"),
        capacity=ResourceList({"storage": GIB}), storage_class_name="std"))
    pod = pend_pod(store, "vol-pod")
    pod.spec.pvc_names = ["c"]
    Scheduler(store).run_cycle(now=NOW)
    cond = scheduled_cond(store, "default/vol-pod")
    assert cond.message == "pod has unbound immediate PersistentVolumeClaims"


def test_condition_flips_true_on_bind():
    store = make_store(2)
    store.add(KIND_POD_GROUP, PodGroup(
        meta=ObjectMeta(name="g2", namespace="default"), min_member=2))
    pend_pod(store, "m1", labels={LABEL_POD_GROUP: "g2"})
    sched = Scheduler(store)
    sched.run_cycle(now=NOW)
    assert scheduled_cond(store, "default/m1").status == "False"
    pend_pod(store, "m2", labels={LABEL_POD_GROUP: "g2"})
    result = sched.run_cycle(now=NOW + 10)
    assert len(result.bound) == 2
    for key in ("default/m1", "default/m2"):
        cond = scheduled_cond(store, key)
        assert cond.status == "True"
        assert cond.last_transition_time == NOW + 10


def test_condition_write_is_idempotent():
    """A permanently-pending pod's condition is written once; later cycles
    with the same message leave the stored object untouched (no churn, no
    snapshot-cache invalidation)."""
    store = make_store(2, cpu=4000)
    pend_pod(store, "huge", cpu=64000)
    sched = Scheduler(store)
    sched.run_cycle(now=NOW)
    rv1 = store.get(KIND_POD, "default/huge").meta.resource_version
    sched.run_cycle(now=NOW + 30)
    sched.run_cycle(now=NOW + 60)
    assert store.get(KIND_POD, "default/huge").meta.resource_version == rv1
    cond = scheduled_cond(store, "default/huge")
    assert cond.last_transition_time == NOW  # first write's flip time


def test_spread_blocked_pod_reports_mismatch_not_capacity():
    """A DoNotSchedule spread constraint over a topology key no node
    carries rejects every node in-kernel; the condition must name the
    spread/affinity stage, not the in-batch-contention fallback."""
    from koordinator_tpu.api.objects import TopologySpreadConstraint

    store = make_store(3)
    pod = pend_pod(store, "spread-pod")
    pod.meta.labels["app"] = "web"
    pod.spec.topology_spread.append(TopologySpreadConstraint(
        max_skew=1, topology_key="topology.kubernetes.io/zone",
        selector={"app": "web"}))
    Scheduler(store).run_cycle(now=NOW)
    cond = scheduled_cond(store, "default/spread-pod")
    assert cond.status == "False"
    assert "affinity/anti-affinity/spread mismatch" in cond.message


def test_required_affinity_without_match_reports_mismatch():
    """requiredDuringScheduling podAffinity whose selector matches nothing
    (and not the pod itself) fails every node; the condition names the
    affinity stage even though no matching pod exists anywhere."""
    from koordinator_tpu.api.objects import PodAffinityTerm

    store = make_store(3)
    pod = pend_pod(store, "needs-db")
    pod.spec.pod_affinity.append(PodAffinityTerm(
        selector={"app": "db"}, topology_key="kubernetes.io/hostname"))
    Scheduler(store).run_cycle(now=NOW)
    cond = scheduled_cond(store, "default/needs-db")
    assert cond.status == "False"
    assert "affinity/anti-affinity/spread mismatch" in cond.message


def test_gang_timeout_writes_terminal_condition():
    """Pods of a terminally-failed gang never reach the batch pass; the
    'gang schedule timeout' reason must still land on their status."""
    from koordinator_tpu.api.objects import PodGroup

    store = make_store(3)
    store.add(KIND_POD_GROUP, PodGroup(
        meta=ObjectMeta(name="g-slow", namespace="default",
                        creation_timestamp=NOW),
        min_member=3, schedule_timeout_seconds=60))
    pend_pod(store, "gm1", labels={LABEL_POD_GROUP: "g-slow"})
    sched = Scheduler(store)
    sched.run_cycle(now=NOW)  # pending: minMember unmet
    assert "gang minMember" in scheduled_cond(store, "default/gm1").message
    sched.run_cycle(now=NOW + 120)  # past the schedule timeout -> Failed
    cond = scheduled_cond(store, "default/gm1")
    assert cond.status == "False"
    assert cond.message == "gang schedule timeout"
