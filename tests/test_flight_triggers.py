"""Flight-recorder trigger coverage beyond the serial path.

PR 5 pinned the ``cycle_exception`` trigger only through the serial
driver (`test_explain.test_cycle_exception_triggers_dump` patches
`_run_cycle_traced` wholesale). The fused-wave and mesh drivers have
their own failure surfaces — the wave replay after a successful
dispatch, and the ladder-exhausted path where even the host fallback
dies — and both must leave a schema-valid wreck behind and re-raise.
This file pins them, plus the ladder's own ``degradation`` dump reason.
"""

import pytest

from koordinator_tpu.obs.flight import load_bundle
from koordinator_tpu.scheduler import metrics as scheduler_metrics
from koordinator_tpu.scheduler.cycle import Scheduler
from koordinator_tpu.scheduler.degrade import (
    LEVEL_HOST_FALLBACK,
    LEVEL_NO_MESH,
    DegradationLadder,
)
from koordinator_tpu.scheduler.pipeline_parity import build_store_from_state
from koordinator_tpu.testing import synth_full_cluster

NOW = 1_000_000.0


def make_world(nodes=8, pods=24, seed=9):
    _cluster, state = synth_full_cluster(
        nodes, pods, seed=seed, num_quotas=0, num_gangs=0)
    return state, build_store_from_state(state)


def _dump_reason_count(reason: str) -> float:
    return scheduler_metrics.FLIGHT_DUMPS.get(reason=reason) or 0.0


def test_cycle_exception_dump_under_fused_waves(monkeypatch):
    """An exception in the WAVE REPLAY (after a clean fused dispatch —
    not a dispatch failure, so the ladder must NOT absorb it) dumps the
    flight ring with the wreck record and re-raises."""
    from koordinator_tpu.api.objects import ObjectMeta, Pod, PodSpec
    from koordinator_tpu.api.resources import ResourceList
    from koordinator_tpu.client.store import KIND_POD

    state, store = make_world()
    sched = Scheduler(store, waves=4, explain="off")
    sched.run_cycle(now=state.now)  # a healthy cycle in the ring first
    for i in range(6):  # fresh pending pods so the second cycle binds
        store.add(KIND_POD, Pod(
            meta=ObjectMeta(name=f"fresh-{i}", namespace="t",
                            uid=f"fresh-{i}",
                            creation_timestamp=state.now + 1),
            spec=PodSpec(requests=ResourceList.of(cpu=200,
                                                  memory=1 << 28))))
    before = sched.flight.dumps
    metric_before = _dump_reason_count("cycle_exception")

    def boom(*a, **k):
        raise RuntimeError("bind exploded mid-replay")

    monkeypatch.setattr(sched, "_reserve_and_bind", boom)
    with pytest.raises(RuntimeError, match="mid-replay"):
        sched.run_cycle(now=state.now + 5)
    assert sched.flight.dumps == before + 1
    assert _dump_reason_count("cycle_exception") == metric_before + 1
    records = sched.flight.snapshot()
    assert records[-1]["error"].startswith("RuntimeError")
    # the wreck came from the fused driver: its kernel span ran with waves
    kernel = [s for s in records[-1]["spans"] if s["name"] == "kernel"]
    assert kernel and kernel[0]["attrs"].get("waves") == "4"
    # the ladder saw no DISPATCH failure: no demotion happened
    assert sched.ladder.level == 0
    _h, _r, errors = load_bundle(sched.flight.dump("post").splitlines())
    assert not errors, errors


def test_cycle_exception_dump_when_ladder_exhausted_on_mesh(
        monkeypatch, cpu_devices):
    """The mesh path's worst case: every device dispatch fails AND the
    host fallback itself dies. The ladder walks mesh -> ... -> host
    fallback (degradation dumps along the way), the bottom rung raises,
    and the cycle driver dumps cycle_exception + re-raises — the ladder
    never turns a genuinely unservable cycle into silence."""
    state, store = make_world()
    sched = Scheduler(store, waves=1, explain="off", mesh=2,
                      ladder=DegradationLadder(promote_after=4))
    sched.fault_injector = lambda stage: (_ for _ in ()).throw(
        RuntimeError(f"device dead ({stage})"))
    import koordinator_tpu.scheduler.cycle as cycle_mod

    def host_dead(fc, pods, n_real):
        raise RuntimeError("host fallback dead too")

    monkeypatch.setattr(cycle_mod, "host_fallback_schedule", host_dead)
    degr_before = _dump_reason_count("degradation")
    exc_before = _dump_reason_count("cycle_exception")
    with pytest.raises(RuntimeError, match="host fallback dead"):
        sched.run_cycle(now=state.now)
    assert sched.ladder.level == LEVEL_HOST_FALLBACK
    # one degradation dump per demotion: full -> no-mesh, then (waves and
    # explain were never on, so those rungs are skipped) -> host-fallback
    assert _dump_reason_count("degradation") == degr_before + 2
    assert _dump_reason_count("cycle_exception") == exc_before + 1
    records = sched.flight.snapshot()
    assert "host fallback dead" in records[-1]["error"]
    _h, _r, errors = load_bundle(sched.flight.dump("post").splitlines())
    assert not errors, errors


def test_degradation_dump_carries_prior_cycles(cpu_devices):
    """A ladder transition dumps the ring: the bundle holds the healthy
    cycles BEFORE the incident — the incident context — and validates."""
    from koordinator_tpu.api.objects import ObjectMeta, Pod, PodSpec
    from koordinator_tpu.api.resources import ResourceList
    from koordinator_tpu.client.store import KIND_POD

    state, store = make_world()
    sched = Scheduler(store, waves=1, explain="off", mesh=2,
                      ladder=DegradationLadder(promote_after=4))
    sched.run_cycle(now=state.now)
    sched.run_cycle(now=state.now + 5)
    for i in range(4):  # fresh pending pods so the next cycle dispatches
        store.add(KIND_POD, Pod(
            meta=ObjectMeta(name=f"fresh-{i}", namespace="t",
                            uid=f"fresh-{i}",
                            creation_timestamp=state.now + 6),
            spec=PodSpec(requests=ResourceList.of(cpu=200,
                                                  memory=1 << 28))))
    budget = {"n": 2}

    def flaky(stage):
        if budget["n"] > 0:
            budget["n"] -= 1
            raise RuntimeError("transient mesh fault")

    sched.fault_injector = flaky
    before = sched.flight.dumps
    res = sched.run_cycle(now=state.now + 10)  # retry fails -> demote, succeeds
    assert res.duration_seconds > 0
    assert sched.ladder.level == LEVEL_NO_MESH
    assert sched.flight.dumps == before + 1
    body = sched.flight.dump("post")
    header, records, errors = load_bundle(body.splitlines())
    assert not errors, errors
    assert len(records) >= 2  # the pre-incident cycles are in the bundle


def test_deferred_store_write_failure_bypasses_the_ladder(monkeypatch):
    """A store-write failure in the deferred condition flush runs INSIDE
    the dispatch window (pipeline overlap), but it is a host/store
    fault, not a device fault: the ladder must not absorb it — no
    retry, no demotion (shedding device capability cannot fix a store)
    — it re-raises as a cycle exception and dumps the wreck."""
    from koordinator_tpu.api.objects import Node, ObjectMeta, Pod, PodSpec
    from koordinator_tpu.api.resources import ResourceList
    from koordinator_tpu.client.store import KIND_NODE, KIND_POD, ObjectStore
    from koordinator_tpu.scheduler.cycle import CyclePipeline

    store = ObjectStore()
    store.add(KIND_NODE, Node(
        meta=ObjectMeta(name="n0", namespace=""),
        allocatable=ResourceList.of(cpu=2000, memory=8 << 30, pods=20)))

    def pend(name, cpu):
        store.add(KIND_POD, Pod(
            meta=ObjectMeta(name=name, uid=name, creation_timestamp=NOW),
            spec=PodSpec(requests=ResourceList.of(cpu=cpu,
                                                  memory=1 << 28))))

    pend("too-big", 64000)  # unschedulable: its condition write defers
    sched = Scheduler(store)
    pipeline = CyclePipeline(sched, enabled=True)
    pipeline.run_cycle(now=NOW)
    assert len(sched._deferred_diagnose) == 1

    metric_before = _dump_reason_count("cycle_exception")
    retries_before = (scheduler_metrics.DISPATCH_RETRIES.get(stage="serial")
                      or 0.0)
    orig_update = store.update
    orig_update_many = store.update_many

    def _is_target(obj):
        return getattr(getattr(obj, "meta", None), "key", "") == (
            "default/too-big")

    def faulty_update(kind, obj, **kw):
        if _is_target(obj):
            raise RuntimeError("injected store-write fault")
        return orig_update(kind, obj, **kw)

    def faulty_update_many(kind, objs):
        # overlapped replay batches the deferred flush into ONE
        # update_many transaction — the fault must hit that path too
        if any(_is_target(o) for o in objs):
            raise RuntimeError("injected store-write fault")
        return orig_update_many(kind, objs)

    monkeypatch.setattr(store, "update", faulty_update)
    monkeypatch.setattr(store, "update_many", faulty_update_many)
    pend("late", 500)  # next cycle has a kernel window -> in-window flush
    with pytest.raises(RuntimeError, match="store-write fault"):
        pipeline.run_cycle(now=NOW + 2)
    # the ladder saw nothing: still full, no transition, no retry counted
    assert sched.ladder.level == 0
    assert sched.ladder.transitions == []
    assert (scheduler_metrics.DISPATCH_RETRIES.get(stage="serial")
            or 0.0) == retries_before
    # but the flight recorder kept the wreck
    assert _dump_reason_count("cycle_exception") == metric_before + 1
    records = sched.flight.snapshot()
    assert records[-1]["error"].startswith("RuntimeError")
