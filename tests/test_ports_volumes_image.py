"""NodePorts, CSI volume limits, VolumeZone, and ImageLocality — the stock
kube-scheduler capabilities the reference inherits by wrapping the upstream
scheduler app (/root/reference/cmd/koord-scheduler/main.go:53-62) — in the
batched chain, bit-identical across XLA, oracle, Pallas interpret, wave,
and the C++ floor."""

import numpy as np
import pytest

from koordinator_tpu.models.full_chain import build_full_chain_step
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.scheduler.parity import serial_schedule_full
from koordinator_tpu.scheduler.snapshot import build_full_chain_inputs
from koordinator_tpu.testing import synth_full_cluster


def _all_backends_agree(args, fc, pods, ng, ngroups, wave=8):
    from koordinator_tpu.models.wave_chain import build_wave_full_chain_step
    from koordinator_tpu.native import floor as native_floor
    from koordinator_tpu.ops.pallas_full_chain import (
        build_pallas_full_chain_step,
    )

    chosen = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    serial = serial_schedule_full(fc, args)
    n = len(pods.keys)
    np.testing.assert_array_equal(chosen[:n], serial[:n])
    chosen_p = np.asarray(
        build_pallas_full_chain_step(args, ng, ngroups, interpret=True)(fc)[0])
    np.testing.assert_array_equal(chosen, chosen_p)
    chosen_w = np.asarray(
        build_wave_full_chain_step(args, ng, ngroups, wave=wave)(fc)[0])
    np.testing.assert_array_equal(chosen, chosen_w)
    if native_floor.available() or native_floor.build():
        chosen_nat = native_floor.serial_schedule_full_native(
            fc, args, num_groups=ngroups)
        np.testing.assert_array_equal(chosen[:n], chosen_nat[:n])
    return chosen


def test_host_port_conflicts_spread_pods_across_nodes():
    """Two pods wanting the same hostPort can never share a node; an
    existing pod's bound port blocks its node entirely."""
    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(4, 6, seed=3, num_gangs=0,
                                        num_quotas=0)
    # existing pod binds 8080 on its node
    existing = next(p for p in state.pods_by_key.values()
                    if p.is_assigned and not p.is_terminated)
    existing.spec.host_ports.append(("TCP", 8080))
    blocked_node = existing.spec.node_name
    for pod in state.pending_pods:
        pod.spec.host_ports.append(("TCP", 8080))
    fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    assert fc.port_used.shape[1] == 1
    assert (np.asarray(fc.port_used) > 0).sum() == 1
    chosen = _all_backends_agree(args, fc, pods, ng, ngroups)
    n = len(pods.keys)
    placed_nodes = [state.nodes[chosen[i]].meta.name
                    for i in range(n) if chosen[i] >= 0]
    # 4 nodes, 1 already bound: exactly 3 pending pods place, all distinct
    assert len(placed_nodes) == 3
    assert len(set(placed_nodes)) == 3
    assert blocked_node not in placed_nodes


def test_distinct_ports_do_not_conflict():
    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(3, 4, seed=7, num_gangs=0,
                                        num_quotas=0)
    for i, pod in enumerate(state.pending_pods):
        pod.spec.host_ports.append(("TCP", 9000 + i))
    fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    chosen = _all_backends_agree(args, fc, pods, ng, ngroups)
    assert (chosen[: len(pods.keys)] >= 0).all()


def test_csi_volume_limit_caps_attachments():
    """A node reporting attachable_volume_limit takes only as many PVC
    volumes; pods overflow to unlimited nodes or stay pending."""
    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(2, 6, seed=11, num_gangs=0,
                                        num_quotas=0)
    state.nodes[0].attachable_volume_limit = 2
    state.nodes[1].attachable_volume_limit = 2
    for i, pod in enumerate(state.pending_pods):
        pod.spec.pvc_names = [f"claim-{i}"]
    fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    assert np.isfinite(np.asarray(fc.vol_free)[:2]).all()
    chosen = _all_backends_agree(args, fc, pods, ng, ngroups)
    n = len(pods.keys)
    placed = [int(chosen[i]) for i in range(n) if chosen[i] >= 0]
    assert len(placed) == 4  # 2 volumes per node max
    from collections import Counter

    assert max(Counter(placed).values()) <= 2


def test_volume_zone_pins_pod_to_pv_zone():
    """A pod mounting a claim bound to a zoned PV may only land in that
    zone (VolumeZone filter riding the admission bitmask)."""
    from koordinator_tpu.api.objects import (
        ObjectMeta,
        PersistentVolume,
        PersistentVolumeClaim,
    )

    ZONE = "topology.kubernetes.io/zone"
    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(9, 6, seed=13, num_gangs=0,
                                        num_quotas=0)
    for j, node in enumerate(state.nodes):
        node.meta.labels[ZONE] = f"z{j % 3}"
    pv = PersistentVolume(meta=ObjectMeta(name="pv-a", namespace=""))
    pv.meta.labels[ZONE] = "z1"
    ns = state.pending_pods[0].meta.namespace
    pvc = PersistentVolumeClaim(
        meta=ObjectMeta(name="data", namespace=ns), volume_name="pv-a")
    state.pvs = {"pv-a": pv}
    state.pvcs = {pvc.meta.key: pvc}
    for pod in state.pending_pods:
        pod.spec.pvc_names = ["data"]
    fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    chosen = _all_backends_agree(args, fc, pods, ng, ngroups)
    n = len(pods.keys)
    zones = {state.nodes[chosen[i]].meta.labels[ZONE]
             for i in range(n) if chosen[i] >= 0}
    assert zones == {"z1"}, zones


def test_image_locality_prefers_nodes_with_the_image():
    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(12, 12, seed=17, num_gangs=0,
                                        num_quotas=0)
    MB = 1024 * 1024
    for j, node in enumerate(state.nodes):
        if j % 3 == 0:
            node.images["registry/app:v1"] = 500 * MB
    for pod in state.pending_pods:
        pod.spec.images = ["registry/app:v1"]
    fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    assert (np.asarray(fc.pod_img_id)[: len(pods.keys)] >= 0).all()
    assert (np.asarray(fc.img_scores) > 0).any()
    # the score rows strictly favor image-holding nodes
    rows = np.asarray(fc.img_scores)
    have = [j for j, node in enumerate(state.nodes) if node.images]
    lack = [j for j, node in enumerate(state.nodes) if not node.images]
    assert rows[have, 0].min() > rows[lack, 0].max()
    chosen = _all_backends_agree(args, fc, pods, ng, ngroups)
    n = len(pods.keys)
    on_img = total = 0
    for i in range(n):
        if chosen[i] < 0:
            continue
        total += 1
        on_img += "registry/app:v1" in state.nodes[chosen[i]].images
    # directional: ImageLocality is ONE score among LoadAware/NUMA spread
    # incentives (upstream weights it equally), so pods land on the 1/3 of
    # image-holding nodes MORE often than capacity spreading alone would
    assert total > 0 and on_img > total / 3, (on_img, total)


def test_port_slot_overflow_marks_pods_unschedulable():
    from koordinator_tpu.ops.ports import MAX_PORT_SLOTS

    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(8, MAX_PORT_SLOTS + 4, seed=19,
                                        num_gangs=0, num_quotas=0)
    for i, pod in enumerate(state.pending_pods):
        pod.spec.host_ports.append(("TCP", 10000 + i))
    fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    assert fc.port_used.shape[1] == MAX_PORT_SLOTS
    chosen = _all_backends_agree(args, fc, pods, ng, ngroups)
    assert (chosen[: len(pods.keys)] < 0).sum() >= 4


def test_pallas_volume_less_variant_parity():
    """The selector compiles OUT the volume machinery for volume-less
    batches (enable_volumes=False); that variant must stay bit-identical
    to the XLA step — CI coverage for the production common case."""
    from koordinator_tpu.ops.pallas_full_chain import (
        build_pallas_full_chain_step,
    )

    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(14, 20, seed=23)
    fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    assert not (np.asarray(fc.vol_needed) > 0).any()
    ref = np.asarray(build_full_chain_step(args, ng, ngroups)(fc)[0])
    novol = np.asarray(build_pallas_full_chain_step(
        args, ng, ngroups, interpret=True, enable_volumes=False)(fc)[0])
    np.testing.assert_array_equal(novol, ref)


def test_cycle_driver_feeds_pvcs_and_pvs():
    """End-to-end through the cycle driver: VolumeZone pins via the store's
    PVC/PV objects."""
    from koordinator_tpu.api.objects import (
        Node,
        ObjectMeta,
        PersistentVolume,
        PersistentVolumeClaim,
        Pod,
        PodSpec,
    )
    from koordinator_tpu.api.resources import ResourceList
    from koordinator_tpu.client.store import (
        KIND_NODE,
        KIND_POD,
        KIND_PV,
        KIND_PVC,
        ObjectStore,
    )
    from koordinator_tpu.scheduler.cycle import Scheduler

    ZONE = "topology.kubernetes.io/zone"
    GIB = 1024**3
    store = ObjectStore()
    for i in range(4):
        node = Node(meta=ObjectMeta(name=f"n{i}", namespace=""),
                    allocatable=ResourceList.of(cpu=8000, memory=32 * GIB,
                                                pods=20))
        node.meta.labels[ZONE] = f"z{i % 2}"
        store.add(KIND_NODE, node)
    pv = PersistentVolume(meta=ObjectMeta(name="pv-z0", namespace=""))
    pv.meta.labels[ZONE] = "z0"
    store.add(KIND_PV, pv)
    store.add(KIND_PVC, PersistentVolumeClaim(
        meta=ObjectMeta(name="data", namespace="default"),
        volume_name="pv-z0"))
    pod = Pod(meta=ObjectMeta(name="db", uid="db", creation_timestamp=1.0),
              spec=PodSpec(requests=ResourceList.of(cpu=1000, memory=GIB)))
    pod.spec.pvc_names = ["data"]
    store.add(KIND_POD, pod)
    result = Scheduler(store).run_cycle(now=1_000_000.0)
    by_pod = {b.pod_key: b.node_name for b in result.bound}
    assert by_pod.get("default/db") in ("n0", "n2")  # the z0 nodes


def test_csi_already_attached_claims_exempt():
    """Upstream NodeVolumeLimits counts only NEW attachments: a node at its
    CSI limit still admits a pod whose claims are already attached there
    (shared RWX volume / pod replacement), while a node without the claim
    rejects — the volume-group encoding, bit-identical in every backend."""
    args = LoadAwareArgs()
    cluster, state = synth_full_cluster(2, 4, seed=13, num_gangs=0,
                                        num_quotas=0)
    # both nodes fully at their volume limit via existing pods
    existing = [p for p in state.pods_by_key.values()
                if p.is_assigned and not p.is_terminated]
    node0, node1 = (n.meta.name for n in state.nodes[:2])
    for node_name in (node0, node1):
        ex = next(p for p in existing if p.spec.node_name == node_name)
        ex.spec.pvc_names = [f"vol-{node_name}"]
    for node in state.nodes[:2]:
        node.attachable_volume_limit = 1
    # pending pod 0 mounts node0's already-attached claim; pod 1 mounts a
    # fresh claim (no headroom anywhere -> stays pending)
    p0, p1 = state.pending_pods[0], state.pending_pods[1]
    p0.spec.pvc_names = [f"vol-{node0}"]
    p0.meta.namespace = next(p for p in existing
                             if p.spec.node_name == node0).meta.namespace
    p1.spec.pvc_names = ["brand-new-claim"]
    for pod in state.pending_pods[2:]:
        pod.spec.pvc_names = []
    fc, pods, nodes, tree, gi, ng, ngroups = build_full_chain_inputs(
        state, args)
    assert fc.vol_needed.shape[1] > 1  # the exemption groups materialized
    chosen = _all_backends_agree(args, fc, pods, ng, ngroups)
    placed = {pods.keys[i]: int(chosen[i]) for i in range(len(pods.keys))}
    assert placed[p0.meta.key] == 0  # admitted where its claim lives
    assert placed[p1.meta.key] == -1  # no node has a free attachment slot
