"""Tests for the extended webhook handlers: node resource amplification,
multi-quota-tree affinity injection, resource verify, quota deletion guard,
and the generic admit dispatcher (reference pkg/webhook/node/plugins/
resourceamplification, pod/mutating/multi_quota_tree_affinity.go,
webhook/elasticquota)."""

import json

import pytest

from koordinator_tpu.api.objects import (
    LABEL_POD_QOS,
    LABEL_QUOTA_IS_PARENT,
    LABEL_QUOTA_NAME,
    LABEL_QUOTA_PARENT,
    LABEL_QUOTA_TREE_ID,
    ElasticQuota,
    ElasticQuotaProfile,
    Node,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.api.resources import ResourceList, ResourceName
from koordinator_tpu.client.store import (
    KIND_ELASTIC_QUOTA,
    KIND_NODE,
    KIND_QUOTA_PROFILE,
    ObjectStore,
)
from koordinator_tpu.utils.features import MANAGER_GATES
from koordinator_tpu.webhook.server import AdmissionError, AdmissionServer

GIB = 1024**3
RATIO_ANN = AdmissionServer.AMPLIFICATION_RATIO_ANNOTATION
RAW_ANN = AdmissionServer.RAW_ALLOCATABLE_ANNOTATION


def mk_node(cpu=16_000, mem=64 * GIB, annotations=None):
    return Node(meta=ObjectMeta(name="n0", namespace="",
                                annotations=annotations or {}),
                allocatable=ResourceList.of(cpu=cpu, memory=mem))


class TestNodeAmplification:
    def test_amplify_and_remember_raw(self):
        srv = AdmissionServer(ObjectStore())
        node = mk_node(annotations={RATIO_ANN: json.dumps({"cpu": 2.0})})
        srv.mutate_node(node)
        assert node.allocatable.get(ResourceName.CPU) == 32_000
        assert node.allocatable.get(ResourceName.MEMORY) == 64 * GIB  # no ratio
        raw = json.loads(node.meta.annotations[RAW_ANN])
        assert raw[ResourceName.CPU] == 16_000

    def test_repeat_admission_does_not_compound(self):
        srv = AdmissionServer(ObjectStore())
        node = mk_node(annotations={RATIO_ANN: json.dumps({"cpu": 2.0})})
        srv.mutate_node(node)
        before = node.allocatable.get(ResourceName.CPU)
        srv.mutate_node(node, old=node)
        assert node.allocatable.get(ResourceName.CPU) == before == 32_000

    def test_kubelet_change_refreshes_raw(self):
        srv = AdmissionServer(ObjectStore())
        node = mk_node(annotations={RATIO_ANN: json.dumps({"cpu": 2.0})})
        srv.mutate_node(node)
        # kubelet reduces allocatable (more reserved): cpu raw becomes 8000
        old = mk_node(cpu=32_000, annotations=dict(node.meta.annotations))
        node.allocatable.quantities[ResourceName.CPU] = 8_000
        srv.mutate_node(node, old=old)
        assert json.loads(node.meta.annotations[RAW_ANN])[ResourceName.CPU] == 8_000
        assert node.allocatable.get(ResourceName.CPU) == 16_000

    def test_clearing_ratio_restores_raw(self):
        srv = AdmissionServer(ObjectStore())
        node = mk_node(annotations={RATIO_ANN: json.dumps({"cpu": 2.0})})
        srv.mutate_node(node)
        del node.meta.annotations[RATIO_ANN]
        srv.mutate_node(node)
        assert node.allocatable.get(ResourceName.CPU) == 16_000
        assert RAW_ANN not in node.meta.annotations

    def test_ratio_below_one_ignored(self):
        srv = AdmissionServer(ObjectStore())
        node = mk_node(annotations={RATIO_ANN: json.dumps({"cpu": 0.5})})
        srv.mutate_node(node)
        assert node.allocatable.get(ResourceName.CPU) == 16_000

    def test_bad_json_rejected(self):
        srv = AdmissionServer(ObjectStore())
        node = mk_node(annotations={RATIO_ANN: "not-json"})
        with pytest.raises(AdmissionError):
            srv.mutate_node(node)

    def test_non_object_json_rejected(self):
        # valid JSON but not an object: must be an admission error, not an
        # AttributeError escaping the handler
        srv = AdmissionServer(ObjectStore())
        node = mk_node(annotations={RATIO_ANN: json.dumps("5")})
        with pytest.raises(AdmissionError):
            srv.mutate_node(node)
        node = mk_node(annotations={RATIO_ANN: json.dumps([2.0])})
        with pytest.raises(AdmissionError):
            srv.mutate_node(node)

    def test_non_numeric_ratio_rejected(self):
        srv = AdmissionServer(ObjectStore())
        node = mk_node(annotations={RATIO_ANN: json.dumps({"cpu": "x"})})
        with pytest.raises(AdmissionError):
            srv.mutate_node(node)
        for nonfinite in ("inf", "nan", 1e400):
            node = mk_node(annotations={RATIO_ANN: json.dumps({"cpu": nonfinite})})
            with pytest.raises(AdmissionError):
                srv.mutate_node(node)
        node = mk_node(annotations={RATIO_ANN: json.dumps({"cpu": None})})
        srv.mutate_node(node)  # explicit null = no ratio for cpu
        assert node.allocatable.get(ResourceName.CPU) == 16_000


class TestQuotaTreeAffinity:
    def _setup(self):
        store = ObjectStore()
        store.add(KIND_ELASTIC_QUOTA, ElasticQuota(
            meta=ObjectMeta(name="team-a", namespace="",
                            labels={LABEL_QUOTA_TREE_ID: "tree-1"}),
            min=ResourceList.of(cpu=1000)))
        store.add(KIND_QUOTA_PROFILE, ElasticQuotaProfile(
            meta=ObjectMeta(name="prof-1"),
            quota_name="team-a",
            node_selector={"zone": "z1"},
            quota_labels={LABEL_QUOTA_TREE_ID: "tree-1"}))
        return store, AdmissionServer(store)

    def test_selector_injected(self):
        store, srv = self._setup()
        pod = Pod(meta=ObjectMeta(name="p",
                                  labels={LABEL_POD_QOS: "LS",
                                          LABEL_QUOTA_NAME: "team-a"}),
                  spec=PodSpec(requests=ResourceList.of(cpu=1000)))
        srv.mutate_pod(pod)
        assert pod.spec.node_selector == {"zone": "z1"}

    def test_existing_selector_not_overwritten(self):
        store, srv = self._setup()
        pod = Pod(meta=ObjectMeta(name="p",
                                  labels={LABEL_POD_QOS: "LS",
                                          LABEL_QUOTA_NAME: "team-a"}),
                  spec=PodSpec(requests=ResourceList.of(cpu=1000),
                               node_selector={"zone": "keep"}))
        srv.mutate_pod(pod)
        assert pod.spec.node_selector["zone"] == "keep"

    def test_no_tree_no_injection(self):
        store, srv = self._setup()
        pod = Pod(meta=ObjectMeta(name="p", labels={LABEL_POD_QOS: "LS"}),
                  spec=PodSpec(requests=ResourceList.of(cpu=1000)))
        srv.mutate_pod(pod)
        assert pod.spec.node_selector == {}


class TestResourceVerifyAndQuotaDelete:
    def test_request_over_limit_rejected(self):
        srv = AdmissionServer(ObjectStore())
        pod = Pod(meta=ObjectMeta(name="p", labels={LABEL_POD_QOS: "LS"}),
                  spec=PodSpec(requests=ResourceList.of(cpu=4000),
                               limits=ResourceList.of(cpu=2000)))
        with pytest.raises(AdmissionError, match="exceeds limit"):
            srv.validate_pod(pod)

    def test_parent_with_children_cannot_be_deleted(self):
        store = ObjectStore()
        parent = ElasticQuota(meta=ObjectMeta(
            name="root", namespace="",
            labels={LABEL_QUOTA_IS_PARENT: "true"}))
        child = ElasticQuota(meta=ObjectMeta(
            name="leaf", namespace="",
            labels={LABEL_QUOTA_PARENT: "root"}))
        store.add(KIND_ELASTIC_QUOTA, parent)
        store.add(KIND_ELASTIC_QUOTA, child)
        srv = AdmissionServer(store)
        with pytest.raises(AdmissionError, match="children"):
            srv.validate_elastic_quota_delete(parent)
        srv.validate_elastic_quota_delete(child)  # leaves delete fine

    def test_admit_dispatcher(self):
        store = ObjectStore()
        srv = AdmissionServer(store)
        MANAGER_GATES.set_from_map({"NodeMutatingWebhook": True})
        try:
            node = mk_node(annotations={RATIO_ANN: json.dumps({"cpu": 2.0})})
            srv.admit(KIND_NODE, node)
            assert node.allocatable.get(ResourceName.CPU) == 32_000
        finally:
            MANAGER_GATES.reset()


def _quota(name, parent="", is_parent=False, min_rl=None, max_rl=None):
    labels = {}
    if parent:
        labels[LABEL_QUOTA_PARENT] = parent
    if is_parent:
        labels[LABEL_QUOTA_IS_PARENT] = "true"
    return ElasticQuota(meta=ObjectMeta(name=name, namespace="", labels=labels),
                        min=min_rl or ResourceList(),
                        max=max_rl or ResourceList())


class TestQuotaTopologyChecks:
    """quota_topology_check.go invariants: sibling/children min sums, max-key
    subsetting, isParent flips."""

    def _store_with_parent(self, parent_min=None, parent_max=None):
        store = ObjectStore()
        store.add(KIND_ELASTIC_QUOTA, _quota(
            "parent", is_parent=True,
            min_rl=parent_min or ResourceList.of(cpu=10_000),
            max_rl=parent_max or ResourceList.of(cpu=20_000)))
        return store, AdmissionServer(store)

    def test_sibling_min_sum_exceeding_parent_min_rejected(self):
        store, srv = self._store_with_parent()
        store.add(KIND_ELASTIC_QUOTA, _quota(
            "a", parent="parent", min_rl=ResourceList.of(cpu=7_000)))
        ok = _quota("b", parent="parent", min_rl=ResourceList.of(cpu=3_000))
        srv.validate_elastic_quota(ok)
        bad = _quota("c", parent="parent", min_rl=ResourceList.of(cpu=4_000))
        with pytest.raises(AdmissionError, match="sibling min"):
            srv.validate_elastic_quota(bad)

    def test_max_key_not_in_parent_rejected(self):
        store, srv = self._store_with_parent()
        bad = _quota("a", parent="parent",
                     max_rl=ResourceList.of(cpu=1_000, memory=GIB))
        with pytest.raises(AdmissionError, match="max keys"):
            srv.validate_elastic_quota(bad)

    def test_shrinking_min_below_children_sum_rejected(self):
        store, srv = self._store_with_parent()
        store.add(KIND_ELASTIC_QUOTA, _quota(
            "a", parent="parent", min_rl=ResourceList.of(cpu=6_000)))
        shrunk = _quota("parent", is_parent=True,
                        min_rl=ResourceList.of(cpu=5_000),
                        max_rl=ResourceList.of(cpu=20_000))
        with pytest.raises(AdmissionError, match="children min"):
            srv.validate_elastic_quota(shrunk)

    def test_is_parent_flip_with_children_rejected(self):
        store, srv = self._store_with_parent()
        store.add(KIND_ELASTIC_QUOTA, _quota("a", parent="parent"))
        now_leaf = _quota("parent", is_parent=False,
                          min_rl=ResourceList.of(cpu=10_000))
        old = _quota("parent", is_parent=True,
                     min_rl=ResourceList.of(cpu=10_000))
        with pytest.raises(AdmissionError, match="isParent"):
            srv.validate_elastic_quota(now_leaf, old=old)

    def test_is_parent_flip_with_bound_pods_rejected(self):
        from koordinator_tpu.client.store import KIND_POD

        store = ObjectStore()
        store.add(KIND_ELASTIC_QUOTA, _quota("q"))
        store.add(KIND_POD, Pod(meta=ObjectMeta(
            name="p", labels={LABEL_QUOTA_NAME: "q"})))
        srv = AdmissionServer(store)
        flip = _quota("q", is_parent=True)
        with pytest.raises(AdmissionError, match="bound pods"):
            srv.validate_elastic_quota(flip, old=_quota("q"))

    def test_child_min_key_absent_from_parent_min_rejected(self):
        store, srv = self._store_with_parent()  # parent min has cpu only
        bad = _quota("a", parent="parent",
                     min_rl=ResourceList.of(memory=5 * GIB))
        with pytest.raises(AdmissionError, match="sibling min"):
            srv.validate_elastic_quota(bad)

    def test_is_parent_flip_with_namespace_default_pods_rejected(self):
        from koordinator_tpu.client.store import KIND_POD

        store = ObjectStore()
        store.add(KIND_ELASTIC_QUOTA, _quota("team-a"))
        store.add(KIND_POD, Pod(meta=ObjectMeta(
            name="p", namespace="team-a")))  # no quota label: ns default
        srv = AdmissionServer(store)
        with pytest.raises(AdmissionError, match="bound pods"):
            srv.validate_elastic_quota(_quota("team-a", is_parent=True),
                                       old=_quota("team-a"))


class TestProfileMatching:
    """cluster_colocation_profile.go namespaceSelector + Probability."""

    def _store(self, probability=None, ns_selector=None, ns_labels=None):
        from koordinator_tpu.api.objects import (
            ClusterColocationProfile,
            Namespace,
        )
        from koordinator_tpu.client.store import (
            KIND_COLOCATION_PROFILE,
            KIND_NAMESPACE,
        )

        store = ObjectStore()
        store.add(KIND_COLOCATION_PROFILE, ClusterColocationProfile(
            meta=ObjectMeta(name="profile", namespace=""),
            namespace_selector=ns_selector or {},
            probability=probability,
            labels={"injected": "yes"}))
        if ns_labels is not None:
            store.add(KIND_NAMESPACE, Namespace(
                meta=ObjectMeta(name="team-a", namespace="",
                                labels=ns_labels)))
        return store, AdmissionServer(store)

    def test_namespace_selector_matches(self):
        store, srv = self._store(ns_selector={"env": "prod"},
                                 ns_labels={"env": "prod"})
        pod = Pod(meta=ObjectMeta(name="p", namespace="team-a"))
        srv.mutate_pod(pod)
        assert pod.meta.labels.get("injected") == "yes"

    def test_namespace_selector_mismatch_skips(self):
        store, srv = self._store(ns_selector={"env": "prod"},
                                 ns_labels={"env": "dev"})
        pod = Pod(meta=ObjectMeta(name="p", namespace="team-a"))
        srv.mutate_pod(pod)
        assert "injected" not in pod.meta.labels

    def test_missing_namespace_object_skips(self):
        store, srv = self._store(ns_selector={"env": "prod"})
        pod = Pod(meta=ObjectMeta(name="p", namespace="team-a"))
        srv.mutate_pod(pod)
        assert "injected" not in pod.meta.labels

    def test_probability_zero_always_skips(self):
        store, srv = self._store(probability=0)
        pod = Pod(meta=ObjectMeta(name="p"))
        srv.mutate_pod(pod)
        assert "injected" not in pod.meta.labels

    def test_probability_hundred_always_applies(self):
        store, srv = self._store(probability=100)
        pod = Pod(meta=ObjectMeta(name="p"))
        srv.mutate_pod(pod)
        assert pod.meta.labels.get("injected") == "yes"

    def test_probability_draw_uses_injected_rand(self):
        import koordinator_tpu.webhook.server as websrv

        store, srv = self._store(probability=50)
        try:
            websrv._rand_intn = lambda n: 99  # above percent -> skip
            pod = Pod(meta=ObjectMeta(name="p"))
            srv.mutate_pod(pod)
            assert "injected" not in pod.meta.labels
            websrv._rand_intn = lambda n: 10  # below percent -> apply
            pod2 = Pod(meta=ObjectMeta(name="p2"))
            srv.mutate_pod(pod2)
            assert pod2.meta.labels.get("injected") == "yes"
        finally:
            websrv._rand_intn = None

    def test_reserve_pod_annotation_forbidden(self):
        from koordinator_tpu.api.objects import ANNOTATION_RESERVE_POD

        srv = AdmissionServer(ObjectStore())
        pod = Pod(meta=ObjectMeta(
            name="p", labels={LABEL_POD_QOS: "LS"},
            annotations={ANNOTATION_RESERVE_POD: "true"}))
        with pytest.raises(AdmissionError, match="cannot be set"):
            srv.validate_pod(pod)
