"""Tests for scheduler componentconfig (defaults/validation/strict decode),
the Reservation GC controller, and PodGroup timeout handling (reference
pkg/scheduler/apis/config, plugins/reservation/controller,
plugins/coscheduling/controller/podgroup.go)."""

import pytest

from koordinator_tpu.api.objects import (
    LABEL_POD_GROUP,
    LABEL_POD_QOS,
    Node,
    ObjectMeta,
    Pod,
    PodGroup,
    PodSpec,
    Reservation,
    ReservationOwner,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_POD,
    KIND_POD_GROUP,
    KIND_RESERVATION,
    ObjectStore,
)
from koordinator_tpu.scheduler import config as schedcfg
from koordinator_tpu.scheduler.cycle import Scheduler
from koordinator_tpu.scheduler.plugins.reservation import (
    ReservationController,
    ReservationPlugin,
)

GIB = 1024**3
NOW = 1_000_000.0


class TestComponentConfig:
    def test_defaults_validate(self):
        schedcfg.SchedulerConfiguration().validate()

    def test_invalid_fields_aggregate(self):
        cfg = schedcfg.SchedulerConfiguration()
        cfg.node_numa_resource.default_cpu_bind_policy = "Bogus"
        cfg.coscheduling.default_timeout_seconds = -1
        cfg.load_aware.usage_thresholds = {"cpu": 150}
        with pytest.raises(schedcfg.ConfigValidationError) as e:
            cfg.validate()
        assert len(e.value.errors) == 3

    def test_from_dict_defaults_and_overrides(self):
        cfg = schedcfg.from_dict({
            "Reservation": {"gc_duration_seconds": 60.0},
            "Coscheduling": {},
        })
        assert cfg.reservation.gc_duration_seconds == 60.0
        assert cfg.coscheduling.default_timeout_seconds == 600.0

    def test_from_dict_strict(self):
        with pytest.raises(schedcfg.ConfigValidationError) as e:
            schedcfg.from_dict({
                "NopePlugin": {},
                "Reservation": {"bogus_field": 1},
            })
        assert len(e.value.errors) == 2

    def test_scheduler_wires_config(self):
        store = ObjectStore()
        cfg = schedcfg.SchedulerConfiguration()
        cfg.node_numa_resource.max_ref_count = 3
        cfg.reservation.gc_duration_seconds = 1.0
        sched = Scheduler(store, config=cfg)
        assert sched.extender.plugin("NodeNUMAResource").max_ref_count == 3
        assert sched.reservation_controller.gc_duration == 1.0

    def test_scheduler_rejects_invalid_config(self):
        cfg = schedcfg.SchedulerConfiguration()
        cfg.device_share.scoring_strategy = "Bogus"
        with pytest.raises(schedcfg.ConfigValidationError):
            Scheduler(ObjectStore(), config=cfg)


def _reservation(name, phase="Pending", node="", ttl=None, created=NOW,
                 allocate_once=True, owners=()):
    return Reservation(
        meta=ObjectMeta(name=name, namespace="", creation_timestamp=created),
        template=PodSpec(requests=ResourceList.of(cpu=1000, memory=GIB)),
        owners=list(owners) or [ReservationOwner()],
        ttl_seconds=ttl, phase=phase, node_name=node,
        allocatable=ResourceList.of(cpu=1000, memory=GIB))


class TestReservationController:
    def _setup(self, gc=100.0):
        store = ObjectStore()
        plugin = ReservationPlugin()
        plugin.register(store)
        ctl = ReservationController(plugin, store, gc_duration_seconds=gc)
        return store, plugin, ctl

    def test_expire_then_gc(self):
        store, plugin, ctl = self._setup(gc=100.0)
        store.add(KIND_RESERVATION,
                  _reservation("r1", ttl=50, created=NOW - 60))
        out = ctl.reconcile(NOW)
        assert out["expired"] == ["r1"]
        assert store.get(KIND_RESERVATION, "/r1").phase == "Failed"
        # still within gc window
        assert ctl.reconcile(NOW + 50)["deleted"] == []
        assert ctl.reconcile(NOW + 101)["deleted"] == ["r1"]
        assert store.get(KIND_RESERVATION, "/r1") is None

    def test_allocate_once_consumed_succeeds(self):
        store, plugin, ctl = self._setup()
        res = _reservation("r2", phase="Available", node="node-0")
        res.current_owners = ["default/p1"]
        store.add(KIND_RESERVATION, res)
        out = ctl.reconcile(NOW)
        assert out["succeeded"] == ["r2"]
        assert store.get(KIND_RESERVATION, "/r2").phase == "Succeeded"

    def test_live_reservation_untouched(self):
        store, plugin, ctl = self._setup()
        store.add(KIND_RESERVATION,
                  _reservation("r3", phase="Available", node="node-0",
                               allocate_once=False))
        out = ctl.reconcile(NOW + 10_000)
        assert out == {"expired": [], "succeeded": [], "deleted": []}


class TestPodGroupTimeout:
    def _gang_pod(self, name, gang):
        return Pod(
            meta=ObjectMeta(name=name, creation_timestamp=NOW - 700,
                            labels={LABEL_POD_QOS: "LS",
                                    LABEL_POD_GROUP: gang}),
            spec=PodSpec(requests=ResourceList.of(cpu=1000, memory=GIB)))

    def test_timed_out_gang_rejected(self):
        store = ObjectStore()
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name="node-0", namespace=""),
            allocatable=ResourceList.of(cpu=32_000, memory=64 * GIB)))
        # gang created 700s ago with 600s timeout and unreachable min_member
        store.add(KIND_POD_GROUP, PodGroup(
            meta=ObjectMeta(name="gang-a", creation_timestamp=NOW - 700),
            min_member=5, schedule_timeout_seconds=600))
        store.add(KIND_POD, self._gang_pod("m1", "gang-a"))
        sched = Scheduler(store)
        result = sched.run_cycle(now=NOW)
        assert result.rejected == ["default/m1"]
        assert store.get(KIND_POD_GROUP, "default/gang-a").phase == "Failed"
        # failure reason recorded through the dispatcher
        assert ("default/m1", "gang schedule timeout") in list(
            sched.extender.error_handlers.failures)

    def test_once_scheduled_gang_never_timeout_failed(self):
        """A gang that reached min-member must not be failed when a member
        later terminates, no matter how old the PodGroup is."""
        store = ObjectStore()
        store.add(KIND_POD_GROUP, PodGroup(
            meta=ObjectMeta(name="gang-c", creation_timestamp=NOW - 10_000),
            min_member=2, schedule_timeout_seconds=600))
        sched = Scheduler(store)
        gang = sched.extender.plugin("Coscheduling")
        gang.assumed["default/gang-c"] = 2
        gang.update_pod_group_status(store, NOW)
        assert store.get(KIND_POD_GROUP, "default/gang-c").phase == "Scheduled"
        gang.assumed["default/gang-c"] = 1  # member died
        gang.update_pod_group_status(store, NOW + 100)
        assert store.get(KIND_POD_GROUP, "default/gang-c").phase == "Scheduling"
        assert gang.timed_out_gangs() == []

    def test_default_timeout_from_config(self):
        import koordinator_tpu.scheduler.config as schedcfg_mod

        store = ObjectStore()
        cfg = schedcfg_mod.SchedulerConfiguration()
        cfg.coscheduling.default_timeout_seconds = 50.0
        # PodGroup leaves scheduleTimeoutSeconds unset (0) -> config default
        store.add(KIND_POD_GROUP, PodGroup(
            meta=ObjectMeta(name="gang-d", creation_timestamp=NOW - 60),
            min_member=2))
        sched = Scheduler(store, config=cfg)
        gang = sched.extender.plugin("Coscheduling")
        gang.update_pod_group_status(store, NOW)
        assert store.get(KIND_POD_GROUP, "default/gang-d").phase == "Failed"


class TestQuotaOveruseRevoke:
    def test_revoke_after_grace(self):
        from koordinator_tpu.api.objects import (
            LABEL_QUOTA_NAME,
            ElasticQuota,
        )
        from koordinator_tpu.client.store import KIND_ELASTIC_QUOTA
        from koordinator_tpu.scheduler.config import SchedulerConfiguration

        store = ObjectStore()
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name="node-0", namespace=""),
            allocatable=ResourceList.of(cpu=8000, memory=16 * GIB)))
        store.add(KIND_ELASTIC_QUOTA, ElasticQuota(
            meta=ObjectMeta(name="team-a", namespace=""),
            min=ResourceList.of(cpu=1000, memory=GIB),
            max=ResourceList.of(cpu=2000, memory=2 * GIB)))
        cfg = SchedulerConfiguration()
        cfg.elastic_quota.monitor_all_quotas = True
        cfg.elastic_quota.delay_evict_time_seconds = 100.0
        cfg.elastic_quota.revoke_pod_interval_seconds = 1.0
        sched = Scheduler(store, config=cfg)
        # a running pod way over the group's max (and hence over runtime)
        store.add(KIND_POD, Pod(
            meta=ObjectMeta(name="hog", owner_kind="ReplicaSet",
                            owner_name="rs-hog",
                            labels={LABEL_POD_QOS: "LS",
                                    LABEL_QUOTA_NAME: "team-a"}),
            spec=PodSpec(node_name="node-0",
                         requests=ResourceList.of(cpu=4000, memory=4 * GIB)),
            phase="Running"))
        ctl = sched.quota_revoke_controller
        assert ctl.reconcile(NOW) == []          # grace period
        assert ctl.reconcile(NOW + 50) == []     # still within grace
        evicted = ctl.reconcile(NOW + 150)
        assert evicted == ["default/hog"]
        assert store.get(KIND_POD, "default/hog").phase == "Failed"

    def test_disabled_by_default(self):
        store = ObjectStore()
        sched = Scheduler(store)
        assert sched.quota_revoke_controller.reconcile(NOW) == []


class TestPodGroupWithinTimeout:
    def test_gang_within_timeout_not_failed(self):
        store = ObjectStore()
        store.add(KIND_POD_GROUP, PodGroup(
            meta=ObjectMeta(name="gang-b", creation_timestamp=NOW - 10),
            min_member=2, schedule_timeout_seconds=600))
        sched = Scheduler(store)
        gang = sched.extender.plugin("Coscheduling")
        gang.update_pod_group_status(store, NOW)
        assert store.get(KIND_POD_GROUP, "default/gang-b").phase == "Pending"
        assert gang.timed_out_gangs() == []


class TestPerNodeColocationMetadata:
    """node_colocation.go: reclaim-ratio labels and the colocation-strategy
    annotation override the merged strategy per node."""

    def test_reclaim_ratio_labels_override(self):
        from koordinator_tpu.utils.sloconfig import (
            LABEL_CPU_RECLAIM_RATIO,
            ColocationConfig,
        )

        cfg = ColocationConfig()
        base = cfg.strategy_for_node({})
        assert base.cpu_reclaim_threshold_percent == 60
        s = cfg.strategy_for_node({LABEL_CPU_RECLAIM_RATIO: "0.8"})
        assert s.cpu_reclaim_threshold_percent == 80.0
        # out-of-bounds / malformed values are ignored
        s2 = cfg.strategy_for_node({LABEL_CPU_RECLAIM_RATIO: "1.5"})
        assert s2.cpu_reclaim_threshold_percent == 60
        s3 = cfg.strategy_for_node({LABEL_CPU_RECLAIM_RATIO: "abc"})
        assert s3.cpu_reclaim_threshold_percent == 60

    def test_strategy_annotation_merges_then_labels_win(self):
        import json

        from koordinator_tpu.utils.sloconfig import (
            ANNOTATION_NODE_COLOCATION_STRATEGY,
            LABEL_CPU_RECLAIM_RATIO,
            ColocationConfig,
        )

        cfg = ColocationConfig()
        ann = {ANNOTATION_NODE_COLOCATION_STRATEGY: json.dumps(
            {"cpuReclaimThresholdPercent": 70,
             "memoryReclaimThresholdPercent": 50})}
        s = cfg.strategy_for_node({LABEL_CPU_RECLAIM_RATIO: "0.9"}, ann)
        assert s.cpu_reclaim_threshold_percent == 90.0  # label wins last
        assert s.memory_reclaim_threshold_percent == 50
        # the shared cluster strategy object is never mutated
        assert cfg.cluster_strategy.cpu_reclaim_threshold_percent == 60


class TestHostApplicationConfig:
    """host-application-config renders into NodeSLO.extensions, per-node
    overridable (nodeslo_controller.go getHostApplicationConfig)."""

    def test_rendered_with_node_override(self):
        import json

        from koordinator_tpu.api.objects import ConfigMap, Node, ObjectMeta
        from koordinator_tpu.api.resources import ResourceList
        from koordinator_tpu.client.store import (
            KIND_CONFIG_MAP,
            KIND_NODE,
            KIND_NODE_SLO,
            ObjectStore,
        )
        from koordinator_tpu.slocontroller.nodeslo import NodeSLOController
        from koordinator_tpu.utils.sloconfig import CONFIG_MAP_NAME

        store = ObjectStore()
        for name, labels in (("plain", {}), ("edge", {"tier": "edge"})):
            store.add(KIND_NODE, Node(
                meta=ObjectMeta(name=name, namespace="", labels=labels),
                allocatable=ResourceList.of(cpu=8000)))
        store.add(KIND_CONFIG_MAP, ConfigMap(
            meta=ObjectMeta(name=CONFIG_MAP_NAME, namespace="koordinator-system"),
            data={"host-application-config": json.dumps({
                "applications": [
                    {"name": "nginx", "cgroupPath": "host/nginx",
                     "qos": "LS"}],
                "nodeConfigs": [{
                    "nodeSelector": {"tier": "edge"},
                    "applications": [
                        {"name": "edge-proxy", "cgroupPath": "host/proxy",
                         "qos": "BE"}],
                }],
            })}))
        NodeSLOController(store).reconcile()
        plain = store.get(KIND_NODE_SLO, "/plain")
        assert plain.extensions["hostApplications"][0]["name"] == "nginx"
        edge = store.get(KIND_NODE_SLO, "/edge")
        assert edge.extensions["hostApplications"][0]["name"] == "edge-proxy"


class TestColocationWireSafety:
    """Malformed configmap payloads surface as (default config, error),
    never AttributeError (koordlint wire-unguarded-access class)."""

    def test_non_dict_node_configs_entries(self):
        import json

        from koordinator_tpu.utils.sloconfig import (
            COLOCATION_CONFIG_KEY,
            parse_colocation_config,
        )

        cfg, err = parse_colocation_config({COLOCATION_CONFIG_KEY: json.dumps(
            {"nodeConfigs": ["not-an-object"]})})
        assert err is not None and "nodeConfigs entry" in err
        assert cfg.node_strategies == []

        cfg, err = parse_colocation_config({COLOCATION_CONFIG_KEY: json.dumps(
            {"nodeConfigs": "nope"})})
        assert err is not None and "must be a list" in err
        assert cfg.node_strategies == []

    def test_well_formed_still_parses(self):
        import json

        from koordinator_tpu.utils.sloconfig import (
            COLOCATION_CONFIG_KEY,
            parse_colocation_config,
        )

        cfg, err = parse_colocation_config({COLOCATION_CONFIG_KEY: json.dumps(
            {"nodeConfigs": [
                {"nodeSelector": {"pool": "batch"},
                 "cpuReclaimThresholdPercent": 70}]})})
        assert err is None
        assert len(cfg.node_strategies) == 1
        assert cfg.node_strategies[0].node_selector == {"pool": "batch"}
