"""Pack/device overlap (PR 15): the in-window pre-pack is a pure
latency lever — the produced ScheduleInputs (and every decision) must be
byte-identical to the non-overlapped pack.

  * overlap-on vs overlap-off twins over churn rounds: every encode's
    post-reduce FullChainInputs arrays byte-compare, bound sequences and
    final conditions match — serial (pipeline) and fused-chain paths;
  * mid-window reconciliation: a store mutation injected INSIDE the
    device window (after the pre-pack ran) must be re-packed before the
    next upload — the dirtied pod's row byte-compares against the
    serial-pack twin (the (key, resourceVersion) memo keying IS the
    reconciliation);
  * the memo warm actually happens: pre-packed rows turn the next
    build's per-object Python into memo hits.
"""

from __future__ import annotations

import numpy as np

from koordinator_tpu.client.store import KIND_POD
from koordinator_tpu.scheduler.cycle import CyclePipeline, Scheduler
from koordinator_tpu.scheduler.pipeline_parity import (
    apply_round_delta,
    build_store_from_state,
)
from koordinator_tpu.testing import synth_full_cluster


def _world(seed=7, nodes=16, pods=40):
    _cluster, state = synth_full_cluster(
        nodes, pods, seed=seed, num_quotas=2, num_gangs=2,
        topology_fraction=0.5, lsr_fraction=0.2)
    return state, build_store_from_state(state)


def _snap_fc(fc):
    out = {}
    for name in fc._fields:
        value = getattr(fc, name)
        if name == "base":
            for f2 in value._fields:
                out["base." + f2] = np.array(
                    np.asarray(getattr(value, f2)), copy=True)
        else:
            out[name] = np.array(np.asarray(value), copy=True)
    return out


def _diff_fields(a, b):
    bad = []
    for key in a:
        if a[key].shape != b[key].shape or not np.array_equal(a[key],
                                                              b[key]):
            bad.append(key)
    return bad


def _run_world(waves, overlap, rounds=4, seed=7, mutate_mid_window=None):
    state, store = _world(seed=seed)
    sched = Scheduler(store, waves=waves, explain="off",
                      pack_overlap=overlap)
    pipe = CyclePipeline(sched, enabled=True)
    encodes = []
    sched.encode_observer = lambda fc: encodes.append(_snap_fc(fc))
    if mutate_mid_window is not None:
        # the sync-delay hook runs INSIDE every monitored device window
        # — after the pre-pack snapshotted store deltas — so a mutation
        # here is exactly a "row dirtied during the window"
        sched.sync_delay_injector = lambda: mutate_mid_window(store)
    now = state.now
    bound = []
    for r in range(rounds):
        if r:
            apply_round_delta(store, r, now, 7)
        res = pipe.run_cycle(now=now + 2 * r)
        bound.append([(b.pod_key, b.node_name) for b in res.bound])
    pipe.flush()
    conditions = {
        p.meta.key: (c.status, c.reason, c.message)
        for p in store.list(KIND_POD)
        for c in [p.get_condition("PodScheduled")] if c is not None}
    return encodes, bound, conditions


class TestPackOverlapParity:
    def test_serial_pipeline_byte_parity(self):
        enc_on, bound_on, cond_on = _run_world(1, True)
        enc_off, bound_off, cond_off = _run_world(1, False)
        assert bound_on == bound_off
        assert cond_on == cond_off
        assert len(enc_on) == len(enc_off)
        for i, (a, b) in enumerate(zip(enc_on, enc_off)):
            assert _diff_fields(a, b) == [], f"encode {i}"

    def test_fused_chain_byte_parity(self):
        enc_on, bound_on, cond_on = _run_world(4, True)
        enc_off, bound_off, cond_off = _run_world(4, False)
        assert bound_on == bound_off
        assert cond_on == cond_off
        for i, (a, b) in enumerate(zip(enc_on, enc_off)):
            assert _diff_fields(a, b) == [], f"encode {i}"

    def test_mid_window_mutation_repacked_before_upload(self):
        """A pod spec rewritten DURING the device window (bind patches /
        watch events land exactly like this) bumps its resourceVersion,
        so the pre-packed row goes stale and the next build re-packs it
        — the overlapped world's ScheduleInputs stay byte-identical to
        the serial pack's."""
        from koordinator_tpu.api.resources import ResourceList

        hit = {"n": 0}

        def mutate(store):
            # rewrite one still-pending pod's requests mid-window: the
            # pre-pack already staged its row from the OLD spec
            for pod in store.list(KIND_POD):
                if not pod.is_assigned and not pod.is_terminated:
                    pod.spec.requests = ResourceList.of(
                        cpu=3000 + 250 * hit["n"], memory=2 * 1024 ** 3,
                        pods=1)
                    store.update(KIND_POD, pod)
                    hit["n"] += 1
                    break

        enc_on, bound_on, _ = _run_world(1, True,
                                         mutate_mid_window=mutate)
        hit["n"] = 0
        enc_off, bound_off, _ = _run_world(1, False,
                                           mutate_mid_window=mutate)
        assert hit["n"] > 0, "the mid-window mutation must have fired"
        assert bound_on == bound_off
        for i, (a, b) in enumerate(zip(enc_on, enc_off)):
            assert _diff_fields(a, b) == [], f"encode {i}"

    def test_prepack_warms_the_memo(self):
        """The overlap's point: rows the pre-pack staged in the window
        are memo HITS at the next build instead of per-object repacks."""
        from koordinator_tpu.api.objects import ObjectMeta, Pod, PodSpec
        from koordinator_tpu.api.resources import ResourceList

        state, store = _world(seed=11)
        sched = Scheduler(store, waves=1, explain="off", pack_overlap=True)
        assert sched.pack_overlap is True
        pipe = CyclePipeline(sched, enabled=True)
        now = state.now
        pipe.run_cycle(now=now)
        # permanently-pending pods: their failure verdicts defer into
        # the NEXT cycle's window (pipeline), whose flush bumps their
        # resourceVersion — exactly the rows the in-window pre-pack
        # exists to stage for the cycle after
        for i in range(4):
            store.add(KIND_POD, Pod(
                meta=ObjectMeta(name=f"impossible-{i}", namespace="po",
                                uid=f"impossible-{i}",
                                creation_timestamp=now),
                spec=PodSpec(requests=ResourceList.of(
                    cpu=10_000_000, memory=1024 ** 4, pods=1))))
        stats = sched.snapshot_cache.stats
        pipe.run_cycle(now=now + 2)  # verdicts captured, writes deferred
        pipe.run_cycle(now=now + 4)  # flush dirties rows, prepack stages
        pipe.run_cycle(now=now + 6)
        pipe.flush()
        assert stats.get("pod_rows_prepacked", 0) > 0, (
            "deferred condition writes inside the window must leave "
            "rows for the pre-pack to stage")

    def test_prepack_failure_never_wrecks_the_cycle(self, monkeypatch):
        """The pre-pack is best-effort by contract: a raise inside it
        must not reach the ladder or the cycle — the next pack simply
        runs in the gap."""
        import koordinator_tpu.scheduler.snapshot as snapshot_mod

        def boom(cache, pods, args):
            raise RuntimeError("prepack wrecked")

        monkeypatch.setattr(snapshot_mod, "prepack_pending_rows", boom)
        state, store = _world(seed=23)
        sched = Scheduler(store, waves=4, explain="off", pack_overlap=True)
        pipe = CyclePipeline(sched, enabled=True)
        res = pipe.run_cycle(now=state.now)
        res2 = pipe.run_cycle(now=state.now + 2)
        pipe.flush()
        assert res.bound or res2.bound
        assert sched.ladder.level == 0  # no ladder demotion from host work

    def test_prefilter_view_transform_disables_prepack(self):
        """A registered BeforePreFilter view transform rewrites pod
        views the real pack consumes WITHOUT bumping the store
        resourceVersion — a pre-packed raw row would be a stale (key,
        rv) hit, so the pre-pack must stand down (and decisions must
        still match the overlap-off twin)."""
        import dataclasses

        from koordinator_tpu.api.resources import ResourceList
        from koordinator_tpu.scheduler.frameworkext import (
            PreFilterTransformer,
        )

        class DoubleCpuView(PreFilterTransformer):
            name = "DoubleCpuView"

            def before_prefilter(self, pod, ctx):
                req = pod.spec.requests
                cpu = req["cpu"] or 0
                if not cpu:
                    return None
                doubled = ResourceList.of(
                    cpu=min(2 * cpu, 16_000),
                    memory=req["memory"] or 0,
                    pods=req["pods"] or 0)
                return dataclasses.replace(
                    pod, spec=dataclasses.replace(pod.spec,
                                                  requests=doubled))

        worlds = {}
        for overlap in (True, False):
            state, store = _world(seed=31)
            sched = Scheduler(store, waves=1, explain="off",
                              pack_overlap=overlap)
            sched.extender.register_transformer(DoubleCpuView())
            pipe = CyclePipeline(sched, enabled=True)
            now = state.now
            bound = []
            for r in range(3):
                if r:
                    apply_round_delta(store, r, now, 7)
                res = pipe.run_cycle(now=now + 2 * r)
                bound.append([(b.pod_key, b.node_name)
                              for b in res.bound])
            pipe.flush()
            worlds[overlap] = (bound,
                               sched.snapshot_cache.stats.get(
                                   "pod_rows_prepacked", 0))
        assert worlds[True][0] == worlds[False][0]
        assert worlds[True][1] == 0, (
            "the pre-pack must stand down under a view transform")

    def test_mid_prepack_wreck_poisons_the_memo_not_the_bytes(
            self, monkeypatch):
        """A pre-pack that wrecks AFTER bumping some rows'
        resourceVersions (the pack-column refresh landed, the flag/sel
        refresh did not) must not leave half-updated memo rows the next
        build serves as hits — the memo is dropped wholesale and the
        cold repack keeps decisions identical to the overlap-off
        twin."""
        import koordinator_tpu.scheduler.snapshot as snapshot_mod
        from koordinator_tpu.api.objects import ObjectMeta, Pod, PodSpec
        from koordinator_tpu.api.resources import ResourceList
        from koordinator_tpu.ops.packing import prepack_memo_rows

        wrecked = {"n": 0}

        def half_prepack(cache, pods, args):
            # EXACTLY the hazard: rv bumped + pack columns written,
            # then a wreck before the flag/sel/mask_valid refresh
            placed = prepack_memo_rows(cache, pods,
                                       args.resource_weights,
                                       args.estimated_scaling_factors)
            if placed:
                wrecked["n"] += 1
                raise RuntimeError("wreck after rv bump")
            return 0

        def run(overlap):
            state, store = _world(seed=37)
            sched = Scheduler(store, waves=1, explain="off",
                              pack_overlap=overlap)
            pipe = CyclePipeline(sched, enabled=True)
            now = state.now
            for i in range(3):
                store.add(KIND_POD, Pod(
                    meta=ObjectMeta(name=f"imp-{i}", namespace="pw",
                                    uid=f"imp-{i}",
                                    creation_timestamp=now),
                    spec=PodSpec(requests=ResourceList.of(
                        cpu=10_000_000, memory=1024 ** 4, pods=1))))
            bound = []
            for r in range(4):
                if r:
                    apply_round_delta(store, r, now, 7)
                res = pipe.run_cycle(now=now + 2 * r)
                bound.append([(b.pod_key, b.node_name)
                              for b in res.bound])
            pipe.flush()
            conditions = {
                p.meta.key: (c.status, c.reason, c.message)
                for p in store.list(KIND_POD)
                for c in [p.get_condition("PodScheduled")]
                if c is not None}
            return bound, conditions

        monkeypatch.setattr(snapshot_mod, "prepack_pending_rows",
                            half_prepack)
        bound_on, cond_on = run(True)
        assert wrecked["n"] > 0, "the mid-prepack wreck must have fired"
        bound_off, cond_off = run(False)
        assert bound_on == bound_off
        assert cond_on == cond_off
