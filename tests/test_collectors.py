"""Tests for the expanded metricsadvisor collector profile and the koordlet
metrics registry (reference pkg/koordlet/metricsadvisor collectors +
pkg/koordlet/metrics)."""

import pytest

from koordinator_tpu.api.objects import (
    LABEL_POD_QOS,
    Node,
    NodeSLO,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_NODE_SLO,
    KIND_POD,
    ObjectStore,
)
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet import metrics as km
from koordinator_tpu.koordlet.metricsadvisor import MetricsAdvisor
from koordinator_tpu.koordlet.metriccache import MetricCache
from koordinator_tpu.koordlet.statesinformer import StatesInformer
from koordinator_tpu.koordlet.util import kidled as kidled_util
from koordinator_tpu.koordlet.util import machineinfo
from koordinator_tpu.koordlet.util import system as sysutil
from koordinator_tpu.koordlet.util.system import FakeFS
from koordinator_tpu.utils.features import KOORDLET_GATES

GIB = 1024**3
NOW = 1_000_000.0


@pytest.fixture
def fs():
    f = FakeFS(use_cgroup_v2=True)
    yield f
    f.cleanup()


def build(fs, pods=()):
    store = ObjectStore()
    store.add(KIND_NODE, Node(
        meta=ObjectMeta(name="node-0", namespace=""),
        allocatable=ResourceList.of(cpu=16_000, memory=64 * GIB)))
    for pod in pods:
        store.add(KIND_POD, pod)
    cache = MetricCache()
    informer = StatesInformer(store, "node-0", cache)
    advisor = MetricsAdvisor(informer, cache, fs.config)
    return store, cache, informer, advisor


def mk_pod(name, qos="LS"):
    return Pod(
        meta=ObjectMeta(name=name, uid=name, labels={LABEL_POD_QOS: qos}),
        spec=PodSpec(node_name="node-0",
                     requests=ResourceList.of(cpu=2000, memory=2 * GIB),
                     limits=ResourceList.of(cpu=2000, memory=2 * GIB)),
        phase="Running")


class TestNewCollectors:
    def test_nodeinfo_kv(self, fs):
        machineinfo.write_fake_machine(fs, 1, 2, 4)
        _, cache, _, advisor = build(fs)
        advisor.collect_node_info(NOW)
        topo = cache.get_kv(mc.NODE_CPU_INFO_KEY)
        assert topo is not None and topo.num_cpus == 16
        assert len(cache.get_kv(mc.NODE_NUMA_INFO_KEY)) == 2
        # collected once only
        advisor.collect_node_info(NOW + 60)
        assert cache.get_kv(mc.NODE_CPU_INFO_KEY) is topo

    def test_pagecache(self, fs):
        pod = mk_pod("p1")
        _, cache, _, advisor = build(fs, [pod])
        rel = fs.config.pod_relative_path("", "p1")
        fs.set_cgroup(rel, sysutil.MEMORY_STAT,
                      "anon 1048576\nfile 2097152\nkernel 4096\n")
        advisor.collect_pagecache(NOW)
        assert cache.query(mc.POD_PAGECACHE, "latest",
                           pod=pod.meta.key) == 2097152

    def test_pod_throttled_ratio_needs_two_ticks(self, fs):
        pod = mk_pod("p1")
        _, cache, _, advisor = build(fs, [pod])
        rel = fs.config.pod_relative_path("", "p1")
        fs.set_cgroup(rel, sysutil.CPU_STAT,
                      "usage_usec 1000\nnr_periods 100\nnr_throttled 10\n")
        advisor.collect_pod_throttled(NOW)
        assert cache.query(mc.POD_CPU_THROTTLED_RATIO, "latest",
                           pod=pod.meta.key) is None
        fs.set_cgroup(rel, sysutil.CPU_STAT,
                      "usage_usec 2000\nnr_periods 200\nnr_throttled 60\n")
        advisor.collect_pod_throttled(NOW + 60)
        # delta 50 throttled / 100 periods
        assert cache.query(mc.POD_CPU_THROTTLED_RATIO, "latest",
                           pod=pod.meta.key) == pytest.approx(0.5)

    def test_cold_memory_collector(self, fs):
        pod = mk_pod("p1", qos="BE")
        _, cache, _, advisor = build(fs, [pod])
        kidled_util.KidledInterface(fs.config).enable(scan_period_s=120)
        rel = fs.config.pod_relative_path(sysutil.QOS_BESTEFFORT, "p1")
        fs.set_cgroup(rel, kidled_util.IDLE_PAGE_STATS,
                      "# version: 1.0\n# scans: 10\n"
                      "# scan_period_in_seconds: 120\n"
                      "# buckets: 1,2,5,15,30,60,120,240\n"
                      "cfei 0 0 0 4096 0 0 0 8192\n")
        advisor.collect_cold_memory(NOW)
        # boundary 300s -> buckets >= 5 periods: 4096 + 8192
        assert cache.query(mc.POD_COLD_MEMORY, "latest",
                           pod=pod.meta.key) == 12288

    def test_host_application_collector(self, fs):
        store, cache, _, advisor = build(fs)
        store.add(KIND_NODE_SLO, NodeSLO(
            meta=ObjectMeta(name="node-0", namespace=""),
            extensions={"hostApplications": [
                {"name": "nginx", "cgroupPath": "host-latency-sensitive/nginx"},
            ]}))
        fs.set_cgroup("host-latency-sensitive/nginx", sysutil.CPU_STAT,
                      "usage_usec 1000000\n")
        fs.set_cgroup("host-latency-sensitive/nginx", sysutil.MEMORY_USAGE,
                      str(GIB))
        advisor.collect_host_application(NOW)
        assert cache.query(mc.HOST_APP_MEMORY_USAGE, "latest",
                           app="nginx") == GIB
        # cpu rate needs a second tick
        fs.set_cgroup("host-latency-sensitive/nginx", sysutil.CPU_STAT,
                      "usage_usec 2000000\n")
        advisor.collect_host_application(NOW + 10)
        assert cache.query(mc.HOST_APP_CPU_USAGE, "latest",
                           app="nginx") == pytest.approx(0.1)

    def test_storage_collector(self, fs):
        _, cache, _, advisor = build(fs)
        fs.set_proc("diskstats",
                    " 259 0 nvme0n1 1 0 1 1 1 0 1 1 0 5000 10\n")
        advisor.collect_node_storage_info(NOW)
        advisor.collect_node_storage_info(NOW + 10)  # rate needs two ticks
        assert cache.query(mc.NODE_FS_TOTAL_BYTES, "latest") > 0
        assert cache.query(mc.NODE_FS_USED_BYTES, "latest") >= 0

    def test_profile_respects_gates(self, fs):
        pod = mk_pod("p1", qos="BE")
        _, cache, _, advisor = build(fs, [pod])
        kidled_util.KidledInterface(fs.config).enable(scan_period_s=120)
        rel = fs.config.pod_relative_path(sysutil.QOS_BESTEFFORT, "p1")
        fs.set_cgroup(rel, kidled_util.IDLE_PAGE_STATS,
                      "# scan_period_in_seconds: 120\n"
                      "# buckets: 1,2,5,15,30,60,120,240\n"
                      "cfei 0 0 0 0 0 0 0 8192\n")
        assert not KOORDLET_GATES.enabled("ColdPageCollector")
        advisor.collect_once(NOW)
        assert cache.query(mc.POD_COLD_MEMORY, "latest",
                           pod=pod.meta.key) is None


class TestMetricsRegistry:
    def test_gauge_counter_and_exposition(self):
        reg = km.Registry()
        g = reg.gauge("test_gauge", "a gauge")
        c = reg.counter("test_counter", "a counter")
        g.set(2.5, node="n1")
        c.inc(reason="mem")
        c.inc(reason="mem")
        c.inc(reason="cpu")
        assert g.get(node="n1") == 2.5
        assert c.get(reason="mem") == 2.0
        text = reg.expose()
        assert "# TYPE test_gauge gauge" in text
        assert 'test_counter{reason="mem"} 2' in text

    def test_label_value_escaping(self):
        # exposition format: backslash, double-quote and newline in label
        # values must be escaped or the scrape output is invalid
        reg = km.Registry()
        g = reg.gauge("esc_gauge")
        g.set(1.0, pod='ns/we"ird\\pod\nx')
        text = reg.expose()
        assert 'pod="ns/we\\"ird\\\\pod\\nx"' in text
        assert "\n" not in text.split("esc_gauge{", 1)[1].split("}", 1)[0]
        # HELP lines escape backslash and newline too
        reg.gauge("esc_help", "multi\nline \\help")
        help_line = [l for l in reg.expose().splitlines()
                     if l.startswith("# HELP esc_help")][0]
        assert help_line == "# HELP esc_help multi\\nline \\\\help"

    def test_reregistration_returns_same_metric(self):
        reg = km.Registry()
        g1 = reg.gauge("g")
        g2 = reg.gauge("g")
        assert g1 is g2
        with pytest.raises(ValueError):
            reg.counter("g")

    def test_qos_actions_recorded(self, fs):
        km.POD_EVICTION_TOTAL.clear(reason="test_mem")
        from koordinator_tpu.koordlet.qosmanager import Evictor

        store = ObjectStore()
        pod = mk_pod("victim", qos="BE")
        store.add(KIND_POD, pod)
        cache = MetricCache()
        informer = StatesInformer(store, "node-0", cache)
        evictor = Evictor(store, informer, cache)
        evictor.evict(pod, "test_mem")
        assert km.POD_EVICTION_TOTAL.get(reason="test_mem") == 1.0


class TestDeviceCollector:
    def test_device_usage_series_recorded(self, fs):
        _, cache, _, advisor = build(fs)
        advisor.device_sampler = lambda: [
            {"minor": 0, "uuid": "TPU-0", "core_pct": 37.5,
             "mem_bytes": 6 * GIB},
            {"minor": 1, "uuid": "TPU-1", "core_pct": 80.0,
             "mem_bytes": 12 * GIB},
        ]
        KOORDLET_GATES.set_from_map({"TPUDeviceCollector": True})
        try:
            advisor.collect_once(now=NOW)
        finally:
            KOORDLET_GATES.set_from_map({"TPUDeviceCollector": False})
        assert cache.query(mc.NODE_GPU_CORE_USAGE, "latest", now=NOW,
                           minor="0", uuid="TPU-0") == 37.5
        assert cache.query(mc.NODE_GPU_MEM_USAGE, "latest", now=NOW,
                           minor="1", uuid="TPU-1") == 12 * GIB

    def test_default_sampler_degrades_off_tpu(self, fs):
        from koordinator_tpu.koordlet.metricsadvisor import sample_tpu_devices

        # under the CPU test mesh there are no TPU chips: [] and no metrics,
        # never an exception
        _, cache, _, advisor = build(fs)
        assert sample_tpu_devices() == []
        advisor.collect_once(now=NOW)
        assert cache.query(mc.NODE_GPU_CORE_USAGE, "latest", now=NOW,
                           minor="0", uuid="TPU-0") is None
